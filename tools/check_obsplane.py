#!/usr/bin/env python
"""Verify gate for the production observability plane (run by ``make
check-obsplane`` inside ``make verify``) — the p99-attribution and
black-box drill.

CPU end-to-end, two child processes on the 8-virtual-device mesh:

1. **Scrape-under-chaos drill**: the child builds the serving model,
   warms the ladder, starts the Prometheus scrape endpoint on an
   ephemeral port, and drives a seeded request stream under
   ``DETPU_FAULT=slow:serve_step:<s>,burst@<pos>`` (the same degraded
   backend + QPS spike the serving gate uses). MID-LOAD it scrapes
   ``GET /metrics`` over real HTTP and checks the body is valid
   Prometheus text carrying the serve latency summary. After the drive,
   the per-stage latency sketches (queue-wait / coalesce / dispatch /
   device-compute / reply-slice) must SUM to the total served latency
   within 5% — the p99-decomposition instrument is only trustworthy if
   the stages partition the end-to-end time. 0 steady-state recompiles
   throughout: observing must never retrace.
2. **Black-box drill**: a training child runs under
   ``DETPU_FAULT=nan@<pos>`` with a one-shot stream (rollback
   impossible, so the NaN storm is terminal). The escalation must leave
   a CRC-intact ``<dir>.blackbox.json`` whose payload names the trigger
   (``nan_escalation``), the unhealthy table(s) via the per-table
   health sentinels, and carries the ringed pre-crash step metrics.

Exit 0 when both drills pass; 1 with a readable reason otherwise.
"""

from __future__ import annotations

import os
import subprocess
import sys
import tempfile

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

WORLD = 8
BURST_AT = 1      # second of the stream the QPS spike hits
BURST_X = 8       # arrival-rate multiplier during the burst
SLOW_S = 0.02     # injected per-flush latency (the degraded backend)
QPS = 40.0
DURATION_S = 2.0
NAN_AT = 3        # stream position the poisoned batch hits

_SERVE_CHILD = """
import sys, urllib.request
sys.path.insert(0, {repo!r})
import numpy as np, jax, jax.numpy as jnp, optax
jax.config.update('jax_platforms', 'cpu')
from jax.sharding import Mesh
from distributed_embeddings_tpu.parallel import (
    DistributedEmbedding, ServeConfig, ServingRuntime, SparseSGD,
    init_hybrid_state)
from distributed_embeddings_tpu.parallel import serving as sv
from distributed_embeddings_tpu.utils import mplane

world = {world}
mesh = Mesh(np.array(jax.devices()[:world]), ("data",))
sizes = [20000, 10000, 10000, 5000, 5000, 2000, 2000, 1000]
configs = [{{"input_dim": v, "output_dim": 8}} for v in sizes]
de = DistributedEmbedding(configs, world_size=world)
tx = optax.sgd(0.05)
state = init_hybrid_state(de, SparseSGD(),
                          {{"w": jnp.ones((8 * len(configs) + 2, 1),
                                          jnp.float32) * 0.01}},
                          tx, jax.random.key(0), mesh=mesh)

def pred_fn(dp, outs, batch):
    x = jnp.concatenate(list(outs) + [batch], axis=-1)
    return jax.nn.sigmoid(x @ dp["w"])[:, 0]

cfg = ServeConfig(max_batch=32, max_wait_ms=5, deadline_ms=2000,
                  max_queue=64, shed_frac=0.5)
rt = ServingRuntime(de, pred_fn, state, mesh=mesh, config=cfg)
rng = np.random.default_rng(0)
tmpl = sv.synthetic_request(rng, sizes, 2, numerical=2)
rt.warmup((tmpl.cats, tmpl.batch))

exp = mplane.start_http_exporter(rt.metrics, port=0)

def make_request(i):
    return sv.synthetic_request(rng, sizes, int(rng.integers(1, 5)),
                                numerical=2)

served = []
def collect(res):
    served.extend(r for r in res if isinstance(r, sv.Served))

collect(sv.drive(rt, make_request, {qps}, {duration}, burst_x={burst_x}))

# ---- MID-LOAD scrape: the queue refills, then a real HTTP GET ------
for _ in range(8):
    rt.submit(make_request(-1))
with urllib.request.urlopen(exp.url(), timeout=30) as resp:
    ctype = resp.headers["Content-Type"]
    body = resp.read().decode("utf-8")
collect(rt.poll())
collect(sv.drive(rt, make_request, {qps}, 0.5, burst_positions=()))
collect(rt.flush())
exp.stop()

# valid Prometheus text: every sample line is "name[labels] value"
samples = 0
scrape_ok = 1 if ctype.startswith("text/plain") else 0
for ln in body.splitlines():
    if not ln or ln.startswith("#"):
        continue
    try:
        float(ln.rsplit(None, 1)[1])
        samples += 1
    except (IndexError, ValueError):
        scrape_ok = 0

s = rt.stats()
total_lat = sum(r.latency_ms for r in served)
stage_total = sum(st["sum"] for st in s["latency_stages_ms"].values())
ratio = stage_total / total_lat if total_lat else -1.0
print("FINAL",
      "SERVED", s["served"],
      "SCRAPE_OK", scrape_ok,
      "SCRAPE_SAMPLES", samples,
      "SCRAPE_HAS_LAT", int("detpu_serve_latency_ms_count" in body),
      "SCRAPE_HAS_STAGE", int('detpu_serve_stage_ms' in body),
      "STAGE_RATIO", round(ratio, 4),
      "DOMINANT", s["p99_dominant_stage"],
      "STEADY", s["steady_state_recompiles"], flush=True)
"""

_NAN_CHILD = """
import sys
sys.path.insert(0, {repo!r})
import numpy as np, jax, jax.numpy as jnp, optax
jax.config.update('jax_platforms', 'cpu')
from distributed_embeddings_tpu.parallel import (
    DistributedEmbedding, SparseAdagrad, init_hybrid_state,
    make_hybrid_train_step, run_resilient)
from distributed_embeddings_tpu.parallel import resilient as rz
from distributed_embeddings_tpu.utils import mplane, runtime

configs = [{{"input_dim": 20 + 3 * i, "output_dim": 4}}
           for i in range(6)]
de = DistributedEmbedding(configs, world_size=1)
emb_opt = SparseAdagrad()
tx = optax.sgd(0.1)
state = init_hybrid_state(de, emb_opt, {{"w": jnp.float32(0.5)}}, tx,
                          jax.random.key(0))

def loss_fn(dp, outs, batch):
    return (sum(jnp.mean(o) for o in outs) * dp["w"]
            - jnp.mean(batch)) ** 2

step = make_hybrid_train_step(de, loss_fn, tx, emb_opt,
                              with_metrics=True)

def data():  # ONE-SHOT: rollback impossible -> the NaN storm is terminal
    for i in range(10):
        rng = np.random.default_rng(i)
        cats = [jnp.asarray(rng.integers(0, c["input_dim"], 16),
                            jnp.int32) for c in configs]
        y = jnp.asarray(rng.normal(size=(16,)), jnp.float32)
        yield cats, y

ck = {ckpt!r}
try:
    run_resilient(step, state, data(), de=de, checkpoint_dir=ck,
                  escalate_after=2, metrics_interval=1,
                  save_on_exit=False)
    print("FINAL CRASHED 0", flush=True)
    sys.exit(0)
except runtime.NonFiniteLossError:
    pass
payload = mplane.verify_blackbox(rz.blackbox_path(ck))  # raises on CRC
print("FINAL",
      "CRASHED", 1,
      "TRIGGER", payload["trigger"],
      "UNHEALTHY", len(payload["context"].get("unhealthy_tables", [])),
      "STEPS_RING", len(payload["steps"]), flush=True)
"""


def _run_child(code: str, extra_env: dict) -> dict:
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    for k in ("DETPU_OBS", "DETPU_TELEMETRY", "DETPU_METRICS_PORT"):
        env.pop(k, None)
    env["XLA_FLAGS"] = (env.get("XLA_FLAGS", "")
                        + f" --xla_force_host_platform_device_count={WORLD}")
    env.update(extra_env)
    p = subprocess.run([sys.executable, "-c", code], env=env,
                       capture_output=True, text=True, timeout=900)
    if p.returncode != 0:
        raise RuntimeError(f"drill child failed rc={p.returncode}: "
                           f"{(p.stderr or p.stdout).strip()[-1200:]}")
    for line in reversed(p.stdout.strip().splitlines()):
        if line.startswith("FINAL"):
            parts = line.split()
            return dict(zip(parts[1::2], parts[2::2]))
    raise RuntimeError("drill child printed no FINAL line: "
                       f"{p.stdout.strip()[-800:]}")


def main() -> int:
    errors = []

    # ---- drill 1: scrape + p99 decomposition under burst chaos -------
    try:
        got = _run_child(
            _SERVE_CHILD.format(repo=REPO, world=WORLD, qps=QPS,
                                duration=DURATION_S, burst_x=BURST_X),
            {"DETPU_FAULT": f"slow:serve_step:{SLOW_S},burst@{BURST_AT}",
             "DETPU_SERVE_BURST_X": str(BURST_X)})
    except RuntimeError as e:
        return _fail([str(e)])
    if int(got.get("SERVED", 0)) <= 0:
        errors.append("scrape drill served nothing")
    if got.get("SCRAPE_OK") != "1" or int(got.get("SCRAPE_SAMPLES", 0)) < 10:
        errors.append(
            f"mid-load scrape is not valid Prometheus text "
            f"(ok={got.get('SCRAPE_OK')}, "
            f"samples={got.get('SCRAPE_SAMPLES')})")
    if got.get("SCRAPE_HAS_LAT") != "1" or got.get("SCRAPE_HAS_STAGE") != "1":
        errors.append(
            "the scrape body is missing the serve latency / stage "
            "summaries — the runtime's registry is not on the endpoint")
    ratio = float(got.get("STAGE_RATIO", -1))
    if not (0.95 <= ratio <= 1.05):
        errors.append(
            f"per-stage latency sums / end-to-end served latency = "
            f"{ratio} — outside [0.95, 1.05]: the stage decomposition "
            "does not partition the request's life, so p99 attribution "
            "cannot be trusted")
    if got.get("DOMINANT") in (None, "None"):
        errors.append("stats() attributed the p99 tail to no stage")
    if got.get("STEADY") != "0":
        errors.append(
            f"{got.get('STEADY')} steady-state recompile(s) during the "
            "observed drill — observing must never retrace")

    # ---- drill 2: NaN escalation leaves a CRC-intact black box -------
    with tempfile.TemporaryDirectory() as tmp:
        try:
            got2 = _run_child(
                _NAN_CHILD.format(repo=REPO,
                                  ckpt=os.path.join(tmp, "ck")),
                {"DETPU_FAULT": f"nan@{NAN_AT},nan@{NAN_AT + 1}"})
        except RuntimeError as e:
            return _fail(errors + [str(e)])
    if got2.get("CRASHED") != "1":
        errors.append("nan@ injection did not escalate terminally")
    elif got2.get("TRIGGER") != "nan_escalation":
        errors.append(
            f"black box names trigger {got2.get('TRIGGER')!r}, expected "
            "'nan_escalation'")
    elif int(got2.get("UNHEALTHY", 0)) < 1:
        errors.append(
            "the black box names NO unhealthy table — the per-table "
            "health sentinels did not reach the post-mortem")
    elif int(got2.get("STEPS_RING", 0)) < 1:
        errors.append(
            "the black box carries no ringed step metrics — the "
            "pre-crash history is missing")

    if errors:
        return _fail(errors)
    print(f"check_obsplane: OK (scraped {got['SCRAPE_SAMPLES']} samples "
          f"mid-load under burst@{BURST_AT}s x{BURST_X}, stage sums / "
          f"total latency = {got['STAGE_RATIO']} (p99 tail -> "
          f"{got['DOMINANT']}), 0 steady-state recompiles; nan@{NAN_AT} "
          f"left a CRC-intact black box naming {got2['UNHEALTHY']} "
          f"unhealthy table(s) with {got2['STEPS_RING']} ringed steps)")
    return 0


def _fail(errors) -> int:
    for e in errors:
        print(f"check_obsplane: {e}", file=sys.stderr)
    return 1


if __name__ == "__main__":
    sys.exit(main())

#!/usr/bin/env python
"""Verify gate for the rollback-and-replay recovery (run by ``make
verify``) — the NaN-storm chaos drill.

CPU end-to-end, deterministic, no backend required beyond the CPU one:

1. spawn a child training driver (tiny model, 12 batches through
   ``parallel.resilient.run_resilient`` with a checkpoint ring) under
   ``DETPU_FAULT=nan@5`` + ``DETPU_NANGUARD_K=1`` — batch 5's dense
   coefficients are poisoned with a NaN in-flight, the on-device guard
   skips the update, and the host driver must ROLL BACK to a ring
   checkpoint, replay the window, QUARANTINE the poisoned batch, and run
   to clean completion (exit 0, no human);
2. assert the recovery artifacts: the quarantine ledger names stream
   position 5, the metrics sidecar carries the ``training_rollback`` /
   ``batch_quarantined`` / ``training_recovered`` events, and the
   quarantine event's per-table health sentinels name table 0 (the one
   whose cotangent the poisoned coefficient NaN'd) — the "which table
   went unhealthy" acceptance;
3. run the identical driver on the same stream WITH BATCH 5 REMOVED in a
   fresh directory and assert both end at the same final step with
   CRC-identical final checkpoints — recovery rewrites history to
   exactly the stream-minus-poison trajectory.

Exit 0 when the drill passes; 1 with a readable reason otherwise.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import tempfile

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

STEPS = 12
BAD = 5  # stream position the nan@ drill poisons

# the loss gives each table its own batch coefficient, so the in-flight
# NaN (first element of the dense batch) poisons ONLY table 0's
# cotangent — the sentinel naming the table is load-bearing, not vacuous
_CHILD = """
import sys
sys.path.insert(0, {repo!r})
import jax, optax, numpy as np, jax.numpy as jnp
jax.config.update('jax_platforms', 'cpu')
from distributed_embeddings_tpu.parallel import (
    DistributedEmbedding, SparseAdagrad, init_hybrid_state,
    make_hybrid_train_step, run_resilient)
from distributed_embeddings_tpu.utils import obs
configs = [{{"input_dim": 16 + 3 * i, "output_dim": 4}} for i in range(4)]
de = DistributedEmbedding(configs, world_size=1)
emb_opt = SparseAdagrad()
tx = optax.sgd(0.1)
state = init_hybrid_state(de, emb_opt,
                          {{"w": jnp.ones((4, 1), jnp.float32)}},
                          tx, jax.random.key(0))
def loss_fn(dp, outs, batch):
    return sum(batch[:, i].mean() * jnp.mean(o)
               for i, o in enumerate(outs)) * jnp.mean(dp["w"])
def data(start):
    idx = [i for i in range({steps}) if i not in {drop!r}]
    for i in idx[start:]:
        rng = np.random.default_rng(500 + i)
        cats = [jnp.asarray(rng.integers(0, c["input_dim"], 8), jnp.int32)
                for c in configs]
        yield cats, jnp.asarray(rng.normal(size=(8, 4)), jnp.float32)
step = make_hybrid_train_step(de, loss_fn, tx, emb_opt,
                              with_metrics=True, nan_guard=True)
logger = obs.MetricsLogger({sidecar!r})
r = run_resilient(step, state, data, de=de, checkpoint_dir={ckpt!r},
                  checkpoint_every_steps=2, resume=True,
                  emb_optimizer=emb_opt, dense_tx=tx,
                  metrics_logger=logger, metrics_interval=0)
print("FINAL", r.step, "ROLLBACKS", r.rollbacks,
      "QUARANTINED", list(r.quarantined), flush=True)
"""


def _run_child(ckpt, sidecar, fault=None, drop=()):
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env.pop("DETPU_FAULT", None)
    env.pop("DETPU_OBS", None)
    # one non-finite step is enough to engage recovery in the drill
    env["DETPU_NANGUARD_K"] = "1"
    env["DETPU_CKPT_RING"] = "2"
    if fault:
        env["DETPU_FAULT"] = fault
    code = _CHILD.format(repo=REPO, ckpt=ckpt, sidecar=sidecar,
                         steps=STEPS, drop=tuple(drop))
    return subprocess.run([sys.executable, "-c", code], env=env,
                          capture_output=True, text=True, timeout=600)


def _final_crcs(ckpt):
    with open(os.path.join(ckpt, "meta.json"), encoding="utf-8") as f:
        return json.load(f)["files"]


def _events(sidecar, kind):
    from distributed_embeddings_tpu.utils.obs import MetricsLogger

    return [r for r in MetricsLogger.load(sidecar)
            if r.get("section") == kind]


def main() -> int:
    errors = []
    with tempfile.TemporaryDirectory(prefix="detpu_recovery_") as tmp:
        ckpt = os.path.join(tmp, "ck")
        sidecar = os.path.join(tmp, "metrics.jsonl")

        # 1: the chaos run — poisoned batch -> rollback -> quarantine ->
        # clean completion, unattended
        p = _run_child(ckpt, sidecar, fault=f"nan@{BAD}")
        if p.returncode != 0:
            return _fail([f"chaos child failed rc={p.returncode}: "
                          f"{(p.stderr or p.stdout).strip()[-800:]}"])
        final = p.stdout.strip().splitlines()[-1].split()
        if final[:2] != ["FINAL", str(STEPS - 1)]:
            errors.append(
                f"chaos child ended at {' '.join(final[:2])} — want FINAL "
                f"{STEPS - 1} ({STEPS} batches minus 1 quarantined)")
        if "ROLLBACKS 1" not in p.stdout:
            errors.append(f"expected exactly one rollback: {final}")

        # 2: the recovery artifacts
        ledger_path = ckpt + ".quarantine.json"
        if not os.path.isfile(ledger_path):
            errors.append("no quarantine ledger written")
        else:
            with open(ledger_path, encoding="utf-8") as f:
                ledger = json.load(f)
            if ledger.get("quarantined") != [BAD]:
                errors.append(f"ledger quarantined {ledger.get('quarantined')}"
                              f" — want [{BAD}]")
        rb = _events(sidecar, "training_rollback")
        qu = _events(sidecar, "batch_quarantined")
        rec = _events(sidecar, "training_recovered")
        if not rb:
            errors.append("no training_rollback event in the metrics "
                          "sidecar")
        if not rec:
            errors.append("no training_recovered event in the metrics "
                          "sidecar")
        if not qu:
            errors.append("no batch_quarantined event in the metrics "
                          "sidecar")
        else:
            if qu[0].get("stream_pos") != BAD:
                errors.append(f"quarantine event at stream_pos "
                              f"{qu[0].get('stream_pos')} — want {BAD}")
            unhealthy = qu[0].get("unhealthy_tables")
            if unhealthy != [0]:
                errors.append(
                    f"quarantine event names unhealthy tables {unhealthy} "
                    "— the poisoned coefficient NaNs exactly table 0's "
                    "cotangent, so the sentinels must name [0]")

        if errors:
            return _fail(errors)

        # 3: CRC-identity vs the clean run on the stream minus the poison
        ref = os.path.join(tmp, "ref")
        p2 = _run_child(ref, os.path.join(tmp, "ref.jsonl"), drop=(BAD,))
        if p2.returncode != 0:
            return _fail([f"reference child failed rc={p2.returncode}: "
                          f"{(p2.stderr or p2.stdout).strip()[-800:]}"])
        if f"FINAL {STEPS - 1}" not in p2.stdout:
            errors.append(f"reference child did not reach step "
                          f"{STEPS - 1}: {p2.stdout.strip()[-200:]}")
        if not errors and _final_crcs(ckpt) != _final_crcs(ref):
            errors.append(
                "final checkpoints differ between the recovered run and "
                "the clean run trained on the stream with the poisoned "
                "batch removed (CRC manifests unequal) — recovery is not "
                "trajectory-exact")
    if errors:
        return _fail(errors)
    print(f"check_recovery: OK (nan@{BAD} storm rolled back to a ring "
          f"checkpoint, quarantined the batch naming table 0, finished at "
          f"step {STEPS - 1}, final state CRC-identical to the clean "
          "stream-minus-poison run)")
    return 0


def _fail(errors) -> int:
    for e in errors:
        print(f"check_recovery: {e}", file=sys.stderr)
    return 1


if __name__ == "__main__":
    sys.exit(main())

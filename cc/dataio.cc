// Native data runtime for distributed_embeddings_tpu.
//
// TPU-native equivalent of the reference's native layer: where the reference
// spends its C++/CUDA on lookup kernels (distributed_embeddings/cc/), the TPU
// compute path is XLA/Pallas — the host-native piece that still matters is
// feeding the chips. This library provides the input-pipeline hot loops:
//
//  * power-law id generation (reference python generator:
//    examples/benchmarks/synthetic_models/synthetic_models.py:31-45)
//  * COO row-ids -> CSR row_splits (reference RowToSplit CUDA kernel:
//    cc/kernels/embedding_lookup_kernels.cu:331-350), host-side for pipelines
//  * Criteo split-binary batch reader with dtype widening
//    (reference RawBinaryDataset: examples/dlrm/utils.py:157-307): label
//    bool->f32, numerical f16->f32, categorical int8/16/32 -> int32
//
// Exposed as a plain C ABI consumed via ctypes (no pybind11 dependency);
// distributed_embeddings_tpu/utils/native.py holds the python bindings and a
// pure-numpy fallback.

#include <cstdint>
#include <cstdio>
#include <cstring>
#include <cmath>
#include <fcntl.h>
#include <string>
#include <sys/stat.h>
#include <unistd.h>
#include <vector>

extern "C" {

// ---------------------------------------------------------------- random ids

// splitmix64: tiny, fast, good enough for synthetic benchmark ids.
static inline uint64_t splitmix64(uint64_t* s) {
  uint64_t z = (*s += 0x9E3779B97f4A7C15ULL);
  z = (z ^ (z >> 30)) * 0xBF58476D1CE4E5B9ULL;
  z = (z ^ (z >> 27)) * 0x94D049BB133111EBULL;
  return z ^ (z >> 31);
}

// Power-law distributed ids in [0, vocab): inverse-CDF of p(x) ~ x^-alpha on
// [1, vocab+1), minus 1 — matching the reference's power_law().
void detpu_power_law_ids(uint64_t seed, double alpha, int64_t vocab,
                         int64_t n, int32_t* out) {
  uint64_t s = seed ? seed : 0x853c49e6748fea9bULL;
  const double gamma = 1.0 - alpha;
  const double k_min = 1.0, k_max = (double)vocab + 1.0;
  const double pk_min = pow(k_min, gamma), pk_max = pow(k_max, gamma);
  const double inv_gamma = 1.0 / gamma;
  for (int64_t i = 0; i < n; ++i) {
    double r = (double)(splitmix64(&s) >> 11) * (1.0 / 9007199254740992.0);
    double y = pow(r * (pk_max - pk_min) + pk_min, inv_gamma) - 1.0;
    int64_t id = (int64_t)y;
    if (id < 0) id = 0;
    if (id >= vocab) id = vocab - 1;
    out[i] = (int32_t)id;
  }
}

// Uniform ids in [0, vocab).
void detpu_uniform_ids(uint64_t seed, int64_t vocab, int64_t n, int32_t* out) {
  uint64_t s = seed ? seed : 0x9e3779b97f4a7c15ULL;
  for (int64_t i = 0; i < n; ++i) {
    out[i] = (int32_t)(splitmix64(&s) % (uint64_t)vocab);
  }
}

// ------------------------------------------------------------- row_to_split

// Sorted COO row ids [nnz] -> CSR row_splits [dim0+1] (binary search per
// split, like the reference kernel's per-thread search).
void detpu_row_to_split(const int64_t* rows, int64_t nnz, int64_t dim0,
                        int32_t* splits) {
  for (int64_t r = 0; r <= dim0; ++r) {
    // lower_bound of r
    int64_t lo = 0, hi = nnz;
    while (lo < hi) {
      int64_t mid = (lo + hi) / 2;
      if (rows[mid] < r) lo = mid + 1; else hi = mid;
    }
    splits[r] = (int32_t)lo;
  }
}

// ------------------------------------------------------------ criteo reader

struct CriteoFile {
  int fd;
  int elem_size;  // bytes per element as stored
};

struct CriteoReader {
  std::vector<CriteoFile> cats;
  int label_fd = -1;
  int numerical_fd = -1;
  int num_numerical = 0;
  int64_t num_samples = 0;
  // Closes all fds opened so far, so `delete r` on partial-open error paths
  // cannot leak descriptors (repeated open failures would exhaust the fd
  // table otherwise).
  ~CriteoReader() {
    if (label_fd >= 0) close(label_fd);
    if (numerical_fd >= 0) close(numerical_fd);
    for (auto& f : cats) {
      if (f.fd >= 0) close(f.fd);
    }
  }
};

static int cat_elem_size(int64_t vocab) {
  if (vocab < 127) return 1;
  if (vocab < 32767) return 2;
  return 4;
}

// Open <dir>/{label.bin, numerical.bin, cat_<i>.bin}. cat_ids selects which
// categorical files this worker reads (model-parallel input reads only local
// tables' files, reference main.py:166-176). Returns NULL on failure.
void* detpu_criteo_open(const char* dir, const int32_t* cat_ids, int num_cats,
                        const int64_t* all_sizes, int num_numerical) {
  CriteoReader* r = new CriteoReader();
  std::string base(dir);
  std::string lp = base + "/label.bin";
  r->label_fd = open(lp.c_str(), O_RDONLY);
  if (r->label_fd < 0) { delete r; return nullptr; }
  struct stat st;
  fstat(r->label_fd, &st);
  r->num_samples = st.st_size;  // bool = 1 byte/sample
  r->num_numerical = num_numerical;
  if (num_numerical > 0) {
    std::string np_ = base + "/numerical.bin";
    r->numerical_fd = open(np_.c_str(), O_RDONLY);
    if (r->numerical_fd < 0) { delete r; return nullptr; }
  }
  for (int i = 0; i < num_cats; ++i) {
    int cid = cat_ids[i];
    std::string cp = base + "/cat_" + std::to_string(cid) + ".bin";
    CriteoFile f;
    f.fd = open(cp.c_str(), O_RDONLY);
    f.elem_size = cat_elem_size(all_sizes[cid]);
    if (f.fd < 0) { delete r; return nullptr; }
    r->cats.push_back(f);
  }
  return r;
}

int64_t detpu_criteo_num_samples(void* handle) {
  return ((CriteoReader*)handle)->num_samples;
}

static inline float half_to_float(uint16_t h) {
  uint32_t sign = (uint32_t)(h >> 15) << 31;
  uint32_t exp = (h >> 10) & 0x1F;
  uint32_t mant = h & 0x3FF;
  uint32_t bits;
  if (exp == 0) {
    if (mant == 0) { bits = sign; }
    else {
      // subnormal: normalize
      int e = -1;
      do { mant <<= 1; ++e; } while (!(mant & 0x400));
      bits = sign | ((127 - 15 - e) << 23) | ((mant & 0x3FF) << 13);
    }
  } else if (exp == 31) {
    bits = sign | 0x7F800000u | (mant << 13);
  } else {
    bits = sign | ((exp - 15 + 127) << 23) | (mant << 13);
  }
  float out;
  memcpy(&out, &bits, 4);
  return out;
}

// Read one batch at sample offset `start`, `batch` samples:
//   labels_out [batch] f32, numerical_out [batch*num_numerical] f32,
//   cats_out [num_cats * batch] i32 (feature-major).
// Returns 0 on success.
int detpu_criteo_read_batch(void* handle, int64_t start, int64_t batch,
                            float* labels_out, float* numerical_out,
                            int32_t* cats_out) {
  CriteoReader* r = (CriteoReader*)handle;
  if (start + batch > r->num_samples) return -1;

  std::vector<uint8_t> buf;
  buf.resize((size_t)batch * 4);

  if (pread(r->label_fd, buf.data(), batch, start) != batch) return -2;
  for (int64_t i = 0; i < batch; ++i) labels_out[i] = (float)buf[i];

  if (r->numerical_fd >= 0) {
    int64_t nbytes = batch * r->num_numerical * 2;
    buf.resize(nbytes);
    if (pread(r->numerical_fd, buf.data(), nbytes,
              start * r->num_numerical * 2) != nbytes) return -3;
    const uint16_t* h = (const uint16_t*)buf.data();
    for (int64_t i = 0; i < batch * r->num_numerical; ++i)
      numerical_out[i] = half_to_float(h[i]);
  }

  for (size_t c = 0; c < r->cats.size(); ++c) {
    const CriteoFile& f = r->cats[c];
    int64_t nbytes = batch * f.elem_size;
    buf.resize(nbytes);
    if (pread(f.fd, buf.data(), nbytes, start * f.elem_size) != nbytes)
      return -4;
    int32_t* out = cats_out + c * batch;
    switch (f.elem_size) {
      case 1: {
        const int8_t* p = (const int8_t*)buf.data();
        for (int64_t i = 0; i < batch; ++i) out[i] = p[i];
        break;
      }
      case 2: {
        const int16_t* p = (const int16_t*)buf.data();
        for (int64_t i = 0; i < batch; ++i) out[i] = p[i];
        break;
      }
      default: {
        memcpy(out, buf.data(), nbytes);
        break;
      }
    }
  }
  return 0;
}

void detpu_criteo_close(void* handle) {
  delete (CriteoReader*)handle;  // destructor closes all fds
}

}  // extern "C"

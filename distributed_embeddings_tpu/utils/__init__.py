"""Data pipelines and metrics for the example models."""

from .checkpoint import restore_train_state, save_train_state
from .data import DummyDataset, RawBinaryDataset, power_law_ids
from .metrics import binary_auc

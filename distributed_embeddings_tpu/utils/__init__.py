"""Data pipelines and metrics for the example models."""

from .data import DummyDataset, RawBinaryDataset, power_law_ids
from .metrics import binary_auc

"""Data pipelines, metrics, checkpointing, the fault-tolerant runtime
layer, and the step-level observability layer for the example models and
entry points."""

from . import obs, runtime
from .checkpoint import (previous_checkpoint_path, reshard_checkpoint,
                         restore_train_state, ring_dir, ring_entries,
                         rollback_candidates, save_train_state,
                         validate_checkpoint_model, verify_checkpoint)
from .data import DummyDataset, RawBinaryDataset, fast_forward, power_law_ids
from .metrics import binary_auc
from .obs import (MetricsLogger, StepTimer, counter_inc, counters,
                  fetch_metrics, install_compile_listener,
                  maybe_start_server, metrics_enabled, nanguard_enabled,
                  nanguard_escalation_k, profile_trace, reset_counters,
                  scope)
from .runtime import (BackendProbe, BackendUnavailable, CheckpointCorrupt,
                      CheckpointMismatch, CoordinatorUnreachable,
                      DeadlineExceeded, DeviceSpec, FaultInjected,
                      InvalidInputError, NonFiniteLossError, SectionRecorder,
                      deadline, fault_point, preempt_step, probe_backend,
                      require_devices, retry, run_section)

"""Data pipelines, metrics, checkpointing, and the fault-tolerant runtime
layer for the example models and entry points."""

from . import runtime
from .checkpoint import (previous_checkpoint_path, restore_train_state,
                         save_train_state, verify_checkpoint)
from .data import DummyDataset, RawBinaryDataset, power_law_ids
from .metrics import binary_auc
from .runtime import (BackendProbe, BackendUnavailable, CheckpointCorrupt,
                      CoordinatorUnreachable, DeadlineExceeded, DeviceSpec,
                      FaultInjected, SectionRecorder, deadline, fault_point,
                      probe_backend, require_devices, retry, run_section)

"""Single registry of every ``DETPU_*`` environment variable.

The knob surface grew one env read at a time (``DETPU_OBS``,
``DETPU_FAULT``, ``DETPU_BENCH_SMOKE``, ...) with no one place that says
what exists, what the default is, or what a value means — and nothing
stopping a typo'd ``os.environ.get("DETPU_OBSS")`` from shipping as a
silently-dead knob. This module is that place: every ``DETPU_*`` variable
is :func:`declare`'d here with its default and one-line meaning, and the
``env-registry`` detlint rule (``tools/detlint/rules/env_registry.py``)
fails the build on any ``DETPU_*`` env read whose name is not registered.

Reads may keep using ``os.environ`` directly with a registered name (the
lint rule resolves literals and module-level ``X_ENV = "DETPU_X"``
constants), or go through :func:`get`/:func:`enabled`/:func:`get_float`,
which also raise loudly on an undeclared name at run time.

Like the rest of :mod:`..utils`'s host-side layer, this module never
imports jax: the registry must be readable by pure-AST tooling and by
processes that never load a backend.
"""

from __future__ import annotations

import os
from typing import Dict, NamedTuple, Optional


class EnvVar(NamedTuple):
    """One registered knob: its default (``None`` = unset) and meaning."""
    name: str
    default: Optional[str]
    doc: str


_REGISTRY: Dict[str, EnvVar] = {}


def declare(name: str, default: Optional[str] = None, doc: str = "") -> str:
    """Register one ``DETPU_*`` variable; returns the name so call sites
    can do ``FOO_ENV = declare("DETPU_FOO", ...)``. Declarations live in
    this module (below) so the detlint rule can extract the full set from
    the AST without importing anything."""
    _REGISTRY[name] = EnvVar(name, default, doc)
    return name


def registered() -> Dict[str, EnvVar]:
    """Snapshot of the full registry (name -> :class:`EnvVar`)."""
    return dict(_REGISTRY)


def _require(name: str) -> EnvVar:
    spec = _REGISTRY.get(name)
    if spec is None:
        raise KeyError(
            f"{name!r} is not a registered DETPU env var — declare it in "
            "distributed_embeddings_tpu/utils/envvars.py (the env-registry "
            "lint rule would reject the read anyway)")
    return spec


def get(name: str, default: Optional[str] = None) -> Optional[str]:
    """Read a registered variable; ``default`` overrides the declared
    default for this one call (tests and shims occasionally need that)."""
    spec = _require(name)
    fallback = spec.default if default is None else default
    return os.environ.get(name, fallback)


def enabled(name: str) -> bool:
    """Truthy read with the repo-wide convention: unset-with-falsy-default,
    empty, and ``"0"`` are off; anything else is on."""
    v = get(name)
    return v not in (None, "", "0")


def get_float(name: str, default: Optional[float] = None) -> float:
    """Float read of a registered variable; a malformed value falls back
    to the default instead of crashing a training run over a typo."""
    spec = _require(name)
    fb = default if default is not None else float(spec.default or 0.0)
    try:
        return float(os.environ.get(name, fb))
    except (TypeError, ValueError):
        return fb


def get_int(name: str, default: Optional[int] = None) -> int:
    """Int read of a registered variable (same fallback policy as
    :func:`get_float`)."""
    spec = _require(name)
    fb = default if default is not None else int(spec.default or 0)
    try:
        return int(os.environ.get(name, fb))
    except (TypeError, ValueError):
        return fb


# --------------------------------------------------------------------------
# The registry. One declare() per knob, literal names only (the lint rule
# reads these calls from the AST). Keep alphabetical within each block.
# --------------------------------------------------------------------------

# observability (utils/obs.py + utils/mplane.py)
declare("DETPU_BLACKBOX", default="1",
        doc="0 = disable the flight recorder (utils/mplane.py): no "
            "black-box ring is installed and no <dir>.blackbox.json "
            "post-mortem is dumped on NaN escalation / rollback "
            "exhaustion / freshness breach / preemption / crash")
declare("DETPU_BLACKBOX_RING", default="64",
        doc="flight-recorder ring capacity: how many recent step-metric "
            "summaries, events, and stats snapshots (each kind "
            "separately) the black-box dump carries")
declare("DETPU_METRICS_PORT", default=None,
        doc="opt-in Prometheus scrape endpoint port (utils/mplane.py "
            "start_http_exporter serves GET /metrics as text "
            "exposition); unset = no endpoint, 0 = ephemeral port "
            "(tests/drills read it back from the exporter handle)")
declare("DETPU_OBS", default="",
        doc="1 = build train steps with on-device step metrics (3-tuple "
            "return) and emit metrics sidecars")
declare("DETPU_OBS_MAX_BYTES", default="0",
        doc="MetricsLogger sidecar size cap in bytes; on overflow the "
            "file rotates through <path>.1..<path>.N "
            "(DETPU_OBS_MAX_FILES generations kept). 0 = unbounded "
            "(the historical behavior)")
declare("DETPU_OBS_MAX_FILES", default="2",
        doc="rotated MetricsLogger generations kept beyond the live "
            "sidecar (<path>.1 newest .. <path>.N oldest — the "
            "checkpoint-ring idiom); total disk is bounded by "
            "(N + 1) * DETPU_OBS_MAX_BYTES")
declare("DETPU_OBS_SIDECAR", default="BENCH.metrics.jsonl",
        doc="path of the step-metrics JSONL sidecar bench.py writes under "
            "DETPU_OBS=1")

# access telemetry (analysis/telemetry.py; carried through train steps
# built by parallel/trainer.py when enabled)
declare("DETPU_TELEMETRY", default="",
        doc="1 = telemetry-aware entry points (examples/dlrm, "
            "tools/obs_report.py, bench telemetry section) build their "
            "steps with jit-carried access telemetry. Plain step "
            "builders need the explicit telemetry= opt-in (it changes "
            "the step's call arity)")
declare("DETPU_TELEMETRY_CANDIDATES", default="0",
        doc="per-step unique-id candidates merged into the hot-row "
            "top-k; 0 = 4 * DETPU_TELEMETRY_TOPK")
declare("DETPU_TELEMETRY_INTERVAL", default="100",
        doc="metrics-log cadence (steps) of tools/obs_report.py's demo "
            "run (clamped to sample short runs)")
declare("DETPU_TELEMETRY_SKETCH_DEPTH", default="4",
        doc="count-min sketch rows (independent hashes) per width slab")
declare("DETPU_TELEMETRY_SKETCH_WIDTH", default="2048",
        doc="count-min sketch buckets per row; estimate error ~ "
            "total_ids/buckets")
declare("DETPU_TELEMETRY_TOPK", default="32",
        doc="hot-row slots tracked per width slab per rank")
declare("DETPU_PROFILE_DIR", default=None,
        doc="directory for XLA profile captures (obs.profile_trace); "
            "unset = no capture")
declare("DETPU_PROFILE_PORT", default=None,
        doc="port for a live jax profiler server (obs.maybe_start_server); "
            "unset = no server")

# measured phase-time observatory (analysis/phase_profile.py +
# tools/phase_profile.py = make phase-profile)
declare("DETPU_PHASE_PROFILE_STEPS", default="5",
        doc="timed steps captured per case by the measured phase profile "
            "(each step gets its own jax.profiler.trace so per-phase "
            "numbers carry real p50/p95 spread)")
declare("DETPU_PHASE_PROFILE_DIR", default=None,
        doc="keep the phase-profile trace captures (TensorBoard-loadable) "
            "under this directory instead of a deleted temp dir")
declare("DETPU_PHASE_DRIFT_MAX", default="2.0",
        doc="calibration flag threshold: a phase whose measured/modeled "
            "cost ratio exceeds this factor (or falls below its inverse) "
            "relative to the step's cost-weighted median ratio is "
            "reported as model drift (analysis.phase_profile.calibrate)")

# streaming vocab: frequency-gated admission + approximate-LFU eviction
# (parallel/streaming.py; carried through train steps built by
# parallel/trainer.py with dynamic=)
declare("DETPU_ADMIT_MIN_COUNT", default="2",
        doc="count-min estimate an external id needs before it may claim "
            "a dynamic-table slot; below it the id is served from its "
            "shared hash bucket")
declare("DETPU_ADMIT_SKETCH_DEPTH", default="4",
        doc="admission count-min sketch rows (independent hashes) per "
            "streaming width slab")
declare("DETPU_ADMIT_SKETCH_WIDTH", default="4096",
        doc="admission count-min sketch buckets per row; estimate error "
            "~ total_ids/buckets")
declare("DETPU_EVICT_MARGIN", default="1",
        doc="approximate-LFU eviction margin: a claim on an occupied "
            "slot succeeds only when the incoming estimate >= occupant "
            "frequency + margin (0 = ties evict)")

# pipelined hybrid step (parallel/schedule.py + parallel/trainer.py):
# K-microbatch software pipelining that hides the all-to-all exchange
# under dense compute (ROADMAP item 2)
declare("DETPU_MICROBATCH", default="2",
        doc="microbatch count K of steps built with a pipelined schedule "
            "(parallel.schedule.pipelined_schedule(K=None) resolves K "
            "here — only schedule='pipelined' opt-ins read it; the "
            "default schedule stays serialized regardless). The global "
            "batch splits into K chains inside ONE jitted step — "
            "microbatch k+1's id all-to-all is data-independent of "
            "microbatch k's dense fwd/bwd, so XLA can overlap them — "
            "with gradients accumulated so the applied update matches "
            "the serialized step (K=1 IS the serialized baseline, "
            "bitwise — the opt-in default is 2 so asking for a pipeline "
            "actually builds one). The per-device batch must divide by K")
declare("DETPU_MICROBATCH_BENCH", default="2",
        doc="microbatch count K of bench.py's `pipeline` section (the "
            "pipelined-vs-serialized throughput A/B); independent of "
            "DETPU_MICROBATCH so a bench run never inherits a training "
            "run's K")

# deadline-bounded serving runtime (parallel/serving.py +
# tools/serve_bench.py / tools/check_serving.py = make check-serving)
declare("DETPU_SERVE_BURST_X", default="8",
        doc="arrival-rate multiplier of the burst@<pos> QPS-spike drill "
            "(the serving load generator applies it during each burst "
            "second; the admission controller must absorb the spike)")
declare("DETPU_SERVE_DEADLINE_MS", default="100",
        doc="default per-request deadline (ms, from submit): the "
            "scheduler flushes early to make it, drops requests already "
            "past it (typed Expired, counted deadline_missed) instead "
            "of wasting a rung on answers nobody is waiting for; "
            "requests may pin their own deadline_ms")
declare("DETPU_SERVE_MAX_BATCH", default="256",
        doc="largest padded-batch rung (global samples per flush) of "
            "the serving coalescer's compiled-executable ladder")
declare("DETPU_SERVE_MAX_QUEUE", default="1024",
        doc="hard admission bound (queued samples): a submit that would "
            "exceed it is shed with a typed Overloaded response — queue "
            "growth is bounded by construction, whatever the QPS")
declare("DETPU_SERVE_MAX_WAIT_MS", default="5",
        doc="batching delay: a queued request is flushed no later than "
            "this many ms after submit even when the batch is not full "
            "(the degradation ladder shrinks it to 0 under pressure)")
declare("DETPU_SERVE_RUNGS", default="",
        doc="comma-separated explicit padded-batch ladder (global "
            "samples, ascending, each divisible by the world size) "
            "overriding the power-of-two default; one compiled "
            "executable per rung, warmed up front so steady-state "
            "serving never recompiles")
declare("DETPU_SERVE_SHED_FRAC", default="0.5",
        doc="queue fraction of DETPU_SERVE_MAX_QUEUE at which the "
            "admission controller enters its shed level: new lowest-"
            "priority (<= 0) requests are refused with a typed "
            "Overloaded response while higher-priority traffic keeps "
            "being served")
declare("DETPU_SERVE_SLO_MS", default="2000",
        doc="p99 latency bound (ms) the make check-serving overload "
            "drill enforces on served requests — generous on the CPU "
            "proxy (flushes are injected 20+ ms slow there); tighten "
            "per deployment for a real SLO")

# online learning runtime: concurrent train-and-serve with RCU snapshot
# publication and a freshness SLO (parallel/online.py +
# tools/check_online.py = make check-online)
declare("DETPU_FRESHNESS_MAX_S", default="0",
        doc="wall-clock half of the freshness SLO (seconds): when the "
            "installed serving snapshot's age exceeds it the runtime "
            "enters its freshness shed rung, like the step half below. "
            "0 = disabled (step SLO only)")
declare("DETPU_FRESHNESS_MAX_STEPS", default="8",
        doc="staleness SLO in train steps: when snapshot publication "
            "falls more than this many completed steps behind training, "
            "serving enters its shed rung (new priority<=0 requests are "
            "refused with a typed Overloaded reason='stale_snapshot', a "
            "snapshot_lagging event fires) — load is shed serve-side "
            "before training is ever blocked on publication; the next "
            "publication recovers. <=0 disables the step SLO")
declare("DETPU_ONLINE_PUBLISH_STEPS", default="1",
        doc="publication cadence (train steps) of the online runtime's "
            "RCU snapshot publisher: every N completed steps the "
            "training tables are copied into fresh buffers and installed "
            "atomically as one monotonically-versioned serving view "
            "(rollback-and-replay republishes immediately, whatever the "
            "cadence)")

# process-isolated serving: shared-memory snapshot transport + the
# serving-worker supervisor (utils/shm.py + parallel/supervisor.py +
# tools/check_isolation.py = make check-isolation)
declare("DETPU_SHM_READ_RETRIES", default="8",
        doc="seqlock read attempts per SnapshotShm.read_latest() call: a "
            "reader that keeps catching the writer mid-publish (sequence "
            "stamps disagree or the CRC32 fails) retries this many times, "
            "then returns None and keeps serving its previous snapshot — "
            "a torn cross-process read is impossible by construction, "
            "only a missed refresh")
declare("DETPU_SHM_SLACK", default="1.25",
        doc="sizing multiplier for the shared-memory snapshot region: "
            "each of the two seqlock buffers holds slack * the template "
            "payload's serialized bytes (pickle framing varies a little "
            "run to run; shapes/dtypes never do). A later payload that "
            "exceeds the buffer raises — the region is sized once, "
            "before the worker attaches")
declare("DETPU_SUPERVISE_BACKOFF_BASE_S", default="0.1",
        doc="base delay of the supervisor's jittered exponential backoff "
            "between serving-worker restart attempts (the runtime.retry "
            "idiom: doubles per attempt, jittered in [0.5x, 1.5x))")
declare("DETPU_SUPERVISE_BACKOFF_MAX_S", default="2",
        doc="cap on the supervisor's restart backoff delay (seconds)")
declare("DETPU_SUPERVISE_DEADLINE_S", default="5",
        doc="heartbeat deadline: a serving worker whose last pong is "
            "older than this is declared HUNG, killed (SIGKILL — hang "
            "detection never depends on the child cooperating) and "
            "restarted under the restart budget")
declare("DETPU_SUPERVISE_HEARTBEAT_S", default="0.25",
        doc="interval between supervisor heartbeat pings to the serving "
            "worker; pongs carry the worker's live stats subset")
declare("DETPU_SUPERVISE_MAX_RESTARTS", default="3",
        doc="restart budget per Supervisor lifetime: after this many "
            "worker deaths the supervisor stays down (every request "
            "answers typed Unavailable) instead of crash-looping — "
            "training is never taken down with it")
declare("DETPU_SUPERVISE_START_TIMEOUT_S", default="300",
        doc="deadline for a (re)started serving worker to finish its "
            "warmup and report ready; a worker that blows it is treated "
            "as crashed (kill + backoff + next attempt)")

# cross-process request tracing: per-request causal spans with
# tail-based sampling and a bounded retained ring (utils/reqtrace.py +
# tools/check_tracing.py = make check-tracing)
declare("DETPU_TRACE", default="1",
        doc="request tracing master switch: when enabled every "
            "ServingRuntime/Supervisor submit mints a trace whose stage "
            "spans partition the request's life (sum == latency_ms); "
            "the per-request cost is a dict and a hash, and the bench "
            "tracing section gates that tracing-off throughput is "
            "unchanged. Empty/0 disables minting entirely")
declare("DETPU_TRACE_RING", default="256",
        doc="capacity of the retained-trace ring per TraceBuffer: "
            "tail-sampled traces beyond this evict oldest-first, so "
            "trace memory is bounded no matter the burst (the 10x-burst "
            "property tests/test_reqtrace.py pins)")
declare("DETPU_TRACE_SAMPLE", default="0.02",
        doc="retention probability for HEALTHY served traces that miss "
            "the latency top decile; applied as a deterministic hash of "
            "(DETPU_TRACE_SEED, trace_id), never a random draw. "
            "Unhealthy outcomes (expired/failed/overloaded/unavailable) "
            "and top-decile latencies are always retained — that is the "
            "tail-based half of the policy")
declare("DETPU_TRACE_SEED", default="0",
        doc="seed of the deterministic sampling hash (and of minted "
            "trace ids): pin it and the same request stream replays the "
            "same retention decisions run-to-run, which is what makes "
            "sampled traces reproducible in drills and tests")

# concurrency auditor: lock-discipline analysis + interleaving model
# checker over the serving plane (analysis/concurrency_audit.py +
# tools/concurrency_audit.py = make concurrency-audit)
declare("DETPU_CONCURRENCY_DEPTH", default="8",
        doc="virtual-clock tick bound of the supervisor heartbeat model "
            "explored by make concurrency-audit: larger values widen "
            "the interleaving space (more crash/restart phases per "
            "proof) at exponential state cost; 8 covers two full "
            "fault -> detect -> restart -> re-ingest cycles")
declare("DETPU_CONCURRENCY_WORDS", default="2",
        doc="payload words in the seqlock interleaving model: each word "
            "is an independently-timed copy step, so more words = more "
            "distinct torn prefixes the explorer must prove detected; "
            "2 already exhibits every mix class (old/new, new/old)")

# non-finite guard (utils/obs.py + parallel/trainer.py + resilient.py)
declare("DETPU_NANGUARD", default="1",
        doc="on-device non-finite guard in the hybrid step; 0 = build the "
            "unguarded step")
declare("DETPU_NANGUARD_K", default="3",
        doc="consecutive guard-skipped steps before the resilient driver "
            "enters rollback-and-replay recovery (and, once the rollback "
            "budget is exhausted, escalates NonFiniteLossError)")

# rollback-and-replay recovery (parallel/resilient.py + utils/checkpoint.py)
declare("DETPU_CKPT_RING", default="2",
        doc="ring size of last-good checkpoints kept BEYOND <dir> and "
            "<dir>.prev (utils.checkpoint.save_train_state keep_last_n): "
            "each save archives the displaced .prev under <dir>.ring/ and "
            "prunes to this many entries; the rollback-and-replay recovery "
            "restores the newest healthy entry predating the poisoned "
            "window. 0 = no ring (the pre-ring layout)")
declare("DETPU_ROLLBACK_MAX", default="2",
        doc="rollback-and-replay attempts per resilient run before the "
            "NaN escalation turns terminal (NonFiniteLossError with the "
            "quarantine ledger attached); persisted in the ledger so the "
            "budget survives preemption/resume")
declare("DETPU_QUARANTINE_MAX", default="8",
        doc="max batches the recovery may quarantine (total, across "
            "rollbacks) before declaring the stream poisoned and raising "
            "terminally — a transient bad window is quarantinable, a "
            "fully-poisoned stream is not")

# per-table numerical health sentinels (parallel/trainer.py + utils/obs.py)
declare("DETPU_HEALTH_GRAD_NORM", default="0",
        doc="per-table sparse-gradient L2-norm threshold for the health "
            "contract (obs.TableHealthContract): a table whose "
            "table_grad_norm exceeds it is named unhealthy in recovery "
            "logs/events. <= 0 = disabled (non-finite counts are always "
            "checked)")
declare("DETPU_HEALTH_UPDATE_MAXABS", default="0",
        doc="per-table row-update max-abs threshold for the health "
            "contract; <= 0 = disabled")

# fault injection + runtime probes (utils/runtime.py)
declare("DETPU_FAULT", default="",
        doc="comma-separated fault injections: hang|slow|raise|die:<point>, "
            "preempt@<step> (driver self-SIGTERM drill), corrupt@ckpt "
            "(flip bytes in each just-committed checkpoint shard so the "
            "CRC manifest + .prev fallback are exercisable end to end), "
            "nan@<step> (poison one rank's loss at that batch — the NaN-"
            "storm drill the rollback-and-replay recovery quarantines), or "
            "badbatch@<step> (corrupt that input batch's categorical ids — "
            "exercises the invalid-input policies end to end), or "
            "oovflood@<pos> (replace that batch's categorical ids with a "
            "burst of never-before-seen ids — the non-stationary-traffic "
            "drill the streaming-vocab admission/bucket machinery must "
            "absorb without recompiles or crashes), or burst@<pos> (QPS "
            "spike: the serving load generator multiplies the arrival "
            "rate by DETPU_SERVE_BURST_X during that second of the "
            "stream — the overload drill the serving runtime's "
            "degradation ladder must absorb with clean typed shedding, "
            "bounded p99, and post-burst recovery). Specs comma-combine: "
            "oovflood@P,burst@P is the joint online-learning chaos drill "
            "(a traffic spike of never-seen ids while serving, make "
            "check-online); in the online runtime burst@ positions are "
            "train-step ordinals (requests-per-step multiply by "
            "DETPU_SERVE_BURST_X at those steps). die@<pos> / hang@<pos> "
            "target a SUPERVISED serving worker (parallel/supervisor.py): "
            "at that arrival ordinal the worker hard-exits (die@, the "
            "SIGKILL/OOM equivalent) or stops answering (hang@, the "
            "wedged-process equivalent) — the supervisor must detect "
            "either, answer in-flight requests typed Unavailable, dump "
            "the black box on the child's behalf, and restart within its "
            "budget (make check-isolation)")
declare("DETPU_ON_MISMATCH", default="reshard",
        doc="resilient-driver restore policy when a checkpoint's recorded "
            "sharding plan/world size differs from the model's: 'reshard' "
            "= re-slice the logical tables under the current plan and "
            "continue (elastic resume; degradation logged), 'error' = "
            "raise CheckpointMismatch (the strict pre-elastic behavior)")
declare("DETPU_PROBE_TIMEOUT_S", default="120",
        doc="time box (seconds) for the subprocess backend probe")
declare("DETPU_DRYRUN_TIMEOUT_S", default="600",
        doc="time box (seconds) for the __graft_entry__ dryrun child")
declare("_DETPU_DRYRUN_CHILD", default=None,
        doc="internal: set in the dryrun child's environment so it knows "
            "to touch the backend directly")

# bench.py
declare("DETPU_BENCH_SMOKE", default="",
        doc="1 = shrink every bench shape to smoke-test size")
declare("DETPU_BENCH_SIDECAR", default="BENCH.partial.jsonl",
        doc="path of bench.py's crash-surviving per-section JSONL sidecar")
declare("DETPU_BENCH_SECTION_DEADLINE_S", default="1200",
        doc="best-effort SIGALRM deadline (seconds) per bench section")

# sparse optimizer paths (parallel/optimizers.py, parallel/sparse_optax.py)
declare("DETPU_SGD_DEDUP", default="",
        doc="1 = force the sort/segment-sum dedup pass back INTO the "
            "SGD sparse paths that statically skip it (SparseSGD declares "
            "needs_dedup=False; sparse_value_and_grad(dedup=False)) — the "
            "A/B escape hatch for the ROADMAP 3(a) pass cut. Read at step "
            "BUILD time; trajectories are mathematically identical either "
            "way (SGD is linear in the gradient)")

# debug / test harness
declare("DETPU_DEBUG_LANE_EXTRACT", default="0",
        doc="1 = swap the packed-slab lane extraction for the reference "
            "gather (ops/packed_slab.py divergence debugging)")
declare("DETPU_FORCE_CPU_DEVICES", default=None,
        doc="N = examples force JAX_PLATFORMS=cpu with N virtual host "
            "devices (test harness for the example mains)")

"""Cross-process request tracing: per-request causal spans with
tail-based sampling, a bounded retained ring, and Chrome-trace export.

PR 17's five-stage p99 decomposition is an *aggregate*: it can say the
``device_compute`` stage dominates the tail but not WHICH requests were
exchange-bound, and PR 18's process boundary made even the aggregate
one-sided (the worker's sketches never reach the supervisor's scrape).
This module is the per-request instrument. One class, stdlib only —
like the rest of the host layer it never imports jax or numpy, so the
supervisor, the worker, and an offline reader all share it:

* **Trace minting** — :meth:`TraceBuffer.begin` mints a deterministic
  trace id at ``submit`` time (``f(seed, rid)`` — never a wall clock,
  never ``random``), or ADOPTS a context minted elsewhere: the
  supervisor mints at its ``submit``, the context dict rides the
  existing request queue (``Request.trace``), and the worker's runtime
  re-parents its stage spans under the supervisor's id — across a
  ``die@`` restart the reborn worker keeps adopting, so one trace id
  names the request's whole life on both sides of the boundary.
* **Span model** — every finished trace carries ``stages_ms``, a dict
  of stage spans that PARTITIONS ``[t_submit, t_end]``: their sum
  equals ``latency_ms`` within float error (the invariant ``make
  check-tracing`` asserts at 1e-6 ms). Served requests carry the five
  :data:`~..parallel.serving.STAGES`; terminal non-served outcomes
  (``expired`` / ``failed`` / ``overloaded`` / ``unavailable``) carry
  the minimal ``{"queue_wait": latency_ms}`` span so the unhealthy
  tail is traceable too. Lifecycle annotations (``outage``, ``worker
  _restarted``, ``boundary``) ride ``events`` — markers, deliberately
  OUTSIDE the partition sum.
* **Tail-based sampling** — :meth:`finish` always retains traces whose
  outcome is not ``served``, retains served traces whose latency lands
  at or above the owner's top-decile threshold (``top_fn``, typically
  the latency sketch's q90), and samples the healthy rest at
  ``DETPU_TRACE_SAMPLE`` via a deterministic hash of ``(seed,
  trace_id)`` — the same seed replays the same retention decisions,
  which is what makes the sampling testable run-to-run.
* **The bounded ring** — at most ``DETPU_TRACE_RING`` retained traces,
  oldest evicted first; memory never grows with load (the 10x-burst
  property ``tests/test_reqtrace.py`` pins). :meth:`drain_new` hands
  newly retained traces to the flight recorder exactly once.
* **Chrome-trace export** — :meth:`export` writes the ring as a
  standard ``traceEvents`` JSON document (names under
  :data:`~.obs.REQ_EVENT_PREFIX`, one enclosing ``req/<outcome>``
  event per trace, ``req/stage/<name>`` children laid out
  sequentially, one ``req/flush`` coalesce span linking the requests
  that shared a flush) that :func:`~.traceparse.parse_request_traces`
  and ``tools/obs_report.py --traces`` read back.

The buffer is thread-safe: one internal lock covers the active table,
the ring, and every counter — the serving driver finishes traces while
the trainer thread annotates and the exporter thread snapshots
(``analysis/concurrency_audit.py`` lists :class:`TraceBuffer` among
the synchronized types for exactly this reason).
"""

from __future__ import annotations

import collections
import gzip
import json
import threading
import zlib
from typing import Any, Callable, Dict, List, Optional

from . import envvars
from .obs import REQ_EVENT_PREFIX

TRACE_ENV = "DETPU_TRACE"
RING_ENV = "DETPU_TRACE_RING"
SAMPLE_ENV = "DETPU_TRACE_SAMPLE"
SEED_ENV = "DETPU_TRACE_SEED"

#: span-sum tolerance (ms): ``sum(stages_ms) == latency_ms`` within this
#: for every retained trace — the partition invariant the check drills
SPAN_SUM_TOL_MS = 1e-6


def hash01(seed: int, trace_id: str) -> float:
    """Deterministic [0, 1) probe for one trace id: a CRC32 of
    ``"{seed}:{trace_id}"`` scaled down. No wall clock, no ``random``
    module — the retention decision replays bit-identically under a
    pinned seed (the sampling-determinism contract)."""
    h = zlib.crc32(f"{seed}:{trace_id}".encode("utf-8")) & 0xFFFFFFFF
    return h / 2.0 ** 32


class TraceBuffer:
    """Thread-safe per-process request-trace store: active table +
    bounded retained ring + the sampling policy.

    ``top_fn`` (optional) returns the owner's current top-decile
    latency threshold in ms (e.g. the serving latency sketch's q90) or
    ``None`` while the estimate is cold; ``process`` labels exported
    events so merged multi-process captures stay attributable.
    Construction resolves ``None`` policy knobs from the registered
    ``DETPU_TRACE_*`` environment defaults.
    """

    def __init__(self, capacity: Optional[int] = None,
                 sample: Optional[float] = None,
                 seed: Optional[int] = None,
                 enabled: Optional[bool] = None,
                 process: str = "serve",
                 top_fn: Optional[Callable[[], Optional[float]]] = None):
        self.enabled = (envvars.enabled(TRACE_ENV) if enabled is None
                        else bool(enabled))
        self.capacity = max(1, int(envvars.get_int(RING_ENV)
                                   if capacity is None else capacity))
        self.sample = float(envvars.get_float(SAMPLE_ENV)
                            if sample is None else sample)
        self.seed = int(envvars.get_int(SEED_ENV) if seed is None else seed)
        self.process = str(process)
        self._top_fn = top_fn
        self._lock = threading.Lock()
        # rid -> {"trace_id", "t_submit", "events", "attrs"}; bounded by
        # the owner's admission control (every submitted rid terminates
        # through exactly one finish())
        self._active: Dict[int, Dict[str, Any]] = {}
        self._ring: collections.deque = collections.deque()
        self._by_id: Dict[str, Dict[str, Any]] = {}  # retained index
        self._seq = 0
        self._drained_seq = 0
        self.finished = 0
        self.retained_total = 0
        self.sampled_out = 0
        self.evicted = 0

    # ------------------------------------------------------------- intake

    def mint(self, rid: int) -> str:
        """The deterministic trace id for one rid under this buffer's
        seed (pure function — reborn processes re-derive it)."""
        return f"t{self.seed & 0xFFFFFFFF:08x}-{int(rid):08d}"

    def begin(self, rid: int, t_submit: float,
              ctx: Optional[Dict[str, Any]] = None,
              **attrs: Any) -> Optional[Dict[str, Any]]:
        """Open the trace for one rid at submit time and return its
        portable span context (``None`` when tracing is off — callers
        pass the result straight into ``Request.trace``).

        ``ctx`` re-parents: when a context minted by another process
        (the supervisor) rides in, its ``trace_id`` is adopted verbatim
        so this process's spans join the existing trace instead of
        starting a sibling."""
        if not self.enabled:
            return None
        trace_id = (str(ctx["trace_id"]) if ctx and ctx.get("trace_id")
                    else self.mint(rid))
        rec = {"trace_id": trace_id, "t_submit": float(t_submit),
               "events": [], "attrs": dict(attrs)}
        if ctx and ctx.get("attrs"):
            rec["attrs"].update(ctx["attrs"])
        with self._lock:
            self._active[int(rid)] = rec
        return {"trace_id": trace_id, "rid": int(rid),
                "t_submit": float(t_submit)}

    def event(self, rid: int, name: str, t: Optional[float] = None,
              dur_ms: float = 0.0, **attrs: Any) -> None:
        """Append one lifecycle annotation to an ACTIVE trace (markers
        like ``outage`` — outside the stage partition by design)."""
        if not self.enabled:
            return
        with self._lock:
            rec = self._active.get(int(rid))
            if rec is None:
                return
            rec["events"].append(dict({"name": str(name), "t": t,
                                       "dur_ms": float(dur_ms)}, **attrs))

    # ------------------------------------------------------ finish/retain

    def finish(self, rid: int, outcome: str, latency_ms: float,
               t_end: float, stages_ms: Dict[str, float],
               **attrs: Any) -> Optional[Dict[str, Any]]:
        """Close one trace with its terminal outcome and stage
        partition, apply the tail-sampling policy, and return the
        retained trace dict (``None`` when sampled out or tracing is
        off). ``stages_ms`` must sum to ``latency_ms`` within
        :data:`SPAN_SUM_TOL_MS` — the caller owns the partition."""
        if not self.enabled:
            return None
        rid = int(rid)
        with self._lock:
            rec = self._active.pop(rid, None)
        if rec is None:
            # finish without begin (e.g. a context-free supervisor-side
            # answer): synthesize so the outcome is still traceable
            rec = {"trace_id": self.mint(rid),
                   "t_submit": float(t_end) - float(latency_ms) / 1e3,
                   "events": [], "attrs": {}}
        trace = {
            "trace_id": rec["trace_id"],
            "rid": rid,
            "outcome": str(outcome),
            "latency_ms": float(latency_ms),
            "t_submit": rec["t_submit"],
            "t_end": float(t_end),
            "stages_ms": {str(k): float(v) for k, v in stages_ms.items()},
            "events": rec["events"],
            "attrs": dict(rec["attrs"], **attrs),
            "process": self.process,
        }
        keep, why = self._retain_decision(trace)
        with self._lock:
            self.finished += 1
            if not keep:
                self.sampled_out += 1
                return None
            trace["retained_because"] = why
            self._seq += 1
            trace["seq"] = self._seq
            self._ring.append(trace)
            self._by_id[trace["trace_id"]] = trace
            while len(self._ring) > self.capacity:
                old = self._ring.popleft()
                self._by_id.pop(old["trace_id"], None)
                self.evicted += 1
            self.retained_total += 1
        return trace

    def _retain_decision(self, trace: Dict[str, Any]) -> (bool, str):
        # tail-based: every unhealthy outcome is evidence, never sampled
        if trace["outcome"] != "served":
            return True, "outcome"
        thr = None
        if self._top_fn is not None:
            try:
                thr = self._top_fn()
            except Exception:  # noqa: BLE001 - a cold/broken threshold
                # source must not take the tracing plane down
                thr = None
        if thr is not None and trace["latency_ms"] >= thr:
            return True, "top_decile"
        if hash01(self.seed, trace["trace_id"]) < self.sample:
            return True, "sampled"
        return False, ""

    # ----------------------------------------- post-retention annotation

    def append_event(self, trace_id: str, name: str,
                     t: Optional[float] = None, dur_ms: float = 0.0,
                     **attrs: Any) -> bool:
        """Append a lifecycle annotation to an already-RETAINED trace
        (the restart-crossing path: the supervisor appends ``worker_
        restarted`` / ``served_after_restart`` to the outage trace it
        finished when the worker died). Returns whether the trace was
        still in the ring."""
        with self._lock:
            tr = self._by_id.get(str(trace_id))
            if tr is None:
                return False
            tr["events"].append(dict({"name": str(name), "t": t,
                                      "dur_ms": float(dur_ms)}, **attrs))
            return True

    def annotate(self, trace_id: str, **attrs: Any) -> bool:
        """Merge attrs into a retained trace (e.g. ``restart_crossed``)."""
        with self._lock:
            tr = self._by_id.get(str(trace_id))
            if tr is None:
                return False
            tr["attrs"].update(attrs)
            return True

    # -------------------------------------------------------------- views

    def snapshot(self) -> List[Dict[str, Any]]:
        """The retained ring, oldest first (structure-copied: later
        annotation never mutates a snapshot a reader already holds)."""
        with self._lock:
            return [self._copy(t) for t in self._ring]

    @staticmethod
    def _copy(t: Dict[str, Any]) -> Dict[str, Any]:
        out = dict(t)
        out["stages_ms"] = dict(t["stages_ms"])
        out["events"] = [dict(e) for e in t["events"]]
        out["attrs"] = dict(t["attrs"])
        return out

    def drain_new(self) -> List[Dict[str, Any]]:
        """Retained traces appended since the last drain (each handed
        out exactly once — the flight-recorder feed)."""
        with self._lock:
            out = [self._copy(t) for t in self._ring
                   if t["seq"] > self._drained_seq]
            self._drained_seq = self._seq
        return out

    def exemplars(self, k: int = 5) -> List[Dict[str, Any]]:
        """The ``p99_exemplars`` view: the ``k`` slowest retained
        traces, each with its trace id, outcome, and per-stage
        breakdown plus the stage that dominated it — the join between
        the aggregate p99 attribution and actual requests."""
        with self._lock:
            worst = sorted(self._ring, key=lambda t: -t["latency_ms"])[:k]
            out = []
            for t in worst:
                stages = t["stages_ms"]
                out.append({
                    "trace_id": t["trace_id"],
                    "rid": t["rid"],
                    "outcome": t["outcome"],
                    "latency_ms": t["latency_ms"],
                    "stages_ms": dict(stages),
                    "dominant_stage": (max(stages, key=stages.get)
                                       if stages else None),
                })
            return out

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            return {"enabled": self.enabled, "capacity": self.capacity,
                    "retained": len(self._ring),
                    "retained_total": self.retained_total,
                    "finished": self.finished,
                    "sampled_out": self.sampled_out,
                    "evicted": self.evicted, "sample": self.sample,
                    "seed": self.seed}

    # ------------------------------------------------------------- export

    def to_chrome(self) -> Dict[str, Any]:
        """The retained ring as a Chrome trace-event document (the
        format ``utils/traceparse.py`` already parses). All request
        events live under :data:`~.obs.REQ_EVENT_PREFIX` so mixed
        captures keep device op events and request spans separable."""
        return traces_to_chrome(self.snapshot())

    def export(self, path: str) -> str:
        """Write :meth:`to_chrome` as JSON (gzip when the path ends in
        ``.gz``); returns the path."""
        body = json.dumps(self.to_chrome())
        if path.endswith(".gz"):
            with gzip.open(path, "wt", encoding="utf-8") as f:
                f.write(body)
        else:
            with open(path, "w", encoding="utf-8") as f:
                f.write(body)
        return path


def traces_to_chrome(traces: List[Dict[str, Any]]) -> Dict[str, Any]:
    """Render finished trace dicts as one Chrome trace-event document:
    an enclosing ``req/<outcome>`` X event per trace (args carry the
    trace id, rid, outcome, and attrs), ``req/stage/<name>`` children
    laid out sequentially from ``t_submit`` (the partition renders as
    touching spans), ``req/mark/<name>`` lifecycle annotations, and ONE
    ``req/flush`` span per flush id — the coalesce span linking the N
    request traces that shared a flush."""
    events: List[Dict[str, Any]] = []
    flushes: Dict[Any, Dict[str, Any]] = {}
    for t in traces:
        base_us = t["t_submit"] * 1e6
        tid = int(t.get("rid", 0))
        args = dict(t.get("attrs", {}))
        args.update(trace_id=t["trace_id"], rid=t.get("rid"),
                    outcome=t["outcome"], latency_ms=t["latency_ms"],
                    process=t.get("process", "?"),
                    retained_because=t.get("retained_because"))
        events.append({"name": REQ_EVENT_PREFIX + t["outcome"],
                       "ph": "X", "ts": base_us,
                       "dur": t["latency_ms"] * 1e3,
                       "pid": 1, "tid": tid, "args": args})
        cur = base_us
        for stage, ms in t.get("stages_ms", {}).items():
            events.append({"name": f"{REQ_EVENT_PREFIX}stage/{stage}",
                           "ph": "X", "ts": cur, "dur": ms * 1e3,
                           "pid": 1, "tid": tid,
                           "args": {"trace_id": t["trace_id"],
                                    "stage": stage, "ms": ms}})
            cur += ms * 1e3
        for ev in t.get("events", []):
            ts = (ev.get("t") * 1e6 if ev.get("t") is not None
                  else base_us + t["latency_ms"] * 1e3)
            extra = {k: v for k, v in ev.items()
                     if k not in ("name", "t", "dur_ms")}
            events.append({"name": f"{REQ_EVENT_PREFIX}mark/{ev['name']}",
                           "ph": "X", "ts": ts,
                           "dur": ev.get("dur_ms", 0.0) * 1e3,
                           "pid": 1, "tid": tid,
                           "args": dict(extra, trace_id=t["trace_id"])})
        fid = t.get("attrs", {}).get("flush")
        if fid is not None and fid not in flushes:
            t0 = t["attrs"].get("flush_t0", t["t_submit"])
            flushes[fid] = {
                "name": REQ_EVENT_PREFIX + "flush", "ph": "X",
                "ts": t0 * 1e6, "dur": max(0.0, (t["t_end"] - t0) * 1e6),
                "pid": 1, "tid": tid,
                "args": {"flush_id": fid,
                         "coalesced": t["attrs"].get("coalesced"),
                         "rung": t["attrs"].get("rung")}}
    events.extend(flushes.values())
    return {"traceEvents": events, "displayTimeUnit": "ms"}

"""Full train-state checkpoint/resume for hybrid-parallel training.

The reference checkpoints only embedding tables (``get_weights`` +
``np.savez``, ``examples/dlrm/main.py:246-248``) — "no optimizer-state or
step checkpointing" (SURVEY §5). Here the WHOLE
:class:`~.parallel.trainer.HybridTrainState` round-trips:

* embedding tables stream through
  :meth:`~.parallel.DistributedEmbedding.get_weights` /
  :meth:`~.parallel.DistributedEmbedding.set_weights` (chunked, multi-host
  safe, mmap restore) into per-table ``.npy`` files;
* sparse-optimizer slab state rides the SAME path — the optimizer states
  are width-keyed slab dicts shaped exactly like the params
  (:class:`~.parallel.optimizers.SparseAdagrad` accumulators,
  :class:`~.parallel.optimizers.SparseMomentum` traces) or tuples of them
  plus small counters (:class:`~.parallel.optimizers.SparseAdam`), so each
  component reassembles to per-table arrays;
* the replicated dense params / dense optimizer state / step counter
  serialize with ``flax.serialization`` msgpack.

Layout under ``path/``::

    tables/table_000.npy ...
    emb_opt/<component>/table_000.npy ...   # slab-shaped components
    emb_opt/<component>.npy                 # non-slab leaves (Adam counts)
    dense.msgpack                           # dense params+opt+step

Multi-host: every process calls both functions (the streamed fetches are
collective); only process 0 writes, and restore reads are per-process.

Fault tolerance (``utils.runtime``): a killed process mid-checkpoint and a
torn file on disk are normal operating conditions, not fatal errors.

* **Atomic writes.** Every file goes through tmp-file + fsync + rename,
  and the whole checkpoint is staged in ``<path>.staging`` then swapped
  into ``<path>`` in one directory rename — a reader never observes a
  half-written (torn) checkpoint at ``<path>``. One narrow window exists:
  the swap is two renames (old → ``.prev``, staging → ``path``), so a
  crash exactly between them leaves ``path`` absent while the old
  checkpoint sits COMPLETE at ``<path>.prev`` (and the new one at
  ``<path>.staging``) — :func:`restore_train_state`'s default fallback
  recovers from ``.prev`` automatically; only torn state is impossible.
* **Self-validation.** ``meta.json`` records a CRC32 per file; it is
  written last, so its presence certifies the set. :func:`verify_checkpoint`
  re-hashes on load and raises
  :class:`~distributed_embeddings_tpu.utils.runtime.CheckpointCorrupt` on
  any mismatch (truncation, bit rot, partial external copy).
* **Previous-checkpoint fallback.** The swap keeps the displaced
  checkpoint at ``<path>.prev``; :func:`restore_train_state` falls back to
  it (with a clear log line) instead of loading torn state.
* ``DETPU_FAULT=die:checkpoint_write`` kills the process inside the write
  path, and ``DETPU_FAULT=corrupt@ckpt`` flips bytes in a just-committed
  shard file (silent bit rot the CRC manifest must catch), so the whole
  story is testable on CPU (see ``tests/test_checkpoint_atomic.py``).

Elastic topology (the logical-table codec): every array in a checkpoint is
a **full logical table** — ``save_train_state`` reassembles each table
(params and every slab-shaped optimizer component) from its slices via the
strategy's row-offset/column-slice metadata before writing, and restore
re-slices it under the restoring model's plan through the streaming
``set_weights``. The on-disk format therefore carries NO sharding: a
checkpoint written on a v5e-16 under ``memory_balanced`` restores on 8
chips under a ``telemetry_balanced`` plan, table by table, with peak host
memory one table. ``meta.json`` records the *plan fingerprint*
(``DistEmbeddingStrategy.plan_spec``) purely so restore can TELL the
topologies apart: ``restore_train_state(on_mismatch=...)`` either raises a
named :class:`~.runtime.CheckpointMismatch` (``"error"``) or re-shards in
place (``"reshard"``), logging the degradation (old plan, new plan,
per-rank byte deltas) through :mod:`.obs`. :func:`reshard_checkpoint` is
the offline half — it rewrites a checkpoint to a new plan/world size
without touching a device (``tools/reshard.py`` is the CLI).
"""

from __future__ import annotations

import json
import logging
import os
import shutil
import zlib
from typing import TYPE_CHECKING, Any, Callable, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np
from flax import serialization

from . import runtime

if TYPE_CHECKING:  # function-local at run time: a module-scope import of
    # parallel.trainer from here would close an import cycle the moment a
    # parallel module imports utils.obs (utils/__init__ -> checkpoint ->
    # parallel -> dist_embedding -> utils, mid-initialization)
    from ..parallel.trainer import HybridTrainState

logger = logging.getLogger(__name__)


# ------------------------------------------------------- atomic file layer


def _crc32_file(path: str, chunk_bytes: int = 1 << 20) -> int:
    """Streaming CRC32 of a file (constant memory; tables can be GBs)."""
    crc = 0
    with open(path, "rb") as f:
        while True:
            block = f.read(chunk_bytes)
            if not block:
                break
            crc = zlib.crc32(block, crc)
    return crc & 0xFFFFFFFF


def _fsync_dir(path: str) -> None:
    """Best-effort directory fsync so renames inside it are durable."""
    try:
        fd = os.open(path, os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    finally:
        os.close(fd)


class _CRCWriter:
    """File proxy accumulating a CRC32 over sequential writes, so multi-GB
    table dumps don't need a full re-read to build the manifest. A writer
    that seeks back (zipfile patching local headers in ``np.savez``)
    invalidates the running CRC — ``dirty`` flags it and the caller falls
    back to the streaming re-read."""

    def __init__(self, f):
        self._f = f
        self.crc = 0
        self.dirty = False

    def write(self, data):
        self.crc = zlib.crc32(data, self.crc)
        return self._f.write(data)

    def seek(self, *args, **kwargs):
        self.dirty = True
        return self._f.seek(*args, **kwargs)

    def __getattr__(self, name):
        return getattr(self._f, name)


def _atomic_file(path: str, writer: Callable[[Any], None]) -> int:
    """Write ``path`` via tmp + flush + fsync + rename; returns the file's
    CRC32 (accumulated during the write — see :class:`_CRCWriter`).
    ``fault_point('checkpoint_write')`` fires first, so an injected death
    leaves at most a ``.tmp`` orphan — never a torn final file."""
    runtime.fault_point("checkpoint_write")
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        proxy = _CRCWriter(f)
        writer(proxy)
        f.flush()
        os.fsync(f.fileno())
    crc = _crc32_file(tmp) if proxy.dirty else proxy.crc
    os.replace(tmp, path)
    return crc


def previous_checkpoint_path(path: str) -> str:
    """Where the swap parks the displaced checkpoint (restore fallback)."""
    return path.rstrip(os.sep) + ".prev"


def ring_dir(path: str) -> str:
    """Directory holding the checkpoint ring (last-good checkpoints older
    than ``<path>.prev``), one subdirectory per retained save."""
    return path.rstrip(os.sep) + ".ring"


def _meta_field(path: str, key: str):
    """One field of a checkpoint's manifest (``None`` when the manifest
    is unreadable or the field absent)."""
    try:
        with open(os.path.join(path, "meta.json"), encoding="utf-8") as f:
            return json.load(f).get(key)
    except (OSError, json.JSONDecodeError, UnicodeDecodeError):
        return None


def _meta_step(path: str) -> Optional[int]:
    """The ``step`` a checkpoint's manifest records (``None`` for
    pre-ring checkpoints or an unreadable manifest)."""
    step = _meta_field(path, "step")
    return int(step) if step is not None else None


def meta_run_id(path: str) -> Optional[str]:
    """The run-lineage id a checkpoint's manifest records (``None`` for
    pre-lineage checkpoints). The resilient driver stamps every save
    with its lineage (fresh runs mint one, resumes inherit the restored
    checkpoint's) and the rollback refuses candidates from a DIFFERENT
    lineage — a fresh run in a dirty directory must never roll back into
    a previous run's parameters."""
    rid = _meta_field(path, "run_id")
    return str(rid) if rid is not None else None


def ring_entries(path: str) -> list:
    """The checkpoint ring of ``path``, newest first: ``[(step, dir),
    ...]``. Entries are listed, not validated — a rollback consumer CRC-
    verifies the one it picks (:func:`verify_checkpoint`) and moves on to
    the next on corruption."""
    d = ring_dir(path)
    if not os.path.isdir(d):
        return []
    out = []
    for name in os.listdir(d):
        if not name.startswith("step_"):
            continue
        try:
            step = int(name[len("step_"):])
        except ValueError:
            continue
        out.append((step, os.path.join(d, name)))
    out.sort(key=lambda e: e[0], reverse=True)
    return out


def prune_ring(path: str, keep_last_n: int) -> None:
    """Drop the oldest ring entries beyond ``keep_last_n``."""
    for _, entry in ring_entries(path)[max(0, keep_last_n):]:
        shutil.rmtree(entry, ignore_errors=True)


def rollback_candidates(path: str) -> list:
    """Every restorable checkpoint generation of ``path``, newest first:
    ``[(step, dir), ...]`` across ``path`` itself, ``<path>.prev``, and
    the ring. ``step`` is the manifest-recorded step counter (``None``
    for pre-ring checkpoints, which a step-aware rollback skips). Nothing
    is CRC-validated here — the consumer verifies its pick."""
    out = []
    for p in (path, previous_checkpoint_path(path)):
        if os.path.isfile(os.path.join(p, "meta.json")):
            out.append((_meta_step(p), p))
    out.extend(ring_entries(path))
    # newest first; step-less (pre-ring) checkpoints sort last
    out.sort(key=lambda e: (e[0] is not None, e[0] or 0), reverse=True)
    return out


def _archive_to_ring(path: str, prev: str, keep_last_n: int) -> None:
    """Move the about-to-be-deleted second-newest checkpoint (``prev``)
    into the ring instead of dropping it, then prune. Checkpoints whose
    manifest predates step recording cannot be placed in the ring (their
    position is unknowable) and are dropped as before."""
    step = _meta_step(prev)
    if step is None:
        logger.debug("checkpoint ring: %s has no recorded step "
                     "(pre-ring format); dropping instead of archiving",
                     prev)
        shutil.rmtree(prev)
        return
    entry = os.path.join(ring_dir(path), f"step_{step:012d}")
    os.makedirs(ring_dir(path), exist_ok=True)
    if os.path.isdir(entry):  # same-step re-save: newest wins
        shutil.rmtree(entry)
    os.replace(prev, entry)
    prune_ring(path, keep_last_n)


def _commit_staging(staging: str, path: str,
                    keep_previous: bool = True, ring_n: int = 0) -> None:
    """Swap a fully written staging directory into ``path`` (one directory
    rename; the displaced valid checkpoint survives at ``<path>.prev``
    when ``keep_previous``, and with ``ring_n > 0`` the checkpoint THAT
    displaces — the former ``.prev`` — rotates into ``<path>.ring/``
    instead of being deleted, keeping the newest ``ring_n`` generations
    restorable), then honor a ``DETPU_FAULT=corrupt@ckpt``
    drill by flipping bytes mid-file in the committed checkpoint's first
    table shard — AFTER the commit, so the manifest certifies a file the
    disk then silently diverges from (the scenario CRC validation
    exists for)."""
    runtime.fault_point("checkpoint_commit")
    prev = previous_checkpoint_path(path)
    if os.path.isdir(path):
        if keep_previous and os.path.isfile(
                os.path.join(path, "meta.json")):
            if os.path.isdir(prev):
                if ring_n > 0 and os.path.isfile(
                        os.path.join(prev, "meta.json")):
                    _archive_to_ring(path, prev, ring_n)
                else:
                    shutil.rmtree(prev)
            os.replace(path, prev)
        else:  # invalid leftovers (or fallback disabled): drop them
            shutil.rmtree(path)
    os.replace(staging, path)
    _fsync_dir(os.path.dirname(os.path.abspath(path)))
    if runtime.corrupt_ckpt_requested():
        target = os.path.join(path, "tables", "table_000.npy")
        if os.path.isfile(target):
            with open(target, "r+b") as f:
                f.seek(max(0, os.path.getsize(target) // 2))
                byte = f.read(1) or b"\x00"
                f.seek(-len(byte), os.SEEK_CUR)
                f.write(bytes([byte[0] ^ 0xFF]))
            logger.error("DETPU_FAULT=corrupt@ckpt: flipped a byte in %s",
                         target)
            from . import obs  # lazy: obs is jax-free but keep parity with
            # runtime's own lazy pattern
            obs.record_fault("ckpt_corrupt")


def _staging_path(path: str) -> str:
    return path.rstrip(os.sep) + ".staging"


def verify_checkpoint(path: str) -> Dict[str, Any]:
    """Validate a checkpoint directory; returns its parsed ``meta.json``.

    Raises :class:`~.runtime.CheckpointCorrupt` when the manifest is
    missing/torn, a listed file is absent, or a CRC32 mismatches. Pre-CRC
    checkpoints (no ``files`` manifest) pass with a debug note — their
    files simply cannot be validated.
    """
    meta_path = os.path.join(path, "meta.json")
    if not os.path.isfile(meta_path):
        raise runtime.CheckpointCorrupt(
            f"no checkpoint at {path!r} (missing meta.json)")
    try:
        with open(meta_path, encoding="utf-8") as f:
            meta = json.load(f)
    except (json.JSONDecodeError, UnicodeDecodeError) as e:
        raise runtime.CheckpointCorrupt(
            f"torn manifest at {meta_path!r}: {e}") from e
    files = meta.get("files")
    if files is None:
        logger.debug("checkpoint %s predates CRC manifests; skipping "
                     "content validation", path)
        return meta
    for rel, crc in files.items():
        fp = os.path.join(path, rel)
        if not os.path.isfile(fp):
            raise runtime.CheckpointCorrupt(
                f"checkpoint {path!r} is missing {rel!r}")
        actual = _crc32_file(fp)
        if actual != crc:
            raise runtime.CheckpointCorrupt(
                f"CRC mismatch for {rel!r} in {path!r}: manifest "
                f"{crc:#010x}, on disk {actual:#010x} (torn write?)")
    return meta


def validate_checkpoint_model(path: str, meta: Dict[str, Any], de) -> None:
    """Check that a (whole, CRC-valid) checkpoint structurally matches the
    model it is being restored into: table count and every table's
    (vocab, dim) against ``de.strategy.global_configs``.

    Raises :class:`~.runtime.CheckpointMismatch` naming the first
    offending table with expected-vs-found shapes — the alternative is a
    scatter-shape traceback from deep inside ``set_weights`` hours into a
    resumed run. Shapes come from the ``tables`` manifest entry when
    present; older checkpoints fall back to the ``.npy`` headers (an mmap
    open reads only the header)."""
    want = de.strategy.global_configs
    n = int(meta.get("num_tables", -1))
    if n != len(want):
        raise runtime.CheckpointMismatch(
            f"checkpoint at {path!r} holds {n} table(s) but the model "
            f"declares {len(want)} — wrong checkpoint or changed model "
            "config")
    saved = meta.get("tables")
    for t, cfg in enumerate(want):
        exp = (int(cfg["input_dim"]), int(cfg["output_dim"]))
        if saved is not None:
            got = tuple(int(x) for x in saved[t])
        else:
            fp = os.path.join(path, "tables", f"table_{t:03d}.npy")
            try:
                got = tuple(np.load(fp, mmap_mode="r").shape)
            except (OSError, ValueError) as e:
                raise runtime.CheckpointCorrupt(
                    f"cannot read table header {fp!r}: {e}") from e
        if got != exp:
            raise runtime.CheckpointMismatch(
                f"table {t}: checkpoint at {path!r} was saved with "
                f"vocab x dim {got}, the model expects {exp} — fix the "
                "embedding configs or point at the matching checkpoint")


def _plan_tools():
    """Lazy import of the plan-fingerprint helpers. Function-local for the
    same reason ``parallel.trainer`` is (module docstring): a module-scope
    ``..parallel`` import from here would close an import cycle while
    ``utils`` is mid-initialization."""
    from ..parallel.strategy import plan_diff, plans_equal

    return plans_equal, plan_diff


def _check_plan(path: str, meta: Dict[str, Any], de,
                on_mismatch: str) -> bool:
    """Compare the checkpoint's recorded plan fingerprint against ``de``'s.
    Returns True when they differ and ``on_mismatch='reshard'`` authorizes
    re-slicing (the degradation is recorded through ``obs.record_event``
    and a warning log); raises :class:`~.runtime.CheckpointMismatch` under
    ``'error'``. Pre-manifest checkpoints (no recorded plan) compare as
    matching — there is nothing to diff."""
    saved = meta.get("plan")
    if saved is None:
        return False
    plans_equal, plan_diff = _plan_tools()
    current = de.strategy.plan_spec()
    if plans_equal(saved, current):
        return False
    param_bytes = jnp.dtype(
        meta.get("dtypes", {}).get("tables", "float32")).itemsize
    diff = plan_diff(saved, current, param_bytes=param_bytes)
    desc = (f"world {diff['world_size'][0]} -> {diff['world_size'][1]}, "
            f"strategy {diff['strategy'][0]!r} -> {diff['strategy'][1]!r}, "
            f"{len(diff['moved_tables'])} table(s) change ranks")
    if on_mismatch != "reshard":
        raise runtime.CheckpointMismatch(
            f"checkpoint at {path!r} was written under a different "
            f"sharding plan ({desc}). Pass on_mismatch='reshard' to "
            "re-slice it under the current plan on the fly, or rewrite it "
            "offline with tools/reshard.py")
    from . import obs

    obs.record_event("checkpoint_reshard", path=path,
                     old_plan=saved, new_plan=current, diff=diff)
    logger.warning(
        "restore_train_state: re-sharding checkpoint %s onto a different "
        "topology (%s; per-rank byte deltas on common ranks: %s)",
        path, desc, diff["per_rank_byte_deltas"])
    return True


def _is_slab_dict(tree, params) -> bool:
    """True when ``tree`` is a width-keyed dict of arrays shaped like the
    param slabs (Adagrad accumulators, momentum traces)."""
    if not isinstance(tree, dict) or set(tree) != set(params):
        return False
    return all(
        hasattr(v, "shape") and tuple(v.shape) == tuple(params[k].shape)
        for k, v in tree.items())


def _components(opt_state, params):
    """Split an embedding-optimizer state into named checkpointable
    components: ``(slab_components, aux_components)`` where slab components
    are ``{wkey: slab}`` dicts (table-reassemblable) and aux components are
    small arrays saved verbatim."""
    if _is_slab_dict(opt_state, params):
        return {"state": opt_state}, {}
    if isinstance(opt_state, dict) and set(opt_state) == set(params):
        vals = list(opt_state.values())
        if all(isinstance(v, tuple) for v in vals):
            ln = {len(v) for v in vals}
            if len(ln) == 1:
                n = ln.pop()
                slabs, aux = {}, {}
                for i in range(n):
                    comp = {k: opt_state[k][i] for k in opt_state}
                    if _is_slab_dict(comp, params):
                        slabs[f"state{i}"] = comp
                    else:
                        aux[f"state{i}"] = comp
                return slabs, aux
        if all(v == () or v == [] for v in vals):  # SparseSGD
            return {}, {}
    raise ValueError(
        "Unrecognized embedding-optimizer state structure; expected the "
        "slab-dict layouts of the parallel.optimizers classes")


def save_train_state(path: str, de, state: HybridTrainState,
                     is_chief: Optional[bool] = None,
                     keep_previous: bool = True,
                     keep_last_n: int = 0,
                     run_id: Optional[str] = None,
                     aux_states: Optional[Dict[str, Dict[str, Any]]]
                     = None) -> None:
    """Write the full train state under ``path`` (a directory), atomically.

    Every process must call this (the streamed table fetches are
    collective); only the chief writes files.

    The write is crash-safe end to end: files land in ``<path>.staging``
    (each via tmp + fsync + rename, CRC32s collected into the manifest,
    ``meta.json`` last) and the staging directory is swapped into ``path``
    — a process killed at any point never leaves torn state at ``path``:
    it is either the old checkpoint, the new checkpoint, or (crash exactly
    between the swap's two renames) absent with the old checkpoint whole
    at ``<path>.prev``, which restore's fallback picks up. With
    ``keep_previous`` (the default) the displaced checkpoint survives at
    ``<path>.prev`` as the restore fallback.

    ``keep_last_n`` > 0 additionally keeps a RING of older generations:
    the checkpoint the swap would have deleted (the former ``.prev``)
    rotates into ``<path>.ring/step_<step>`` and the ring is pruned to
    the newest ``keep_last_n`` entries — so at any time up to
    ``keep_last_n + 2`` whole checkpoints are restorable
    (:func:`rollback_candidates`). This is the rollback-and-replay
    recovery's supply of known-good states: when a NaN storm escalates,
    the driver restores the newest HEALTHY entry predating the poisoned
    batch window instead of dying.

    ``run_id`` stamps the manifest with a run-lineage id
    (:func:`meta_run_id`) so a rollback can tell this run's generations
    from a previous run's leftovers in the same directory.

    ``aux_states`` persists named jit-carried auxiliary state INSIDE the
    checkpoint (``aux/<name>.npz``, CRC-manifested like every other
    file): each entry is a flat ``{key: array}`` dict in a
    plan-AGNOSTIC encoding chosen by its producer (e.g. the
    streaming-vocab slot maps via
    :func:`~..parallel.streaming.encode_state`). Because every ring
    generation carries its own aux snapshot, the rollback-and-replay
    recovery rewinds aux state to EXACTLY the candidate it restores —
    not to some newer sidecar — and :func:`reshard_checkpoint` moves
    the files byte-identically (the encoding owes its plan-agnosticism
    to the producer). Read back with :func:`load_aux_state`."""
    if is_chief is None:
        is_chief = jax.process_index() == 0
    staging = _staging_path(path)
    manifest: Dict[str, int] = {}

    def put(rel, writer):
        manifest[rel] = _atomic_file(os.path.join(staging, rel), writer)

    if is_chief:
        if os.path.isdir(staging):  # leftover of an earlier killed save
            shutil.rmtree(staging)
        os.makedirs(os.path.join(staging, "tables"))
    n_tables = len(de.strategy.global_configs)

    def dump_tables(sub, comp):
        # table-at-a-time: chief host memory caps at ONE reassembled table
        if is_chief:
            os.makedirs(os.path.join(staging, sub), exist_ok=True)
        for t in range(n_tables):
            arr = de.get_table(comp, t, all_ranks=False)
            if is_chief:
                put(f"{sub}/table_{t:03d}.npy",
                    lambda f, a=arr: np.save(f, a))

    dump_tables("tables", state.emb_params)
    slabs, aux = _components(state.emb_opt_state, state.emb_params)
    for name, comp in slabs.items():
        dump_tables(f"emb_opt/{name}", comp)
    if is_chief:
        os.makedirs(os.path.join(staging, "emb_opt"), exist_ok=True)
        # aux components save per width key (one npz entry each) — stacking
        # across keys would require every key's aux leaf to have the same
        # element count, which only holds for scalar counters (ADVICE r4)
        for name, comp in aux.items():
            put(f"emb_opt/{name}.npz",
                lambda f, c=comp: np.savez(
                    f, **{k: np.asarray(v) for k, v in c.items()}))
        if aux_states:
            os.makedirs(os.path.join(staging, "aux"), exist_ok=True)
            for name, enc in sorted(aux_states.items()):
                put(f"aux/{name}.npz",
                    lambda f, c=enc: np.savez(
                        f, **{k: np.asarray(v) for k, v in c.items()}))
        dense = {"dense_params": state.dense_params,
                 "dense_opt_state": state.dense_opt_state,
                 "step": state.step}
        put("dense.msgpack",
            lambda f: f.write(serialization.to_bytes(dense)))

        def dt(tree):
            return str(jnp.dtype(next(iter(tree.values())).dtype).name)

        meta = {"num_tables": n_tables,
                # the step counter at save time: lets the ring name its
                # entries and the rollback pick a candidate that predates
                # a poisoned batch window without opening dense.msgpack
                "step": int(np.asarray(jax.device_get(state.step))),
                # per-table (vocab, dim): lets restore reject a checkpoint
                # that does not match the model with a named error instead
                # of a scatter-shape traceback (CheckpointMismatch)
                "tables": [[int(c["input_dim"]), int(c["output_dim"])]
                           for c in de.strategy.global_configs],
                # the sharding-plan fingerprint: the DATA is plan-agnostic
                # (full logical tables); this records which topology wrote
                # it so restore can tell "same layout" from "needs a
                # re-shard" and diff the two (strategy.plan_diff)
                "plan": de.strategy.plan_spec(),
                "slab_components": sorted(slabs),
                "aux_components": sorted(aux),
                # jit-carried auxiliary states riding the checkpoint
                # (aux/<name>.npz; plan-agnostic encodings — see the
                # aux_states docstring)
                "aux_states": sorted(aux_states or {}),
                # per-component saved dtypes: a bf16-tables + fp32-accumulator
                # run must restore with the SAME mixed dtypes by default
                # (ADVICE r4) — restore reads these unless overridden
                "dtypes": {"tables": dt(state.emb_params),
                           **{name: dt(comp)
                              for name, comp in slabs.items()}},
                # per-file CRC32s, manifest written LAST: its presence
                # certifies every other file hit the disk whole
                "files": dict(manifest)}
        if run_id is not None:
            # run lineage: lets the rollback refuse another run's
            # leftover generations in the same directory
            meta["run_id"] = str(run_id)
        _atomic_file(os.path.join(staging, "meta.json"),
                     lambda f: f.write(json.dumps(meta).encode()))
        _fsync_dir(staging)
        # ---- commit: one directory swap; old checkpoint -> <path>.prev
        # (and the former .prev -> the ring, under keep_last_n)
        _commit_staging(staging, path, keep_previous=keep_previous,
                        ring_n=int(keep_last_n))


def _aux_consensus(comp: Dict[str, Any]) -> float:
    """Collapse a saved aux component (per-width-slab counter arrays) to
    its single representative value. The only aux leaves the optimizer
    zoo produces are per-slab step counters (SparseAdam), which advance
    in lockstep across slabs — take the max and warn if they ever
    disagree (max keeps Adam's bias correction conservative)."""
    flat = [np.asarray(v).reshape(-1) for v in comp.values()]
    allv = np.concatenate(flat) if flat else np.zeros((1,))
    top = float(allv.max()) if allv.size else 0.0
    if allv.size and not np.all(allv == top):
        logger.warning(
            "aux optimizer component: per-slab values disagree (min %s, "
            "max %s) across the re-shard; using the max", allv.min(), top)
    return top


def _adapt_aux(name: str, comp: Dict[str, Any], wkey: str, spec,
               resharding: bool):
    """Restore one aux optimizer leaf (``emb_opt/<name>.npz`` entry
    ``wkey``). Same-plan restores reproduce the saved array exactly; a
    re-shard rebuilds the leaf at the NEW width/world geometry from the
    saved per-slab consensus (a new width group or changed world size has
    no saved twin to reshape from)."""
    arr = comp.get(wkey)
    if arr is not None:
        arr = np.asarray(arr)
        if arr.size == int(np.prod(spec.shape)):
            return jnp.asarray(arr).reshape(spec.shape).astype(spec.dtype)
        if not resharding:
            raise runtime.CheckpointMismatch(
                f"aux optimizer component {name}/{wkey}: saved shape "
                f"{arr.shape} cannot fill {spec.shape} and the checkpoint "
                "plan matches the model — corrupt aux component?")
    elif not resharding:
        raise runtime.CheckpointMismatch(
            f"aux optimizer component {name} is missing width key "
            f"{wkey!r} though the checkpoint plan matches the model")
    return jnp.full(spec.shape, _aux_consensus(comp), spec.dtype)


def restore_train_state(path: str, de, emb_optimizer, dense_template,
                        dense_tx, mesh=None, dtype=None,
                        fallback: bool = True,
                        on_mismatch: str = "error") -> HybridTrainState:
    """Rebuild a :class:`HybridTrainState` from :func:`save_train_state`
    output. ``dense_template`` supplies the dense params/opt pytree
    structure (e.g. a freshly initialized state's ``dense_params``);
    tables restore via mmap'd streaming ``set_weights``.

    ``dtype``: by default every component restores in the dtype it was
    SAVED in (recorded in ``meta.json`` — a bf16-tables + fp32-accumulator
    run resumes with the same mixed dtypes and an unchanged trajectory).
    Pass a single dtype to force it everywhere, or a dict keyed by
    component name (``"tables"``, ``"state"``, ``"state0"``, ...) for
    per-component overrides (missing keys keep their saved dtype).

    ``on_mismatch``: what to do when the checkpoint's recorded sharding
    plan (world size / placement / slicing) differs from ``de``'s:

    * ``"error"`` (default): raise :class:`~.runtime.CheckpointMismatch`
      naming both topologies — restoring onto a different mesh is an
      operator decision, not something to do silently.
    * ``"reshard"``: re-slice every logical table (params + slab-shaped
      optimizer state) under ``de``'s plan while streaming it in, adapt
      the per-slab optimizer aux leaves (Adam step counts) to the new
      width/world geometry, and record the degradation — old plan, new
      plan, per-rank byte deltas — through
      :func:`~.obs.record_event` (``"checkpoint_reshard"``) plus a
      warning log. This is the elastic-resume path
      (``parallel.resilient.run_resilient`` defaults to it).

    Checkpoints written before plan manifests existed restore as before
    (nothing to compare against).

    Validation: the checkpoint is CRC-verified against its manifest before
    anything loads. A torn checkpoint is never restored — with ``fallback``
    (the default) the previous valid checkpoint at ``<path>.prev`` is
    restored instead (clear warning logged); otherwise
    :class:`~.runtime.CheckpointCorrupt` propagates."""
    if on_mismatch not in ("error", "reshard"):
        raise ValueError(
            f"on_mismatch must be 'error' | 'reshard', got {on_mismatch!r}")
    runtime.fault_point("checkpoint_read")
    try:
        meta = verify_checkpoint(path)
    except runtime.CheckpointCorrupt as e:
        prev = previous_checkpoint_path(path)
        if not (fallback and os.path.isdir(prev)):
            raise
        logger.warning(
            "checkpoint at %s failed validation (%s); falling back to the "
            "previous valid checkpoint at %s", path, e, prev)
        meta = verify_checkpoint(prev)  # must itself be whole, or we raise
        from . import obs

        # let drivers learn WHICH generation actually restored: anything
        # restored alongside the params (the streaming aux state) must
        # come from the SAME directory, or two trajectories splice
        obs.record_event("checkpoint_prev_fallback", path=path, prev=prev)
        path = prev
    # structural match BEFORE any data streams: a mismatched-but-whole
    # checkpoint is a config error, not corruption — no .prev fallback
    validate_checkpoint_model(path, meta, de)
    resharding = _check_plan(path, meta, de, on_mismatch)
    n = meta["num_tables"]
    saved_dtypes = meta.get("dtypes", {})

    def saved(component):  # the dtype files were written in (also the view
        # hint for bf16 .npy, whose descriptor np.load cannot map back)
        return jnp.dtype(saved_dtypes.get(component, "float32"))

    def dtype_of(component):
        if isinstance(dtype, dict):
            if component in dtype:
                return dtype[component]
        elif dtype is not None:
            return dtype
        return saved(component)

    def table_paths(sub):
        return [os.path.join(path, sub, f"table_{t:03d}.npy")
                for t in range(n)]

    emb_params = de.set_weights(table_paths("tables"), mesh=mesh,
                                dtype=dtype_of("tables"),
                                src_dtype=saved("tables"))
    # inspect the optimizer-state STRUCTURE without materializing it (a
    # real init would transiently allocate full slab-sized moments)
    opt_struct = jax.eval_shape(emb_optimizer.init, emb_params)
    slab_comps = {
        name: de.set_weights(table_paths(os.path.join("emb_opt", name)),
                             mesh=mesh, dtype=dtype_of(name),
                             src_dtype=saved(name))
        for name in meta["slab_components"]}
    aux_comps = {}
    for name in meta["aux_components"]:
        npz = os.path.join(path, "emb_opt", f"{name}.npz")
        if os.path.exists(npz):
            aux_comps[name] = dict(np.load(npz))
        else:  # pre-r5 stacked format: rows in aux_wkey_order
            rows = np.load(os.path.join(path, "emb_opt", f"{name}.npy"))
            aux_comps[name] = {
                k: rows[i] for i, k in enumerate(meta["aux_wkey_order"])}
    if _is_slab_dict(opt_struct, emb_params):
        assert set(meta["slab_components"]) == {"state"}, meta
        opt_state = slab_comps["state"]
    elif meta["slab_components"] or meta["aux_components"]:
        # tuple-structured state (Adam): substitute per-position components
        new = {}
        for k in opt_struct:
            parts = []
            for i in range(len(opt_struct[k])):
                name = f"state{i}"
                if name in slab_comps:
                    parts.append(slab_comps[name][k])
                else:
                    spec = opt_struct[k][i]
                    parts.append(_adapt_aux(name, aux_comps[name], k,
                                            spec, resharding))
            new[k] = tuple(parts)
        opt_state = new
    else:
        # stateless (SparseSGD): the real init is trivially cheap
        opt_state = emb_optimizer.init(emb_params)
    if mesh is not None:
        from jax.sharding import NamedSharding, PartitionSpec as P
        sharding = NamedSharding(mesh, P(de.axis_name))
        opt_state = jax.tree.map(
            lambda a: jax.device_put(a, sharding)
            if hasattr(a, "ndim") and a.ndim >= 3 else a, opt_state)

    dense = {"dense_params": dense_template,
             "dense_opt_state": dense_tx.init(dense_template),
             "step": jnp.zeros((), jnp.int32)}
    with open(os.path.join(path, "dense.msgpack"), "rb") as f:
        dense = serialization.from_bytes(dense, f.read())
    from ..parallel.trainer import HybridTrainState

    return HybridTrainState(
        emb_params=emb_params, emb_opt_state=opt_state,
        dense_params=dense["dense_params"],
        dense_opt_state=dense["dense_opt_state"],
        step=jnp.asarray(dense["step"]))


def load_aux_state(path: str, name: str) -> Optional[Dict[str, Any]]:
    """Read one ``aux_states`` entry written by :func:`save_train_state`
    back as a ``{key: numpy array}`` dict. ``None`` when the checkpoint
    predates aux persistence or never carried ``name`` — aux state is
    auxiliary by contract and must never block a restore (its producer
    decodes ``None`` into a pristine warm-up state)."""
    fp = os.path.join(path, "aux", f"{name}.npz")
    if not os.path.isfile(fp):
        return None
    try:
        with np.load(fp) as loaded:
            return {k: loaded[k] for k in loaded.files}
    except (OSError, ValueError, zlib.error) as e:
        logger.warning("aux state %s at %s unreadable (%s); treating as "
                       "absent", name, path, e)
        return None


# --------------------------------------------------- offline re-shard codec


def _copy_file(src: str, dst: str, chunk_bytes: int = 1 << 20) -> None:
    """Streamed copy + fsync (constant memory; tables can be GBs)."""
    with open(src, "rb") as fin, open(dst, "wb") as fout:
        shutil.copyfileobj(fin, fout, chunk_bytes)
        fout.flush()
        os.fsync(fout.fileno())


def reshard_checkpoint(src: str, dst: str, target,
                       dry_run: bool = False) -> Dict[str, Any]:
    """Rewrite the checkpoint at ``src`` to ``dst`` under ``target``'s
    sharding plan — entirely host-side (no device, no jax arrays): the
    on-disk data is full logical tables, so re-sharding copies them
    byte-identically (streamed file by file; peak memory one copy chunk)
    and rewrites only the plan-dependent pieces — the ``meta.json`` plan
    fingerprint and the per-slab optimizer aux leaves (Adam step counts),
    which are rebuilt at the target's width/world geometry from the saved
    consensus. ``dst`` then restores cleanly (no ``on_mismatch`` needed)
    into a model using the target plan, and a round trip back to the
    original plan reproduces every array bit for bit.

    Args:
      src: source checkpoint directory (CRC-verified before anything is
        read; must carry a ``files`` manifest — pre-CRC-era checkpoints
        must be re-saved first).
      dst: destination directory (atomic staging + swap, like
        :func:`save_train_state`; an existing valid checkpoint there is
        kept at ``<dst>.prev``). Must differ from ``src``.
      target: the topology to re-shard to — a
        :class:`~..parallel.strategy.DistEmbeddingStrategy` or anything
        carrying one as ``.strategy`` (a ``DistributedEmbedding``). Its
        global table shapes must match the checkpoint's.
      dry_run: diff only — nothing is written.

    Returns:
      The :func:`~..parallel.strategy.plan_diff` dict (old plan vs target
      plan: world sizes, per-rank byte loads and deltas, moved tables).
    """
    strat = target if hasattr(target, "plan_spec") else target.strategy
    if len(strat.global_configs) < int(strat.world_size):
        # mirror DistributedEmbedding's fewer-tables-than-positions limit:
        # the rewrite would succeed but no model could ever load it
        raise ValueError(
            f"target plan has {int(strat.world_size)} ranks but only "
            f"{len(strat.global_configs)} table(s) — fewer tables than "
            "mesh positions is unsupported, so the re-sharded checkpoint "
            "could never be restored")
    meta = verify_checkpoint(src)
    if meta.get("files") is None:
        raise runtime.CheckpointCorrupt(
            f"checkpoint at {src!r} predates CRC/plan manifests — re-save "
            "it with the current code before re-sharding")
    # the target must describe the SAME logical model
    saved_tables = meta.get("tables")
    want = [[int(c["input_dim"]), int(c["output_dim"])]
            for c in strat.global_configs]
    if int(meta.get("num_tables", -1)) != len(want) or (
            saved_tables is not None
            and [list(map(int, t)) for t in saved_tables] != want):
        raise runtime.CheckpointMismatch(
            f"target plan declares tables {want} but the checkpoint at "
            f"{src!r} holds {meta.get('num_tables')} table(s) "
            f"{saved_tables} — re-sharding changes the topology, never "
            "the model")
    _, plan_diff = _plan_tools()
    new_plan = strat.plan_spec()
    param_bytes = jnp.dtype(
        meta.get("dtypes", {}).get("tables", "float32")).itemsize
    diff = plan_diff(meta.get("plan"), new_plan, param_bytes=param_bytes)
    if dry_run:
        return diff
    if os.path.abspath(src) == os.path.abspath(dst):
        raise ValueError(
            "reshard_checkpoint: src and dst must differ (the staging swap "
            "would otherwise displace the source mid-copy)")

    new_world = int(strat.world_size)
    new_widths = sorted({int(c["output_dim"])
                         for cfgs in strat.local_configs_list
                         for c in cfgs})
    aux_files = {}
    for name in meta.get("aux_components", []):
        aux_files[f"emb_opt/{name}.npz"] = name
        aux_files[f"emb_opt/{name}.npy"] = name  # pre-r5 stacked format

    staging = _staging_path(dst)
    if os.path.isdir(staging):  # leftover of an earlier killed reshard
        shutil.rmtree(staging)
    manifest: Dict[str, int] = {}
    for rel, crc in meta["files"].items():
        out = os.path.join(staging, rel)
        os.makedirs(os.path.dirname(out), exist_ok=True)
        name = aux_files.get(rel)
        if name is None:
            # logical-table data (and the replicated dense state) is
            # plan-agnostic: byte-identical streamed copy, CRC carried
            # over from the just-verified source manifest
            _copy_file(os.path.join(src, rel), out)
            manifest[rel] = crc
            continue
        if rel.endswith(".npy"):  # pre-r5 stacked rows -> per-wkey dict
            rows = np.load(os.path.join(src, rel))
            comp = {k: rows[i]
                    for i, k in enumerate(meta["aux_wkey_order"])}
            rel = rel[:-len(".npy")] + ".npz"  # rewrite in the npz format
            out = os.path.join(staging, rel)
        else:
            with np.load(os.path.join(src, rel)) as loaded:
                comp = {k: loaded[k] for k in loaded.files}
        value = _aux_consensus(comp)
        tail = (np.asarray(next(iter(comp.values()))).shape[1:]
                if comp else (1, 1))
        dt = (np.asarray(next(iter(comp.values()))).dtype
              if comp else np.float32)
        rebuilt = {f"w{w}": np.full((new_world,) + tuple(tail), value, dt)
                   for w in new_widths}
        manifest[rel] = _atomic_file(
            out, lambda f, c=rebuilt: np.savez(f, **c))
    meta_new = dict(meta, plan=new_plan, files=manifest)
    _atomic_file(os.path.join(staging, "meta.json"),
                 lambda f: f.write(json.dumps(meta_new).encode()))
    _fsync_dir(staging)
    _commit_staging(staging, dst, keep_previous=True)
    logger.info(
        "reshard_checkpoint: %s -> %s (world %s -> %s, strategy %s -> %s, "
        "%d table(s) moved ranks)", src, dst, diff["world_size"][0],
        diff["world_size"][1], diff["strategy"][0], diff["strategy"][1],
        len(diff["moved_tables"]))
    return diff

"""Full train-state checkpoint/resume for hybrid-parallel training.

The reference checkpoints only embedding tables (``get_weights`` +
``np.savez``, ``examples/dlrm/main.py:246-248``) — "no optimizer-state or
step checkpointing" (SURVEY §5). Here the WHOLE
:class:`~.parallel.trainer.HybridTrainState` round-trips:

* embedding tables stream through
  :meth:`~.parallel.DistributedEmbedding.get_weights` /
  :meth:`~.parallel.DistributedEmbedding.set_weights` (chunked, multi-host
  safe, mmap restore) into per-table ``.npy`` files;
* sparse-optimizer slab state rides the SAME path — the optimizer states
  are width-keyed slab dicts shaped exactly like the params
  (:class:`~.parallel.optimizers.SparseAdagrad` accumulators,
  :class:`~.parallel.optimizers.SparseMomentum` traces) or tuples of them
  plus small counters (:class:`~.parallel.optimizers.SparseAdam`), so each
  component reassembles to per-table arrays;
* the replicated dense params / dense optimizer state / step counter
  serialize with ``flax.serialization`` msgpack.

Layout under ``path/``::

    tables/table_000.npy ...
    emb_opt/<component>/table_000.npy ...   # slab-shaped components
    emb_opt/<component>.npy                 # non-slab leaves (Adam counts)
    dense.msgpack                           # dense params+opt+step

Multi-host: every process calls both functions (the streamed fetches are
collective); only process 0 writes, and restore reads are per-process.
"""

from __future__ import annotations

import json
import os
from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np
from flax import serialization

from ..parallel.trainer import HybridTrainState


def _is_slab_dict(tree, params) -> bool:
    """True when ``tree`` is a width-keyed dict of arrays shaped like the
    param slabs (Adagrad accumulators, momentum traces)."""
    if not isinstance(tree, dict) or set(tree) != set(params):
        return False
    return all(
        hasattr(v, "shape") and tuple(v.shape) == tuple(params[k].shape)
        for k, v in tree.items())


def _components(opt_state, params):
    """Split an embedding-optimizer state into named checkpointable
    components: ``(slab_components, aux_components)`` where slab components
    are ``{wkey: slab}`` dicts (table-reassemblable) and aux components are
    small arrays saved verbatim."""
    if _is_slab_dict(opt_state, params):
        return {"state": opt_state}, {}
    if isinstance(opt_state, dict) and set(opt_state) == set(params):
        vals = list(opt_state.values())
        if all(isinstance(v, tuple) for v in vals):
            ln = {len(v) for v in vals}
            if len(ln) == 1:
                n = ln.pop()
                slabs, aux = {}, {}
                for i in range(n):
                    comp = {k: opt_state[k][i] for k in opt_state}
                    if _is_slab_dict(comp, params):
                        slabs[f"state{i}"] = comp
                    else:
                        aux[f"state{i}"] = comp
                return slabs, aux
        if all(v == () or v == [] for v in vals):  # SparseSGD
            return {}, {}
    raise ValueError(
        "Unrecognized embedding-optimizer state structure; expected the "
        "slab-dict layouts of the parallel.optimizers classes")


def save_train_state(path: str, de, state: HybridTrainState,
                     is_chief: Optional[bool] = None) -> None:
    """Write the full train state under ``path`` (a directory).

    Every process must call this (the streamed table fetches are
    collective); only the chief writes files."""
    if is_chief is None:
        is_chief = jax.process_index() == 0
    if is_chief:
        os.makedirs(os.path.join(path, "tables"), exist_ok=True)
    n_tables = len(de.strategy.global_configs)

    def dump_tables(sub, comp):
        # table-at-a-time: chief host memory caps at ONE reassembled table
        if is_chief:
            os.makedirs(os.path.join(path, sub), exist_ok=True)
        for t in range(n_tables):
            arr = de.get_table(comp, t, all_ranks=False)
            if is_chief:
                np.save(os.path.join(path, sub, f"table_{t:03d}.npy"), arr)

    dump_tables("tables", state.emb_params)
    slabs, aux = _components(state.emb_opt_state, state.emb_params)
    for name, comp in slabs.items():
        dump_tables(os.path.join("emb_opt", name), comp)
    if is_chief:
        os.makedirs(os.path.join(path, "emb_opt"), exist_ok=True)
        # aux components save per width key (one npz entry each) — stacking
        # across keys would require every key's aux leaf to have the same
        # element count, which only holds for scalar counters (ADVICE r4)
        for name, comp in aux.items():
            np.savez(os.path.join(path, "emb_opt", f"{name}.npz"),
                     **{k: np.asarray(v) for k, v in comp.items()})
        dense = {"dense_params": state.dense_params,
                 "dense_opt_state": state.dense_opt_state,
                 "step": state.step}
        with open(os.path.join(path, "dense.msgpack"), "wb") as f:
            f.write(serialization.to_bytes(dense))

        def dt(tree):
            return str(jnp.dtype(next(iter(tree.values())).dtype).name)

        meta = {"num_tables": n_tables,
                "slab_components": sorted(slabs),
                "aux_components": sorted(aux),
                # per-component saved dtypes: a bf16-tables + fp32-accumulator
                # run must restore with the SAME mixed dtypes by default
                # (ADVICE r4) — restore reads these unless overridden
                "dtypes": {"tables": dt(state.emb_params),
                           **{name: dt(comp)
                              for name, comp in slabs.items()}}}
        with open(os.path.join(path, "meta.json"), "w") as f:
            json.dump(meta, f)


def restore_train_state(path: str, de, emb_optimizer, dense_template,
                        dense_tx, mesh=None,
                        dtype=None) -> HybridTrainState:
    """Rebuild a :class:`HybridTrainState` from :func:`save_train_state`
    output. ``dense_template`` supplies the dense params/opt pytree
    structure (e.g. a freshly initialized state's ``dense_params``);
    tables restore via mmap'd streaming ``set_weights``.

    ``dtype``: by default every component restores in the dtype it was
    SAVED in (recorded in ``meta.json`` — a bf16-tables + fp32-accumulator
    run resumes with the same mixed dtypes and an unchanged trajectory).
    Pass a single dtype to force it everywhere, or a dict keyed by
    component name (``"tables"``, ``"state"``, ``"state0"``, ...) for
    per-component overrides (missing keys keep their saved dtype)."""
    with open(os.path.join(path, "meta.json")) as f:
        meta = json.load(f)
    n = meta["num_tables"]
    saved_dtypes = meta.get("dtypes", {})

    def saved(component):  # the dtype files were written in (also the view
        # hint for bf16 .npy, whose descriptor np.load cannot map back)
        return jnp.dtype(saved_dtypes.get(component, "float32"))

    def dtype_of(component):
        if isinstance(dtype, dict):
            if component in dtype:
                return dtype[component]
        elif dtype is not None:
            return dtype
        return saved(component)

    def table_paths(sub):
        return [os.path.join(path, sub, f"table_{t:03d}.npy")
                for t in range(n)]

    emb_params = de.set_weights(table_paths("tables"), mesh=mesh,
                                dtype=dtype_of("tables"),
                                src_dtype=saved("tables"))
    # inspect the optimizer-state STRUCTURE without materializing it (a
    # real init would transiently allocate full slab-sized moments)
    opt_struct = jax.eval_shape(emb_optimizer.init, emb_params)
    slab_comps = {
        name: de.set_weights(table_paths(os.path.join("emb_opt", name)),
                             mesh=mesh, dtype=dtype_of(name),
                             src_dtype=saved(name))
        for name in meta["slab_components"]}
    aux_comps = {}
    for name in meta["aux_components"]:
        npz = os.path.join(path, "emb_opt", f"{name}.npz")
        if os.path.exists(npz):
            aux_comps[name] = dict(np.load(npz))
        else:  # pre-r5 stacked format: rows in aux_wkey_order
            rows = np.load(os.path.join(path, "emb_opt", f"{name}.npy"))
            aux_comps[name] = {
                k: rows[i] for i, k in enumerate(meta["aux_wkey_order"])}
    if _is_slab_dict(opt_struct, emb_params):
        assert set(meta["slab_components"]) == {"state"}, meta
        opt_state = slab_comps["state"]
    elif meta["slab_components"] or meta["aux_components"]:
        # tuple-structured state (Adam): substitute per-position components
        new = {}
        for k in opt_struct:
            parts = []
            for i in range(len(opt_struct[k])):
                name = f"state{i}"
                if name in slab_comps:
                    parts.append(slab_comps[name][k])
                else:
                    spec = opt_struct[k][i]
                    parts.append(jnp.asarray(aux_comps[name][k])
                                 .reshape(spec.shape).astype(spec.dtype))
            new[k] = tuple(parts)
        opt_state = new
    else:
        # stateless (SparseSGD): the real init is trivially cheap
        opt_state = emb_optimizer.init(emb_params)
    if mesh is not None:
        from jax.sharding import NamedSharding, PartitionSpec as P
        sharding = NamedSharding(mesh, P(de.axis_name))
        opt_state = jax.tree.map(
            lambda a: jax.device_put(a, sharding)
            if hasattr(a, "ndim") and a.ndim >= 3 else a, opt_state)

    dense = {"dense_params": dense_template,
             "dense_opt_state": dense_tx.init(dense_template),
             "step": jnp.zeros((), jnp.int32)}
    with open(os.path.join(path, "dense.msgpack"), "rb") as f:
        dense = serialization.from_bytes(dense, f.read())
    return HybridTrainState(
        emb_params=emb_params, emb_opt_state=opt_state,
        dense_params=dense["dense_params"],
        dense_opt_state=dense["dense_opt_state"],
        step=jnp.asarray(dense["step"]))

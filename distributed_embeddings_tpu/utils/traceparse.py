"""Chrome-trace parsing of XLA profiler captures — the measured half of
the phase observatory.

``DETPU_PROFILE_DIR`` (``obs.profile_trace``) has dumped raw TensorBoard
trace directories since PR 2, and nothing in the repo ever *read* one:
every phase number so far is modeled (``analysis/schedule_audit.py``
prices bytes, it does not look at a clock). This module is the reader.
It parses the ``.trace.json[.gz]`` files ``jax.profiler.trace`` writes
(Chrome trace-event JSON under ``plugins/profile/<run>/``), attributes
every XLA op-level event to its ``obs.scope`` phase, and reduces the
events to measured per-phase durations and wall-clock interval unions —
the inputs :mod:`..analysis.phase_profile` turns into a
:class:`~..analysis.phase_profile.PhaseProfile` and calibrates against
the schedule auditor's cost model.

Attribution has two tiers, because backends disagree about where the
scope names survive:

* **metadata-carrying events** (TPU-style): the event's ``args`` (or its
  ``name``) embed the XLA ``op_name``, and :data:`~.obs.SCOPE_RE` — the
  SAME regex the HLO census and schedule auditor use, owned by
  ``utils/obs.py`` next to the :func:`~.obs.scope` writer — extracts the
  ``detpu/...`` path directly;
* **bare-name events** (this container's CPU backend): the event name is
  just the HLO instruction name (``all-to-all.6``,
  ``cosine_add_fusion.clone``). The caller passes a ``resolver`` built
  from the compiled module's own text (instruction name -> phase;
  :func:`~..analysis.phase_profile.HloPhaseIndex` provides it), joining
  the measured events against exactly the program the static gates
  audit.

Like the rest of :mod:`..utils`'s host-side layer this module never
imports jax: parsing a trace somebody else captured must work in
processes that never load a backend (``tools/obs_report.py --selftest``
exercises exactly that on a checked-in miniature trace).
"""

from __future__ import annotations

import dataclasses
import glob
import gzip
import json
import os
from typing import (Any, Callable, Dict, Iterable, List, Optional,
                    Sequence, Tuple)

from . import obs

#: event-name prefixes of host-side bookkeeping the profiler interleaves
#: with the op stream (python frames, threadpool markers, runtime
#: plumbing) — never attributable device work
HOST_EVENT_PREFIXES = (
    "$",                    # python frames ($module.py:line fn)
    "ThreadpoolListener",
    "ThunkExecutor",
    "TfrtCpu", "PjRt", "Pjit", "ParseArguments", "ExecuteContext",
    "DevicePut", "D2D ", "H2D ", "D2H ", "BufferFromHost",
    "TransferTo", "TransferFrom", "CopyTo", "CopyFrom",
)

#: phase-leaf substrings that mark a phase as a cross-chip exchange (the
#: collective phases of the step schedule)
COLLECTIVE_PHASE_MARK = "all_to_all"

#: step-attribution groups of the measured breakdown (exchange vs lookup
#: vs apply vs dense — the ROADMAP item 2 vocabulary)
GROUPS = ("exchange", "lookup", "dense", "apply", "streaming", "other")


@dataclasses.dataclass
class TraceEvent:
    """One complete (``ph == "X"``) trace event, microsecond units."""
    name: str
    ts: float                 # begin, us
    dur: float                # duration, us
    pid: int
    tid: int
    phase: str                # detpu scope path ("" = unattributed)
    resolved: bool            # joined to an HLO instruction / op metadata

    @property
    def end(self) -> float:
        return self.ts + self.dur


def is_host_event(name: str) -> bool:
    """Whether an event name is host-side bookkeeping (python frames,
    runtime plumbing) rather than a candidate op event."""
    return name.startswith(HOST_EVENT_PREFIXES)


def trace_files(root: str) -> List[str]:
    """The ``.trace.json[.gz]`` files of a capture: ``root`` may be the
    profile directory ``jax.profiler.trace`` wrote (searched recursively,
    the ``plugins/profile/<run>/<host>.trace.json.gz`` layout), or one
    trace file directly. Sorted for determinism; every matching file is
    parsed (multi-host captures write one per host)."""
    if os.path.isfile(root):
        return [root]
    out: List[str] = []
    for pat in ("*.trace.json.gz", "*.trace.json"):
        out.extend(glob.glob(os.path.join(root, "**", pat),
                             recursive=True))
    return sorted(out)


def load_trace(path: str) -> Dict[str, Any]:
    """One trace file -> its JSON document (gzip or plain)."""
    opener = gzip.open if path.endswith(".gz") else open
    with opener(path, "rb") as f:  # type: ignore[operator]
        return json.loads(f.read().decode("utf-8"))


#: args keys that carry XLA op metadata in profiler events (TPU/GPU
#: traces spell the op_name under one of these)
_METADATA_KEYS = ("op_name", "long_name", "tf_op", "hlo_op", "hlo_module")


def _phase_from_args(name: str, args: Optional[Dict[str, Any]]
                     ) -> Tuple[str, bool]:
    """Tier-1 attribution: ``(phase, metadata_found)``. The phase is a
    detpu scope embedded in the event name or in any string-valued arg
    (TPU traces put the ``op_name`` / ``long_name`` metadata there;
    scanning every string key survives renames). ``metadata_found`` is
    True whenever the event carries op metadata at all — an op with
    metadata but no detpu scope is RESOLVED as genuinely-unscoped
    compute, which is different from an event nothing could identify."""
    p = obs.phase_path(name)
    if p:
        return p, True
    found = False
    if args:
        found = any(k in args for k in _METADATA_KEYS)
        for v in args.values():
            if isinstance(v, str) and "detpu/" in v:
                p = obs.phase_path(v)
                if p:
                    return p, True
    return "", found


def parse_events(doc: Dict[str, Any],
                 resolver: Optional[Callable[[str], Optional[str]]] = None,
                 ) -> List[TraceEvent]:
    """Extract attributable op events from one trace document.

    Every complete (``"X"``) event with a positive duration that is not
    host bookkeeping is kept; ``phase`` comes from the event's own
    metadata when present, else from ``resolver(instruction_name)``
    (compiled-HLO join). Events neither tier can attribute keep
    ``phase=""`` with ``resolved=False`` — they still count toward wall
    time if they look like op events, but a caller can drop them.
    """
    out: List[TraceEvent] = []
    for e in doc.get("traceEvents") or []:
        if e.get("ph") != "X":
            continue
        dur = e.get("dur")
        if not isinstance(dur, (int, float)) or dur <= 0:
            continue
        name = str(e.get("name", ""))
        if is_host_event(name) or obs.is_request_event(name):
            # request-tracing events (utils/reqtrace.py exports into the
            # same Chrome-trace container) are serving spans, not device
            # work — parse_request_traces reads them
            continue
        args = e.get("args")
        phase, resolved = _phase_from_args(
            name, args if isinstance(args, dict) else None)
        if not phase and resolver is not None:
            key = name.lstrip("%")
            hit = resolver(key)
            if hit is None and isinstance(args, dict) \
                    and isinstance(args.get("hlo_op"), str):
                hit = resolver(args["hlo_op"])
            if hit is not None:
                phase, resolved = hit, True
        out.append(TraceEvent(
            name=name, ts=float(e.get("ts", 0.0)), dur=float(dur),
            pid=int(e.get("pid", 0)), tid=int(e.get("tid", 0)),
            phase=phase, resolved=resolved))
    return out


def parse_capture(root: str,
                  resolver: Optional[Callable[[str], Optional[str]]] = None,
                  ) -> List[TraceEvent]:
    """All attributable op events of one capture directory (every trace
    file merged — multi-host/multi-stream captures concatenate; interval
    math below handles the overlap)."""
    events: List[TraceEvent] = []
    for path in trace_files(root):
        events.extend(parse_events(load_trace(path), resolver=resolver))
    return events


# ------------------------------------------------------------ interval math


def merge_intervals(spans: Iterable[Tuple[float, float]]
                    ) -> List[Tuple[float, float]]:
    """Sorted union of (begin, end) spans."""
    out: List[Tuple[float, float]] = []
    for s, t in sorted(spans):
        if out and s <= out[-1][1]:
            if t > out[-1][1]:
                out[-1] = (out[-1][0], t)
        else:
            out.append((s, t))
    return out


def union_of(events: Iterable[TraceEvent]) -> List[Tuple[float, float]]:
    return merge_intervals((e.ts, e.end) for e in events)


def total(union: Sequence[Tuple[float, float]]) -> float:
    return sum(t - s for s, t in union)


def intersect_total(a: Sequence[Tuple[float, float]],
                    b: Sequence[Tuple[float, float]]) -> float:
    """Total length of the intersection of two merged interval unions."""
    i = j = 0
    tot = 0.0
    while i < len(a) and j < len(b):
        s = max(a[i][0], b[j][0])
        t = min(a[i][1], b[j][1])
        if t > s:
            tot += t - s
        if a[i][1] < b[j][1]:
            i += 1
        else:
            j += 1
    return tot


# --------------------------------------------------------- phase grouping


def is_collective_phase(phase: str) -> bool:
    """Whether a phase path names a cross-chip exchange."""
    return COLLECTIVE_PHASE_MARK in phase


def group_of(phase: str) -> str:
    """Fold a full phase path into the measured step-attribution group
    (``exchange`` / ``lookup`` / ``dense`` / ``apply`` / ``streaming`` /
    ``other``). Unscoped events land in ``other`` — with the compiled-HLO
    join they are rare (fusion/while internals resolve to their entry
    op's phase)."""
    if not phase:
        return "other"
    if is_collective_phase(phase):
        return "exchange"
    head = phase.split("/", 1)[0]
    if head.startswith("dense"):
        return "dense"
    if head.startswith("sparse_apply") or "dedup" in phase \
            or "expand_update_rows" in phase:
        return "apply"
    if "stream" in phase or "admission" in phase:
        return "streaming"
    if head.startswith("embedding_forward") or "lookup" in phase \
            or "gather" in phase or "decode" in phase \
            or "unique" in phase or "combine" in phase:
        return "lookup"
    return "other"


# ----------------------------------------------------------- measurement


def measure_events(events: Sequence[TraceEvent],
                   independent_spans: Optional[
                       Dict[str, List[Tuple[float, float]]]] = None,
                   overlap_min_frac: float = 0.5) -> Dict[str, Any]:
    """Reduce one capture's op events to the measured step summary.

    Returns a plain JSON-able dict:

    * ``wall_ms`` — length of the union of every op-event interval (the
      measured busy wall clock of the capture);
    * ``phase_ms`` / ``group_ms`` — summed event durations per detpu
      phase path and per :data:`GROUPS` entry (sums EXCEED ``wall_ms``
      whenever devices/streams genuinely run concurrently — that excess
      is the measured overlap);
    * ``concurrency`` — ``sum(phase_ms) / wall_ms``;
    * ``a2a_union_ms`` / ``a2a_frac`` — wall-clock during which at least
      one exchange event was in flight, and its fraction of ``wall_ms``;
    * ``collectives`` — per exchange phase: in-flight union, concurrent
      *hideable* compute (``hidden_ms``), ``hidden_frac``, and the
      measured classification: ``"overlapped"`` when ``hidden_frac >=
      overlap_min_frac``, else ``"serialized"``;
    * ``measured_serialized_fraction`` — exposed (non-hidden) exchange
      time over total exchange time, the measured analogue of the
      schedule auditor's modeled ``serialized_collective_fraction``.

    ``independent_spans`` maps each collective phase to the merged spans
    of compute that is DAG-INDEPENDENT of it (computed by
    :mod:`..analysis.phase_profile` from the schedule auditor's
    dependency cones). Without it, concurrent compute of ANY other
    non-exchange phase counts as hideable — an upper bound that
    over-credits lockstep-skew artifacts; the DAG-aware caller is the
    honest one.
    """
    phase_ms: Dict[str, float] = {}
    group_ms: Dict[str, float] = {g: 0.0 for g in GROUPS}
    for e in events:
        key = e.phase or "(unscoped)"
        phase_ms[key] = phase_ms.get(key, 0.0) + e.dur / 1e3
        group_ms[group_of(e.phase)] += e.dur / 1e3

    wall_union = union_of(events)
    wall_ms = total(wall_union) / 1e3

    coll_phases = sorted({e.phase for e in events
                          if is_collective_phase(e.phase)})
    compute_events = [e for e in events
                      if not is_collective_phase(e.phase)]
    collectives = []
    exposed_us = in_flight_us = 0.0
    for phase in coll_phases:
        cu = union_of([e for e in events if e.phase == phase])
        if independent_spans is not None:
            ind = independent_spans.get(phase, [])
        else:
            ind = union_of(compute_events)
        hidden_us = intersect_total(cu, ind)
        cu_us = total(cu)
        frac = hidden_us / cu_us if cu_us > 0 else 0.0
        in_flight_us += cu_us
        exposed_us += cu_us - hidden_us
        collectives.append({
            "phase": phase,
            "union_ms": round(cu_us / 1e3, 4),
            "hidden_ms": round(hidden_us / 1e3, 4),
            "hidden_frac": round(frac, 4),
            "classification": ("overlapped" if frac >= overlap_min_frac
                               else "serialized"),
        })
    a2a_union = union_of([e for e in events
                          if is_collective_phase(e.phase)])
    a2a_ms = total(a2a_union) / 1e3
    busy_ms = sum(phase_ms.values())
    return {
        "events": len(events),
        "events_resolved": sum(e.resolved for e in events),
        "wall_ms": round(wall_ms, 4),
        "busy_ms": round(busy_ms, 4),
        "concurrency": round(busy_ms / wall_ms, 4) if wall_ms > 0 else 0.0,
        "phase_ms": {k: round(v, 4) for k, v in sorted(phase_ms.items())},
        "group_ms": {k: round(v, 4) for k, v in group_ms.items()},
        "a2a_union_ms": round(a2a_ms, 4),
        "a2a_frac": round(a2a_ms / wall_ms, 4) if wall_ms > 0 else 0.0,
        "collectives": collectives,
        "measured_serialized_fraction": (
            round(exposed_us / in_flight_us, 4) if in_flight_us > 0
            else None),
        "overlap_min_frac": overlap_min_frac,
    }


# ------------------------------------------------- request-trace parsing


def parse_request_traces(path_or_doc: Any) -> List[Dict[str, Any]]:
    """The inverse of :func:`~.reqtrace.traces_to_chrome`: regroup the
    ``req/*`` events of a Chrome trace document (or a path to one,
    ``.gz`` fine) back into per-request trace summaries.

    Every returned dict has ``trace_id`` / ``outcome`` / ``rid`` /
    ``latency_ms`` / ``stages_ms`` / ``events`` / ``attrs`` — enough to
    re-check the span-partition invariant (``sum(stages_ms.values()) ==
    latency_ms``) and to find the restart-crossing trace without ever
    importing the writer. Coalesce (``req/flush``) spans are surfaced
    separately under ``"flushes"`` in each trace's ``attrs`` owner; they
    are returned as-is in no trace (they link several).
    """
    doc = (load_trace(path_or_doc) if isinstance(path_or_doc, str)
           else path_or_doc)
    traces: Dict[str, Dict[str, Any]] = {}
    for e in doc.get("traceEvents") or []:
        name = str(e.get("name", ""))
        if e.get("ph") != "X" or not obs.is_request_event(name):
            continue
        kind = name[len(obs.REQ_EVENT_PREFIX):]
        args = e.get("args") or {}
        tid = args.get("trace_id")
        if kind == "flush" or tid is None:
            continue
        rec = traces.setdefault(tid, {
            "trace_id": tid, "outcome": None, "rid": None,
            "latency_ms": None, "stages_ms": {}, "events": [],
            "attrs": {}})
        if kind.startswith("stage/"):
            rec["stages_ms"][kind[len("stage/"):]] = float(
                args.get("ms", float(e.get("dur", 0.0)) / 1e3))
        elif kind.startswith("mark/"):
            ev = {k: v for k, v in args.items() if k != "trace_id"}
            ev["name"] = kind[len("mark/"):]
            ev["dur_ms"] = float(e.get("dur", 0.0)) / 1e3
            rec["events"].append(ev)
        else:
            # the envelope event: kind IS the outcome
            rec["outcome"] = kind
            rec["rid"] = args.get("rid")
            rec["latency_ms"] = args.get(
                "latency_ms", float(e.get("dur", 0.0)) / 1e3)
            rec["attrs"] = {k: v for k, v in args.items()
                            if k not in ("trace_id", "rid", "outcome",
                                         "latency_ms")}
    # envelope-less fragments (partial exports) are dropped: without an
    # outcome there is nothing to gate on
    return [t for t in traces.values() if t["outcome"] is not None]

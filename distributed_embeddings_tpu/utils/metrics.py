"""Evaluation metrics (the reference evaluates with Keras ``AUC``,
``examples/dlrm/main.py:223-243``)."""

from __future__ import annotations

import numpy as np


def binary_auc(labels: np.ndarray, predictions: np.ndarray) -> float:
    """Exact ROC AUC via the rank statistic (equivalent to the trapezoidal
    ROC integral at every threshold; no binning error unlike the reference's
    8000-bucket Keras metric)."""
    labels = np.asarray(labels).reshape(-1)
    predictions = np.asarray(predictions).reshape(-1)
    order = np.argsort(predictions, kind="mergesort")
    ranks = np.empty_like(order, dtype=np.float64)
    ranks[order] = np.arange(1, len(order) + 1)
    # average ties
    sorted_pred = predictions[order]
    uniq, inv, counts = np.unique(sorted_pred, return_inverse=True,
                                  return_counts=True)
    if len(uniq) != len(sorted_pred):
        cum = np.cumsum(counts)
        avg_rank = cum - (counts - 1) / 2.0
        ranks[order] = avg_rank[inv]
    pos = labels > 0.5
    n_pos, n_neg = pos.sum(), (~pos).sum()
    if n_pos == 0 or n_neg == 0:
        return float("nan")
    return float((ranks[pos].sum() - n_pos * (n_pos + 1) / 2) / (n_pos * n_neg))

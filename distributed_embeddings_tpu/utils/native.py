"""ctypes bindings for the native data runtime (``cc/libdetpu_dataio.so``).

The reference loads its CUDA custom-op library at import
(``python/ops/embedding_lookup_ops.py:23``); here the native piece is host
data IO and it is optional — every entry point has a numpy fallback, so the
package works without the compiled library (build with ``make -C cc``).
"""

from __future__ import annotations

import ctypes
import os
from typing import Optional, Sequence

import numpy as np

_LIB = None


def _find_lib() -> Optional[ctypes.CDLL]:
    here = os.path.dirname(os.path.abspath(__file__))
    candidates = [
        os.path.join(here, "..", "..", "cc", "libdetpu_dataio.so"),
        os.path.join(here, "libdetpu_dataio.so"),
    ]
    for c in candidates:
        if os.path.exists(c):
            try:
                lib = ctypes.CDLL(os.path.abspath(c))
            except OSError:
                continue
            lib.detpu_power_law_ids.argtypes = [
                ctypes.c_uint64, ctypes.c_double, ctypes.c_int64,
                ctypes.c_int64, ctypes.POINTER(ctypes.c_int32)]
            lib.detpu_uniform_ids.argtypes = [
                ctypes.c_uint64, ctypes.c_int64, ctypes.c_int64,
                ctypes.POINTER(ctypes.c_int32)]
            lib.detpu_row_to_split.argtypes = [
                ctypes.POINTER(ctypes.c_int64), ctypes.c_int64,
                ctypes.c_int64, ctypes.POINTER(ctypes.c_int32)]
            lib.detpu_criteo_open.restype = ctypes.c_void_p
            lib.detpu_criteo_open.argtypes = [
                ctypes.c_char_p, ctypes.POINTER(ctypes.c_int32),
                ctypes.c_int, ctypes.POINTER(ctypes.c_int64), ctypes.c_int]
            lib.detpu_criteo_num_samples.restype = ctypes.c_int64
            lib.detpu_criteo_num_samples.argtypes = [ctypes.c_void_p]
            lib.detpu_criteo_read_batch.restype = ctypes.c_int
            lib.detpu_criteo_read_batch.argtypes = [
                ctypes.c_void_p, ctypes.c_int64, ctypes.c_int64,
                ctypes.POINTER(ctypes.c_float), ctypes.POINTER(ctypes.c_float),
                ctypes.POINTER(ctypes.c_int32)]
            lib.detpu_criteo_close.argtypes = [ctypes.c_void_p]
            return lib
    return None


def get_lib() -> Optional[ctypes.CDLL]:
    global _LIB
    if _LIB is None:
        _LIB = _find_lib() or False
    return _LIB or None


def have_native() -> bool:
    return get_lib() is not None


def native_power_law_ids(seed: int, alpha: float, vocab: int,
                         shape) -> Optional[np.ndarray]:
    """Native power-law ids, or None when the library is unavailable."""
    lib = get_lib()
    if lib is None:
        return None
    n = int(np.prod(shape))
    out = np.empty(n, np.int32)
    lib.detpu_power_law_ids(
        ctypes.c_uint64(seed), ctypes.c_double(alpha), ctypes.c_int64(vocab),
        ctypes.c_int64(n), out.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)))
    return out.reshape(shape)


def native_row_to_split(rows: np.ndarray, dim0: int) -> Optional[np.ndarray]:
    lib = get_lib()
    if lib is None:
        return None
    rows = np.ascontiguousarray(rows, np.int64)
    out = np.empty(dim0 + 1, np.int32)
    lib.detpu_row_to_split(
        rows.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        ctypes.c_int64(len(rows)), ctypes.c_int64(dim0),
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)))
    return out


class NativeCriteoReader:
    """Criteo split-binary reader backed by the C library.

    Same file format as :class:`~distributed_embeddings_tpu.utils.data.RawBinaryDataset`
    (and the reference's, ``examples/dlrm/utils.py:157-307``); this path does
    the dtype widening (bool→f32, f16→f32, int8/16→i32) in C.
    """

    def __init__(self, split_dir: str, cat_ids: Sequence[int],
                 all_sizes: Sequence[int], num_numerical: int):
        lib = get_lib()
        if lib is None:
            raise RuntimeError(
                "native library not built; run `make -C cc` or use "
                "RawBinaryDataset")
        self._lib = lib
        self._num_numerical = num_numerical
        self._num_cats = len(cat_ids)
        cat_arr = np.asarray(cat_ids, np.int32)
        size_arr = np.asarray(all_sizes, np.int64)
        self._h = lib.detpu_criteo_open(
            split_dir.encode(), cat_arr.ctypes.data_as(
                ctypes.POINTER(ctypes.c_int32)),
            len(cat_ids),
            size_arr.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
            num_numerical)
        if not self._h:
            raise FileNotFoundError(f"cannot open criteo files in {split_dir}")
        self.num_samples = lib.detpu_criteo_num_samples(self._h)

    def read(self, start: int, batch: int):
        labels = np.empty(batch, np.float32)
        numerical = np.empty(batch * self._num_numerical, np.float32)
        cats = np.empty(self._num_cats * batch, np.int32)
        rc = self._lib.detpu_criteo_read_batch(
            self._h, ctypes.c_int64(start), ctypes.c_int64(batch),
            labels.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
            numerical.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
            cats.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)))
        if rc != 0:
            raise IOError(f"criteo read failed with code {rc}")
        return (numerical.reshape(batch, self._num_numerical),
                [cats[c * batch:(c + 1) * batch] for c in range(self._num_cats)],
                labels.reshape(batch, 1))

    def close(self):
        if self._h:
            self._lib.detpu_criteo_close(self._h)
            self._h = None

    def __del__(self):
        try:
            self.close()
        except Exception:  # pylint: disable=broad-except
            pass

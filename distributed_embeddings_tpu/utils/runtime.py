"""Fault-tolerant runtime layer: time-boxed backend probing, retries,
deadlines, fault injection, and crash-surviving section records.

Production training stacks treat a flaky accelerator runtime, a killed
process mid-checkpoint, and a slow coordinator as normal operating
conditions, not fatal errors. The reference library assumes a healthy
NCCL/Horovod world and dies (or hangs) otherwise; this module is the
TPU-native reproduction's answer (VERDICT r5 "What's missing" #1: a bare
``jax.device_count()`` hung >2 min when the device tunnel stalled and took
the whole round's artifacts with it).

Pieces, all composable and CPU-testable:

* :func:`probe_backend` — the ONLY safe first backend touch: runs
  ``jax.device_count()`` in a watched subprocess with a wall-clock timeout,
  so the calling process never blocks on a stalled tunnel. Returns a
  :class:`BackendProbe` verdict instead of hanging or raising.
* :func:`require_devices` — probe + policy: a :class:`DeviceSpec` saying
  either "the real backend has your ``n`` devices" or "run on a forced
  ``n``-virtual-device CPU mesh" (the ``tests/conftest.py`` mechanism),
  with :meth:`DeviceSpec.child_env` producing the environment for a child
  process. The parent never initializes any backend.
* :func:`retry` — jittered exponential backoff under a deadline and/or an
  attempt budget.
* :func:`deadline` — best-effort wall-clock bound on a code block
  (``SIGALRM``; main thread, Unix). A section stuck inside a C call is
  interrupted when it next returns to Python — pair with an external
  watchdog (or :class:`SectionRecorder`) for hard hangs.
* :func:`fault_point` — env-driven fault injection
  (``DETPU_FAULT=hang:backend,slow:coordinator,die:checkpoint_write``)
  so every failure mode above is exercisable in CPU-only tests.
* :class:`SectionRecorder` / :func:`run_section` — append-only,
  fsynced JSONL sidecar of per-section results, so a process killed
  mid-run (OOM, SIGKILL, driver timeout) leaves every completed section's
  record parseable on disk. ``bench.py`` rides this.

This module deliberately does NOT import jax at module scope: importing it
must never risk touching (or waiting on) an accelerator backend.
"""

from __future__ import annotations

import contextlib
import dataclasses
import json
import logging
import os
import random
import signal
import subprocess
import sys
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

from . import envvars

logger = logging.getLogger(__name__)

FAULT_ENV = "DETPU_FAULT"
_PROBE_MARKER = "DETPU_PROBE "
# repo root: runtime.py -> utils -> distributed_embeddings_tpu -> root
_PKG_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


# ------------------------------------------------------------------ errors


class RuntimeFault(RuntimeError):
    """Base class for the fault layer's own errors."""


class BackendUnavailable(RuntimeFault):
    """The accelerator backend could not be probed within its deadline."""

    def __init__(self, msg: str, probe: Optional["BackendProbe"] = None):
        super().__init__(msg)
        self.probe = probe


class DeadlineExceeded(RuntimeFault):
    """A :func:`deadline`-bounded block (or :func:`retry`) ran out of time."""


class CoordinatorUnreachable(RuntimeFault):
    """A multi-process job was expected but the coordinator join kept
    failing — raised by ``bootstrap.initialize`` after its retry budget."""


class CheckpointCorrupt(RuntimeFault):
    """A checkpoint failed validation (missing file, CRC mismatch, torn
    manifest) and no fallback was available."""


class CheckpointMismatch(RuntimeFault):
    """A (whole, CRC-valid) checkpoint does not match the model it is being
    restored into — wrong table count, or a table whose saved vocab/dim
    disagrees with ``de.strategy.global_configs``. Raised by
    ``utils.checkpoint.restore_train_state`` BEFORE any data streams, so a
    config drift surfaces as one clear error instead of a scatter-shape
    traceback deep inside ``set_weights``."""


class InvalidInputError(RuntimeFault):
    """An input batch violated the id contract (negative / out-of-vocab ids,
    or a ragged batch whose claimed lengths overflow its static capacity)
    under the ``'raise'`` invalid-id policy or the opt-in
    ``ragged_overflow_raise`` escalation."""


class NonFiniteLossError(RuntimeFault):
    """The training loss stayed non-finite for K consecutive steps — the
    on-device guard kept skipping updates (params untouched), and the host
    driver escalates instead of spinning on a poisoned stream. The message
    names the last good step."""


class FaultInjected(RuntimeFault):
    """Raised by :func:`fault_point` under ``DETPU_FAULT=raise:<point>``."""


# --------------------------------------------------------- fault injection

# per-process fire counts, keyed by (mode, point): lets a spec carry a
# budget ("fail the first N calls, then pass") for retry-then-succeed tests
_fire_counts: Dict[Tuple[str, str], int] = {}


def reset_fault_counts() -> None:
    """Forget fire-count state (test isolation helper)."""
    _fire_counts.clear()


def _fault_specs() -> List[Tuple[str, str, Optional[str]]]:
    """Parse ``DETPU_FAULT`` (read at every call so tests can flip it at
    runtime): comma-separated ``mode:point[:arg]`` entries."""
    out = []
    for item in (envvars.get(FAULT_ENV) or "").split(","):
        item = item.strip()
        if not item:
            continue
        if (item.startswith(("preempt@", "nan@", "badbatch@", "oovflood@",
                             "burst@", "die@", "hang@"))
                or item == "corrupt@ckpt"):
            continue  # driver/checkpoint-level drills: see preempt_step(),
            # nan_steps(), badbatch_steps(), oovflood_steps(),
            # burst_steps(), die_steps(), hang_steps() and
            # corrupt_ckpt_requested()
        parts = item.split(":", 2)
        if len(parts) < 2:
            logger.warning("ignoring malformed %s entry %r", FAULT_ENV, item)
            continue
        out.append((parts[0], parts[1], parts[2] if len(parts) > 2 else None))
    return out


def preempt_step() -> Optional[int]:
    """Step index of a ``DETPU_FAULT=preempt@<step>`` preemption drill, or
    ``None``. At that step boundary the resilient driver
    (``parallel.resilient.run_resilient``) delivers itself a real SIGTERM —
    exercising the full preemption path (handler, finish the in-flight
    step, checkpoint, resume sentinel) deterministically on CPU. Parsed per
    call like the other fault specs, so tests can flip it at runtime."""
    for item in (envvars.get(FAULT_ENV) or "").split(","):
        item = item.strip()
        if not item.startswith("preempt@"):
            continue
        try:
            return int(item.split("@", 1)[1])
        except ValueError:
            logger.warning("ignoring malformed %s entry %r", FAULT_ENV, item)
    return None


def _at_steps(prefix: str) -> Tuple[int, ...]:
    """Step indices of every ``<prefix>@<step>`` entry in ``DETPU_FAULT``
    (parsed per call like the other fault specs, so tests can flip the
    variable at runtime). Malformed entries warn and are dropped."""
    out = []
    for item in (envvars.get(FAULT_ENV) or "").split(","):
        item = item.strip()
        if not item.startswith(prefix + "@"):
            continue
        try:
            out.append(int(item.split("@", 1)[1]))
        except ValueError:
            logger.warning("ignoring malformed %s entry %r", FAULT_ENV, item)
    return tuple(out)


def nan_steps() -> Tuple[int, ...]:
    """Batch indices of ``DETPU_FAULT=nan@<step>`` drills: at each of
    those stream positions the resilient driver poisons ONE rank's slice
    of the dense batch with a NaN before dispatch, so the poison flows
    through the real forward into the loss and the on-device guard (and,
    after ``DETPU_NANGUARD_K`` in a row, the rollback-and-replay
    recovery) sees an organic non-finite step — the NaN-storm chaos
    drill, deterministic on CPU."""
    return _at_steps("nan")


def badbatch_steps() -> Tuple[int, ...]:
    """Batch indices of ``DETPU_FAULT=badbatch@<step>`` drills: at each
    of those stream positions the resilient driver corrupts the batch's
    categorical ids (scrambled negative/out-of-vocab values) before
    dispatch — the garbled-input chaos drill the ``invalid_id_policy``
    machinery (clamp / drop / raise + ``invalid_id_count``) must absorb
    or escalate."""
    return _at_steps("badbatch")


def oovflood_steps() -> Tuple[int, ...]:
    """Batch indices of ``DETPU_FAULT=oovflood@<pos>`` drills: at each of
    those stream positions the resilient driver replaces the batch's
    categorical ids with a burst of NEVER-BEFORE-SEEN ids before
    dispatch — the non-stationary-traffic chaos drill. A streaming-vocab
    run (``parallel/streaming.py``) must absorb the flood gracefully:
    the novel ids land in their shared hash buckets (no crash, no
    recompile, no hot-row eviction until the sketch gate passes); a
    static-vocab run sees them as out-of-vocab ids the
    ``invalid_id_policy`` machinery clamps/drops/escalates. Targets
    STREAM positions (like ``nan@``/``badbatch@``) so rollback replays
    re-inject deterministically."""
    return _at_steps("oovflood")


def burst_steps() -> Tuple[int, ...]:
    """Positions of ``DETPU_FAULT=burst@<pos>`` drills: at each of those
    positions of a serving request stream (whole seconds since the stream
    started) the load generator multiplies the arrival rate by
    ``DETPU_SERVE_BURST_X`` — the QPS-spike chaos drill the serving
    runtime's admission controller (``parallel/serving.py``) must absorb
    by walking its degradation ladder: shrink the batching delay, then
    shed lowest-priority requests with a typed ``Overloaded`` response —
    never unbounded queue growth, never a crash, and normal service must
    resume once the burst passes. Deterministic per position (the drill
    decides WHEN the spike hits; the stream contents stay the seeded
    Zipfian draw), parsed per call like the other fault specs."""
    return _at_steps("burst")


def die_steps() -> Tuple[int, ...]:
    """Positions of ``DETPU_FAULT=die@<pos>`` drills: at each of those
    positions of a supervised serving worker's request stream (GLOBAL
    ordinals — the supervisor's request counter, monotone across
    restarts, so each position fires at most once and a drill kill is
    followed by clean recovery, not a crash loop) the worker hard-exits
    (``os._exit``, no cleanup handlers — the SIGKILL/OOM-kill
    equivalent). The trainer-side :class:`~..parallel.supervisor
    .Supervisor` must detect the death, answer every in-flight request
    with a typed ``Unavailable``, dump the crash black box on the
    child's behalf, and restart the worker under its backoff budget —
    the crash-containment drill ``make check-isolation`` runs. Parsed
    per call like the other fault specs."""
    return _at_steps("die")


def hang_steps() -> Tuple[int, ...]:
    """Positions of ``DETPU_FAULT=hang@<pos>`` drills: at each of those
    positions of a supervised serving worker's request stream the worker
    stops answering (a long sleep on its control loop — the wedged-
    process equivalent of ``die@``). Heartbeats stop, the supervisor's
    deadline trips, and the worker is killed and restarted exactly like
    a crash — hang detection must never depend on the child
    cooperating. Parsed per call like the other fault specs."""
    return _at_steps("hang")


def corrupt_ckpt_requested() -> bool:
    """True when ``DETPU_FAULT=corrupt@ckpt`` asks the checkpoint layer to
    flip bytes in a just-committed shard file — simulated silent on-disk
    corruption (bit rot, torn external copy) that the CRC manifest must
    catch on the next restore. Parsed per call like the other fault specs,
    so tests can flip it at runtime and corrupt exactly the save they
    choreograph."""
    return any(item.strip() == "corrupt@ckpt"
               for item in (envvars.get(FAULT_ENV) or "").split(","))


def fault_point(point: str) -> None:
    """Named fault-injection hook. No-op unless ``DETPU_FAULT`` targets
    ``point``. Modes:

    * ``hang:<point>[:secs]`` — sleep (default 3600 s): a stalled backend
      tunnel / unreachable service that never errors out.
    * ``slow:<point>[:secs]`` — sleep (default 5 s): a degraded service
      that eventually responds.
    * ``raise:<point>[:count]`` — raise :class:`FaultInjected`; with a
      count, only the first ``count`` calls raise (then the point passes) —
      the retry-then-succeed scenario.
    * ``die:<point>`` — ``os._exit(17)``: hard process death (SIGKILL /
      OOM-kill equivalent), no cleanup handlers run.
    """
    for mode, p, arg in _fault_specs():
        if p != point:
            continue
        key = (mode, p)
        n = _fire_counts.get(key, 0)
        if mode == "raise" and arg is not None and n >= int(arg):
            continue  # budget exhausted: the point now passes
        _fire_counts[key] = n + 1
        from . import obs  # lazy: obs imports this module at its top

        obs.record_fault(point)
        if mode == "hang":
            time.sleep(float(arg) if arg else 3600.0)
        elif mode == "slow":
            time.sleep(float(arg) if arg else 5.0)
        elif mode == "raise":
            raise FaultInjected(f"injected fault at {point!r}")
        elif mode == "die":
            logger.error("DETPU_FAULT: dying at %r", point)
            os._exit(17)
        else:
            logger.warning("ignoring unknown %s mode %r", FAULT_ENV, mode)


# ------------------------------------------------------------------- retry


def retry(fn: Callable[[], Any], *,
          deadline_s: Optional[float] = None,
          max_attempts: Optional[int] = None,
          base_delay_s: float = 0.5,
          max_delay_s: float = 8.0,
          retry_on: Tuple[type, ...] = (Exception,),
          describe: str = "operation") -> Any:
    """Call ``fn()`` until it succeeds, with jittered exponential backoff.

    Stops when either budget runs out: ``deadline_s`` (wall clock over all
    attempts, including backoff sleeps) or ``max_attempts``. At least one
    attempt always runs. On exhaustion re-raises the last error (wrapped in
    :class:`DeadlineExceeded` when the deadline was the binding budget).
    """
    if deadline_s is None and max_attempts is None:
        max_attempts = 3
    start = time.monotonic()
    attempt = 0
    while True:
        attempt += 1
        try:
            return fn()
        except retry_on as e:  # noqa: PERF203 - retry loop
            if max_attempts is not None and attempt >= max_attempts:
                raise
            delay = min(max_delay_s, base_delay_s * (2 ** (attempt - 1)))
            delay *= 0.5 + random.random()  # jitter in [0.5x, 1.5x)
            if deadline_s is not None:
                elapsed = time.monotonic() - start
                if elapsed + delay >= deadline_s:
                    raise DeadlineExceeded(
                        f"{describe} still failing after {attempt} attempt(s)"
                        f" / {elapsed:.1f}s (deadline {deadline_s}s): "
                        f"{e!r}") from e
            logger.warning("%s failed (attempt %d): %r — retrying in %.2fs",
                           describe, attempt, e, delay)
            from . import obs  # lazy: obs imports this module at its top

            obs.record_retry(describe)
            time.sleep(delay)


# ---------------------------------------------------------------- deadline


@contextlib.contextmanager
def deadline(seconds: Optional[float], label: str = "block"):
    """Best-effort wall-clock bound: raises :class:`DeadlineExceeded` from
    inside the block after ``seconds``.

    Implemented with ``SIGALRM`` (``setitimer``), so it only engages on the
    main thread of a Unix process; elsewhere (or with ``seconds`` falsy) it
    is a transparent no-op. The alarm interrupts Python bytecode and most
    blocking syscalls (``time.sleep``, socket waits); code stuck inside a
    non-signal-aware C call (e.g. a wedged XLA compile) is only interrupted
    when it returns to Python — the layer above should pair this with a
    subprocess watchdog (:func:`probe_backend`) or crash-surviving records
    (:class:`SectionRecorder`) for those.
    """
    if (not seconds
            or not hasattr(signal, "SIGALRM")
            or threading.current_thread() is not threading.main_thread()):
        yield
        return

    def _alarm(signum, frame):
        raise DeadlineExceeded(f"{label} exceeded {seconds}s deadline")

    old_handler = signal.signal(signal.SIGALRM, _alarm)
    signal.setitimer(signal.ITIMER_REAL, seconds)
    try:
        yield
    finally:
        signal.setitimer(signal.ITIMER_REAL, 0.0)
        signal.signal(signal.SIGALRM, old_handler)


# ----------------------------------------------------------- backend probe


@dataclasses.dataclass(frozen=True)
class BackendProbe:
    """Verdict of one time-boxed backend probe."""

    ok: bool
    platform: Optional[str]
    device_count: int
    elapsed_s: float
    error: Optional[str] = None

    def to_json(self) -> Dict[str, Any]:
        return dataclasses.asdict(self)


def _probe_child() -> None:
    """Body of the probe subprocess: the actual first backend touch.

    ``fault_point('backend')`` runs BEFORE jax initializes any backend, so
    ``DETPU_FAULT=hang:backend`` simulates the stalled-tunnel scenario the
    probe exists for.
    """
    fault_point("backend")
    import jax

    out = {"platform": jax.default_backend(),
           "device_count": jax.device_count()}
    sys.stdout.write(_PROBE_MARKER + json.dumps(out) + "\n")
    sys.stdout.flush()


def probe_backend(timeout_s: float = 120.0,
                  platform: Optional[str] = None) -> BackendProbe:
    """First backend touch, in a watched subprocess with a hard timeout.

    Returns a :class:`BackendProbe` — never raises and never hangs past
    ``timeout_s`` (plus child-kill slack). ``platform`` forces the child's
    ``JAX_PLATFORMS`` (e.g. ``"cpu"``); by default the child inherits this
    process's environment and probes whatever backend a bare ``import jax;
    jax.device_count()`` would have touched here.
    """
    env = dict(os.environ)
    if platform is not None:
        env["JAX_PLATFORMS"] = platform
    code = (f"import sys; sys.path.insert(0, {_PKG_ROOT!r}); "
            "from distributed_embeddings_tpu.utils.runtime import "
            "_probe_child; _probe_child()")
    start = time.monotonic()
    # own session/process group: an accelerator runtime may fork helpers
    # that inherit the stdout/stderr pipes — killing only the direct child
    # would leave communicate() blocked on the open pipe (the exact hang
    # this function exists to prevent), so on timeout the whole group dies
    proc = subprocess.Popen(
        [sys.executable, "-c", code], env=env, text=True,
        stdout=subprocess.PIPE, stderr=subprocess.PIPE,
        start_new_session=True)
    try:
        stdout, stderr = proc.communicate(timeout=timeout_s)
    except subprocess.TimeoutExpired:
        try:
            os.killpg(proc.pid, signal.SIGKILL)
        except (ProcessLookupError, PermissionError, OSError):
            proc.kill()
        try:  # reap; bounded in case a grandchild survived the killpg
            proc.communicate(timeout=5)
        except subprocess.TimeoutExpired:
            pass
        elapsed = time.monotonic() - start
        logger.warning("backend probe timed out after %.1fs "
                       "(stalled tunnel?)", elapsed)
        return BackendProbe(ok=False, platform=None, device_count=0,
                            elapsed_s=elapsed,
                            error=f"probe timed out after {timeout_s}s")
    elapsed = time.monotonic() - start
    for line in reversed((stdout or "").splitlines()):
        if line.startswith(_PROBE_MARKER):
            info = json.loads(line[len(_PROBE_MARKER):])
            return BackendProbe(ok=True, platform=info["platform"],
                                device_count=int(info["device_count"]),
                                elapsed_s=elapsed)
    tail = (stderr or stdout or "").strip()[-500:]
    return BackendProbe(ok=False, platform=None, device_count=0,
                        elapsed_s=elapsed,
                        error=f"probe child rc={proc.returncode}: {tail}")


@dataclasses.dataclass(frozen=True)
class DeviceSpec:
    """How to get the devices a caller asked for (see
    :func:`require_devices`): run on the probed real backend, or fall back
    to a forced virtual-CPU mesh in a child process."""

    platform: str
    device_count: int
    forced_cpu: bool
    probe: BackendProbe

    def child_env(self, base: Optional[Dict[str, str]] = None
                  ) -> Dict[str, str]:
        """Environment for a child process running under this spec. For the
        forced-CPU fallback this pins ``JAX_PLATFORMS=cpu`` and appends
        ``--xla_force_host_platform_device_count`` (the conftest mechanism;
        last flag occurrence wins inside XLA_FLAGS)."""
        env = dict(os.environ if base is None else base)
        if self.forced_cpu:
            env["JAX_PLATFORMS"] = "cpu"
            env["XLA_FLAGS"] = (
                env.get("XLA_FLAGS", "")
                + f" --xla_force_host_platform_device_count="
                  f"{self.device_count}")
        return env


def require_devices(n: int, timeout_s: float = 120.0,
                    probe: Optional[BackendProbe] = None) -> DeviceSpec:
    """Probe the backend and decide where ``n`` devices will come from.

    If the probe succeeds within ``timeout_s`` and reports ``>= n``
    devices, the spec points at the real backend. Otherwise (stalled
    tunnel, dead plugin, or simply too few chips) it falls back to an
    ``n``-virtual-device CPU mesh spec — without this process ever
    initializing any accelerator backend itself.

    Pass ``probe`` to reuse a :func:`probe_backend` result already in hand
    — each probe is a full subprocess (package import included), and on
    the stalled-tunnel path each one costs the whole ``timeout_s``.
    """
    if probe is None:
        probe = probe_backend(timeout_s=timeout_s)
    if probe.ok and probe.device_count >= n:
        return DeviceSpec(platform=probe.platform or "unknown",
                          device_count=probe.device_count,
                          forced_cpu=False, probe=probe)
    if not probe.ok:
        logger.warning("backend unavailable (%s): falling back to a "
                       "%d-virtual-device CPU mesh", probe.error, n)
    else:
        logger.info("backend %s has %d device(s) < %d required: falling "
                    "back to a forced CPU mesh", probe.platform,
                    probe.device_count, n)
    return DeviceSpec(platform="cpu", device_count=n, forced_cpu=True,
                      probe=probe)


# ------------------------------------------- crash-surviving section records


class SectionRecorder:
    """Append-only JSONL sidecar of per-section results.

    Every :meth:`record` appends one JSON line and fsyncs it, so a process
    killed at ANY later point (SIGKILL, OOM, driver timeout) leaves every
    previously completed section's record intact and parseable. A torn
    final line (killed mid-write) is skipped by :meth:`load`.
    """

    def __init__(self, path: str):
        self.path = path
        d = os.path.dirname(os.path.abspath(path))
        os.makedirs(d, exist_ok=True)

    def record(self, section: str, **fields: Any) -> Dict[str, Any]:
        rec: Dict[str, Any] = {"section": section, **fields}
        line = json.dumps(rec, default=_jsonable)
        with open(self.path, "a", encoding="utf-8") as f:
            f.write(line + "\n")
            f.flush()
            os.fsync(f.fileno())
        return rec

    @staticmethod
    def load(path: str) -> List[Dict[str, Any]]:
        """Parse a sidecar, tolerating a torn trailing line."""
        out: List[Dict[str, Any]] = []
        if not os.path.exists(path):
            return out
        with open(path, encoding="utf-8") as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    out.append(json.loads(line))
                except json.JSONDecodeError:
                    logger.warning("skipping torn sidecar line in %s", path)
        return out


def _jsonable(x: Any) -> Any:
    """Best-effort JSON coercion for section payloads (numpy scalars AND
    arrays, tuples of floats, dataclasses)."""
    if dataclasses.is_dataclass(x) and not isinstance(x, type):
        return dataclasses.asdict(x)
    if hasattr(x, "tolist"):  # numpy/jax scalar or array, any shape
        return x.tolist()
    if isinstance(x, (set, tuple)):
        return list(x)
    return repr(x)


def run_section(recorder: Optional[SectionRecorder], name: str,
                fn: Callable[[], Any], *, default: Any = None,
                retries: int = 1, deadline_s: Optional[float] = None
                ) -> Any:
    """Run one named section under a (best-effort) deadline, with retries,
    recording the outcome to ``recorder`` the moment it is known.

    One failed or hung section must not take down the run: failures are
    logged + recorded and ``default`` is returned. ``fault_point('<name>')``
    fires first, so any section is individually killable/hangable via
    ``DETPU_FAULT`` in tests.
    """
    import traceback

    last_err = None
    for attempt in range(retries + 1):
        t0 = time.monotonic()
        try:
            # fault_point INSIDE the deadline: an injected hang at a
            # section point must be bounded like any other section work
            with deadline(deadline_s, label=f"section {name!r}"):
                fault_point(name)
                value = fn()
        except Exception as e:  # noqa: BLE001 - report and continue
            last_err = e
            print(f"[runtime] section {name} failed "
                  f"(attempt {attempt + 1}/{retries + 1}):", file=sys.stderr)
            traceback.print_exc()
            continue
        if recorder is not None:
            # outside the try: a recording hiccup (full disk, odd payload)
            # must not re-run — or worse, discard — a computed result
            try:
                recorder.record(name, ok=True, value=value,
                                elapsed_s=round(time.monotonic() - t0, 3),
                                attempt=attempt + 1)
            except Exception:  # noqa: BLE001 - the value still stands
                logger.exception("could not record section %r result", name)
        return value
    if recorder is not None:
        try:
            recorder.record(name, ok=False, error=repr(last_err),
                            attempts=retries + 1)
        except Exception:  # noqa: BLE001 - sidecar is best-effort
            logger.exception("could not record section %r failure", name)
    return default

"""Production observability plane: one metrics registry, mergeable
quantile sketches, a Prometheus scrape surface, and a crash flight
recorder.

The runtimes grew four ad-hoc signal surfaces — :class:`~.obs.
MetricsLogger` JSONL, :func:`~.obs.record_event`, the serving runtime's
raw latency lists, and the process counters — with no single scrapeable
plane, no bounded-memory percentiles, and no post-mortem artifact when a
run dies. This module is that plane. Four pieces, zero dependencies
(stdlib only — like the rest of :mod:`..utils`'s host layer it never
imports jax OR numpy, so it works in processes that never load a
backend):

* **:class:`QuantileSketch`** — a DDSketch-style log-bucketed quantile
  sketch: values land in geometrically-spaced buckets (ratio
  ``gamma = (1+a)/(1-a)`` for relative accuracy ``a``), so any quantile
  reads back within a GUARANTEED relative error ``a`` of the true value,
  memory is O(buckets) however many samples arrive (the serving runtime
  previously kept O(STATS_WINDOW) raw floats per signal and full-sorted
  them per ``stats()`` call), and two sketches MERGE associatively and
  commutatively by bucket-count addition — per-rank/per-process sketches
  fold into one fleet view losslessly.
* **:class:`MetricsRegistry`** — labeled counter / gauge / sketch
  families, one namespace. Families render to the Prometheus text
  exposition format (counters/gauges as-is, sketches as ``summary``
  quantiles); collector callbacks registered with
  :meth:`MetricsRegistry.register_collector` refresh adapter-fed values
  at scrape time (the idiomatic pull model), which is how the existing
  surfaces — process counters, serving stats, step metrics — feed the
  plane without any caller changing.
* **The scrape endpoint** — :func:`start_http_exporter` serves
  ``GET /metrics`` from a stdlib ``ThreadingHTTPServer`` on an opt-in
  port (``DETPU_METRICS_PORT``; 0 picks an ephemeral port for tests),
  and :meth:`MetricsRegistry.export_file` atomically writes the same
  text for air-gapped runs (tmp + fsync + rename — the ``_atomic_json``
  idiom).
* **:class:`FlightRecorder`** — a bounded ring of recent step metrics,
  events, and stats snapshots, dumped ATOMICALLY (with a CRC32 stamp of
  the payload) to ``<checkpoint_dir>.blackbox.json`` on NaN escalation,
  rollback exhaustion, freshness/SLO breach, preemption, and unhandled
  crash. The ring is tiny and always on once installed; the dump is the
  only I/O and happens exactly when the run is already dying (or
  breaching) — a black box, not a logger.

``parallel/serving.py`` owns a registry per runtime (its ``stats()``
dict stays a VIEW over the sketches — no caller breaks),
``parallel/resilient.py`` installs the process flight recorder beside
its checkpoint directory, and ``tools/check_obsplane.py`` (= ``make
check-obsplane``) drills the whole plane end to end: scrape under
burst chaos, per-stage p99 decomposition summing to the end-to-end
latency, and a CRC-intact black box after an injected NaN escalation.
"""

from __future__ import annotations

import contextlib
import json
import logging
import math
import os
import threading
import time
import zlib
from typing import (Any, Callable, Dict, Iterable, List, Optional, Tuple)

from . import envvars

logger = logging.getLogger(__name__)

METRICS_PORT_ENV = "DETPU_METRICS_PORT"
BLACKBOX_ENV = "DETPU_BLACKBOX"
BLACKBOX_RING_ENV = "DETPU_BLACKBOX_RING"

#: Default guaranteed relative accuracy of registry sketches: a reported
#: quantile ``q`` satisfies ``|q - true| <= 0.01 * true`` — more than
#: enough to gate a p99 against an SLO, at ~1.4k buckets per *decade
#: span* of the data (sparse dict: only touched buckets exist).
DEFAULT_RELATIVE_ACCURACY = 0.01

#: Values at or below this observe into the dedicated zero bucket (the
#: log mapping needs a positive floor); latencies in ms sit far above.
MIN_TRACKABLE = 1e-9

# stand-in second lock for self-merge (merge(sk, sk) must not re-acquire)
_NULL_CTX = contextlib.nullcontext()

_LabelKey = Tuple[Tuple[str, str], ...]


def _label_key(labels: Dict[str, str]) -> _LabelKey:
    return tuple(sorted((str(k), str(v)) for k, v in labels.items()))


def _render_labels(key: _LabelKey, extra: Tuple[Tuple[str, str], ...] = ()
                   ) -> str:
    items = key + extra
    if not items:
        return ""
    body = ",".join(
        '{}="{}"'.format(k, v.replace("\\", r"\\").replace('"', r'\"'))
        for k, v in items)
    return "{" + body + "}"


def _fmt(v: float) -> str:
    """Prometheus number formatting: integers render bare, floats
    ``repr``-style (full precision, parseable back)."""
    f = float(v)
    if f != f:
        return "NaN"
    if f in (float("inf"), float("-inf")):
        return "+Inf" if f > 0 else "-Inf"
    if f == int(f) and abs(f) < 1e15:  # capacity-ok: float-precision
        # bound for bare-integer rendering, not a byte limit
        return str(int(f))
    return repr(f)


# ------------------------------------------------------------ the sketch


class QuantileSketch:
    """Mergeable log-bucketed quantile sketch (DDSketch-style).

    A positive value ``x`` lands in bucket ``ceil(log_gamma(x))`` where
    ``gamma = (1 + a) / (1 - a)``; reporting the bucket's log-midpoint
    ``2 * gamma^i / (gamma + 1)`` guarantees relative error ``<= a`` for
    every quantile. Buckets are a sparse dict (only touched indices
    exist), so memory is O(distinct buckets) — bounded by
    ``max_buckets`` via DDSketch's lowest-bucket collapse, which
    preserves the accuracy of every quantile above the collapsed floor
    (the high quantiles a latency SLO reads).

    :meth:`merge` adds bucket counts — associative and commutative by
    construction, so per-rank / per-process sketches fold into one
    fleet-wide view in any order with no accuracy loss.

    Thread-safe: a per-sketch lock covers every mutation and every read
    of the bucket dict, so a runtime thread can :meth:`observe` while
    the HTTP exporter's daemon thread renders quantiles — the
    concurrent-scrape case the real-time serving driver creates (a bare
    dict here throws ``dictionary changed size during iteration`` under
    that interleaving, or silently tears ``count``/``sum``).
    """

    __slots__ = ("relative_accuracy", "_gamma", "_log_gamma", "buckets",
                 "zero_count", "count", "sum", "min", "max", "max_buckets",
                 "_lock")

    def __init__(self, relative_accuracy: float = DEFAULT_RELATIVE_ACCURACY,
                 max_buckets: int = 4096):
        if not (0.0 < relative_accuracy < 1.0):
            raise ValueError("relative_accuracy must be in (0, 1)")
        self.relative_accuracy = float(relative_accuracy)
        self._gamma = (1.0 + relative_accuracy) / (1.0 - relative_accuracy)
        self._log_gamma = math.log(self._gamma)
        self.max_buckets = int(max_buckets)
        self.buckets: Dict[int, int] = {}
        self.zero_count = 0
        self.count = 0
        self.sum = 0.0
        self.min = math.inf
        self.max = -math.inf
        self._lock = threading.Lock()

    def observe(self, value: float) -> None:
        """Fold one sample in (O(1); negative values clamp to the zero
        bucket — every signal here is a latency/depth/age, never below
        zero by construction)."""
        v = float(value)
        with self._lock:
            self.count += 1
            self.sum += v
            if v < self.min:
                self.min = v
            if v > self.max:
                self.max = v
            if v <= MIN_TRACKABLE:
                self.zero_count += 1
                return
            i = math.ceil(math.log(v) / self._log_gamma)
            self.buckets[i] = self.buckets.get(i, 0) + 1
            if len(self.buckets) > self.max_buckets:
                self._collapse()

    def _collapse(self) -> None:
        # DDSketch collapse: fold the LOWEST buckets together so the
        # high quantiles (the ones SLOs read) keep their guarantee
        idx = sorted(self.buckets)
        floor = idx[len(idx) - self.max_buckets]
        folded = 0
        for i in idx:
            if i >= floor:
                break
            folded += self.buckets.pop(i)
        self.buckets[floor] = self.buckets.get(floor, 0) + folded

    def quantile(self, q: float) -> Optional[float]:
        """Value at quantile ``q`` in [0, 1] (within the relative-error
        guarantee), ``None`` when empty."""
        if not (0.0 <= q <= 1.0):
            raise ValueError(f"quantile {q} outside [0, 1]")
        with self._lock:
            if self.count == 0:
                return None
            rank = q * (self.count - 1)
            seen = self.zero_count
            if rank < seen:
                return 0.0
            for i in sorted(self.buckets):
                seen += self.buckets[i]
                if rank < seen:
                    # bucket (gamma^(i-1), gamma^i]: the log-midpoint
                    # keeps |reported - true| <= a * true for anything
                    # inside
                    mid = 2.0 * self._gamma ** i / (self._gamma + 1.0)
                    return min(mid, self.max)
            return self.max if self.max > -math.inf else None

    @property
    def mean(self) -> Optional[float]:
        return self.sum / self.count if self.count else None

    def merge(self, other: "QuantileSketch") -> "QuantileSketch":
        """Fold ``other`` into self (in place); bucket-count addition,
        so merge order never matters. Accuracies must match — merging
        differently-spaced buckets would silently void the guarantee."""
        if other.relative_accuracy != self.relative_accuracy:
            raise ValueError(
                f"cannot merge sketches of different accuracy "
                f"({self.relative_accuracy} vs {other.relative_accuracy})")
        # both locks, in id order, so two threads merging opposite
        # directions can't deadlock
        first, second = ((self, other) if id(self) <= id(other)
                         else (other, self))
        with first._lock:
            with second._lock if first is not second else _NULL_CTX:  # lock-order-ok: id-ordered acquisition (first/second sorted by id above) — both orders converge on one global order
                for i, n in other.buckets.items():
                    self.buckets[i] = self.buckets.get(i, 0) + n
                self.zero_count += other.zero_count
                self.count += other.count
                self.sum += other.sum
                self.min = min(self.min, other.min)
                self.max = max(self.max, other.max)
                if len(self.buckets) > self.max_buckets:
                    self._collapse()
        return self

    def to_dict(self) -> Dict[str, Any]:
        """JSON-portable form (cross-process merge / file export)."""
        with self._lock:
            return {"relative_accuracy": self.relative_accuracy,
                    "buckets": {str(i): n for i, n in self.buckets.items()},
                    "zero_count": self.zero_count, "count": self.count,
                    "sum": self.sum,
                    "min": self.min if self.count else None,
                    "max": self.max if self.count else None}

    @classmethod
    def from_dict(cls, doc: Dict[str, Any]) -> "QuantileSketch":
        sk = cls(relative_accuracy=float(doc["relative_accuracy"]))
        sk.buckets = {int(i): int(n)
                      for i, n in dict(doc.get("buckets", {})).items()}
        sk.zero_count = int(doc.get("zero_count", 0))
        sk.count = int(doc.get("count", 0))
        sk.sum = float(doc.get("sum", 0.0))
        sk.min = doc["min"] if doc.get("min") is not None else math.inf
        sk.max = doc["max"] if doc.get("max") is not None else -math.inf
        return sk


# ---------------------------------------------------------- the registry


class _Family:
    """One named metric family: children keyed by their label set.

    ``_children`` is guarded by a per-family lock: the runtime thread
    creates children (first observation of a new label set) while the
    exporter's daemon thread sorts them for a scrape — unguarded, that
    interleaving dies with ``dictionary changed size during iteration``.
    """

    kind = "untyped"

    def __init__(self, name: str, help_text: str):
        self.name = name
        self.help = help_text
        self._children: Dict[_LabelKey, Any] = {}
        self._lock = threading.Lock()

    def child(self, **labels: str):
        key = _label_key(labels)
        with self._lock:
            c = self._children.get(key)
            if c is None:
                c = self._new_child()
                self._children[key] = c
            return c

    def _new_child(self):
        raise NotImplementedError

    def items(self) -> Iterable[Tuple[_LabelKey, Any]]:
        with self._lock:
            return sorted(self._children.items())


class _Value:
    __slots__ = ("value",)

    def __init__(self):
        self.value = 0.0


class CounterFamily(_Family):
    """Monotone counts. ``inc`` bumps; ``set_total`` is the adapter
    entry point for mirroring an externally-owned monotone total (the
    process counters) without double counting."""

    kind = "counter"

    def _new_child(self) -> _Value:
        return _Value()

    def inc(self, n: float = 1, **labels: str) -> None:
        self.child(**labels).value += n

    def set_total(self, total: float, **labels: str) -> None:
        self.child(**labels).value = float(total)


class GaugeFamily(_Family):
    """Point-in-time values (queue depth, level, pad fraction)."""

    kind = "gauge"

    def _new_child(self) -> _Value:
        return _Value()

    def set(self, v: float, **labels: str) -> None:
        self.child(**labels).value = float(v)


class SketchFamily(_Family):
    """Labeled :class:`QuantileSketch` children; renders as a
    Prometheus ``summary`` (quantile series + ``_sum`` + ``_count``)."""

    kind = "summary"

    #: quantiles each sketch exposes on the scrape surface
    QUANTILES = (0.5, 0.9, 0.95, 0.99)

    def __init__(self, name: str, help_text: str,
                 relative_accuracy: float = DEFAULT_RELATIVE_ACCURACY):
        super().__init__(name, help_text)
        self.relative_accuracy = float(relative_accuracy)

    def _new_child(self) -> QuantileSketch:
        return QuantileSketch(relative_accuracy=self.relative_accuracy)

    def observe(self, v: float, **labels: str) -> None:
        self.child(**labels).observe(v)


class MetricsRegistry:
    """One namespace of labeled metric families + the render/export
    surface. Thread-safe for the scrape path (the HTTP exporter renders
    from its own thread while the runtime observes).

    A registry can also FEDERATE foreign registries: a source registered
    with :meth:`add_federated` returns another process's
    :meth:`to_dict` document (or ``None`` while there is nothing to
    report), and every render/snapshot folds those documents in through
    :func:`merge_registry_docs` — sketch series merge by bucket
    addition, counters sum, gauges last-write-win. This is how the
    supervisor's single ``/metrics`` endpoint serves the out-of-process
    serving worker's families (including across worker incarnations:
    the dead worker's final document keeps merging under the reborn
    worker's live one)."""

    def __init__(self):
        self._families: Dict[str, _Family] = {}
        self._collectors: List[Callable[[], None]] = []
        self._federated: List[Callable[[], Optional[Dict[str, Any]]]] = []
        self._lock = threading.Lock()

    def _family(self, name: str, factory: Callable[[], _Family]) -> _Family:
        with self._lock:
            fam = self._families.get(name)
            if fam is None:
                fam = factory()
                self._families[name] = fam
            return fam

    def counter(self, name: str, help_text: str = "") -> CounterFamily:
        fam = self._family(name, lambda: CounterFamily(name, help_text))
        if not isinstance(fam, CounterFamily):
            raise TypeError(f"{name} is registered as a {fam.kind}")
        return fam

    def gauge(self, name: str, help_text: str = "") -> GaugeFamily:
        fam = self._family(name, lambda: GaugeFamily(name, help_text))
        if not isinstance(fam, GaugeFamily):
            raise TypeError(f"{name} is registered as a {fam.kind}")
        return fam

    def sketch(self, name: str, help_text: str = "",
               relative_accuracy: float = DEFAULT_RELATIVE_ACCURACY
               ) -> SketchFamily:
        fam = self._family(
            name, lambda: SketchFamily(name, help_text, relative_accuracy))
        if not isinstance(fam, SketchFamily):
            raise TypeError(f"{name} is registered as a {fam.kind}")
        return fam

    def register_collector(self, fn: Callable[[], None]) -> None:
        """Register a callback run at the START of every render — the
        pull-model adapter hook: a runtime syncs its counts/gauges into
        the registry exactly when someone scrapes."""
        with self._lock:
            self._collectors.append(fn)

    def add_federated(self,
                      source: Callable[[], Optional[Dict[str, Any]]]
                      ) -> None:
        """Register a federation source: a callable returning a foreign
        registry's :meth:`to_dict` document (or ``None`` when nothing is
        available yet). Its families join every render/snapshot of THIS
        registry via :func:`merge_registry_docs`."""
        with self._lock:
            self._federated.append(source)

    def _federated_docs(self) -> List[Dict[str, Any]]:
        docs: List[Dict[str, Any]] = []
        for fn in list(self._federated):
            try:
                doc = fn()
            except Exception:  # noqa: BLE001 - a broken federation
                # source must not take the scrape surface down
                logger.exception("mplane: federation source failed; "
                                 "skipping")
                continue
            if doc:
                docs.append(doc)
        return docs

    def render(self) -> str:
        """The Prometheus text exposition of every family (own families
        first, then federated documents — a federated family whose name
        collides with an own one emits series lines only, so HELP/TYPE
        stay unique)."""
        for fn in list(self._collectors):
            try:
                fn()
            except Exception:  # noqa: BLE001 - a broken adapter must not
                # take the scrape surface (and every OTHER signal) down
                logger.exception("mplane: collector failed; skipping")
        lines: List[str] = []
        with self._lock:
            fams = sorted(self._families.items())
        for name, fam in fams:
            if fam.help:
                lines.append(f"# HELP {name} {fam.help}")
            lines.append(f"# TYPE {name} {fam.kind}")
            if isinstance(fam, SketchFamily):
                for key, sk in fam.items():
                    for q in fam.QUANTILES:
                        v = sk.quantile(q)
                        if v is None:
                            continue
                        lines.append(
                            f"{name}"
                            f"{_render_labels(key, (('quantile', str(q)),))}"
                            f" {_fmt(v)}")
                    lines.append(
                        f"{name}_sum{_render_labels(key)} {_fmt(sk.sum)}")
                    lines.append(
                        f"{name}_count{_render_labels(key)} {sk.count}")
            else:
                for key, child in fam.items():
                    lines.append(
                        f"{name}{_render_labels(key)} {_fmt(child.value)}")
        fed = self._federated_docs()
        if fed:
            own = {name for name, _ in fams}
            lines.append(render_doc(merge_registry_docs(fed),
                                    skip_meta_for=own).rstrip("\n"))
        return "\n".join(lines) + "\n"

    def export_file(self, path: str) -> str:
        """Atomic file export of :meth:`render` (tmp + fsync + rename)
        for air-gapped runs with no scrape port; returns ``path``."""
        _atomic_write(path, self.render())
        return path

    def to_dict(self) -> Dict[str, Any]:
        """JSON-portable snapshot (sketches in mergeable form) —
        cross-process aggregation reads this, merges sketches with
        :meth:`QuantileSketch.merge`, and re-renders."""
        for fn in list(self._collectors):
            try:
                fn()
            except Exception:  # noqa: BLE001 - same policy as render()
                logger.exception("mplane: collector failed; skipping")
        out: Dict[str, Any] = {}
        with self._lock:
            fams = sorted(self._families.items())
        for name, fam in fams:
            entries = []
            for key, child in fam.items():
                val = (child.to_dict() if isinstance(child, QuantileSketch)
                       else child.value)
                entries.append({"labels": dict(key), "value": val})
            out[name] = {"kind": fam.kind, "help": fam.help,
                         "series": entries}
        fed = self._federated_docs()
        if fed:
            out = merge_registry_docs([out] + fed)
        return out


# ------------------------------------------------ cross-process federation


def merge_registry_docs(docs: Iterable[Dict[str, Any]]) -> Dict[str, Any]:
    """Merge :meth:`MetricsRegistry.to_dict` documents into one: series
    are keyed by (family, label set); summary values merge as sketches
    (bucket addition — the PR 17 mergeability, now exercised
    cross-process), counters SUM (each document is an independent
    process's monotone total), gauges take the last document's value
    (documents are ordered oldest-first by convention, so 'last' is the
    live process). Input documents are never mutated."""
    out: Dict[str, Any] = {}
    for doc in docs:
        for name, fam in doc.items():
            series = fam.get("series", [])
            cur = out.get(name)
            if cur is None:
                out[name] = {"kind": fam.get("kind", "untyped"),
                             "help": fam.get("help", ""),
                             "series": [{"labels": dict(s["labels"]),
                                         "value": s["value"]}
                                        for s in series]}
                continue
            index = {_label_key(s["labels"]): s for s in cur["series"]}
            kind = cur["kind"]
            for s in series:
                key = _label_key(s["labels"])
                have = index.get(key)
                if have is None:
                    have = {"labels": dict(s["labels"]), "value": s["value"]}
                    cur["series"].append(have)
                    index[key] = have
                elif kind == "summary":
                    merged = QuantileSketch.from_dict(have["value"])
                    merged.merge(QuantileSketch.from_dict(s["value"]))
                    have["value"] = merged.to_dict()
                elif kind == "counter":
                    have["value"] = float(have["value"]) + float(s["value"])
                else:
                    have["value"] = s["value"]
    return out


def render_doc(doc: Dict[str, Any],
               skip_meta_for: Optional[set] = None) -> str:
    """Prometheus text exposition of a :meth:`MetricsRegistry.to_dict`
    document (the render half of federation: merge documents first,
    then render once). Families named in ``skip_meta_for`` emit series
    lines only — the caller already emitted their HELP/TYPE."""
    skip_meta = skip_meta_for or set()
    lines: List[str] = []
    for name in sorted(doc):
        fam = doc[name]
        kind = fam.get("kind", "untyped")
        if name not in skip_meta:
            if fam.get("help"):
                lines.append(f"# HELP {name} {fam['help']}")
            lines.append(f"# TYPE {name} {kind}")
        for s in fam.get("series", []):
            key = _label_key(s["labels"])
            if kind == "summary":
                sk = QuantileSketch.from_dict(s["value"])
                for q in SketchFamily.QUANTILES:
                    v = sk.quantile(q)
                    if v is None:
                        continue
                    lines.append(
                        f"{name}"
                        f"{_render_labels(key, (('quantile', str(q)),))}"
                        f" {_fmt(v)}")
                lines.append(
                    f"{name}_sum{_render_labels(key)} {_fmt(sk.sum)}")
                lines.append(
                    f"{name}_count{_render_labels(key)} {sk.count}")
            else:
                lines.append(
                    f"{name}{_render_labels(key)} {_fmt(s['value'])}")
    return "\n".join(lines) + "\n"


_default_registry: Optional[MetricsRegistry] = None
_default_lock = threading.Lock()


def default_registry() -> MetricsRegistry:
    """The process-wide registry (created on first use). Runtimes that
    want isolation (tests, multiple servers) own their own
    :class:`MetricsRegistry` and pass it to the exporter explicitly."""
    global _default_registry
    with _default_lock:
        if _default_registry is None:
            _default_registry = MetricsRegistry()
        return _default_registry


def sync_counters(registry: MetricsRegistry,
                  counts: Dict[str, Any],
                  name: str = "detpu_events_total",
                  label: str = "event") -> None:
    """Adapter: mirror a monotone ``{name: count}`` dict (the
    :func:`~.obs.counters` snapshot, a serving runtime's ``_counts``)
    into one labeled counter family."""
    fam = registry.counter(
        name, "process event totals (mirrored monotone counts)")
    for k, v in counts.items():
        try:
            fam.set_total(float(v), **{label: str(k)})
        except (TypeError, ValueError):
            continue


def sync_step_metrics(registry: MetricsRegistry,
                      summary: Dict[str, Any],
                      prefix: str = "detpu_step_") -> None:
    """Adapter: mirror one :func:`~.obs.summarize`'d step-metrics dict
    into gauges (last-step view — trend history belongs to the JSONL
    sidecar, the scrape plane carries the NOW)."""
    for k, v in summary.items():
        try:
            registry.gauge(prefix + k, f"step metric {k} (last logged "
                           "step)").set(float(v))
        except (TypeError, ValueError):
            continue


# ----------------------------------------------------- the scrape server


class _Exporter:
    """Handle on a running scrape endpoint (daemon thread)."""

    def __init__(self, server, thread):
        self._server = server
        self._thread = thread
        self.port = int(server.server_address[1])

    def url(self) -> str:
        return f"http://127.0.0.1:{self.port}/metrics"

    def stop(self) -> None:
        self._server.shutdown()
        self._server.server_close()
        self._thread.join(timeout=5)


def start_http_exporter(registry: Optional[MetricsRegistry] = None,
                        port: Optional[int] = None
                        ) -> Optional[_Exporter]:
    """Serve ``GET /metrics`` (Prometheus text) from a stdlib HTTP
    server on a daemon thread.

    ``port=None`` reads ``DETPU_METRICS_PORT``; unset/empty means the
    endpoint is OFF and the call is a no-op returning ``None`` (the
    default: serving a port is opt-in). ``port=0`` binds an ephemeral
    port (tests / one-shot drills read it back from the returned
    handle's ``.port``)."""
    if port is None:
        raw = envvars.get(METRICS_PORT_ENV)
        if raw in (None, ""):
            return None
        try:
            port = int(raw)
        except ValueError:
            logger.warning("mplane: DETPU_METRICS_PORT=%r is not a port; "
                           "scrape endpoint disabled", raw)
            return None
    reg = registry if registry is not None else default_registry()

    from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

    class Handler(BaseHTTPRequestHandler):
        def do_GET(self):  # noqa: N802 - http.server API
            if self.path.split("?", 1)[0] not in ("/metrics", "/"):
                self.send_error(404)
                return
            body = reg.render().encode("utf-8")
            self.send_response(200)
            self.send_header("Content-Type",
                             "text/plain; version=0.0.4; charset=utf-8")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def log_message(self, fmt, *args):  # noqa: A003 - silence stderr
            del fmt, args

    server = ThreadingHTTPServer(("127.0.0.1", int(port)), Handler)
    server.daemon_threads = True
    thread = threading.Thread(target=server.serve_forever,
                              name="detpu-metrics-exporter", daemon=True)
    thread.start()
    exp = _Exporter(server, thread)
    logger.info("mplane: metrics scrape endpoint on %s", exp.url())
    return exp


# -------------------------------------------------- the flight recorder


def _atomic_write(path: str, text: str) -> None:
    """Atomic text write (tmp + flush + fsync + rename) — the same
    durability idiom as ``parallel/resilient.py``'s ``_atomic_json``
    (duplicated here because utils must never import parallel)."""
    tmp = path + ".tmp"
    with open(tmp, "w", encoding="utf-8") as f:
        f.write(text)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)


class FlightRecorder:
    """Bounded ring of recent step metrics, events, and stats
    snapshots; :meth:`dump` writes the whole ring atomically as the
    post-mortem black box.

    The ring holds the last ``capacity`` records PER KIND
    (``DETPU_BLACKBOX_RING``, default 64) — appending is a deque push,
    never I/O. :meth:`dump` serializes everything plus the triggering
    event and a CRC32 of the canonical payload into
    ``<checkpoint_dir>.blackbox.json`` via tmp+fsync+rename, so a crash
    mid-dump leaves either the previous black box or the new one,
    never a torn file. ``verify_blackbox`` checks the CRC back.
    """

    def __init__(self, path: str, capacity: Optional[int] = None):
        self.path = path
        self.capacity = (envvars.get_int(BLACKBOX_RING_ENV)
                         if capacity is None else int(capacity))
        self.capacity = max(1, self.capacity)
        self._steps: List[Dict[str, Any]] = []
        self._events: List[Dict[str, Any]] = []
        self._stats: List[Dict[str, Any]] = []
        self._traces: List[Dict[str, Any]] = []
        self._lock = threading.Lock()
        self.dumps = 0

    def _push(self, ring: List[Dict[str, Any]], rec: Dict[str, Any]) -> None:
        with self._lock:
            ring.append(rec)
            if len(ring) > self.capacity:
                del ring[:len(ring) - self.capacity]

    def note_step(self, step: int, metrics: Dict[str, Any]) -> None:
        """Ring in one (host-scalar) step-metrics summary."""
        self._push(self._steps, {"step": int(step), "time": time.time(),
                                 "metrics": _jsonable(metrics)})

    def note_event(self, kind: str, **payload: Any) -> None:
        """Ring in one structured event (the :func:`~.obs.record_event`
        tap feeds every process event here automatically)."""
        self._push(self._events, {"event": kind, "time": time.time(),
                                  **_jsonable(payload)})

    def note_stats(self, stats: Dict[str, Any],
                   source: str = "serving") -> None:
        """Ring in one runtime ``stats()`` snapshot."""
        self._push(self._stats, {"source": source, "time": time.time(),
                                 "stats": _jsonable(stats)})

    def note_trace(self, trace: Dict[str, Any]) -> None:
        """Ring in one retained request trace (a
        :meth:`~.reqtrace.TraceBuffer.drain_new` record): a
        ``serve_worker_crash`` / ``nan_escalation`` black box ships the
        tail-sampled exemplar requests that preceded it, CRC-covered
        like every other ring."""
        self._push(self._traces, _jsonable(trace))

    def snapshot(self) -> Dict[str, Any]:
        with self._lock:
            return {"steps": list(self._steps),
                    "events": list(self._events),
                    "stats": list(self._stats),
                    "traces": list(self._traces)}

    def dump(self, trigger: str, **context: Any) -> Optional[str]:
        """Write the black box. Returns the path, or ``None`` when the
        write failed — a post-mortem must never raise over the original
        failure it is documenting."""
        payload = dict(self.snapshot(), trigger=str(trigger),
                       context=_jsonable(context), time=time.time(),
                       capacity=self.capacity)
        try:
            from . import obs
            payload["counters"] = obs.counters()
        except Exception:  # noqa: BLE001 - counters are best-effort here
            payload["counters"] = {}
        body = json.dumps(payload, sort_keys=True)
        doc = {"crc32": zlib.crc32(body.encode("utf-8")) & 0xFFFFFFFF,
               "payload": payload}
        try:
            _atomic_write(self.path, json.dumps(doc, sort_keys=True))
        except OSError:
            logger.exception("mplane: flight-recorder dump to %s failed",
                             self.path)
            return None
        self.dumps += 1
        logger.warning("mplane: flight recorder dumped black box to %s "
                       "(trigger=%s)", self.path, trigger)
        return self.path


def verify_blackbox(path: str) -> Dict[str, Any]:
    """Load a black box and verify its CRC32 stamp; raises ``ValueError``
    on mismatch (a torn/corrupted post-mortem must not be trusted
    silently). Returns the payload."""
    with open(path, encoding="utf-8") as f:
        doc = json.load(f)
    body = json.dumps(doc["payload"], sort_keys=True)
    crc = zlib.crc32(body.encode("utf-8")) & 0xFFFFFFFF
    if crc != int(doc["crc32"]):
        raise ValueError(f"black box {path} CRC mismatch "
                         f"(recorded {doc['crc32']}, computed {crc})")
    return doc["payload"]


def _jsonable(obj: Any) -> Any:
    """Best-effort JSON coercion: numpy/device scalars via item/tolist,
    unknown objects via repr — a black box must accept whatever payload
    the dying run hands it."""
    if obj is None or isinstance(obj, (bool, int, float, str)):
        return obj
    if isinstance(obj, dict):
        return {str(k): _jsonable(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return [_jsonable(v) for v in obj]
    if hasattr(obj, "tolist"):
        try:
            return _jsonable(obj.tolist())
        except Exception:  # noqa: BLE001 - fall through to repr
            pass
    if hasattr(obj, "item"):
        try:
            return obj.item()
        except Exception:  # noqa: BLE001 - fall through to repr
            pass
    return repr(obj)


_flight_recorder: Optional[FlightRecorder] = None


def flight_recorder() -> Optional[FlightRecorder]:
    """The installed process flight recorder (``None`` until a runtime
    installs one — serving's freshness breach and the resilient
    driver's escalations all dump through this handle)."""
    return _flight_recorder


def install_flight_recorder(path: str,
                            capacity: Optional[int] = None
                            ) -> Optional[FlightRecorder]:
    """Create + install the process flight recorder (idempotent per
    path: re-installing the same path returns the existing recorder so
    its ring survives; a new path replaces it). Registers the
    :func:`~.obs.record_event` tap so every structured event rings in
    automatically. ``DETPU_BLACKBOX=0`` disables installation."""
    global _flight_recorder
    if not envvars.enabled(BLACKBOX_ENV):
        return None
    with _default_lock:
        if _flight_recorder is not None and _flight_recorder.path == path:
            return _flight_recorder
        rec = FlightRecorder(path, capacity=capacity)
        _flight_recorder = rec
    from . import obs
    obs.add_event_tap(_tap_event)
    return rec


def uninstall_flight_recorder() -> None:
    """Drop the installed recorder (test isolation)."""
    global _flight_recorder
    with _default_lock:
        _flight_recorder = None


def _tap_event(kind: str, payload: Dict[str, Any]) -> None:
    rec = _flight_recorder
    if rec is not None:
        rec.note_event(kind, **payload)

"""Step-level observability: named-scope tracing, on-device step metrics,
process counters, and a crash-surviving metrics sidecar.

PR 1's runtime layer (:mod:`.runtime`) made failures *survivable* — a
stalled tunnel or a killed process leaves parseable records. This module
makes runs *explainable*: when throughput drops, or ragged ids silently
overflow their static capacity, there is something to look at. Every later
perf PR is measured against the instrumentation here.

Three layers, all off by default and <1% overhead when disabled:

* **Named-scope tracing** — :func:`scope` wraps the hybrid step's phases
  (id all-to-all, per-width lookups, ragged decode, output exchange,
  sparse apply) in ``jax.named_scope`` so a captured XLA profile
  attributes device time to phases instead of one opaque jit blob.
  Scopes are trace-time-only metadata: they cost nothing at run time and
  are therefore always on. :func:`profile_trace` (gated by
  ``DETPU_PROFILE_DIR``) and :func:`maybe_start_server` (gated by
  ``DETPU_PROFILE_PORT``) capture the profiles the scopes annotate.
* **On-device step metrics** — a plain-dict pytree (keys
  :data:`STEP_METRIC_KEYS`) computed *inside* the jitted step by
  ``DistributedEmbedding.step_metrics`` + ``trainer.make_hybrid_train_step
  (with_metrics=True)``: ids routed per rank, exchange bytes per
  direction, ragged capacity-overflow counts, output-exchange padding
  fraction, dense/embedding grad norms. A handful of sums over tensors the
  step already holds — near-zero cost, and only built when
  ``DETPU_OBS=1`` (or ``with_metrics=True`` is passed explicitly).
* **Host-side collection** — :class:`MetricsLogger` drains step-metric
  pytrees into an fsynced JSONL sidecar (same crash-surviving mechanics as
  :class:`.runtime.SectionRecorder`, which it rides), and module-level
  :func:`counter_inc`/:func:`counters` track process events: recompiles
  (:func:`install_compile_listener`, a ``jax.monitoring`` backend-compile
  listener), runtime retries, fault injections, bootstrap retries.

Like :mod:`.runtime`, this module never imports jax at module scope:
importing it must never risk touching an accelerator backend, and the
counter/logger half works in processes that never load jax at all.
"""

from __future__ import annotations

import contextlib
import dataclasses
import json
import logging
import os
import re
import threading
import time
from typing import Any, Dict, Iterator, List, Optional

from . import envvars
from . import runtime as _runtime

logger = logging.getLogger(__name__)

# names stay importable as module constants; the knobs themselves are
# declared (default + meaning) in utils/envvars.py, the single registry
# the env-registry lint rule enforces
OBS_ENV = "DETPU_OBS"
PROFILE_DIR_ENV = "DETPU_PROFILE_DIR"
PROFILE_PORT_ENV = "DETPU_PROFILE_PORT"
NANGUARD_ENV = "DETPU_NANGUARD"
NANGUARD_K_ENV = "DETPU_NANGUARD_K"

#: Keys of the on-device step-metrics dict (a plain dict so it is a pytree
#: without any registration, and JSON-serializable after a host fetch).
#: Every value is a per-device ``[1]``-shaped array — except the three
#: per-table health sentinels (``table_*``), which are ``[1, n_tables]``.
#: Under ``shard_map`` with ``out_specs=P(axis)`` the per-device rows
#: concatenate into a ``[world]`` per-rank vector (rank ``r``'s entry
#: describes rank ``r``); the sentinels become ``[world, n_tables]``.
STEP_METRIC_KEYS = (
    "ids_routed",        # live (non-padding) ids this rank received
    "id_overflow",       # ragged ids lost to static-capacity truncation
    "invalid_id_count",  # negative / out-of-vocab ids among the live ids
    "id_a2a_bytes",      # id-exchange bytes leaving this chip (dp->mp)
    "out_a2a_bytes",     # activation-exchange bytes leaving (mp->dp fwd)
    "grad_a2a_bytes",    # cotangent-exchange bytes leaving (dp->mp bwd)
    "out_pad_frac",      # dead-column fraction of this rank's output rows
    "loss",              # per-device loss (post-pmean: identical rows)
    "emb_grad_norm",     # L2 norm of this device's embedding cotangents
    "dense_grad_norm",   # L2 norm of the (averaged) dense gradient
    "skipped_steps",     # 1 when the non-finite guard skipped this step
    "step",              # step counter at the START of the step
    # -- per-table numerical health sentinels ([1, n_tables] per device):
    # computed from this device's per-table embedding cotangents inside
    # the jitted step, so a recovery log can name WHICH table went
    # unhealthy, not just the step (see TableHealthContract)
    "table_grad_norm",      # per-table L2 norm of the sparse cotangents
    "table_update_maxabs",  # per-table max |row update| (lr/world scaled)
    "table_nonfinite",      # per-table count of non-finite cotangents
)

#: The per-table health-sentinel subset of :data:`STEP_METRIC_KEYS`.
TABLE_HEALTH_KEYS = ("table_grad_norm", "table_update_maxabs",
                     "table_nonfinite")

#: Extra step-metric keys of streaming-vocab (dynamic-table) steps —
#: present only when the step was built with ``dynamic=`` on
#: (``parallel/streaming.py``). Per-device ``[1]`` counts of THIS step's
#: slot-map transitions, gated by the non-finite guard like the updates
#: they describe (a skipped step reports zeros).
STREAMING_METRIC_KEYS = (
    "stream_admitted",    # external ids admitted to a real slot
    "stream_evicted",     # slot occupants evicted back to their bucket
    "stream_bucket_ids",  # live ids served from a shared hash bucket
    "stream_hit_ids",     # live ids served from their admitted slot
)


def metrics_enabled() -> bool:
    """Whether ``DETPU_OBS`` asks for step metrics (read per call so tests
    can flip it at runtime; an env read is nanoseconds against a train
    step)."""
    return envvars.enabled(OBS_ENV)


def nanguard_enabled() -> bool:
    """Whether the on-device non-finite guard is on. Default ON
    (``DETPU_NANGUARD`` unset or truthy): a NaN/Inf batch must never
    corrupt the sharded tables silently. Set ``DETPU_NANGUARD=0`` to build
    the unguarded step. Read at step-build time (trace-time static), like
    ``with_metrics``."""
    return envvars.enabled(NANGUARD_ENV)


def nanguard_escalation_k(default: int = 3) -> int:
    """Consecutive guard-skipped steps before the host driver escalates
    with :class:`~.runtime.NonFiniteLossError` (``DETPU_NANGUARD_K``)."""
    return envvars.get_int(NANGUARD_K_ENV, default)


# ------------------------------------------------------------- named scopes

#: Prefix every :func:`scope` stamps on its ``jax.named_scope`` — the one
#: identifier that threads a phase through the jaxpr auditor, the HLO
#: census, the schedule-graph auditor, and the measured trace parser.
SCOPE_PREFIX = "detpu"

#: The phase-name extractor every consumer of ``metadata.op_name`` shares
#: (``analysis/hlo_census.py`` compiled-HLO attribution, the schedule
#: auditor's DAG nodes, ``utils/traceparse.py``'s profiler events): each
#: match is one ``detpu/<component>`` scope level. Lives HERE — next to
#: :func:`scope`, which mints the names, and derived from the same
#: :data:`SCOPE_PREFIX` — so the writer and every reader agree by
#: construction.
SCOPE_RE = re.compile(re.escape(SCOPE_PREFIX) + r"/([\w.\-]+)")


def phase_path(op_name: Optional[str]) -> str:
    """Full ``detpu`` scope path embedded in an XLA ``op_name`` (or a
    profiler event's metadata), e.g.
    ``"jit(step)/.../detpu/embedding_forward/detpu/id_all_to_all/..."``
    -> ``"embedding_forward/id_all_to_all"``. Empty string when the name
    carries no detpu scope."""
    return "/".join(SCOPE_RE.findall(op_name or ""))


def phase_leaf(path: str) -> str:
    """Last component of a phase path (census convention: contracts match
    the full path OR the leaf)."""
    return path.rsplit("/", 1)[-1] if path else ""


#: Event-name namespace for per-REQUEST trace events (utils/reqtrace.py
#: emits them, utils/traceparse.py reads them back). Lives here, next to
#: :data:`SCOPE_RE`, because obs.py owns the naming conventions that keep
#: a mixed capture directory separable: ``detpu/...`` scopes mark device
#: op events, ``req/...`` names mark request spans — phase tooling skips
#: the latter, request-trace tooling keys on them.
REQ_EVENT_PREFIX = "req/"


def is_request_event(name: Optional[str]) -> bool:
    """Whether a trace-event name belongs to the request-tracing
    namespace (vs a device/profiler op event)."""
    return bool(name) and str(name).startswith(REQ_EVENT_PREFIX)


def scope(name: str):
    """``jax.named_scope("detpu/<name>")`` — phase attribution for XLA
    profiles. Trace-time-only metadata (zero run-time cost), so call sites
    use it unconditionally."""
    import jax

    return jax.named_scope(f"{SCOPE_PREFIX}/{name}")


@contextlib.contextmanager
def profile_trace(label: Optional[str] = None) -> Iterator[None]:
    """Capture an XLA profile of the enclosed block into
    ``$DETPU_PROFILE_DIR`` (a TensorBoard-loadable trace directory); a
    transparent no-op when the variable is unset.

    ``label`` names a subdirectory so successive captures (e.g. one per
    bench section) do not overwrite each other.
    """
    base = envvars.get(PROFILE_DIR_ENV)
    if not base:
        yield
        return
    import jax

    path = os.path.join(base, label) if label else base
    os.makedirs(path, exist_ok=True)
    with jax.profiler.trace(path):
        yield


_server_started = False
_server_lock = threading.Lock()


def maybe_start_server() -> bool:
    """Start ``jax.profiler.start_server($DETPU_PROFILE_PORT)`` once per
    process (for live TensorBoard capture); no-op without the variable.
    Returns whether a server is running after the call."""
    global _server_started
    port = envvars.get(PROFILE_PORT_ENV)
    if not port:
        return _server_started
    with _server_lock:
        if not _server_started:
            import jax

            jax.profiler.start_server(int(port))
            _server_started = True
            logger.info("obs: profiler server listening on port %s", port)
    return _server_started


# -------------------------------------------------------- process counters

_counters: Dict[str, int] = {}
_counters_lock = threading.Lock()


def counter_inc(name: str, n: int = 1) -> int:
    """Bump a process-level counter (``recompiles``, ``runtime_retries``,
    ``fault_injections``, ``bootstrap_retries``, ...); returns the new
    value. Thread-safe; always on (a dict bump is free)."""
    with _counters_lock:
        v = _counters.get(name, 0) + n
        _counters[name] = v
    return v


def counters() -> Dict[str, int]:
    """Snapshot of every process counter."""
    with _counters_lock:
        return dict(_counters)


def reset_counters() -> None:
    """Forget counter state (test isolation helper)."""
    with _counters_lock:
        _counters.clear()


# ---------------------------------------------------------- process events

# Structured one-shot events (e.g. a checkpoint re-shard on elastic
# resume): producers deep in library code record them here; the driver
# layer drains and routes them to its MetricsLogger / log output. Unlike
# counters these carry a payload; like counters they are process-global
# so a utils-level producer needs no logger plumbed through.
_events: List[Dict[str, Any]] = []

# observability-plane taps: callbacks that see every record_event() as it
# happens, WITHOUT consuming it (drain_events stays the at-most-once
# delivery path for drivers). The flight recorder (utils/mplane.py) rides
# here so its black-box ring holds recent events with nobody polling.
_event_taps: List[Any] = []


def add_event_tap(fn) -> None:
    """Register ``fn(kind, payload_dict)`` to observe every recorded
    event (idempotent per function object). Taps must not raise; a
    failing tap is dropped from the chain rather than poisoning every
    later producer."""
    with _counters_lock:
        if fn not in _event_taps:
            _event_taps.append(fn)


def record_event(kind: str, **payload: Any) -> Dict[str, Any]:
    """Record one structured event (also bumps the ``event_<kind>``
    counter); returns the stored record."""
    rec = {"event": kind, "time": time.time(), **payload}
    with _counters_lock:
        _events.append(rec)
        taps = list(_event_taps)
    counter_inc(f"event_{kind}")
    for fn in taps:
        try:
            fn(kind, dict(payload))
        except Exception:  # noqa: BLE001 - a broken tap must not poison
            # every event producer in the process
            logger.exception("obs: event tap failed; removing it")
            with _counters_lock:
                if fn in _event_taps:
                    _event_taps.remove(fn)
    return rec


def drain_events(kind: Optional[str] = None) -> List[Dict[str, Any]]:
    """Pop (and return) recorded events — all of them, or only ``kind``.
    Draining is the consumer's acknowledgment; events are delivered at
    most once."""
    with _counters_lock:
        if kind is None:
            out, _events[:] = list(_events), []
            return out
        out = [e for e in _events if e["event"] == kind]
        _events[:] = [e for e in _events if e["event"] != kind]
        return out


_compile_listener_installed = False
# guards the install check-then-act: two threads warming two serving
# runtimes (the online drill's trainer + server) could otherwise both
# pass the installed check and double-register the listener — every
# recompile would then count twice and the 0-steady-state-recompiles
# gates would flag phantom retraces
_compile_lock = threading.Lock()

# one backend compile per jitted-signature miss: cache hits do not fire it
_COMPILE_EVENT = "/jax/core/compile/backend_compile_duration"


def install_compile_listener() -> bool:
    """Count XLA recompiles into the ``recompiles`` counter.

    Registers a ``jax.monitoring`` duration listener for the
    backend-compile event, which fires exactly once per compiled
    executable (jit cache hits do not emit it) — the cache-miss signal
    that distinguishes "throughput fell because something retraces every
    step" from a genuine regression. Idempotent; returns False when the
    running jax has no monitoring hooks (the caller loses the counter,
    nothing else).
    """
    global _compile_listener_installed
    with _compile_lock:
        if _compile_listener_installed:
            return True
        try:
            import jax.monitoring
        except Exception:  # noqa: BLE001 - counter is best-effort
            return False
        if not hasattr(jax.monitoring,
                       "register_event_duration_secs_listener"):
            return False

        def _on_duration(event: str, duration: float,
                         **kwargs: Any) -> None:
            del duration, kwargs
            if event == _COMPILE_EVENT:
                counter_inc("recompiles")

        jax.monitoring.register_event_duration_secs_listener(_on_duration)
        _compile_listener_installed = True
        return True


# --------------------------------------------------------- host collection


class MetricsLogger:
    """Fsynced JSONL sidecar of step metrics and counters.

    Rides :class:`.runtime.SectionRecorder` (append one JSON line, flush,
    fsync), so a process killed at any point leaves every previously
    logged record parseable — the property that made ``BENCH.partial.jsonl``
    survive rc=124. Records:

    * ``{"section": "step_metrics", "step": N, "metrics": {...}, ...}``
      from :meth:`log_step` — device arrays are fetched and listified
      (``[world]``-shaped per-rank vectors stay vectors);
    * ``{"section": "counters", "counters": {...}}`` from
      :meth:`log_counters` — the process counters, recompiles included.

    ``max_bytes`` (default ``DETPU_OBS_MAX_BYTES``; 0 = unbounded)
    bounds the sidecar for long resilient runs: when the file would
    exceed the cap, it rotates through ``<path>.1`` .. ``<path>.N``
    (``max_files`` generations, default ``DETPU_OBS_MAX_FILES`` = 2 —
    the checkpoint-ring idiom: ``.1`` is the newest rotated generation,
    ``.N`` the oldest, and the one past ``.N`` is dropped) and logging
    continues into a fresh file. Total disk is therefore bounded by
    ``(max_files + 1) * max_bytes`` however long the run lives.
    Rotation happens between records, so every generation stays
    line-parseable.
    """

    def __init__(self, path: str, max_bytes: Optional[int] = None,
                 max_files: Optional[int] = None):
        self.path = path
        self.max_bytes = (envvars.get_int("DETPU_OBS_MAX_BYTES")
                          if max_bytes is None else int(max_bytes))
        self.max_files = max(1, envvars.get_int("DETPU_OBS_MAX_FILES")
                             if max_files is None else int(max_files))
        self._rec = _runtime.SectionRecorder(path)

    def _maybe_rotate(self) -> None:
        if self.max_bytes <= 0:
            return
        try:
            size = os.path.getsize(self.path)
        except OSError:
            return
        if size < self.max_bytes:
            return
        # shift the ring up one generation, oldest out first (same
        # newest-first numbering as the checkpoint ring): .N drops,
        # .i -> .(i+1), live -> .1
        oldest = f"{self.path}.{self.max_files}"
        if os.path.exists(oldest):
            os.remove(oldest)
        for i in range(self.max_files - 1, 0, -1):
            src = f"{self.path}.{i}"
            if os.path.exists(src):
                os.replace(src, f"{self.path}.{i + 1}")
        os.replace(self.path, self.path + ".1")
        logger.info("obs: rotated metrics sidecar %s (> %d bytes; %d "
                    "generation(s) kept)", self.path, self.max_bytes,
                    self.max_files)

    def log_step(self, metrics: Dict[str, Any], step: Optional[int] = None,
                 **extra: Any) -> Dict[str, Any]:
        """Append one step-metrics record. ``metrics`` is the dict the
        instrumented train step returned (device arrays or numpy); fetching
        the values here is the ONE host readback the caller opted into by
        logging."""
        host = {}
        for k, v in metrics.items():
            host[k] = v.tolist() if hasattr(v, "tolist") else v
        rec = dict(extra)
        if step is not None:
            rec["step"] = int(step)
        self._maybe_rotate()
        return self._rec.record("step_metrics", metrics=host, **rec)

    def log_counters(self, **extra: Any) -> Dict[str, Any]:
        """Append the current process-counter snapshot."""
        self._maybe_rotate()
        return self._rec.record("counters", counters=counters(), **extra)

    def log_event(self, event: str, **fields: Any) -> Dict[str, Any]:
        """Append one structured one-shot record (e.g. a
        ``checkpoint_reshard`` degradation on elastic resume) under its
        own section name."""
        self._maybe_rotate()
        return self._rec.record(event, **fields)

    @staticmethod
    def load(path: str):
        """Parse a metrics sidecar (torn trailing line tolerated)."""
        return _runtime.SectionRecorder.load(path)


def fetch_metrics(metrics: Dict[str, Any]) -> Dict[str, Any]:
    """Host numpy copy of a step-metrics dict, multi-host safe.

    Under ``shard_map`` with ``out_specs=P(axis)`` on a pod, each
    ``[world]`` metrics vector spans devices of EVERY process — a bare
    ``tolist()`` on one process raises (non-addressable shards). This
    gathers such arrays with ``process_allgather``, which is a
    COLLECTIVE: on a multi-process job every process must call
    :func:`fetch_metrics` (even the ones that then drop the result), and
    only the chief hands it to :class:`MetricsLogger`. Single-process:
    a plain device fetch.
    """
    import numpy as np

    out: Dict[str, Any] = {}
    for k, v in metrics.items():
        if getattr(v, "is_fully_addressable", True):
            out[k] = np.asarray(v) if hasattr(v, "tolist") else v
        else:
            from jax.experimental import multihost_utils

            out[k] = np.asarray(
                multihost_utils.process_allgather(v, tiled=True))
    return out


def summarize(metrics: Dict[str, Any]) -> Dict[str, Any]:
    """Host-side scalar summary of one step-metrics dict: per-rank vectors
    reduce to totals (sums for counts/bytes, max for overflow — the rank
    that truncated is the one to look at), norms/fractions to their max.
    Per-rank vectors with more than one entry additionally report their
    p50/p95 (``<key>_p50`` / ``<key>_p95``) — the distribution view the
    imbalance analyses in ``tools/obs_report.py`` read."""
    import numpy as np

    out: Dict[str, Any] = {}
    for k in STEP_METRIC_KEYS + STREAMING_METRIC_KEYS:
        if k not in metrics:
            continue
        v = np.asarray(metrics[k]).reshape(-1)
        if v.size == 0:
            continue
        if k in ("ids_routed", "invalid_id_count", "id_a2a_bytes",
                 "out_a2a_bytes", "grad_a2a_bytes"
                 ) or k in STREAMING_METRIC_KEYS:
            out[k] = float(v.sum())
        elif k in ("id_overflow", "out_pad_frac", "emb_grad_norm",
                   "skipped_steps") or k in TABLE_HEALTH_KEYS:
            # table sentinels reduce to their worst (max) entry here;
            # the per-table view stays available via
            # TableHealthContract.violations_by_table / unhealthy_tables
            out[k] = float(v.max())
        else:
            out[k] = float(v[0])
        if v.size > 1:
            out[f"{k}_p50"] = float(np.percentile(v, 50))
            out[f"{k}_p95"] = float(np.percentile(v, 95))
    return out


# ------------------------------------------- per-table health contracts


@dataclasses.dataclass(frozen=True)
class TableHealthContract:
    """Declarative per-table numerical-health thresholds, audited against
    the ``table_*`` step-metric sentinels the trainer computes inside the
    jitted step — the recovery analogue of the plan-audit
    ``PlanContract``: the contract is data, :meth:`check` returns
    violations naming the offending table, and the resilient driver logs
    them in every skip/rollback event so a NaN storm at step 400k names
    *which table* went unhealthy, not just the step.

    ``max_nonfinite`` is the hard contract (default 0: any non-finite
    cotangent entry is unhealthy). The two magnitude thresholds default
    from ``DETPU_HEALTH_GRAD_NORM`` / ``DETPU_HEALTH_UPDATE_MAXABS`` and
    are disabled at ``<= 0`` — magnitude is workload-dependent, finiteness
    is not."""

    max_grad_norm: float = 0.0       # per-table L2; <= 0 disables
    max_update_maxabs: float = 0.0   # per-table max |update|; <= 0 disables
    max_nonfinite: int = 0           # per-table non-finite entry budget

    def violations_by_table(self, metrics: Dict[str, Any]
                            ) -> Dict[int, List[str]]:
        """Structured contract check of one step-metrics dict (device
        arrays or numpy; each sentinel ``[..., n_tables]``, reduced over
        ranks here): ``{table_id: [violation message, ...]}``. Empty
        dict = every table healthy. Metrics dicts without the sentinels
        (pre-sentinel steps) report nothing. This is the machine-read
        form (recovery events, :func:`unhealthy_tables`);
        :meth:`check` renders it for logs."""
        import numpy as np

        out: Dict[int, List[str]] = {}

        def per_table(key):
            v = metrics.get(key)
            if v is None:
                return None
            arr = np.asarray(v)
            if arr.ndim == 0 or arr.size == 0:
                return None
            return arr.reshape(-1, arr.shape[-1])

        nf = per_table("table_nonfinite")
        if nf is not None:
            for t, n in enumerate(nf.sum(axis=0)):
                if n > self.max_nonfinite:
                    out.setdefault(t, []).append(
                        f"{int(n)} non-finite sparse-gradient "
                        f"entr{'y' if int(n) == 1 else 'ies'} (budget "
                        f"{self.max_nonfinite})")
        for key, cap, what in (
                ("table_grad_norm", self.max_grad_norm, "grad L2 norm"),
                ("table_update_maxabs", self.max_update_maxabs,
                 "row-update max-abs")):
            if cap is None or cap <= 0:
                continue
            v = per_table(key)
            if v is None:
                continue
            for t, x in enumerate(v.max(axis=0)):
                if not np.isfinite(x) or x > cap:
                    out.setdefault(t, []).append(
                        f"{what} {float(x):g} exceeds the {cap:g} "
                        "contract")
        return out

    def check(self, metrics: Dict[str, Any]) -> List[str]:
        """Human-readable violations (``"table <t>: <message>"``), table
        order. Empty list = every table healthy."""
        by_table = self.violations_by_table(metrics)
        return [f"table {t}: {msg}"
                for t in sorted(by_table) for msg in by_table[t]]


def default_health_contract() -> TableHealthContract:
    """The env-configured contract (``DETPU_HEALTH_GRAD_NORM`` /
    ``DETPU_HEALTH_UPDATE_MAXABS``; non-finite budget always 0)."""
    return TableHealthContract(
        max_grad_norm=envvars.get_float("DETPU_HEALTH_GRAD_NORM"),
        max_update_maxabs=envvars.get_float("DETPU_HEALTH_UPDATE_MAXABS"))


def unhealthy_tables(metrics: Dict[str, Any],
                     contract: Optional[TableHealthContract] = None
                     ) -> List[int]:
    """Sorted table ids the contract names unhealthy — the compact form
    recovery events carry (structured, not parsed from log strings)."""
    contract = contract or default_health_contract()
    return sorted(contract.violations_by_table(metrics))


def record_fault(point: str) -> None:
    """Counter hook for :func:`.runtime.fault_point` — one bump per fired
    injection, keyed globally and per point."""
    counter_inc("fault_injections")
    counter_inc(f"fault_injections.{point}")


def record_retry(describe: str) -> None:
    """Counter hook for :func:`.runtime.retry` — one bump per retried
    attempt (the success that needed no retry bumps nothing)."""
    counter_inc("runtime_retries")
    counter_inc(f"runtime_retries.{describe.replace(' ', '_')}")


class StepTimer:
    """Tiny host-side wall-clock phase accumulator for loops that want
    coarse (non-XLA) timing next to the on-device metrics: ``with
    timer.section("eval"): ...``; :meth:`totals` returns seconds per
    label. Not a profiler — the XLA trace is — just enough to see where a
    *host* loop spends its time."""

    def __init__(self):
        self._totals: Dict[str, float] = {}

    @contextlib.contextmanager
    def section(self, label: str) -> Iterator[None]:
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self._totals[label] = (self._totals.get(label, 0.0)
                                   + time.perf_counter() - t0)

    def totals(self) -> Dict[str, float]:
        return dict(self._totals)


def env_stamp() -> Dict[str, Any]:
    """Process/environment identity for stamping benchmark records:
    backend platform + device count are NOT probed here (that is the
    caller's time-boxed :func:`.runtime.probe_backend` verdict, passed
    in); this returns what is knowable without touching a backend."""
    stamp: Dict[str, Any] = {
        "unix_time": time.time(),
        "obs_enabled": metrics_enabled(),
    }
    try:
        import jax

        stamp["jax_version"] = jax.__version__
    except Exception:  # noqa: BLE001 - stamp is best-effort
        stamp["jax_version"] = None
    return stamp


def _selftest_json_roundtrip(metrics: Dict[str, Any]) -> bool:
    """Whether a metrics dict survives a json round trip after host
    fetch — used by the verify gate to fail fast on an unserializable
    field sneaking into :data:`STEP_METRIC_KEYS` payloads."""
    try:
        json.dumps({k: (v.tolist() if hasattr(v, "tolist") else v)
                    for k, v in metrics.items()})
        return True
    except (TypeError, ValueError):
        return False

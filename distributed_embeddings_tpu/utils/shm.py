"""Double-buffered shared-memory snapshot transport with a seqlock.

The process-isolation layer (ISSUE 18) moves serving out of the trainer
process, so snapshot publication crosses a process boundary: the trainer
serializes each published ``(params, streaming_state, version,
train_step, wall_ts)`` and the serving worker picks it up with NO
syscall round-trip per read and NO lock shared with the trainer — a
crashed or wedged worker must never be able to block publication (the
revenue path must not gate the state path), and a mid-write reader must
never see a torn snapshot.

The classic answer is a seqlock over a double buffer, and that is
exactly what this module is — pure stdlib, no jax, importable from any
process:

* the region is ``HEADER + 2 x (BUFHDR + capacity)``;
* the single writer publishes sequence ``s`` into buffer ``s % 2`` —
  readers only ever look at buffer ``latest % 2``, so a reader can only
  race the writer if the writer LAPS it (publishes twice during one
  read);
* each buffer carries ``seq_begin`` / ``seq_end`` stamps (written
  before / after the payload) plus a CRC32 over the canonical payload
  bytes and metadata, so a lapped read is detected by stamp mismatch or
  checksum failure and retried;
* after :data:`READ_RETRIES_ENV` failed attempts :meth:`read_latest`
  returns ``None`` — the caller KEEPS its previous snapshot (bounded
  staleness beats a torn read, the same policy the in-process RCU path
  pins in ``parallel/online.py``).

CPython gives no memory fences, but the protocol does not need them:
the stamps narrow the race window and the CRC is the actual integrity
guarantee — any interleaving that slips past the stamps fails the
checksum and retries. ``tests/test_shm.py`` pins torn-read detection by
corrupting the region between stamp writes.

Ownership: the trainer :meth:`SnapshotShm.create`\\ s (and later
``unlink``\\ s) the region; workers :meth:`SnapshotShm.attach` by name.
Attach explicitly UNREGISTERS the segment from the attaching process's
``multiprocessing.resource_tracker``: on Python < 3.13 an attacher's
tracker believes it owns every segment it has seen and unlinks them all
when that process dies — which would let a SIGKILLed serving worker
destroy the very region the supervisor needs to restart it (the exact
drill ``make check-isolation`` runs).
"""

from __future__ import annotations

import dataclasses
import math
import struct
import zlib
from multiprocessing import resource_tracker, shared_memory
from typing import Optional

from . import envvars

READ_RETRIES_ENV = "DETPU_SHM_READ_RETRIES"
SLACK_ENV = "DETPU_SHM_SLACK"

# region magic: "DEsn" — refuse to read a region we did not lay out
MAGIC = 0x4445736E

# header: magic u32 | capacity u64 | latest published sequence u64
# (latest == 0 means nothing has ever been published)
_HEADER = struct.Struct("<IQQ")
# per-buffer header: seq_begin u64 | seq_end u64 | crc u32 | length u64
#                    | version u64 | train_step u64 | wall_ts f64
_BUFHDR = struct.Struct("<QQIQQQd")
# the metadata the CRC covers alongside the payload bytes
_META = struct.Struct("<QQQQd")

HEADER_SIZE = _HEADER.size
BUFHDR_SIZE = _BUFHDR.size


def region_bytes(capacity: int) -> int:
    """Total shared-memory footprint for a payload ``capacity`` — what
    ``plan_audit`` bills into the rank budget (two buffers: the one
    being served and the one being written)."""
    return HEADER_SIZE + 2 * (BUFHDR_SIZE + int(capacity))


def slack_capacity(payload_len: int) -> int:
    """Buffer capacity for an observed payload size, padded by
    :data:`SLACK_ENV` — streaming tables grow between publishes (new
    rows admitted), so the region is sized off the FIRST payload with
    headroom rather than resized (resizing would break every attached
    reader)."""
    slack = envvars.get_float(SLACK_ENV)
    if slack < 1.0:
        raise ValueError(f"{SLACK_ENV} must be >= 1.0, got {slack}")
    return int(math.ceil(int(payload_len) * slack))


@dataclasses.dataclass(frozen=True)
class ShmSnapshot:
    """One bitwise-consistent read: the serialized payload plus the
    metadata stamped with it (all covered by the CRC that admitted
    this read)."""

    payload: bytes
    seq: int
    version: int
    train_step: int
    wall_ts: float


def _crc(payload: bytes, seq: int, version: int, train_step: int,
         wall_ts: float) -> int:
    meta = _META.pack(seq, len(payload), version, train_step, wall_ts)
    return zlib.crc32(payload, zlib.crc32(meta)) & 0xFFFFFFFF


class SnapshotShm:
    """The transport: one writer (the trainer-side publisher), any
    number of readers (serving workers, including reborn ones)."""

    def __init__(self, shm: shared_memory.SharedMemory, capacity: int,
                 *, owner: bool):
        self._shm = shm
        self._capacity = int(capacity)
        self._owner = owner
        self._closed = False
        # the writer's in-memory cursor; re-derived from the header so a
        # writer re-attach (tests, crash-resume) keeps seqs monotone
        self._seq = self._latest()

    # ------------------------------------------------------ construction

    @classmethod
    def create(cls, capacity: int, name: Optional[str] = None
               ) -> "SnapshotShm":
        """Create (and own) a region able to carry payloads up to
        ``capacity`` bytes."""
        capacity = int(capacity)
        if capacity <= 0:
            raise ValueError(f"capacity must be positive, got {capacity}")
        shm = shared_memory.SharedMemory(
            create=True, size=region_bytes(capacity), name=name)
        _HEADER.pack_into(shm.buf, 0, MAGIC, capacity, 0)
        # zero both buffer headers so a reader racing creation sees
        # seq_begin == seq_end == 0 and reports "nothing published"
        for idx in (0, 1):
            off = HEADER_SIZE + idx * (BUFHDR_SIZE + capacity)
            _BUFHDR.pack_into(shm.buf, off, 0, 0, 0, 0, 0, 0, 0.0)
        return cls(shm, capacity, owner=True)

    @classmethod
    def attach(cls, name: str) -> "SnapshotShm":
        """Attach to an existing region by name (reader side)."""
        shm = shared_memory.SharedMemory(name=name)
        magic, capacity, _ = _HEADER.unpack_from(shm.buf, 0)
        if magic != MAGIC:
            shm.close()
            raise ValueError(
                f"shared memory region {name!r} is not a snapshot region "
                f"(magic 0x{magic:08X} != 0x{MAGIC:08X})")
        try:
            # see module docstring: the attacher must NOT let its
            # resource tracker unlink a region it does not own
            resource_tracker.unregister(shm._name, "shared_memory")  # noqa: SLF001
        except Exception:  # noqa: BLE001 - tracker layout is stdlib-private;
            # failing to unregister only risks a spurious unlink warning
            pass
        return cls(shm, capacity, owner=False)

    # --------------------------------------------------------- accessors

    @property
    def name(self) -> str:
        return self._shm.name

    @property
    def capacity(self) -> int:
        return self._capacity

    @property
    def size(self) -> int:
        return region_bytes(self._capacity)

    def _latest(self) -> int:
        _, _, latest = _HEADER.unpack_from(self._shm.buf, 0)
        return latest

    def latest_seq(self) -> int:
        """Sequence number of the most recently published snapshot
        (0 when nothing has been published yet)."""
        return self._latest()

    def _buf_off(self, seq: int) -> int:
        return HEADER_SIZE + (seq % 2) * (BUFHDR_SIZE + self._capacity)

    # ----------------------------------------------------------- writing

    def publish_bytes(self, payload: bytes, *, version: int,
                      train_step: int, wall_ts: float) -> int:
        """Publish one serialized snapshot; returns its sequence number.

        Seqlock write order: stamp ``seq_begin`` (poisoning in-progress
        reads of this buffer), copy payload + metadata + CRC, stamp
        ``seq_end``, then flip the header's ``latest`` — a reader either
        sees the old sequence (old buffer, untouched) or the new one
        (fully written)."""
        n = len(payload)
        if n > self._capacity:
            raise ValueError(
                f"snapshot payload of {n} bytes exceeds the region "
                f"capacity of {self._capacity}; size the region with "
                f"slack_capacity() off the largest expected payload "
                f"(raise {SLACK_ENV} if streaming growth outpaced it)")
        seq = self._seq + 1
        off = self._buf_off(seq)
        buf = self._shm.buf
        crc = _crc(payload, seq, int(version), int(train_step),
                   float(wall_ts))
        # begin stamp first (seq_end still stale -> mismatch -> retry)
        _BUFHDR.pack_into(buf, off, seq, 0, crc, n, int(version),
                          int(train_step), float(wall_ts))
        data_off = off + BUFHDR_SIZE
        buf[data_off:data_off + n] = payload
        # end stamp validates the buffer ...
        struct.pack_into("<Q", buf, off + 8, seq)
        # ... and only then does the region advertise it
        _HEADER.pack_into(buf, 0, MAGIC, self._capacity, seq)
        self._seq = seq
        return seq

    # ----------------------------------------------------------- reading

    def read_latest(self, *, retries: Optional[int] = None
                    ) -> Optional[ShmSnapshot]:
        """One consistent snapshot, or ``None`` (nothing published yet,
        or the writer lapped us ``retries`` times — keep the previous
        snapshot and try again later)."""
        if retries is None:
            retries = envvars.get_int(READ_RETRIES_ENV)
        buf = self._shm.buf
        for _ in range(max(1, retries)):
            seq = self._latest()
            if seq == 0:
                return None
            off = self._buf_off(seq)
            (seq_begin, seq_end, crc, n, version, train_step,
             wall_ts) = _BUFHDR.unpack_from(buf, off)
            if seq_begin != seq or seq_end != seq or n > self._capacity:
                continue  # mid-write or lapped: retry against `latest`
            data_off = off + BUFHDR_SIZE
            payload = bytes(buf[data_off:data_off + n])
            if _crc(payload, seq, version, train_step, wall_ts) != crc:
                continue  # torn copy slipped past the stamps
            return ShmSnapshot(payload=payload, seq=seq, version=version,
                               train_step=train_step, wall_ts=wall_ts)
        return None

    # ---------------------------------------------------------- lifetime

    def close(self) -> None:
        """Detach this process's mapping (the region lives on)."""
        if not self._closed:
            self._closed = True
            self._shm.close()

    def unlink(self) -> None:
        """Destroy the region (owner only; after every reader is done
        with it — a supervisor tears this down last)."""
        self.close()
        if self._owner:
            try:
                # a SAME-process attach (tests) unregistered this name;
                # re-register (set-add, idempotent) so SharedMemory
                # .unlink()'s own unregister finds it instead of
                # spraying KeyError noise in the tracker daemon
                resource_tracker.register(self._shm._name, "shared_memory")  # noqa: SLF001
            except Exception:  # noqa: BLE001 - cosmetic only
                pass
            self._shm.unlink()

    def __enter__(self) -> "SnapshotShm":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

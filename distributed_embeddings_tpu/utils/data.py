"""Datasets: synthetic benchmark feeds and the Criteo raw-binary reader.

TPU equivalents of the reference's data layer (``examples/dlrm/utils.py``):

* :class:`DummyDataset` — constant synthetic batches for benchmarking
  (reference ``utils.py:126-154``).
* :class:`RawBinaryDataset` — the split Criteo binary format (``label.bin``,
  ``numerical.bin`` float16, per-feature ``cat_<i>.bin`` in the smallest int
  type that fits the vocab; reference ``utils.py:157-307``). Reading uses
  ``np.memmap`` + a background prefetch thread instead of raw ``os.pread``;
  a C-accelerated path can plug in transparently (see ``cc/``).
* :func:`power_law_ids` — the power-law id generator used by the synthetic
  model benchmarks (``examples/benchmarks/synthetic_models/synthetic_models.py:31-113``).
"""

from __future__ import annotations

import itertools
import math
import os
import queue
import threading
from typing import Any, Iterator, List, Optional, Sequence

import numpy as np


def fast_forward(data: Any, start: int) -> Iterator:
    """Deterministically position a data source at batch ``start`` for a
    resumed run — the resume contract: no batch replayed, none skipped.

    Dispatch, cheapest first:

    * a **callable** ``data(start) -> iterable`` positions itself (the
      factory form; ``RawBinaryDataset(start_batch=...)`` or a seeded
      generator that folds the step into its key);
    * an object with ``iter_from(start)`` (e.g. :class:`RawBinaryDataset`)
      seeks directly — random access via the memmaps, no replay cost;
    * any other iterable is advanced with ``itertools.islice`` — the
      skipped batches are *generated* and discarded (deterministic for a
      seeded generator, but O(start) work; prefer the first two forms for
      long runs).
    """
    if start < 0:
        raise ValueError(f"fast_forward start must be >= 0, got {start}")
    if callable(data):
        return iter(data(start))
    if hasattr(data, "iter_from"):
        return data.iter_from(start)
    it = iter(data)
    if start:
        next(itertools.islice(it, start - 1, start), None)
    return it


def get_categorical_feature_type(size: int):
    """Smallest signed int dtype that can hold ids below ``size``
    (reference ``utils.py:116-123``)."""
    for t in (np.int8, np.int16, np.int32):
        if size < np.iinfo(t).max:
            return t
    raise RuntimeError(f"Categorical feature of size {size} is too big")


def power_law_ids(rng: np.random.Generator, vocab: int, shape,
                  alpha: float = 1.05) -> np.ndarray:
    """Power-law distributed ids in ``[0, vocab)``: hot ids dominate, matching
    real recommender id distributions (reference ``power_law`` /
    ``gen_power_law_data``)."""
    u = rng.random(size=shape)
    # inverse-CDF of p(x) ~ x^(-alpha) on [1, vocab+1)
    exp = 1.0 - alpha
    ids = ((vocab + 1) ** exp * u + (1 - u)) ** (1.0 / exp) - 1.0
    return np.clip(ids.astype(np.int64), 0, vocab - 1)


class DummyDataset:
    """Fixed synthetic batches (all-zero ids, like the reference's
    ``DummyDataset`` — measuring the compute path, not input randomness)."""

    def __init__(self, batch_size: int, num_numerical_features: int,
                 table_sizes: Sequence[int], num_batches: int,
                 hotness=1, num_workers: int = 1):
        local_bs = batch_size // num_workers
        self.numerical = np.zeros((local_bs, num_numerical_features),
                                  np.float32)
        # hotness: one int for all tables, or a per-table sequence (the
        # reference's DummyDataset takes per-feature hotness, utils.py:126-154)
        if isinstance(hotness, (int, np.integer)):
            hotness = [int(hotness)] * len(table_sizes)
        if len(hotness) != len(table_sizes):
            raise ValueError("hotness list must match table_sizes")
        self.categorical = [np.zeros((local_bs, h), np.int32)
                            for h in hotness]
        self.labels = np.ones((local_bs, 1), np.float32)
        self.num_batches = num_batches

    def __len__(self):
        return self.num_batches

    def __getitem__(self, idx):
        if idx >= self.num_batches:
            raise IndexError
        return self.numerical, self.categorical, self.labels

    def __iter__(self):
        for i in range(self.num_batches):
            yield self[i]


class RawBinaryDataset:
    """Split-binary Criteo reader.

    Layout (identical to the reference's, ``examples/dlrm/utils.py:157-237``):
    ``<root>/<train|test>/label.bin`` (bool), ``numerical.bin`` (float16,
    ``[N, num_numerical]`` row-major), ``cat_<i>.bin`` (per-feature smallest
    int type). Yields ``(numerical [B, F] float32, categorical list of
    [B] int32, labels [B, 1] float32)``.

    Args:
      data_path: dataset root.
      batch_size: global batch size.
      numerical_features: how many numerical columns to read (0 = none).
      categorical_features: feature ids this worker needs (model-parallel
        input reads only the local tables' files, reference ``main.py:166-176``).
      categorical_feature_sizes: vocab sizes for ALL features (determines the
        stored dtype of each file).
      offset/lbs: slice ``[offset, offset+lbs)`` of each batch for
        data-parallel shards (labels/numerical always sliced; categorical
        sliced only when ``dp_input``).
      drop_last_batch: drop the trailing partial batch.
      valid: read the ``test`` split.
      prefetch_depth: background-thread read-ahead.
      start_batch: iteration begins at this batch index (random access via
        the memmaps, no replay cost) — lets a resumed run continue the data
        stream where the checkpointed step left off instead of re-training
        the early batches with a late-step LR (ADVICE r4).
    """

    # detlint thread-shared: the prefetch producer spawned per
    # iteration touches only its closure locals plus the synchronized
    # queue/stop-event pair — no instance attribute is shared with it
    _THREAD_SHARED = ()

    def __init__(self, data_path: str, batch_size: int = 1,
                 numerical_features: int = 0,
                 categorical_features: Optional[Sequence[int]] = None,
                 categorical_feature_sizes: Optional[Sequence[int]] = None,
                 prefetch_depth: int = 10, drop_last_batch: bool = False,
                 valid: bool = False, offset: int = -1, lbs: int = -1,
                 dp_input: bool = False, start_batch: int = 0):
        split_dir = os.path.join(data_path, "test" if valid else "train")
        self._batch_size = batch_size
        self._num_numerical = numerical_features
        self.offset, self.lbs, self.valid = offset, lbs, valid
        self.dp_input = dp_input

        self._labels = np.memmap(os.path.join(split_dir, "label.bin"),
                                 dtype=np.bool_, mode="r")
        n = len(self._labels)
        self._num_entries = (n // batch_size if drop_last_batch
                             else math.ceil(n / batch_size))

        if numerical_features > 0:
            num = np.memmap(os.path.join(split_dir, "numerical.bin"),
                            dtype=np.float16, mode="r")
            self._numerical = num.reshape(-1, numerical_features)
            if len(self._numerical) != n:
                raise ValueError("numerical.bin row count mismatch")
        else:
            self._numerical = None

        self._cat_maps: List[np.memmap] = []
        self._cat_ids = list(categorical_features or [])
        sizes = list(categorical_feature_sizes or [])
        for cid in self._cat_ids:
            dt = get_categorical_feature_type(sizes[cid])
            m = np.memmap(os.path.join(split_dir, f"cat_{cid}.bin"),
                          dtype=dt, mode="r")
            if len(m) != n:
                raise ValueError(f"cat_{cid}.bin row count mismatch")
            self._cat_maps.append(m)

        # NOT wrapped modulo the epoch: resuming a checkpoint saved at run
        # completion (step == num batches) must yield an EMPTY stream, not
        # silently retrain an extra epoch; multi-epoch drivers pass
        # ``step % len(ds)`` themselves
        self._start_batch = int(start_batch)
        self._prefetch_depth = min(prefetch_depth, self._num_entries)

    def __len__(self):
        # full-epoch batch count; iteration with start_batch > 0 yields
        # len(self) - start_batch items (absolute __getitem__ indexing is
        # unaffected)
        return self._num_entries

    def _read(self, idx: int):
        lo, hi = idx * self._batch_size, (idx + 1) * self._batch_size
        labels = np.asarray(self._labels[lo:hi], np.float32)[:, None]
        numerical = (np.asarray(self._numerical[lo:hi], np.float32)
                     if self._numerical is not None else
                     np.zeros((labels.shape[0], 0), np.float32))
        cats = [np.asarray(m[lo:hi], np.int32) for m in self._cat_maps]
        if self.offset >= 0:
            sl = slice(self.offset, self.offset + self.lbs)
            if not self.valid:
                labels = labels[sl]
            numerical = numerical[sl]
            if self.dp_input:
                cats = [c[sl] for c in cats]
        return numerical, cats, labels

    def __getitem__(self, idx: int):
        if idx >= self._num_entries:
            raise IndexError
        return self._read(idx)

    def iter_from(self, start: int):
        """Iterate from absolute batch ``start`` regardless of the
        constructor's ``start_batch`` — the :func:`fast_forward` resume
        hook (random access via the memmaps, no replay cost). Like
        ``start_batch``, NOT wrapped modulo the epoch: resuming at or past
        the end yields an empty stream."""
        return self._iter_range(int(start))

    def __iter__(self):
        return self._iter_range(self._start_batch)

    def _iter_range(self, start_batch: int):
        if self._prefetch_depth <= 1:
            for i in range(start_batch, self._num_entries):
                yield self._read(i)
            return

        # Fresh bounded queue + thread per iteration: maxsize caps read-ahead
        # memory at prefetch_depth batches, and an abandoned iteration can't
        # leak stale batches into the next epoch. The stop event makes the
        # producer exit promptly when the consumer abandons the generator —
        # a thread blocked forever on put() would keep the queue and memmaps
        # alive for the process lifetime.
        q: "queue.Queue" = queue.Queue(maxsize=self._prefetch_depth)
        stop = threading.Event()

        def put_until_stopped(item) -> bool:
            while not stop.is_set():
                try:
                    q.put(item, timeout=0.1)
                    return True
                except queue.Full:
                    continue
            return False

        def producer():
            # An exception (truncated file, transient IO error) must reach
            # the consumer — a silently dead producer would leave the
            # consumer blocked on q.get() forever.
            try:
                for i in range(start_batch, self._num_entries):
                    if not put_until_stopped(self._read(i)):
                        return
                put_until_stopped(None)
            except BaseException as e:  # noqa: BLE001 - relayed, not dropped
                put_until_stopped(e)

        threading.Thread(target=producer, daemon=True).start()
        try:
            while True:
                item = q.get()
                if item is None:
                    return
                if isinstance(item, BaseException):
                    raise item
                yield item
        finally:
            stop.set()

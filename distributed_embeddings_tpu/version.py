"""Package version (reference: version.txt)."""

__version__ = "0.1.0"

"""Version-compatibility polyfills for the jax API surface this package
uses.

The package is written against the current jax API (``jax.shard_map``
public, VMA typing via ``jax.typeof``/``lax.pvary``); deployment images
often pin older releases (the container baseline is jax 0.4.37, where
``shard_map`` still lives in ``jax.experimental.shard_map`` and VMA typing
does not exist). A runtime layer that survives flaky backends but dies on
an ``AttributeError`` at import is not fault-tolerant — so the gaps are
bridged here, once, instead of per call site.

Imported for its side effect by the package root. Provides:

* ``jax.shard_map`` — installed from ``jax.experimental.shard_map`` when
  the public name is missing (keyword-compatible for the subset this
  package uses: ``mesh``/``in_specs``/``out_specs``; a ``check_vma`` kwarg
  is translated to the legacy ``check_rep``).
* :func:`pvary` — mark a constant device-varying under VMA typing;
  identity on pre-VMA jax, where replicated values join varying values in
  collectives without explicit casts.
* :func:`vma_of` — the value's varying-manual-axes set, or ``None`` when
  the running jax has no VMA typing (callers fall back to pre-VMA
  semantics; see ``parallel.grads.resolve_dp_gradient``).
"""

from __future__ import annotations

from typing import Optional

import jax
from jax import lax

HAS_VMA = hasattr(jax, "typeof") and (hasattr(lax, "pvary")
                                      or hasattr(lax, "pcast"))

if not hasattr(jax, "shard_map"):
    from jax.experimental.shard_map import shard_map as _legacy_shard_map

    def _shard_map(f, mesh=None, in_specs=None, out_specs=None, **kwargs):
        if "check_vma" in kwargs:
            kwargs["check_rep"] = kwargs.pop("check_vma")
        return _legacy_shard_map(f, mesh=mesh, in_specs=in_specs,
                                 out_specs=out_specs, **kwargs)

    jax.shard_map = _shard_map


def pvary(x: jax.Array, axis_name: str) -> jax.Array:
    """Mark a constant as device-varying over ``axis_name`` so it can join
    varying values in collectives/switch branches under VMA typing; identity
    on pre-VMA jax (no cast exists or is needed there)."""
    if hasattr(lax, "pcast"):
        return lax.pcast(x, axis_name, to="varying")
    if hasattr(lax, "pvary"):
        return lax.pvary(x, axis_name)
    return x


def axis_size(axis_name: str):
    """``lax.axis_size`` polyfill: on pre-VMA jax a ``psum`` of 1 over the
    axis, which XLA constant-folds to the (static) axis size."""
    if hasattr(lax, "axis_size"):
        return lax.axis_size(axis_name)
    return lax.psum(1, axis_name)


def vma_of(x) -> Optional[frozenset]:
    """The varying-manual-axes set of ``x``, or ``None`` on pre-VMA jax."""
    if not hasattr(jax, "typeof"):
        return None
    return getattr(jax.typeof(x), "vma", None)


def distributed_is_initialized() -> bool:
    """``jax.distributed.is_initialized`` polyfill: older releases only
    expose the client handle through the private global state."""
    if hasattr(jax.distributed, "is_initialized"):
        return jax.distributed.is_initialized()
    try:
        from jax._src import distributed as _dist

        return _dist.global_state.client is not None
    except Exception:  # noqa: BLE001 - conservatively "not initialized"
        return False

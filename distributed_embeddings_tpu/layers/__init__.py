"""Module layer (TPU equivalent of the reference's Keras layers,
``distributed_embeddings/python/layers/``)."""

from .embedding import ConcatEmbedding, Embedding
from .dist_flax import DistributedEmbeddingLayer

"""Flax ``nn.Module`` adapter for :class:`~.parallel.DistributedEmbedding`.

The reference packages its distributed embedding as a ``tf.keras.layers.Layer``
(``distributed_embeddings/python/layers/dist_model_parallel.py:199-259``) so it
composes with stock Keras training loops. This module is the Flax analogue
(VERDICT r3 Missing #2): the width-grouped slab dict becomes a normal Flax
parameter, so the layer trains with plain ``flax`` + ``optax`` — any optax
transform, standard ``TrainState``, no
:func:`~.parallel.trainer.make_hybrid_train_step` required.

Two training modes over the SAME layer and parameters:

* **Plain autodiff** (this adapter's default contract): differentiating
  through the forward produces *dense* slab cotangents (XLA turns the gather
  transpose into a scatter-add over a zero slab), and optax updates the whole
  slab. Exact, composable, and fine whenever tables are small enough that an
  O(all rows) update is acceptable — the same trade the reference makes when
  the Keras optimizer densifies ``IndexedSlices``.
* **Sparse trainer** (O(touched rows) updates for huge tables): pass
  ``module.de`` and the slab subtree to
  :func:`~.parallel.trainer.make_hybrid_train_step` /
  :class:`~.parallel.optimizers.SparseAdagrad` — same parameter pytree, so
  checkpoints interchange freely.

For single-table / op-layer models (``layers.Embedding`` over plain
``[vocab, width]`` tables, no executor), a third route keeps BOTH plain
optax composability and O(touched rows) updates:
:func:`~.parallel.sparse_optax.sparse_value_and_grad` +
``sparse_rows_*`` transforms — the op-layer IndexedSlices pipeline
(reference ``embedding_lookup_ops.py:105-122``), see
``parallel/sparse_optax.py``.

Autodiff contract note: the forward clips out-of-range ids into the last row
(module contract, see ``parallel/dist_embedding.py``), so plain autodiff
*trains* that clipped row on bad ids where the sparse backward *drops* them.

Usage (single chip)::

    layer = DistributedEmbeddingLayer(de=DistributedEmbedding(cfgs, 1))
    vars_ = layer.init(key, cat_batch)
    outs = layer.apply(vars_, cat_batch)

Usage (mesh; executor must run inside ``shard_map`` with the axis bound)::

    layer = DistributedEmbeddingLayer(de=DistributedEmbedding(cfgs, world))
    vars_ = layer.init(key, cat_batch)          # global [world, ...] slabs
    # shard vars_ with P(de.axis_name) on the slab leaves, then inside
    # shard_map: layer.apply(local_vars, local_batch)
"""

from __future__ import annotations

from typing import Any, List, Sequence

import flax.linen as nn
import jax
import jax.numpy as jnp

from ..ops.embedding_lookup import Ragged

# NOTE: no top-level import of DistributedEmbedding — parallel.dist_embedding
# imports layers.embedding, so importing it here would make the two packages
# circularly dependent. The ``de`` field is typed ``Any`` for that reason.


class DistributedEmbeddingLayer(nn.Module):
    """Flax wrapper: slab dict as a Flax param, forward = the plan executor.

    Attributes:
      de: a constructed :class:`~.parallel.DistributedEmbedding` (placement,
        slicing and exchange config live there).
      param_dtype: slab parameter dtype.
    """

    de: Any
    param_dtype: Any = jnp.float32

    def _init_output_stubs(self, inputs) -> List[jax.Array]:
        """Correctly-shaped zero outputs for ``init`` when the executor can't
        run (world > 1 traces ``lax.axis_index``, which needs the mesh axis
        bound — but ``module.init`` happens *outside* ``shard_map``)."""
        strat = self.de.strategy
        dt = self.de.compute_dtype or self.param_dtype
        outs = []
        for i, inp in enumerate(inputs):
            cfg = strat.global_configs[strat.input_table_map[i]]
            w = int(cfg["output_dim"])
            if isinstance(inp, Ragged):
                b = inp.row_splits.shape[-1] - 1
                outs.append(jnp.zeros((b, w), dt))
                continue
            inp = jnp.asarray(inp)
            b = inp.shape[0]
            hot = 1 if inp.ndim == 1 else int(inp.shape[1])
            if cfg.get("combiner") is None and hot > 1:
                outs.append(jnp.zeros((b, hot * w), dt))
            else:
                outs.append(jnp.zeros((b, w), dt))
        # column-sliced tables were already re-concatenated by the executor;
        # stub widths above use the full (unsliced) table width, matching it
        return outs

    @nn.compact
    def __call__(self, inputs: Sequence[Any]) -> List[jax.Array]:
        # self.variable instead of self.param: the param-shape check would
        # compare the stored *global* [world, rows, w] slabs against a fresh
        # init's shape, which inside shard_map is the *local* [1, rows, w]
        # view — self.variable skips that check while keeping the slabs in
        # the "params" collection (optax/TrainState-compatible).
        slabs_var = self.variable(
            "params", "slabs",
            lambda: self.de.init(self.make_rng("params"),
                                 dtype=self.param_dtype))
        if self.is_initializing() and self.de.world_size > 1:
            return self._init_output_stubs(inputs)
        return self.de(slabs_var.value, inputs)

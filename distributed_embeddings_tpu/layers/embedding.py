"""Embedding modules (Flax) with the reference layer's shape semantics.

TPU re-design of ``distributed_embeddings/python/layers/embedding.py:41-183``:
the Keras ``Embedding``/``ConcatEmbedding`` layers become Flax ``nn.Module``s
over the functional :func:`~distributed_embeddings_tpu.ops.embedding_lookup`.

Differences from the reference, by design:

* Initialization on huge tables: the reference forces init onto the CPU device
  to dodge GPU OOM (``embedding.py:28-38``). Here initializers are ordinary
  ``jax.nn.initializers`` callables; sharded/host init for oversized tables is
  handled where sharding is known — in the distributed wrapper — not here.
* ``get_config``/``from_config`` carry plain dicts (used by the planner the
  same way the reference strategy consumes Keras configs,
  ``dist_model_parallel.py:44``).
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Optional

import jax
import jax.numpy as jnp
from flax import linen as nn

from ..ops.embedding_lookup import Ragged, SparseIds, embedding_lookup

Initializer = Callable[..., jax.Array]

# Keras's 'uniform' default: RandomUniform(-0.05, 0.05).
def default_embeddings_init(key, shape, dtype=jnp.float32):
    return jax.random.uniform(key, shape, dtype, minval=-0.05, maxval=0.05)


class Embedding(nn.Module):
    """Turns ids into fixed-width vectors, with optional multi-hot reduction.

    Parity surface (reference ``embedding.py:41-133``):

    * dense N-D input, ``combiner=None`` → output ``(..., output_dim)``
    * dense N-D input (N>=2) + combiner → reduced over the last dim
      → output ``(d1, ..., dn-1, output_dim)``
    * 1-D dense input + combiner raises (ambiguous, as in the reference)
    * 2-D :class:`Ragged` / :class:`SparseIds` + combiner → ``(batch, output_dim)``

    Attributes:
      input_dim: vocabulary size.
      output_dim: embedding width.
      embeddings_initializer: flax-style initializer ``f(key, shape, dtype)``.
      combiner: ``None``, ``'sum'`` or ``'mean'``.
      param_dtype: dtype of the table.
      dtype: compute/output dtype (casts after lookup, pre-reduction happens in
        param dtype like the reference's no-autocast policy, ``embedding.py:82``).
    """

    input_dim: int
    output_dim: int
    embeddings_initializer: Initializer = default_embeddings_init
    combiner: Optional[str] = None
    param_dtype: Any = jnp.float32
    dtype: Optional[Any] = None

    def setup(self):
        if self.input_dim <= 0 or self.output_dim <= 0:
            raise ValueError(
                "Both input_dim and output_dim should be positive, "
                f"found {self.input_dim} and {self.output_dim}")
        self.embeddings = self.param(
            "embeddings", self.embeddings_initializer,
            (self.input_dim, self.output_dim), self.param_dtype)

    def __call__(self, inputs, weights=None):
        out = self.lookup(self.embeddings, inputs, weights=weights)
        if self.dtype is not None:
            out = out.astype(self.dtype)
        return out

    def lookup(self, table: jax.Array, inputs, weights=None) -> jax.Array:
        """Pure lookup used by both this module and the distributed wrapper.

        ``weights``: optional per-id multipliers matching the id layout
        (Ragged/SparseIds may instead carry their own ``weights`` field) —
        the reference kernel's optional ``weights`` input
        (``cc/kernels/embedding_lookup_kernels.cu:52-55``) plumbed through
        the layer (VERDICT r4 Missing #5)."""
        if isinstance(inputs, (Ragged, SparseIds)):
            if self.combiner is None:
                raise ValueError("Ragged/sparse input requires a combiner")
            return embedding_lookup(table, inputs, combiner=self.combiner,
                                    weights=weights)
        inputs = jnp.asarray(inputs)
        if not jnp.issubdtype(inputs.dtype, jnp.integer):
            inputs = inputs.astype(jnp.int32)
        if self.combiner is None and weights is not None:
            # weights scale a reduction; without a combiner they would be
            # silently dropped — refuse like other ambiguous inputs
            raise ValueError("weights require a combiner ('sum'/'mean')")
        if inputs.ndim == 1:
            if self.combiner is not None:
                raise ValueError(
                    "1D input with combiner is ambiguous. Please create batch dimension.")
            return embedding_lookup(table, inputs)
        if self.combiner is None:
            return embedding_lookup(table, inputs)
        # combiner reduces the trailing dimension; flatten leading dims like the
        # reference's non-2D reshape (embedding.py:115-132)
        lead = inputs.shape[:-1]
        flat = inputs.reshape(-1, inputs.shape[-1])
        wflat = (jnp.asarray(weights).reshape(flat.shape)
                 if weights is not None else None)
        out = embedding_lookup(table, flat, combiner=self.combiner,
                               weights=wflat)
        return out.reshape(lead + (self.output_dim,))

    def get_config(self) -> Dict[str, Any]:
        return {
            "input_dim": self.input_dim,
            "output_dim": self.output_dim,
            "embeddings_initializer": self.embeddings_initializer,
            "combiner": self.combiner,
            "param_dtype": self.param_dtype,
            "dtype": self.dtype,
        }

    @classmethod
    def from_config(cls, config: Dict[str, Any]) -> "Embedding":
        """Build from a config dict; ignores Keras-only keys the way the
        reference's override does (``embedding.py:148-155``)."""
        config = {k: v for k, v in config.items()
                  if k not in ("mask_zero", "input_length", "name")}
        return cls(**config)


class ConcatEmbedding(nn.Module):
    """Many same-width one-hot tables fused into one weight matrix with row
    offsets; lookup is a single gather of ``input + offsets``
    (reference ``embedding.py:158-183``).

    Input: ``[batch, num_tables]`` ids, one per table.
    Output: ``[batch, num_tables, embedding_width]``.
    """

    feature_sizes: tuple
    embedding_width: int
    embeddings_initializer: Initializer = default_embeddings_init
    param_dtype: Any = jnp.float32

    def setup(self):
        total = int(sum(self.feature_sizes))
        self.params_matrix = self.param(
            "embeddings", self.embeddings_initializer,
            (total, self.embedding_width), self.param_dtype)

    @property
    def offsets(self) -> jax.Array:
        import numpy as np
        off = np.concatenate([[0], np.cumsum(self.feature_sizes)])
        return jnp.asarray(off, jnp.int32)

    def __call__(self, inputs):
        if inputs.shape[1] != len(self.feature_sizes):
            raise ValueError(
                f"Expected {len(self.feature_sizes)} id columns, got {inputs.shape[1]}")
        idx = inputs + self.offsets[:-1]
        return jnp.take(self.params_matrix, idx, axis=0, mode="clip")

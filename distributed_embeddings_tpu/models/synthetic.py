"""Synthetic benchmark models.

TPU re-design of the reference's benchmark model
(``examples/benchmarks/synthetic_models/synthetic_models.py:116-243``):
multi-hot sum-combiner embeddings (distributed), an optional
average-pooling "interaction" that emulates memory-bound FM/pooling layers,
and an MLP head. The dense half is a Flax module fed embedding activations,
composable with :class:`~distributed_embeddings_tpu.parallel.DistributedEmbedding`
via the hybrid trainer, like the DLRM example.
"""

from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np

from .synthetic_configs import ModelConfig


def expand_embedding_configs(model_config: ModelConfig
                             ) -> Tuple[List[dict], List[int], List[int]]:
    """Flatten grouped ``EmbeddingConfig`` rows to per-table configs plus the
    input→table map and per-input hotness (reference
    ``synthetic_models.py:130-143``)."""
    table_configs: List[dict] = []
    input_table_map: List[int] = []
    input_hotness: List[int] = []
    for cfg in model_config.embedding_configs:
        if len(cfg.nnz) > 1 and not cfg.shared:
            raise NotImplementedError(
                "Nonshared multihot embedding is not implemented yet")
        for _ in range(cfg.num_tables):
            table_id = len(table_configs)
            table_configs.append({
                "input_dim": int(cfg.num_rows),
                "output_dim": int(cfg.width),
                "combiner": "sum",
            })
            for hotness in cfg.nnz:
                input_table_map.append(table_id)
                input_hotness.append(int(hotness))
    return table_configs, input_table_map, input_hotness


def average_pool_1d(x: jax.Array, stride: int) -> jax.Array:
    """SAME-padded 1-D average pooling over the feature axis with
    window == stride (the reference's ``AveragePooling1D(...,
    data_format='channels_first')`` applied to the concatenated embedding
    vector, ``synthetic_models.py:151-155``)."""
    b, t = x.shape
    pad = (-t) % stride
    if pad:
        x = jnp.concatenate([x, jnp.zeros((b, pad), x.dtype)], axis=1)
    # windows never cross the original boundary after SAME padding; average
    # uses the true element count per window like Keras (count_includes_pad=False)
    counts = jnp.concatenate(
        [jnp.ones((t,), x.dtype), jnp.zeros((pad,), x.dtype)])
    sums = x.reshape(b, -1, stride).sum(-1)
    denom = jnp.maximum(counts.reshape(-1, stride).sum(-1), 1)
    return sums / denom[None, :]


class SyntheticDense(nn.Module):
    """Dense half: optional pooled interaction + MLP head
    (reference ``synthetic_models.py:150-175``)."""

    mlp_sizes: Sequence[int]
    interact_stride: Optional[int] = None

    @nn.compact
    def __call__(self, numerical_features: jax.Array,
                 embedding_outputs: Sequence[jax.Array]) -> jax.Array:
        cat = jnp.concatenate(
            [e.reshape(e.shape[0], -1) for e in embedding_outputs], axis=1)
        if self.interact_stride is not None:
            cat = average_pool_1d(cat, self.interact_stride)
        x = jnp.concatenate([cat, numerical_features], axis=1)
        for size in self.mlp_sizes:
            x = nn.relu(nn.Dense(size)(x))
        return nn.Dense(1)(x)


def build_synthetic(model_config: ModelConfig, world_size: int,
                    strategy: str = "memory_balanced",
                    column_slice_threshold: Optional[int] = None,
                    row_cap: Optional[int] = None):
    """Build ``(dist_embedding, dense_module, input_hotness)`` for a zoo model.

    ``row_cap`` optionally clips table vocab sizes so the multi-TiB zoo scales
    (reference ``config_v3.py``) can smoke-run on small hardware; benchmarks on
    real pods run uncapped.
    """
    from ..parallel import DistributedEmbedding

    table_configs, input_table_map, hotness = expand_embedding_configs(
        model_config)
    if row_cap is not None:
        for cfg in table_configs:
            cfg["input_dim"] = min(cfg["input_dim"], row_cap)
    de = DistributedEmbedding(table_configs, world_size=world_size,
                              strategy=strategy,
                              column_slice_threshold=column_slice_threshold,
                              input_table_map=input_table_map,
                              input_hotness=hotness)
    dense = SyntheticDense(mlp_sizes=tuple(model_config.mlp_sizes),
                           interact_stride=model_config.interact_stride)
    return de, dense, hotness


class InputGenerator:
    """Synthetic data-parallel batches: uniform or power-law ids
    (reference ``InputGenerator``, ``synthetic_models.py:51-113``).

    Yields ``(numerical [lbs, F], cats list of [lbs, hotness], labels
    [lbs, 1])`` — ids over the full vocab (dp input; each device slice is
    taken by the caller's sharding).
    """

    def __init__(self, model_config: ModelConfig, global_batch_size: int,
                 alpha: float = 0.0, num_batches: int = 4, seed: int = 0,
                 row_cap: Optional[int] = None):
        from ..utils.data import power_law_ids
        rng = np.random.default_rng(seed)
        table_configs, input_table_map, hotness = expand_embedding_configs(
            model_config)
        self.batches = []
        for _ in range(num_batches):
            cats = []
            for inp, h in zip(input_table_map, hotness):
                rows = table_configs[inp]["input_dim"]
                if row_cap is not None:
                    rows = min(rows, row_cap)
                if alpha == 0.0:
                    ids = rng.integers(0, rows, size=(global_batch_size, h))
                else:
                    ids = power_law_ids(rng, rows, (global_batch_size, h),
                                        alpha)
                cats.append(jnp.asarray(ids, jnp.int32))
            numerical = jnp.asarray(
                rng.random(size=(global_batch_size,
                                 model_config.num_numerical_features)) * 100,
                jnp.float32)
            labels = jnp.asarray(
                rng.integers(0, 2, size=(global_batch_size, 1)), jnp.float32)
            self.batches.append((numerical, cats, labels))

    def __len__(self):
        return len(self.batches)

    def __getitem__(self, idx):
        return self.batches[idx % len(self.batches)]

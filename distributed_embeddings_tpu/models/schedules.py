"""Learning-rate schedules.

TPU equivalent of the reference's ``LearningRateScheduler``
(``examples/dlrm/utils.py:45-88``): linear warmup, constant plateau, then
polynomial (power-2) decay. The reference mutates ``optimizer.lr`` via a tf
Variable each step; in JAX a schedule is a pure ``step -> lr`` function usable
both by optax and by the sparse embedding optimizers.
"""

from __future__ import annotations

import jax.numpy as jnp


def warmup_poly_decay_schedule(base_lr: float, warmup_steps: int,
                               decay_start_step: int, decay_steps: int,
                               poly_power: int = 2):
    """``step -> lr``: ramp 0→base over ``warmup_steps``, hold, then decay to 0
    over ``decay_steps`` with ``(remaining/decay_steps)**poly_power``."""
    decay_end_step = decay_start_step + decay_steps

    def schedule(step):
        step = jnp.asarray(step, jnp.float32)
        warmup = 1.0 - (warmup_steps - step) / warmup_steps
        decay = jnp.clip(
            (decay_end_step - step) / decay_steps, 0.0, 1.0) ** poly_power
        factor = jnp.where(step < warmup_steps, warmup,
                           jnp.where(step < decay_start_step, 1.0, decay))
        return base_lr * factor

    return schedule

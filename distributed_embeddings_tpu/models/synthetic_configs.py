"""Synthetic model zoo configs.

Same declarative scale definitions as the reference benchmark suite
(``examples/benchmarks/synthetic_models/config_v3.py:21-133``): each model is
a list of ``EmbeddingConfig`` groups plus MLP sizes. ``nnz`` is a list of
hotness values; a ``shared`` group maps ``len(nnz)`` inputs to one table.
Tables per group = ``num_tables`` (× ``len(nnz)`` if not shared — which the
reference leaves unimplemented; same here).
"""

from collections import namedtuple

EmbeddingConfig = namedtuple(
    "EmbeddingConfig", ["num_tables", "nnz", "num_rows", "width", "shared"])

ModelConfig = namedtuple(
    "ModelConfig",
    ["name", "embedding_configs", "mlp_sizes", "num_numerical_features",
     "interact_stride"])

model_tiny = ModelConfig(
    name="Tiny V3",
    embedding_configs=[
        EmbeddingConfig(1, [1, 10], 10000, 8, True),
        EmbeddingConfig(1, [1, 10], 1000000, 16, True),
        EmbeddingConfig(1, [1, 10], 25000000, 16, True),
        EmbeddingConfig(1, [1], 25000000, 16, False),
        EmbeddingConfig(16, [1], 10, 8, False),
        EmbeddingConfig(10, [1], 1000, 8, False),
        EmbeddingConfig(4, [1], 10000, 8, False),
        EmbeddingConfig(2, [1], 100000, 16, False),
        EmbeddingConfig(19, [1], 1000000, 16, False),
    ],
    mlp_sizes=[256, 128],
    num_numerical_features=10,
    interact_stride=None)

model_small = ModelConfig(
    name="Small V3",
    embedding_configs=[
        EmbeddingConfig(5, [1, 30], 10000, 16, True),
        EmbeddingConfig(3, [1, 30], 4000000, 32, True),
        EmbeddingConfig(1, [1, 30], 50000000, 32, True),
        EmbeddingConfig(1, [1], 50000000, 32, False),
        EmbeddingConfig(30, [1], 10, 16, False),
        EmbeddingConfig(30, [1], 1000, 16, False),
        EmbeddingConfig(5, [1], 10000, 16, False),
        EmbeddingConfig(5, [1], 100000, 32, False),
        EmbeddingConfig(27, [1], 4000000, 32, False),
    ],
    mlp_sizes=[512, 256, 128],
    num_numerical_features=10,
    interact_stride=None)

model_medium = ModelConfig(
    name="Medium v3",
    embedding_configs=[
        EmbeddingConfig(20, [1, 50], 100000, 64, True),
        EmbeddingConfig(5, [1, 50], 10000000, 64, True),
        EmbeddingConfig(1, [1, 50], 100000000, 128, True),
        EmbeddingConfig(1, [1], 100000000, 128, False),
        EmbeddingConfig(80, [1], 10, 32, False),
        EmbeddingConfig(60, [1], 1000, 32, False),
        EmbeddingConfig(80, [1], 100000, 64, False),
        EmbeddingConfig(24, [1], 200000, 64, False),
        EmbeddingConfig(40, [1], 10000000, 64, False),
    ],
    mlp_sizes=[1024, 512, 256, 128],
    num_numerical_features=25,
    interact_stride=7)

model_large = ModelConfig(
    name="Large v3",
    embedding_configs=[
        EmbeddingConfig(40, [1, 100], 100000, 64, True),
        EmbeddingConfig(16, [1, 100], 15000000, 64, True),
        EmbeddingConfig(1, [1, 100], 200000000, 128, True),
        EmbeddingConfig(1, [1], 200000000, 128, False),
        EmbeddingConfig(100, [1], 10, 32, False),
        EmbeddingConfig(100, [1], 10000, 32, False),
        EmbeddingConfig(160, [1], 100000, 64, False),
        EmbeddingConfig(50, [1], 500000, 64, False),
        EmbeddingConfig(144, [1], 15000000, 64, False),
    ],
    mlp_sizes=[2048, 1024, 512, 256],
    num_numerical_features=100,
    interact_stride=8)

model_jumbo = ModelConfig(
    name="Jumbo v3",
    embedding_configs=[
        EmbeddingConfig(50, [1, 200], 100000, 128, True),
        EmbeddingConfig(24, [1, 200], 20000000, 128, True),
        EmbeddingConfig(1, [1, 200], 400000000, 256, True),
        EmbeddingConfig(1, [1], 400000000, 256, False),
        EmbeddingConfig(100, [1], 10, 32, False),
        EmbeddingConfig(200, [1], 10000, 64, False),
        EmbeddingConfig(350, [1], 100000, 128, False),
        EmbeddingConfig(80, [1], 1000000, 128, False),
        EmbeddingConfig(216, [1], 20000000, 128, False),
    ],
    mlp_sizes=[2048, 1024, 512, 256],
    num_numerical_features=200,
    interact_stride=20)

model_colossal = ModelConfig(
    name="Colossal v3",
    embedding_configs=[
        EmbeddingConfig(100, [1, 300], 100000, 128, True),
        EmbeddingConfig(50, [1, 300], 40000000, 256, True),
        EmbeddingConfig(1, [1, 300], 2000000000, 256, True),  # capacity-ok: reference zoo vocab size, not a hardware limit
        EmbeddingConfig(1, [1], 1000000000, 256, False),
        EmbeddingConfig(100, [1], 10, 32, False),
        EmbeddingConfig(400, [1], 10000, 128, False),
        EmbeddingConfig(100, [1], 100000, 128, False),
        EmbeddingConfig(800, [1], 1000000, 128, False),
        EmbeddingConfig(450, [1], 40000000, 256, False),
    ],
    mlp_sizes=[4096, 2048, 1024, 512, 256],
    num_numerical_features=500,
    interact_stride=30)

synthetic_models_v3 = {
    "tiny": model_tiny,
    "small": model_small,
    "medium": model_medium,
    "large": model_large,
    "jumbo": model_jumbo,
    "colossal": model_colossal,
}

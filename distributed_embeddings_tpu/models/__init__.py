"""Model zoo (TPU equivalents of the reference's examples: DLRM and the
synthetic benchmark models)."""

from .dlrm import DLRM, DLRMConfig, dlrm_initializer, dot_interact
from .learnable import LearnableClicks, train_dlrm_convergence
from .schedules import warmup_poly_decay_schedule
from .synthetic import (
    InputGenerator,
    SyntheticDense,
    build_synthetic,
    expand_embedding_configs,
)
from .synthetic_configs import synthetic_models_v3

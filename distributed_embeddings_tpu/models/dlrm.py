"""DLRM (MLPerf-style) for TPU.

TPU re-design of the reference example (``examples/dlrm/main.py:76-147`` and
``examples/dlrm/utils.py:27-113``): bottom MLP over dense features, one
embedding per categorical feature, pairwise dot-product interaction, top MLP
to a single logit. The dense half is a Flax module (data-parallel); the
embedding half is fed in as activations so it can come from either local
tables or a :class:`~distributed_embeddings_tpu.parallel.DistributedEmbedding`
— mirroring how the reference swaps local Keras embeddings for the
distributed wrapper (``main.py:95-98``).

TPU notes: interaction and MLPs run in bf16-friendly matmuls shaped for the
MXU (the dot-interaction is one batched ``[B, F, D] @ [B, D, F]``); the
lower-triangle extraction uses a static mask + reshape, no dynamic shapes.
"""

from __future__ import annotations

import math
from typing import Any, Optional, Sequence, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np


def dlrm_initializer(rows: int):
    """Uniform(-1/sqrt(rows), +1/sqrt(rows)) table initializer
    (reference ``DLRMInitializer``, ``examples/dlrm/utils.py:27-41``)."""

    def init(key, shape, dtype=jnp.float32):
        maxval = 1.0 / math.sqrt(rows)
        return jax.random.uniform(key, shape, dtype, -maxval, maxval)

    return init


def dot_interact(emb_outs: Sequence[jax.Array],
                 bottom_mlp_out: jax.Array) -> jax.Array:
    """Pairwise dot-product feature interaction
    (reference ``dot_interact``, ``examples/dlrm/utils.py:92-113``).

    Stacks ``[bottom_mlp_out] + emb_outs`` into ``[B, F, D]``, takes the
    strictly-lower-triangular entries of the ``[B, F, F]`` Gram matrix, and
    concatenates the bottom-MLP output back on.
    """
    feats = jnp.stack([bottom_mlp_out] + list(emb_outs), axis=1)  # [B, F, D]
    gram = jnp.einsum("bfd,bgd->bfg", feats, feats)
    f = feats.shape[1]
    li, lj = np.tril_indices(f, k=-1)
    # static 0/1 selection MATMUL instead of the advanced-index gather
    # gram[:, li, lj]: the [F*F, P] matmul rides the MXU (measured 4.6 ms
    # faster per train step at the bench shapes — and the gather form made
    # XLA compile pathologically at batch 65536 in isolation); 0/1 selection
    # through the MXU is bit-exact for both bf16 and fp32 operands.
    sel = np.zeros((f * f, len(li)), np.float32)
    sel[li * f + lj, np.arange(len(li))] = 1.0
    lower = gram.reshape(gram.shape[0], f * f) @ jnp.asarray(sel, gram.dtype)
    return jnp.concatenate([lower, bottom_mlp_out], axis=1)


class DLRMConfig:
    """Model hyperparameters (reference flags, ``examples/dlrm/main.py:32-59``)."""

    def __init__(self,
                 table_sizes: Sequence[int] = (1000,) * 26,
                 embedding_dim: int = 128,
                 num_numerical_features: int = 13,
                 bottom_mlp_dims: Sequence[int] = (512, 256, 128),
                 top_mlp_dims: Sequence[int] = (1024, 1024, 512, 256, 1),
                 compute_dtype: Any = jnp.float32):
        if bottom_mlp_dims[-1] != embedding_dim:
            raise ValueError(
                "bottom MLP must project to embedding_dim for dot interaction")
        self.table_sizes = list(table_sizes)
        self.embedding_dim = embedding_dim
        self.num_numerical_features = num_numerical_features
        self.bottom_mlp_dims = list(bottom_mlp_dims)
        self.top_mlp_dims = list(top_mlp_dims)
        self.compute_dtype = compute_dtype

    def embedding_configs(self, combiner: Optional[str] = None):
        """Table configs for DistributedEmbedding / Embedding layers."""
        return [{
            "input_dim": int(s),
            "output_dim": self.embedding_dim,
            "combiner": combiner,
            "embeddings_initializer": dlrm_initializer(int(s)),
        } for s in self.table_sizes]


class DLRMDense(nn.Module):
    """The data-parallel half: bottom MLP -> dot interaction -> top MLP.

    Takes embedding activations as inputs (one ``[B, D]`` per table) so the
    embedding half can be local or distributed.
    """

    config: DLRMConfig

    @nn.compact
    def __call__(self, numerical_features: jax.Array,
                 embedding_outputs: Sequence[jax.Array]) -> jax.Array:
        cfg = self.config
        dt = cfg.compute_dtype
        x = numerical_features.astype(dt)
        for dim in cfg.bottom_mlp_dims:
            x = nn.Dense(
                dim, dtype=dt,
                kernel_init=nn.initializers.glorot_normal(),
                bias_init=nn.initializers.normal(math.sqrt(1.0 / dim)))(x)
            x = nn.relu(x)
        embs = [e.astype(dt) for e in embedding_outputs]
        y = dot_interact(embs, x)
        for dim in cfg.top_mlp_dims[:-1]:
            y = nn.Dense(
                dim, dtype=dt,
                kernel_init=nn.initializers.glorot_normal(),
                bias_init=nn.initializers.normal(math.sqrt(1.0 / dim)))(y)
            y = nn.relu(y)
        y = nn.Dense(
            cfg.top_mlp_dims[-1], dtype=jnp.float32,
            kernel_init=nn.initializers.glorot_normal(),
            bias_init=nn.initializers.normal(
                math.sqrt(1.0 / cfg.top_mlp_dims[-1])))(y)
        return y


class DLRM:
    """Full model: local (single-device) embedding tables + DLRMDense.

    For the distributed version, pair :class:`DLRMDense` with
    :class:`~distributed_embeddings_tpu.parallel.DistributedEmbedding` over
    ``config.embedding_configs()`` (see ``examples/dlrm/main.py`` here and in
    the reference).
    """

    def __init__(self, config: DLRMConfig):
        self.config = config
        self.dense = DLRMDense(config)

    def init(self, key) -> dict:
        kt, kd = jax.random.split(key)
        cfg = self.config
        tables = []
        for i, size in enumerate(cfg.table_sizes):
            tables.append(dlrm_initializer(size)(
                jax.random.fold_in(kt, i), (size, cfg.embedding_dim)))
        dense_params = self.dense.init(
            kd,
            jnp.zeros((2, cfg.num_numerical_features), jnp.float32),
            [jnp.zeros((2, cfg.embedding_dim), jnp.float32)
             for _ in cfg.table_sizes])
        return {"tables": tables, "dense": dense_params}

    def apply(self, params, numerical_features, categorical_features):
        embs = [jnp.take(t, ids.reshape(-1), axis=0)
                for t, ids in zip(params["tables"], categorical_features)]
        return self.dense.apply(params["dense"], numerical_features, embs)


def bce_with_logits(logits: jax.Array, labels: jax.Array) -> jax.Array:
    """Mean binary cross-entropy from logits (reference uses
    ``tf.keras.losses.BinaryCrossentropy(from_logits=True)``,
    ``examples/dlrm/main.py:198-199``)."""
    logits = logits.reshape(-1)
    labels = labels.reshape(-1).astype(logits.dtype)
    return jnp.mean(jnp.clip(logits, 0) - logits * labels +
                    jnp.log1p(jnp.exp(-jnp.abs(logits))))

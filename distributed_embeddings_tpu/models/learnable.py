"""A planted-signal recommender task that DLRM can provably learn.

The reference publishes trained quality (AUC 0.80248/0.80262 on Criteo,
``examples/dlrm/README.md:7-8``) as its end-to-end evidence that the stack
learns. Criteo itself is not bundled here, so this module plants a
DLRM-shaped signal in synthetic data instead:

* every categorical id carries a hidden scalar preference
  ``s_f[id] ~ N(0, 1)``;
* the click logit mixes PAIRWISE interactions — exactly what DLRM's
  dot-interaction models (``models/dlrm.py:dot_interact``; reference
  ``examples/dlrm/utils.py:92-113``) — with a linear numerical term:
  ``logit = scale * (sum over pairs (2k, 2k+1) of s[2k][i]*s[2k+1][j])
  + w . x_num + bias``;
* labels draw ``Bernoulli(sigmoid(logit))``.

A model that learns nothing scores AUC 0.5 on held-out draws; the Bayes
ceiling is well above 0.8 for the default scale. Used by the convergence
bench (``bench.py``) and the slow convergence test.
"""

from __future__ import annotations

from typing import List, Sequence, Tuple

import numpy as np


class LearnableClicks:
    """Planted-signal synthetic CTR task.

    Args:
      table_sizes: vocab per categorical feature (pairs ``(2k, 2k+1)``
        interact; an odd trailing feature is noise).
      num_numerical: dense feature count (linear signal).
      seed: ground-truth seed (fixed per task instance).
      scale: interaction strength; higher = more separable.
    """

    def __init__(self, table_sizes: Sequence[int], num_numerical: int = 13,
                 seed: int = 0, scale: float = 1.0):
        self.table_sizes = [int(s) for s in table_sizes]
        self.num_numerical = int(num_numerical)
        self.scale = float(scale)
        rng = np.random.default_rng(seed)
        self._scores = [rng.normal(size=s).astype(np.float32)
                        for s in self.table_sizes]
        self._wnum = rng.normal(size=num_numerical).astype(np.float32) * 0.3
        self._bias = 0.0

    def sample(self, rng: np.random.Generator, batch: int
               ) -> Tuple[np.ndarray, List[np.ndarray], np.ndarray]:
        """One batch ``(numerical [B,F] f32, cats list of [B] i32,
        labels [B,1] f32)``."""
        cats = [rng.integers(0, s, size=batch).astype(np.int32)
                for s in self.table_sizes]
        num = rng.normal(size=(batch, self.num_numerical)).astype(np.float32)
        logit = num @ self._wnum + self._bias
        for k in range(0, len(cats) - 1, 2):
            logit = logit + self.scale * (
                self._scores[k][cats[k]] * self._scores[k + 1][cats[k + 1]])
        p = 1.0 / (1.0 + np.exp(-logit))
        labels = (rng.random(batch) < p).astype(np.float32)[:, None]
        return num, cats, labels


def train_dlrm_convergence(task: LearnableClicks, *, world_size: int = 1,
                           mesh=None, steps: int = 360, batch: int = 8192,
                           embedding_dim: int = 16, lr_schedule=0.01,
                           param_dtype=None, eval_n: int = 16384,
                           seed: int = 0, optimizer: str = "adam",
                           dense_lr=None, emb_init_scale=None):
    """Train DLRM on ``task`` through the FULL hybrid path and return
    ``(auc_start, auc_mid, auc_end)`` on a held-out draw.

    The one convergence driver shared by the bench (single chip) and the
    slow tests (8-device CPU mesh) — sparse embedding optimizer, optax
    dense side, eval via :func:`~..parallel.make_hybrid_eval_step` +
    exact AUC.

    ``optimizer="adam"`` (default): :class:`~..parallel.SparseAdam` +
    ``optax.adam`` — the historical capture. ``optimizer="sgd"``:
    :class:`~..parallel.SparseSGD` + ``optax.sgd``, the reference's
    flagship recipe (its DLRM trains with plain SGD lr=24 to AUC
    0.80248) and the ROADMAP 1 diagnostic subject: under the default
    DLRM table init (uniform ``±1/sqrt(vocab)`` ≈ ±0.022 at vocab 2000)
    the pairwise-product signal puts SGD at a saddle — gradients w.r.t.
    one table's rows are proportional to the OTHER table's tiny rows, so
    escape is multiplicative with rate ~ ``lr * |e|^2`` and lr=0.01
    learns only the linear numerical part (AUC ~0.636). Raising the
    embedding lr toward the reference's recipe (or the init scale via
    ``emb_init_scale``, which multiplies the default initializer)
    restores convergence; see ``docs/perf_tpu.md`` Round 9 for the
    measured (lr, init) matrix.

    ``dense_lr`` decouples the dense side's lr when the embedding lr is
    cranked SGD-style (defaults to ``lr_schedule``)."""
    import jax
    import jax.numpy as jnp
    import optax

    from ..parallel import (DistributedEmbedding, SparseAdam, SparseSGD,
                            init_hybrid_state, make_hybrid_eval_step,
                            make_hybrid_train_step)
    from ..utils import binary_auc
    from .dlrm import DLRMConfig, DLRMDense, bce_with_logits

    cfg = DLRMConfig(table_sizes=task.table_sizes,
                     embedding_dim=embedding_dim,
                     num_numerical_features=task.num_numerical,
                     bottom_mlp_dims=[2 * embedding_dim, embedding_dim],
                     top_mlp_dims=[64, 32, 1])
    emb_configs = cfg.embedding_configs()
    if emb_init_scale is not None:
        def scaled(base, s=float(emb_init_scale)):
            return lambda key, shape, dtype=jnp.float32: (
                s * base(key, shape, dtype))
        for c in emb_configs:
            c["embeddings_initializer"] = scaled(
                c["embeddings_initializer"])
    de = DistributedEmbedding(emb_configs,
                              world_size=world_size,
                              strategy="memory_balanced")
    dense = DLRMDense(cfg)
    dp = dense.init(
        jax.random.key(seed),
        jnp.zeros((2, task.num_numerical), jnp.float32),
        [jnp.zeros((2, embedding_dim), jnp.float32)
         for _ in task.table_sizes])
    if dense_lr is None:
        dense_lr = lr_schedule
    if optimizer == "adam":
        tx = optax.adam(dense_lr)
        emb_opt = SparseAdam()
    elif optimizer == "sgd":
        tx = optax.sgd(dense_lr)
        emb_opt = SparseSGD()
    elif optimizer == "mixed":
        # dense Adam + embedding SparseSGD: isolates whether the SPARSE
        # path learns under plain SGD when the dense half is not the
        # bottleneck — the ROADMAP 1 control that separates "sparse-path
        # defect" from "task conditioning starves the whole model"
        tx = optax.adam(dense_lr)
        emb_opt = SparseSGD()
    else:
        raise ValueError(f"optimizer must be 'adam' | 'sgd' | 'mixed', "
                         f"got {optimizer!r}")

    def loss_fn(d, outs, batch_):
        num, y = batch_
        return bce_with_logits(dense.apply(d, num, outs), y)

    state = init_hybrid_state(
        de, emb_opt, dp, tx, jax.random.key(seed + 1), mesh=mesh,
        **({"dtype": param_dtype} if param_dtype is not None else {}))
    # convergence probe, not a training loop: keep the 2-tuple step
    # contract even when the environment sets DETPU_OBS=1
    step = make_hybrid_train_step(de, loss_fn, tx, emb_opt, mesh=mesh,
                                  lr_schedule=lr_schedule,
                                  with_metrics=False)
    eval_fn = make_hybrid_eval_step(
        de, lambda d, outs, num: jax.nn.sigmoid(dense.apply(d, num, outs)),
        mesh=mesh)

    def put(x):
        if mesh is None:
            return jnp.asarray(x)
        from jax.sharding import NamedSharding, PartitionSpec as P
        return jax.device_put(jnp.asarray(x),
                              NamedSharding(mesh, P(de.axis_name)))

    ev_num, ev_cats, ev_y = task.sample(np.random.default_rng(999), eval_n)
    ev_num = put(ev_num)
    ev_cats = [put(c) for c in ev_cats]

    def auc(st):
        return float(binary_auc(ev_y, np.asarray(eval_fn(st, ev_cats,
                                                         ev_num))))

    auc0 = auc(state)
    rng = np.random.default_rng(seed + 7)
    mid = None
    for i in range(steps):
        num, cats, y = task.sample(rng, batch)
        _, state = step(state, [put(c) for c in cats],
                        (put(num), put(y)))
        if i == steps // 3:
            mid = auc(state)
    return auc0, mid, auc(state)

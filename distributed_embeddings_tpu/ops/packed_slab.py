"""Lane-packed slab layout for narrow embedding tables.

XLA's TPU row gather/scatter has a fast path when rows are full 128-lane
tiles: measured on v5e, a random 2M-row gather from a ``[2M, 128]`` table
runs at ~10 ns/row and scatter-add at ~15 ns/row, while the same gather from
a ``[8M, 16]`` table costs ~22 ns/row and scatter-add ~100 ns/row (the
sub-tile rows take a serialized path; see ``docs/perf_tpu.md``). The
reference meets the same hardware reality on GPUs with width-specialized
kernels (``cc/kernels/embedding_lookup_kernels.cu:397-453`` switches tile
shapes by power-of-2 width).

Here narrow tables pack ``p = 128 // width`` logical rows into each 128-lane
physical row:

* logical row ``L`` lives at physical row ``L // p``, lanes
  ``[(L % p) * w, (L % p + 1) * w)``;
* gathers fetch physical rows and extract lanes with a vectorized select;
* scatters expand ``[n, w]`` update rows into lane-placed ``[n, 128]`` rows
  and hit the full-tile scatter path — lane-disjoint expansion keeps
  duplicate handling and per-row optimizer semantics exact (different
  logical rows of one physical row touch disjoint lanes).

Tables with ``width >= 128`` keep their natural layout (``p == 1``).
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..utils import envvars

LANES = 128

# Debug switch for lane extraction in :func:`packed_gather`. The default
# one-hot contraction is the fastest form measured, but 0*NaN=NaN means one
# non-finite table row contaminates gathers of the other p-1 logical rows
# sharing its physical row, which muddies blast-radius diagnosis of a
# divergence. Setting this True (or env DETPU_DEBUG_LANE_EXTRACT=1) swaps in
# a where/select chain that touches only the addressed lane, isolating
# non-finite rows exactly. Slower (~1.8x on the extract step) — debugging
# only, never needed for training health.
# int() parse kept deliberately loud: a debug knob set to a typo ("false",
# "off") must fail at import, not silently flip the ~1.8x-slower extract
# path on and surface as an unexplained bench regression
DEBUG_LANE_EXTRACT = bool(int(envvars.get("DETPU_DEBUG_LANE_EXTRACT")))


def pack_factor(width: int) -> int:
    """Logical rows per physical row: ``floor(128/w)`` for narrow tables,
    1 for ``w >= 128`` (already full tiles)."""
    return max(1, LANES // int(width))


def phys_width(width: int) -> int:
    """Physical row width: 128 lanes when packed, the natural width when
    ``p == 1`` (w >= 128)."""
    return LANES if pack_factor(width) > 1 else int(width)


def align_rows(rows: int, width: int) -> int:
    """Logical row count rounded up to a physical-row boundary (tables are
    laid out at physical boundaries so they never share a physical row)."""
    p = pack_factor(width)
    return -(-int(rows) // p) * p


def packed_shape(rows_aligned: int, width: int) -> Tuple[int, int]:
    """Physical ``(rows, cols)`` of a packed buffer holding ``rows_aligned``
    (already aligned) logical rows."""
    p = pack_factor(width)
    assert rows_aligned % p == 0
    return rows_aligned // p, phys_width(width)


def pack_rows_np(chunk: np.ndarray, width: int) -> np.ndarray:
    """Host-side pack of ``[n, w]`` logical rows (n a multiple of p) into
    ``[n/p, phys_width]`` physical rows."""
    p = pack_factor(width)
    if p == 1:
        return chunk
    n = chunk.shape[0]
    assert n % p == 0, (n, p)
    out = np.zeros((n // p, LANES), chunk.dtype)
    out[:, :p * width] = chunk.reshape(n // p, p * width)
    return out


def unpack_rows_np(phys: np.ndarray, width: int) -> np.ndarray:
    """Host-side inverse of :func:`pack_rows_np`: ``[m, phys_width]`` →
    ``[m*p, w]`` logical rows."""
    p = pack_factor(width)
    if p == 1:
        return phys
    m = phys.shape[0]
    return phys[:, :p * width].reshape(m * p, width)


def pack_rows(x: jax.Array, width: int) -> jax.Array:
    """Device-side :func:`pack_rows_np`: ``[n, w]`` (n a multiple of p) →
    ``[n/p, phys_width]``."""
    p = pack_factor(width)
    if p == 1:
        return x
    n = x.shape[0]
    assert n % p == 0, (n, p)
    out = x.reshape(n // p, p * width)
    pad = LANES - p * width
    if pad:
        out = jnp.concatenate(
            [out, jnp.zeros((n // p, pad), x.dtype)], axis=1)
    return out


@jax.named_scope("detpu/packed_gather")
def packed_gather(slab: jax.Array, logical_ids: jax.Array,
                  width: int) -> jax.Array:
    """Gather logical rows from a packed slab: ``[..., w]`` for any id
    shape. Fetches full physical rows (fast path) and lane-extracts."""
    p = pack_factor(width)
    if p == 1:
        return jnp.take(slab, logical_ids, axis=0, mode="clip")
    flat = logical_ids.reshape(-1)
    rows = jnp.take(slab, flat // p, axis=0, mode="clip")  # [n, LANES]
    lane = (flat % p).astype(jnp.int32)
    # One-hot lane contraction: measured faster than a p-term select chain
    # (W=8, 2M rows: 25.9 ms at HIGHEST precision / 25.6 at default, vs
    # 45.3 for the chain), a where-mask sum (50.7) and take_along_axis
    # (56.1). HIGHEST precision keeps f32 gathers bit-exact
    # (TPU default matmul precision would truncate operands to ~bf16); it
    # measures as fast as default here. Caveat: 0*inf=NaN means a
    # non-finite value in one lane contaminates gathers of the other p-1
    # logical rows sharing its physical row — a debugging (not training-
    # health) concern, since any non-finite table row means training is
    # already broken.
    r3 = rows[:, :p * width].reshape(-1, p, width)
    if DEBUG_LANE_EXTRACT:
        # NaN-isolating select chain: only the addressed lane is read, so a
        # corrupted row cannot poison its physical-row neighbours.
        out = r3[:, 0, :]
        for j in range(1, p):
            out = jnp.where((lane == j)[:, None], r3[:, j, :], out)
    else:
        oh = jax.nn.one_hot(lane, p, dtype=rows.dtype)
        out = jnp.einsum("np,npw->nw", oh, r3,
                         precision=jax.lax.Precision.HIGHEST)
    return out.reshape(*logical_ids.shape, width)


@jax.named_scope("detpu/expand_update_rows")
def expand_update_rows(vals: jax.Array, logical_ids: jax.Array,
                       width: int) -> Tuple[jax.Array, jax.Array]:
    """Turn ``[n, w]`` update rows at logical ids into ``(phys_ids,
    [n, phys_width])`` lane-placed rows for a full-tile scatter. Out-of-range
    logical ids stay out of range physically (``L // p`` of a sentinel past
    the aligned capacity lands past the physical capacity)."""
    p = pack_factor(width)
    if p == 1:
        return logical_ids, vals
    lane = (logical_ids % p).astype(jnp.int32)
    zero = jnp.zeros_like(vals)
    expanded = jnp.concatenate(
        [jnp.where((lane == j)[:, None], vals, zero) for j in range(p)],
        axis=1)
    pad = LANES - p * width
    if pad:
        expanded = jnp.concatenate(
            [expanded, jnp.zeros((vals.shape[0], pad), vals.dtype)], axis=1)
    return logical_ids // p, expanded


def lane_one_hot(logical_ids: jax.Array, width: int,
                 dtype=jnp.float32) -> Optional[jax.Array]:
    """Compact ``[n, p]`` one-hot of each update row's lane slot
    (``p = 128 // width``), marking which packed *logical* row an expanded
    update row addresses.

    Needed by stateful-moment optimizers (momentum/Adam): their update is
    nonzero wherever *state* is nonzero, so after duplicate physical rows are
    summed, lanes belonging to packed *neighbour* logical rows must be
    distinguishable from genuinely-touched lanes — a zero gradient value
    cannot encode that (a touched row may legitimately have zero gradient).
    Kept ``p`` columns wide (not ``phys_width``) so riding it through the
    dedup sort costs ``p/128`` of the value payload, and expanded to lanes
    only after deduplication (:func:`expand_lane_mask`). Returns ``None``
    for ``width >= 128`` (one logical row per physical row; every summed
    row was genuinely touched)."""
    p = pack_factor(width)
    if p == 1:
        return None
    return jax.nn.one_hot((logical_ids % p).astype(jnp.int32), p, dtype=dtype)


def expand_lane_mask(narrow: jax.Array, width: int,
                     phys_w: Optional[int] = None) -> jax.Array:
    """Expand a deduped ``[n, p]`` lane mask to lane-placed ``[n,
    phys_width]`` booleans: column ``j`` of the narrow mask covers lanes
    ``[j*width, (j+1)*width)`` — the same placement
    :func:`expand_update_rows` gives the update values."""
    p = narrow.shape[1]
    out = jnp.repeat(narrow > 0, width, axis=1)
    target = phys_w if phys_w is not None else LANES
    pad = target - p * width
    if pad:
        out = jnp.concatenate(
            [out, jnp.zeros((narrow.shape[0], pad), bool)], axis=1)
    return out

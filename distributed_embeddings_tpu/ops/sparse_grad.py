"""Sparse (IndexedSlices-style) embedding gradients, TPU-native.

The reference's backward kernel (``cc/kernels/embedding_lookup_kernels.cu:457-629``)
turns per-output-row gradients into ``(unique_ids, unique_grad)`` via CUB
radix-sort + unique-by-key, wrapped as ``tf.IndexedSlices``
(``python/ops/embedding_lookup_ops.py:105-122``). On TPU we reproduce the same
dataflow with static shapes:

* :func:`combiner_grad_values` — expand a ``[batch, width]`` output cotangent
  to per-id row gradients (the ``OffsetToWeightsAndRowId`` + weighted-reuse
  trick of the reference backward, ``.cu:493-494,539-627``).
* :func:`dedup_sparse_grad` — sort ids, segment-sum duplicate rows; output
  buffers keep the input capacity (the dynamic ``num_unique`` of the reference,
  ``.cu:519-528``, becomes a pad-id sentinel + ``mode='drop'`` scatters).

Deduplication is only *required* by optimizers whose update is nonlinear in the
gradient (Adagrad/Adam); plain SGD can scatter-add duplicates directly — the
sparse optimizers declare that via ``needs_dedup`` (:mod:`..parallel.optimizers`)
and the SGD paths skip this pass entirely (``DETPU_SGD_DEDUP=1`` forces it
back on for A/B). :func:`dedup_sparse_grad` runs under the ``detpu/dedup``
named scope so the HLO pass census (:mod:`..analysis.hlo_census`) can
attribute — and budget — its sort/segment-sum passes per compiled program.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from .embedding_lookup import ragged_row_ids


def combiner_grad_values(out_grad: jax.Array, row_splits: jax.Array,
                         capacity: int, combiner: str) -> jax.Array:
    """Per-id gradient rows for a CSR lookup-with-combiner.

    Args:
      out_grad: ``[batch, width]`` cotangent of the combined output.
      row_splits: ``[batch+1]`` CSR offsets of the forward input.
      capacity: static id capacity of the forward input.
      combiner: ``'sum'`` or ``'mean'``.

    Returns:
      ``[capacity, width]`` gradient for each id position (zeros at padding).
    """
    seg = ragged_row_ids(row_splits, capacity)
    vals = jnp.take(out_grad, seg, axis=0, mode="fill", fill_value=0)
    if combiner == "mean":
        counts = (row_splits[1:] - row_splits[:-1]).astype(out_grad.dtype)
        inv = 1.0 / jnp.maximum(counts, 1)
        per_id = jnp.take(inv, seg, mode="fill", fill_value=0)
        vals = vals * per_id[:, None]
    return vals


def dedup_sparse_grad(ids: jax.Array, grads: jax.Array, *,
                      pad_id: int,
                      valid: Optional[jax.Array] = None,
                      max_unique: Optional[int] = None
                      ) -> Tuple[jax.Array, jax.Array]:
    """Sort ids and sum gradient rows of duplicates.

    Args:
      ids: ``[n]`` int row ids; entries equal to (or marked invalid via
        ``valid=False``) are treated as padding.
      grads: ``[n, width]`` per-id gradient rows.
      pad_id: sentinel for padding/unused output slots. Must be >= vocab so
        that ``.at[ids].op(..., mode='drop')`` ignores those rows.
      valid: optional ``[n]`` bool mask; invalid entries are replaced by
        ``pad_id`` before sorting.
      max_unique: optional static bound on the number of distinct values in
        ``ids`` (including the sentinel) — the **vocab bound**: distinct row
        ids can never exceed the table's row capacity + 1. Output buffers
        shrink to ``U = min(n, max_unique)``, shrinking every downstream
        per-unique-row op with them — a multiplicative win whenever the
        batch id stream is much longer than the vocab (small tables under
        power-law traffic: tiny-zoo w=8 is a 2.7M-id stream over ~60k rows).
        Passing a bound smaller than the true distinct count silently drops
        the largest ids' gradients — callers must guarantee it.

    Returns:
      ``(unique_ids [U], unique_grads [U, width])``: position
      ``k < num_unique`` holds the k-th smallest unique id and the sum of
      its gradient rows; positions past that hold ``pad_id`` and garbage
      (callers scatter with ``mode='drop'``).
    """
    with jax.named_scope("detpu/dedup"):
        return _dedup_sparse_grad(ids, grads, pad_id, valid, max_unique)


def _dedup_sparse_grad(ids, grads, pad_id, valid, max_unique):
    n = ids.shape[0]
    u = n if max_unique is None else min(n, int(max_unique))
    if valid is not None:
        ids = jnp.where(valid, ids, pad_id)
    sorted_ids, perm = jax.lax.sort_key_val(ids, jnp.arange(n, dtype=jnp.int32))
    sorted_grads = jnp.take(grads, perm, axis=0)
    boundary = jnp.concatenate(
        [jnp.ones((1,), jnp.int32),
         (sorted_ids[1:] != sorted_ids[:-1]).astype(jnp.int32)])
    seg = jnp.cumsum(boundary) - 1  # [n], segment index per sorted row
    # seg ascends by construction; declaring it buys the sorted-scatter fast
    # path (measured 1.8x on v5e, docs/perf_tpu.md)
    unique_grads = jnp.zeros((u,) + grads.shape[1:], grads.dtype
                             ).at[seg].add(sorted_grads, mode="drop",
                                           indices_are_sorted=True)
    unique_ids = jnp.full((u,), pad_id, dtype=ids.dtype
                          ).at[seg].set(sorted_ids, mode="drop",
                                        indices_are_sorted=True)
    # Padding ids sort last and get their own segment(s) holding pad_id:
    # either past u (dropped here) or dropped downstream by the same
    # out-of-range rule the scatters rely on.
    return unique_ids, unique_grads

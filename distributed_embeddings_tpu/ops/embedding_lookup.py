"""Functional embedding lookup with multi-hot combiners, TPU-native.

This is the TPU equivalent of the reference's op layer
(``distributed_embeddings/python/ops/embedding_lookup_ops.py:37-102`` plus the
CUDA kernels behind it, ``cc/kernels/embedding_lookup_kernels.cu``). Design
differences are deliberate:

* **Static shapes.** TF ragged/sparse tensors carry dynamic nnz; XLA on TPU
  wants static shapes. :class:`Ragged` and :class:`SparseIds` carry a
  compile-time capacity (``values.shape[0]``); the *actual* number of ids is
  ``row_splits[-1]`` (traced). Padding positions are dropped by routing them to
  an out-of-range segment and scattering with ``mode="drop"``.
* **No custom gradient op needed for the baseline.** ``jnp.take`` +
  ``segment_sum`` differentiate to a scatter-add, which is exactly the
  reference backward's semantics (``cc/kernels/embedding_lookup_kernels.cu:457-629``
  produces (unique_ids, unique_grad) IndexedSlices). The sparse/deduplicated
  gradient path used by the distributed trainer lives in
  :mod:`distributed_embeddings_tpu.ops.sparse_grad`.
* **``row_to_split``** converts COO row indices to CSR offsets with a
  vectorized ``searchsorted`` instead of the reference's per-thread binary
  search kernel (``cc/kernels/embedding_lookup_kernels.cu:331-350``) — on TPU
  this is a tiny fused op, not worth a kernel.
"""

from __future__ import annotations

from typing import Optional, Tuple, Union

import jax
import jax.numpy as jnp
from flax import struct


@struct.dataclass
class Ragged:
    """Static-capacity CSR ragged batch of ids.

    ``values[k]`` for ``k < row_splits[-1]`` are the ids; positions past that
    are padding (any value; they are ignored). ``row_splits`` has length
    ``batch_size + 1`` with ``row_splits[0] == 0``.

    This mirrors the (values, row_splits) encoding the reference feeds its
    variable-hotness kernel (``embedding_lookup_ops.py:79-80``), with the
    capacity made explicit so XLA sees a fixed shape.

    ``weights`` (optional, ``[capacity]`` float): per-id multipliers — the
    reference kernel's optional ``weights`` input
    (``cc/kernels/embedding_lookup_kernels.cu:52-55``). With a ``'mean'``
    combiner the weighted sum divides by the row's id COUNT (the kernel's
    semantics, ``.cu:220-222``), not by the weight sum.
    """

    values: jax.Array  # [capacity] int
    row_splits: jax.Array  # [batch_size + 1] int
    weights: Optional[jax.Array] = None  # [capacity] float

    @property
    def nrows(self) -> int:
        return self.row_splits.shape[0] - 1

    @property
    def capacity(self) -> int:
        return self.values.shape[0]

    @classmethod
    def from_lists(cls, rows, capacity: Optional[int] = None, dtype=jnp.int32,
                   weights=None) -> "Ragged":
        """Build from a python list of per-row id lists (test/data-pipeline
        helper); ``weights`` takes the same nested-list shape."""
        import numpy as np

        flat = [i for row in rows for i in row]
        splits = np.zeros(len(rows) + 1, dtype=np.int64)
        np.cumsum([len(r) for r in rows], out=splits[1:])
        cap = capacity if capacity is not None else max(len(flat), 1)
        if len(flat) > cap:
            raise ValueError(f"total nnz {len(flat)} exceeds capacity {cap}")
        vals = np.zeros(cap, dtype=np.int64)
        vals[: len(flat)] = flat
        warr = None
        if weights is not None:
            wflat = [w for row in weights for w in row]
            if len(wflat) != len(flat):
                raise ValueError("weights must mirror rows' nesting")
            wbuf = np.zeros(cap, dtype=np.float32)
            wbuf[: len(wflat)] = wflat
            warr = jnp.asarray(wbuf)
        return cls(values=jnp.asarray(vals, dtype=dtype),
                   row_splits=jnp.asarray(splits, dtype=dtype),
                   weights=warr)


@struct.dataclass
class SparseIds:
    """Static-capacity COO sparse batch of ids (reference: ``tf.SparseTensor`` path,
    ``embedding_lookup_ops.py:81-96``).

    ``indices[k] = (row, col)`` for the k-th id; rows must be sorted ascending
    (TF sparse tensors are ordered; same contract here). Padding rows use
    ``row >= dense_shape[0]``.
    """

    indices: jax.Array  # [capacity, 2] int
    values: jax.Array  # [capacity] int
    dense_shape: Tuple[int, int] = struct.field(pytree_node=False)
    weights: Optional[jax.Array] = None  # [capacity] float (see Ragged)

    @property
    def nrows(self) -> int:
        return self.dense_shape[0]


IdsLike = Union[jax.Array, Ragged, SparseIds]


def row_to_split(indices: jax.Array, dim_0: int, dtype=None) -> jax.Array:
    """COO row indices ``[nnz, 2]`` (or ``[nnz]``) → CSR ``row_splits [dim_0+1]``.

    TPU-native replacement for the reference's ``RowToSplit`` CUDA kernel
    (``cc/kernels/embedding_lookup_kernels.cu:331-350``): ``row_splits[i]`` is
    the number of entries with row id < i, found by vectorized binary search.
    Rows >= dim_0 (padding) land past the end and are excluded.
    """
    rows = indices[:, 0] if indices.ndim == 2 else indices
    if dtype is None:
        dtype = rows.dtype
    targets = jnp.arange(dim_0 + 1, dtype=rows.dtype)
    return jnp.searchsorted(rows, targets, side="left").astype(dtype)


def ragged_row_ids(row_splits: jax.Array, capacity: int) -> jax.Array:
    """Per-value row id for a CSR batch; padding positions get ``nrows`` (one
    past the last valid segment, so downstream scatters drop them).

    Equivalent of the reference's ``OffsetToWeightsAndRowId`` device function
    (``cc/kernels/embedding_lookup_kernels.cu:352-361``), minus the weights
    (see :func:`distributed_embeddings_tpu.ops.sparse_grad.combiner_grad_values`).

    Implementation: scatter a 1 at each row's *end* offset, then prefix-sum —
    ``seg[p] = #\\{rows ending at or before p\\}``. O(capacity) streaming work.
    The obvious ``searchsorted(row_splits, positions)`` form lowers to a
    per-position binary-search loop that measured **~1.0 s** at the DCNv2
    bench shapes (26 features x 256k positions) where this form runs the
    whole decode in ~15 ms — the single biggest ragged-path cost found in
    round 4 (docs/perf_tpu.md, phase table).
    """
    ends = row_splits[1:].astype(jnp.int32)
    marks = jnp.zeros((capacity + 1,), jnp.int32)
    # ends ascend (cumulative offsets): sorted-scatter fast path applies
    marks = marks.at[jnp.clip(ends, 0, capacity)].add(
        1, indices_are_sorted=True)
    return jnp.cumsum(marks[:capacity]).astype(row_splits.dtype)


@jax.named_scope("detpu/ragged_combine")
def _ragged_combine(params: jax.Array, values: jax.Array, row_splits: jax.Array,
                    combiner: str, weights: Optional[jax.Array]) -> jax.Array:
    """Fused gather + segment-reduce for CSR input. The XLA analogue of the
    reference's ``EmbeddingLookUpVariableHot`` kernel family
    (``cc/kernels/embedding_lookup_kernels.cu:175-330``)."""
    nrows = row_splits.shape[0] - 1
    capacity = values.shape[0]
    seg = ragged_row_ids(row_splits, capacity)
    # searchsorted(side='right') maps position 0 of an all-empty prefix to -1
    # only when row_splits[0] != 0; contract says row_splits[0] == 0 so seg>=0.
    gathered = jnp.take(params, values, axis=0, mode="clip")
    if weights is not None:
        gathered = gathered * weights[:, None].astype(gathered.dtype)
    out = jnp.zeros((nrows + 1, params.shape[1]), dtype=gathered.dtype)
    out = out.at[seg].add(gathered, mode="drop")
    out = out[:nrows]
    if combiner == "mean":
        counts = (row_splits[1:] - row_splits[:-1]).astype(out.dtype)
        out = out / jnp.maximum(counts, 1)[:, None]
    return out


def embedding_lookup(params: jax.Array, ids: IdsLike,
                     combiner: Optional[str] = None,
                     weights: Optional[jax.Array] = None) -> jax.Array:
    """Looks up (and optionally reduces) embedding rows for ``ids``.

    Behavioral parity with the reference dispatcher
    (``distributed_embeddings/python/ops/embedding_lookup_ops.py:37-102``):

    * ``combiner=None``: plain gather; output shape ``ids.shape + (width,)``.
      Only dense ``ids`` are supported without a combiner (the reference
      likewise routes combiner-less lookups to ``tf.nn.embedding_lookup``).
    * dense 2-D ``[batch, hotness]`` + combiner: reduce over hotness with
      ``'sum'`` or ``'mean'``; hotness 1 degenerates to a squeeze+gather.
    * :class:`Ragged` + combiner: CSR variable-hotness fused lookup-reduce.
    * :class:`SparseIds` + combiner: converted to CSR via :func:`row_to_split`.

    Args:
      params: ``[vocab, width]`` embedding matrix.
      ids: dense int array, :class:`Ragged`, or :class:`SparseIds`.
      combiner: ``None``, ``'sum'`` or ``'mean'``.
      weights: optional per-id multipliers (ragged/sparse paths only) matching
        ``ids.values``; the reference kernel's optional ``weights`` input
        (``cc/kernels/embedding_lookup_kernels.cu:52-55``).

    Returns:
      ``float`` array of embeddings, reduced over the hotness dimension when
      ``combiner`` is given.
    """
    if combiner not in (None, "sum", "mean"):
        raise ValueError(f"Unsupported combiner {combiner!r}")
    if combiner is None:
        if not isinstance(ids, jax.Array) and not hasattr(ids, "ndim"):
            raise ValueError("combiner=None requires dense ids")
        return jnp.take(params, ids, axis=0, mode="clip")

    if isinstance(ids, Ragged):
        if weights is None:
            weights = ids.weights
        return _ragged_combine(params, ids.values, ids.row_splits, combiner, weights)

    if isinstance(ids, SparseIds):
        if weights is None:
            weights = ids.weights
        splits = row_to_split(ids.indices, ids.dense_shape[0], dtype=ids.values.dtype)
        return _ragged_combine(params, ids.values, splits, combiner, weights)

    if ids.ndim != 2:
        raise ValueError(f"Only 2D dense input is supported with a combiner, got {ids.ndim}D")
    if ids.shape[1] == 1 and weights is None:
        return jnp.take(params, ids[:, 0], axis=0, mode="clip")
    gathered = jnp.take(params, ids, axis=0, mode="clip")  # [B, H, W]
    if weights is not None:
        gathered = gathered * weights[..., None].astype(gathered.dtype)
    if combiner == "sum":
        return jnp.sum(gathered, axis=1)
    return jnp.mean(gathered, axis=1)

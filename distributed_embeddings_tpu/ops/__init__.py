"""Embedding lookup ops (TPU-native equivalents of the reference custom-op layer).

The reference implements these as TensorFlow custom ops backed by CUDA kernels
(``distributed_embeddings/cc/ops/embedding_lookup_ops.cc:24-88``); here the
baseline is pure XLA (gather + segment-reduce, which XLA fuses well on TPU) with
Pallas kernels layered behind the same functional API.
"""

from .embedding_lookup import (
    Ragged,
    SparseIds,
    embedding_lookup,
    row_to_split,
    ragged_row_ids,
)
from .sparse_grad import (
    combiner_grad_values,
    dedup_sparse_grad,
)

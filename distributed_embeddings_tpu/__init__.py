"""distributed_embeddings_tpu — TPU-native distributed embedding framework.

A JAX/XLA/Pallas re-design of the capability surface of NVIDIA's
``distributed-embeddings`` (reference: ``distributed_embeddings/__init__.py:17-18``,
which exports ``embedding_lookup`` and ``__version__``): large-embedding
recommender training with hybrid model/data parallelism over a TPU mesh.
"""

from . import compat  # noqa: F401 - polyfills jax API gaps (older releases)
from .version import __version__
from .ops.embedding_lookup import (
    Ragged,
    SparseIds,
    embedding_lookup,
    row_to_split,
)

__all__ = [
    "__version__",
    "embedding_lookup",
    "row_to_split",
    "Ragged",
    "SparseIds",
    "AuditReport",
    "audit_train_step",
]

_ANALYSIS_EXPORTS = ("AuditReport", "audit_train_step")


def __getattr__(name):
    # the step auditor pulls in the whole parallel stack (flax/optax);
    # loaded lazily so `import distributed_embeddings_tpu` stays light
    if name in _ANALYSIS_EXPORTS:
        from . import analysis

        return getattr(analysis, name)
    raise AttributeError(
        f"module {__name__!r} has no attribute {name!r}")

"""Streaming vocab: frequency-gated admission and approximate-LFU
eviction for capacity-bounded dynamic embedding tables.

The reference library (and every static plan in this repo) assumes a
fixed ``[vocab, dim]`` table per feature — but production recommender
traffic is non-stationary: new users and items appear continuously, and
a static vocab either OOMs as it grows or silently degrades as unseen
ids collide. This module is the dynamic-table mode of
:class:`~.dist_embedding.DistributedEmbedding` (ROADMAP item 5, the
scenario-diversity flagship): external ids from an UNBOUNDED id space
are served out of a fixed-capacity slab, with three-state semantics per
id:

* **tracked** — every live id folds into a count-min sketch (the PR 5
  telemetry sketches of :mod:`~..analysis.telemetry`, reused verbatim as
  the admission oracle) and, until admitted, reads/trains a **shared
  hash bucket** row: cold and never-seen ids degrade gracefully into
  ``buckets`` shared rows instead of crashing, evicting hot rows, or
  silently clipping into a neighbour.
* **admitted** — once an id's sketch estimate crosses
  ``admit_min_count`` (``DETPU_ADMIT_MIN_COUNT``) it claims its
  direct-mapped slot (``hash(id) % capacity``). The claimed row is
  zeroed (fresh embedding) at the claim step and the id is served from
  it on every later occurrence.
* **evicted** — a claim on an occupied slot only succeeds when the
  incoming estimate beats the occupant's recorded frequency by
  ``evict_margin`` (``DETPU_EVICT_MARGIN``) — approximate LFU: the
  colder row loses. The evicted id transparently degrades back to its
  shared hash bucket (its next occurrence simply misses the slot map).

Everything runs INSIDE the jitted step: the slot map, frequency
estimates, and sketch are carried as donated pytree leaves (like the
telemetry state) and updated with pure, static-shaped jax ops — no host
round-trips, 0 steady-state recompiles (enforced by the existing
audits). All scatters that decide admission use associative
``max``-reductions with explicit tie-breaks, so the transition is
DETERMINISTIC even under duplicate batch ids — the property the
checkpoint-CRC-identity drills (``tools/check_streaming.py``,
``tests/test_streaming_checkpoint.py``) assert.

Table declaration: a config dict grows a ``"streaming"`` entry::

    {"input_dim": capacity + buckets, "output_dim": dim,
     "streaming": {"capacity": 1 << 16, "buckets": 512}}

``input_dim`` must equal ``capacity + buckets`` — the slab physically
holds the slots followed by the shared bucket rows, so every existing
subsystem (checkpoint streaming, plan audit, re-shard, HLO census)
prices and moves the dynamic table like any other table of that size.
Row/column-sliced streaming tables are rejected (a slot map cannot span
slices).

State is **part of the recoverable trajectory**: :func:`encode_state`
converts the carried (slab-row-space) state to a plan-agnostic
per-table form that ``utils.checkpoint.save_train_state(aux_states=)``
persists CRC-manifested inside the checkpoint, :func:`decode_state`
rebuilds it under the restoring model's plan (re-shard included), and
the resilient driver's generalized aux-rewind restores it from the SAME
ring candidate a rollback picks — an interrupted-and-resumed streaming
run is checkpoint-CRC-identical to an uninterrupted one.

Like :mod:`~..analysis.telemetry`, the math here is pure jax on state
the step already holds; the emission point is
:meth:`~.dist_embedding.DistributedEmbedding.forward_with_residuals`
(``streaming=``) and the threading lives in
:func:`~.trainer.make_hybrid_train_step` (``dynamic=``).
"""

from __future__ import annotations

from typing import Any, Dict, List, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..utils import envvars
from ..analysis.telemetry import cms_query, cms_update

#: free-slot marker in the carried slot map (fingerprints are >= 0)
SLOT_FREE = -1

# odd multipliers for the slot/bucket/fingerprint hashes — disjoint from
# the telemetry sketch's _MULTS so slot placement and sketch buckets
# decorrelate even for equal geometry
_H_SLOT = np.uint32(0x7FEB352D)
_H_BUCKET = np.uint32(0x846CA68B)
_H_FP = np.uint32(0x9E3779B1)
_H_SALT = np.uint32(0x85EBCA77)


class StreamingConfig(NamedTuple):
    """Static (trace-time) admission/eviction policy. Hashable so step
    builders can close over it; every field is a compile-time constant."""

    admit_min_count: int = 2   #: sketch estimate gating slot admission
    evict_margin: int = 1      #: incoming est must beat occupant freq by this
    depth: int = 4             #: admission-sketch rows (independent hashes)
    buckets: int = 4096        #: admission-sketch columns per row


def config_from_env() -> StreamingConfig:
    """The env-configured policy (``DETPU_ADMIT_MIN_COUNT`` /
    ``DETPU_EVICT_MARGIN`` / ``DETPU_ADMIT_SKETCH_DEPTH`` /
    ``DETPU_ADMIT_SKETCH_WIDTH``)."""
    return StreamingConfig(
        admit_min_count=max(1, envvars.get_int("DETPU_ADMIT_MIN_COUNT")),
        evict_margin=max(0, envvars.get_int("DETPU_EVICT_MARGIN")),
        depth=max(1, envvars.get_int("DETPU_ADMIT_SKETCH_DEPTH")),
        buckets=max(2, envvars.get_int("DETPU_ADMIT_SKETCH_WIDTH")))


def resolve_config(dynamic) -> Optional[StreamingConfig]:
    """Normalize a step builder's ``dynamic=`` argument: ``None``/
    ``False`` is off, ``True`` is the env-configured policy, a
    :class:`StreamingConfig` passes through. Like ``telemetry=``, this is
    an EXPLICIT opt-in at step-build time — it changes the step's call
    arity, so no env variable may flip it under an unsuspecting call
    site."""
    if dynamic is None or dynamic is False:
        return None
    if dynamic is True:
        return config_from_env()
    if isinstance(dynamic, StreamingConfig):
        return dynamic
    raise TypeError(
        f"dynamic= takes None | bool | StreamingConfig, got "
        f"{type(dynamic).__name__}")


# ------------------------------------------------------------------- state


def _wkey(width: int) -> str:
    return f"w{width}"


def streaming_widths(de) -> List[int]:
    """Widths whose slab holds at least one streaming table."""
    out = set()
    for tid, _ in de.streaming_tables.items():
        out.add(int(de.strategy.global_configs[tid]["output_dim"]))
    return sorted(out)


def init_streaming(de, config: Optional[StreamingConfig] = None,
                   mesh=None) -> Dict[str, Any]:
    """Fresh streaming-vocab state for ``de``: a plain-dict pytree whose
    leaves all carry a leading ``[world]`` axis (``local_state`` squeezes
    it inside the step, mirroring the slab/telemetry convention), laid
    out over ``mesh`` when given.

    Per width slab with a streaming table: the slot map (31-bit id
    fingerprint per logical slab row; :data:`SLOT_FREE` = free), the
    per-slot frequency record (the occupant's sketch estimate at its
    last admission/hit), and the admission count-min sketch. Top-level:
    the step counter and the cumulative admission / eviction /
    bucket-service / hit counters (the step metrics integrate these)."""
    if not de.streaming_tables:
        raise ValueError(
            "init_streaming: no table declares a 'streaming' config "
            "entry — nothing to carry")
    config = config or config_from_env()
    world = de.world_size

    def stacked(shape, dtype, fill=0):
        return jnp.full((world,) + shape, fill, dtype)

    state: Dict[str, Any] = {
        "steps": stacked((1,), jnp.int32),
        "admitted": stacked((1,), jnp.float32),
        "evicted": stacked((1,), jnp.float32),
        "bucket_ids": stacked((1,), jnp.float32),
        "hit_ids": stacked((1,), jnp.float32),
    }
    for w in streaming_widths(de):
        rows = de.rows_cap[w]
        state[_wkey(w)] = {
            "slot_fp": stacked((rows,), jnp.int32, SLOT_FREE),
            "slot_freq": stacked((rows,), jnp.int32),
            "cms": stacked((config.depth, config.buckets), jnp.int32),
        }
    if mesh is not None:
        sharding = jax.sharding.NamedSharding(
            mesh, jax.sharding.PartitionSpec(de.axis_name))
        state = jax.tree.map(lambda a: jax.device_put(a, sharding), state)
    return state


def local_state(state):
    """Strip the leading world axis (``[1, ...]`` per-device leaves
    inside ``shard_map`` / world 1) — the streaming twin of
    ``de.local_view``."""
    return jax.tree.map(lambda v: v[0], state)


def stacked_state(state):
    """Re-add the leading world axis for ``P(axis)`` out_specs."""
    return jax.tree.map(lambda v: v[None], state)


def fresh_like(state):
    """A pristine state with the SAME structure/shapes/placement as
    ``state`` — the aux-rewind fallback when a rollback candidate
    predates streaming aux persistence (slot maps then warm up again,
    which only degrades ids back to their buckets, never corrupts)."""
    def leaf(path, v):
        fill = SLOT_FREE if path[-1].key == "slot_fp" else 0
        return jnp.full(v.shape, fill, v.dtype)

    return jax.tree_util.tree_map_with_path(leaf, state)


# ------------------------------------------------------------- hash helpers


def _mix(ids: jax.Array, salt: jax.Array, mult: np.uint32) -> jax.Array:
    """xxhash-style avalanche of ``ids`` salted per-position (the table
    id, so one table's stream never aliases another's) — uint32 output.
    64-bit ids fold their high word in first: a bare uint32 cast would
    make ids congruent mod 2^32 alias COMPLETELY (same slot, same
    fingerprint, same sketch cell) — systematic identity collapse for
    structured ids carrying type/hash bits up top, not the documented
    ~2^-31 fingerprint collision."""
    if jnp.dtype(ids.dtype).itemsize > 4:
        ids = ids ^ (ids >> 32)
    h = ids.astype(jnp.uint32) ^ (salt.astype(jnp.uint32) * _H_SALT)
    h = h * mult
    h = h ^ (h >> 15)
    h = h * np.uint32(0x2C1B3C6D)
    h = h ^ (h >> 13)
    return h


def _fingerprint(ext: jax.Array, tid: jax.Array) -> jax.Array:
    """31-bit non-negative id fingerprint stored in the slot map. Two
    distinct external ids collide with probability ~2^-31 per slot — an
    approximate structure by design (like the sketch it gates on)."""
    return (_mix(ext, tid, _H_FP) >> np.uint32(1)).astype(jnp.int32)


def sketch_key(ext: jax.Array, tid: jax.Array) -> jax.Array:
    """Non-negative int32 count-min key of an external id, salted by its
    (plan-invariant) table id — the admission oracle's input. Exposed so
    tests can query the sketch the way the step does."""
    return _fingerprint(ext, tid)


# --------------------------------------------------------- the core update


class WidthStream(NamedTuple):
    """One width slab's flattened id stream for one step (built by the
    executor's plan traversal): every leaf ``[n]`` over the positions of
    that width's streaming-table slots."""

    ext: jax.Array    #: raw external ids (pre-remap region values)
    live: jax.Array   #: bool — position holds a real id on a live slot
    cap: jax.Array    #: per-position slot capacity of the owning table
    nbuckets: jax.Array  #: per-position shared-bucket count
    tid: jax.Array    #: per-position global table id (the hash salt)
    roff: jax.Array   #: per-position table row offset inside the slab


def remap_width(wstate: Dict[str, jax.Array], stream: WidthStream,
                rows_cap: int, config: StreamingConfig,
                update: bool = True):
    """Serve one width slab's external-id stream out of the slot map and
    (``update=True``) stage this step's admission/eviction transitions.

    Returns ``(local_rows, pending)`` where ``local_rows [n]`` is the
    table-LOCAL row each position reads (slot for map hits, shared
    bucket otherwise; positions with ``live=False`` return the raw
    value unchanged), and ``pending`` is ``None`` for read-only remaps
    or ``(new_wstate, scrub_rows, stats)``:

    * ``new_wstate`` — the updated slot map / freq / sketch (NOT yet
      gated by the nan-guard verdict; :func:`commit` selects),
    * ``scrub_rows [n]`` — logical slab rows claimed this step (the
      rows :func:`commit` zeroes so admitted ids train from fresh
      embeddings), ``rows_cap`` sentinel elsewhere — at most one live
      entry per claimed row (deterministic tie-broken),
    * ``stats`` — per-step scalar counts (admitted, evicted,
      bucket_ids, hit_ids).

    Freshly admitted ids are still served from their bucket THIS step
    (their slot row is only zeroed at commit, after the optimizer
    scatter); from the next occurrence they hit the slot map. The
    decision chain uses only ``max``-scatters with explicit
    estimate-then-fingerprint-then-position tie-breaks, so duplicate
    batch ids and colliding claims resolve deterministically.
    """
    ext = stream.ext.reshape(-1)
    live = stream.live.reshape(-1)
    cap = stream.cap.reshape(-1).astype(jnp.int32)
    nb = stream.nbuckets.reshape(-1).astype(jnp.int32)
    tid = stream.tid.reshape(-1).astype(jnp.int32)
    roff = stream.roff.reshape(-1).astype(jnp.int32)
    n = ext.shape[0]
    live = live & (ext >= 0)

    key = sketch_key(ext, tid)
    cms = wstate["cms"]
    if update:
        cms = cms_update(cms, key, live)
    est = cms_query(cms, key)

    cap_s = jnp.maximum(cap, 1)
    nb_s = jnp.maximum(nb, 1)
    slot = (_mix(ext, tid, _H_SLOT)
            % cap_s.astype(jnp.uint32)).astype(jnp.int32)
    bucket = (_mix(ext, tid, _H_BUCKET)
              % nb_s.astype(jnp.uint32)).astype(jnp.int32)
    row = roff + slot                      # logical slab row of the slot
    rowc = jnp.where(live, row, 0)         # gather-safe
    fp = _fingerprint(ext, tid)

    occ = wstate["slot_fp"][rowc]
    hit = live & (occ == fp)
    local = jnp.where(hit, slot, cap + bucket)
    local_rows = jnp.where(live, local, ext.astype(jnp.int32))

    if not update:
        return local_rows, None

    free = occ == SLOT_FREE
    occ_freq = wstate["slot_freq"][rowc]
    admit = live & ~hit & (est >= config.admit_min_count)
    claim = admit & (free | (est >= occ_freq + config.evict_margin))

    # deterministic winner per claimed row: max estimate, then max
    # fingerprint, then max stream position — pure associative
    # max-scatters, so duplicate ids and colliding claims cannot make
    # the transition order-dependent (the CRC-identity drills rely on
    # this)
    neg = jnp.full((rows_cap,), -1, jnp.int32)
    best_est = neg.at[rowc].max(jnp.where(claim, est, -1))
    cand = claim & (est == best_est[rowc])
    best_fp = neg.at[rowc].max(jnp.where(cand, fp, -1))
    cand = cand & (fp == best_fp[rowc])
    pos = jnp.arange(n, dtype=jnp.int32)
    best_pos = neg.at[rowc].max(jnp.where(cand, pos, -1))
    scrub = cand & (best_pos[rowc] == pos)  # exactly once per claimed row

    sent = jnp.asarray(rows_cap, jnp.int32)
    scrub_rows = jnp.where(scrub, row, sent)  # OOB scatters drop
    hit_rows = jnp.where(hit, row, sent)
    new_fp = wstate["slot_fp"].at[scrub_rows].set(fp)
    new_freq = wstate["slot_freq"].at[scrub_rows].set(est)
    # a map hit refreshes the occupant's recorded frequency from the
    # (monotone) sketch — the approximate-LFU signal evictions compare
    # against; max dedups duplicate hits deterministically
    new_freq = new_freq.at[hit_rows].max(est)

    stats = {
        "admitted": jnp.sum(scrub, dtype=jnp.float32).reshape(1),
        "evicted": jnp.sum(scrub & ~free, dtype=jnp.float32).reshape(1),
        "bucket_ids": jnp.sum(live & ~hit,
                              dtype=jnp.float32).reshape(1),
        "hit_ids": jnp.sum(hit, dtype=jnp.float32).reshape(1),
    }
    new_wstate = {"slot_fp": new_fp, "slot_freq": new_freq, "cms": cms}
    return local_rows, (new_wstate, scrub_rows, stats)


def commit(de, params: Dict[str, jax.Array], pending, old_state,
           enable=None, opt_state=None, optimizer=None):
    """Apply one step's staged streaming transitions — called by the
    trainer AFTER the optimizer scatter, next to the nan-guard so a
    skipped step leaves the slot map, sketch, counters AND slabs
    bitwise-unchanged (the rollback/quarantine machinery requires the
    guard's skip to be total).

    * claimed slab rows are ZEROED in the (post-apply) width slabs via an
      O(claims) lane-masked scatter (gather current lanes, add the
      negative) — never a slab-wide pass; with ``enable=False`` the rows
      route to the dropped sentinel exactly like the optimizer skip;
    * with ``opt_state``/``optimizer`` given, every SLAB-SHAPED optimizer
      state leaf is reset on the claimed rows in the same commit scatter
      machinery, to the optimizer's declared fresh-row value
      (``fresh_row_fill``: Adagrad's ``initial_accumulator_value``, zero
      for momentum/Adam moments) — an admitted id's moments start
      exactly like a freshly initialized table's, not as the evictee's
      leftovers. Non-slab leaves (Adam's step count) are untouched;
    * the new slot-map/sketch state is where-selected against the old
      (streaming state is MBs, not GBs — a select is cheap);
    * cumulative counters advance by the (gated) per-step stats.

    Returns ``(params, new_state, step_stats)`` — or ``(params,
    opt_state, new_state, step_stats)`` when ``opt_state`` is given —
    where ``step_stats`` is the gated per-step counter dict the trainer
    surfaces as the ``stream_*`` step metrics.
    """
    from ..ops import packed_slab as ps
    from ..utils import obs

    new_state = dict(old_state)
    if opt_state is not None:
        opt_state = dict(opt_state)
    totals = {k: jnp.zeros((1,), jnp.float32)
              for k in ("admitted", "evicted", "bucket_ids", "hit_ids")}
    for w, (new_wstate, scrub_rows, stats) in sorted(pending.items()):
        k = _wkey(w)
        with obs.scope(f"streaming_commit_w{w}"):
            rows = scrub_rows
            if enable is not None:
                rows = jnp.where(enable, rows,
                                 jnp.asarray(de.rows_cap[w], rows.dtype))
            slab = params[k]
            cur = ps.packed_gather(slab, jnp.minimum(
                rows, de.rows_cap[w] - 1), w)
            # sentinel rows expand to physical ids past the slab and the
            # scatter drops them — the same O(ids) skip the optimizer uses
            phys, pvals = ps.expand_update_rows(-cur, rows, w)
            params = dict(params)
            params[k] = slab.at[phys].add(pvals)
            if opt_state is not None:
                # moment hygiene: reset slab-shaped optimizer state on
                # the claimed rows with the SAME gather/expand/scatter
                # machinery (O(claims), guard-gated through `rows`);
                # matching on shape keeps mixed dtypes (fp32 accumulators
                # over bf16 slabs) and tuple states (Adam) leaf-exact
                fill = float(getattr(optimizer, "fresh_row_fill", 0.0))
                slab_shape = tuple(slab.shape)

                def scrub_leaf(leaf, rows=rows, w=w, fill=fill,
                               slab_shape=slab_shape):
                    if tuple(getattr(leaf, "shape", ())) != slab_shape:
                        return leaf
                    c = ps.packed_gather(leaf, jnp.minimum(
                        rows, de.rows_cap[w] - 1), w)
                    # zero-then-add, NOT add(fill - cur): x + (-x) is
                    # exactly 0 and 0 + fill exactly fill, so the reset
                    # row is BITWISE the fresh-init value regardless of
                    # the evictee's magnitude (fill - cur would leave a
                    # rounding residue, or cancel fill entirely under a
                    # huge accumulator)
                    ph, pv = ps.expand_update_rows(-c, rows, w)
                    leaf = leaf.at[ph].add(pv)
                    if fill:
                        _, pf = ps.expand_update_rows(
                            jnp.full_like(c, fill), rows, w)
                        leaf = leaf.at[ph].add(pf)
                    return leaf

                opt_state[k] = jax.tree.map(scrub_leaf, opt_state[k])
            if enable is None:
                new_state[k] = new_wstate
            else:
                new_state[k] = jax.tree.map(
                    lambda a, b: jnp.where(enable, a, b),
                    new_wstate, old_state[k])
            for name, v in stats.items():
                gated = (v if enable is None
                         else jnp.where(enable, v, 0.0))
                totals[name] = totals[name] + gated
    one = jnp.ones((1,), jnp.int32)
    if enable is not None:
        one = jnp.where(enable, one, 0)
    new_state["steps"] = old_state["steps"] + one
    for name, v in totals.items():
        new_state[name] = old_state[name] + v
    if opt_state is not None:
        return params, opt_state, new_state, totals
    return params, new_state, totals


# ------------------------------------------------------ state persistence


def encode_state(de, state) -> Dict[str, np.ndarray]:
    """Host-side, plan-AGNOSTIC encoding of a carried streaming state for
    ``utils.checkpoint.save_train_state(aux_states=)``: per streaming
    table, its slot fingerprints and frequencies as ``[capacity]``
    arrays (slab-row-space decoded through the layout the checkpoint
    plan already knows), plus each width's admission sketch and the
    per-rank counters. ``decode_state`` inverts it under ANY plan whose
    logical tables match — the dynamic form re-shards exactly like the
    tables themselves (``tools/reshard.py`` copies the aux file
    byte-identically; only a changed world size resets the per-rank
    sketches/counters, logged as a warm-up degradation)."""
    host = jax.tree.map(np.asarray, state)
    out: Dict[str, np.ndarray] = {
        "world": np.asarray([de.world_size], np.int32),
    }
    for name in ("steps", "admitted", "evicted", "bucket_ids", "hit_ids"):
        out[f"c_{name}"] = np.asarray(host[name])
    for tid, (cap, _) in sorted(de.streaming_tables.items()):
        r, roff, w = _table_home(de, tid)
        ws = host[_wkey(w)]
        out[f"t{tid}_fp"] = np.asarray(ws["slot_fp"][r, roff:roff + cap])
        out[f"t{tid}_freq"] = np.asarray(
            ws["slot_freq"][r, roff:roff + cap])
    for w in streaming_widths(de):
        out[f"w{w}_cms"] = np.asarray(host[_wkey(w)]["cms"])
    return out


def decode_state(de, template, encoded: Optional[Dict[str, np.ndarray]]):
    """Rebuild a carried streaming state from :func:`encode_state` output
    under ``de``'s (possibly different) plan, using ``template`` (an
    :func:`init_streaming` result for the SAME config) for structure and
    placement. ``None``/empty input returns a pristine
    :func:`fresh_like` state — streaming aux must never block a restore
    (cold slot maps only degrade ids back to their buckets)."""
    import logging

    log = logging.getLogger(__name__)
    # np.array (not asarray): jax-array views are read-only, and the
    # per-table writes below mutate in place
    state = jax.tree.map(np.array, fresh_like(template))
    if not encoded:
        return jax.tree.map(jnp.asarray, state)
    try:
        same_world = (int(np.asarray(encoded["world"]).reshape(-1)[0])
                      == de.world_size)
        for tid, (cap, _) in sorted(de.streaming_tables.items()):
            r, roff, w = _table_home(de, tid)
            for field, key in (("slot_fp", f"t{tid}_fp"),
                               ("slot_freq", f"t{tid}_freq")):
                src = np.asarray(encoded[key])
                if src.shape != (cap,):
                    raise ValueError(
                        f"{key}: saved shape {src.shape} != ({cap},) — "
                        "streaming capacity drift")
                arr = state[_wkey(w)][field]
                arr[r, roff:roff + cap] = src
        for name in ("steps", "admitted", "evicted", "bucket_ids",
                     "hit_ids"):
            src = encoded.get(f"c_{name}")
            if src is not None and same_world \
                    and src.shape == state[name].shape:
                state[name] = np.asarray(src).astype(state[name].dtype)
        for w in streaming_widths(de):
            src = encoded.get(f"w{w}_cms")
            tgt = state[_wkey(w)]["cms"]
            if src is not None and same_world and src.shape == tgt.shape:
                state[_wkey(w)]["cms"] = np.asarray(src).astype(tgt.dtype)
            elif src is not None:
                log.warning(
                    "streaming decode: admission sketch w%d re-shards "
                    "from world/geometry %s to %s — resetting (warm-up "
                    "degradation; slot maps carried over intact)", w,
                    src.shape, tgt.shape)
    except Exception:  # noqa: BLE001 - see docstring: never block a restore
        log.exception("streaming state decode failed; starting fresh")
        state = jax.tree.map(np.array, fresh_like(template))
    out = jax.tree.map(jnp.asarray, state)
    # restore the template leaves' device placement (mesh-sharded runs)
    def place(t, v):
        sharding = getattr(t, "sharding", None)
        return (jax.device_put(v, sharding) if sharding is not None
                else v)
    return jax.tree.map(place, template, out)


def _table_home(de, tid: int) -> Tuple[int, int, int]:
    """``(rank, slab row offset, width)`` of an (unsliced) streaming
    table — the placement encode/decode translate through."""
    for r, tids in enumerate(de.strategy.table_ids_list):
        for m, t in enumerate(tids):
            if t == tid:
                return (r, de.row_offsets_list[r][m],
                        int(de.strategy.local_configs_list[r][m]
                            ["output_dim"]))
    raise ValueError(f"streaming table {tid} placed on no rank")


# --------------------------------------------------------- host analysis


def occupancy(de, state) -> Dict[str, Any]:
    """Host summary of a streaming state: per-table slot occupancy and
    the cumulative admission/eviction/bucket counters — the streaming
    analogue of ``telemetry.load_balance`` (``tools/check_streaming.py``
    and the bench section read this)."""
    host = jax.tree.map(np.asarray, state)
    tables = []
    for tid, (cap, nb) in sorted(de.streaming_tables.items()):
        r, roff, w = _table_home(de, tid)
        fp = np.asarray(host[_wkey(w)]["slot_fp"][r, roff:roff + cap])
        tables.append({
            "table_id": int(tid), "capacity": int(cap),
            "buckets": int(nb),
            "occupied": int((fp != SLOT_FREE).sum()),
            "occupancy_frac": float((fp != SLOT_FREE).mean()),
        })
    def c(name):
        return float(np.asarray(host[name]).sum())
    return {
        "steps": int(np.asarray(host["steps"]).reshape(-1).max()),
        "admitted": c("admitted"), "evicted": c("evicted"),
        "bucket_ids": c("bucket_ids"), "hit_ids": c("hit_ids"),
        "tables": tables,
    }

"""Static exchange plans for the rank-uniform executor.

The reference executes per-rank heterogeneity as per-rank *programs*: each
Horovod process builds only its local layers and runs its own Python loop
over them (``dist_model_parallel.py:261-311``). The first TPU port of that
idea expressed the same thing as ``lax.switch`` over rank-specialized
branches — but SPMD compiles every branch on every device, so HLO grew as
O(world x tables) and colossal-scale models (2002 tables,
``config_v3.py:107-121``) became a compile-time cliff.

This module makes per-rank heterogeneity *data* instead of *program*. The
id-exchange block and the output-exchange row are laid out as a sequence of
**group regions at static offsets that are identical on every rank**:

* a *dense group* ``(width w, hotness h)`` holds ``n`` slots, each slot one
  combiner lookup: ``b*h`` ids in the block, ``w`` output columns;
* a *ragged group* ``(width w, capacity c)`` holds ``n`` slots, each slot one
  static-capacity CSR feature: ``c`` values + ``b`` lengths in the block,
  ``w`` output columns;
* ``n`` is the max slot count over ranks — ranks with fewer tables of that
  shape pad with dead slots (zero ids in, never-read columns out).

What *differs* per rank — which table a slot reads (row count, slab row
offset), its combiner, whether the slot is live — is carried in small
``[world, n]`` plan tensors indexed by ``lax.axis_index`` at run time. One
compiled program serves every mesh position: per group, ONE reshape of the
block region, ONE slab gather, ONE reduction — O(#groups) heavy HLO ops
total, independent of world size and table count.

A multi-hot feature *without* a combiner ([batch, h] ids -> [batch, h*w]
activations) is expressed as ``h`` consecutive hotness-1 slots; its ids
travel column-major ([h, b]) so each slot's ids stay contiguous.

Plans depend on the per-input encodings and the local batch size, both known
only at trace time, so :class:`~.dist_embedding.DistributedEmbedding` builds
them lazily and caches by ``(encodings, batch)``.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np


@dataclasses.dataclass(frozen=True)
class GroupSpec:
    """One rank-uniform region of the exchange layout."""

    kind: str    # "d" dense | "r" ragged | "rw" ragged with per-id weights
    width: int   # per-slot output width (the column-slice width for slices)
    hot: int     # dense: ids per batch row per slot; ragged: value capacity
    n: int       # slots (max over ranks; shorter ranks are padded)
    blen: int    # ints one slot occupies per source block
    goff: int    # region start within the [l_max] id block
    col: int     # region start within the [s_max] output row


@dataclasses.dataclass(frozen=True)
class InstanceSpec:
    """One routed input on one rank (worker-order entry).

    ``num_slots > 1`` for no-combiner multi-hot features (one slot per hot
    position, ids sent column-major) and for N-D dense combiner inputs
    (``[b, d1, ..., h]``: one hotness-``h`` slot per lead position — the
    reference flattens such inputs through its exchange the same way,
    ``dist_model_parallel.py:273-288``)."""

    input_id: int
    rank: int
    group: int
    slot0: int
    num_slots: int

    @property
    def transposed(self) -> bool:
        return self.num_slots > 1


@dataclasses.dataclass(frozen=True)
class ExchangePlan:
    """Complete static layout + per-rank plan tensors for one input signature.

    Plan arrays are all ``[world, n_g]`` numpy, one per group:

    * ``rows``  — table row count a slot reads (1 for dead slots);
    * ``roff``  — slot's table row offset inside its width slab;
    * ``valid`` — 1.0 for live slots, 0.0 for padding (backward routes dead
      slots' ids to the dropped sentinel);
    * ``mean``  — 1.0 where the slot's combiner is ``'mean'`` (forward
      divides the reduced sum, backward divides the cotangent);
    * ``rbase`` — slot's first global row for row-sliced tables (subtracted
      from incoming ids; out-of-slice ids read zero forward and drop
      backward). 0 everywhere else;
    * ``rsliced`` — 1.0 exactly for row-sliced slots (``rbase`` can't mark
      them: a table's FIRST row slice has base 0). Gates the forward
      zero-read mask per slot so unsliced tables sharing the group keep the
      documented clip-to-last-row read.
    """

    b: int
    groups: Tuple[GroupSpec, ...]
    instances: Tuple[InstanceSpec, ...]
    l_max: int
    s_max: int
    rows: Tuple[np.ndarray, ...]
    roff: Tuple[np.ndarray, ...]
    valid: Tuple[np.ndarray, ...]
    mean: Tuple[np.ndarray, ...]
    rbase: Tuple[np.ndarray, ...]
    rsliced: Tuple[np.ndarray, ...]

    def out_width(self, inst: InstanceSpec) -> int:
        return self.groups[inst.group].width * inst.num_slots


def build_plan(strategy, row_offsets_list: Sequence[Sequence[int]],
               encs: Sequence[tuple], b: int) -> ExchangePlan:
    """Build the exchange plan for one input signature.

    Args:
      strategy: a planned :class:`~.strategy.DistEmbeddingStrategy`.
      row_offsets_list: per-rank per-local-table logical slab row offsets.
      encs: per global input: dense ``("d", hotness[, num_slots])`` (the
        third element — N-D lead positions — defaults to 1) or ragged
        ``("r", capacity)`` / ``("rw", capacity)`` (per-id weights ride
        the block as bitcast floats past the lengths).
      b: per-shard batch size.
    """
    world = strategy.world_size
    # pass 1: per-rank slot lists per group key, in worker order
    key_slots: Dict[tuple, List[list]] = {}
    inst_raw = []  # (input_id, rank, key, slot0, num_slots)
    for r in range(world):
        for j, i in enumerate(strategy.input_ids_list[r]):
            m = strategy.local_map_list[r][j]
            cfg = strategy.local_configs_list[r][m]
            w = int(cfg["output_dim"])
            # row offsets stay < 2^31 in practice: physical slab rows are
            # HBM-bounded and roff <= phys_rows * pack_factor
            rows = int(cfg["input_dim"])
            roff = int(row_offsets_list[r][m])
            comb = cfg.get("combiner")
            rbase = int(cfg.get("_row_base", 0))
            rsl = 1.0 if "_row_base" in cfg else 0.0
            enc = encs[i]
            kind, param = enc[0], int(enc[1])
            nslots = int(enc[2]) if len(enc) > 2 else 1
            if kind == "d":
                if comb:
                    # N-D inputs: one hotness-`param` slot per lead position
                    key = ("d", w, param)
                    entries = [(rows, roff, 1.0,
                                1.0 if comb == "mean" else 0.0, rbase, rsl)
                               ] * nslots
                else:
                    key = ("d", w, 1)
                    entries = [(rows, roff, 1.0, 0.0, rbase, rsl)
                               ] * (param * nslots)
            else:
                if comb is None:
                    # without this, a combiner-less table would silently get
                    # the mean-flag 0.0, i.e. 'sum' semantics (ADVICE r3)
                    raise ValueError(
                        f"Input {i} is Ragged but table "
                        f"{strategy.input_table_map[i]} has no combiner; "
                        "ragged features require combiner='sum' or 'mean'")
                key = (kind, w, param)  # "r" | "rw" (per-id weights ride
                # the block as bitcast floats, so weighted features group
                # separately — their slots are one capacity longer)
                entries = [(rows, roff, 1.0,
                            1.0 if comb == "mean" else 0.0, rbase, rsl)]
            slots = key_slots.setdefault(key, [[] for _ in range(world)])
            inst_raw.append((i, r, key, len(slots[r]), len(entries)))
            slots[r].extend(entries)

    # pass 2: deterministic group order, cumulative offsets, plan tensors
    keys = sorted(key_slots)
    gidx = {k: g for g, k in enumerate(keys)}
    groups = []
    rows_l, roff_l, valid_l, mean_l, rbase_l, rsl_l = [], [], [], [], [], []
    goff = col = 0
    for k in keys:
        slots = key_slots[k]
        kind, w, hp = k
        n = max(len(s) for s in slots)
        blen = {"d": b * hp, "r": hp + b, "rw": 2 * hp + b}[kind]
        groups.append(GroupSpec(kind, w, hp, n, blen, goff, col))
        goff += n * blen
        col += n * w
        rows_a = np.ones((world, n), np.int32)
        roff_a = np.zeros((world, n), np.int32)
        val_a = np.zeros((world, n), np.float32)
        mn_a = np.zeros((world, n), np.float32)
        rb_a = np.zeros((world, n), np.int32)
        rs_a = np.zeros((world, n), np.float32)
        for r in range(world):
            for kk, (tr, to, tv, tm, trb, trs) in enumerate(slots[r]):
                rows_a[r, kk], roff_a[r, kk] = tr, to
                val_a[r, kk], mn_a[r, kk] = tv, tm
                rb_a[r, kk], rs_a[r, kk] = trb, trs
        rows_l.append(rows_a)
        roff_l.append(roff_a)
        valid_l.append(val_a)
        mean_l.append(mn_a)
        rbase_l.append(rb_a)
        rsl_l.append(rs_a)

    instances = tuple(
        InstanceSpec(i, r, gidx[k], s0, ns) for i, r, k, s0, ns in inst_raw)
    return ExchangePlan(
        b=b, groups=tuple(groups), instances=instances,
        l_max=max(goff, 1), s_max=max(col, 1),
        rows=tuple(rows_l), roff=tuple(roff_l),
        valid=tuple(valid_l), mean=tuple(mean_l), rbase=tuple(rbase_l),
        rsliced=tuple(rsl_l))

"""Online learning runtime: concurrent train-and-serve in ONE process
against ONE set of tables, bridged by RCU snapshot publication.

Production recommenders read the model WHILE clicks train it. Before
this module the two halves existed separately — the resilient training
loop (``parallel/resilient.py``: streaming-vocab tables, nan-guard,
rollback-and-replay, preemption/auto-resume) and the deadline-bounded
coalescer (``parallel/serving.py``) — but serving only ever answered
from frozen snapshots. :class:`OnlineRuntime` runs both interleaved in
one process, connected by a snapshot/versioning layer::

       train step t  ──donates──▶  state_{t+1} ──┐
            ▲                                    │ SnapshotPublisher
            │ resilient loop                     │ (RCU copy, version v)
            │ (rollback / preempt /              ▼
            │  quarantine / resume)     ┌─ published view v ─┐
            │                           │ params (copy)      │
       on_step_aux pump ───────────────▶│ streaming (copy)   │
        publish → submit → poll         │ frozen opt shapes  │
                                        └────────┬───────────┘
                                                 │ install_snapshot
                                                 ▼  (atomic swap)
                                    ServingRuntime compiled ladder
                                    (flush reads ONE view: no torn
                                     reads; same shapes: 0 recompiles)

**Why RCU double-buffering, not a checkpoint-ring handoff.** The train
step donates its state every step (``donate_argnums=(0, ...)``), so any
view that outlives the step must be a genuine copy — and a device-side
elementwise copy (:func:`~.trainer.clone_pytree`) is orders of
magnitude cheaper than a disk round-trip through the checkpoint ring,
preserves shardings bitwise (the serving ladder's jit cache keys match
across versions → 0 steady-state recompiles), and decouples publication
cadence from checkpoint cadence. The serving view never reads optimizer
slots (the eval forward DCEs them, but its shard_map specs still
require the full :class:`~.trainer.HybridTrainState`), so the publisher
clones the optimizer state ONCE and shares those frozen buffers across
every version: steady-state footprint is two param copies (published +
in-flight during a publish) plus one opt-shaped slab —
``analysis/plan_audit.py`` bills exactly this as
``RankBudget.snapshot_bytes``.

**Consistency contracts** (drilled by ``tools/check_online.py`` =
``make check-online`` and pinned bitwise in ``tests/test_online.py``):

* *No torn reads* — a serve flush observes exactly one version, never a
  mid-publish mix: the publisher swaps one reference between polls and
  the flush reads it once.
* *Monotone versions* — versions only grow, across publication,
  preemption/resume (the ``<ckpt>.online.json`` sidecar persists the
  counter) and rollback (train_step may rewind; the version never
  does).
* *Freshness SLO* — per-response staleness in steps and seconds rides
  :meth:`~.serving.ServingRuntime.stats` next to p99; when publication
  falls behind ``DETPU_FRESHNESS_MAX_STEPS`` the server sheds
  serve-side load (typed, via the existing degradation ladder) before
  training is ever blocked on publication.
* *Training unperturbed* — the training trajectory is
  checkpoint-CRC-identical to the same run WITHOUT concurrent serving:
  publishes copy, serves read copies, and the published-version record
  lives in a sidecar BESIDE the checkpoint directory (never inside —
  ``meta.json`` manifests aux keys, so an in-checkpoint record would
  break CRC identity).
* *Robustness composition* — preemption mid-serve checkpoints the
  training state while the sidecar holds the published version
  (a consistent pair: the sidecar's step never exceeds the saved
  step's publish point); auto-resume restores the state, continues the
  version counter, and republishes immediately; rollback-and-replay
  rewinds the publisher with the ring candidate (the next
  ``maybe_publish`` sees ``state.step`` behind the published step and
  republishes at once).
"""

from __future__ import annotations

import dataclasses
import json
import logging
import os
import time
from typing import Any, Callable, Dict, List, Optional

from ..utils import envvars, mplane, obs
from ..utils import runtime as runtime_mod
from .resilient import ResilientResult, _atomic_json, run_resilient
from .serving import RealtimeDriver, Request, ServeResult, ServingRuntime
from .trainer import HybridTrainState, clone_pytree

logger = logging.getLogger(__name__)

#: Every Nth pump rings a serving ``stats()`` snapshot into the flight
#: recorder — cheap (sketch reads, no sorts) but not free, so not every
#: step.
_STATS_RING_EVERY = 10


def online_sidecar_path(checkpoint_dir: str) -> str:
    """The publisher's version-record sidecar, BESIDE the checkpoint
    directory (``<dir>.online.json``). Never inside it: the checkpoint
    manifest must stay byte-identical to a run without concurrent
    serving (the CRC-identity contract ``make check-online`` compares),
    and ``meta.json`` records every aux key it carries."""
    return checkpoint_dir.rstrip(os.sep) + ".online.json"


class OnlineConfig:
    """Static online-runtime policy: publication cadence + freshness
    SLO. Defaults come from the ``DETPU_ONLINE_*`` /
    ``DETPU_FRESHNESS_*`` env knobs."""

    def __init__(self, publish_every_steps: Optional[int] = None,
                 freshness_max_steps: Optional[int] = None,
                 freshness_max_s: Optional[float] = None):
        self.publish_every_steps = int(
            publish_every_steps if publish_every_steps is not None
            else envvars.get_int("DETPU_ONLINE_PUBLISH_STEPS"))
        if self.publish_every_steps < 1:
            raise ValueError("publish_every_steps must be >= 1")
        self.freshness_max_steps = int(
            freshness_max_steps if freshness_max_steps is not None
            else envvars.get_int("DETPU_FRESHNESS_MAX_STEPS"))
        self.freshness_max_s = float(
            freshness_max_s if freshness_max_s is not None
            else envvars.get_float("DETPU_FRESHNESS_MAX_S"))


@dataclasses.dataclass(frozen=True)
class Snapshot:
    """One published, immutable table view: fresh buffers, one version.

    ``state`` is a full :class:`~.trainer.HybridTrainState` (the eval
    shard_map specs require it) whose param leaves are copies of the
    training state at ``train_step`` and whose optimizer leaves are the
    publisher's shared frozen buffers (never read by the forward)."""

    version: int
    train_step: int
    published_t: float
    state: Any
    streaming_state: Any = None


class SnapshotPublisher:
    """RCU writer side: copy the live (donated-every-step) training
    state into fresh buffers and install the copy atomically into a
    :class:`~.serving.ServingRuntime`.

    Single-threaded like the server itself: publishes happen between
    polls (the online runtime's step pump), so the atomic-swap +
    read-once discipline in :meth:`~.serving.ServingRuntime
    .install_snapshot` / ``_run_flush`` makes torn reads impossible by
    construction. At most two param copies are ever live (the published
    view and the in-flight one during a publish) — the double-buffer
    footprint ``plan_audit`` bills as ``snapshot_bytes``.

    ``sidecar_path`` (``<ckpt>.online.json``) persists the version
    counter and last-published step across preemption/resume so
    versions stay monotone for the lifetime of the checkpointed run;
    ``resume=False`` starts a fresh lineage (a stale sidecar in a dirty
    directory is deleted, mirroring the quarantine-ledger policy)."""

    def __init__(self, serving: ServingRuntime, *,
                 config: Optional[OnlineConfig] = None,
                 sidecar_path: Optional[str] = None,
                 resume: bool = True,
                 clock: Callable[[], float] = time.monotonic):
        self.serving = serving
        self.config = config or OnlineConfig()
        self.sidecar_path = sidecar_path
        self._clock = clock
        self._version = 0
        self._last_step: Optional[int] = None
        self._opt_frozen = None
        self.published: Optional[Snapshot] = None
        serving.set_freshness_slo(self.config.freshness_max_steps,
                                  self.config.freshness_max_s)
        if sidecar_path and os.path.isfile(sidecar_path):
            if resume:
                try:
                    with open(sidecar_path, encoding="utf-8") as f:
                        doc = json.load(f)
                    self._version = int(doc.get("version", 0))
                    logger.info(
                        "online publisher: resumed version counter at %d "
                        "(last published step %s) from %s", self._version,
                        doc.get("train_step"), sidecar_path)
                except (OSError, ValueError):
                    logger.warning(
                        "online publisher: unreadable sidecar %s — "
                        "version counter restarts (versions stay "
                        "monotone within this run only)", sidecar_path)
            else:
                # fresh lineage over a dead run's record: a later resume
                # of THIS run must not inherit the old run's counter
                os.remove(sidecar_path)

    @property
    def version(self) -> int:
        """Last published version (0 = nothing published yet)."""
        return self._version

    def _frozen_opt(self, state: HybridTrainState):
        # the serve forward never reads optimizer slots (DCE'd), but the
        # eval shard_map specs require the full state — clone them ONCE
        # and share the buffers across every published version: RCU
        # footprint stays at 2x params + 1x opt instead of 2x (params+opt)
        if self._opt_frozen is None:
            self._opt_frozen = clone_pytree(
                (state.emb_opt_state, state.dense_opt_state))
        return self._opt_frozen

    def warm(self, state: HybridTrainState, streaming_state=None) -> None:
        """Compile the copy programs against (template-shaped) state and
        discard the result — so the publisher's one-time compiles land
        BEFORE :meth:`~.serving.ServingRuntime.warmup` marks the
        steady-state recompile baseline."""
        self._frozen_opt(state)
        clone_pytree((state.emb_params, state.dense_params, state.step))
        if streaming_state is not None:
            clone_pytree(streaming_state)

    def publish(self, state: HybridTrainState, streaming_state=None, *,
                train_step: Optional[int] = None,
                now: Optional[float] = None) -> Snapshot:
        """Copy + install one new version unconditionally. The copies
        are real device buffers (:func:`~.trainer.clone_pytree`), so the
        training step may donate the sources immediately after."""
        now = self._clock() if now is None else now
        step = int(state.step) if train_step is None else int(train_step)
        emb_opt, dense_opt = self._frozen_opt(state)
        emb_p, dense_p, step_a = clone_pytree(
            (state.emb_params, state.dense_params, state.step))
        snap_state = HybridTrainState(
            emb_params=emb_p, emb_opt_state=emb_opt,
            dense_params=dense_p, dense_opt_state=dense_opt, step=step_a)
        stream_copy = (clone_pytree(streaming_state)
                       if streaming_state is not None else None)
        snap = Snapshot(version=self._version + 1, train_step=step,
                        published_t=now, state=snap_state,
                        streaming_state=stream_copy)
        self.serving.install_snapshot(
            snap_state, stream_copy, version=snap.version,
            train_step=step, published_t=now, now=now)
        # the retired view's buffers free when the last reference drops
        # (served predictions are already materialized numpy slices)
        self._version = snap.version
        self._last_step = step
        self.published = snap
        if self.sidecar_path:
            _atomic_json(self.sidecar_path, {
                "version": snap.version, "train_step": step,
                "published_t": now, "time": time.time()})
        return snap

    def maybe_publish(self, state: HybridTrainState, streaming_state=None,
                      *, now: Optional[float] = None) -> Optional[Snapshot]:
        """Cadence-gated publish; also the rollback rewind point: when
        ``state.step`` is BEHIND the published step, training rolled
        back to a ring candidate underneath the published view —
        republish immediately (version still advances; versions are
        monotone even when train_step rewinds) so serving never answers
        from a future the trainer abandoned. Off-cadence calls still
        notify the server of training progress (the freshness clock)."""
        step = int(state.step)
        if self._last_step is not None and step < self._last_step:
            logger.warning(
                "online publisher: training rewound under the published "
                "view (step %d < published %d) — republishing the ring-"
                "candidate state as v%d", step, self._last_step,
                self._version + 1)
            obs.record_event("snapshot_rewound", from_step=self._last_step,
                             to_step=step, version=self._version + 1)
            return self.publish(state, streaming_state, now=now)
        if (self.published is None
                or step - self._last_step >= self.config.publish_every_steps):
            return self.publish(state, streaming_state, now=now)
        self.serving.note_train_step(step, now=now)
        return None


def warm_checkpoint_io(de, state, streaming_state=None) -> None:
    """Compile the checkpoint writer's device->host fetch programs (and
    the streaming encoder's gathers) without writing anything.

    The resilient loop's FIRST ring save jit-compiles
    ``DistributedEmbedding.get_table``'s chunked row fetches — one
    program per slab component — and that save lands steps AFTER the
    serving ladder's warmup marks the steady-state recompile baseline.
    Those are one-time compiles, not retraces; the online runtime warms
    them up front so ``steady_state_recompiles == 0`` keeps meaning
    "nothing retraced", with checkpointing running concurrently."""
    from ..utils.checkpoint import _components

    n_tables = len(de.strategy.global_configs)
    for t in range(n_tables):
        de.get_table(state.emb_params, t, all_ranks=False)
    slabs, _ = _components(state.emb_opt_state, state.emb_params)
    for comp in slabs.values():
        for t in range(n_tables):
            de.get_table(comp, t, all_ranks=False)
    if streaming_state is not None:
        from . import streaming as streaming_mod
        streaming_mod.encode_state(de, streaming_state)


@dataclasses.dataclass
class OnlineResult:
    """What one :meth:`OnlineRuntime.run` produced: the training result,
    every typed serve response, the server's final stats, and where
    publication ended."""

    train: ResilientResult
    serve_results: List[ServeResult]
    serve_stats: Dict[str, Any]
    published_version: int
    published_train_step: Optional[int]


class OnlineRuntime:
    """Concurrent train-and-serve: the resilient training loop and the
    serving coalescer interleaved in one process, one set of tables.

    Usage::

        rt = ServingRuntime(de, pred_fn, state, mesh=mesh,
                            streaming=(scfg, sstate), config=...)
        online = OnlineRuntime(rt, config=OnlineConfig(),
                               checkpoint_dir=ckpt)
        res = online.run(step_fn, state, data, de=de, until_step=100,
                         warmup_template=(tmpl_cats, tmpl_batch),
                         make_request=gen, requests_per_step=4,
                         streaming_state=sstate, emb_optimizer=...,
                         dense_tx=...)

    The serve side is pumped from the training loop's ``on_step_aux``
    hook, once per completed step: publish when due →
    (first call only) warm the serving ladder → submit this step's
    arrivals → poll. Ordering matters twice over: the publisher's copy
    compiles and the ladder warmup both land AFTER the train step's own
    compile and BEFORE the steady-state baseline, so
    ``steady_state_recompiles`` stays 0 across any mix of training,
    publication, rollback and serving; and no flush ever runs before
    the first publication, so every response carries a version.

    Serve arrivals come in one of two modes. **Step-paced** (default):
    ``make_request(i)`` is submitted ``requests_per_step`` times per
    train step — multiplied by ``burst_x`` (default
    ``DETPU_SERVE_BURST_X``) at the ``DETPU_FAULT=burst@<step>`` drill
    positions — which keeps chaos drills and CRC-identity comparisons
    reproducible. **Real-time** (``realtime_qps=...``): a
    :class:`~.serving.RealtimeDriver` on its own thread of control
    submits and polls an open-loop Poisson-free arrival schedule
    against the live publisher while training runs, so
    ``freshness_p95_s`` measures WALL-CLOCK staleness under true
    concurrency instead of step-paced pumping. In real-time mode the
    pump only publishes (the driver owns submit/poll), the driver
    starts after the first publication + ladder warmup (every response
    carries a version; the steady baseline predates traffic), and
    burst drill positions are seconds of stream, not step ordinals.

    Training never blocks on serving: the pump is strictly post-step
    host work, publication is a bounded device copy, and when it still
    falls behind the freshness SLO the SERVER sheds load (typed,
    ``reason="stale_snapshot"``) rather than the trainer waiting."""

    def __init__(self, serving: ServingRuntime, *,
                 config: Optional[OnlineConfig] = None,
                 checkpoint_dir: Optional[str] = None,
                 clock: Callable[[], float] = time.monotonic):
        self.serving = serving
        self.config = config or OnlineConfig()
        self.checkpoint_dir = checkpoint_dir
        self.sidecar_path = (online_sidecar_path(checkpoint_dir)
                             if checkpoint_dir else None)
        self._clock = clock
        self.publisher: Optional[SnapshotPublisher] = None

    def run(self, step_fn: Callable, state, data, *, de,
            warmup_template=None,
            make_request: Optional[Callable[[int], Request]] = None,
            requests_per_step: int = 0,
            realtime_qps: Optional[float] = None,
            realtime_drain_s: float = 30.0,
            burst_x: Optional[float] = None,
            resume: bool = True,
            on_step: Optional[Callable] = None,
            **resilient_kwargs) -> OnlineResult:
        """Train ``step_fn`` over ``data`` under the resilient driver
        while publishing snapshots and serving between steps.

        ``warmup_template``: a ``(cats, batch)`` template request
        compiled into the serving ladder on the first pump (skip it if
        the runtime was already warmed). ``**resilient_kwargs`` pass
        through to :func:`~.resilient.run_resilient` (``until_step``,
        ``emb_optimizer``, ``dense_tx``, ``streaming_state``,
        ``checkpoint_every_steps``, ...); ``checkpoint_dir`` and
        ``resume`` come from this runtime so the publisher sidecar and
        the checkpoint agree on lineage.

        ``realtime_qps`` switches serve load to the wall-clock open
        loop (see the class docstring); it is mutually exclusive with
        ``requests_per_step`` and requires ``make_request``. The driver
        is stopped and drained (up to ``realtime_drain_s``) after
        training returns, and its typed responses land in
        ``serve_results`` alongside any shed submissions."""
        if "checkpoint_dir" in resilient_kwargs:
            raise ValueError(
                "pass checkpoint_dir to OnlineRuntime(...), not run() — "
                "the publisher sidecar must share the checkpoint lineage")
        if realtime_qps is not None:
            if requests_per_step:
                raise ValueError(
                    "pick ONE load mode: step-paced requests_per_step "
                    "or wall-clock realtime_qps, not both")
            if make_request is None:
                raise ValueError("realtime_qps requires make_request")
            if realtime_qps <= 0:
                raise ValueError("realtime_qps must be positive")
        self.publisher = SnapshotPublisher(
            self.serving, config=self.config,
            sidecar_path=self.sidecar_path, resume=resume,
            clock=self._clock)
        burst = set(runtime_mod.burst_steps())
        bx = (float(burst_x) if burst_x is not None
              else envvars.get_float("DETPU_SERVE_BURST_X"))
        results: List[ServeResult] = []
        seq = {"i": 0}
        driver: Dict[str, Optional[RealtimeDriver]] = {"drv": None}

        def _pump(cur, loss, metrics, state_now, telem, stream):
            now = self._clock()
            self.publisher.maybe_publish(state_now, stream, now=now)
            rec = mplane.flight_recorder()
            if rec is not None and cur % _STATS_RING_EVERY == 0:
                # ring a serving-stats snapshot so a post-mortem shows
                # the serve plane's recent history, not just training's
                rec.note_stats(self.serving.stats())
                # ... and the newly retained request traces, so a crash
                # dump carries the exact slow/failed requests that led
                # up to it (drain_new is an exactly-once cursor)
                traces = getattr(self.serving, "traces", None)
                if traces is not None:
                    for tr in traces.drain_new():
                        rec.note_trace(tr)
            if warmup_template is not None and not self.serving._warm:
                # after the train step's compile, before any traffic:
                # the steady-state recompile baseline includes every
                # one-time compile in the process
                self.serving.warmup(warmup_template)
            if realtime_qps is not None:
                if (driver["drv"] is None
                        and self.publisher.published is not None):
                    # first pump: a snapshot exists and the ladder is
                    # warm — hand the serve plane its own thread of
                    # control; from here on the pump only publishes
                    drv = RealtimeDriver(
                        self.serving, make_request, realtime_qps,
                        duration_s=None, burst_x=bx,
                        drain_s=realtime_drain_s, clock=self._clock)
                    driver["drv"] = drv
                    drv.start()
            elif make_request is not None and requests_per_step > 0:
                n = int(round(requests_per_step
                              * (bx if cur in burst else 1.0)))
                for _ in range(n):
                    req = make_request(seq["i"])
                    seq["i"] += 1
                    rej = self.serving.submit(req)
                    if rej is not None:
                        results.append(rej)
            if realtime_qps is None:
                results.extend(self.serving.poll())
            if on_step is not None:
                return on_step(cur, loss, metrics, state_now)
            return None

        # publisher copy programs and checkpoint-writer fetch programs
        # compile against the entry state's shapes (identical to the
        # restored state's — restore is shape-preserving), before the
        # steady baseline exists at all
        self.publisher.warm(state,
                            resilient_kwargs.get("streaming_state"))
        if self.checkpoint_dir is not None:
            warm_checkpoint_io(de, state,
                               resilient_kwargs.get("streaming_state"))
        try:
            train = run_resilient(
                step_fn, state, data, de=de,
                checkpoint_dir=self.checkpoint_dir, resume=resume,
                on_step_aux=_pump, **resilient_kwargs)
        except BaseException:
            drv = driver["drv"]
            if drv is not None:
                drv.stop()
                drv.join(timeout=realtime_drain_s + 5.0)
            raise
        if not train.preempted:
            # final publish + drain: the freshest completed state serves
            # the tail (and the bench's served-AUC tracks the offline
            # final model)
            if self.publisher._last_step != train.step:
                self.publisher.publish(train.state, train.streaming)
        drv = driver["drv"]
        if drv is not None:
            # stop AFTER the final publish so the driver's drain serves
            # the tail from the freshest completed state
            drv.stop()
            drv.join(timeout=realtime_drain_s + 5.0)
            results.extend(drv.results())
        if not train.preempted and realtime_qps is None:
            results.extend(self.serving.flush())
        return OnlineResult(
            train=train, serve_results=results,
            serve_stats=self.serving.stats(),
            published_version=self.publisher.version,
            published_train_step=self.publisher._last_step)

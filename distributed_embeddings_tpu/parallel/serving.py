"""Deadline-bounded serving runtime: request coalescing, overload
admission control, and graceful degradation at QPS.

Every robustness subsystem before this one protects *training*
(preemption, rollback, elastic resume, streaming degradation); this
module is the inference half of "millions of users": answering
variable-size lookup requests under a latency SLO without recompiling
and without falling over when traffic spikes (ROADMAP item 4's serving
scenario). Three pieces, all host-side around ONE compiled program
family:

* **The compiled forward** — a no-grad step built from
  :func:`~.trainer.make_hybrid_eval_step` with ``donate_inputs=True``
  (each flush's freshly packed input buffers are dead the moment the
  step consumes them) and frozen tables. Streaming tables serve
  READ-ONLY: admitted ids read their slots, cold/evicted ids degrade to
  their shared hash-bucket rows, and no admission/eviction runs at
  serve time — the slot map, sketch and counters are bitwise-unchanged
  by any amount of serving. The program family is a small fixed
  **ladder** of padded batch shapes (one compiled executable per rung,
  warmed up front), so steady-state serving is pinned to ZERO
  recompiles by the same compile-listener counter the bench gates on.
* **The request coalescer** — variable-size requests (1..n samples
  each, single-hot, fixed multi-hot, or ragged-hotness inputs) are
  packed FIFO into the smallest rung that holds them; padding samples
  are whole fake rows (id 0, zero features) whose predictions are
  sliced off, and the padding fraction is a first-class metric (every
  padded slot is latency and exchange bytes spent on nobody).
* **The robustness core** — a deadline scheduler (flush on ``max_batch``
  OR ``max_wait_ms``, with per-request deadline propagation: the flush
  happens early when the oldest deadline demands it, and requests
  already past their deadline are dropped with a typed
  :class:`Expired` instead of wasting a rung) and an overload admission
  controller with an explicit DEGRADATION LADDER:

  - **level 0 (healthy)** — batch up to ``max_wait_ms`` for efficiency;
  - **level 1 (pressure)** — a full rung is queued: the batching delay
    shrinks to zero and the queue drains flush-after-flush;
  - **level 2 (shed)** — the queue passed ``shed_frac x max_queue``:
    new lowest-priority (``priority <= 0``) requests are refused with a
    typed :class:`Overloaded` response while higher-priority traffic
    keeps being served; at ``max_queue`` everything incoming is shed.
    Queue growth is bounded by construction — there is no input rate at
    which memory grows without bound.

  Every level transition is surfaced via
  :func:`~..utils.obs.record_event` (``serve_degraded`` /
  ``serve_recovered``) and the served/shed/deadline-missed counts bump
  the process counters next to the recompile counter.

Under the online-learning runtime (``parallel/online.py``) the frozen
tables become *published snapshots*: :meth:`ServingRuntime.
install_snapshot` atomically swaps in monotonically-versioned table
copies between polls (every flush reads the installed view exactly once
— no torn reads), per-response staleness is tracked next to latency
(``freshness_p95_steps`` / ``freshness_p95_s`` in :meth:`stats`), and a
FRESHNESS rung joins the ladder: when publication falls behind
``DETPU_FRESHNESS_MAX_STEPS`` (or ages past ``DETPU_FRESHNESS_MAX_S``)
the server sheds low-priority load (typed ``Overloaded``,
``reason="stale_snapshot"``; ``snapshot_lagging`` event) instead of
ever blocking training.

Drills: ``DETPU_FAULT=slow:serve_step`` injects latency into every
flush (the degraded-backend drill) and ``DETPU_FAULT=burst@<pos>``
makes :func:`drive` spike the arrival rate during second ``<pos>`` of
the stream (the QPS-spike drill). ``tools/check_serving.py`` (= ``make
check-serving``) runs both against the ladder in CI and requires
bounded p99, clean typed shedding, zero steady-state recompiles, and
post-burst recovery; ``tools/serve_bench.py`` measures p50/p95/p99 at a
fixed Zipfian QPS for the bench ``serving`` section.

The runtime is single-threaded and clock-injectable: callers own the
loop (``submit`` + ``poll``), tests drive a manual clock, and
:func:`drive` is the shared real-time load loop the tools use. Nothing
here imports a backend beyond what the compiled forward already needs.
"""

from __future__ import annotations

import dataclasses
import logging
import threading
import time
from typing import (Any, Callable, Dict, List, Optional, Sequence, Tuple)

import jax
import numpy as np

from ..utils import envvars, mplane, obs, reqtrace
from ..utils import runtime as runtime_mod
from ..ops.embedding_lookup import Ragged
from . import streaming as streaming_mod
from .trainer import make_hybrid_eval_step

logger = logging.getLogger(__name__)

#: degradation-ladder levels (index = level)
LEVELS = ("healthy", "pressure", "shed")

#: per-request latency decomposition stages, in pipeline order: the time
#: between submit and the reply splits EXACTLY into these five spans
#: (queue wait is per request; the other four are per flush), each rolled
#: into its own registry sketch so :meth:`ServingRuntime.stats` can
#: attribute the p99 tail to a stage — the instrument behind ROADMAP
#: item 1's "the p99 tail is exchange-bound" claim
STAGES = ("queue_wait", "coalesce", "dispatch", "device_compute",
          "reply_slice")


class ServeConfig:
    """Static serving policy (ladder, deadlines, admission bounds).

    A plain attribute bag (hashable not required — the runtime closes
    over it host-side only). ``rungs`` overrides the power-of-two
    ladder; every rung must be divisible by the world size (the
    shard_map splits the padded batch evenly over ranks).
    """

    def __init__(self,
                 max_batch: Optional[int] = None,
                 rungs: Optional[Sequence[int]] = None,
                 max_wait_ms: Optional[float] = None,
                 deadline_ms: Optional[float] = None,
                 max_queue: Optional[int] = None,
                 shed_frac: Optional[float] = None,
                 ragged_hotness: int = 0):
        env_rungs = envvars.get("DETPU_SERVE_RUNGS") or ""
        if rungs is None and env_rungs.strip():
            rungs = [int(x) for x in env_rungs.split(",") if x.strip()]
        self.rungs = tuple(int(r) for r in rungs) if rungs else None
        self.max_batch = int(
            max_batch if max_batch is not None
            else (self.rungs[-1] if self.rungs
                  else envvars.get_int("DETPU_SERVE_MAX_BATCH")))
        self.max_wait_ms = float(
            max_wait_ms if max_wait_ms is not None
            else envvars.get_float("DETPU_SERVE_MAX_WAIT_MS"))
        self.deadline_ms = float(
            deadline_ms if deadline_ms is not None
            else envvars.get_float("DETPU_SERVE_DEADLINE_MS"))
        self.max_queue = int(
            max_queue if max_queue is not None
            else envvars.get_int("DETPU_SERVE_MAX_QUEUE"))
        self.shed_frac = float(
            shed_frac if shed_frac is not None
            else envvars.get_float("DETPU_SERVE_SHED_FRAC"))
        #: per-sample id budget of ragged (list-of-lists) inputs; the
        #: rung's static value capacity is ``rung x ragged_hotness``.
        #: 0 = no ragged inputs accepted
        self.ragged_hotness = int(ragged_hotness)
        if self.max_batch < 1:
            raise ValueError("max_batch must be >= 1")
        if not (0.0 < self.shed_frac <= 1.0):
            raise ValueError("shed_frac must be in (0, 1]")
        if self.max_queue < self.max_batch:
            raise ValueError(
                f"max_queue ({self.max_queue}) must hold at least one "
                f"full batch ({self.max_batch}) — a queue smaller than "
                "a rung sheds healthy traffic")


def resolve_rungs(config: ServeConfig, world: int) -> Tuple[int, ...]:
    """The padded-batch ladder: explicit ``config.rungs`` validated, or
    powers of two from ``max(8, world)`` up to ``max_batch`` (each
    rounded up to a ``world`` multiple). One compiled executable per
    rung — keep the ladder small; every rung is a warmup compile."""
    if config.rungs:
        rungs = list(config.rungs)
        if sorted(rungs) != rungs or len(set(rungs)) != len(rungs):
            raise ValueError(f"rungs must be strictly ascending: {rungs}")
        for r in rungs:
            if r < 1 or r % world:
                raise ValueError(
                    f"rung {r} is not a positive multiple of world "
                    f"{world}")
        return tuple(rungs)

    def up(x: int) -> int:
        return ((x + world - 1) // world) * world

    lo = up(max(8, world))
    # the TOP rung rounds DOWN to a world multiple (never past the
    # configured max_batch — admission and the max_queue validation
    # bind against it), except when max_batch < world, where one
    # world-sized rung is the minimum viable ladder
    hi = max(world, (config.max_batch // world) * world)
    rungs = []
    r = lo
    while r < hi:
        rungs.append(r)
        r *= 2
    rungs.append(hi)
    return tuple(sorted(set(rungs)))


# ---------------------------------------------------------------- requests


@dataclasses.dataclass
class Request:
    """One inference request: ``n`` samples of categorical ids (+ the
    dense ``batch`` pytree the ``pred_fn`` consumes).

    ``cats`` holds one entry per model input: an int array ``[n]``
    (single-hot), ``[n, h]`` (fixed multi-hot), or a length-``n`` list
    of id lists (ragged hotness — per-sample lists longer than the
    configured ``ragged_hotness`` budget are clipped and counted).
    Higher ``priority`` survives longer under overload; ``deadline_ms``
    (from submit time) defaults to the config's."""

    cats: Sequence[Any]
    batch: Any = None
    priority: int = 0
    deadline_ms: Optional[float] = None
    # filled in by submit():
    rid: int = -1
    n: int = 0
    t_submit: float = 0.0
    deadline: float = 0.0
    # span context (utils/reqtrace.py): minted at submit, or provided by
    # an upstream minter (the supervisor) — it pickles across the worker
    # socket with the rest of the request, which is HOW one trace id
    # spans the process boundary: the worker's runtime adopts it in
    # _normalize and its stage spans re-parent under the upstream trace
    trace: Optional[Dict[str, Any]] = None


@dataclasses.dataclass
class ServeResult:
    """Base of the typed responses (``isinstance`` IS the status)."""

    rid: int
    latency_ms: float

    @property
    def status(self) -> str:
        return type(self).__name__.lower()


@dataclasses.dataclass
class Served(ServeResult):
    """Predictions for one request, sliced from its flush."""

    predictions: Any = None
    rung: int = 0
    deadline_missed: bool = False  # completed, but after the deadline
    # online-learning provenance: which published table snapshot answered
    # (the whole flush observed exactly this one version — never a
    # mid-publish mix), and how stale it was at flush time. -1 / None =
    # no snapshot installed (the classic frozen-table server)
    version: int = -1
    staleness_steps: Optional[float] = None
    staleness_s: Optional[float] = None
    # latency decomposition: one ``<stage>_ms`` entry per :data:`STAGES`
    # member; the five spans sum to ``latency_ms`` by construction
    # (queue wait is this request's own, the rest are its flush's)
    spans: Optional[Dict[str, float]] = None


@dataclasses.dataclass
class Overloaded(ServeResult):
    """Typed load-shed rejection: the admission controller refused the
    request (full queue, or shed level + low priority). The caller can
    retry after backing off — nothing about the request was wrong."""

    reason: str = "queue_full"
    level: int = 0
    queue_samples: int = 0
    # minimal decomposition: everything a shed request spent was queue
    # admission time (0 — refused at the door), kept span-shaped so the
    # unhealthy tail reads like the healthy one
    spans: Optional[Dict[str, float]] = None


@dataclasses.dataclass
class Expired(ServeResult):
    """The request's deadline passed while it was still queued — the
    scheduler dropped it instead of spending a rung on an answer nobody
    is waiting for. Counted ``deadline_missed``."""

    deadline_ms: float = 0.0
    # minimal decomposition: an expired request's whole life was queue
    # wait — ``{"queue_wait_ms": latency_ms}`` by construction
    spans: Optional[Dict[str, float]] = None


@dataclasses.dataclass
class Failed(ServeResult):
    """The flush this request was coalesced into raised (injected
    fault, transient backend error, a pred_fn bug): the request is
    consumed and answered TYPED instead of the exception escaping
    ``poll()`` and silently losing every co-batched request — one bad
    flush must never kill the serving loop. Counted ``failed``;
    recorded as a ``serve_flush_error`` event."""

    reason: str = ""
    # minimal decomposition: time from submit to the flush failure,
    # booked as queue wait (the flush's own spans died with it)
    spans: Optional[Dict[str, float]] = None


@dataclasses.dataclass
class Unavailable(ServeResult):
    """The serving WORKER is down: it crashed or hung and its
    supervisor is restarting it (or has exhausted the restart budget).
    One rung below ``stale_snapshot`` on the degradation ladder — a
    stale server still answers, a dead one answers TYPED: every request
    arriving during the outage (and every request that was in flight
    inside the dead worker) gets this instead of being lost or hanging
    a caller forever. ``outage_s`` is how long the worker had been down
    when this request arrived; ``restarts`` how many supervised
    restarts have been spent. Emitted only by the trainer-side
    ``parallel.supervisor.Supervisor`` — the in-process runtime cannot
    be "down" while it runs."""

    reason: str = "worker_down"
    outage_s: float = 0.0
    restarts: int = 0
    # minimal decomposition: how long the request waited before the
    # supervisor answered for the dead worker (0 when refused on
    # arrival, the stranded wait when answered by _on_worker_down)
    spans: Optional[Dict[str, float]] = None


# ----------------------------------------------------------- the runtime


class ServingRuntime:
    """Single-threaded deadline-bounded server around one compiled
    forward family.

    Usage::

        rt = ServingRuntime(de, pred_fn, state, mesh=mesh,
                            config=ServeConfig(max_batch=128))
        rt.warmup((template_cats, template_batch))
        rej = rt.submit(Request(cats=..., batch=...))  # None or Overloaded
        results += rt.poll()                           # flushes when due

    ``streaming=(StreamingConfig, streaming_state)`` serves dynamic
    tables read-only (cold ids degrade to their buckets; the state is
    never donated, never mutated). ``clock`` is injectable for
    deterministic tests; ``poll(now=...)`` accepts explicit time.
    """

    def __init__(self, de, pred_fn: Callable, state, mesh=None,
                 config: Optional[ServeConfig] = None,
                 streaming: Optional[tuple] = None,
                 clock: Callable[[], float] = time.monotonic,
                 trace: Optional[bool] = None):
        self.de = de
        self.config = config or ServeConfig()
        self.world = int(de.world_size)
        if self.world > 1 and mesh is None:
            raise ValueError("mesh is required for world_size > 1")
        if not de.dp_input:
            raise ValueError(
                "ServingRuntime requires dp_input=True: requests arrive "
                "as data-parallel id shards and ride the id exchange "
                "(pre-packed MpInputs cannot be coalesced per request)")
        self.rungs = resolve_rungs(self.config, self.world)
        # the installed (state, streaming_state, snapshot-meta) triple.
        # ONE reference, swapped atomically by install_snapshot() and read
        # ONCE per flush — a flush can never observe a mid-publish mix of
        # versions (the online runtime's no-torn-read contract). meta is
        # None for the classic frozen-table server, else
        # (version, train_step, published_t)
        self._published: Tuple[Any, Any, Optional[Tuple[int, int, float]]] \
            = (None, None, None)
        self.state = state
        self._clock = clock
        self._streaming_cfg = None
        self.streaming_state = None
        if streaming is not None:
            cfg, sstate = streaming
            self._streaming_cfg = streaming_mod.resolve_config(cfg)
            self.streaming_state = sstate
        self._eval = make_hybrid_eval_step(
            de, pred_fn, mesh=mesh, dynamic=self._streaming_cfg,
            donate_inputs=True)
        # writer-side state lock (reentrant: the staleness/level helpers
        # re-acquire it from already-locked callers). In realtime mode
        # ONE runtime is driven from three threads of control — the
        # RealtimeDriver submits/polls, the trainer installs snapshots,
        # the mplane exporter scrapes _collect — so every host-side
        # mutable (queue, outcome counters, freshness, ladder level)
        # mutates under this lock. The flush device path deliberately
        # stays OUTSIDE it: _published is read once per flush (RCU), so
        # publication never waits on device compute and vice versa
        self._state_lock = threading.RLock()
        self._queue: List[Request] = []
        self._queued_samples = 0
        self._level = 0
        self._next_rid = 0
        self._input_spec: Optional[List[tuple]] = None
        self._batch_spec: Optional[Any] = None
        self._est_s = 0.0           # EMA of flush wall seconds
        self._warm = False
        self.warmup_compiles = 0
        self._compiles_at_steady = 0
        # ---- observability plane (utils/mplane.py): every latency /
        # depth / freshness signal folds into a mergeable log-bucketed
        # sketch — O(buckets) memory however long the server lives (the
        # former raw lists grew to 2x STATS_WINDOW floats per signal and
        # full-sorted per stats() call), quantiles within the sketch's
        # guaranteed relative error, and per-rank sketches merge
        # associatively for a fleet view. stats() stays a VIEW over
        # these; the registry also renders the Prometheus scrape text
        self.metrics = mplane.MetricsRegistry()
        self._lat_sketch = self.metrics.sketch(
            "detpu_serve_latency_ms",
            "end-to-end served-request latency (ms)").child()
        stage_fam = self.metrics.sketch(
            "detpu_serve_stage_ms",
            "served-request latency decomposition by stage (ms)")
        # the plain (outcome-less) children below are the SERVED-only
        # partition stats() sums against end-to-end latency; terminal
        # non-served outcomes observe into outcome-labeled siblings via
        # _terminal_spans so the unhealthy tail is counted without
        # skewing that sum
        self._stage_fam = stage_fam
        self._stage_sketch = {s: stage_fam.child(stage=s) for s in STAGES}
        self._qdepth_sketch = self.metrics.sketch(
            "detpu_serve_queue_depth",
            "queued samples observed at each admitted submit").child()
        self._fresh_steps_sketch = self.metrics.sketch(
            "detpu_serve_staleness_steps",
            "per-response snapshot staleness (train steps)").child()
        self._fresh_s_sketch = self.metrics.sketch(
            "detpu_serve_staleness_s",
            "per-response snapshot age (seconds)").child()
        self.metrics.register_collector(self._collect)
        self._pad_slots = 0
        self._total_slots = 0
        self._rung_flushes: Dict[int, int] = {r: 0 for r in self.rungs}
        self._counts = {"served": 0, "shed": 0, "deadline_missed": 0,
                        "expired": 0, "failed": 0, "flushes": 0,
                        "served_samples": 0, "ragged_clipped": 0,
                        "degraded": 0, "recovered": 0,
                        "snapshots_installed": 0, "stale_shed": 0}
        # freshness SLO state (online learning, parallel/online.py): the
        # trainer's newest completed step vs the installed snapshot's.
        # Inert (stale never trips) until a snapshot is installed
        self._latest_train_step: Optional[int] = None
        self._stale = False
        self._freshness_max_steps = envvars.get_int(
            "DETPU_FRESHNESS_MAX_STEPS")
        self._freshness_max_s = envvars.get_float("DETPU_FRESHNESS_MAX_S")
        # ---- request tracing (utils/reqtrace.py): a trace per rid,
        # minted in _normalize (or adopted from Request.trace when an
        # upstream supervisor minted it), finished with the five-stage
        # partition in _run_flush or the minimal queue_wait span on a
        # terminal outcome. ``trace=None`` defers to DETPU_TRACE; the
        # bench passes explicit False/True to measure the delta
        self.traces = reqtrace.TraceBuffer(
            enabled=trace, process="serve", top_fn=self._trace_top_decile)

    def _trace_top_decile(self) -> Optional[float]:
        """Tail-retention threshold: the latency sketch's q90 once it
        has enough samples to mean something (None while cold — a cold
        threshold would retain everything and drown the sample)."""
        sk = self._lat_sketch
        return sk.quantile(0.9) if sk.count >= 20 else None

    def _terminal_spans(self, rid: int, outcome: str, latency_ms: float,
                        t_end: float, **attrs: Any) -> Dict[str, float]:
        """Book one terminal non-served outcome: observe its queue wait
        into the outcome-labeled stage sketch (the unhealthy tail stops
        under-counting) and finish its trace with the minimal
        ``{"queue_wait": latency_ms}`` partition — always retained, by
        the tail-sampling policy. Returns the ``spans`` dict the typed
        result carries (same ``<stage>_ms`` key shape as Served)."""
        lat = max(0.0, float(latency_ms))
        self._stage_fam.child(stage="queue_wait",
                              outcome=outcome).observe(lat)
        self.traces.finish(rid, outcome, latency_ms, t_end,
                           {"queue_wait": latency_ms}, **attrs)
        return {"queue_wait_ms": latency_ms}

    def _collect(self) -> None:
        """Scrape-time adapter: mirror the host counts and point-in-time
        gauges into the runtime's registry. The sketches observe inline
        on the hot path; everything countable syncs lazily, exactly when
        someone renders — scraping is the only cost of being scrapable."""
        mplane.sync_counters(self.metrics, self._counts,
                             name="detpu_serve_total", label="outcome")
        mplane.sync_counters(self.metrics, obs.counters())
        g = self.metrics.gauge
        g("detpu_serve_level",
          "degradation-ladder level (0 healthy, 1 pressure, 2 shed)"
          ).set(self._level)
        g("detpu_serve_queued_samples",
          "samples queued right now").set(self._queued_samples)
        g("detpu_serve_pad_fraction",
          "aggregate padded-slot fraction across flushes").set(
            self._pad_slots / self._total_slots if self._total_slots
            else 0.0)
        g("detpu_serve_steady_state_recompiles",
          "compiles since warmup (the 0-recompile contract)").set(
            self.steady_recompiles())
        g("detpu_serve_freshness_stale",
          "1 while the freshness SLO is breached").set(int(self._stale))
        g("detpu_serve_trace_ring",
          "tail-sampled request traces retained in the ring").set(
            self.traces.stats()["retained"])

    def _count(self, key: str, n: int = 1) -> None:
        """Bump one outcome counter under the state lock. A bare dict
        ``+=`` is a read-modify-write: concurrent bumps from the driver
        and trainer threads can lose increments (the concurrency
        auditor's first real finding in this file)."""
        with self._state_lock:
            self._counts[key] += n

    # --------------------------------------------- published table views

    @property
    def state(self):
        """The train state the compiled forward reads — the currently
        installed table view (a published snapshot under the online
        runtime, the construction-time state otherwise)."""
        return self._published[0]

    @state.setter
    def state(self, value) -> None:
        _, ss, meta = self._published
        # thread-local-ok: RCU — single-reference swap; construction /
        # checkpoint-restore path, before any concurrent serving
        self._published = (value, ss, meta)  # thread-local-ok: RCU swap

    @property
    def streaming_state(self):
        """Read-only streaming-vocab state of the installed view."""
        return self._published[1]

    @streaming_state.setter
    def streaming_state(self, value) -> None:
        st, _, meta = self._published
        # thread-local-ok: RCU — single-reference swap; construction /
        # checkpoint-restore path, before any concurrent serving
        self._published = (st, value, meta)  # thread-local-ok: RCU swap

    def install_snapshot(self, state, streaming_state=None, *,
                         version: int, train_step: int,
                         published_t: Optional[float] = None,
                         now: Optional[float] = None) -> None:
        """Atomically swap in one published table view (RCU reader side).

        The online runtime's :class:`~.online.SnapshotPublisher` calls
        this between polls with freshly copied buffers; ``version`` must
        be strictly monotonic (a regression raises — the versioning
        contract, not a recoverable condition). The swap is a single
        reference assignment and every flush reads the triple exactly
        once, so a flush observes exactly one version. The arrays must
        match the warmed-up state's structure/shapes/dtypes bitwise-in-
        spec, or the compiled ladder would retrace (the 0-steady-state-
        recompiles contract ``make check-online`` drills)."""
        now = self._clock() if now is None else now
        published_t = now if published_t is None else float(published_t)
        if self._streaming_cfg is not None and streaming_state is None:
            raise ValueError(
                "this runtime serves streaming tables: install_snapshot "
                "needs the matching streaming_state copy")
        with self._state_lock:
            # the version check is a check-then-act: it and the swap
            # must be one atom or two racing publishers could both pass
            meta = self._published[2]
            if meta is not None and version <= meta[0]:
                raise ValueError(
                    f"snapshot version must be monotonic: got {version}, "
                    f"installed {meta[0]}")
            self._published = (state, streaming_state,
                               (int(version), int(train_step),
                                published_t))
            # the snapshot IS the freshest trained view at publish time
            self._latest_train_step = int(train_step)
            self._counts["snapshots_installed"] += 1
            obs.counter_inc("snapshot_published")
            obs.record_event("snapshot_published", version=int(version),
                             train_step=int(train_step))
            self._refresh_staleness(now)

    def note_train_step(self, step: int, now: Optional[float] = None) -> None:
        """Tell the server how far training has advanced (the freshness
        reference point). When the installed snapshot falls more than
        ``DETPU_FRESHNESS_MAX_STEPS`` behind (or ages past
        ``DETPU_FRESHNESS_MAX_S``), the runtime enters its shed rung —
        load is refused serve-side (typed, ``reason="stale_snapshot"``)
        before the trainer is ever blocked on publication."""
        now = self._clock() if now is None else now
        with self._state_lock:
            if (self._latest_train_step is None
                    or step > self._latest_train_step):
                self._latest_train_step = int(step)
            self._refresh_staleness(now)

    def set_freshness_slo(self, max_steps: Optional[int] = None,
                          max_s: Optional[float] = None) -> None:
        """Override the env-default freshness SLO (the online runtime
        pushes its :class:`~.online.OnlineConfig` through here so one
        config governs publisher and server)."""
        with self._state_lock:
            if max_steps is not None:
                self._freshness_max_steps = int(max_steps)
            if max_s is not None:
                self._freshness_max_s = float(max_s)

    def _staleness(self, now: float) -> Optional[Tuple[int, float, float]]:
        """(version, lag_steps, age_s) of the installed snapshot, or
        ``None`` when no snapshot was ever installed."""
        meta = self._published[2]
        if meta is None:
            return None
        version, snap_step, pub_t = meta
        latest = (self._latest_train_step if self._latest_train_step
                  is not None else snap_step)
        return version, max(0, latest - snap_step), max(0.0, now - pub_t)

    def _refresh_staleness(self, now: float) -> None:
        # reentrant: install_snapshot/note_train_step call this with
        # the state lock already held; poll() calls it bare
        with self._state_lock:
            st = self._staleness(now)
            if st is None:
                return
            version, lag_steps, age_s = st
            stale = ((self._freshness_max_steps > 0
                      and lag_steps > self._freshness_max_steps)
                     or (self._freshness_max_s > 0
                         and age_s > self._freshness_max_s))
            if stale and not self._stale:
                obs.counter_inc("snapshot_lagging")
                obs.record_event("snapshot_lagging", version=version,
                                 lag_steps=int(lag_steps),
                                 age_s=float(age_s),
                                 max_steps=self._freshness_max_steps,
                                 max_s=self._freshness_max_s)
                logger.warning(
                    "serving snapshot v%d is STALE (%d step(s) / %.3f s "
                    "behind training) — entering the shed rung", version,
                    lag_steps, age_s)
                rec = mplane.flight_recorder()
                if rec is not None:
                    # freshness/SLO breach: park a post-mortem while the
                    # breach is live (the black box names the lagging
                    # version and carries the recent stats ring, plus
                    # the exemplar requests that led up to the breach)
                    rec.note_stats(self.stats())
                    for tr in self.traces.drain_new():
                        rec.note_trace(tr)
                    rec.dump("freshness_breach", version=int(version),
                             lag_steps=int(lag_steps), age_s=float(age_s))
            self._stale = stale
            self._update_level()

    @property
    def freshness_stale(self) -> bool:
        """Whether the freshness SLO is currently violated (the shed
        rung is forced on until the next publication)."""
        return self._stale

    # ------------------------------------------------------------ intake

    def _normalize(self, req: Request, now: float) -> Request:
        """Derive ``n``, validate shapes against the (template-derived)
        input spec, clip over-budget ragged rows, stamp the deadline."""
        if len(req.cats) != len(self.de.strategy.input_table_map):
            raise ValueError(
                f"request has {len(req.cats)} categorical inputs, the "
                f"model takes {len(self.de.strategy.input_table_map)}")
        spec = self._spec_of(req.cats, req.batch)
        with self._state_lock:
            # first-submit initialization is a check-then-act
            if self._input_spec is None:
                self._input_spec, self._batch_spec = spec
        if spec[0] != self._input_spec:
            raise ValueError(
                f"request input spec {spec[0]} does not match the "
                f"warmed-up spec {self._input_spec} — one compiled "
                "ladder serves one input layout")
        elif spec[1] != self._batch_spec:
            # reject HERE, while nothing is queued: a malformed batch
            # that only failed at pack time would crash the flush and
            # lose every healthy request coalesced with it
            raise ValueError(
                f"request batch spec {spec[1]} does not match the "
                f"warmed-up spec {self._batch_spec}")
        n = None
        for i, c in enumerate(req.cats):
            ni = len(c) if isinstance(c, (list, tuple)) \
                else int(np.asarray(c).shape[0])
            if n is None:
                n = ni
            elif n != ni:
                raise ValueError(
                    f"input {i} has {ni} samples, input 0 has {n}")
        if not n:
            raise ValueError("empty request")
        if n > self.rungs[-1]:
            raise ValueError(
                f"request of {n} samples exceeds the largest rung "
                f"{self.rungs[-1]} — split it client-side")
        hot = self.config.ragged_hotness
        cats = []
        for i, c in enumerate(req.cats):
            if isinstance(c, (list, tuple)):
                rows = []
                for row in c:
                    row = list(row)
                    if len(row) > hot:
                        self._count("ragged_clipped", len(row) - hot)
                        row = row[:hot]
                    rows.append(row)
                cats.append(rows)
            else:
                cats.append(np.asarray(c))
        req.cats = cats
        req.n = int(n)
        with self._state_lock:
            # rid assignment must be atomic or two racing submits can
            # share a rid (the result-matching key)
            req.rid = self._next_rid
            self._next_rid += 1
        req.t_submit = now
        dl = (req.deadline_ms if req.deadline_ms is not None
              else self.config.deadline_ms)
        req.deadline_ms = float(dl)
        req.deadline = now + dl / 1e3
        # trace mint point: every admitted-or-shed rid gets a span
        # context here; a context already on the request (the supervisor
        # minted upstream) is adopted, re-parenting this runtime's spans
        req.trace = self.traces.begin(req.rid, now, ctx=req.trace,
                                      priority=req.priority, n=req.n)
        return req

    def _spec_of(self, cats, batch) -> tuple:
        spec = []
        for c in cats:
            if isinstance(c, (list, tuple)):
                if self.config.ragged_hotness < 1:
                    raise ValueError(
                        "ragged (list-of-lists) input needs "
                        "ServeConfig(ragged_hotness=...) > 0")
                spec.append(("r", self.config.ragged_hotness))
            else:
                a = np.asarray(c)
                if a.ndim == 1:
                    spec.append(("d", 1))
                elif a.ndim == 2:
                    spec.append(("d", int(a.shape[1])))
                else:
                    raise ValueError(
                        f"categorical input rank {a.ndim} unsupported")
        bspec = jax.tree.map(
            lambda a: (tuple(np.asarray(a).shape[1:]),
                       np.asarray(a).dtype.str), batch)
        return spec, bspec

    def submit(self, req: Request,
               now: Optional[float] = None) -> Optional[Overloaded]:
        """Admit one request. Returns ``None`` (queued — the answer
        arrives from a later :meth:`poll`) or a typed
        :class:`Overloaded` when the admission controller sheds it."""
        now = self._clock() if now is None else now
        req = self._normalize(req, now)
        q = self._queued_samples
        shed_at = self.config.shed_frac * self.config.max_queue
        reason = None
        if q + req.n > self.config.max_queue:
            reason = "queue_full"
        elif self._stale and req.priority <= 0:
            # freshness rung: publication fell behind the SLO — refuse
            # low-priority load rather than serve ever-staler answers
            # (or block training to catch up)
            reason = "stale_snapshot"
        elif q >= shed_at and req.priority <= 0:
            reason = "load_shed"
        if reason is not None:
            self._count("shed")
            if reason == "stale_snapshot":
                self._count("stale_shed")
            obs.counter_inc("serve_shed")
            self._update_level()
            spans = self._terminal_spans(req.rid, "overloaded", 0.0, now,
                                         reason=reason, level=self._level,
                                         queue_samples=q)
            return Overloaded(rid=req.rid, latency_ms=0.0, reason=reason,
                              level=self._level, queue_samples=q,
                              spans=spans)
        with self._state_lock:
            self._queue.append(req)
            self._queued_samples += req.n
        self._qdepth_sketch.observe(self._queued_samples)
        self._update_level()
        return None

    @property
    def queued_samples(self) -> int:
        return self._queued_samples

    @property
    def level(self) -> int:
        """Current degradation-ladder level (0 healthy, 1 pressure,
        2 shed)."""
        return self._level

    # ------------------------------------------------- degradation ladder

    def _target_level(self, q: int) -> int:
        if self._stale:
            # the freshness rung rides the same ladder as queue pressure:
            # serve_degraded/serve_recovered events fire on the
            # transitions, and recovery is the next publication
            return 2
        if q >= self.config.shed_frac * self.config.max_queue:
            return 2
        if q >= self.rungs[-1]:
            return 1
        return 0

    def _set_level(self, new: int, q: int) -> None:
        # reentrant: reads-then-writes _level and fires the transition
        # event exactly once, however many threads race the transition
        with self._state_lock:
            old = self._level
            if new == old:
                return
            self._level = new
            if new > old:
                self._counts["degraded"] += 1
                obs.record_event("serve_degraded", level=new,
                                 from_level=old, level_name=LEVELS[new],
                                 queue_samples=q)
                logger.warning("serving degraded to %s (queue %d "
                               "samples)", LEVELS[new], q)
            else:
                self._counts["recovered"] += 1
                obs.record_event("serve_recovered", level=new,
                                 from_level=old, level_name=LEVELS[new],
                                 queue_samples=q)
                logger.info("serving recovered to %s (queue %d samples)",
                            LEVELS[new], q)

    def _update_level(self) -> None:
        with self._state_lock:
            self._set_level(self._target_level(self._queued_samples),
                            self._queued_samples)

    # ----------------------------------------------------------- packing

    def _rung_for(self, n: int) -> int:
        for r in self.rungs:
            if r >= n:
                return r
        return self.rungs[-1]

    def _zero_inputs(self, rung: int):
        """Zero-filled padded inputs of one rung (warmup / audit)."""
        if self._input_spec is None:
            raise RuntimeError("call warmup(template) first — the input "
                               "layout comes from the template request")
        return self._pack([], rung)

    def _pack(self, reqs: List[Request], rung: int):
        """Coalesce ``reqs`` (total samples <= rung) into one padded
        rung-shaped input set. Padding samples are whole fake rows: id 0
        everywhere, zero dense features, zero-length ragged rows —
        their predictions are sliced off below."""
        import jax.numpy as jnp

        spec, bspec = self._input_spec, self._batch_spec
        offsets = []
        off = 0
        for r in reqs:
            offsets.append(off)
            off += r.n
        cats_out = []
        for i, (kind, hot) in enumerate(spec):
            if kind == "d":
                shape = (rung,) if hot == 1 else (rung, hot)
                buf = np.zeros(shape, np.int32)
                for r, o in zip(reqs, offsets):
                    a = np.asarray(r.cats[i], np.int32)
                    buf[o:o + r.n] = a if hot > 1 or a.ndim == 1 \
                        else a.reshape(r.n)
                cats_out.append(jnp.asarray(buf))
            else:
                # ragged: per-SHARD CSR segments concatenated, so the
                # shard_map P(axis) split hands each rank a local
                # (values[cap_local], row_splits[b_local+1]) pair
                b_local = rung // self.world
                cap_local = b_local * hot
                values = np.zeros((self.world * cap_local,), np.int32)
                splits = np.zeros((self.world * (b_local + 1),), np.int32)
                row_lists: List[List[int]] = [[] for _ in range(rung)]
                for r, o in zip(reqs, offsets):
                    for j, row in enumerate(r.cats[i]):
                        row_lists[o + j] = row
                for s in range(self.world):
                    base = s * cap_local
                    pos = 0
                    sbase = s * (b_local + 1)
                    splits[sbase] = 0
                    for j in range(b_local):
                        row = row_lists[s * b_local + j]
                        values[base + pos:base + pos + len(row)] = row
                        pos += len(row)
                        splits[sbase + j + 1] = pos
                cats_out.append(Ragged(values=jnp.asarray(values),
                                       row_splits=jnp.asarray(splits)))

        def pack_leaf(path_spec, leaves):
            trailing, dtype = path_spec
            buf = np.zeros((rung,) + trailing, np.dtype(dtype))
            for r, o, leaf in zip(reqs, offsets, leaves):
                buf[o:o + r.n] = np.asarray(leaf)
            return jnp.asarray(buf)

        if bspec is None or not jax.tree.leaves(bspec):
            batch_out = bspec if bspec is None else jax.tree.map(
                lambda s: None, bspec)
        else:
            req_leaves = [jax.tree.leaves(r.batch) for r in reqs] or None
            flat_spec, tree = jax.tree_util.tree_flatten(
                self._batch_spec, is_leaf=lambda x: isinstance(x, tuple)
                and len(x) == 2 and isinstance(x[1], str))
            packed = []
            for li, s in enumerate(flat_spec):
                leaves = ([rl[li] for rl in req_leaves]
                          if req_leaves else [])
                packed.append(pack_leaf(s, leaves))
            batch_out = jax.tree_util.tree_unflatten(tree, packed)
        return cats_out, batch_out, offsets

    # ----------------------------------------------------------- serving

    def warmup(self, template) -> int:
        """Compile the whole ladder up front from a ``(cats, batch)``
        template (one representative request's inputs). Installs the
        compile listener and records the warmup compile count; after
        this, :meth:`steady_recompiles` must stay 0 whatever mix of
        request sizes arrives — the property ``make check-serving``
        drills. Returns the number of warmup compiles."""
        import warnings

        obs.install_compile_listener()
        cats, batch = template
        # thread-local-ok: warmup precedes serving — the driver/trainer
        # threads only start once the ladder is compiled
        self._input_spec, self._batch_spec = self._spec_of(cats, batch)  # thread-local-ok: warmup precedes serving
        before = obs.counters().get("recompiles", 0)
        for rung in self.rungs:
            c, b, _ = self._pack([], rung)
            with warnings.catch_warnings():
                # input donation is best-effort: a backend that cannot
                # alias an int32 id buffer into the f32 predictions
                # warns per compile — expected here, not actionable
                warnings.filterwarnings(
                    "ignore", message="Some donated buffers were not")
                out = self._dispatch(c, b)
            np.asarray(out)  # block: the compile must finish inside warmup
        self.warmup_compiles = obs.counters().get("recompiles", 0) - before  # thread-local-ok: warmup precedes serving
        self._compiles_at_steady = obs.counters().get("recompiles", 0)  # thread-local-ok: warmup precedes serving
        self._warm = True  # thread-local-ok: warmup precedes serving
        return self.warmup_compiles

    def steady_recompiles(self) -> int:
        """Compiles observed since :meth:`warmup` finished — the serving
        analogue of the bench's ``steady_state_recompiles`` gate."""
        if not self._warm:
            return 0
        return obs.counters().get("recompiles", 0) - self._compiles_at_steady

    def _dispatch(self, cats, batch, published=None):
        state, sstate, _ = (self._published if published is None
                            else published)
        if sstate is not None:
            return self._eval(state, cats, batch, sstate)
        return self._eval(state, cats, batch)

    def _run_flush(self, reqs: List[Request],
                   rung: int) -> List[Served]:
        runtime_mod.fault_point("serve_step")
        t0 = self._clock()
        # read the published triple ONCE: the whole flush — tables,
        # streaming state, version stamp — observes exactly this view,
        # however the publisher interleaves (the no-torn-read contract)
        published = self._published
        cats, batch, offsets = self._pack(reqs, rung)
        t_pack = self._clock()
        pending = self._dispatch(cats, batch, published)
        t_disp = self._clock()
        preds = np.asarray(pending)  # device compute + host fetch
        t_dev = self._clock()
        slices = [preds[o:o + r.n] for r, o in zip(reqs, offsets)]
        t1 = self._clock()
        with self._state_lock:
            # flush accounting only — the device work above ran
            # lock-free against the RCU-read published triple
            self._est_s = (t_dev - t0 if not self._est_s
                           else 0.7 * self._est_s + 0.3 * (t_dev - t0))
            n = sum(r.n for r in reqs)
            self._pad_slots += rung - n
            self._total_slots += rung
            self._counts["flushes"] += 1
            self._rung_flushes[rung] = self._rung_flushes.get(rung, 0) + 1
            # the flush ordinal doubles as the coalesce-span id linking
            # the N request traces that shared this flush
            flush_id = self._counts["flushes"]
        # latency decomposition: the flush-level spans are shared by
        # every coalesced request (they waited on the SAME pack /
        # dispatch / device / slice work); queue wait is per request.
        # The five spans sum to each request's latency by construction
        coalesce_ms = (t_pack - t0) * 1e3
        dispatch_ms = (t_disp - t_pack) * 1e3
        device_ms = (t_dev - t_disp) * 1e3
        reply_ms = (t1 - t_dev) * 1e3
        # per-response freshness: how stale the answering snapshot was at
        # flush time, in steps (vs the trainer's newest completed step)
        # and seconds (snapshot age) — the freshness SLO's raw samples
        meta = published[2]
        version = -1
        stale_steps: Optional[float] = None
        stale_s: Optional[float] = None
        if meta is not None:
            version, snap_step, pub_t = meta
            latest = (self._latest_train_step if self._latest_train_step
                      is not None else snap_step)
            stale_steps = float(max(0, latest - snap_step))
            stale_s = float(max(0.0, t_dev - pub_t))
        out = []
        for r, pred in zip(reqs, slices):
            lat = (t1 - r.t_submit) * 1e3
            queue_wait_ms = (t0 - r.t_submit) * 1e3
            missed = t1 > r.deadline
            spans = {"queue_wait_ms": queue_wait_ms,
                     "coalesce_ms": coalesce_ms,
                     "dispatch_ms": dispatch_ms,
                     "device_compute_ms": device_ms,
                     "reply_slice_ms": reply_ms}
            self._lat_sketch.observe(lat)
            for stage, v in zip(STAGES, spans.values()):
                self._stage_sketch[stage].observe(max(0.0, v))
            if meta is not None:
                self._fresh_steps_sketch.observe(stale_steps)
                self._fresh_s_sketch.observe(stale_s)
            self._count("served")
            self._count("served_samples", r.n)
            if missed:
                self._count("deadline_missed")
                obs.counter_inc("serve_deadline_missed")
            obs.counter_inc("serve_served")
            # the trace's stage partition is exactly the spans dict
            # (bare stage names): sum == latency_ms by the telescoping
            # construction above — the 1e-6 invariant check-tracing
            # asserts on every retained trace
            self.traces.finish(r.rid, "served", lat, t1,
                               dict(zip(STAGES, spans.values())),
                               flush=flush_id, coalesced=len(reqs),
                               rung=rung, flush_t0=t0, version=version,
                               deadline_missed=missed)
            out.append(Served(rid=r.rid, latency_ms=lat,
                              predictions=pred, rung=rung,
                              deadline_missed=missed, version=version,
                              staleness_steps=stale_steps,
                              staleness_s=stale_s, spans=spans))
        return out

    def poll(self, now: Optional[float] = None) -> List[ServeResult]:
        """Run the scheduler once: expire dead requests, flush every due
        batch, update the degradation level. Returns the completed
        results (:class:`Served` / :class:`Expired`); call it often —
        it is cheap when nothing is due."""
        out: List[ServeResult] = []
        explicit = now is not None
        # the seconds half of the freshness SLO can trip between
        # publications with no train-step notification — re-evaluate it
        # on the scheduler tick. Guarded so the classic (no-snapshot)
        # server keeps its exact clock-read sequence
        if self._published[2] is not None:
            self._refresh_staleness(now if explicit else self._clock())
        while True:
            t = now if explicit else self._clock()
            # deadline propagation, part 1: requests already past their
            # deadline are dead weight — drop them (typed) rather than
            # spend rung slots on them (strictly past: at exactly the
            # deadline the flush below still gets its chance)
            keep = []
            expired_now: List[Request] = []
            with self._state_lock:
                for r in self._queue:
                    if r.deadline < t:
                        self._queued_samples -= r.n
                        self._counts["expired"] += 1
                        self._counts["deadline_missed"] += 1
                        obs.counter_inc("serve_deadline_missed")
                        expired_now.append(r)
                    else:
                        keep.append(r)
                self._queue = keep
            # span booking outside the state lock (sketch + trace locks
            # are leaves; no reason to nest them under the queue's)
            for r in expired_now:
                lat = (t - r.t_submit) * 1e3
                spans = self._terminal_spans(r.rid, "expired", lat, t,
                                             deadline_ms=r.deadline_ms)
                out.append(Expired(rid=r.rid, latency_ms=lat,
                                   deadline_ms=r.deadline_ms,
                                   spans=spans))
            if not self._queue:
                break
            oldest = self._queue[0]
            full = self._queued_samples >= self.rungs[-1]
            # degradation ladder, level 1: under pressure the batching
            # delay shrinks to zero — latency is spent on compute only
            wait_s = (0.0 if self._level >= 1
                      else self.config.max_wait_ms / 1e3)
            timed_out = t >= oldest.t_submit + wait_s
            # deadline propagation, part 2: flush early when the
            # TIGHTEST queued deadline (not necessarily the oldest
            # request's) would be missed by waiting any longer (the
            # flush itself costs ~est_s)
            tightest = min(r.deadline for r in self._queue)
            deadline_due = t + self._est_s >= tightest
            if not (full or timed_out or deadline_due):
                break
            out.extend(self._flush_picked())
        self._update_level()
        return out

    def _flush_picked(self) -> List[ServeResult]:
        """Pop one rung's worth of requests FIFO and run the flush.
        Shared by :meth:`poll` and :meth:`flush` (ONE packing policy);
        a flush that raises answers its requests with typed
        :class:`Failed` instead of letting the exception escape and
        lose every co-batched request."""
        picked: List[Request] = []
        total = 0
        with self._state_lock:
            while (self._queue
                   and total + self._queue[0].n <= self.rungs[-1]):
                r = self._queue.pop(0)
                picked.append(r)
                total += r.n
            self._queued_samples -= total
        try:
            return self._run_flush(picked, self._rung_for(total))
        except Exception as e:  # noqa: BLE001 - typed failure, see Failed
            self._count("failed", len(picked))
            obs.counter_inc("serve_failed", len(picked))
            obs.record_event("serve_flush_error", error=repr(e),
                             requests=len(picked))
            logger.exception("serve flush failed (%d request(s) answered "
                             "Failed)", len(picked))
            t = self._clock()
            return [Failed(rid=r.rid,
                           latency_ms=(t - r.t_submit) * 1e3,
                           reason=repr(e),
                           spans=self._terminal_spans(
                               r.rid, "failed",
                               (t - r.t_submit) * 1e3, t,
                               reason=repr(e))) for r in picked]

    def flush(self, now: Optional[float] = None) -> List[ServeResult]:
        """Force every queued request out (drain), regardless of the
        batching delay — shutdown / test helper."""
        del now  # kept for signature symmetry with poll()
        out: List[ServeResult] = []
        while self._queue:
            out.extend(self._flush_picked())
        self._update_level()
        return out

    # ------------------------------------------------------------- stats

    def stats(self) -> Dict[str, Any]:
        """Host summary: counts, latency percentiles over served
        requests, aggregate pad fraction, queue-depth p95, recompile
        verdicts — the dict the bench section and the check drill
        read. Percentiles come from the registry's mergeable
        log-bucketed sketches (bounded memory, no full sort); every
        key that predates the sketch migration is preserved as a view,
        plus ``latency_stages_ms`` / ``p99_dominant_stage`` — the
        p99-attribution instrument."""
        lat = self._lat_sketch
        pct = ((lambda p: lat.quantile(p / 100.0)) if lat.count
               else (lambda p: None))
        stages: Dict[str, Dict[str, float]] = {}
        for stage in STAGES:
            sk = self._stage_sketch[stage]
            if not sk.count:
                continue
            stages[stage] = {
                "p50": sk.quantile(0.50), "p95": sk.quantile(0.95),
                "p99": sk.quantile(0.99), "mean": sk.mean,
                "sum": sk.sum, "count": sk.count,
            }
        dominant = (max(stages, key=lambda s: stages[s]["p99"])
                    if stages else None)
        # the unhealthy tail, by outcome: the outcome-labeled siblings
        # _terminal_spans observes (kept OUT of latency_stages_ms so the
        # served partition still sums against served latency)
        unhealthy: Dict[str, Dict[str, float]] = {}
        for key, sk in self._stage_fam.items():
            oc = dict(key).get("outcome")
            if oc and sk.count:
                unhealthy[oc] = {"p95": sk.quantile(0.95),
                                 "p99": sk.quantile(0.99),
                                 "sum": sk.sum, "count": sk.count}
        meta = self._published[2]
        return {
            **self._counts,
            "level": self._level,
            "level_name": LEVELS[self._level],
            "queued_samples": self._queued_samples,
            "latency_p50_ms": pct(50),
            "latency_p95_ms": pct(95),
            "latency_p99_ms": pct(99),
            "latency_stages_ms": stages,
            "p99_dominant_stage": dominant,
            "latency_stages_unhealthy_ms": unhealthy,
            # exemplar join: the slowest retained traces with their
            # per-stage breakdowns — the p99 is no longer just a number,
            # it names requests
            "p99_exemplars": self.traces.exemplars(5),
            "trace": self.traces.stats(),
            "pad_fraction": (self._pad_slots / self._total_slots
                             if self._total_slots else 0.0),
            "queue_depth_p95": (self._qdepth_sketch.quantile(0.95)
                                if self._qdepth_sketch.count else 0.0),
            "rung_flushes": {str(k): v
                             for k, v in sorted(self._rung_flushes.items())
                             if v},
            "warmup_compiles": self.warmup_compiles,
            "steady_state_recompiles": self.steady_recompiles(),
            "est_flush_ms": self._est_s * 1e3,
            "shed_frac_of_submitted": (self._counts["shed"] / self._next_rid
                                       if self._next_rid else 0.0),
            # freshness SLO, next to p99 (None until a snapshot serves)
            "freshness_p95_steps": (self._fresh_steps_sketch.quantile(0.95)
                                    if self._fresh_steps_sketch.count
                                    else None),
            "freshness_p95_s": (self._fresh_s_sketch.quantile(0.95)
                                if self._fresh_s_sketch.count else None),
            "snapshot_version": meta[0] if meta is not None else None,
            "snapshot_train_step": meta[1] if meta is not None else None,
            "freshness_stale": bool(self._stale),
        }


# ------------------------------------------------------------------ audit


def audit_serve_program(rt: ServingRuntime, rung: Optional[int] = None,
                        expected: Optional[Dict[str, Any]] = None,
                        expected_donated: Optional[int] = None):
    """Static census of the compiled serve program (one rung): traces
    the forward abstractly and enforces the forward-only contract —
    id + output exchange and NOTHING else (no grad exchange, no psum,
    never an all_gather), no host interop, no f64. The serving twin of
    ``audit_train_step``; ``tests/test_serving.py`` and the check drill
    run it so a pred_fn that quietly pays training-shaped communication
    per request cannot ship.

    Input donation is reported but not required by default
    (``expected_donated=None``): it is best-effort — a backend that
    cannot alias an int32 id buffer into the f32 predictions drops the
    marker at lowering (the CPU proxy always does), which is a missed
    optimization, not a correctness hole. Pass the donated leaf count
    to enforce it on a backend where aliasing is expected to stick."""
    from ..analysis import audit as audit_mod

    rung = rung or rt.rungs[0]
    cats, batch, _ = rt._zero_inputs(rung)
    args: tuple = (rt.state, cats, batch)
    if rt.streaming_state is not None:
        args = args + (rt.streaming_state,)
    if expected is None:
        expected = audit_mod.expected_eval_collectives(rt.de)
    return audit_mod.audit_step_fn(
        rt._eval, args, world=rt.world, dp_input=rt.de.dp_input,
        expected=expected, expected_donated=expected_donated,
        label=f"serve_rung{rung}")


# ---------------------------------------------------- load gen + driving


def synthetic_request(rng: np.random.Generator, table_sizes: Sequence[int],
                      n: int, *, numerical: int = 0,
                      ragged: Sequence[int] = (),
                      ragged_hotness: int = 4,
                      alpha: float = 1.05,
                      id_offset: int = 0,
                      priority: int = 0) -> Request:
    """One seeded Zipfian request: ``n`` samples of power-law ids per
    table (``ragged`` table indices get variable-length id lists up to
    ``ragged_hotness``), plus an ``[n, numerical]`` dense block when
    ``numerical`` > 0. ``id_offset`` shifts ids (streaming-table
    drills feed external-id spaces through it)."""
    from ..utils.data import power_law_ids

    cats: List[Any] = []
    for i, v in enumerate(table_sizes):
        if i in ragged:
            lens = rng.integers(0, ragged_hotness + 1, size=n)
            cats.append([
                list(power_law_ids(rng, v, (int(k),), alpha=alpha)
                     + id_offset) for k in lens])
        else:
            cats.append(np.asarray(
                power_law_ids(rng, v, (n,), alpha=alpha) + id_offset,
                np.int32))
    batch = (np.asarray(rng.normal(size=(n, numerical)), np.float32)
             if numerical else None)
    return Request(cats=cats, batch=batch, priority=priority)


class RealtimeDriver:
    """Wall-clock open-loop load driver on its OWN thread of control.

    The process-isolation layer (ISSUE 18) needs serving load that is
    concurrent with the trainer — not step-paced pumping interleaved
    with train steps — so that ``freshness_p95_s`` measures TRUE
    wall-clock staleness: the driver thread submits and polls in real
    time while the trainer thread publishes snapshots whenever ITS loop
    gets there. Works against anything with the ``submit``/``poll``
    surface: the in-process :class:`ServingRuntime` or the trainer-side
    ``parallel.supervisor.Supervisor`` proxy for an out-of-process
    worker.

    Arrival generation matches :func:`drive` (fixed ``qps``; whole
    seconds named in ``burst_positions`` multiply the rate by
    ``burst_x``; open-loop, so a slow backend piles real pressure onto
    the admission controller instead of stalling the generator).
    ``duration_s=None`` runs until :meth:`stop` — the supervised-outage
    drill kills and restarts the worker mid-stream and needs load that
    simply keeps arriving.

    Usage::

        drv = RealtimeDriver(rt, make_request, qps=200, duration_s=2.0)
        drv.start()
        ...                      # trainer keeps training + publishing
        drv.join()               # waits for the stream + drain
        results = drv.results()
    """

    # state the driver thread and its caller both touch (detlint
    # thread-shared): _results is guarded by _lock; submitted is
    # written once by the driver thread at stream end and read by the
    # caller only after join()
    _THREAD_SHARED = ("_results", "submitted")

    def __init__(self, server, make_request: Callable[[int], Request],
                 qps: float, *, duration_s: Optional[float] = None,
                 burst_positions: Optional[Sequence[int]] = None,
                 burst_x: Optional[float] = None, drain_s: float = 10.0,
                 clock: Callable[[], float] = time.monotonic):
        if burst_positions is None:
            burst_positions = runtime_mod.burst_steps()
        if burst_x is None:
            burst_x = envvars.get_float("DETPU_SERVE_BURST_X")
        self._server = server
        self._make_request = make_request
        self._qps = float(qps)
        self._duration_s = duration_s
        self._burst = set(int(p) for p in burst_positions)
        self._burst_x = float(burst_x)
        self._drain_s = float(drain_s)
        self._clock = clock
        self._results: List[ServeResult] = []
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.submitted = 0

    # ------------------------------------------------------------ control

    def start(self) -> "RealtimeDriver":
        if self._thread is not None:
            raise RuntimeError("driver already started")
        self._thread = threading.Thread(
            target=self._run, name="detpu-serve-driver", daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        """Stop generating arrivals; the loop still drains the queue
        (in-flight requests get real answers, not silence)."""
        self._stop.set()

    def join(self, timeout: Optional[float] = None) -> None:
        if self._thread is None:
            raise RuntimeError("driver never started")
        self._thread.join(timeout)

    def results(self) -> List[ServeResult]:
        """Everything collected so far (the full stream after
        :meth:`join`); safe to call from any thread."""
        with self._lock:
            return list(self._results)

    # --------------------------------------------------------- the loop

    def _collect(self, out: Sequence[ServeResult]) -> None:
        if out:
            with self._lock:
                self._results.extend(out)

    def _run(self) -> None:
        start = self._clock()
        next_t, i = 0.0, 0
        while not self._stop.is_set() and (
                self._duration_s is None or next_t < self._duration_s):
            now = self._clock() - start
            while next_t <= now and (
                    self._duration_s is None or next_t < self._duration_s):
                rej = self._server.submit(self._make_request(i))
                if rej is not None:
                    self._collect([rej])
                i += 1
                rate = self._qps * (self._burst_x
                                    if int(next_t) in self._burst else 1.0)
                next_t += 1.0 / rate
                if self._stop.is_set():
                    break
            self._collect(self._server.poll())
            wait = next_t - (self._clock() - start)
            if wait > 0:
                time.sleep(min(0.0005, wait))  # poll tick, 0.5 ms cap
        self.submitted = i  # thread-local-ok: single write by the driver thread at stream end; callers read after join()
        deadline = self._clock() + self._drain_s
        while (getattr(self._server, "queued_samples", 0)
               and self._clock() < deadline):
            self._collect(self._server.poll())
            time.sleep(0.0005)
        self._collect(self._server.poll())


def drive(rt: ServingRuntime, make_request: Callable[[int], Request],
          qps: float, duration_s: float, *,
          burst_positions: Optional[Sequence[int]] = None,
          burst_x: Optional[float] = None,
          drain_s: float = 10.0) -> List[ServeResult]:
    """Real-time load loop the tools share: submit ``make_request(i)``
    at a fixed ``qps`` for ``duration_s`` seconds, polling the runtime
    between arrivals, then drain.

    ``burst_positions`` (default: :func:`~..utils.runtime.burst_steps`
    — the ``DETPU_FAULT=burst@<pos>`` drill) names whole seconds of the
    stream during which the arrival rate multiplies by ``burst_x``
    (default ``DETPU_SERVE_BURST_X``) — the QPS-spike injection,
    deterministic per position: the same positions always spike, only
    wall-clock jitter differs run to run.

    Since ISSUE 18 this is a thin synchronous wrapper over
    :class:`RealtimeDriver` — ONE arrival/poll loop serves both the
    blocking tools and the concurrent train-while-serve drills — so the
    load runs on the driver's own thread even here (the calling thread
    just waits)."""
    drv = RealtimeDriver(rt, make_request, qps, duration_s=duration_s,
                         burst_positions=burst_positions, burst_x=burst_x,
                         drain_s=drain_s, clock=rt._clock)
    drv.start()
    drv.join()
    return drv.results()

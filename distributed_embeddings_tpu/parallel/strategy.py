"""Table-placement planner for hybrid-parallel embeddings.

Pure-Python port of the reference's ``DistEmbeddingStrategy``
(``distributed_embeddings/python/layers/dist_model_parallel.py:25-196``): the
planning algorithms are device-agnostic and carry over to TPU unchanged —
only the executor around them differs. Every rank computes the identical global
plan (SPMD-friendly: on TPU the "ranks" are mesh positions in one program).

Planned artifacts (names kept aligned with the reference for parity auditing):

* ``table_ids_list[r]``      — global (sliced) table ids owned by rank ``r``
* ``local_configs_list[r]``  — configs of the tables rank ``r`` owns
* ``input_ids_list[r]``      — global input indices routed to rank ``r``
* ``local_map_list[r]``      — local input → local table map on rank ``r``
* ``widths_list_flat``       — output widths in (rank-major) worker order
* ``rev_global_input_ids``   — permutation restoring caller input order
* ``sliced_out_ranges``      — output ranges to re-concat after column slicing
"""

from __future__ import annotations

import json
from collections import Counter, defaultdict
from typing import Any, Dict, List, Optional, Sequence


Config = Dict[str, Any]

_STRATEGIES = ("basic", "memory_balanced", "memory_optimized",
               "comm_balanced", "telemetry_balanced")


def _table_elements(config: Config) -> int:
    return int(config["input_dim"]) * int(config["output_dim"])


def maybe_slice_table_column(orig_config: Config,
                             column_slice_threshold: Optional[int],
                             world_size: int) -> List[Config]:
    """Split a table width-wise into the smallest power-of-2 number of slices
    that brings each slice under ``column_slice_threshold`` elements, capped at
    ``min(world_size, output_dim)``; width remainder spread over the first
    slices (reference ``dist_model_parallel.py:100-131``)."""
    if column_slice_threshold is None:
        return [dict(orig_config)]
    elements = _table_elements(orig_config)
    num_slices = 1
    while elements > column_slice_threshold * num_slices:
        num_slices *= 2
    if num_slices == 1:
        return [dict(orig_config)]
    num_slices = min(num_slices, world_size, int(orig_config["output_dim"]))
    base, rem = divmod(int(orig_config["output_dim"]), num_slices)
    slices = []
    for i in range(num_slices):
        cfg = dict(orig_config)
        cfg["output_dim"] = base + (1 if i < rem else 0)
        slices.append(cfg)
    return slices


def maybe_slice_table_row(orig_config: Config,
                          row_slice_threshold: Optional[int],
                          world_size: int) -> List[Config]:
    """Split a table row-wise (vocab ranges) into the smallest power-of-2
    number of slices that brings each slice under ``row_slice_threshold``
    elements, capped at ``min(world_size, input_dim)``; row remainder spread
    over the first slices. Each slice carries its first global row in
    ``_row_base`` (consumed by the exchange plan and checkpoint paths).

    The reference declares-but-never-implements this mode
    (``dist_model_parallel.py:225,233-234``); semantics here mirror
    :func:`maybe_slice_table_column` with rows in place of columns. Unlike
    column slices (every slice serves every id, outputs concatenate), a row
    slice serves only ids inside its range — out-of-range ids read as zero
    rows — and slice outputs SUM.
    """
    if row_slice_threshold is None:
        return [dict(orig_config)]
    elements = _table_elements(orig_config)
    num_slices = 1
    while elements > row_slice_threshold * num_slices:
        num_slices *= 2
    if num_slices == 1:
        return [dict(orig_config)]
    num_slices = min(num_slices, world_size, int(orig_config["input_dim"]))
    base, rem = divmod(int(orig_config["input_dim"]), num_slices)
    slices, row_base = [], 0
    for i in range(num_slices):
        cfg = dict(orig_config)
        cfg["input_dim"] = base + (1 if i < rem else 0)
        cfg["_row_base"] = row_base
        row_base += cfg["input_dim"]
        slices.append(cfg)
    return slices


def apply_strategy(mode: str, world_size: int,
                   sliced_configs: List[List[Config]],
                   input_table_map: Optional[Sequence[int]] = None,
                   input_hotness: Optional[Sequence[int]] = None,
                   table_loads: Optional[Sequence[float]] = None
                   ) -> List[List[int]]:
    """Assign sliced tables to ranks; returns per-rank lists of global table ids
    (reference ``dist_model_parallel.py:160-196``).

    * ``basic``: round-robin in id order.
    * ``memory_balanced``: size-sorted snake deal — keeps per-rank table counts
      even while balancing bytes.
    * ``memory_optimized``: greedy largest-first onto the least-loaded rank —
      best byte balance, table counts may skew.
    * ``comm_balanced``: balances the *exchange*, not just bytes. The
      executor's output all-to-all pads each (width, hotness) slot group to
      the max per-rank slot count (``parallel/plan.py``), so skewed per-group
      counts turn into padded exchange bytes (measured 40%+ waste under
      ``memory_optimized`` on the tiny/small zoo, ``docs/perf_tpu.md``).
      Each table's group footprint — one slot in group ``(width, h)`` per
      input of hotness ``h`` it serves (hotness from ``input_hotness`` when
      given, else assumed 1) — is placed greedily, largest footprint first,
      on the rank that minimally grows the total padded exchange width
      ``sum_g w_g * max_r n_{g,r}``, tie-broken by byte load. Directly
      minimizes the executor's padding objective while keeping bytes close.
    * ``telemetry_balanced``: balances MEASURED per-table traffic
      (``table_loads``, e.g. from
      :func:`...analysis.telemetry.table_loads_from_summary`) instead of
      bytes — the feedback half of the telemetry observatory (ROADMAP
      item 2b). Slices are placed greedily, heaviest measured load first,
      on the least-loaded rank (ties broken by byte load, then rank id).
      A table's load spreads evenly over its slices — exact for column
      slices' bytes-per-id and the uniform-range approximation for row
      slices (per-range traffic is not in the summary). Cold tables
      (load 0) fall back to pure byte balancing via the tie-break.
    """
    flat_ids: List[int] = []
    flat_sizes: List[int] = []
    flat_widths: List[int] = []
    for tid, slices in enumerate(sliced_configs):
        for cfg in slices:
            flat_ids.append(tid)
            flat_sizes.append(_table_elements(cfg))
            flat_widths.append(int(cfg["output_dim"]))

    if mode == "basic":
        return [flat_ids[r::world_size] for r in range(world_size)]

    if mode == "memory_balanced":
        order = [tid for _, tid in
                 sorted(zip(flat_sizes, flat_ids), reverse=True)]
        period = 2 * world_size
        return [order[r::period] + order[period - 1 - r::period]
                for r in range(world_size)]

    if mode == "memory_optimized":
        by_size = sorted(zip(flat_sizes, flat_ids))
        bins: List[List[Any]] = [[0, []] for _ in range(world_size)]
        while by_size:
            size, tid = by_size.pop()
            bins[0][0] += size
            bins[0][1].append(tid)
            bins.sort()
        return [b[1] for b in bins]

    if mode == "comm_balanced":
        itm = (list(input_table_map) if input_table_map is not None
               else list(range(len(sliced_configs))))
        hot = (list(input_hotness) if input_hotness is not None
               else [1] * len(itm))
        # hotness multiset per source table; every slice of it inherits
        table_hots: Dict[int, Counter] = defaultdict(Counter)
        for i, tid in enumerate(itm):
            table_hots[tid][int(hot[i])] += 1
        # slice footprint: slots contributed per (width, hotness) group.
        # NOTE (ADVICE r3): slice widths are modeled by flat position, but
        # DistEmbeddingStrategy hands a table's slices to ranks FIFO in rank
        # order, so when the width remainder spreads base+1 columns over the
        # first slices, the slice a rank receives can be one column narrower/
        # wider than the one this objective counted. Bounded by one column
        # per (table, rank) pair — noise next to the padding term — so the
        # modeling error is accepted rather than threading slice identity
        # through the assignment.
        items = []
        for pos, (tid, size, w) in enumerate(
                zip(flat_ids, flat_sizes, flat_widths)):
            groups = {(w, h): c for h, c in table_hots[tid].items()}
            fp = w * sum(table_hots[tid].values())  # output columns it adds
            items.append((fp, size, pos, tid, groups))
        items.sort(key=lambda t: (-t[0], -t[1], t[2]))  # LPT on columns
        n: Dict[tuple, List[int]] = defaultdict(lambda: [0] * world_size)
        loads = [0] * world_size
        out: List[List[tuple]] = [[] for _ in range(world_size)]
        for fp, size, pos, tid, groups in items:
            best, best_key = None, None
            for r in range(world_size):
                # marginal growth of the padded exchange width
                delta = 0
                for (w, h), c in groups.items():
                    cur_max = max(n[(w, h)])
                    delta += w * max(0, n[(w, h)][r] + c - cur_max)
                key = (delta, loads[r], r)
                if best_key is None or key < best_key:
                    best, best_key = r, key
            out[best].append((pos, tid))
            loads[best] += size
            for (w, h), c in groups.items():
                n[(w, h)][best] += c
        return [[tid for _, tid in sorted(rank)] for rank in out]

    if mode == "telemetry_balanced":
        if table_loads is None:
            raise ValueError(
                "telemetry_balanced needs table_loads= (per-global-table "
                "measured traffic, e.g. analysis.telemetry."
                "table_loads_from_summary of a flushed telemetry summary)")
        if len(table_loads) != len(sliced_configs):
            raise ValueError(
                f"table_loads has {len(table_loads)} entries but there are "
                f"{len(sliced_configs)} tables (it is per-table)")
        per_slice_load = [float(table_loads[tid]) / len(sliced_configs[tid])
                          for tid in flat_ids]
        # LPT on measured load; stable position index keeps ties
        # deterministic across processes (every rank must plan identically)
        order = sorted(range(len(flat_ids)),
                       key=lambda i: (-per_slice_load[i], -flat_sizes[i], i))
        loads = [0.0] * world_size
        sizes = [0] * world_size
        out = [[] for _ in range(world_size)]
        for i in order:
            r = min(range(world_size),
                    key=lambda r: (loads[r], sizes[r], r))
            out[r].append((i, flat_ids[i]))
            loads[r] += per_slice_load[i]
            sizes[r] += flat_sizes[i]
        return [[tid for _, tid in sorted(rank)] for rank in out]

    raise ValueError(f"Unsupported strategy {mode}")


# ------------------------------------------------------- plan fingerprints


#: plan_spec keys that determine the physical layout of checkpointed state.
#: Two plans whose material keys match restore identically regardless of
#: the strategy LABEL that produced them (e.g. a basic and a
#: memory_balanced plan that happen to agree).
_MATERIAL_PLAN_KEYS = ("world_size", "table_ids_list", "local_tables")


def _canon(x):
    """JSON-normalize (tuples -> lists, numpy ints -> ints) so specs read
    back from a ``meta.json`` compare equal to freshly computed ones."""
    return json.loads(json.dumps(x))


def plans_equal(a: Optional[Dict[str, Any]],
                b: Optional[Dict[str, Any]]) -> bool:
    """Material equality of two :meth:`DistEmbeddingStrategy.plan_spec`
    dicts: same world size, same rank->tables assignment, same per-rank
    slice geometry. The strategy *name* and thresholds are advisory (they
    describe how the plan was derived, not what it is)."""
    if a is None or b is None:
        return False
    return all(_canon(a.get(k)) == _canon(b.get(k))
               for k in _MATERIAL_PLAN_KEYS)


def plan_diff(old: Optional[Dict[str, Any]], new: Dict[str, Any],
              param_bytes: int = 4) -> Dict[str, Any]:
    """Structured diff of two plan specs — what the re-shard dry run
    prints and what the degradation log records on an elastic resume.

    Returns world sizes, strategy labels, per-rank byte loads under both
    plans (``param_bytes`` per table element; pass 2 for bf16 tables),
    per-rank deltas over the common ranks, and the tables whose owning
    rank set changed. ``old`` may be ``None`` (pre-plan-manifest
    checkpoint): the old half is then reported as unknown."""
    def rank_bytes(spec):
        if spec is None or "per_rank_elements" not in spec:
            return None
        return [int(e) * param_bytes for e in spec["per_rank_elements"]]

    def owners(spec):
        if spec is None:
            return {}
        own: Dict[int, List[int]] = {}
        for r, tids in enumerate(spec.get("table_ids_list", [])):
            for tid in tids:
                own.setdefault(int(tid), []).append(r)
        return own

    old_b, new_b = rank_bytes(old), rank_bytes(new)
    deltas = None
    if old_b is not None and new_b is not None:
        deltas = [new_b[r] - old_b[r]
                  for r in range(min(len(old_b), len(new_b)))]
    old_own, new_own = owners(old), owners(new)
    moved = sorted(t for t in new_own
                   if old_own and old_own.get(t) != new_own[t])
    return {
        "equal": plans_equal(old, new),
        "world_size": [old.get("world_size") if old else None,
                       new.get("world_size")],
        "strategy": [old.get("strategy") if old else None,
                     new.get("strategy")],
        "per_rank_bytes_old": old_b,
        "per_rank_bytes_new": new_b,
        "per_rank_byte_deltas": deltas,
        "moved_tables": moved,
    }


class DistEmbeddingStrategy:
    """Global placement plan: slicing, rank assignment, and routing index maps.

    Args:
      configs: per-table config dicts (must carry ``input_dim``/``output_dim``;
        other keys — initializer, combiner, dtype — pass through to the local
        table configs). Accepts :class:`...layers.Embedding` modules too.
      world_size: number of model-parallel positions on the mesh axis.
      strategy: one of ``basic | memory_balanced | memory_optimized``.
      input_table_map: ``input[i]`` looks up ``table[input_table_map[i]]``;
        ``None`` means the identity (shared tables = repeated ids).
      column_slice_threshold: max elements per table slice (power-of-2 split).
      input_hotness: optional per-input hotness hint used only by the
        ``comm_balanced`` strategy to model the executor's (width, hotness)
        exchange groups exactly; placement stays valid without it.
      table_loads: per-global-table measured traffic weights, required by
        (and only used by) the ``telemetry_balanced`` strategy — feed it
        :func:`...analysis.telemetry.table_loads_from_summary` of a
        flushed telemetry summary.
    """

    def __init__(self,
                 configs: Sequence[Any],
                 world_size: int,
                 strategy: str = "basic",
                 input_table_map: Optional[Sequence[int]] = None,
                 column_slice_threshold: Optional[int] = None,
                 input_hotness: Optional[Sequence[int]] = None,
                 row_slice_threshold: Optional[int] = None,
                 table_loads: Optional[Sequence[float]] = None):
        if strategy not in _STRATEGIES:
            raise ValueError(f"Unsupported shard strategy {strategy}")
        self.strategy = strategy
        self.world_size = world_size
        self.column_slice_threshold = column_slice_threshold
        self.row_slice_threshold = row_slice_threshold
        self.table_loads = (None if table_loads is None
                            else [float(x) for x in table_loads])
        self.global_configs = [
            c.get_config() if hasattr(c, "get_config") else dict(c)
            for c in configs]
        if input_table_map is None:
            input_table_map = list(range(len(self.global_configs)))
        if len(input_table_map) and max(input_table_map) >= len(self.global_configs):
            raise ValueError("input_table_map refers to a nonexistent table")
        self.input_table_map = list(input_table_map)
        if (input_hotness is not None
                and len(input_hotness) != len(self.input_table_map)):
            raise ValueError(
                f"input_hotness has {len(input_hotness)} entries but there "
                f"are {len(self.input_table_map)} inputs (it is per-input, "
                "not per-table)")
        if (self.table_loads is not None
                and len(self.table_loads) != len(self.global_configs)):
            raise ValueError(
                f"table_loads has {len(self.table_loads)} entries but "
                f"there are {len(self.global_configs)} tables")

        if world_size == 1:
            self.local_configs = self.global_configs
            self.local_input_table_map = self.input_table_map
            self.input_ids_list = [list(range(len(self.input_table_map)))]
            self.table_ids_list = [list(range(len(self.global_configs)))]
            self.local_configs_list = [self.global_configs]
            self.local_map_list = [self.local_input_table_map]
            self.widths_list_flat = [
                int(self.global_configs[t]["output_dim"])
                for t in self.input_table_map]
            self.rev_global_input_ids = list(range(len(self.input_table_map)))
            self.sliced_out_ranges = []
            self.row_sliced_out_ranges = []
            self.row_sliced_tables = set()
            return

        (sliced_configs, self.sliced_out_ranges,
         self.row_sliced_out_ranges, self.row_sliced_tables) = \
            self.create_sliced_configs(
                world_size, column_slice_threshold, self.input_table_map,
                row_slice_threshold)
        self.table_ids_list = apply_strategy(strategy, world_size,
                                             sliced_configs,
                                             self.input_table_map,
                                             input_hotness,
                                             table_loads=self.table_loads)

        # Build the global routing view, consuming each table's slices in rank
        # order (reference dist_model_parallel.py:70-98).
        remaining = [list(slices) for slices in sliced_configs]
        self.input_ids_list: List[List[int]] = []
        self.local_map_list: List[List[int]] = []
        self.local_configs_list: List[List[Config]] = []
        self.widths_list_flat: List[int] = []
        for rank_table_ids in self.table_ids_list:
            rank_configs: List[Config] = []
            rank_input_ids: List[int] = []
            rank_input_map: List[int] = []
            for m, table_idx in enumerate(rank_table_ids):
                cfg = remaining[table_idx].pop(0)
                rank_configs.append(cfg)
                for k, mapped in enumerate(self.input_table_map):
                    if mapped == table_idx:
                        self.widths_list_flat.append(int(cfg["output_dim"]))
                        rank_input_ids.append(k)
                        rank_input_map.append(m)
            self.local_configs_list.append(rank_configs)
            self.input_ids_list.append(rank_input_ids)
            self.local_map_list.append(rank_input_map)

        worker_order_input_ids = [
            i for rank_ids in self.input_ids_list for i in rank_ids]
        self.rev_global_input_ids = [
            pos for _, pos in sorted(
                zip(worker_order_input_ids, range(len(worker_order_input_ids))))]

    def create_sliced_configs(self, world_size: int,
                              column_slice_threshold: Optional[int],
                              input_table_map: Sequence[int],
                              row_slice_threshold: Optional[int] = None):
        """Slice each oversized table and record, in *input order*, the
        output ranges to reassemble: column slices concatenate (reference
        ``dist_model_parallel.py:133-157``), row slices sum.

        Column slicing takes precedence; a table it split is not row-sliced
        (the two thresholds express the same capacity constraint, and a
        doubly-sliced table would need a 2-D slice grid the exchange layout
        has no use for).

        Range bookkeeping invariant: ranges are expressed as
        ``[input_id, input_id + num_slices]`` and consumed in increasing input
        order with in-place collapse — after collapsing all earlier ranges each
        input's expanded output block starts exactly at its input id. The
        forward must therefore process column and row ranges together in
        ascending input order.
        """
        sliced_configs = []
        row_sliced_tables = set()
        for tid, cfg in enumerate(self.global_configs):
            col = maybe_slice_table_column(cfg, column_slice_threshold,
                                           world_size)
            if len(col) > 1:
                sliced_configs.append(col)
                continue
            row = maybe_slice_table_row(cfg, row_slice_threshold, world_size)
            if len(row) > 1:
                row_sliced_tables.add(tid)
            sliced_configs.append(row)
        sliced_out_ranges = []
        row_sliced_out_ranges = []
        for input_id, table_id in enumerate(input_table_map):
            if len(sliced_configs[table_id]) > 1:
                rng = [input_id, input_id + len(sliced_configs[table_id])]
                if table_id in row_sliced_tables:
                    row_sliced_out_ranges.append(rng)
                else:
                    sliced_out_ranges.append(rng)
        return (sliced_configs, sliced_out_ranges, row_sliced_out_ranges,
                row_sliced_tables)

    # ----- derived views used by the executor -----

    def local_table_sizes(self, rank: int) -> int:
        return sum(_table_elements(c) for c in self.local_configs_list[rank])

    def plan_spec(self) -> Dict[str, Any]:
        """JSON-able fingerprint of this plan — recorded in every
        checkpoint's ``meta.json`` so restore can tell "same layout" from
        "needs a re-shard" (:func:`plans_equal`) and the re-shard tooling
        can diff placements (:func:`plan_diff`).

        ``local_tables[r]`` lists, per local table ``m``,
        ``[table_id, rows, width, row_base, col_start]`` — the same slice
        geometry the checkpoint codec routes by (column slices consumed
        in rank order, row slices carrying their first global row)."""
        col_pos = {tid: 0 for tid in range(len(self.global_configs))}
        local_tables: List[List[List[int]]] = []
        for r, cfgs in enumerate(self.local_configs_list):
            rank_entries = []
            for m, cfg in enumerate(cfgs):
                tid = self.table_ids_list[r][m]
                w = int(cfg["output_dim"])
                if tid in self.row_sliced_tables:
                    rank_entries.append(
                        [tid, int(cfg["input_dim"]), w,
                         int(cfg.get("_row_base", 0)), 0])
                else:
                    rank_entries.append(
                        [tid, int(cfg["input_dim"]), w, 0, col_pos[tid]])
                    col_pos[tid] += w
            local_tables.append(rank_entries)
        return {
            "world_size": int(self.world_size),
            "strategy": self.strategy,
            "column_slice_threshold": self.column_slice_threshold,
            "row_slice_threshold": self.row_slice_threshold,
            "table_ids_list": [list(map(int, t))
                               for t in self.table_ids_list],
            "local_tables": local_tables,
            "per_rank_elements": [self.local_table_sizes(r)
                                  for r in range(self.world_size)],
        }

    @property
    def num_inputs(self) -> int:
        return len(self.input_table_map)

    def predicted_cost(self, global_batch: int, **audit_kw):
        """Price this plan without building anything — the planner-side
        cost hook. Delegates to :func:`...analysis.plan_audit.audit_plan`
        (a backend-free byte/comms model calibrated against the executor:
        slab geometry, exchange padding, per-step all-to-all payloads)
        and returns its :class:`~...analysis.plan_audit.PlanReport`.

        Keyword args pass through (``optimizer=``, ``param_dtype=``,
        ``encodings=``, ``contract=``, ...). Use
        :func:`...analysis.plan_audit.rank_strategies` to compare
        candidate strategies by this cost before committing to one —
        "does it fit, and what does the exchange cost" answered at plan
        time, the way GSPMD-style systems validate placements before
        touching a pod."""
        from ..analysis import plan_audit

        return plan_audit.audit_plan(self, global_batch, **audit_kw)

    def describe(self, param_bytes: int = 4) -> str:
        """Human-readable placement summary. ``param_bytes``: bytes per
        table element (pass 2 for bf16 tables — the benched headline
        variant; the planner itself is dtype-agnostic, VERDICT r4 Weak
        #7)."""
        lines = [f"DistEmbeddingStrategy(strategy={self.strategy}, "
                 f"world_size={self.world_size})"]
        for r, (tids, cfgs) in enumerate(
                zip(self.table_ids_list, self.local_configs_list)):
            bytes_ = sum(_table_elements(c) for c in cfgs) * param_bytes
            lines.append(f"  rank {r}: tables {tids} ({bytes_ / 2**20:.1f} MiB)")
        return "\n".join(lines)

"""Exchange layer of the hybrid step: block assembly + the three
all-to-alls.

One of the three executor modules the 2,200-line ``dist_embedding.py``
monolith split into (exchange / :mod:`.lookup` / :mod:`.apply`),
orchestrated by the :class:`~.schedule.StepSchedule` phases whose names
the ``obs.scope`` labels here come from. This module owns everything
that touches the wire:

* the rank-uniform group-region **block layout** shared by the forward
  id blocks and the backward cotangent blocks (:func:`assemble_cells` —
  dead cells zero-filled, multi-slot instances spanning their cells);
* the **dp→mp id exchange** (:func:`exchange_ids`), the **mp→dp
  activation exchange** (:func:`exchange_outputs`), and the **reverse
  cotangent exchange** (:func:`exchange_grads`) — the three collectives
  of the step, each under its schedule phase scope so the jaxpr
  auditor, the HLO census, and the schedule auditor all see the same
  names.

Every function takes the owning
:class:`~.dist_embedding.DistributedEmbedding` as its first argument;
the split is pure code motion from the monolith — the traced program
(and therefore the compiled HLO, the census pass counts, and the
trajectory CRCs) is bit-for-bit what the methods produced before.
"""

from __future__ import annotations

from typing import Dict, List

import jax
import jax.numpy as jnp
from jax import lax

from ..utils import obs
from . import schedule as schedule_mod

# Marks exchange-layout cells covered by a multi-cell content array placed
# at an earlier slot (no-combiner multi-hot features span `hotness` slots).
_SPANNED = object()


def assemble_cells(de, plan, fill, dead_shape, full_shape, dtype,
                   axis: int) -> jax.Array:
    """Shared layout assembly for the forward id blocks and backward grad
    blocks: place each instance's content at its (rank, group, slot0)
    cell — content spans all ``num_slots`` cells of a multi-slot
    instance — fill dead cells with zeros, concatenate in group/slot
    layout order per destination rank, and stack over ranks.

    Args:
      fill: ``fill(inst) -> array`` — the instance's content in layout
        form (ids flattened / grad block).
      dead_shape: ``dead_shape(group) -> shape`` of one dead cell.
      full_shape: shape of an all-dead destination row (no-groups edge).
      dtype: content dtype (zeros match it).
      axis: concat axis of the per-destination parts.
    """
    cells = [[[None] * g.n for g in plan.groups]
             for _ in range(de.world_size)]
    for inst in plan.instances:
        row = cells[inst.rank][inst.group]
        row[inst.slot0] = fill(inst)
        for k in range(1, inst.num_slots):
            row[inst.slot0 + k] = _SPANNED
    zeros_cache: Dict[tuple, jax.Array] = {}

    def dead(shape):
        z = zeros_cache.get(shape)
        if z is None:
            z = de._vary(jnp.zeros(shape, dtype))
            zeros_cache[shape] = z
        return z

    blocks = []
    for dest in range(de.world_size):
        parts = []
        for gi, g in enumerate(plan.groups):
            for k in range(g.n):
                c = cells[dest][gi][k]
                if c is _SPANNED:
                    continue
                parts.append(dead(dead_shape(g)) if c is None else c)
        blocks.append(jnp.concatenate(parts, axis=axis) if parts
                      else dead(full_shape))
    return jnp.stack(blocks)


def build_send_blocks(de, plan, entries, comm_dtype) -> jax.Array:
    """Assemble the dp->mp id blocks ``[world, l_max]`` in the plan's
    group-region layout. Dead (padding) slots send zeros; a multi-slot
    feature (no-combiner multi-hot, or N-D dense) sends its ids
    slot-major so each slot's ids stay contiguous."""

    def fill(inst):
        e = entries[inst.input_id]
        if isinstance(e, tuple):  # ("r"|"rw", values, lengths[, wbits])
            parts = [e[1].astype(comm_dtype), e[2].astype(comm_dtype)]
            if e[0] == "rw":
                parts.append(e[3].astype(comm_dtype))
            return jnp.concatenate(parts)
        if inst.transposed:  # slot-major: [b, ns*h] -> [ns, b, h] flat
            h = plan.groups[inst.group].hot
            return e.reshape(e.shape[0], inst.num_slots, h
                             ).transpose(1, 0, 2).reshape(-1)
        return e.reshape(-1)

    return assemble_cells(
        de, plan, fill, dead_shape=lambda g: (g.blen,),
        full_shape=(plan.l_max,), dtype=comm_dtype, axis=0)


def exchange_ids(de, plan, entries, comm_dtype, tag: str = "") -> jax.Array:
    """The dp→mp id exchange (schedule phase
    :data:`~.schedule.PHASE_ID_EXCHANGE`): assemble the send blocks and
    run the tiled all-to-all. Blocks use the rank-uniform group-region
    layout (``parallel/plan.py``); the reference pads to the max
    per-rank split instead (``dist_model_parallel.py:273-282``) — same
    idea, but static regions let the lookup run without per-rank
    branches. ``tag`` suffixes the phase scope (the pipelined step's
    ``_mb{k}`` microbatch instances; empty for the serialized step, so
    its program text is byte-identical to before)."""
    with obs.scope(schedule_mod.PHASE_ID_EXCHANGE + tag):
        ids_send = build_send_blocks(de, plan, entries, comm_dtype)
        return lax.all_to_all(ids_send, de.axis_name, 0, 0, tiled=True)


def exchange_outputs(de, mp_out: jax.Array, tag: str = "") -> jax.Array:
    """The mp→dp activation exchange (schedule phase
    :data:`~.schedule.PHASE_OUT_EXCHANGE`): ``dp_recv[r]`` is this
    rank's batch as computed by source rank ``r``. ``tag`` as in
    :func:`exchange_ids`."""
    with obs.scope(schedule_mod.PHASE_OUT_EXCHANGE + tag):
        return lax.all_to_all(mp_out, de.axis_name, 0, 0, tiled=True)


def pack_grad_blocks(de, plan, grads_by_worker, b: int,
                     out_dtype) -> jax.Array:
    """Pack the output cotangents ``[world, b, s_max]`` in the plan's
    column layout (the reverse of the forward unpack): each worker-order
    instance's grad spans its columns, dead columns are zero."""
    return assemble_cells(
        de, plan,
        # a multi-slot instance's grad [b, num_slots*w] spans its columns
        fill=lambda inst: grads_by_worker[inst].astype(out_dtype),
        dead_shape=lambda g: (b, g.width),
        full_shape=(b, plan.s_max), dtype=out_dtype,
        axis=1)  # [world, b, s_max]


def exchange_grads(de, packed: jax.Array, tag: str = "") -> jax.Array:
    """The reverse cotangent exchange (schedule phase
    :data:`~.schedule.PHASE_GRAD_EXCHANGE`): autodiff of the forward
    exchange would insert the same collective; the reference rides
    Horovod's registered alltoall grad. World 1 is a passthrough (the
    packed block already is this worker's). ``tag`` as in
    :func:`exchange_ids`."""
    with obs.scope(schedule_mod.PHASE_GRAD_EXCHANGE + tag):
        return (lax.all_to_all(packed, de.axis_name, 0, 0, tiled=True)
                if de.world_size > 1 else packed)


__all__: List[str] = [
    "assemble_cells", "build_send_blocks", "exchange_ids",
    "exchange_outputs", "pack_grad_blocks", "exchange_grads",
]

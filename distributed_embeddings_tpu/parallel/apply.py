"""Apply layer of the hybrid step: the manual sparse backward and the
per-width optimizer scatters.

One of the three executor modules the ``dist_embedding.py`` monolith
split into (:mod:`.exchange` / :mod:`.lookup` / apply). This module owns
everything after the dense backward: inverting the output collapse back
to worker order, packing the cotangent blocks for the reverse exchange
(:func:`~.exchange.pack_grad_blocks` + :func:`~.exchange.exchange_grads`),
rebuilding the per-group id streams from the forward residual, and the
ONE optimizer scatter per width slab (:func:`apply_width_streams`, the
:data:`~.schedule.PHASE_APPLY` phase family — ``sparse_apply_w{k}``).

Every function takes the owning
:class:`~.dist_embedding.DistributedEmbedding` as its first argument;
the split is pure code motion — the traced program is bit-for-bit what
the monolith's methods produced.
"""

from __future__ import annotations

from typing import Dict, List, Optional

import jax
import jax.numpy as jnp
from jax import lax

from ..utils import obs
from ..ops import packed_slab as ps
from . import exchange as exchange_mod
from . import lookup as lookup_mod
from .lookup import _wkey


def apply_width_streams(de, params, opt_state,
                        per_width: Dict[str, List], optimizer, lr,
                        scale, enable=None):
    """Concatenate each width's (logical ids, update rows) stream,
    lane-expand to physical full-tile rows, and run ONE optimizer scatter
    per width slab. Stateful-moment optimizers additionally receive the
    lane touch-mask (``ops/packed_slab.py:expand_touch_mask``) so packed
    neighbour rows keep their state.

    ``enable`` (scalar bool, traced): when False every update row is
    routed to the dropped sentinel — the scatters drop out of bounds,
    so the slabs AND every slab-shaped optimizer state component stay
    bitwise-unchanged. This is the non-finite guard's skip path: an
    O(ids) mask instead of a slab-wide select (which would read+write
    gigabytes of tables per step just to discard the result)."""
    new_params = dict(params)
    new_state = dict(opt_state) if isinstance(opt_state, dict) else opt_state
    wants_mask = getattr(optimizer, "needs_touch_mask", False)
    for k in sorted(per_width):
        with obs.scope(f"sparse_apply_{k}"):
            tris = per_width[k]
            w = tris[0][2]
            ids = jnp.concatenate([t[0].reshape(-1) for t in tris])
            if enable is not None:
                # disabled step: all rows -> logical sentinel (the same
                # dropped-row id the backward uses for OOB ids)
                ids = jnp.where(enable, ids,
                                jnp.asarray(de.rows_cap[w], ids.dtype))
            vals = jnp.concatenate(
                [t[1].reshape(-1, w) for t in tris]) * scale
            # lane-expand to physical rows: the scatter (and any dedup
            # in the optimizer) runs on full-tile rows; lane-disjoint
            # placement keeps per-logical-row semantics exact
            # (ops/packed_slab.py)
            phys_ids, pvals = ps.expand_update_rows(vals, ids, w)
            kw = {}
            if wants_mask:
                # compact [n, p] lane mask rides the optimizer's dedup
                # and expands to lanes after
                # (ops/packed_slab.py:lane_one_hot)
                m = ps.lane_one_hot(ids, w, dtype=pvals.dtype)
                if m is not None:
                    kw["mask"] = m
                    kw["lane_width"] = w
            slab = new_params[k]
            st = (new_state[k] if isinstance(new_state, dict)
                  else new_state)
            slab, st = optimizer.apply_rows(slab, st, phys_ids, pvals,
                                            lr, **kw)
            new_params[k] = slab
            if isinstance(new_state, dict):
                new_state[k] = st
    return new_params, new_state


def sparse_apply_gradients(de, params, opt_state, residuals, out_grads,
                           optimizer, lr, scale=None, enable=None):
    """Manual sparse backward + in-place optimizer update (the body of
    :meth:`~.dist_embedding.DistributedEmbedding.sparse_apply_gradients`;
    see that method's docstring for the full argument contract).

    Routes the output cotangents through the reverse all-to-all
    (:mod:`.exchange`), rebuilds the per-group id streams from the
    forward residual (:mod:`.lookup`'s ragged machinery), and applies
    per-row scatter updates via :func:`apply_width_streams` — never
    materializing dense table gradients. This is the IndexedSlices
    pipeline of the reference (``dist_model_parallel.py:526-567`` + the
    grad kernel) in SPMD form."""
    params = de.local_view(params)
    if isinstance(opt_state, dict):
        opt_state = de.local_view(opt_state)
    if scale is None:
        scale = 1.0 / de.world_size
    fallback = next(iter(params.values())).dtype
    per_width = cotangent_width_streams(de, residuals, out_grads,
                                        fallback_dtype=fallback)
    return apply_width_streams(de, params, opt_state, per_width,
                               optimizer, lr, scale, enable=enable)


def cotangent_width_streams(de, residuals, out_grads, fallback_dtype=None,
                            tag: str = ""):
    """The sparse backward MINUS the optimizer scatter: route the output
    cotangents through the reverse all-to-all and rebuild the per-width
    ``(ids, update rows)`` streams from the forward residual. Split out
    of :func:`sparse_apply_gradients` so the pipelined step can build
    one stream set per microbatch (each behind its own
    ``grad_all_to_all_mb{k}`` exchange, overlapping other microbatches'
    dense compute) and MERGE them into the one
    :func:`apply_width_streams` scatter per width slab — grad
    accumulation across microbatches without a second pass over the
    slabs. ``tag`` suffixes the exchange scope (empty = the serialized
    step, byte-identical to the pre-split program)."""
    _, ids_recv, encs, b = residuals
    # single-worker no-combiner outputs keep their [b, h, w] rank
    # (reference call semantics); the exchange layout is flat columns
    out_grads = [g.reshape(g.shape[0], -1) for g in out_grads]
    world = de.world_size
    plan = de._get_plan(list(encs), b)

    # Invert the column-slice collapse then the input-order reorder,
    # rebuilding worker order. In fully-expanded coordinates, output entry
    # e has width worker_widths[rev[e]]; input i owns the next
    # slices-per-table[table(i)] expanded entries.
    worker_widths = [plan.out_width(inst) for inst in plan.instances]
    rev = de.strategy.rev_global_input_ids
    expanded: List[Optional[jax.Array]] = []
    e = 0
    for i, g in enumerate(out_grads):
        tid = de.strategy.input_table_map[i]
        k = de._slices_per_table[tid]
        if k == 1:
            expanded.append(g)
        elif tid in de.strategy.row_sliced_tables:
            # output was the SUM of row slices, so every slice's
            # cotangent is the full g (its own out-of-range rows drop)
            expanded.extend([g] * k)
        else:
            pos = 0
            for s in range(k):
                w = worker_widths[rev[e + s]]
                expanded.append(lax.slice(g, (0, pos), (b, pos + w)))
                pos += w
        e += k
    worker_grads: List[Optional[jax.Array]] = [None] * len(rev)
    for idx, g in enumerate(expanded):
        worker_grads[rev[idx]] = g

    # Pack [world, b, s_max] in the plan's column layout and reverse the
    # output all-to-all (autodiff of the forward exchange would insert the
    # same collective; reference rides Horovod's registered alltoall grad).
    out_dtype = (out_grads[0].dtype if out_grads else fallback_dtype)
    grads_by_worker = dict(zip(plan.instances, worker_grads))
    packed = exchange_mod.pack_grad_blocks(de, plan, grads_by_worker, b,
                                           out_dtype)
    mp_grad = exchange_mod.exchange_grads(de, packed, tag=tag)

    # Rank-uniform sparse update: per group, rebuild the id stream from
    # the forward's residual block and expand slot cotangents to per-id
    # update rows; per width, one optimizer scatter.
    my = de._my_rank()
    per_width: Dict[str, List] = {}
    for gi, g in enumerate(plan.groups):
        rows = de._plan_row(plan.rows[gi], my)
        roff = de._plan_row(plan.roff[gi], my)
        any_mean = bool(plan.mean[gi].any())
        all_mean = bool(plan.mean[gi].all())
        all_valid = bool((plan.valid[gi] > 0).all())
        valid = (None if all_valid
                 else de._plan_row(plan.valid[gi], my))
        rbase = (de._plan_row(plan.rbase[gi], my)
                 if plan.rsliced[gi].any() else None)
        sent = de.rows_cap[g.width]  # dropped-row sentinel (logical)
        region = lax.slice(ids_recv, (0, g.goff),
                           (world, g.goff + g.n * g.blen))
        gsl = lax.slice(mp_grad, (0, 0, g.col),
                        (world, b, g.col + g.n * g.width))
        gsl = gsl.reshape(world, b, g.n, g.width)
        if g.kind == "d":
            # b-major stream: the value rows are then exactly the
            # [world, b, n, w] grad layout — a FREE reshape of the
            # exchange row instead of a materialized transpose (the
            # [b, n*w] -> [n, b, w] copy + cast measured ~26 ms at the
            # DLRM headline shapes); only the small int id tensor
            # transposes. The optimizer sorts the stream anyway, so
            # stream order is free to choose (docs/perf_tpu.md r4).
            ids4 = region.reshape(world, g.n, b, g.hot
                                  ).transpose(0, 2, 1, 3)
            if rbase is not None:  # row-sliced slots: range-local ids
                ids4 = ids4 - rbase[None, None, :, None]
            # out-of-range ids were clipped in the forward (safety net)
            # but are dropped here: a bad id trains nothing (see the
            # dist_embedding module docstring contract)
            ok = (ids4 >= 0) & (ids4 < rows[None, None, :, None])
            if valid is not None:
                ok = ok & (valid[None, None, :, None] > 0)
            ids = jnp.where(ok, ids4 + roff[None, None, :, None], sent)
            gb = gsl
            if g.hot > 1 and any_mean:
                if all_mean:
                    gb = gsl / g.hot
                else:
                    mean = de._plan_row(plan.mean[gi], my)
                    gb = jnp.where(mean[None, None, :, None] > 0,
                                   gsl / g.hot, gsl)
            vals = jnp.broadcast_to(
                gb[:, :, :, None, :],
                (world, b, g.n, g.hot, g.width))
        else:
            gsl = gsl.transpose(0, 2, 1, 3)  # ragged sidx layout is
            # (source, slot, row): one small copy, the take absorbs it
            values, _, seg, _, counts = lookup_mod.ragged_decode(
                de, g, b, region, rows, roff, valid,
                need_counts=any_mean, rbase=rbase)
            if rbase is not None:  # row-sliced slots: range-local ids
                values = values - rbase[None, :, None]
            sidx = lookup_mod.ragged_scatter_idx(g, b, world, seg)
            gpad = jnp.concatenate(
                [gsl, de._vary(jnp.zeros((world, g.n, 1, g.width),
                                         gsl.dtype))],
                axis=2)  # [world, n, b+1, w]
            vals = jnp.take(gpad.reshape(-1, g.width), sidx.reshape(-1),
                            axis=0).reshape(world, g.n, g.hot, g.width)
            if g.kind == "rw":
                # d(w_i * x_i)/dx_i: the weight multiplies the per-id
                # cotangent (the reference backward reuses the forward
                # kernel with the same weights input, .cu:539-627)
                wts = lookup_mod.region_weights(de, g, b, region)
                vals = vals * wts[..., None].astype(vals.dtype)
            if any_mean:
                cpad = jnp.concatenate(
                    [counts, jnp.ones((world, g.n, 1), counts.dtype)],
                    axis=2)
                cval = jnp.take(cpad.reshape(-1), sidx.reshape(-1)
                                ).reshape(world, g.n, g.hot)
                div = vals / cval[..., None].astype(vals.dtype)
                if all_mean:
                    vals = div
                else:
                    mean = de._plan_row(plan.mean[gi], my)
                    vals = jnp.where(mean[None, :, None, None] > 0,
                                     div, vals)
            ok = (seg < b) & (values >= 0) & (values < rows[None, :, None])
            if valid is not None:
                ok = ok & (valid[None, :, None] > 0)
            ids = jnp.where(ok, values + roff[None, :, None], sent)
        per_width.setdefault(_wkey(g.width), []).append(
            (ids, vals, g.width))

    return per_width

"""The explicit step schedule: named phases, declared ordering, declared
overlap.

The hybrid step is a fixed chain of phases — id exchange, lookup, output
exchange, dense forward/backward, gradient exchange, sparse apply — that
used to exist only implicitly, as the order of statements inside one
2,200-line module. This module makes the schedule a first-class object:

* each phase has a **name** that doubles as its ``obs.scope`` label, so
  the same identifier threads from the Python orchestration through the
  jaxpr auditor's collective contract, the HLO census's pass budgets, and
  the schedule auditor's dependency DAG
  (:mod:`~..analysis.schedule_audit`);
* a :class:`StepSchedule` declares, per phase, what it must run
  **after** and what it claims to **overlap** with. The declaration is a
  CONTRACT, not a wish: ``tools/schedule_audit.py --strict`` checks every
  declared overlap against the dependency structure of the compiled
  program and fails when the overlap does not exist in what XLA emitted
  (a schedule that *says* "the id exchange hides under dense compute"
  while the program serializes them is exactly the silent perf lie the
  auditor exists to catch).

The executor modules (:mod:`.exchange`, :mod:`.lookup`, :mod:`.apply`)
take their scope names from the constants below; the orchestrator
(:meth:`~.dist_embedding.DistributedEmbedding.forward_with_residuals` +
:meth:`~.dist_embedding.DistributedEmbedding.sparse_apply_gradients`)
steps through :func:`default_schedule`'s phases in declaration order.
Today's default schedule is honest about being SERIALIZED — every
collective declares ``overlaps=()`` — which the schedule auditor's
baseline report documents as the measured starting line; a pipelined
step (ROADMAP item 2) will ship a schedule whose declared overlaps the
same auditor then has to certify.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Tuple

# ---------------------------------------------------------------- phase names
# These strings ARE the obs.scope labels of the compiled step (and hence
# the detpu/ phase paths in the optimized HLO). Globs (trailing ``*``)
# name phase FAMILIES that expand per width group at trace time.

#: dp→mp id all-to-all (block assembly + the collective)
PHASE_ID_EXCHANGE = "id_all_to_all"
#: per-(width, kind) gather+combine groups — ``lookup_w{w}_{kind}``
PHASE_LOOKUP = "lookup_*"
#: mp→dp activation all-to-all
PHASE_OUT_EXCHANGE = "out_all_to_all"
#: the dense model's forward + backward (trainer scope)
PHASE_DENSE = "dense_forward_backward"
#: reverse (cotangent) all-to-all
PHASE_GRAD_EXCHANGE = "grad_all_to_all"
#: per-width optimizer scatter streams — ``sparse_apply`` and
#: ``sparse_apply_w{k}``
PHASE_APPLY = "sparse_apply*"


class ScheduleError(ValueError):
    """An inconsistent :class:`StepSchedule` declaration."""


@dataclasses.dataclass(frozen=True)
class PhaseDecl:
    """One named phase of the step schedule.

    ``name`` is the ``obs.scope`` label (an ``fnmatch`` glob for phase
    families like ``lookup_*``). ``kind`` is ``"collective"`` (pays ICI
    bandwidth) or ``"compute"`` (pays HBM bandwidth). ``after`` lists the
    phases that must have produced this phase's inputs — the declared
    dependency order. ``overlaps`` lists the phases this one CLAIMS to
    run concurrently with; the schedule auditor verifies each claim
    against the compiled program's dependency DAG and fails a declared
    overlap the program serializes."""

    name: str
    kind: str = "compute"
    after: Tuple[str, ...] = ()
    overlaps: Tuple[str, ...] = ()

    def __post_init__(self) -> None:
        if self.kind not in ("collective", "compute"):
            raise ScheduleError(
                f"phase {self.name!r}: kind must be 'collective' | "
                f"'compute', got {self.kind!r}")


@dataclasses.dataclass(frozen=True)
class StepSchedule:
    """A named, ordered set of :class:`PhaseDecl`\\ s.

    Declaration order is execution order for the serialized portions of
    the step; ``validate()`` (run on construction) checks the references
    and rejects ordering cycles, self-overlap, and overlap claims that
    contradict the declared ``after`` chain (a phase cannot overlap a
    phase it depends on)."""

    name: str
    phases: Tuple[PhaseDecl, ...]

    def __post_init__(self) -> None:
        self.validate()

    # -- introspection ----------------------------------------------------
    def by_name(self) -> Dict[str, PhaseDecl]:
        return {p.name: p for p in self.phases}

    def phase(self, name: str) -> PhaseDecl:
        try:
            return self.by_name()[name]
        except KeyError:
            raise ScheduleError(
                f"schedule {self.name!r} declares no phase {name!r} "
                f"(has: {[p.name for p in self.phases]})") from None

    def collectives(self) -> Tuple[PhaseDecl, ...]:
        return tuple(p for p in self.phases if p.kind == "collective")

    def declared_overlaps(self) -> Tuple[Tuple[str, str], ...]:
        """Every (phase, partner) overlap claim, in declaration order."""
        return tuple((p.name, q) for p in self.phases for q in p.overlaps)

    def depends_on(self, name: str, other: str) -> bool:
        """Whether phase ``name`` transitively runs after ``other``."""
        decls = self.by_name()
        seen = set()
        stack = [name]
        while stack:
            cur = stack.pop()
            if cur in seen or cur not in decls:
                continue
            seen.add(cur)
            for dep in decls[cur].after:
                if dep == other:
                    return True
                stack.append(dep)
        return False

    # -- validation -------------------------------------------------------
    def validate(self) -> "StepSchedule":
        names = [p.name for p in self.phases]
        if len(set(names)) != len(names):
            dup = sorted({n for n in names if names.count(n) > 1})
            raise ScheduleError(
                f"schedule {self.name!r}: duplicate phase name(s) {dup}")
        known = set(names)
        for p in self.phases:
            for ref in p.after + p.overlaps:
                if ref not in known:
                    raise ScheduleError(
                        f"schedule {self.name!r}: phase {p.name!r} "
                        f"references undeclared phase {ref!r}")
            if p.name in p.overlaps:
                raise ScheduleError(
                    f"schedule {self.name!r}: phase {p.name!r} cannot "
                    "overlap itself")
        # cycle check over the `after` relation (iterative DFS)
        decls = self.by_name()
        color: Dict[str, int] = {}  # 0 in-stack, 1 done

        def visit(root: str) -> None:
            stack = [(root, iter(decls[root].after))]
            color[root] = 0
            while stack:
                node, it = stack[-1]
                dep = next(it, None)
                if dep is None:
                    color[node] = 1
                    stack.pop()
                    continue
                c = color.get(dep)
                if c == 0:
                    chain = [n for n, _ in stack] + [dep]
                    raise ScheduleError(
                        f"schedule {self.name!r}: ordering cycle "
                        f"{' -> '.join(chain)}")
                if c is None:
                    color[dep] = 0
                    stack.append((dep, iter(decls[dep].after)))

        for n in names:
            if n not in color:
                visit(n)
        # an overlap claim against a phase this phase (transitively)
        # depends on is self-contradictory: the data dependency forces
        # serialization regardless of what the compiler does
        for p in self.phases:
            for q in p.overlaps:
                if self.depends_on(p.name, q) or self.depends_on(q, p.name):
                    raise ScheduleError(
                        f"schedule {self.name!r}: phase {p.name!r} "
                        f"declares overlap with {q!r} but the `after` "
                        "chain orders them — a data dependency cannot "
                        "overlap")
        return self


def default_schedule() -> StepSchedule:
    """The serialized baseline schedule of today's hybrid step.

    Honest declaration of what the unpipelined step does: the three
    all-to-alls sit strictly between their producers and consumers, and
    no phase claims overlap. This is the schedule the auditor's baseline
    report certifies (all three collectives serialized on the critical
    path) and the one every A/B-identity guarantee is pinned against."""
    return StepSchedule(
        name="serialized-v1",
        phases=(
            PhaseDecl(PHASE_ID_EXCHANGE, kind="collective"),
            PhaseDecl(PHASE_LOOKUP, kind="compute",
                      after=(PHASE_ID_EXCHANGE,)),
            PhaseDecl(PHASE_OUT_EXCHANGE, kind="collective",
                      after=(PHASE_LOOKUP,)),
            PhaseDecl(PHASE_DENSE, kind="compute",
                      after=(PHASE_OUT_EXCHANGE,)),
            PhaseDecl(PHASE_GRAD_EXCHANGE, kind="collective",
                      after=(PHASE_DENSE,)),
            PhaseDecl(PHASE_APPLY, kind="compute",
                      after=(PHASE_GRAD_EXCHANGE,)),
        ))

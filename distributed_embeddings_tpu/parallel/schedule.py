"""The explicit step schedule: named phases, declared ordering, declared
overlap.

The hybrid step is a fixed chain of phases — id exchange, lookup, output
exchange, dense forward/backward, gradient exchange, sparse apply — that
used to exist only implicitly, as the order of statements inside one
2,200-line module. This module makes the schedule a first-class object:

* each phase has a **name** that doubles as its ``obs.scope`` label, so
  the same identifier threads from the Python orchestration through the
  jaxpr auditor's collective contract, the HLO census's pass budgets, and
  the schedule auditor's dependency DAG
  (:mod:`~..analysis.schedule_audit`);
* a :class:`StepSchedule` declares, per phase, what it must run
  **after** and what it claims to **overlap** with. The declaration is a
  CONTRACT, not a wish: ``tools/schedule_audit.py --strict`` checks every
  declared overlap against the dependency structure of the compiled
  program and fails when the overlap does not exist in what XLA emitted
  (a schedule that *says* "the id exchange hides under dense compute"
  while the program serializes them is exactly the silent perf lie the
  auditor exists to catch).

The executor modules (:mod:`.exchange`, :mod:`.lookup`, :mod:`.apply`)
take their scope names from the constants below; the orchestrator
(:meth:`~.dist_embedding.DistributedEmbedding.forward_with_residuals` +
:meth:`~.dist_embedding.DistributedEmbedding.sparse_apply_gradients`)
steps through :func:`default_schedule`'s phases in declaration order.
Today's default schedule is honest about being SERIALIZED — every
collective declares ``overlaps=()`` — which the schedule auditor's
baseline report documents as the measured starting line; a pipelined
step (ROADMAP item 2) will ship a schedule whose declared overlaps the
same auditor then has to certify.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Optional, Tuple, Union

from ..utils import envvars

# ---------------------------------------------------------------- phase names
# These strings ARE the obs.scope labels of the compiled step (and hence
# the detpu/ phase paths in the optimized HLO). Globs (trailing ``*``)
# name phase FAMILIES that expand per width group at trace time.

#: dp→mp id all-to-all (block assembly + the collective)
PHASE_ID_EXCHANGE = "id_all_to_all"
#: per-(width, kind) gather+combine groups — ``lookup_w{w}_{kind}``
PHASE_LOOKUP = "lookup_*"
#: mp→dp activation all-to-all
PHASE_OUT_EXCHANGE = "out_all_to_all"
#: the dense model's forward + backward (trainer scope)
PHASE_DENSE = "dense_forward_backward"
#: reverse (cotangent) all-to-all
PHASE_GRAD_EXCHANGE = "grad_all_to_all"
#: per-width optimizer scatter streams — ``sparse_apply`` and
#: ``sparse_apply_w{k}``
PHASE_APPLY = "sparse_apply*"
#: streaming-vocab admission staging — the count-min fold + claim
#: resolution chain (``streaming_admit_w{w}``), consumed only at commit,
#: so it is DAG-independent of the out/grad exchanges (the measured
#: overlap candidate of docs/perf_tpu.md Round 13)
PHASE_STREAM_ADMIT = "streaming_admit_*"
#: streaming-vocab commit — post-apply slot-map select + claimed-row
#: scrub (``streaming_commit`` / ``streaming_commit_w{w}``)
PHASE_STREAM_COMMIT = "streaming_commit*"
#: per-microbatch slot-map SERVE remap of the pipelined streaming step
#: (``streaming_serve_w{w}_mb{k}``) — read-only against the carried
#: slot map, so each microbatch's lookup depends only on its own id
#: exchange, never on the admission staging
PHASE_STREAM_SERVE = "streaming_serve_*"

#: scope-name suffix of microbatch ``k``'s phase instances in a
#: pipelined step (``id_all_to_all_mb0``, ``lookup_w8_d_mb1``, ...)
MICROBATCH_TAG = "_mb{k}"


def microbatch_tag(k: int) -> str:
    """The scope suffix the executors append for microbatch ``k``."""
    return MICROBATCH_TAG.format(k=k)


def mb_phase(name: str, k: int) -> str:
    """Microbatch ``k``'s instance of a phase name. Glob families keep
    their trailing ``*`` AFTER the suffix (``lookup_*`` ->
    ``lookup_*_mb0``) so ``lookup_w8_d_mb0`` still matches."""
    tag = microbatch_tag(k)
    if name.endswith("*"):
        return name.rstrip("*") + "*" + tag
    return name + tag


class ScheduleError(ValueError):
    """An inconsistent :class:`StepSchedule` declaration."""


@dataclasses.dataclass(frozen=True)
class PhaseDecl:
    """One named phase of the step schedule.

    ``name`` is the ``obs.scope`` label (an ``fnmatch`` glob for phase
    families like ``lookup_*``). ``kind`` is ``"collective"`` (pays ICI
    bandwidth) or ``"compute"`` (pays HBM bandwidth). ``after`` lists the
    phases that must have produced this phase's inputs — the declared
    dependency order. ``overlaps`` lists the phases this one CLAIMS to
    run concurrently with; the schedule auditor verifies each claim
    against the compiled program's dependency DAG and fails a declared
    overlap the program serializes."""

    name: str
    kind: str = "compute"
    after: Tuple[str, ...] = ()
    overlaps: Tuple[str, ...] = ()

    def __post_init__(self) -> None:
        if self.kind not in ("collective", "compute"):
            raise ScheduleError(
                f"phase {self.name!r}: kind must be 'collective' | "
                f"'compute', got {self.kind!r}")


@dataclasses.dataclass(frozen=True)
class StepSchedule:
    """A named, ordered set of :class:`PhaseDecl`\\ s.

    Declaration order is execution order for the serialized portions of
    the step; ``validate()`` (run on construction) checks the references
    and rejects ordering cycles, self-overlap, and overlap claims that
    contradict the declared ``after`` chain (a phase cannot overlap a
    phase it depends on)."""

    name: str
    phases: Tuple[PhaseDecl, ...]
    #: microbatch count the trainer splits the step into (1 = the
    #: serialized, unpipelined program). Carried on the schedule so the
    #: one ``schedule=`` selection drives BOTH the declaration the
    #: auditor certifies and the program the trainer traces.
    microbatches: int = 1

    def __post_init__(self) -> None:
        if int(self.microbatches) < 1:
            raise ScheduleError(
                f"schedule {self.name!r}: microbatches must be >= 1, got "
                f"{self.microbatches}")
        self.validate()

    # -- introspection ----------------------------------------------------
    def by_name(self) -> Dict[str, PhaseDecl]:
        return {p.name: p for p in self.phases}

    def phase(self, name: str) -> PhaseDecl:
        try:
            return self.by_name()[name]
        except KeyError:
            raise ScheduleError(
                f"schedule {self.name!r} declares no phase {name!r} "
                f"(has: {[p.name for p in self.phases]})") from None

    def collectives(self) -> Tuple[PhaseDecl, ...]:
        return tuple(p for p in self.phases if p.kind == "collective")

    def declared_overlaps(self) -> Tuple[Tuple[str, str], ...]:
        """Every (phase, partner) overlap claim, in declaration order."""
        return tuple((p.name, q) for p in self.phases for q in p.overlaps)

    def depends_on(self, name: str, other: str) -> bool:
        """Whether phase ``name`` transitively runs after ``other``."""
        decls = self.by_name()
        seen = set()
        stack = [name]
        while stack:
            cur = stack.pop()
            if cur in seen or cur not in decls:
                continue
            seen.add(cur)
            for dep in decls[cur].after:
                if dep == other:
                    return True
                stack.append(dep)
        return False

    # -- validation -------------------------------------------------------
    def validate(self) -> "StepSchedule":
        names = [p.name for p in self.phases]
        if len(set(names)) != len(names):
            dup = sorted({n for n in names if names.count(n) > 1})
            raise ScheduleError(
                f"schedule {self.name!r}: duplicate phase name(s) {dup}")
        known = set(names)
        for p in self.phases:
            for ref in p.after + p.overlaps:
                if ref not in known:
                    raise ScheduleError(
                        f"schedule {self.name!r}: phase {p.name!r} "
                        f"references undeclared phase {ref!r}")
            if p.name in p.overlaps:
                raise ScheduleError(
                    f"schedule {self.name!r}: phase {p.name!r} cannot "
                    "overlap itself")
        # cycle check over the `after` relation (iterative DFS)
        decls = self.by_name()
        color: Dict[str, int] = {}  # 0 in-stack, 1 done

        def visit(root: str) -> None:
            stack = [(root, iter(decls[root].after))]
            color[root] = 0
            while stack:
                node, it = stack[-1]
                dep = next(it, None)
                if dep is None:
                    color[node] = 1
                    stack.pop()
                    continue
                c = color.get(dep)
                if c == 0:
                    chain = [n for n, _ in stack] + [dep]
                    raise ScheduleError(
                        f"schedule {self.name!r}: ordering cycle "
                        f"{' -> '.join(chain)}")
                if c is None:
                    color[dep] = 0
                    stack.append((dep, iter(decls[dep].after)))

        for n in names:
            if n not in color:
                visit(n)
        # an overlap claim against a phase this phase (transitively)
        # depends on is self-contradictory: the data dependency forces
        # serialization regardless of what the compiler does
        for p in self.phases:
            for q in p.overlaps:
                if self.depends_on(p.name, q) or self.depends_on(q, p.name):
                    raise ScheduleError(
                        f"schedule {self.name!r}: phase {p.name!r} "
                        f"declares overlap with {q!r} but the `after` "
                        "chain orders them — a data dependency cannot "
                        "overlap")
        return self


def default_schedule() -> StepSchedule:
    """The serialized baseline schedule of today's hybrid step.

    Honest declaration of what the unpipelined step does: the three
    all-to-alls sit strictly between their producers and consumers, and
    no phase claims overlap. This is the schedule the auditor's baseline
    report certifies (all three collectives serialized on the critical
    path) and the one every A/B-identity guarantee is pinned against."""
    return StepSchedule(
        name="serialized-v1",
        phases=(
            PhaseDecl(PHASE_ID_EXCHANGE, kind="collective"),
            PhaseDecl(PHASE_LOOKUP, kind="compute",
                      after=(PHASE_ID_EXCHANGE,)),
            PhaseDecl(PHASE_OUT_EXCHANGE, kind="collective",
                      after=(PHASE_LOOKUP,)),
            PhaseDecl(PHASE_DENSE, kind="compute",
                      after=(PHASE_OUT_EXCHANGE,)),
            PhaseDecl(PHASE_GRAD_EXCHANGE, kind="collective",
                      after=(PHASE_DENSE,)),
            PhaseDecl(PHASE_APPLY, kind="compute",
                      after=(PHASE_GRAD_EXCHANGE,)),
        ))


def streaming_schedule() -> StepSchedule:
    """The serialized streaming-vocab schedule, with the one overlap the
    compiled program ALREADY has declared: the admission-staging chain
    (count-min fold + claim resolution, ``streaming_admit_w*``) branches
    off the received ids and is consumed only at commit, so it is
    DAG-independent of the out/grad exchanges — the schedule auditor
    classified it overlappable in PR 12 (fraction 0.225) and the
    measured phase profile confirmed it on the clock in PR 13 (0.036
    measured serialized). Declaring it here is what lets
    ``make schedule-audit`` certify the overlap against the compiled
    DAG and ``compare_bench.check_schedule`` ratchet it so a refactor
    that re-serializes the staging chain fails loudly.

    The lookup's real dependency on the SERVE half of the admit phase
    (slot-map reads feeding the remapped ids) is deliberately not
    declared: the auditor's overlap check excludes exactly those
    ancestor-cone nodes from the independent sum, so the declaration is
    verified against the genuinely independent staging nodes only."""
    return StepSchedule(
        name="streaming-serialized-v1",
        phases=(
            PhaseDecl(PHASE_ID_EXCHANGE, kind="collective"),
            PhaseDecl(PHASE_STREAM_ADMIT, kind="compute",
                      after=(PHASE_ID_EXCHANGE,)),
            PhaseDecl(PHASE_LOOKUP, kind="compute",
                      after=(PHASE_ID_EXCHANGE,)),
            PhaseDecl(PHASE_OUT_EXCHANGE, kind="collective",
                      after=(PHASE_LOOKUP,),
                      overlaps=(PHASE_STREAM_ADMIT,)),
            PhaseDecl(PHASE_DENSE, kind="compute",
                      after=(PHASE_OUT_EXCHANGE,)),
            PhaseDecl(PHASE_GRAD_EXCHANGE, kind="collective",
                      after=(PHASE_DENSE,),
                      overlaps=(PHASE_STREAM_ADMIT,)),
            PhaseDecl(PHASE_APPLY, kind="compute",
                      after=(PHASE_GRAD_EXCHANGE,)),
            PhaseDecl(PHASE_STREAM_COMMIT, kind="compute",
                      after=(PHASE_APPLY, PHASE_STREAM_ADMIT)),
        ))


def resolve_microbatches(k: Optional[int] = None) -> int:
    """The microbatch count: an explicit ``k`` wins, else
    ``DETPU_MICROBATCH`` (declared default 2 — only pipelined-schedule
    opt-ins resolve through here, and asking for a pipeline must build
    one; ``DETPU_MICROBATCH=1`` or an explicit ``k=1`` selects the
    serialized degenerate)."""
    if k is None:
        k = envvars.get_int("DETPU_MICROBATCH")
    k = int(k)
    if k < 1:
        raise ScheduleError(f"microbatches must be >= 1, got {k}")
    return k


def pipelined_schedule(microbatches: Optional[int] = None,
                       streaming: bool = False) -> StepSchedule:
    """The K-microbatch software-pipelined schedule (ROADMAP item 2).

    The global batch splits into K microbatches INSIDE the jitted step;
    each runs its own id-exchange → lookup → out-exchange → dense
    fwd/bwd chain (phase instances suffixed ``_mb{k}``), gradients
    accumulate across microbatches, and ONE sparse apply runs at the
    end — so the applied update is numerically equivalent to the
    serialized step while the K chains share no data dependencies until
    the accumulation point. That independence is what the declared
    overlaps claim and what the schedule auditor certifies against the
    compiled DAG:

    * microbatch ``k``'s id and out exchanges overlap microbatch
      ``k-1``'s dense forward/backward (ship the next microbatch's ids
      while the current one computes);
    * microbatch ``k``'s grad exchange overlaps microbatch ``k+1``'s
      dense forward/backward (drain cotangents under later compute);
    * microbatch 0's collectives overlap microbatch 1's lookup chain
      (the pipeline has no cold edge at K >= 2).

    ``microbatches=None`` resolves K from ``DETPU_MICROBATCH``; K == 1
    returns the serialized baseline schedule unchanged (the trainer
    then traces the bitwise-identical serialized program — the K=1
    identity contract). ``streaming=True`` adds the streaming-vocab
    phases: per-microbatch read-only slot-map serves
    (``streaming_serve_*_mb{k}``), ONE admission-staging pass over the
    concatenated id streams (bitwise the serialized staging decision),
    and the post-apply commit — with the out/grad exchanges also
    declaring the staging overlap the serialized streaming schedule
    already certifies."""
    K = resolve_microbatches(microbatches)
    if K == 1:
        return streaming_schedule() if streaming else default_schedule()

    def dense(k: int) -> str:
        return mb_phase(PHASE_DENSE, k)

    def chain(j: int) -> Tuple[str, str]:
        """Microbatch ``j``'s hideable compute: its lookup gathers and
        its dense forward/backward."""
        return (mb_phase(PHASE_LOOKUP, j), dense(j))

    phases = []
    for k in range(K):
        id_k = mb_phase(PHASE_ID_EXCHANGE, k)
        lookup_k = mb_phase(PHASE_LOOKUP, k)
        out_k = mb_phase(PHASE_OUT_EXCHANGE, k)
        grad_k = mb_phase(PHASE_GRAD_EXCHANGE, k)
        # the partners a collective hides under: every OTHER
        # microbatch's lookup + dense chain (none of it shares a data
        # dependency with this microbatch's exchanges before the
        # accumulation point — the whole design of the pipeline)
        others = tuple(p for j in range(K) if j != k for p in chain(j))
        fwd_partner = others
        bwd_partner = others
        admit = (PHASE_STREAM_ADMIT,) if streaming else ()
        lookup_after = (id_k,)
        phases.append(PhaseDecl(id_k, kind="collective",
                                overlaps=fwd_partner))
        if streaming:
            serve_k = mb_phase(PHASE_STREAM_SERVE, k)
            phases.append(PhaseDecl(serve_k, kind="compute",
                                    after=(id_k,)))
            lookup_after = (id_k, serve_k)
        phases.append(PhaseDecl(lookup_k, kind="compute",
                                after=lookup_after))
        phases.append(PhaseDecl(out_k, kind="collective",
                                after=(lookup_k,),
                                overlaps=fwd_partner + admit))
        phases.append(PhaseDecl(dense(k), kind="compute",
                                after=(out_k,)))
        phases.append(PhaseDecl(grad_k, kind="collective",
                                after=(dense(k),),
                                overlaps=bwd_partner + admit))
    if streaming:
        phases.append(PhaseDecl(
            PHASE_STREAM_ADMIT, kind="compute",
            after=tuple(mb_phase(PHASE_ID_EXCHANGE, k)
                        for k in range(K))))
    phases.append(PhaseDecl(
        PHASE_APPLY, kind="compute",
        after=tuple(mb_phase(PHASE_GRAD_EXCHANGE, k) for k in range(K))))
    if streaming:
        phases.append(PhaseDecl(
            PHASE_STREAM_COMMIT, kind="compute",
            after=(PHASE_APPLY, PHASE_STREAM_ADMIT)))
    return StepSchedule(
        name=f"pipelined-k{K}" + ("-streaming" if streaming else ""),
        phases=tuple(phases), microbatches=K)


def without_streaming(schedule: StepSchedule) -> StepSchedule:
    """The non-streaming twin of a schedule that declares streaming
    phases — what a program built WITHOUT ``dynamic=`` on a
    streaming-capable layer honestly executes (its compiled DAG has no
    ``streaming_admit_*`` nodes, so the staging overlap declaration
    must not be checked against it). Schedules without streaming
    declarations pass through unchanged."""
    streamy = (PHASE_STREAM_ADMIT, PHASE_STREAM_COMMIT,
               PHASE_STREAM_SERVE)
    if not any(p.name in streamy or p.name.startswith("streaming_serve")
               for p in schedule.phases):
        return schedule
    if schedule.microbatches > 1:
        return pipelined_schedule(schedule.microbatches, streaming=False)
    return default_schedule()


def resolve_schedule(spec: Union[None, str, StepSchedule] = None,
                     streaming: bool = False) -> StepSchedule:
    """Normalize :class:`~.dist_embedding.DistributedEmbedding`'s
    ``schedule=`` argument: ``None``/``"serialized"`` is the honest
    serialized baseline (the streaming declaration included when the
    layer has dynamic tables), ``"pipelined"`` builds
    :func:`pipelined_schedule` with ``DETPU_MICROBATCH``'s K, and a
    :class:`StepSchedule` passes through as-is."""
    if spec is None or spec == "serialized":
        return streaming_schedule() if streaming else default_schedule()
    if spec == "pipelined":
        return pipelined_schedule(streaming=streaming)
    if isinstance(spec, StepSchedule):
        return spec
    raise ScheduleError(
        f"schedule= takes None | 'serialized' | 'pipelined' | a "
        f"StepSchedule, got {spec!r}")

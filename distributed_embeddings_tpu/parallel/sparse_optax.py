"""O(touched-rows) embedding training under plain optax — the op-layer
IndexedSlices pipeline.

The reference registers a gradient for its lookup op that returns
``tf.IndexedSlices(unique_grad, unique_ids)`` even on ONE device
(``distributed_embeddings/python/ops/embedding_lookup_ops.py:105-122``), so
any Keras optimizer's sparse path updates only the looked-up rows. JAX
autodiff cannot return a sparse cotangent (cotangents must match primal
shapes), so differentiating through :func:`...ops.embedding_lookup`
materializes a dense ``[vocab, width]`` gradient and optax updates every
row — O(all rows) per step where the reference is O(touched rows).

This module restores the sparse pipeline without the hybrid trainer
(:func:`~.trainer.make_hybrid_train_step`), in three composable pieces:

* :func:`unique_ids_static` — static-shape sort/unique of an id stream
  (the CUB ``SortPairs`` + ``UniqueByKey`` of the reference backward,
  ``cc/kernels/embedding_lookup_kernels.cu:499-515``) returning the unique
  ids and each position's index into them.
* :func:`sparse_value_and_grad` — wraps a ``loss_fn(dense_params,
  emb_outs, *args)`` so that one backward produces dense-parameter grads
  AND per-table :class:`SparseRows` ``(unique_ids, unique_grad)``. The
  mechanism is a basis split, not a custom cotangent type: each table's id
  stream is deduped up front, the ``[U, width]`` unique rows are gathered
  once, and the loss is differentiated w.r.t. those *gathered rows* — so
  the table-side cotangent has U rows, never ``vocab``. Forward values are
  bitwise what direct lookups produce (same gather + combine).
* :func:`sparse_rows_sgd` / :func:`sparse_rows_adagrad` /
  :func:`sparse_rows_momentum` / :func:`sparse_rows_adam` — optax
  ``GradientTransformation``s whose ``update`` consumes :class:`SparseRows`
  leaves and touches only those rows of the (dense, ``[vocab, width]``)
  optimizer state; :func:`apply_sparse_updates` is the matching
  ``optax.apply_updates``. Numerics follow the package's sparse-optimizer
  semantics (:mod:`.optimizers`): optax-equal when every row is touched,
  lazy moments otherwise.

Padding/out-of-range contract: ids ``>= vocab`` read the clipped last row
in the forward (like the op layer) and are DROPPED by the update scatters
(like the hybrid path) — a bad id trains nothing. NEGATIVE ids clamp to 0
on both sides: the forward reads row 0 (``jnp.take(mode="clip")``, the op
layer's read) and the update trains row 0 — symmetric with the read,
instead of letting JAX's negative-index normalization wrap the scatter to
unrelated tail rows (ADVICE r5).
"""

from __future__ import annotations

from typing import Any, Callable, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import optax
from flax import struct
from jax import lax

from ..ops.embedding_lookup import IdsLike, Ragged, SparseIds, embedding_lookup
from ..utils import obs
from .optimizers import _SORT_STREAM_MAX, _SORT_STREAM_MIN, sgd_dedup_forced


def _sorted_decl(n: int) -> bool:
    """Whether a scatter should DECLARE its (truly sorted) indices sorted.

    The declaration changes XLA's TPU scatter lowering, and the sorted
    lowering measured 3x WORSE for small streams into huge slabs (the
    regime window of :mod:`.optimizers`; a 16M-row table step here went
    ~100 GB/s -> full-rate when the declaration was dropped). Outside the
    measured win window, stay on the default lowering."""
    return _SORT_STREAM_MIN <= int(n) <= _SORT_STREAM_MAX


@struct.dataclass
class SparseRows:
    """IndexedSlices analogue: ``rows[k]`` is the gradient (or update) for
    table row ``ids[k]``; unused capacity is marked ``>= vocab`` (dropped
    by scatters). ``unique=True`` (the default, what
    :func:`sparse_value_and_grad` builds under ``dedup=True``) additionally
    guarantees the ids are sorted and duplicate-free — stateful
    (read-modify-write) optimizers require that; the linear SGD transform
    and :func:`apply_sparse_updates` accept ``unique=False`` rows (the
    dedup-skipped path) and simply scatter-add the repeats."""

    ids: jax.Array  # [U] int32
    rows: jax.Array  # [U, width]
    vocab: int = struct.field(pytree_node=False)
    unique: bool = struct.field(pytree_node=False, default=True)


def unique_ids_static(ids: jax.Array, vocab: int,
                      max_unique: Optional[int] = None
                      ) -> Tuple[jax.Array, jax.Array]:
    """Sorted-unique of a flat id stream with static output capacity.

    Returns ``(uids [U], inv [n])`` with ``U = min(n, vocab + 1)`` (distinct
    ids can never exceed the vocab; one extra slot absorbs out-of-range
    sentinels): ``uids`` holds the distinct ids ascending, padded with
    ``vocab``; ``inv[k]`` is the index of ``ids[k]`` in ``uids``. The
    static-shape form of the reference backward's CUB sort + unique-by-key
    (``cc/kernels/embedding_lookup_kernels.cu:499-515``)."""
    n = ids.shape[0]
    u = min(n, int(vocab) + 1) if max_unique is None else int(max_unique)
    return _unique_ids_static(ids, int(vocab), n, u)


@jax.named_scope("detpu/unique_ids")
def _unique_ids_static(ids, vocab: int, n: int, u: int):
    # clamp BOTH ends BEFORE sorting. Above: ids > vocab would otherwise
    # sort past the pad slots (which hold exactly ``vocab``) and break the
    # ascending-uids property the scatters later declare; clamping merges
    # every bad id into the one dropped sentinel entry while keeping the
    # clipped-last-row forward read identical. Below: a negative id
    # surviving into uids would read row 0 in the forward (take
    # mode="clip") but WRAP to a tail row in the update scatters (JAX
    # negative-index normalization), training an unrelated row — clamping
    # to 0 makes invalid ids train row 0, symmetric with the read
    # (module docstring "Padding/out-of-range contract"; ADVICE r5).
    ids = jnp.clip(ids.astype(jnp.int32), 0, jnp.int32(vocab))
    sorted_ids, perm = lax.sort_key_val(
        ids, jnp.arange(n, dtype=jnp.int32))
    boundary = jnp.concatenate(
        [jnp.ones((1,), jnp.int32),
         (sorted_ids[1:] != sorted_ids[:-1]).astype(jnp.int32)])
    seg = jnp.cumsum(boundary) - 1  # ascending
    uids = jnp.full((u,), vocab, jnp.int32).at[seg].set(
        sorted_ids, mode="drop", indices_are_sorted=True)
    inv = jnp.zeros((n,), jnp.int32).at[perm].set(seg)
    return uids, inv


def _flat_stream(inp: IdsLike) -> jax.Array:
    """The flat id stream of one input (Ragged capacities included —
    padding positions become redundant unique entries, harmless)."""
    if isinstance(inp, Ragged):
        return inp.values.reshape(-1)
    if isinstance(inp, SparseIds):
        return inp.values.reshape(-1)
    return jnp.asarray(inp).reshape(-1)


def _remap(inp: IdsLike, inv_slice: jax.Array) -> IdsLike:
    """Rebuild an input with its ids replaced by indices into the unique
    rows (same static encoding, so the remapped lookup reuses
    :func:`...ops.embedding_lookup` unchanged). ``weights`` carry through:
    positions are unchanged by the remap, so per-id weights stay aligned
    and the remapped lookup stays bitwise-identical to the direct weighted
    lookup (a dropped field here silently computed an UNWEIGHTED
    forward/gradient for weighted inputs — ADVICE r5, medium)."""
    if isinstance(inp, Ragged):
        return Ragged(values=inv_slice, row_splits=inp.row_splits,
                      weights=inp.weights)
    if isinstance(inp, SparseIds):
        return SparseIds(indices=inp.indices, values=inv_slice,
                         dense_shape=inp.dense_shape, weights=inp.weights)
    return inv_slice.reshape(jnp.asarray(inp).shape)


def sparse_value_and_grad(loss_fn: Callable,
                          combiners: Sequence[Optional[str]],
                          input_table_map: Optional[Sequence[int]] = None,
                          has_aux: bool = False,
                          dedup: bool = True):
    """Build ``f(dense_params, tables, inputs, *args) -> (loss,
    (dense_grads, sparse_grads))`` with table gradients in O(touched rows).

    Args:
      loss_fn: ``loss_fn(dense_params, emb_outs, *args) -> scalar`` (or
        ``(scalar, aux)`` with ``has_aux``) — the same contract as the
        hybrid trainer's, with ``emb_outs[i]`` the combined lookup of
        ``inputs[i]``.
      combiners: per-TABLE combiner (``None``/'sum'/'mean').
      input_table_map: ``inputs[i]`` looks up ``tables[input_table_map[i]]``
        (default: identity — one input per table). Inputs sharing a table
        dedup jointly, so shared tables still see one unique-row gather.
      has_aux: forwarded to ``jax.value_and_grad``.
      dedup: ``True`` (default) runs the :func:`unique_ids_static`
        sort-unique pass per table, yielding ``unique=True``
        :class:`SparseRows` every ``sparse_rows_*`` transform accepts.
        ``False`` SKIPS that pass entirely — the ROADMAP 3(a) SGD dedup
        cut: the per-position rows are gathered directly (bitwise the same
        forward: a gather of a gather of the same clamped ids) and the
        returned rows carry the raw clamped id stream with
        ``unique=False``, which only gradient-LINEAR consumers
        (:func:`sparse_rows_sgd`, :func:`apply_sparse_updates`) accept —
        duplicates scatter-add exactly; the stateful transforms raise.
        One sort + cumsum + two scatters + an inverse-permutation gather
        per table per step are eliminated. ``DETPU_SGD_DEDUP=1`` (checked
        at build time) forces ``dedup=True`` back on for A/B.

    Returns a function over ``tables``: a list (or dict values in order) of
    dense ``[vocab, width]`` arrays. Its ``sparse_grads`` output is a list
    of :class:`SparseRows` aligned with ``tables`` — feed them to a
    ``sparse_rows_*`` transform + :func:`apply_sparse_updates`.
    """
    combiners = list(combiners)
    if not dedup and sgd_dedup_forced():
        dedup = True  # the A/B escape hatch wins over the caller's skip

    def f(dense_params, tables: Sequence[jax.Array], inputs: Sequence[IdsLike],
          *args):
        tables = list(tables)
        inputs = list(inputs)
        tmap = (list(input_table_map) if input_table_map is not None
                else list(range(len(inputs))))
        if len(tmap) != len(inputs):
            raise ValueError("input_table_map must align with inputs")
        if len(combiners) != len(tables):
            raise ValueError("combiners must align with tables (one per "
                             "table)")
        # --- 1. per table: joint unique over all its inputs' id streams
        streams: List[List[jax.Array]] = [[] for _ in tables]
        for i, inp in enumerate(inputs):
            streams[tmap[i]].append(_flat_stream(inp))
        uids, invs, urows = [], [], []
        for t, parts in enumerate(streams):
            if not parts:
                raise ValueError(f"Table {t} has no inputs")
            cat = jnp.concatenate(parts) if len(parts) > 1 else parts[0]
            vocab = tables[t].shape[0]
            if dedup:
                u, inv = unique_ids_static(cat, vocab)
            else:
                # dedup skipped: the "unique" rows are simply the
                # per-position rows under the same [0, vocab] clamp
                # unique_ids_static applies (negative -> row 0 symmetric
                # with the read; > vocab -> the dropped sentinel), and the
                # remap indices are the identity — the forward gather chain
                # and the update contract are bitwise unchanged
                u = jnp.clip(cat.astype(jnp.int32), 0, jnp.int32(vocab))
                inv = jnp.arange(cat.shape[0], dtype=jnp.int32)
            uids.append(u)
            invs.append(inv)
            # one gather per DISTINCT row (pad ids clip into the last row,
            # the op layer's documented read; their grads drop at apply)
            urows.append(jnp.take(tables[t], u, axis=0, mode="clip"))

        # --- 2. differentiate w.r.t. the gathered unique rows
        def inner(dp, rows_list):
            outs = []
            offs = [0] * len(tables)
            for i, inp in enumerate(inputs):
                t = tmap[i]
                nvals = _flat_stream(inp).shape[0]
                sl = lax.slice(invs[t], (offs[t],), (offs[t] + nvals,))
                offs[t] += nvals
                outs.append(embedding_lookup(rows_list[t], _remap(inp, sl),
                                             combiner=combiners[t]))
            return loss_fn(dp, outs, *args)

        (loss, *aux), (dgrads, rgrads) = _vg(inner, has_aux)(
            dense_params, urows)
        sgrads = [SparseRows(ids=u, rows=g, vocab=tables[t].shape[0],
                             unique=dedup)
                  for t, (u, g) in enumerate(zip(uids, rgrads))]
        if has_aux:
            return (loss, aux[0]), (dgrads, sgrads)
        return loss, (dgrads, sgrads)

    return f


def _vg(fn, has_aux):
    vg = jax.value_and_grad(fn, argnums=(0, 1), has_aux=has_aux)
    if has_aux:
        def run(dp, rows):
            (loss, aux), grads = vg(dp, rows)
            return (loss, aux), grads
        return run

    def run(dp, rows):
        loss, grads = vg(dp, rows)
        return (loss,), grads
    return run


# --------------------------------------------------------------- optax side


def _tree_rows(fn, updates, *rest):
    """Map ``fn`` over every :class:`SparseRows` leaf of ``updates`` (and
    aligned leaves of ``rest`` trees)."""
    return jax.tree.map(fn, updates, *rest,
                        is_leaf=lambda x: isinstance(x, SparseRows))


class _Out:
    """Opaque multi-value result of a per-leaf update fn. Deliberately NOT
    a registered pytree: jax.tree treats it as a leaf, so unpacking the
    per-leaf results cannot be confused with structural tuples/lists in
    the caller's parameter tree (a tuple-valued params pytree once made an
    ``is_leaf=tuple`` unpack return optimizer state as the update)."""

    __slots__ = ("vals",)

    def __init__(self, *vals):
        self.vals = vals


def _unpack(tree, i):
    return jax.tree.map(lambda o: o.vals[i], tree)


def _resolve_lr(lr, count):
    return lr(count) if callable(lr) else lr


def _require_unique(g: "SparseRows", who: str) -> None:
    """Stateful (read-modify-write) transforms need sorted-unique rows: a
    duplicated id would read stale state for its second occurrence. Raise
    at trace time rather than silently corrupt."""
    if not g.unique:
        raise ValueError(
            f"{who} requires unique SparseRows (duplicate ids would "
            "read-modify-write stale per-row state) — build the gradients "
            "with sparse_value_and_grad(dedup=True); dedup=False is only "
            "valid for gradient-linear consumers (sparse_rows_sgd, "
            "apply_sparse_updates)")


def sparse_rows_sgd(learning_rate) -> optax.GradientTransformation:
    """SGD over :class:`SparseRows` gradients: update rows are
    ``-lr * grad_rows``; dense (non-SparseRows) leaves get plain SGD.
    Linear in the gradient, so ``unique=False`` (dedup-skipped) rows are
    accepted — duplicates accumulate exactly in the apply scatter."""

    def init(params):
        del params
        return {"count": jnp.zeros((), jnp.int32)}

    def update(updates, state, params=None):
        del params
        lr = _resolve_lr(learning_rate, state["count"])

        def one(g):
            if isinstance(g, SparseRows):
                return SparseRows(ids=g.ids, rows=-lr * g.rows,
                                  vocab=g.vocab, unique=g.unique)
            return -lr * g
        return _tree_rows(one, updates), {"count": state["count"] + 1}

    return optax.GradientTransformation(init, update)


def sparse_rows_adagrad(learning_rate,
                        initial_accumulator_value: float = 0.1,
                        eps: float = 1e-7) -> optax.GradientTransformation:
    """Adagrad over :class:`SparseRows` gradients; ``optax.adagrad``
    numerics on the touched rows, untouched rows' accumulators unchanged
    (the Keras sparse-apply behavior the reference relies on)."""

    def init(params):
        return {"count": jnp.zeros((), jnp.int32),
                "acc": jax.tree.map(
                    lambda p: jnp.full(p.shape, initial_accumulator_value,
                                       jnp.result_type(p, jnp.float32)),
                    params)}

    def update(updates, state, params=None):
        del params
        lr = _resolve_lr(learning_rate, state["count"])
        accs = state["acc"]

        def one(g, acc):
            if not isinstance(g, SparseRows):
                new = acc + g * g
                return _Out(-lr * g * lax.rsqrt(new + eps), new)
            _require_unique(g, "sparse_rows_adagrad")
            rows = g.rows.astype(acc.dtype)
            # scatter-add FIRST, gather the updated rows after: the
            # accumulator's only write is a single-use scatter-add, which
            # XLA's TPU backend updates in place under donation — the
            # gather+scatter-set form has two uses of the old buffer and
            # forces a full slab copy every step (measured 4 GB/step at
            # vocab 16M; docs/perf_tpu.md r5)
            new_acc = acc.at[g.ids].add(
                rows * rows, mode="drop",
                indices_are_sorted=_sorted_decl(g.ids.shape[0]))
            new_rows = jnp.take(new_acc, g.ids, axis=0, mode="clip")
            upd = (-lr * rows * lax.rsqrt(new_rows + eps)).astype(
                g.rows.dtype)
            return _Out(SparseRows(ids=g.ids, rows=upd, vocab=g.vocab),
                        new_acc)

        pairs = _tree_rows(one, updates, accs)
        return _unpack(pairs, 0), {"count": state["count"] + 1,
                                   "acc": _unpack(pairs, 1)}

    return optax.GradientTransformation(init, update)


def sparse_rows_momentum(learning_rate, momentum: float = 0.9,
                         nesterov: bool = False
                         ) -> optax.GradientTransformation:
    """Heavy-ball SGD with lazy row momentum (``optax.trace`` numerics on
    touched rows; untouched rows' traces neither decay nor update)."""

    def init(params):
        return {"count": jnp.zeros((), jnp.int32),
                "trace": jax.tree.map(jnp.zeros_like, params)}

    def update(updates, state, params=None):
        del params
        lr = _resolve_lr(learning_rate, state["count"])

        def one(g, tr):
            if not isinstance(g, SparseRows):
                t_new = g + momentum * tr
                step = g + momentum * t_new if nesterov else t_new
                return _Out(-lr * step, t_new)
            _require_unique(g, "sparse_rows_momentum")
            rows = g.rows.astype(tr.dtype)
            srt = _sorted_decl(g.ids.shape[0])
            # the affine state transition t <- m*t + g runs as two single-
            # use scatters (multiply, add) so the trace slab updates in
            # place under donation; a gather+scatter-set would copy the
            # whole slab every step (see sparse_rows_adagrad)
            new_tr = tr.at[g.ids].multiply(
                momentum, mode="drop", indices_are_sorted=srt
            ).at[g.ids].add(rows, mode="drop", indices_are_sorted=srt)
            t_new = jnp.take(new_tr, g.ids, axis=0, mode="clip")
            step = rows + momentum * t_new if nesterov else t_new
            return _Out(SparseRows(ids=g.ids,
                                   rows=(-lr * step).astype(g.rows.dtype),
                                   vocab=g.vocab), new_tr)

        pairs = _tree_rows(one, updates, state["trace"])
        return _unpack(pairs, 0), {"count": state["count"] + 1,
                                   "trace": _unpack(pairs, 1)}

    return optax.GradientTransformation(init, update)


def sparse_rows_adam(learning_rate, b1: float = 0.9, b2: float = 0.999,
                     eps: float = 1e-8, eps_root: float = 0.0
                     ) -> optax.GradientTransformation:
    """Adam with lazy row moments (LazyAdam: bias correction by the global
    step count; untouched rows' moments frozen — see
    :mod:`.optimizers`)."""

    def init(params):
        return {"count": jnp.zeros((), jnp.int32),
                "mu": jax.tree.map(jnp.zeros_like, params),
                "nu": jax.tree.map(jnp.zeros_like, params)}

    def update(updates, state, params=None):
        del params
        count = state["count"] + 1
        lr = _resolve_lr(learning_rate, state["count"])
        t = count.astype(jnp.float32)

        def one(g, mu, nu):
            if not isinstance(g, SparseRows):
                mu_n = b1 * mu + (1 - b1) * g
                nu_n = b2 * nu + (1 - b2) * g * g
                mu_hat = mu_n / (1 - b1 ** t)
                nu_hat = nu_n / (1 - b2 ** t)
                return _Out(
                    -lr * mu_hat / (jnp.sqrt(nu_hat + eps_root) + eps),
                    mu_n, nu_n)
            _require_unique(g, "sparse_rows_adam")
            rows = g.rows.astype(mu.dtype)
            srt = _sorted_decl(g.ids.shape[0])
            # affine moment transitions as in-place-able multiply+add
            # scatter pairs (see sparse_rows_momentum)
            new_mu = mu.at[g.ids].multiply(
                b1, mode="drop", indices_are_sorted=srt
            ).at[g.ids].add((1 - b1) * rows, mode="drop",
                            indices_are_sorted=srt)
            new_nu = nu.at[g.ids].multiply(
                b2, mode="drop", indices_are_sorted=srt
            ).at[g.ids].add((1 - b2) * rows * rows, mode="drop",
                            indices_are_sorted=srt)
            mu_n = jnp.take(new_mu, g.ids, axis=0, mode="clip")
            nu_n = jnp.take(new_nu, g.ids, axis=0, mode="clip")
            mu_hat = mu_n / (1 - b1 ** t)
            nu_hat = nu_n / (1 - b2 ** t)
            upd = -lr * mu_hat / (jnp.sqrt(nu_hat + eps_root) + eps)
            return _Out(SparseRows(ids=g.ids, rows=upd.astype(g.rows.dtype),
                                   vocab=g.vocab), new_mu, new_nu)

        triples = _tree_rows(one, updates, state["mu"], state["nu"])
        return _unpack(triples, 0), {"count": count,
                                     "mu": _unpack(triples, 1),
                                     "nu": _unpack(triples, 2)}

    return optax.GradientTransformation(init, update)


def apply_sparse_updates(params, updates):
    """``optax.apply_updates`` for trees whose leaves may be
    :class:`SparseRows`: sparse leaves scatter-add their rows (ids past the
    vocab drop); dense leaves add elementwise."""

    def one(p, u):
        if isinstance(u, SparseRows):
            with obs.scope("sparse_rows_apply"):
                # unique=False rows (dedup skipped) are unsorted: declaring
                # sortedness would be a lie XLA is allowed to punish
                srt = u.unique and _sorted_decl(u.ids.shape[0])
                return p.at[u.ids].add(
                    u.rows.astype(p.dtype), mode="drop",
                    indices_are_sorted=srt)
        return p + u
    return jax.tree.map(one, params, updates,
                        is_leaf=lambda x: isinstance(x, SparseRows))


def sparse_grad_metrics(sparse_grads: Sequence[SparseRows]):
    """On-device observability of one sparse backward: per-table
    touched-row counts and gradient norms, jit-safe and near-free
    (the :mod:`~..utils.obs` layer's view into the sparse-optax pipeline).

    Returns ``{"touched_rows": [T] int32, "sparse_grad_norm": [T] f32}``
    aligned with ``sparse_grads`` — ``touched_rows`` counts the LIVE
    entries (ids below the vocab; pad/out-of-range sentinel entries at
    ``>= vocab`` excluded). :class:`SparseRows` built by
    :func:`sparse_value_and_grad` / :func:`unique_ids_static` carry
    sorted-unique ids, so there the live count IS the distinct-row count;
    hand-built rows with repeated ids count each repeat.
    ``sparse_grad_norm`` is the L2 norm of the live update rows. Log them
    next to the step metrics to see skew (a table whose touched count
    approaches its unique capacity every step is a dedup-win candidate; a
    norm spike localizes divergence to a table).
    """
    with obs.scope("sparse_grad_metrics"):
        touched, norms = [], []
        for g in sparse_grads:
            live = g.ids < g.vocab
            touched.append(jnp.sum(live.astype(jnp.int32)))
            rows = g.rows.astype(jnp.float32)
            norms.append(jnp.sqrt(jnp.sum(
                jnp.square(rows) * live[:, None].astype(rows.dtype))))
        return {"touched_rows": jnp.stack(touched),
                "sparse_grad_norm": jnp.stack(norms)}

"""Lookup layer of the hybrid step: plan-driven gathers and combiners.

One of the three executor modules the ``dist_embedding.py`` monolith
split into (:mod:`.exchange` / lookup / :mod:`.apply`). This module owns
everything between the two forward exchanges: decoding the received
group regions, the per-(width, kind) slab gathers, combiner reductions,
and the shared ragged CSR machinery the backward reuses.

Each (width, kind) group runs under its own ``obs.scope`` in the
:data:`~.schedule.PHASE_LOOKUP` phase family (``lookup_w{w}_{kind}``),
so profiles, the HLO census, and the schedule auditor attribute
gather/combine cost to the width it serves.

Every function takes the owning
:class:`~.dist_embedding.DistributedEmbedding` as its first argument
(except the pure shape helpers); the split is pure code motion — the
traced program is bit-for-bit what the monolith's methods produced.
"""

from __future__ import annotations

import functools
from typing import List

import jax
import jax.numpy as jnp
from jax import lax

from ..utils import obs
from ..ops.embedding_lookup import ragged_row_ids
from ..ops import packed_slab as ps


def _wkey(width: int) -> str:
    return f"w{width}"


def csr_seg(lengths, cap: int):
    """CSR offsets and per-position segment ids from per-row lengths,
    for any leading batch dims: ``lengths [..., b]`` ->
    ``(splits [..., b+1], seg [..., cap])`` with positions past each
    CSR's total mapped to ``b``. The one derivation every ragged path
    shares (the reference's ``RowToSplit``/``OffsetToWeightsAndRowId``
    pair, ``embedding_lookup_kernels.cu:331-361``)."""
    lead = lengths.shape[:-1]
    b = lengths.shape[-1]
    flat = lengths.reshape(-1, b)
    zero = jnp.zeros((flat.shape[0], 1), flat.dtype)
    splits = jnp.concatenate([zero, jnp.cumsum(flat, axis=1)], axis=1)
    seg = jax.vmap(functools.partial(ragged_row_ids, capacity=cap))(
        splits)
    return splits.reshape(*lead, b + 1), seg.reshape(*lead, cap)


def ragged_decode(de, g, b: int, region, rows, roff, valid,
                  need_counts: bool = True, rbase=None):
    """Decode one ragged group region ``[world, n*(cap+b)]`` into
    ``(values, lengths, seg, grow, counts)``, all ``[world, n, ...]``.
    Dead slots get zero lengths, so every position routes to the dropped
    segment ``b``. ``valid=None`` means every slot is statically live
    (skips the mask multiply); ``need_counts=False`` skips the
    mean-divisor counts (sum-only groups never read them); ``rbase``
    (row-sliced slots) is subtracted from the raw values before the
    clip — ``values`` stays raw so callers mask consistently."""
    world = de.world_size
    with obs.scope("ragged_decode"):
        r3 = region.reshape(world, g.n, g.blen)
        values = r3[:, :, :g.hot]
        lengths = r3[:, :, g.hot:g.hot + b]  # "rw" blocks carry weight
        # bits past the lengths (decoded by region_weights)
        if valid is not None:
            lengths = lengths * valid[None, :, None].astype(r3.dtype)
        _, seg = csr_seg(lengths, g.hot)
        loc = (values - rbase[None, :, None] if rbase is not None
               else values)
        grow = (jnp.clip(loc, 0, (rows - 1)[None, :, None])
                + roff[None, :, None])
        counts = jnp.maximum(lengths, 1) if need_counts else None
        return values, lengths, seg, grow, counts


def region_weights(de, g, b: int, region) -> jax.Array:
    """Decode a weighted-ragged ("rw") region's per-id weights
    ``[world, n, cap]`` from the bitcast payload past the lengths."""
    world = de.world_size
    r3 = region.reshape(world, g.n, g.blen)
    bits = r3[:, :, g.hot + b:].astype(jnp.int32)
    return lax.bitcast_convert_type(bits, jnp.float32)


def ragged_scatter_idx(g, b: int, world: int, seg) -> jax.Array:
    """Flattened per-value output index into a ``[world*n*(b+1), w]``
    segment buffer; row ``b`` of each slot is the dropped sentinel."""
    s_ix = jnp.arange(world, dtype=seg.dtype)[:, None, None]
    f_ix = jnp.arange(g.n, dtype=seg.dtype)[None, :, None]
    return (s_ix * g.n + f_ix) * (b + 1) + seg


def plan_lookup(de, plan, params, ids_recv, tag: str = "") -> jax.Array:
    """All local lookups in exchange-row layout ``[world, b, s_max]``
    (``compute_dtype`` — the pre-comm mixed-precision cast, reference
    ``dist_model_parallel.py:300``). Dead slots produce garbage columns
    that no consumer ever slices. ``tag`` suffixes the group scopes
    (the pipelined step's ``_mb{k}`` instances; empty = serialized)."""
    world = de.world_size
    b = plan.b
    # plan_lookup_groups already casts to compute_dtype; only the
    # no-groups zeros fallback needs the explicit dtype
    zdt = (de.compute_dtype
           or next(iter(params.values())).dtype)
    sections = [
        red.transpose(0, 2, 1, 3).reshape(world, b, -1)
        for red in plan_lookup_groups(de, plan, params, ids_recv,
                                      tag=tag)]
    return (jnp.concatenate(sections, axis=2) if sections
            else de._vary(jnp.zeros((world, b, plan.s_max), zdt)))


def plan_lookup_groups(de, plan, params, ids_recv,
                       tag: str = "") -> List[jax.Array]:
    """Per-group combined lookups in slot-major ``[world, n, b, width]``
    layout: one region reshape, one slab gather, one combine per group.
    The single-worker forward consumes these directly (its per-instance
    outputs are plain slot slices), skipping the ``[world, b, s_max]``
    exchange-row transpose that only the all-to-all needs — the dense
    model re-stacks outputs feature-major anyway, so the transpose
    round trip was a pure extra pass at headline shapes."""
    my = de._my_rank()
    sections = []
    for gi, g in enumerate(plan.groups):
        # one named scope per (width, kind) group: a profile of the
        # step attributes gather/combine time to the width it serves
        with obs.scope(f"lookup_w{g.width}_{g.kind}{tag}"):
            red = lookup_group(de, plan, gi, g, params[_wkey(g.width)],
                               ids_recv, my, plan.b)
        dt = de.compute_dtype
        sections.append(red.astype(dt) if dt is not None else red)
    return sections


def lookup_group(de, plan, gi: int, g, slab, ids_recv, my,
                 b: int) -> jax.Array:
    """One exchange group's combined lookup in slot-major
    ``[world, n, b, width]`` layout (the body of
    :func:`plan_lookup_groups`, split out so each group runs under its
    own named scope)."""
    world = de.world_size
    rows = de._plan_row(plan.rows[gi], my)
    roff = de._plan_row(plan.roff[gi], my)
    # mean/valid are *static* plan tensors: when no slot on any rank
    # is a mean combiner (resp. dead), the divide (resp. mask) is
    # skipped at trace time — sum-only groups never touch counts
    any_mean = bool(plan.mean[gi].any())
    all_mean = bool(plan.mean[gi].all())
    all_valid = bool((plan.valid[gi] > 0).all())
    # row-sliced slots subtract their range base and must read zero
    # outside the range (their outputs SUM across slices); the same
    # mask doubles as the opt-in masked_reads debug contract. The
    # mask is gated PER SLOT (plan.rsliced): an unsliced table that
    # shares the exchange group keeps the documented
    # clip-to-last-row read unless masked_reads=True.
    any_rslice = bool(plan.rsliced[gi].any())
    use_mask = any_rslice or de.masked_reads
    rbase = (de._plan_row(plan.rbase[gi], my) if any_rslice
             else None)
    region = lax.slice(ids_recv, (0, g.goff),
                       (world, g.goff + g.n * g.blen))
    if g.kind == "d":
        ids = region.reshape(world, g.n, b, g.hot)
        if rbase is not None:
            ids = ids - rbase[None, :, None, None]
        grow = (jnp.clip(ids, 0, (rows - 1)[None, :, None, None])
                + roff[None, :, None, None])
        gath = ps.packed_gather(slab, grow, g.width)
        if use_mask:
            inr = ((ids >= 0) & (ids < rows[None, :, None, None]))
            if not de.masked_reads:  # only sliced slots mask
                rsl = de._plan_row(plan.rsliced[gi], my)
                inr = inr | (rsl[None, :, None, None] == 0)
            gath = gath * inr[..., None].astype(gath.dtype)
        red = jnp.sum(gath, axis=3)  # [world, n, b, w]
        if g.hot > 1 and any_mean:
            if all_mean:
                red = red / g.hot
            else:
                mean = de._plan_row(plan.mean[gi], my)
                red = jnp.where(mean[None, :, None, None] > 0,
                                red / g.hot, red)
    else:
        values, _, seg, grow, counts = ragged_decode(
            de, g, b, region, rows, roff,
            None if all_valid else de._plan_row(plan.valid[gi], my),
            need_counts=any_mean, rbase=rbase)
        gath = ps.packed_gather(slab, grow, g.width)  # [w, n, cap, ww]
        if g.kind == "rw":
            # per-id weights multiply the gathered rows (reference
            # kernel's optional weights, .cu:52-55); mean still
            # divides by the id count (.cu:220-222)
            wts = region_weights(de, g, b, region)
            gath = gath * wts[..., None].astype(gath.dtype)
        if use_mask:
            loc = (values - rbase[None, :, None]
                   if rbase is not None else values)
            inr = ((loc >= 0) & (loc < rows[None, :, None]))
            if not de.masked_reads:  # only sliced slots mask
                rsl = de._plan_row(plan.rsliced[gi], my)
                inr = inr | (rsl[None, :, None] == 0)
            gath = gath * inr[..., None].astype(gath.dtype)
        sidx = ragged_scatter_idx(g, b, world, seg)
        buf = jnp.zeros((world * g.n * (b + 1), g.width), gath.dtype)
        # sidx ascends globally: (source, slot) blocks are laid out
        # ascending and seg ascends within each CSR block
        buf = buf.at[sidx.reshape(-1)].add(
            gath.reshape(-1, g.width), indices_are_sorted=True)
        red = buf.reshape(world, g.n, b + 1, g.width)[:, :, :b, :]
        if any_mean:
            div = red / counts[..., None].astype(red.dtype)
            if all_mean:
                red = div
            else:
                mean = de._plan_row(plan.mean[gi], my)
                red = jnp.where(mean[None, :, None, None] > 0,
                                div, red)
    return red

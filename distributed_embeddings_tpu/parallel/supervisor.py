"""Process-isolated serving: the supervised out-of-process worker.

ISSUE 18's tentpole piece 2. PR 16 proved train-and-serve correctness
with both halves in ONE process — which means a serving crash is a
training crash and serve latency rides the trainer's scheduler. This
module separates the revenue path (serving) from the state path
(training) with a real process boundary and a supervision loop over it:

* :class:`ServingWorker` — a child process (ALWAYS the ``spawn`` start
  method: forking after the jax backend initialises deadlocks in the
  runtime's internal threads) that builds its own model + compiled
  ladder from a picklable factory spec, attaches the
  :class:`~..utils.shm.SnapshotShm` region, runs its own
  :class:`~.serving.ServingRuntime` and mplane HTTP exporter, and
  answers requests over a local AF_UNIX socket.
* :class:`Supervisor` — the trainer-side handle. It mirrors the
  runtime's ``submit``/``poll``/``install_snapshot``/``stats`` surface,
  so the :class:`~.online.SnapshotPublisher` and
  :class:`~.serving.RealtimeDriver` work against it UNCHANGED; under
  the surface it heartbeats the worker on a deadline, detects crashes
  (dead pid, socket EOF) and hangs (missed pongs), kills and restarts
  with jittered exponential backoff under a restart budget, answers
  every request caught in an outage with a typed
  :class:`~.serving.Unavailable` (a rung BELOW ``stale_snapshot``:
  a stale server still answers, a dead one answers typed), and dumps
  the crash flight-recorder black box ON BEHALF of the SIGKILLed child
  — the child cannot dump its own.

The isolation contract, drilled by ``make check-isolation``: training
never blocks on the worker (snapshot publication is a seqlock write
into shared memory; socket sends ride a dedicated sender thread) and
never dies with it; the training trajectory is checkpoint-CRC-identical
to a serving-free run even across worker kills.

Fault injection: ``DETPU_FAULT=die@<pos>`` / ``hang@<pos>`` fire INSIDE
the worker at global request-stream ordinals (the supervisor's request
counter, monotone across restarts — each position fires at most once,
so a drill kill is followed by a clean recovery, not a crash loop).
``die@`` hard-exits with no cleanup (the SIGKILL/OOM-kill equivalent);
``hang@`` stops answering (the wedged-process equivalent) and must be
caught by the heartbeat deadline, never by worker cooperation.
"""

from __future__ import annotations

import collections
import dataclasses
import importlib
import logging
import multiprocessing
import os
import pickle
import queue
import random
import threading
import time
import traceback
from multiprocessing.connection import Client, Listener
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

from ..utils import envvars, mplane, obs, reqtrace
from ..utils import runtime as runtime_mod
from ..utils import shm as shm_mod
from .serving import ServeResult, Served, Unavailable

logger = logging.getLogger(__name__)

HEARTBEAT_ENV = "DETPU_SUPERVISE_HEARTBEAT_S"
DEADLINE_ENV = "DETPU_SUPERVISE_DEADLINE_S"
MAX_RESTARTS_ENV = "DETPU_SUPERVISE_MAX_RESTARTS"
BACKOFF_BASE_ENV = "DETPU_SUPERVISE_BACKOFF_BASE_S"
BACKOFF_MAX_ENV = "DETPU_SUPERVISE_BACKOFF_MAX_S"
START_TIMEOUT_ENV = "DETPU_SUPERVISE_START_TIMEOUT_S"

# the spawn context, requested ONCE at import: fork after jax backend
# init deadlocks, and a supervisor lives in a process that has
# necessarily initialised jax (it trains)  # spawn-ok: module policy
_SPAWN = multiprocessing.get_context("spawn")

#: metrics-federation cadence: the worker attaches its registry's
#: ``to_dict`` document to at most one pong per this many seconds (the
#: document is a few KB of counters + sketch buckets — cheap, but not
#: per-heartbeat cheap), so the supervisor's merged ``/metrics`` view
#: lags the worker by at most this plus one heartbeat
_FED_EVERY_S = 0.5


# ------------------------------------------------- snapshot serialization


def snapshot_payload(state, streaming_state=None) -> bytes:
    """Serialize the SERVABLE view of a train state for the wire: the
    embedding + dense parameter leaves (as host numpy, in tree order)
    plus the streaming-table state. Optimizer slots never cross the
    boundary — eval does not read them, exactly the frozen-opt idiom of
    the in-process :class:`~.online.SnapshotPublisher`."""
    import jax

    params = jax.tree_util.tree_leaves(
        (state.emb_params, state.dense_params))
    stream = (jax.tree_util.tree_leaves(streaming_state)
              if streaming_state is not None else None)
    doc = {
        "step": int(jax.device_get(state.step)),  # host-ok: snapshot export
        "params": [np.asarray(jax.device_get(x))  # host-ok: snapshot export
                   for x in params],
        "stream": ([np.asarray(jax.device_get(x))  # host-ok: snapshot export
                    for x in stream]
                   if stream is not None else None),
    }
    return pickle.dumps(doc, protocol=pickle.HIGHEST_PROTOCOL)


def install_payload(payload: bytes, template_state,
                    template_streaming=None) -> Tuple[Any, Any, int]:
    """Rebuild a served state from :func:`snapshot_payload` bytes onto
    the WORKER's own templates: leaves are ``device_put`` with the
    template leaf's sharding so the compiled ladder's jit cache keys
    stay bitwise-in-spec — 0 steady-state recompiles per install, the
    same contract the in-process path pins."""
    import jax

    doc = pickle.loads(payload)
    tmpl = (template_state.emb_params, template_state.dense_params)
    leaves, treedef = jax.tree_util.tree_flatten(tmpl)
    if len(doc["params"]) != len(leaves):
        raise ValueError(
            f"snapshot has {len(doc['params'])} param leaves, worker "
            f"template has {len(leaves)} — trainer and worker must "
            f"build the SAME model at the SAME world size")

    from jax.sharding import NamedSharding

    def _put(arr, like):
        if arr.shape != like.shape or arr.dtype != like.dtype:
            raise ValueError(
                f"snapshot leaf {arr.shape}/{arr.dtype} does not match "
                f"worker template {like.shape}/{like.dtype}")
        sh = getattr(like, "sharding", None)
        if isinstance(sh, NamedSharding):
            # mesh-sharded template leaf: rebuild the global array with
            # the SAME sharding so the jit cache key matches the ladder
            return jax.device_put(arr, sh)
        # single-device leaf: stay host-side and UNCOMMITTED, exactly
        # like the template jit staged — a committed device_put here
        # changes the cache key and retraces (1 recompile per install)
        return arr

    put = [_put(a, l) for a, l in zip(doc["params"], leaves)]
    emb_params, dense_params = jax.tree_util.tree_unflatten(treedef, put)
    state = template_state._replace(
        emb_params=emb_params, dense_params=dense_params,
        step=np.asarray(doc["step"],
                        np.asarray(template_state.step).dtype))
    streaming_state = None
    if doc["stream"] is not None:
        if template_streaming is None:
            raise ValueError("snapshot carries streaming state but the "
                             "worker serves none")
        sleaves, sdef = jax.tree_util.tree_flatten(template_streaming)
        sput = [_put(a, l) for a, l in zip(doc["stream"], sleaves)]
        streaming_state = jax.tree_util.tree_unflatten(sdef, sput)
    return state, streaming_state, doc["step"]


# ------------------------------------------------------------- the config


@dataclasses.dataclass
class SuperviseConfig:
    """Supervision policy. ``None`` fields resolve from the registered
    ``DETPU_SUPERVISE_*`` knobs at construction."""

    heartbeat_s: Optional[float] = None
    deadline_s: Optional[float] = None
    max_restarts: Optional[int] = None
    backoff_base_s: Optional[float] = None
    backoff_max_s: Optional[float] = None
    start_timeout_s: Optional[float] = None
    # the supervisor-side crash black box (None disables)
    blackbox_path: Optional[str] = None
    # worker-side mplane scrape port (None -> worker env decides)
    metrics_port: Optional[int] = None
    # extra environment for the worker process (applied around spawn)
    env: Dict[str, str] = dataclasses.field(default_factory=dict)

    def __post_init__(self):
        if self.heartbeat_s is None:
            self.heartbeat_s = envvars.get_float(HEARTBEAT_ENV)
        if self.deadline_s is None:
            self.deadline_s = envvars.get_float(DEADLINE_ENV)
        if self.max_restarts is None:
            self.max_restarts = envvars.get_int(MAX_RESTARTS_ENV)
        if self.backoff_base_s is None:
            self.backoff_base_s = envvars.get_float(BACKOFF_BASE_ENV)
        if self.backoff_max_s is None:
            self.backoff_max_s = envvars.get_float(BACKOFF_MAX_ENV)
        if self.start_timeout_s is None:
            self.start_timeout_s = envvars.get_float(START_TIMEOUT_ENV)
        if self.heartbeat_s <= 0 or self.deadline_s <= self.heartbeat_s:
            raise ValueError(
                f"need 0 < heartbeat_s ({self.heartbeat_s}) < deadline_s "
                f"({self.deadline_s}) — a deadline the heartbeat cannot "
                f"beat declares every worker hung")


# ------------------------------------------------------------- the worker


def _resolve_factory(spec: str) -> Callable:
    mod_name, _, attr = spec.partition(":")
    if not attr:
        raise ValueError(
            f"worker factory must be 'module:attr', got {spec!r}")
    return getattr(importlib.import_module(mod_name), attr)


def _worker_main(spec: Dict[str, Any]) -> None:
    """Entry point of the serving worker child (module-level: spawn
    pickles the target by reference). Builds the model via the factory,
    warms the ladder, attaches shared memory, then serves until told to
    shut down — or until a ``die@``/``hang@`` drill takes it out."""
    conn = Client(spec["address"], authkey=spec["authkey"])
    try:
        _worker_body(conn, spec)
    except SystemExit:
        raise
    except BaseException:  # noqa: BLE001 - last-chance telemetry: the
        # supervisor turns the EOF into a crash either way, but the
        # traceback makes the black box actionable
        try:
            conn.send(("worker_error", traceback.format_exc()))
        except Exception:  # noqa: BLE001 - conn may be the casualty
            pass
        raise
    finally:
        try:
            conn.close()
        except Exception:  # noqa: BLE001 - already torn down
            pass


def _worker_body(conn, spec: Dict[str, Any]) -> None:
    from .serving import ServingRuntime  # jax import deferred to child

    factory = _resolve_factory(spec["factory"])
    built = factory(**spec.get("kwargs", {}))
    rt = ServingRuntime(
        built["de"], built["pred_fn"], built["state"],
        mesh=built.get("mesh"), config=built.get("config"),
        streaming=built.get("streaming"))
    template_state = built["state"]
    template_streaming = (built["streaming"][1]
                          if built.get("streaming") else None)
    rt.warmup(built["template"])
    if spec.get("slo") is not None:
        rt.set_freshness_slo(steps=spec["slo"][0], seconds=spec["slo"][1])
    exporter = mplane.start_http_exporter(rt.metrics,
                                          port=spec.get("metrics_port"))
    region = None
    if spec.get("shm_name"):
        region = shm_mod.SnapshotShm.attach(spec["shm_name"])
    installed_seq = 0
    die_at = set(runtime_mod.die_steps())
    hang_at = set(runtime_mod.hang_steps())
    ridmap: Dict[int, int] = {}  # runtime rid -> supervisor rid
    last_fed = 0.0  # last metrics-federation send (worker monotonic)
    conn.send(("ready", {"pid": os.getpid(),
                         "warmup_compiles": rt.warmup_compiles,
                         "metrics_port": exporter.port if exporter else None}))

    def _ingest() -> None:
        nonlocal installed_seq
        if region is None:
            return
        snap = region.read_latest()
        if snap is None or snap.seq <= installed_seq:
            return
        state, streaming_state, _ = install_payload(
            snap.payload, template_state, template_streaming)
        rt.install_snapshot(state, streaming_state, version=snap.version,
                            train_step=snap.train_step,
                            published_t=snap.wall_ts)
        installed_seq = snap.seq

    def _emit(res: ServeResult) -> None:
        sup_rid = ridmap.pop(res.rid, None)
        if sup_rid is None:
            return
        res.rid = sup_rid
        if isinstance(res, Served) and res.predictions is not None:
            res.predictions = np.asarray(res.predictions)
        conn.send(("result", res))

    while True:
        _ingest()
        while conn.poll(0.001):
            msg = conn.recv()
            kind = msg[0]
            if kind == "ping":
                # metrics federation rides the heartbeat it already
                # pays for: at most one registry snapshot per
                # _FED_EVERY_S, so the supervisor's /metrics can serve
                # the worker's families without a second channel
                fed = None
                wnow = time.monotonic()
                if wnow - last_fed >= _FED_EVERY_S:
                    last_fed = wnow
                    fed = rt.metrics.to_dict()
                conn.send(("pong", msg[1], fed))
            elif kind == "request":
                sup_rid, ordinal, req = msg[1], msg[2], msg[3]
                if ordinal in die_at:
                    # the SIGKILL/OOM equivalent: no cleanup, no goodbye
                    os._exit(17)
                if ordinal in hang_at:
                    # the wedged-process equivalent: stop answering
                    # EVERYTHING (heartbeats included) without exiting —
                    # detection must never depend on our cooperation
                    while True:
                        time.sleep(3600)
                rej = rt.submit(req)
                if rej is not None:
                    rej.rid = sup_rid
                    conn.send(("result", rej))
                else:
                    ridmap[req.rid] = sup_rid
            elif kind == "train_step":
                rt.note_train_step(msg[1])
            elif kind == "shm":
                region = shm_mod.SnapshotShm.attach(msg[1])
            elif kind == "slo":
                rt.set_freshness_slo(steps=msg[1], seconds=msg[2])
            elif kind == "flush":
                for res in rt.flush():
                    _emit(res)
            elif kind == "stats":
                conn.send(("stats_reply", rt.stats()))
            elif kind == "shutdown":
                for res in rt.flush():
                    _emit(res)
                conn.send(("bye",))
                if exporter:
                    exporter.stop()
                if region is not None:
                    region.close()
                return
        for res in rt.poll():
            _emit(res)


class ServingWorker:
    """Handle on one worker incarnation: the spawn-context process plus
    its connection. Thin — policy lives in :class:`Supervisor`."""

    def __init__(self, process, conn, info: Dict[str, Any]):
        self.process = process
        self.conn = conn
        self.info = info

    @property
    def pid(self) -> int:
        return self.process.pid

    def alive(self) -> bool:
        return self.process.is_alive()

    def kill(self) -> None:
        """SIGKILL — the worker may be wedged; SIGTERM would trust it."""
        try:
            self.process.kill()
        except Exception:  # noqa: BLE001 - already gone
            pass
        self.process.join(timeout=10)

    def close(self) -> None:
        try:
            self.conn.close()
        except Exception:  # noqa: BLE001 - already closed
            pass


# --------------------------------------------------------- the supervisor


class Supervisor:
    """Trainer-side handle on a supervised out-of-process serving
    worker; presents the :class:`~.serving.ServingRuntime` surface.

    Usage::

        sup = Supervisor("tools.isolation_common:worker_factory",
                         kwargs={"world": 8},
                         config=SuperviseConfig(blackbox_path=...))
        sup.start()                       # blocks until worker warm
        sup.install_snapshot(state, streaming_state,
                             version=1, train_step=0)
        rej = sup.submit(req)             # None | Overloaded | Unavailable
        results = sup.poll()
        ...
        sup.close()

    Thread model: the caller's threads only touch in-memory state and
    the send QUEUE (training never blocks on a slow/hung worker); one
    monitor thread owns the socket (heartbeats, receive, crash/hang
    detection, restart); one sender thread drains the queue into the
    socket. Snapshot publication bypasses the socket entirely — it is a
    seqlock write into shared memory, crash-proof by construction.
    """

    # state the caller / monitor / sender threads share (detlint
    # thread-shared): every mutation holds self._lock, or carries a
    # thread-local-ok waiver at the site explaining why it is safe
    # (pre-thread construction, post-join teardown, atomic reference
    # swap by a sole writer)
    _THREAD_SHARED = (
        "_alive", "_closing", "_counts", "_down_reason", "_down_since",
        "_fed_archive", "_fed_latest", "_inflight", "_last_pong",
        "_last_train_step", "_last_version", "_next_rid", "_outage_trace",
        "_restarts", "_results", "_shm", "_slo", "_warm", "_worker",
        "_worker_stats", "restart_budget_exhausted",
    )

    def __init__(self, factory: str, kwargs: Optional[Dict[str, Any]] = None,
                 *, config: Optional[SuperviseConfig] = None,
                 clock: Callable[[], float] = time.monotonic):
        self.cfg = config or SuperviseConfig()
        self._factory = factory
        self._kwargs = dict(kwargs or {})
        self._clock = clock
        self._listener = Listener(family="AF_UNIX",
                                  authkey=_SPAWN.current_process().authkey)
        self._worker: Optional[ServingWorker] = None
        self._shm: Optional[shm_mod.SnapshotShm] = None
        self._slo: Optional[Tuple[Optional[float], Optional[float]]] = None
        self._lock = threading.Lock()
        self._results: collections.deque = collections.deque()
        self._inflight: Dict[int, float] = {}
        self._next_rid = 0
        self._alive = False
        self._warm = False
        self._closing = False
        self._down_since = self._clock()
        self._down_reason = "never_started"
        self._last_pong = 0.0
        self._restarts = 0
        self.restart_budget_exhausted = False
        self._counts = collections.Counter()
        self._worker_stats: Dict[str, Any] = {}
        self._stats_event = threading.Event()
        self._last_train_step: Optional[int] = None
        self._last_version = 0
        self._publish_ms = mplane.QuantileSketch()
        self._restart_to_serve_ms: List[float] = []
        self._awaiting_first_served: Optional[float] = None
        self._recorder = (mplane.FlightRecorder(self.cfg.blackbox_path)
                          if self.cfg.blackbox_path else None)
        self._send_q: "queue.Queue" = queue.Queue()
        self._monitor: Optional[threading.Thread] = None
        self._sender: Optional[threading.Thread] = None
        # ---- request tracing (utils/reqtrace.py): the supervisor MINTS
        # each trace at submit; the context rides the request over the
        # socket and the worker's runtime adopts it, so its stage spans
        # re-parent under this id — across die@ restarts too. The trace
        # the outage touched LAST (newest stranded rid, then each
        # refused-during-outage rid in turn — the one the bounded ring
        # cannot have evicted) is remembered in _outage_trace; when the
        # reborn worker serves its first request, worker_restarted /
        # served_after_restart marks are appended to it: ONE retained
        # trace shows submit -> outage -> Unavailable -> restart ->
        # served (what make check-tracing asserts)
        self._e2e_ms = mplane.QuantileSketch()  # end-to-end, this side
        self.traces = reqtrace.TraceBuffer(process="supervisor",
                                           top_fn=self._trace_top_decile)
        self._outage_trace: Optional[str] = None
        # ---- metrics federation: the worker's registry documents
        # arrive on pongs (_fed_latest); a dead incarnation's last
        # document is absorbed into _fed_archive (sketch-merged), so
        # counts survive restarts. The supervisor's own registry serves
        # ONE merged /metrics view over both plus its own families
        self._fed_latest: Optional[Dict[str, Any]] = None
        self._fed_archive: Optional[Dict[str, Any]] = None
        self.metrics = mplane.MetricsRegistry()
        self.metrics.register_collector(self._collect_metrics)
        self.metrics.add_federated(self._federated_doc)

    def _trace_top_decile(self) -> Optional[float]:
        """Tail-retention threshold: q90 of the end-to-end latency the
        supervisor itself observed (None while cold)."""
        return (self._e2e_ms.quantile(0.9) if self._e2e_ms.count >= 20
                else None)

    def _collect_metrics(self) -> None:
        """Scrape-time adapter for the supervisor's OWN families (the
        worker's arrive via federation)."""
        with self._lock:
            alive = self._alive
            restarts = self._restarts
            outage = 0.0 if alive else self._clock() - self._down_since
            exhausted = self.restart_budget_exhausted
            counts = dict(self._counts)
        mplane.sync_counters(self.metrics, counts,
                             name="detpu_supervisor_total", label="outcome")
        g = self.metrics.gauge
        g("detpu_supervisor_worker_alive",
          "1 while the serving worker is up").set(int(alive))
        g("detpu_supervisor_restarts",
          "supervised worker restarts spent").set(restarts)
        g("detpu_supervisor_outage_s",
          "current outage age (0 while the worker is up)").set(outage)
        g("detpu_supervisor_restart_budget_exhausted",
          "1 once the restart budget is spent").set(int(exhausted))
        g("detpu_supervisor_trace_ring",
          "retained supervisor-side request traces").set(
            self.traces.stats()["retained"])
        if self._publish_ms.count:
            g("detpu_supervisor_shm_publish_p95_ms",
              "seqlock snapshot publish latency p95 (ms)").set(
                self._publish_ms.quantile(0.95))

    def _federated_doc(self) -> Optional[Dict[str, Any]]:
        """The worker-side registry document for the merged scrape: the
        live incarnation's latest, sketch-merged over every dead
        incarnation's final document."""
        with self._lock:
            docs = [d for d in (self._fed_archive, self._fed_latest) if d]
        if not docs:
            return None
        # merge outside the lock: the documents are immutable once
        # stored (swaps replace the reference, merge copies)
        return (mplane.merge_registry_docs(docs) if len(docs) > 1
                else docs[0])

    # ------------------------------------------------------------ spawn

    def _spawn_spec(self) -> Dict[str, Any]:
        return {
            "address": self._listener.address,
            "authkey": bytes(_SPAWN.current_process().authkey),
            "factory": self._factory,
            "kwargs": self._kwargs,
            "shm_name": self._shm.name if self._shm else None,
            "slo": self._slo,
            "metrics_port": self.cfg.metrics_port,
        }

    def _spawn_worker(self) -> ServingWorker:
        spec = self._spawn_spec()
        proc = _SPAWN.Process(target=_worker_main, args=(spec,),
                              name="detpu-serving-worker", daemon=True)
        saved = {k: os.environ.get(k) for k in self.cfg.env}
        os.environ.update(self.cfg.env)
        try:
            proc.start()
        finally:
            for k, v in saved.items():
                if v is None:
                    os.environ.pop(k, None)
                else:
                    os.environ[k] = v
        conn_box: List[Any] = []
        accept = threading.Thread(
            target=lambda: conn_box.append(self._listener.accept()),
            daemon=True)
        accept.start()
        accept.join(self.cfg.start_timeout_s)
        if not conn_box:
            proc.kill()
            proc.join(timeout=10)
            raise TimeoutError(
                f"serving worker did not connect within "
                f"{self.cfg.start_timeout_s}s")
        conn = conn_box[0]
        deadline = self._clock() + self.cfg.start_timeout_s
        while True:
            if conn.poll(max(0.0, min(1.0, deadline - self._clock()))):
                msg = conn.recv()
                if msg[0] == "ready":
                    return ServingWorker(proc, conn, msg[1])
                if msg[0] == "worker_error":
                    proc.kill()
                    proc.join(timeout=10)
                    raise RuntimeError(
                        f"serving worker failed to build:\n{msg[1]}")
                continue  # unrelated early chatter
            if self._clock() >= deadline or not proc.is_alive():
                proc.kill()
                proc.join(timeout=10)
                raise TimeoutError("serving worker never became ready")

    def start(self) -> "Supervisor":
        """Spawn the first worker and block until it is warm (compiled
        ladder + attached shm); then supervision runs in the
        background."""
        if self._monitor is not None:
            raise RuntimeError("supervisor already started")
        self._worker = self._spawn_worker()  # thread-local-ok: runs before the monitor/sender threads exist
        self._on_worker_up()
        self._sender = threading.Thread(target=self._send_loop,
                                        name="detpu-supervise-send",
                                        daemon=True)
        self._sender.start()
        self._monitor = threading.Thread(target=self._monitor_loop,
                                         name="detpu-supervise",
                                         daemon=True)
        self._monitor.start()
        return self

    def _on_worker_up(self) -> None:
        now = self._clock()
        with self._lock:
            self._alive = True
            self._warm = True
            self._last_pong = now
            if self._restarts:
                self._awaiting_first_served = now
            step = self._last_train_step
        if step is not None:
            self._send_q.put(("train_step", step))

    # ----------------------------------------------------- wire plumbing

    def _send_loop(self) -> None:
        while not self._closing:
            try:
                item = self._send_q.get(timeout=0.1)
            except queue.Empty:
                continue
            worker = self._worker
            if worker is None or not self._alive:
                continue  # outage: the crash path answers for us
            try:
                worker.conn.send(item)
            except Exception:  # noqa: BLE001 - a broken pipe IS the
                # crash signal; the monitor thread makes it official
                pass

    def _handle_msg(self, msg: Tuple) -> None:
        now = self._clock()
        with self._lock:
            self._last_pong = now
        kind = msg[0]
        if kind == "result":
            res = msg[1]
            first_after_restart = False
            with self._lock:
                t_sub = self._inflight.pop(res.rid, None)
                if t_sub is None:
                    # already answered Unavailable at crash detection —
                    # a late duplicate would break request conservation
                    return
                self._results.append(res)
                if (isinstance(res, Served)
                        and self._awaiting_first_served is not None):
                    self._restart_to_serve_ms.append(
                        (now - self._awaiting_first_served) * 1e3)
                    self._awaiting_first_served = None
                    first_after_restart = True
                outage_trace = self._outage_trace
                restarts = self._restarts
            # supervisor-side trace: the worker's stage spans verbatim
            # (their sum equals res.latency_ms exactly — the partition
            # crossed the boundary intact); the socket/queue overhead
            # this side observed on top is a boundary mark, outside the
            # partition by design
            spans = getattr(res, "spans", None)
            stages = ({k[:-3]: v for k, v in spans.items()} if spans
                      else {"queue_wait": res.latency_ms})
            boundary_ms = max(0.0, (now - t_sub) * 1e3 - res.latency_ms)
            self._e2e_ms.observe((now - t_sub) * 1e3)
            self.traces.event(res.rid, "boundary", t=now,
                              dur_ms=boundary_ms)
            self.traces.finish(res.rid, res.status, res.latency_ms, now,
                               stages, boundary_ms=boundary_ms,
                               restarts=restarts)
            if first_after_restart and outage_trace is not None:
                # the restart-crossing evidence: the outage's first
                # stranded trace now carries the full arc
                self.traces.append_event(outage_trace, "worker_restarted",
                                         t=now, restarts=restarts)
                self.traces.append_event(outage_trace,
                                         "served_after_restart", t=now,
                                         dur_ms=res.latency_ms,
                                         served_rid=res.rid)
                self.traces.annotate(outage_trace, restart_crossed=True,
                                     restarts_at_serve=restarts)
                with self._lock:
                    self._outage_trace = None
        elif kind == "pong":
            # liveness (handled above) + the piggybacked federation doc
            if len(msg) > 2 and msg[2]:
                with self._lock:
                    self._fed_latest = msg[2]
        elif kind == "stats_reply":
            with self._lock:
                self._worker_stats = msg[1]
            self._stats_event.set()
        elif kind == "worker_error":
            logger.error("serving worker raised:\n%s", msg[1])
            if self._recorder:
                self._recorder.note_event("serve_worker_error",
                                          traceback=msg[1])
        # "bye" carries nothing beyond liveness

    def _monitor_loop(self) -> None:
        last_ping = 0.0
        while not self._closing:
            worker = self._worker
            if not self._alive or worker is None:
                time.sleep(0.01)
                continue
            now = self._clock()
            if now - last_ping >= self.cfg.heartbeat_s:
                self._send_q.put(("ping", now))
                last_ping = now
            try:
                while worker.conn.poll(self.cfg.heartbeat_s / 4):
                    self._handle_msg(worker.conn.recv())
            except (EOFError, OSError):
                self._on_worker_down("crash")
                continue
            if not worker.alive():
                self._on_worker_down("crash")
            elif self._clock() - self._last_pong > self.cfg.deadline_s:
                worker.kill()  # SIGKILL: a wedged worker won't cooperate
                self._on_worker_down("hang")

    # ------------------------------------------------------ crash path

    def _on_worker_down(self, reason: str) -> None:
        now = self._clock()
        down_reason = f"worker_{reason}"
        with self._lock:
            worker, self._worker = self._worker, None
            self._alive = False
            self._down_since = now
            self._down_reason = down_reason
            self._counts[reason] += 1
            stranded = list(self._inflight.items())
            self._inflight.clear()
            restarts = self._restarts
            for rid, t_sub in stranded:
                self._counts["unavailable"] += 1
                self._results.append(Unavailable(
                    rid=rid, latency_ms=0.0, reason=down_reason,
                    outage_s=0.0, restarts=restarts,
                    spans={"queue_wait_ms":
                           max(0.0, (now - t_sub) * 1e3)}))
            # absorb the dead incarnation's final federation document:
            # its counters and sketch buckets keep merging under the
            # reborn worker's, so the scrape never forgets an outage
            if self._fed_latest:
                self._fed_archive = mplane.merge_registry_docs(
                    [d for d in (self._fed_archive, self._fed_latest)
                     if d])
                self._fed_latest = None
        # stranded traces finish Unavailable with the wait they actually
        # spent (an outage mark annotates the death); the newest one
        # becomes the outage trace the restart-crossing marks land on —
        # later refusals during the outage keep moving the pointer
        # forward so the bounded ring can never evict it first
        last_tid = None
        for rid, t_sub in stranded:
            wait_ms = max(0.0, (now - t_sub) * 1e3)
            self.traces.event(rid, "outage", t=now, reason=down_reason)
            tr = self.traces.finish(rid, "unavailable", wait_ms, now,
                                    {"queue_wait": wait_ms},
                                    reason=down_reason, stranded=True,
                                    restarts=restarts)
            if tr is not None:
                last_tid = tr["trace_id"]
        if last_tid is not None:
            with self._lock:
                self._outage_trace = last_tid
        # purge queued sends: the reborn worker must not receive
        # requests whose rids were just answered Unavailable
        try:
            while True:
                self._send_q.get_nowait()
        except queue.Empty:
            pass
        pid = worker.pid if worker else -1
        if worker:
            worker.kill()
            worker.close()
        logger.warning("serving worker pid=%s down (%s); %d in-flight "
                       "answered Unavailable", pid, reason, len(stranded))
        obs.counter_inc("serve_worker_crash")
        obs.record_event("serve_worker_crash", reason=reason, pid=pid,
                         stranded=len(stranded), restarts=self._restarts)
        if self._recorder:
            # the black box the child can no longer write: the
            # supervisor dumps on its behalf
            self._recorder.note_event("serve_worker_crash", reason=reason,
                                      pid=pid, stranded=len(stranded),
                                      restarts=self._restarts)
            if self._worker_stats:
                self._recorder.note_stats(self._worker_stats)
            for tr in self.traces.drain_new():
                self._recorder.note_trace(tr)
            self._recorder.dump("serve_worker_crash", reason=reason,
                                pid=pid)
        self._restart()

    def _restart(self) -> None:
        """Kill-and-restart under the budget, jittered exponential
        backoff (the ``runtime.retry`` idiom: ``base * 2^k``, capped,
        x(0.5 + rand) jitter so a fleet of supervisors never thunders)."""
        attempt = 0
        while not self._closing:
            if self._restarts >= self.cfg.max_restarts:
                with self._lock:
                    self.restart_budget_exhausted = True
                    self._down_reason = "restart_budget_exhausted"
                logger.error("serving worker restart budget (%d) "
                             "exhausted; serving stays Unavailable",
                             self.cfg.max_restarts)
                obs.record_event("serve_worker_budget_exhausted",
                                 restarts=self._restarts)
                return
            delay = min(self.cfg.backoff_base_s * (2.0 ** attempt),
                        self.cfg.backoff_max_s)
            delay *= 0.5 + random.random()
            time.sleep(delay)
            attempt += 1
            with self._lock:
                self._restarts += 1
            try:
                # spawn outside the lock (blocks on fork + accept +
                # worker warmup); the reference swap itself is atomic
                self._worker = self._spawn_worker()  # thread-local-ok: reference swap by the monitor thread, the sole writer while supervision runs
            except Exception as e:  # noqa: BLE001 - spawn/ready failure
                # burns budget and backs off further, never raises into
                # the trainer
                logger.warning("serving worker restart %d failed: %s",
                               self._restarts, e)
                obs.record_retry(f"serve_worker_restart:{e}")
                continue
            self._on_worker_up()
            obs.counter_inc("serve_worker_restart")
            obs.record_event("serve_worker_restart",
                             restarts=self._restarts,
                             pid=self._worker.pid)
            if self._recorder:
                self._recorder.note_event("serve_worker_restart",
                                          restarts=self._restarts,
                                          pid=self._worker.pid)
            return

    # ------------------------------------- the ServingRuntime surface

    def install_snapshot(self, state, streaming_state=None, *,
                         version: int, train_step: int,
                         published_t: Optional[float] = None,
                         now: Optional[float] = None) -> None:
        """Publish one snapshot INTO SHARED MEMORY (seqlock write, no
        socket, no lock shared with the worker): a crashed, hung, or
        restarting worker can never block the trainer here. A reborn
        worker reads the latest snapshot on attach, so publishing
        during an outage is not just safe but the recovery path."""
        if version <= self._last_version:
            raise ValueError(
                f"snapshot version must be monotonic: got {version}, "
                f"published {self._last_version}")
        t0 = self._clock()
        payload = snapshot_payload(state, streaming_state)
        created = None
        with self._lock:
            # lazy region creation is a check-then-act; _spawn_spec
            # reads _shm from the monitor thread on every restart
            if self._shm is None:
                self._shm = shm_mod.SnapshotShm.create(
                    shm_mod.slack_capacity(len(payload)))
                created = self._shm.name
        if created is not None:
            self._send_q.put(("shm", created))
        wall = time.monotonic() if published_t is None else published_t
        self._shm.publish_bytes(payload, version=int(version),
                                train_step=int(train_step), wall_ts=wall)
        self._publish_ms.observe((self._clock() - t0) * 1e3)
        with self._lock:
            self._last_version = int(version)
            self._last_train_step = int(train_step)

    def note_train_step(self, step: int) -> None:
        with self._lock:
            self._last_train_step = int(step)
        self._send_q.put(("train_step", int(step)))

    def set_freshness_slo(self, steps: Optional[float] = None,
                          seconds: Optional[float] = None) -> None:
        with self._lock:
            self._slo = (steps, seconds)
        self._send_q.put(("slo", steps, seconds))

    def warmup(self, template=None) -> None:
        """No-op: the worker warms its own ladder from its factory's
        template before reporting ready (``_warm`` flips then)."""

    @property
    def queued_samples(self) -> int:
        """In-flight requests (submitted, not yet answered) — the
        drain condition for :class:`~.serving.RealtimeDriver`."""
        with self._lock:
            return len(self._inflight)

    def submit(self, req) -> Optional[ServeResult]:
        now = self._clock()
        with self._lock:
            rid = self._next_rid
            self._next_rid += 1
            alive = self._alive
            restarts = self._restarts
            if alive:
                self._inflight[rid] = now
        # mint (or adopt) the trace here, at the FRONT DOOR: the worker
        # re-parents under this context, so one trace id survives the
        # pickle boundary and any worker rebirth in between
        ctx = self.traces.begin(rid, now,
                                ctx=getattr(req, "trace", None),
                                priority=getattr(req, "priority", 0),
                                incarnation=restarts)
        if not alive:
            with self._lock:
                self._counts["unavailable"] += 1
                outage = now - self._down_since
                reason = self._down_reason
            tr = self.traces.finish(rid, "unavailable", 0.0, now,
                                    {"queue_wait": 0.0}, reason=reason,
                                    outage_s=outage, restarts=restarts)
            if tr is not None:
                # keep pointing at the NEWEST outage trace: every
                # refusal is retained ("outcome"), so under a long
                # outage the oldest ones are exactly what the bounded
                # ring evicts first — the newest is the one guaranteed
                # to still be retained when the restart marks land
                with self._lock:
                    self._outage_trace = tr["trace_id"]
            return Unavailable(rid=rid, latency_ms=0.0, reason=reason,
                               outage_s=outage, restarts=restarts,
                               spans={"queue_wait_ms": 0.0})
        req.rid = rid
        req.trace = ctx
        # the rid doubles as the GLOBAL stream ordinal die@/hang@ key on
        self._send_q.put(("request", rid, rid, req))
        return None

    def poll(self, now=None) -> List[ServeResult]:
        out: List[ServeResult] = []
        with self._lock:
            while self._results:
                out.append(self._results.popleft())
        return out

    def flush(self) -> List[ServeResult]:
        """Ask the worker to flush sub-rung batches, then return what
        has arrived (socket round-trip: poll again for stragglers)."""
        self._send_q.put(("flush",))
        time.sleep(self.cfg.heartbeat_s)
        return self.poll()

    def stats(self, sync: bool = True,
              timeout_s: float = 5.0) -> Dict[str, Any]:
        """The worker's ``ServingRuntime.stats()`` (fresh over the
        socket when ``sync`` and the worker is alive; otherwise the
        last received) plus the ``"supervisor"`` block: restarts,
        outage bookkeeping, shm publish latency, restart-to-first-served
        — the isolation-layer stats the bench gates."""
        if sync and self._alive:
            self._stats_event.clear()
            self._send_q.put(("stats",))
            self._stats_event.wait(timeout_s)
        out = dict(self._worker_stats)
        with self._lock:
            out["supervisor"] = {
                "worker_alive": self._alive,
                "restarts": self._restarts,
                "crashes": self._counts["crash"],
                "hangs": self._counts["hang"],
                "unavailable": self._counts["unavailable"],
                "restart_budget_exhausted": self.restart_budget_exhausted,
                "outage_s": (0.0 if self._alive
                             else self._clock() - self._down_since),
                "shm_region_bytes": self._shm.size if self._shm else 0,
                "shm_publish_p95_ms": (self._publish_ms.quantile(0.95)
                                       if self._publish_ms.count else None),
                "restart_to_first_served_ms": (
                    self._restart_to_serve_ms[-1]
                    if self._restart_to_serve_ms else None),
                "e2e_p99_ms": (self._e2e_ms.quantile(0.99)
                               if self._e2e_ms.count else None),
            }
        # the supervisor's OWN trace ring (end-to-end spans, boundary
        # marks) — distinct from the worker's in-process ring above
        out["supervisor"]["trace"] = self.traces.stats()
        out["supervisor"]["p99_exemplars"] = self.traces.exemplars(5)
        return out

    # ---------------------------------------------------------- teardown

    def close(self) -> None:
        """Orderly shutdown: ask the worker to exit, then escalate;
        tear down the socket and UNLINK the shm region (the supervisor
        owns it — last one out)."""
        # stop supervision FIRST: the monitor must not read the orderly
        # exit below as a crash (and burn a restart + a black box on it)
        self._closing = True  # thread-local-ok: atomic stop flag, sole writer; the loops poll it
        if self._monitor is not None:
            self._monitor.join(timeout=5)
        if self._sender is not None:
            self._sender.join(timeout=5)
        worker = self._worker
        if worker is not None and self._alive:
            try:
                worker.conn.send(("shutdown",))
            except Exception:  # noqa: BLE001 - dying anyway
                pass
            worker.process.join(timeout=5)
        if worker is not None:
            worker.kill()
            worker.close()
        self._worker = None  # thread-local-ok: monitor/sender joined above, no other thread of control remains
        self._alive = False  # thread-local-ok: monitor/sender joined above, no other thread of control remains
        try:
            self._listener.close()
        except Exception:  # noqa: BLE001 - already closed
            pass
        if self._shm is not None:
            self._shm.unlink()
            self._shm = None  # thread-local-ok: monitor/sender joined above, no other thread of control remains

"""Sparse embedding optimizers over width-grouped 2-D table slabs.

The reference applies ``tf.IndexedSlices`` gradients through Keras optimizers'
sparse paths (``optimizer.apply_gradients`` after
``dist_model_parallel.py:526-567``), touching only the looked-up rows. optax
has no IndexedSlices, so dense-gradient training would read+write every table
row each step — the difference between HBM-bound O(touched rows) and
O(all rows). These optimizers reproduce the sparse behavior on the
*physical* slab rows used by
:class:`~distributed_embeddings_tpu.parallel.DistributedEmbedding` — for
narrow widths those are lane-packed ``[phys_rows, 128]`` tiles and the
caller hands in physical row ids plus lane-expanded update rows
(``ops/packed_slab.py``; lane-disjoint expansion keeps per-logical-row
semantics, including Adagrad's dedup, exact).

Performance notes (TPU): updates are native 2-D row scatters
(``slab.at[row_ids].add(values)``) — the one scatter form XLA's TPU backend
lowers efficiently. Flat 1-D windowed/element scatters lower to a serialized
path measured ~30x slower end-to-end; hence the width-grouped 2-D layout.
Invalid/padded ids equal the slab row capacity, land out of bounds, and are
dropped (``mode='drop'``) — the static-shape analogue of the reference's
dynamic ``num_unique``.

:class:`SparseAdagrad`, :class:`SparseMomentum` and :class:`SparseAdam` dedup
duplicate ids first (sort + segment-sum — the CUB sort/unique of the
reference backward, ``.cu:499-515``) because their updates read-modify-write
per-row state; :class:`SparseSGD` scatter-adds duplicates directly. Every
optimizer *declares* which regime it needs via the class attribute
``needs_dedup`` — the statically-enforced dedup pass budget
(:mod:`..analysis.hlo_census`, ``tools/hlo_audit.py --strict``) requires a
compiled step's ``detpu/dedup`` phase to hold ZERO row-op passes when the
optimizer says ``needs_dedup=False``. ``DETPU_SGD_DEDUP=1`` (read at step
build time) forces the dedup pass back into the SGD path for A/B
comparison: the trajectories are mathematically identical (SGD is linear in
the gradient), so the knob exists purely to measure what the skipped pass
would cost and to regression-test the equivalence. Numerics
match ``optax.sgd`` / ``optax.adagrad`` (initial accumulator 0.1, eps 1e-7) /
``optax.sgd(momentum=...)`` / ``optax.adam`` so the dense data-parallel side
can use optax and both families see the same optimizer semantics.

**Lazy moment semantics** (momentum/Adam): only the rows touched by a step
update their momentum/moment state; untouched rows' state neither decays nor
produces an update. This is what the reference gets from Keras optimizers'
sparse ``IndexedSlices`` path (``dist_model_parallel.py:526-567`` +
``optimizer.apply_gradients``) and what every production embedding trainer
uses — decaying millions of untouched rows per step would turn an O(touched)
update into an O(all rows) one. Consequence: trajectories equal dense optax
exactly when every row is touched every step, and diverge (lazily) when not.
Adam's bias correction uses the *global* step count, not a per-row count —
the LazyAdam convention.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from ..ops.packed_slab import expand_lane_mask, pack_factor
from ..ops.sparse_grad import dedup_sparse_grad
from ..utils import envvars

SGD_DEDUP_ENV = "DETPU_SGD_DEDUP"


def sgd_dedup_forced() -> bool:
    """Whether ``DETPU_SGD_DEDUP=1`` asks the linear (SGD) paths to run the
    dedup pass they would otherwise skip. Read at step-BUILD time (like
    ``with_metrics``): flipping the env after a step is compiled changes
    nothing until the step is rebuilt."""
    return envvars.enabled(SGD_DEDUP_ENV)


# The explicit-sort scatter wins only in a WINDOW of stream lengths —
# XLA's TPU scatter lowering changes algorithm with stream length, slab
# size and dtype, and measurement (docs/perf_tpu.md round-4 table) beats
# modeling here:
#   * 1.7M rows:  sorted wins big (5.4 GB fp32: 38.5 -> ~18 ms;
#     10.2 GB bf16: 139 -> 73 ms);
#   * >= 2.9M rows (tiny zoo, DCNv2 ragged): the sort+permute chain costs
#     MORE than the internal lowering (+31 / +16 ms end-to-end);
#   * small streams into huge slabs (65k rows / 10.2 GB bf16, the
#     Criteo-1TB shard): sorted is 3x WORSE (54 vs 19 ms) — the unsorted
#     lowering is slab-copy-bound there and the sorted one is worse still.
# r5 re-test: ISOLATED scan-chained probes at the two loss shapes showed
# sorted winning (86.4 -> 70.2 ms / 154.0 -> 130.7 ms), but lifting the 2M
# cap regressed the END-TO-END benches (tiny-zoo bf16 Adagrad 167 -> 195
# ms; multihot unchanged) — in the full step the scatter fuses/schedules
# differently than in isolation. The window is an end-to-end fact; always
# re-validate candidate changes on the bench variants, not probes alone.
_SORT_STREAM_MIN = 256_000
_SORT_STREAM_MAX = 2_000_000


def _sorted_scatter_add(slab: jax.Array, ids: jax.Array,
                        vals: jax.Array) -> jax.Array:
    """``slab.at[ids].add(vals)``, sorting the id keys first when the stream
    length falls in the measured win window (see above): keys sort at
    3.4 ns/key, the value permute rides the scatter as a fused gather
    operand, and the scatter declares sortedness."""
    n = ids.shape[0]
    if not (_SORT_STREAM_MIN <= n <= _SORT_STREAM_MAX):
        return slab.at[ids].add(vals, mode="drop")
    sorted_ids, perm = lax.sort_key_val(
        ids, jnp.arange(n, dtype=jnp.int32))
    upd = jnp.take(vals, perm, axis=0)  # fuses into the scatter
    return slab.at[sorted_ids].add(upd, mode="drop",
                                   indices_are_sorted=True)


class SparseSGD:
    """Plain SGD on slab rows; duplicate ids accumulate via scatter-add.

    ``needs_dedup=False``: the update is linear in the gradient, so
    duplicate ids are scatter-add-safe (``ops/sparse_grad.py``) and the
    sort + segment-sum dedup pass is skipped entirely — the first
    statically-verified pass cut of ROADMAP 3(a); ``tools/hlo_audit.py
    --strict`` pins the compiled dedup phase to zero row ops on this path.
    ``DETPU_SGD_DEDUP=1`` forces the pass back in for A/B (mathematically
    identical; floating-point-identical too whenever the per-row sums are
    exact, which the equivalence test engineers)."""

    needs_dedup = False
    #: streaming moment hygiene: SGD carries no slab state to reset
    fresh_row_fill = 0.0

    def init(self, params):
        return jax.tree.map(lambda _: (), params)

    def apply_rows(self, slab: jax.Array, state, ids: jax.Array,
                   vals: jax.Array, lr):
        """``slab[ids] -= lr * vals``; ids >= slab rows are dropped."""
        if sgd_dedup_forced():
            # A/B escape hatch: pre-sum duplicate rows exactly like the
            # stateful optimizers do, then scatter the unique rows
            uids, uvals = dedup_sparse_grad(ids, vals,
                                            pad_id=slab.shape[0],
                                            max_unique=slab.shape[0] + 1)
            return slab.at[uids].add(
                (-lr * uvals).astype(slab.dtype), mode="drop",
                indices_are_sorted=True), state
        slab = _sorted_scatter_add(slab, ids,
                                   -lr * vals.astype(slab.dtype))
        return slab, state


class SparseAdagrad:
    """Adagrad with slab-shaped accumulators; optax.adagrad numerics
    (accumulator init 0.1, ``param -= lr * g * rsqrt(acc_new + eps)``).

    Two execution regimes, chosen per call by a measured cost model:

    ``needs_dedup=True``: the accumulator update is nonlinear in the
    gradient, so duplicate rows must be summed before the rsqrt (the
    sparse regime's sort + segment-sum pass, budgeted by the HLO census).

    * **sparse** (stream << slab rows): sort-dedup the id stream, then
      per-unique-row accumulator read-modify-write — 4-5 random row ops on
      the stream at the TPU's ~10-15 ns/row descriptor floor;
    * **dense-apply** (stream > slab rows / ``dense_apply_ratio``): ONE
      scatter-add sums the stream into a zero gradient slab, then the
      Adagrad transition runs elementwise over the whole slab at streaming
      HBM rates (~0.6 ns/row) — numerically identical, because an untouched
      row sees ``g = 0``: ``acc + 0*0 == acc`` and ``param - lr*0*rsqrt ==
      param``. This is what collapsed the tiny-zoo w=16 group's 2.9M-id
      stream cost (VERDICT r3 Weak #3): 4 full-stream row ops became one
      scatter + slab-wide elementwise passes.
    """

    needs_dedup = True

    def __init__(self, initial_accumulator_value: float = 0.1,
                 eps: float = 1e-7, dense_apply_ratio: float = 6.0):
        self.initial_accumulator_value = initial_accumulator_value
        # streaming moment hygiene (parallel/streaming.py commit): a
        # freshly admitted slot's accumulator resets to the same value a
        # fresh table init would give it
        self.fresh_row_fill = initial_accumulator_value
        self.eps = eps
        # dense-apply wins when stream * ratio > slab rows: the sparse path
        # pays ~4.5 random row ops/stream row at 10-15 ns, the dense path
        # ~5 slab-wide streams at ~0.6 ns/row plus the one scatter both pay.
        # None disables the dense path (e.g. when HBM can't hold one extra
        # slab-sized transient).
        self.dense_apply_ratio = dense_apply_ratio

    def init(self, params):
        return jax.tree.map(
            lambda p: jnp.full_like(p, self.initial_accumulator_value), params)

    def apply_rows(self, slab: jax.Array, accum: jax.Array, ids: jax.Array,
                   vals: jax.Array, lr):
        # moments accumulate in the ACCUMULATOR dtype: with bf16 tables +
        # fp32 accumulators, g*g must square in fp32 or the carefully
        # preserved fp32 state would hold bf16-precision statistics
        vals = vals.astype(accum.dtype)
        if (self.dense_apply_ratio is not None
                and vals.shape[0] * self.dense_apply_ratio > slab.shape[0]):
            # dense-apply regime: one scatter-sum, then elementwise Adagrad
            # over the slab (exact — untouched rows see g=0, a no-op)
            g = _sorted_scatter_add(jnp.zeros(slab.shape, accum.dtype),
                                    ids, vals)
            new_acc = accum + g * g
            # update computes in the accumulator dtype but must not promote
            # the slab (bf16 tables + fp32 accumulators would silently turn
            # fp32 here where the sparse regime's scatter keeps bf16)
            slab = slab - (lr * g * lax.rsqrt(new_acc + self.eps)
                           ).astype(slab.dtype)
            return slab, new_acc
        # nonlinear in g: must sum duplicate rows before the rsqrt.
        # vocab bound: distinct physical rows <= slab rows + sentinel, so
        # the unique buffers (and the accumulator ops on them) shrink to
        # min(stream, rows+1) — a large win for small-vocab width groups
        uids, uvals = dedup_sparse_grad(ids, vals, pad_id=slab.shape[0],
                                        max_unique=slab.shape[0] + 1)
        acc_rows = jnp.take(accum, uids, axis=0, mode="clip")
        new_acc = acc_rows + uvals * uvals
        # uids are sorted but NOT formally unique: the dedup tail repeats the
        # pad sentinel (slab row capacity). unique_indices=True would violate
        # XLA's contract (implementation-defined); sorted + mode='drop' keeps
        # the fast path and drops every sentinel copy out of bounds.
        accum = accum.at[uids].set(new_acc, mode="drop",
                                   indices_are_sorted=True)
        # optax scale_by_rss semantics: g * rsqrt(acc_new + eps); computed
        # in the accumulator dtype, cast to the slab's (mixed bf16/fp32)
        update = (lr * uvals * lax.rsqrt(new_acc + self.eps)
                  ).astype(slab.dtype)
        slab = slab.at[uids].add(-update, mode="drop",
                                 indices_are_sorted=True)
        return slab, accum


def _dedup_with_mask(ids, vals, mask, lane_width, pad_id):
    """Dedup vals (and, when given, a compact ``[n, p]`` lane touch-mask,
    ``ops/packed_slab.py:lane_one_hot``) by id in ONE sort + segment-sum:
    the mask rides as ``p`` extra columns (p/128 of the value payload) and
    is expanded to lane placement only after dedup. Returns
    ``(uids, uvals, touched)`` with ``touched=None`` when no mask.

    Why a mask: stateful-moment updates are nonzero wherever *state* is
    nonzero, so after duplicate physical rows are summed, lanes belonging to
    packed neighbour logical rows (``ops/packed_slab.py``) must be masked
    out of the state transition — a zero gradient cannot encode "untouched"
    (a touched row may legitimately have zero gradient)."""
    if mask is None:
        if lane_width is not None and pack_factor(lane_width) > 1:
            # without the mask, summed duplicate physical rows would count
            # packed *neighbour* logical rows as touched and corrupt their
            # momentum/moment state (ADVICE r3) — refuse rather than corrupt
            raise ValueError(
                f"lane_width={lane_width} is a packed width "
                f"(p={pack_factor(lane_width)}) but no lane touch-mask was "
                "given; build one with ops.packed_slab.lane_one_hot(ids, "
                "lane_width) or omit lane_width only for widths >= 128")
        uids, uvals = dedup_sparse_grad(ids, vals, pad_id=pad_id,
                                        max_unique=pad_id + 1)
        return uids, uvals, None
    if lane_width is None:
        raise ValueError(
            "mask requires lane_width (the logical row width the [n, p] "
            "lane mask expands to; 128//p is wrong for odd widths)")
    both = jnp.concatenate([vals, mask.astype(vals.dtype)], axis=1)
    uids, uboth = dedup_sparse_grad(ids, both, pad_id=pad_id,
                                    max_unique=pad_id + 1)
    w = vals.shape[1]
    touched = expand_lane_mask(uboth[:, w:], lane_width, phys_w=w)
    return uids, uboth[:, :w], touched


class SparseMomentum:
    """Heavy-ball SGD with lazy row-wise momentum; ``optax.sgd(momentum=m)``
    (``optax.trace``) numerics: ``trace = g + decay * trace``,
    ``param -= lr * trace`` (``nesterov`` applies the optax formula
    ``g + decay * trace_new``). See the module docstring for the lazy
    semantics of untouched rows."""

    needs_dedup = True
    needs_touch_mask = True
    #: streaming moment hygiene: momentum traces init (and reset) to zero
    fresh_row_fill = 0.0

    def __init__(self, momentum: float = 0.9, nesterov: bool = False):
        self.momentum = momentum
        self.nesterov = nesterov

    def init(self, params):
        return jax.tree.map(jnp.zeros_like, params)

    def apply_rows(self, slab: jax.Array, trace: jax.Array, ids: jax.Array,
                   vals: jax.Array, lr, mask=None, lane_width=None):
        vals = vals.astype(trace.dtype)  # momentum state sets the precision
        # read-modify-write of per-row trace: duplicates must sum first
        uids, uvals, touched = _dedup_with_mask(
            ids, vals, mask, lane_width, pad_id=slab.shape[0])
        t_rows = jnp.take(trace, uids, axis=0, mode="clip")
        t_new = uvals + self.momentum * t_rows
        if touched is not None:  # packed neighbours keep their state
            t_new = jnp.where(touched, t_new, t_rows)
        trace = trace.at[uids].set(t_new, mode="drop",
                                   indices_are_sorted=True)
        step = (uvals + self.momentum * t_new) if self.nesterov else t_new
        if touched is not None:
            step = jnp.where(touched, step, 0.0)
        slab = slab.at[uids].add((-lr * step).astype(slab.dtype),
                                 mode="drop", indices_are_sorted=True)
        return slab, trace


class SparseAdam:
    """Adam with lazy row-wise moments; ``optax.adam`` numerics
    (``scale_by_adam``: ``mu = b1*mu + (1-b1)*g``, ``nu = b2*nu +
    (1-b2)*g^2``, hat-corrected by the optimizer-global step count — the
    LazyAdam convention, see module docstring).

    State per width slab: ``(mu, nu, count)`` where ``count`` rides as a
    ``[..., 1, 1]`` array so it shards/squeezes uniformly with the slabs."""

    needs_dedup = True
    needs_touch_mask = True
    #: streaming moment hygiene: mu/nu init (and reset) to zero; the
    #: non-slab step count is never touched (shape-matched in commit)
    fresh_row_fill = 0.0

    def __init__(self, b1: float = 0.9, b2: float = 0.999,
                 eps: float = 1e-8, eps_root: float = 0.0):
        self.b1, self.b2 = b1, b2
        self.eps, self.eps_root = eps, eps_root

    def init(self, params):
        def one(p):
            cnt_shape = (p.shape[0], 1, 1) if p.ndim == 3 else (1, 1)
            return (jnp.zeros_like(p), jnp.zeros_like(p),
                    jnp.zeros(cnt_shape, jnp.float32))
        return jax.tree.map(one, params)

    def apply_rows(self, slab: jax.Array, state, ids: jax.Array,
                   vals: jax.Array, lr, mask=None, lane_width=None):
        mu, nu, count = state
        vals = vals.astype(mu.dtype)  # moments set the precision
        uids, uvals, touched = _dedup_with_mask(
            ids, vals, mask, lane_width, pad_id=slab.shape[0])
        count = count + 1.0
        t = count.reshape(())  # scalar step for bias correction
        mu_rows = jnp.take(mu, uids, axis=0, mode="clip")
        nu_rows = jnp.take(nu, uids, axis=0, mode="clip")
        mu_new = self.b1 * mu_rows + (1.0 - self.b1) * uvals
        nu_new = self.b2 * nu_rows + (1.0 - self.b2) * uvals * uvals
        if touched is not None:  # packed neighbours keep their state
            mu_new = jnp.where(touched, mu_new, mu_rows)
            nu_new = jnp.where(touched, nu_new, nu_rows)
        mu = mu.at[uids].set(mu_new, mode="drop", indices_are_sorted=True)
        nu = nu.at[uids].set(nu_new, mode="drop", indices_are_sorted=True)
        mu_hat = mu_new / (1.0 - self.b1 ** t)
        nu_hat = nu_new / (1.0 - self.b2 ** t)
        update = lr * mu_hat / (jnp.sqrt(nu_hat + self.eps_root) + self.eps)
        if touched is not None:
            update = jnp.where(touched, update, 0.0)
        slab = slab.at[uids].add(-update.astype(slab.dtype), mode="drop",
                                 indices_are_sorted=True)
        return slab, (mu, nu, count)

"""Sparse embedding optimizers over width-grouped 2-D table slabs.

The reference applies ``tf.IndexedSlices`` gradients through Keras optimizers'
sparse paths (``optimizer.apply_gradients`` after
``dist_model_parallel.py:526-567``), touching only the looked-up rows. optax
has no IndexedSlices, so dense-gradient training would read+write every table
row each step — the difference between HBM-bound O(touched rows) and
O(all rows). These optimizers reproduce the sparse behavior on the
*physical* slab rows used by
:class:`~distributed_embeddings_tpu.parallel.DistributedEmbedding` — for
narrow widths those are lane-packed ``[phys_rows, 128]`` tiles and the
caller hands in physical row ids plus lane-expanded update rows
(``ops/packed_slab.py``; lane-disjoint expansion keeps per-logical-row
semantics, including Adagrad's dedup, exact).

Performance notes (TPU): updates are native 2-D row scatters
(``slab.at[row_ids].add(values)``) — the one scatter form XLA's TPU backend
lowers efficiently. Flat 1-D windowed/element scatters lower to a serialized
path measured ~30x slower end-to-end; hence the width-grouped 2-D layout.
Invalid/padded ids equal the slab row capacity, land out of bounds, and are
dropped (``mode='drop'``) — the static-shape analogue of the reference's
dynamic ``num_unique``.

:class:`SparseAdagrad` dedups duplicate ids first (sort + segment-sum — the
CUB sort/unique of the reference backward, ``.cu:499-515``) because its update
is nonlinear in the gradient; :class:`SparseSGD` scatter-adds duplicates
directly. Numerics match ``optax.sgd`` / ``optax.adagrad`` (initial
accumulator 0.1, eps 1e-7) so the dense data-parallel side can use optax and
both families see the same optimizer semantics.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from ..ops.sparse_grad import dedup_sparse_grad


class SparseSGD:
    """Plain SGD on slab rows; duplicate ids accumulate via scatter-add."""

    def init(self, params):
        return jax.tree.map(lambda _: (), params)

    def apply_rows(self, slab: jax.Array, state, ids: jax.Array,
                   vals: jax.Array, lr):
        """``slab[ids] -= lr * vals``; ids >= slab rows are dropped."""
        slab = slab.at[ids].add(-lr * vals.astype(slab.dtype), mode="drop")
        return slab, state


class SparseAdagrad:
    """Adagrad with slab-shaped accumulators; optax.adagrad numerics
    (accumulator init 0.1, ``param -= lr * g * rsqrt(acc_new + eps)``)."""

    def __init__(self, initial_accumulator_value: float = 0.1,
                 eps: float = 1e-7):
        self.initial_accumulator_value = initial_accumulator_value
        self.eps = eps

    def init(self, params):
        return jax.tree.map(
            lambda p: jnp.full_like(p, self.initial_accumulator_value), params)

    def apply_rows(self, slab: jax.Array, accum: jax.Array, ids: jax.Array,
                   vals: jax.Array, lr):
        vals = vals.astype(slab.dtype)
        # nonlinear in g: must sum duplicate rows before the rsqrt
        uids, uvals = dedup_sparse_grad(ids, vals, pad_id=slab.shape[0])
        acc_rows = jnp.take(accum, uids, axis=0, mode="clip")
        new_acc = acc_rows + uvals * uvals
        # uids are sorted but NOT formally unique: the dedup tail repeats the
        # pad sentinel (slab row capacity). unique_indices=True would violate
        # XLA's contract (implementation-defined); sorted + mode='drop' keeps
        # the fast path and drops every sentinel copy out of bounds.
        accum = accum.at[uids].set(new_acc, mode="drop",
                                   indices_are_sorted=True)
        # optax scale_by_rss semantics: g * rsqrt(acc_new + eps)
        update = lr * uvals * lax.rsqrt(new_acc + self.eps)
        slab = slab.at[uids].add(-update, mode="drop",
                                 indices_are_sorted=True)
        return slab, accum

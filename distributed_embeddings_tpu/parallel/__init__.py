"""Hybrid model/data parallelism over a TPU mesh.

TPU-native re-design of ``distributed_embeddings/python/layers/dist_model_parallel.py``:
the placement planner is pure Python (carried over algorithmically), while the
runtime communication (Horovod all-to-all/allreduce in the reference) becomes
``jax.lax`` collectives inside ``jax.shard_map`` over a named mesh axis.
"""

from . import bootstrap
from .strategy import DistEmbeddingStrategy
from .dist_embedding import DistributedEmbedding, MpInputs
from .grads import (
    broadcast_variables,
    hybrid_gradients,
    hybrid_value_and_grad,
    resolve_dp_gradient,
    split_mp_dp,
)
from .optimizers import SparseAdagrad, SparseAdam, SparseMomentum, SparseSGD
from .sparse_optax import (
    SparseRows,
    apply_sparse_updates,
    sparse_grad_metrics,
    sparse_rows_adagrad,
    sparse_rows_adam,
    sparse_rows_momentum,
    sparse_rows_sgd,
    sparse_value_and_grad,
    unique_ids_static,
)
from .online import (
    OnlineConfig,
    OnlineResult,
    OnlineRuntime,
    Snapshot,
    SnapshotPublisher,
    online_sidecar_path,
)
from .resilient import (
    PREEMPT_EXIT_CODE,
    ResilientResult,
    quarantine_ledger_path,
    resume_sentinel_path,
    run_resilient,
)
from .schedule import (
    PhaseDecl,
    ScheduleError,
    StepSchedule,
    default_schedule,
    pipelined_schedule,
    resolve_schedule,
    streaming_schedule,
)
from .serving import (
    Expired,
    Failed,
    Overloaded,
    RealtimeDriver,
    Request,
    ServeConfig,
    Served,
    ServingRuntime,
    Unavailable,
)
from .streaming import (
    StreamingConfig,
    init_streaming,
)
from .supervisor import (
    SuperviseConfig,
    Supervisor,
)
from .trainer import (
    HybridTrainState,
    clone_pytree,
    init_hybrid_state,
    make_hybrid_eval_step,
    make_hybrid_train_loop,
    make_hybrid_train_step,
)

"""Self-healing training driver: preemption-safe resume, non-finite-loss
escalation, and invalid-input enforcement around the hybrid train step.

PR 1 made the *artifacts* crash-safe (atomic CRC-manifested checkpoints,
``.prev`` fallback) and PR 2 made the step *observable* (``step_metrics``,
counters) — but the training loop itself still died on SIGTERM with all
work since the last manual save lost, and a poisoned batch either corrupted
the sharded tables (guard off) or spun forever (guard on, nobody watching).
:func:`run_resilient` closes that loop around any step built by
:func:`~.trainer.make_hybrid_train_step`:

* **Periodic + wall-clock-budget checkpointing** through the atomic
  :func:`~..utils.checkpoint.save_train_state` (tmp+fsync+rename staging
  swap; a kill at any point leaves a whole checkpoint on disk).
* **Preemption handling**: SIGTERM/SIGINT set a flag, the in-flight step
  finishes, the state checkpoints, a resume sentinel
  (``<checkpoint_dir>.resume.json``) is written, and the driver returns
  ``preempted=True`` (or exits with :data:`PREEMPT_EXIT_CODE` under
  ``exit_on_preempt=True`` — the contract orchestrators requeue on).
* **Auto-resume**: the latest valid checkpoint is restored
  (CRC-verified, ``.prev`` fallback, :class:`~..utils.runtime.
  CheckpointMismatch` on config drift) and the data source is
  deterministically fast-forwarded (:func:`~..utils.data.fast_forward`)
  so no batch is replayed or skipped — an interrupted+resumed run
  reproduces the uninterrupted trajectory bit for bit.
* **Non-finite escalation -> rollback-and-replay recovery**: the
  on-device guard (:func:`~.trainer.make_hybrid_train_step` with
  ``nan_guard``, default ``DETPU_NANGUARD`` = on) skips poisoned updates
  with params bitwise unchanged; this driver counts consecutive skips on
  the host (the step's returned loss stays truthfully non-finite) and,
  after K (``DETPU_NANGUARD_K``, default 3), enters the supervised
  recovery state machine instead of dying: restore the newest *healthy*
  checkpoint generation predating the poisoned window (the
  ``keep_last_n`` ring ``utils.checkpoint`` keeps beyond ``.prev``),
  replay the window batch by batch under the guard, QUARANTINE exactly
  the batches that come out non-finite (each is recorded in the
  ``<dir>.quarantine.json`` ledger and never fed again; the step counter
  is corrected so the trajectory equals a run whose stream never
  contained them), and continue. Each skip/rollback names the unhealthy
  tables via the per-table health sentinels
  (:class:`~..utils.obs.TableHealthContract`). The old terminal
  :class:`~..utils.runtime.NonFiniteLossError` still fires — with the
  full quarantine ledger attached — once the ``DETPU_ROLLBACK_MAX``
  retry budget or the ``DETPU_QUARANTINE_MAX`` quarantine budget is
  exhausted (a fully-poisoned stream is not a transient window), no
  healthy candidate predates the window, or recovery is impossible
  (guard off, one-shot iterator, no checkpoint dir).
* **Invalid-input enforcement**: under
  ``DistributedEmbedding(invalid_id_policy='raise')`` each batch is
  host-validated before dispatch (:meth:`~.dist_embedding.
  DistributedEmbedding.check_inputs`); with ``ragged_overflow_raise`` a
  nonzero on-device ``id_overflow`` metric escalates too.
* **Fault-injection hooks**: every recovery path is exercisable on CPU —
  ``DETPU_FAULT=preempt@<step>`` delivers a real self-SIGTERM at that step
  boundary, and ``die:driver.step`` / ``die:driver.save`` /
  ``die:driver.resume`` / ``die:driver.final`` (plus the checkpoint
  layer's own points) kill the process inside each driver phase.

The reference library (mikemckiernan/distributed-embeddings) leaves all of
this to the user — its examples train in a bare loop and checkpoint only
embedding weights at the end (``examples/dlrm/main.py:246-248`` there).
"""

from __future__ import annotations

import bisect
import collections.abc
import contextlib
import dataclasses
import json
import logging
import math
import os
import signal
import sys
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

import uuid

from ..utils import envvars, mplane, obs, runtime
from ..utils.checkpoint import (load_aux_state, meta_run_id,
                                previous_checkpoint_path,
                                restore_train_state, rollback_candidates,
                                save_train_state)
from ..utils.data import fast_forward

logger = logging.getLogger(__name__)

#: Process exit code of a preempted-and-checkpointed run under
#: ``exit_on_preempt=True`` — distinct from error codes so orchestrators
#: (and ``tools/check_resilience.py``) can requeue instead of failing.
PREEMPT_EXIT_CODE = 83


def resume_sentinel_path(checkpoint_dir: str) -> str:
    """Where the preemption exit parks its resume marker. BESIDE the
    checkpoint directory, not inside it — the atomic save swaps the
    directory wholesale on every checkpoint."""
    return checkpoint_dir.rstrip(os.sep) + ".resume.json"


def quarantine_ledger_path(checkpoint_dir: str) -> str:
    """Where the rollback-and-replay recovery persists its quarantine
    ledger (beside the checkpoint directory, like the resume sentinel)."""
    return checkpoint_dir.rstrip(os.sep) + ".quarantine.json"


def blackbox_path(checkpoint_dir: str) -> str:
    """Where the flight recorder dumps its post-mortem (beside the
    checkpoint directory, like the resume sentinel)."""
    return checkpoint_dir.rstrip(os.sep) + ".blackbox.json"


def _atomic_json(path: str, doc: Dict[str, Any]) -> None:
    """Atomic JSON write (tmp + flush + fsync + rename) — the one
    durability idiom behind the resume sentinel, the telemetry summary,
    and the quarantine ledger."""
    tmp = path + ".tmp"
    with open(tmp, "w", encoding="utf-8") as f:
        json.dump(doc, f)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)


class _QuarantineLedger:
    """Persistent recovery state: the set of quarantined stream positions
    and the rollback count. Written atomically on every change so the
    retry budget and the skip-list survive preemption/restart — a resumed
    run must neither re-feed a quarantined batch nor get a fresh rollback
    budget to burn on the same poison."""

    def __init__(self, path: Optional[str]):
        self.path = path
        self.quarantined: set = set()
        self.rollbacks = 0

    @classmethod
    def load(cls, path: Optional[str]) -> "_QuarantineLedger":
        led = cls(path)
        if path and os.path.isfile(path):
            try:
                with open(path, encoding="utf-8") as f:
                    doc = json.load(f)
                if not isinstance(doc, dict):
                    raise ValueError(f"ledger is {type(doc).__name__}, "
                                     "not an object")
                led.quarantined = {int(x) for x in doc.get("quarantined",
                                                           [])}
                led.rollbacks = int(doc.get("rollbacks", 0))
            except (OSError, json.JSONDecodeError, ValueError, TypeError):
                logger.warning("quarantine ledger %s unreadable; starting "
                               "fresh", path)
        return led

    def save(self, chief: bool = True) -> None:
        if not self.path or not chief:
            return
        _atomic_json(self.path, {"quarantined": sorted(self.quarantined),
                                 "rollbacks": self.rollbacks,
                                 "time": time.time()})


def _stream_pos_for_step(step: int, quarantined) -> int:
    """Invert the step<->stream mapping: the stream position whose
    (quarantine-filtered) prefix contains exactly ``step`` fed batches.
    Quarantined batches occupy stream positions but are never fed, so
    ``pos = step + |{q in ledger : q < pos}|`` — a monotone fixed point
    reached in <= |ledger| iterations."""
    qs = sorted(quarantined)
    pos = step
    while True:
        nxt = step + bisect.bisect_left(qs, pos)
        if nxt == pos:
            return pos
        pos = nxt


def _poison_batch(batch):
    """``DETPU_FAULT=nan@<pos>`` drill: NaN the first element of the
    batch's first floating leaf — one rank's slice of the dense batch, so
    the poison flows through the real loss into the on-device guard (the
    pmean'd verdict makes every rank skip in lockstep)."""
    import jax

    leaves, treedef = jax.tree.flatten(batch)
    out, done = [], False
    for leaf in leaves:
        if (not done and hasattr(leaf, "dtype")
                and np.issubdtype(np.dtype(leaf.dtype), np.inexact)):
            arr = np.array(leaf)
            arr.reshape(-1)[0] = np.nan
            leaf, done = arr, True
        out.append(leaf)
    if not done:
        logger.warning("DETPU_FAULT=nan@: batch has no floating leaf to "
                       "poison")
    return jax.tree.unflatten(treedef, out)


def _corrupt_ids(cat_inputs):
    """``DETPU_FAULT=badbatch@<pos>`` drill: scramble the first integer
    leaf of the categorical inputs to strictly negative ids — a garbled
    batch every ``invalid_id_policy`` must absorb (clamp/drop + a nonzero
    ``invalid_id_count``) or escalate (``raise``)."""
    import jax

    leaves, treedef = jax.tree.flatten(cat_inputs)
    out, done = [], False
    for leaf in leaves:
        if (not done and hasattr(leaf, "dtype")
                and np.issubdtype(np.dtype(leaf.dtype), np.integer)):
            leaf, done = -(np.abs(np.array(leaf)) + 1), True
        out.append(leaf)
    if not done:
        logger.warning("DETPU_FAULT=badbatch@: inputs have no integer "
                       "leaf to corrupt")
    return jax.tree.unflatten(treedef, out)


def _oovflood_ids(cat_inputs, spos: int):
    """``DETPU_FAULT=oovflood@<pos>`` drill: replace every integer leaf
    of the categorical inputs with a burst of NEVER-BEFORE-SEEN ids
    (unique per stream position, far past any sane static vocab) — the
    non-stationary-traffic chaos a streaming-vocab run must absorb via
    its shared hash buckets (no crash, no recompile, no hot-row
    eviction before the admission gate passes) and a static-vocab run
    surfaces as out-of-vocab ids through the ``invalid_id_policy``
    machinery."""
    import jax

    leaves, treedef = jax.tree.flatten(cat_inputs)
    base = 1_500_000_000  # capacity-ok: an id value (far past any vocab,
    # int32-safe), not a byte size
    out, base = [], base + (spos % 1000) * 400_000
    for leaf in leaves:
        if (hasattr(leaf, "dtype")
                and np.issubdtype(np.dtype(leaf.dtype), np.integer)):
            arr = np.array(leaf)
            fresh = base + np.arange(arr.size, dtype=np.int64)
            base += arr.size
            leaf = fresh.reshape(arr.shape).astype(arr.dtype)
        out.append(leaf)
    return jax.tree.unflatten(treedef, out)


@dataclasses.dataclass
class ResilientResult:
    """Outcome of one :func:`run_resilient` invocation."""

    state: Any                 #: final HybridTrainState
    step: int                  #: final step counter (== completed steps)
    steps_run: int             #: steps executed by THIS invocation
    preempted: bool            #: True when a SIGTERM/SIGINT ended the run
    skipped_steps: int         #: host-observed non-finite (guard-skipped)
    checkpoints_saved: int     #: checkpoints written by this invocation
    last_loss: Optional[float]  #: last step's loss (may be non-finite)
    stop_reason: str           #: exhausted | preempted | on_step | until_step
    elapsed_s: float           #: wall-clock of the training loop
    telemetry: Any = None      #: final jit-carried telemetry state (if any)
    streaming: Any = None      #: final jit-carried streaming-vocab state
    rollbacks: int = 0         #: rollback-and-replay recoveries (ledger)
    quarantined: Tuple[int, ...] = ()  #: quarantined stream positions
    rollback_time_s: float = 0.0  #: wall-clock spent restoring rollbacks


class _PreemptCatcher:
    """SIGTERM/SIGINT -> flag; the loop finishes the in-flight step and
    checkpoints before exiting. Installed only on the main thread (signal
    handlers cannot be set elsewhere); previous handlers are restored on
    exit so nested drivers and test harnesses compose."""

    SIGNALS = (signal.SIGTERM, signal.SIGINT)

    def __init__(self):
        self.fired: Optional[int] = None
        self._old: Dict[int, Any] = {}

    def _handler(self, signum, frame):
        del frame
        if self.fired is None:
            logger.warning(
                "run_resilient: received signal %d — finishing the "
                "in-flight step, checkpointing, then exiting", signum)
        self.fired = signum

    def __enter__(self):
        if threading.current_thread() is threading.main_thread():
            for s in self.SIGNALS:
                self._old[s] = signal.signal(s, self._handler)
        return self

    def __exit__(self, *exc):
        for s, h in self._old.items():
            signal.signal(s, h)
        return False


def _as_float(x) -> float:
    """Host scalar of a (possibly device) loss; NaN on fetch failure."""
    try:
        return float(np.asarray(x).reshape(-1)[-1])
    except Exception:  # noqa: BLE001 - a dead value must not mask the run
        logger.exception("run_resilient: loss readback failed")
        return float("nan")


def run_resilient(step_fn: Callable, state, data, *,
                  de,
                  checkpoint_dir: Optional[str] = None,
                  checkpoint_every_steps: int = 0,
                  checkpoint_every_s: float = 0.0,
                  until_step: Optional[int] = None,
                  resume: bool = True,
                  emb_optimizer=None,
                  dense_tx=None,
                  mesh=None,
                  on_mismatch: Optional[str] = None,
                  escalate_after: Optional[int] = None,
                  keep_last_n: Optional[int] = None,
                  rollback_max: Optional[int] = None,
                  quarantine_max: Optional[int] = None,
                  health: Optional[obs.TableHealthContract] = None,
                  metrics_logger=None,
                  metrics_interval: int = 100,
                  on_step: Optional[Callable] = None,
                  on_step_aux: Optional[Callable] = None,
                  exit_on_preempt: bool = False,
                  save_on_exit: bool = True,
                  is_chief: Optional[bool] = None,
                  telemetry_state=None,
                  telemetry_path: Optional[str] = None,
                  streaming_state=None) -> ResilientResult:
    """Drive ``step_fn`` over ``data`` with checkpointing, preemption
    handling, auto-resume, and poisoned-batch escalation.

    Args:
      step_fn: a step built by :func:`~.trainer.make_hybrid_train_step` —
        ``step(state, cat_inputs, batch) -> (loss, state[, metrics])``.
        Build it with the non-finite guard on (the default) for the
        skip-don't-corrupt behavior this driver escalates on.
      state: freshly initialized :class:`~.trainer.HybridTrainState`; on
        auto-resume its ``dense_params`` serve as the restore template and
        the restored state replaces it.
      data: the batch source, yielding ``(cat_inputs, batch)`` pairs —
        either a callable ``data(start_step) -> iterable`` (preferred: it
        positions itself, e.g. ``RawBinaryDataset(start_batch=...)`` or a
        step-seeded generator) or a plain iterable (fast-forwarded by
        generate-and-discard). See :func:`~..utils.data.fast_forward`.
      de: the :class:`~.dist_embedding.DistributedEmbedding` (checkpoint
        streaming + input policies).
      checkpoint_dir: atomic train-state checkpoint directory; ``None``
        disables checkpointing, resume, and the preemption save (the
        preempt flag then just stops the loop).
      checkpoint_every_steps: save every N *absolute* steps (cadence stays
        aligned across resumes); 0 disables the step cadence.
      checkpoint_every_s: save when this much wall-clock passed since the
        last save (preemption-prone fleets bound their lost work this
        way); 0 disables the time cadence.
      until_step: stop once ``state.step`` reaches this absolute step
        (resume-friendly alternative to sizing the iterator).
      resume: restore from ``checkpoint_dir`` when a valid checkpoint (or
        its ``.prev`` fallback) exists; requires ``emb_optimizer`` and
        ``dense_tx`` (the :func:`~..utils.checkpoint.restore_train_state`
        arguments).
      on_mismatch: restore policy when the checkpoint was written under a
        DIFFERENT sharding plan / world size than ``de`` — the elastic
        topology path: a run preempted on 16 chips that comes back on 8
        builds its ``de``/mesh for 8 and the restore re-shards the
        logical tables in place (``"reshard"``) instead of dying. Default
        ``None`` follows ``DETPU_ON_MISMATCH`` (which defaults to
        ``"reshard"``); pass ``"error"`` for the strict pre-elastic
        behavior. Every re-shard is logged as a degradation — warning log
        plus a ``checkpoint_reshard`` record (old plan, new plan,
        per-rank byte deltas) in ``metrics_logger`` when one is given.
        After the re-shard point the run is checkpoint-CRC-deterministic
        again: two resumes onto the same shrunken mesh write identical
        checkpoints.
      escalate_after: consecutive non-finite-loss steps before the
        rollback-and-replay recovery engages (and, once its budgets are
        exhausted, :class:`~..utils.runtime.NonFiniteLossError` fires);
        default ``DETPU_NANGUARD_K`` (3). On a terminal escalation the
        state is checkpointed first — under the guard it still holds the
        last good values.
      keep_last_n: checkpoint-ring size passed to
        :func:`~..utils.checkpoint.save_train_state` — how many
        generations beyond ``<dir>`` and ``<dir>.prev`` stay restorable
        (the rollback's supply of known-good states). Default
        ``DETPU_CKPT_RING`` (2).
      rollback_max: rollback-and-replay attempts before the escalation
        turns terminal; default ``DETPU_ROLLBACK_MAX`` (2). The count
        persists in the quarantine ledger across preemption/resume.
      quarantine_max: total batches the recovery may quarantine before
        declaring the stream poisoned (terminal); default
        ``DETPU_QUARANTINE_MAX`` (8).
      health: per-table numerical health contract
        (:class:`~..utils.obs.TableHealthContract`) evaluated on every
        guard-skipped instrumented step — its violations (and the table
        ids they name) ride the warning logs and the
        ``training_rollback`` / ``batch_quarantined`` recovery events.
        Default: the env-configured contract
        (:func:`~..utils.obs.default_health_contract`).
      metrics_logger: chief-side :class:`~..utils.obs.MetricsLogger`; when
        the step returns metrics, every process joins the collective
        :func:`~..utils.obs.fetch_metrics` each ``metrics_interval`` steps
        and the chief logs the record.
      on_step: ``on_step(step, loss, metrics, state) -> stop`` host
        callback after each step (eval cadence, printing, early stop) —
        truthy return stops the loop cleanly.
      on_step_aux: like ``on_step`` but with the jit-carried aux states
        appended — ``on_step_aux(step, loss, metrics, state,
        telemetry_state, streaming_state) -> stop`` (either aux is
        ``None`` when not threaded). The online runtime's publish-and-
        serve pump rides here: it needs the streaming state that travels
        WITH the params to publish a consistent snapshot pair. Called
        after ``on_step`` when both are given; truthy return stops the
        loop the same way.
      exit_on_preempt: after the preemption checkpoint+sentinel, call
        ``sys.exit(PREEMPT_EXIT_CODE)`` instead of returning. Ignored
        without ``checkpoint_dir`` — exit code 83 asserts a checkpoint
        exists to requeue on; an uncheckpointed preemption returns a
        normal ``preempted=True`` result instead.
      save_on_exit: checkpoint once more on clean completion (and clear
        the resume sentinel).
      is_chief: multi-host chief override (default: process 0 writes).
      telemetry_state: jit-carried access-telemetry state
        (:func:`~..analysis.telemetry.init_telemetry`) for a ``step_fn``
        built with ``telemetry=`` on — the driver then calls
        ``step_fn(state, cat_inputs, batch, telem)``, threads the
        returned (last-element) telemetry state, and FLUSHES a host
        summary (:func:`~..analysis.telemetry.summarize_telemetry`) plus
        the raw state (``<path>.state.npz``) alongside every checkpoint;
        on auto-resume the saved state is restored into the provided
        (fresh) template, so an interrupted+resumed run CONTINUES the
        accumulation — hot-row/skew reports survive preemption exactly
        like the train state does. The final state rides back on
        ``ResilientResult.telemetry``.
      telemetry_path: where the flushed summary JSON goes; defaults to
        ``<checkpoint_dir>.telemetry.json`` (atomic tmp+rename, chief
        only). With neither a path nor a checkpoint dir, telemetry is
        threaded but never flushed.
      streaming_state: jit-carried streaming-vocab state
        (:func:`~.streaming.init_streaming`) for a ``step_fn`` built
        with ``dynamic=`` on. Threaded like the telemetry state (one
        more trailing step argument/return, AFTER telemetry when both
        ride) and — because the slot map is part of the recoverable
        trajectory, not an auxiliary report — persisted INSIDE every
        checkpoint (``aux/streaming.npz``, CRC-manifested, via the
        plan-agnostic :func:`~.streaming.encode_state`): auto-resume
        decodes it from the restored checkpoint, and the
        rollback-and-replay recovery rewinds it from EXACTLY the ring
        candidate it restores — the generalized aux-rewind that keeps an
        interrupted+resumed (or rolled-back) streaming run
        checkpoint-CRC-identical to an uninterrupted one. The final
        state rides back on ``ResilientResult.streaming``.

    Returns:
      :class:`ResilientResult`. Never returns on preemption when
      ``exit_on_preempt=True``.
    """
    if checkpoint_dir is None and resume:
        resume = False
    if escalate_after is None:
        escalate_after = obs.nanguard_escalation_k()
    if on_mismatch is None:
        on_mismatch = envvars.get("DETPU_ON_MISMATCH")
    if keep_last_n is None:
        keep_last_n = envvars.get_int("DETPU_CKPT_RING")
    if rollback_max is None:
        rollback_max = envvars.get_int("DETPU_ROLLBACK_MAX")
    if quarantine_max is None:
        quarantine_max = envvars.get_int("DETPU_QUARANTINE_MAX")
    if health is None:
        health = obs.default_health_contract()
    # rollback needs to re-position the stream: a one-shot iterator that
    # is already being consumed cannot be replayed
    can_replay = (callable(data) or hasattr(data, "iter_from")
                  or not isinstance(data, collections.abc.Iterator))

    if is_chief is None:
        def _chief() -> bool:
            import jax
            return jax.process_index() == 0
    else:
        def _chief() -> bool:
            return bool(is_chief)

    if telemetry_path is None and checkpoint_dir is not None:
        telemetry_path = checkpoint_dir.rstrip(os.sep) + ".telemetry.json"

    # ---- auto-resume -----------------------------------------------------
    ckpt_meta = os.path.join(checkpoint_dir, "meta.json") \
        if checkpoint_dir else None
    have_ckpt = checkpoint_dir is not None and (
        os.path.isfile(ckpt_meta)
        or os.path.isdir(checkpoint_dir + ".prev"))
    # the quarantine ledger belongs to the checkpointed RUN: load it only
    # on an actual resume — a fresh run (resume=False) in a dirty
    # directory must not inherit stale skip positions or a spent budget
    ledger_path = (quarantine_ledger_path(checkpoint_dir)
                   if checkpoint_dir else None)
    run_id: Optional[str] = None
    if resume and have_ckpt:
        ledger = _QuarantineLedger.load(ledger_path)
        # a resume CONTINUES the checkpointed run's lineage: inherit its
        # id so that run's generations stay valid rollback candidates
        for p in (checkpoint_dir,
                  previous_checkpoint_path(checkpoint_dir)):
            run_id = meta_run_id(p)
            if run_id is not None:
                break
    else:
        ledger = _QuarantineLedger(ledger_path)
        if ledger_path and os.path.isfile(ledger_path) and _chief():
            # a previous run's ledger in this directory: DELETE it, or
            # this run's own later resume would inherit the stale skip
            # positions and spent rollback budget
            os.remove(ledger_path)
    if run_id is None:
        # fresh lineage (or a pre-lineage checkpoint): every save below
        # stamps it, and the rollback refuses candidates from any OTHER
        # lineage — a fresh run in a dirty directory must never restore
        # a previous run's parameters
        run_id = uuid.uuid4().hex
    if resume and have_ckpt:
        if emb_optimizer is None or dense_tx is None:
            raise ValueError(
                "run_resilient(resume=True) with an existing checkpoint "
                "needs emb_optimizer= and dense_tx= to rebuild the state")
        runtime.fault_point("driver.resume")
        # events are process-global: discard any reshard/fallback
        # recorded by an earlier unrelated restore so the drains below
        # see only OURS
        obs.drain_events("checkpoint_reshard")
        obs.drain_events("checkpoint_prev_fallback")
        state = restore_train_state(
            checkpoint_dir, de, emb_optimizer, state.dense_params,
            dense_tx, mesh=mesh, on_mismatch=on_mismatch)
        logger.info("run_resilient: resumed at step %d from %s",
                    int(state.step), checkpoint_dir)
        for ev in obs.drain_events("checkpoint_reshard"):
            # degraded elastic resume: surface it loudly and durably —
            # the run continues, but capacity/placement changed underneath
            diff = ev.get("diff", {})
            logger.warning(
                "run_resilient: resumed onto a DIFFERENT topology (world "
                "%s -> %s, strategy %s -> %s, per-rank byte deltas %s) — "
                "re-sharded in place, continuing degraded",
                *diff.get("world_size", [None, None]),
                *diff.get("strategy", [None, None]),
                diff.get("per_rank_byte_deltas"))
            if metrics_logger is not None and _chief():
                metrics_logger.log_event(
                    "checkpoint_reshard", step=int(state.step), diff=diff)
        if telemetry_state is not None and telemetry_path is not None \
                and os.path.isfile(telemetry_path + ".state.npz"):
            from ..analysis import telemetry as tel
            telemetry_state = tel.restore_telemetry_state(
                telemetry_path + ".state.npz", telemetry_state)
        if streaming_state is not None:
            # the slot map rides INSIDE the checkpoint (aux/streaming.npz)
            # — decode under the (possibly re-sharded) current plan; a
            # pre-streaming checkpoint decodes to a pristine warm-up
            # state. Load from the generation the PARAMS actually came
            # from: when restore fell back to <dir>.prev (torn head),
            # the head's newer slot map must not splice onto the older
            # tables
            from . import streaming as streaming_mod
            aux_dir = checkpoint_dir
            for ev in obs.drain_events("checkpoint_prev_fallback"):
                aux_dir = ev.get("prev", aux_dir)
            streaming_state = streaming_mod.decode_state(
                de, streaming_state,
                load_aux_state(aux_dir, "streaming"))

    start_step = int(state.step)

    saves = 0
    last_save_t = time.monotonic()

    def _flush_telemetry():
        if telemetry_state is None or telemetry_path is None \
                or not _chief():
            return
        from ..analysis import telemetry as tel
        try:
            summary = tel.summarize_telemetry(de, telemetry_state)
            _atomic_json(telemetry_path, dict(summary, time=time.time()))
            tel.save_telemetry_state(_telemetry_state_path(),
                                     telemetry_state)
        except Exception:  # noqa: BLE001 - telemetry is auxiliary: a flush
            # failure (summarize bug, disk full, read-only fs) must not
            # kill an otherwise healthy training run
            logger.exception("run_resilient: telemetry flush failed")

    def _telemetry_state_path() -> str:
        # raw carried-state sidecar beside the summary, so a resumed run
        # CONTINUES the accumulation instead of restarting from zero
        return telemetry_path + ".state.npz"

    def _save():
        nonlocal saves, last_save_t
        runtime.fault_point("driver.save")
        aux = None
        if streaming_state is not None:
            # the slot map is trajectory, not telemetry: it rides INSIDE
            # the checkpoint (CRC-manifested, one snapshot per ring
            # generation) in the plan-agnostic per-table encoding
            from . import streaming as streaming_mod
            aux = {"streaming": streaming_mod.encode_state(
                de, streaming_state)}
        save_train_state(checkpoint_dir, de, state, is_chief=is_chief,
                         keep_last_n=keep_last_n, run_id=run_id,
                         aux_states=aux)
        _flush_telemetry()
        saves += 1
        last_save_t = time.monotonic()

    def _sentinel(write: bool, **fields):
        if checkpoint_dir is None or not _chief():
            return
        path = resume_sentinel_path(checkpoint_dir)
        if not write:
            if os.path.exists(path):
                os.remove(path)
            return
        _atomic_json(path, dict(fields, time=time.time()))

    step = start_step - 1
    steps_run = 0
    skipped = 0
    consecutive = 0
    bad_window: List[int] = []  # stream positions of the current streak
    replay_until: Optional[int] = None  # recovery-replay high-water mark
    last_good = start_step - 1
    last_loss: Optional[float] = None
    preempted = False
    stop_reason = "exhausted"
    rollback_time = 0.0
    check_ids = (de is not None
                 and (de.invalid_id_policy == "raise"
                      or de.ragged_overflow_raise))
    t0 = time.monotonic()

    def _ledger_tail() -> str:
        return (f". Quarantine ledger: {sorted(ledger.quarantined)} after "
                f"{ledger.rollbacks} rollback(s)")

    # the process flight recorder rides beside the checkpoint: every
    # recovery event taps in automatically (obs.record_event), step
    # metrics ring in at metrics_interval, and the terminal escalations
    # below dump the black box post-mortem
    flight = (mplane.install_flight_recorder(blackbox_path(checkpoint_dir))
              if checkpoint_dir is not None else mplane.flight_recorder())
    dumped_blackbox = False

    def _blackbox(trigger: str, **context):
        nonlocal dumped_blackbox
        if flight is None:
            return
        context.setdefault("last_good_step", last_good)
        context.setdefault("quarantined", sorted(ledger.quarantined))
        context.setdefault("rollbacks", ledger.rollbacks)
        if flight.dump(trigger, **context) is not None:
            dumped_blackbox = True

    def _terminal(msg: str,
                  trigger: str = "nan_escalation",
                  **context) -> runtime.NonFiniteLossError:
        # park the (guard-clean) state before dying, like the
        # pre-recovery escalation always did
        if checkpoint_dir is not None:
            _save()
        _blackbox(trigger, message=msg, **context)
        err = runtime.NonFiniteLossError(msg + _ledger_tail())
        err.quarantined = tuple(sorted(ledger.quarantined))
        err.rollbacks = ledger.rollbacks
        return err

    def _attempt_rollback(cur_state, window):
        """Restore the newest healthy checkpoint generation whose stream
        position predates the poisoned window. Returns ``(state, dir)``
        on success, ``(None, reason)`` when recovery is impossible."""
        nonlocal rollback_time
        if checkpoint_dir is None:
            return None, "no checkpoint_dir to roll back to"
        if not can_replay:
            return None, ("data source is a one-shot iterator — pass a "
                          "callable factory or an iter_from source to "
                          "make the window replayable")
        if not obs.nanguard_enabled():
            return None, ("DETPU_NANGUARD=0: replayed updates would not "
                          "be guarded, so a quarantined replay cannot "
                          "be trusted")
        if emb_optimizer is None or dense_tx is None:
            return None, ("rollback needs emb_optimizer= and dense_tx= "
                          "(the restore_train_state arguments)")
        if ledger.rollbacks >= rollback_max:
            return None, (f"rollback budget exhausted "
                          f"({ledger.rollbacks}/{rollback_max}, "
                          "DETPU_ROLLBACK_MAX)")
        t_rb = time.monotonic()
        tried = 0
        for cand_step, cand in rollback_candidates(checkpoint_dir):
            if cand_step is None:  # pre-ring format: position unknowable
                continue
            if meta_run_id(cand) != run_id:
                # another run's leftover generation in this directory
                # (fresh start over a dead run's checkpoints): restoring
                # it would silently splice foreign parameters into this
                # run's trajectory
                continue
            if _stream_pos_for_step(cand_step,
                                    ledger.quarantined) > window[0]:
                continue  # saved inside/after the window
            tried += 1
            runtime.fault_point("driver.rollback")
            try:
                restored = restore_train_state(
                    cand, de, emb_optimizer, cur_state.dense_params,
                    dense_tx, mesh=mesh, fallback=False,
                    on_mismatch=on_mismatch)
            except (runtime.CheckpointCorrupt,
                    runtime.CheckpointMismatch) as e:
                logger.warning(
                    "rollback: candidate %s unusable (%s); trying an "
                    "older generation", cand, e)
                continue
            rollback_time += time.monotonic() - t_rb
            return restored, cand
        rollback_time += time.monotonic() - t_rb
        return None, ("no healthy checkpoint generation predates the "
                      f"poisoned window (tried {tried} candidate(s))")

    def _record_recovery(kind: str, **payload):
        obs.record_event(kind, **payload)
        if metrics_logger is not None and _chief():
            metrics_logger.log_event(kind, **payload)

    @contextlib.contextmanager
    def _crash_blackbox():
        # the black box's last line of defense: ANY exception escaping
        # the train loop that did not already dump (the typed terminals
        # above do) leaves a post-mortem before propagating
        try:
            yield
        except (KeyboardInterrupt, SystemExit):
            raise
        except BaseException as e:
            if not dumped_blackbox:
                _blackbox("unhandled_crash", error=repr(e),
                          error_type=type(e).__name__)
            raise

    with _crash_blackbox(), _PreemptCatcher() as catcher:
        restart = True
        while restart:
            restart = False
            step = int(state.step)  # host mirror of the update counter
            # stream position and step counter decouple once batches are
            # quarantined: position = step + |quarantined before it|
            start_pos = _stream_pos_for_step(step, ledger.quarantined)
            batches = fast_forward(data, start_pos)
            for spos, item in enumerate(batches, start=start_pos):
                if spos in ledger.quarantined:
                    continue  # poisoned: never fed again, on any replay
                cur = step  # ordinal of the step this batch would train
                if until_step is not None and cur >= until_step:
                    stop_reason = "until_step"
                    break
                runtime.fault_point("driver.step")
                if runtime.preempt_step() == cur:
                    # the preemption drill: a REAL self-SIGTERM at this
                    # STEP boundary (counter ordinal, as documented —
                    # unlike nan@/badbatch@, which target stream
                    # positions so replays re-inject deterministically),
                    # caught like any external one
                    os.kill(os.getpid(), signal.SIGTERM)
                try:
                    cat_inputs, batch = item
                except (TypeError, ValueError) as e:
                    raise ValueError(
                        "run_resilient data must yield (cat_inputs, "
                        f"batch) pairs; got {type(item).__name__}") from e
                if spos in runtime.nan_steps():
                    batch = _poison_batch(batch)
                if spos in runtime.badbatch_steps():
                    cat_inputs = _corrupt_ids(cat_inputs)
                if spos in runtime.oovflood_steps():
                    cat_inputs = _oovflood_ids(cat_inputs, spos)
                if check_ids:
                    de.check_inputs(cat_inputs)

                # aux-threaded steps return the carried states LAST, in
                # the fixed (telemetry, streaming) order
                aux_in = [a for a in (telemetry_state, streaming_state)
                          if a is not None]
                out = step_fn(state, cat_inputs, batch, *aux_in)
                if aux_in:
                    aux_out = list(out[-len(aux_in):])
                    out = out[:-len(aux_in)]
                    if telemetry_state is not None:
                        telemetry_state = aux_out.pop(0)
                    if streaming_state is not None:
                        streaming_state = aux_out.pop(0)
                loss, state = out[0], out[1]
                metrics = out[2] if len(out) > 2 else None
                steps_run += 1

                # ---- host view of the on-device guard -----------------
                last_loss = _as_float(loss)
                skipped_now = not math.isfinite(last_loss)
                if not skipped_now and metrics is not None \
                        and "skipped_steps" in metrics:
                    # the guard can also skip on non-finite GRADIENT
                    # energy with a finite loss — the on-device flag is
                    # the authoritative verdict when instrumented
                    skipped_now = float(
                        np.asarray(metrics["skipped_steps"]).max()) > 0
                quarantined_now = False
                if not skipped_now:
                    consecutive = 0
                    bad_window = []
                    last_good = cur
                    step = cur + 1
                    if replay_until is not None and spos >= replay_until:
                        # the window replayed clean: recovery complete
                        replay_until = None
                        _record_recovery(
                            "training_recovered", step=cur,
                            quarantined=sorted(ledger.quarantined),
                            rollbacks=ledger.rollbacks)
                        logger.warning(
                            "run_resilient: recovery complete at step %d "
                            "— %d batch(es) quarantined over %d "
                            "rollback(s); continuing", cur,
                            len(ledger.quarantined), ledger.rollbacks)
                elif replay_until is not None and spos <= replay_until:
                    # ---- recovery replay: this batch is PROVEN poisoned
                    # (restored state + guard say so) -> quarantine it
                    quarantined_now = True
                    skipped += 1
                    if len(ledger.quarantined) >= quarantine_max:
                        # undo this batch's counter advance BEFORE the
                        # terminal save: the parked checkpoint must count
                        # only fed batches (the batch itself stays out of
                        # the full ledger — a resume retries it and fails
                        # terminally again rather than silently skipping
                        # an unrecorded position)
                        state = state._replace(step=state.step - 1)
                        raise _terminal(
                            "stream is poisoned beyond the quarantine "
                            f"budget (DETPU_QUARANTINE_MAX="
                            f"{quarantine_max}): the batch at stream "
                            f"position {spos} is non-finite too; last "
                            f"good step: {last_good}",
                            trigger="quarantine_exhaustion")
                    ledger.quarantined.add(spos)
                    ledger.save(_chief())
                    # the guard held params/optimizer state bitwise;
                    # undo the counter advance so the trajectory equals
                    # a stream that never contained this batch
                    state = state._replace(step=state.step - 1)
                    unhealthy = (obs.unhealthy_tables(metrics, health)
                                 if metrics is not None else [])
                    obs.counter_inc("quarantined_batches")
                    _record_recovery(
                        "batch_quarantined", stream_pos=spos, step=cur,
                        loss=last_loss, unhealthy_tables=unhealthy,
                        violations=(health.check(metrics)
                                    if metrics is not None else []))
                    logger.warning(
                        "run_resilient: QUARANTINED batch at stream "
                        "position %d (loss %r%s) — %d/%d quarantine "
                        "slots used", spos, last_loss,
                        (f"; unhealthy tables {unhealthy}" if unhealthy
                         else ""), len(ledger.quarantined), quarantine_max)
                    if spos >= replay_until:
                        replay_until = None
                        _record_recovery(
                            "training_recovered", step=cur,
                            quarantined=sorted(ledger.quarantined),
                            rollbacks=ledger.rollbacks)
                else:
                    consecutive += 1
                    skipped += 1
                    bad_window.append(spos)
                    step = cur + 1
                    obs.counter_inc("nonfinite_steps")
                    unhealthy = (obs.unhealthy_tables(metrics, health)
                                 if metrics is not None else [])
                    logger.warning(
                        "run_resilient: non-finite step %d (loss %r, "
                        "%d consecutive; guard %s%s)", cur, last_loss,
                        consecutive,
                        "on" if obs.nanguard_enabled() else "OFF",
                        (f"; unhealthy tables {unhealthy}" if unhealthy
                         else ""))
                    if consecutive >= escalate_after:
                        new_state, how = _attempt_rollback(state,
                                                           bad_window)
                        if new_state is None:
                            raise _terminal(trigger=(
                                "rollback_exhaustion"
                                if "budget exhausted" in how
                                else "nan_escalation"),
                                unhealthy_tables=unhealthy, msg=(
                                f"non-finite loss/gradients for "
                                f"{consecutive} consecutive steps "
                                f"(through step {cur}); last good step: "
                                f"{last_good}. Params/optimizer state "
                                "are held at the last good values"
                                + (f" and checkpointed to "
                                   f"{checkpoint_dir!r}"
                                   if checkpoint_dir else "")
                                + (" (DETPU_NANGUARD=0: updates were NOT "
                                   "guarded — the saved state may be "
                                   "poisoned)"
                                   if not obs.nanguard_enabled() else "")
                                + ". Rollback-and-replay could not "
                                  f"recover: {how}"))
                        ledger.rollbacks += 1
                        ledger.save(_chief())
                        replay_until = bad_window[-1]
                        payload = dict(
                            escalated_at_step=cur,
                            restored_step=int(new_state.step),
                            candidate=how,
                            window=[bad_window[0], bad_window[-1]],
                            unhealthy_tables=unhealthy,
                            rollbacks=ledger.rollbacks)
                        _record_recovery("training_rollback", **payload)
                        logger.warning(
                            "run_resilient: NaN escalation at step %d — "
                            "ROLLED BACK to %s (step %d); replaying "
                            "stream window [%d, %d] under the guard to "
                            "bisect the poison (rollback %d/%d)",
                            cur, how, payload["restored_step"],
                            bad_window[0], bad_window[-1],
                            ledger.rollbacks, rollback_max)
                        state = new_state
                        # ---- generalized aux rewind: EVERY jit-carried
                        # aux state rewinds with the params — a rollback
                        # that restored step-k tables but kept step-k+n
                        # slot maps / sketches would splice two
                        # trajectories (the "telemetry rewinds but other
                        # aux state is silently kept" bug)
                        if telemetry_state is not None \
                                and telemetry_path is not None \
                                and os.path.isfile(
                                    _telemetry_state_path()):
                            # telemetry rewinds to its last flushed
                            # accumulation (approximate — ids folded
                            # since the flush, incl. a later-quarantined
                            # batch's, may remain counted; sketches are
                            # monotone estimates by design)
                            from ..analysis import telemetry as tel
                            telemetry_state = tel.restore_telemetry_state(
                                _telemetry_state_path(), telemetry_state)
                        if streaming_state is not None:
                            # streaming state rewinds EXACTLY: each ring
                            # generation carries its own aux snapshot,
                            # so the slot map restores from the SAME
                            # candidate the params did (a pre-streaming
                            # candidate decodes to a pristine warm-up
                            # map — degraded to buckets, never spliced)
                            from . import streaming as streaming_mod
                            streaming_state = streaming_mod.decode_state(
                                de, streaming_state,
                                load_aux_state(how, "streaming"))
                        consecutive = 0
                        bad_window = []
                        restart = True
                        break

                # ---- metrics / escalations ---------------------------
                # (quarantined batches are not part of the logical run:
                # the clean-equivalent stream never contained them)
                if metrics is not None and not quarantined_now:
                    if de is not None and de.ragged_overflow_raise:
                        overflow = float(np.asarray(
                            metrics["id_overflow"]).sum())
                        if overflow > 0:
                            raise runtime.InvalidInputError(
                                f"step {cur}: {int(overflow)} ragged "
                                "id(s) overflowed their static capacity "
                                "(ragged_overflow_raise)")
                    if (metrics_interval
                            and cur % metrics_interval == 0):
                        host_metrics = obs.fetch_metrics(metrics)
                        if metrics_logger is not None:
                            metrics_logger.log_step(host_metrics,
                                                    step=cur)
                        if flight is not None:
                            flight.note_step(cur,
                                             obs.summarize(host_metrics))

                if (on_step is not None and not quarantined_now
                        and on_step(cur, last_loss, metrics, state)):
                    stop_reason = "on_step"
                    break
                if (on_step_aux is not None and not quarantined_now
                        and on_step_aux(cur, last_loss, metrics, state,
                                        telemetry_state, streaming_state)):
                    stop_reason = "on_step"
                    break

                # ---- checkpoint cadence ------------------------------
                # suppressed mid-streak (consecutive > 0): the guard
                # holds params at the last good values, so a save now
                # adds nothing — and it would rotate the healthy
                # pre-window generations out of the ring exactly when
                # the rollback is about to need them
                if (checkpoint_dir is not None and not catcher.fired
                        and not quarantined_now and consecutive == 0):
                    due_steps = (checkpoint_every_steps
                                 and step % checkpoint_every_steps == 0)
                    due_time = (checkpoint_every_s
                                and time.monotonic() - last_save_t
                                >= checkpoint_every_s)
                    if due_steps or due_time:
                        _save()

                # ---- preemption: finish-step -> checkpoint -> sentinel
                if catcher.fired:
                    preempted = True
                    stop_reason = "preempted"
                    if checkpoint_dir is not None:
                        _save()
                        _sentinel(True, step=int(state.step),
                                  signal=int(catcher.fired),
                                  reason="preempted")
                    _blackbox("preemption", step=int(state.step),
                              signal=int(catcher.fired))
                    break

    elapsed = time.monotonic() - t0
    if not preempted:
        runtime.fault_point("driver.final")
        if checkpoint_dir is not None and save_on_exit:
            _save()
        else:
            _flush_telemetry()  # no final checkpoint, but the report
            # should still reflect the completed run
        _sentinel(False)

    result = ResilientResult(
        state=state, step=int(state.step), steps_run=steps_run,
        preempted=preempted, skipped_steps=skipped,
        checkpoints_saved=saves, last_loss=last_loss,
        stop_reason=stop_reason, elapsed_s=elapsed,
        telemetry=telemetry_state, streaming=streaming_state,
        rollbacks=ledger.rollbacks,
        quarantined=tuple(sorted(ledger.quarantined)),
        rollback_time_s=round(rollback_time, 4))
    if preempted and exit_on_preempt and checkpoint_dir is not None:
        # exit code 83 asserts "checkpointed, requeue me" — only true
        # when a checkpoint dir exists; an uncheckpointed preemption
        # returns normally so the caller can wind down gracefully
        logger.warning(
            "run_resilient: preempted at step %d — checkpointed, exiting "
            "with code %d", result.step, PREEMPT_EXIT_CODE)
        sys.exit(PREEMPT_EXIT_CODE)
    return result

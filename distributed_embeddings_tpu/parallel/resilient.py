"""Self-healing training driver: preemption-safe resume, non-finite-loss
escalation, and invalid-input enforcement around the hybrid train step.

PR 1 made the *artifacts* crash-safe (atomic CRC-manifested checkpoints,
``.prev`` fallback) and PR 2 made the step *observable* (``step_metrics``,
counters) — but the training loop itself still died on SIGTERM with all
work since the last manual save lost, and a poisoned batch either corrupted
the sharded tables (guard off) or spun forever (guard on, nobody watching).
:func:`run_resilient` closes that loop around any step built by
:func:`~.trainer.make_hybrid_train_step`:

* **Periodic + wall-clock-budget checkpointing** through the atomic
  :func:`~..utils.checkpoint.save_train_state` (tmp+fsync+rename staging
  swap; a kill at any point leaves a whole checkpoint on disk).
* **Preemption handling**: SIGTERM/SIGINT set a flag, the in-flight step
  finishes, the state checkpoints, a resume sentinel
  (``<checkpoint_dir>.resume.json``) is written, and the driver returns
  ``preempted=True`` (or exits with :data:`PREEMPT_EXIT_CODE` under
  ``exit_on_preempt=True`` — the contract orchestrators requeue on).
* **Auto-resume**: the latest valid checkpoint is restored
  (CRC-verified, ``.prev`` fallback, :class:`~..utils.runtime.
  CheckpointMismatch` on config drift) and the data source is
  deterministically fast-forwarded (:func:`~..utils.data.fast_forward`)
  so no batch is replayed or skipped — an interrupted+resumed run
  reproduces the uninterrupted trajectory bit for bit.
* **Non-finite escalation**: the on-device guard
  (:func:`~.trainer.make_hybrid_train_step` with ``nan_guard``, default
  ``DETPU_NANGUARD`` = on) skips poisoned updates with params bitwise
  unchanged; this driver counts consecutive skips on the host (the step's
  returned loss stays truthfully non-finite) and raises
  :class:`~..utils.runtime.NonFiniteLossError` naming the last good step
  after K (``DETPU_NANGUARD_K``, default 3) — after a final checkpoint of
  the still-clean state.
* **Invalid-input enforcement**: under
  ``DistributedEmbedding(invalid_id_policy='raise')`` each batch is
  host-validated before dispatch (:meth:`~.dist_embedding.
  DistributedEmbedding.check_inputs`); with ``ragged_overflow_raise`` a
  nonzero on-device ``id_overflow`` metric escalates too.
* **Fault-injection hooks**: every recovery path is exercisable on CPU —
  ``DETPU_FAULT=preempt@<step>`` delivers a real self-SIGTERM at that step
  boundary, and ``die:driver.step`` / ``die:driver.save`` /
  ``die:driver.resume`` / ``die:driver.final`` (plus the checkpoint
  layer's own points) kill the process inside each driver phase.

The reference library (mikemckiernan/distributed-embeddings) leaves all of
this to the user — its examples train in a bare loop and checkpoint only
embedding weights at the end (``examples/dlrm/main.py:246-248`` there).
"""

from __future__ import annotations

import dataclasses
import json
import logging
import math
import os
import signal
import sys
import threading
import time
from typing import Any, Callable, Dict, Optional

import numpy as np

from ..utils import envvars, obs, runtime
from ..utils.checkpoint import restore_train_state, save_train_state
from ..utils.data import fast_forward

logger = logging.getLogger(__name__)

#: Process exit code of a preempted-and-checkpointed run under
#: ``exit_on_preempt=True`` — distinct from error codes so orchestrators
#: (and ``tools/check_resilience.py``) can requeue instead of failing.
PREEMPT_EXIT_CODE = 83


def resume_sentinel_path(checkpoint_dir: str) -> str:
    """Where the preemption exit parks its resume marker. BESIDE the
    checkpoint directory, not inside it — the atomic save swaps the
    directory wholesale on every checkpoint."""
    return checkpoint_dir.rstrip(os.sep) + ".resume.json"


@dataclasses.dataclass
class ResilientResult:
    """Outcome of one :func:`run_resilient` invocation."""

    state: Any                 #: final HybridTrainState
    step: int                  #: final step counter (== completed steps)
    steps_run: int             #: steps executed by THIS invocation
    preempted: bool            #: True when a SIGTERM/SIGINT ended the run
    skipped_steps: int         #: host-observed non-finite (guard-skipped)
    checkpoints_saved: int     #: checkpoints written by this invocation
    last_loss: Optional[float]  #: last step's loss (may be non-finite)
    stop_reason: str           #: exhausted | preempted | on_step | until_step
    elapsed_s: float           #: wall-clock of the training loop
    telemetry: Any = None      #: final jit-carried telemetry state (if any)


class _PreemptCatcher:
    """SIGTERM/SIGINT -> flag; the loop finishes the in-flight step and
    checkpoints before exiting. Installed only on the main thread (signal
    handlers cannot be set elsewhere); previous handlers are restored on
    exit so nested drivers and test harnesses compose."""

    SIGNALS = (signal.SIGTERM, signal.SIGINT)

    def __init__(self):
        self.fired: Optional[int] = None
        self._old: Dict[int, Any] = {}

    def _handler(self, signum, frame):
        del frame
        if self.fired is None:
            logger.warning(
                "run_resilient: received signal %d — finishing the "
                "in-flight step, checkpointing, then exiting", signum)
        self.fired = signum

    def __enter__(self):
        if threading.current_thread() is threading.main_thread():
            for s in self.SIGNALS:
                self._old[s] = signal.signal(s, self._handler)
        return self

    def __exit__(self, *exc):
        for s, h in self._old.items():
            signal.signal(s, h)
        return False


def _as_float(x) -> float:
    """Host scalar of a (possibly device) loss; NaN on fetch failure."""
    try:
        return float(np.asarray(x).reshape(-1)[-1])
    except Exception:  # noqa: BLE001 - a dead value must not mask the run
        logger.exception("run_resilient: loss readback failed")
        return float("nan")


def run_resilient(step_fn: Callable, state, data, *,
                  de,
                  checkpoint_dir: Optional[str] = None,
                  checkpoint_every_steps: int = 0,
                  checkpoint_every_s: float = 0.0,
                  until_step: Optional[int] = None,
                  resume: bool = True,
                  emb_optimizer=None,
                  dense_tx=None,
                  mesh=None,
                  on_mismatch: Optional[str] = None,
                  escalate_after: Optional[int] = None,
                  metrics_logger=None,
                  metrics_interval: int = 100,
                  on_step: Optional[Callable] = None,
                  exit_on_preempt: bool = False,
                  save_on_exit: bool = True,
                  is_chief: Optional[bool] = None,
                  telemetry_state=None,
                  telemetry_path: Optional[str] = None) -> ResilientResult:
    """Drive ``step_fn`` over ``data`` with checkpointing, preemption
    handling, auto-resume, and poisoned-batch escalation.

    Args:
      step_fn: a step built by :func:`~.trainer.make_hybrid_train_step` —
        ``step(state, cat_inputs, batch) -> (loss, state[, metrics])``.
        Build it with the non-finite guard on (the default) for the
        skip-don't-corrupt behavior this driver escalates on.
      state: freshly initialized :class:`~.trainer.HybridTrainState`; on
        auto-resume its ``dense_params`` serve as the restore template and
        the restored state replaces it.
      data: the batch source, yielding ``(cat_inputs, batch)`` pairs —
        either a callable ``data(start_step) -> iterable`` (preferred: it
        positions itself, e.g. ``RawBinaryDataset(start_batch=...)`` or a
        step-seeded generator) or a plain iterable (fast-forwarded by
        generate-and-discard). See :func:`~..utils.data.fast_forward`.
      de: the :class:`~.dist_embedding.DistributedEmbedding` (checkpoint
        streaming + input policies).
      checkpoint_dir: atomic train-state checkpoint directory; ``None``
        disables checkpointing, resume, and the preemption save (the
        preempt flag then just stops the loop).
      checkpoint_every_steps: save every N *absolute* steps (cadence stays
        aligned across resumes); 0 disables the step cadence.
      checkpoint_every_s: save when this much wall-clock passed since the
        last save (preemption-prone fleets bound their lost work this
        way); 0 disables the time cadence.
      until_step: stop once ``state.step`` reaches this absolute step
        (resume-friendly alternative to sizing the iterator).
      resume: restore from ``checkpoint_dir`` when a valid checkpoint (or
        its ``.prev`` fallback) exists; requires ``emb_optimizer`` and
        ``dense_tx`` (the :func:`~..utils.checkpoint.restore_train_state`
        arguments).
      on_mismatch: restore policy when the checkpoint was written under a
        DIFFERENT sharding plan / world size than ``de`` — the elastic
        topology path: a run preempted on 16 chips that comes back on 8
        builds its ``de``/mesh for 8 and the restore re-shards the
        logical tables in place (``"reshard"``) instead of dying. Default
        ``None`` follows ``DETPU_ON_MISMATCH`` (which defaults to
        ``"reshard"``); pass ``"error"`` for the strict pre-elastic
        behavior. Every re-shard is logged as a degradation — warning log
        plus a ``checkpoint_reshard`` record (old plan, new plan,
        per-rank byte deltas) in ``metrics_logger`` when one is given.
        After the re-shard point the run is checkpoint-CRC-deterministic
        again: two resumes onto the same shrunken mesh write identical
        checkpoints.
      escalate_after: consecutive non-finite-loss steps before
        :class:`~..utils.runtime.NonFiniteLossError`; default
        ``DETPU_NANGUARD_K`` (3). The state is checkpointed first — under
        the guard it still holds the last good values.
      metrics_logger: chief-side :class:`~..utils.obs.MetricsLogger`; when
        the step returns metrics, every process joins the collective
        :func:`~..utils.obs.fetch_metrics` each ``metrics_interval`` steps
        and the chief logs the record.
      on_step: ``on_step(step, loss, metrics, state) -> stop`` host
        callback after each step (eval cadence, printing, early stop) —
        truthy return stops the loop cleanly.
      exit_on_preempt: after the preemption checkpoint+sentinel, call
        ``sys.exit(PREEMPT_EXIT_CODE)`` instead of returning. Ignored
        without ``checkpoint_dir`` — exit code 83 asserts a checkpoint
        exists to requeue on; an uncheckpointed preemption returns a
        normal ``preempted=True`` result instead.
      save_on_exit: checkpoint once more on clean completion (and clear
        the resume sentinel).
      is_chief: multi-host chief override (default: process 0 writes).
      telemetry_state: jit-carried access-telemetry state
        (:func:`~..analysis.telemetry.init_telemetry`) for a ``step_fn``
        built with ``telemetry=`` on — the driver then calls
        ``step_fn(state, cat_inputs, batch, telem)``, threads the
        returned (last-element) telemetry state, and FLUSHES a host
        summary (:func:`~..analysis.telemetry.summarize_telemetry`) plus
        the raw state (``<path>.state.npz``) alongside every checkpoint;
        on auto-resume the saved state is restored into the provided
        (fresh) template, so an interrupted+resumed run CONTINUES the
        accumulation — hot-row/skew reports survive preemption exactly
        like the train state does. The final state rides back on
        ``ResilientResult.telemetry``.
      telemetry_path: where the flushed summary JSON goes; defaults to
        ``<checkpoint_dir>.telemetry.json`` (atomic tmp+rename, chief
        only). With neither a path nor a checkpoint dir, telemetry is
        threaded but never flushed.

    Returns:
      :class:`ResilientResult`. Never returns on preemption when
      ``exit_on_preempt=True``.
    """
    if checkpoint_dir is None and resume:
        resume = False
    if escalate_after is None:
        escalate_after = obs.nanguard_escalation_k()
    if on_mismatch is None:
        on_mismatch = envvars.get("DETPU_ON_MISMATCH")

    if is_chief is None:
        def _chief() -> bool:
            import jax
            return jax.process_index() == 0
    else:
        def _chief() -> bool:
            return bool(is_chief)

    if telemetry_path is None and checkpoint_dir is not None:
        telemetry_path = checkpoint_dir.rstrip(os.sep) + ".telemetry.json"

    # ---- auto-resume -----------------------------------------------------
    ckpt_meta = os.path.join(checkpoint_dir, "meta.json") \
        if checkpoint_dir else None
    have_ckpt = checkpoint_dir is not None and (
        os.path.isfile(ckpt_meta)
        or os.path.isdir(checkpoint_dir + ".prev"))
    if resume and have_ckpt:
        if emb_optimizer is None or dense_tx is None:
            raise ValueError(
                "run_resilient(resume=True) with an existing checkpoint "
                "needs emb_optimizer= and dense_tx= to rebuild the state")
        runtime.fault_point("driver.resume")
        # events are process-global: discard any reshard recorded by an
        # earlier unrelated restore so the drain below sees only OURS
        obs.drain_events("checkpoint_reshard")
        state = restore_train_state(
            checkpoint_dir, de, emb_optimizer, state.dense_params,
            dense_tx, mesh=mesh, on_mismatch=on_mismatch)
        logger.info("run_resilient: resumed at step %d from %s",
                    int(state.step), checkpoint_dir)
        for ev in obs.drain_events("checkpoint_reshard"):
            # degraded elastic resume: surface it loudly and durably —
            # the run continues, but capacity/placement changed underneath
            diff = ev.get("diff", {})
            logger.warning(
                "run_resilient: resumed onto a DIFFERENT topology (world "
                "%s -> %s, strategy %s -> %s, per-rank byte deltas %s) — "
                "re-sharded in place, continuing degraded",
                *diff.get("world_size", [None, None]),
                *diff.get("strategy", [None, None]),
                diff.get("per_rank_byte_deltas"))
            if metrics_logger is not None and _chief():
                metrics_logger.log_event(
                    "checkpoint_reshard", step=int(state.step), diff=diff)
        if telemetry_state is not None and telemetry_path is not None \
                and os.path.isfile(telemetry_path + ".state.npz"):
            from ..analysis import telemetry as tel
            telemetry_state = tel.restore_telemetry_state(
                telemetry_path + ".state.npz", telemetry_state)

    start_step = int(state.step)
    batches = fast_forward(data, start_step)

    saves = 0
    last_save_t = time.monotonic()

    def _flush_telemetry():
        if telemetry_state is None or telemetry_path is None \
                or not _chief():
            return
        from ..analysis import telemetry as tel
        try:
            summary = tel.summarize_telemetry(de, telemetry_state)
            tmp = telemetry_path + ".tmp"
            with open(tmp, "w", encoding="utf-8") as f:
                json.dump(dict(summary, time=time.time()), f)
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, telemetry_path)
            tel.save_telemetry_state(_telemetry_state_path(),
                                     telemetry_state)
        except Exception:  # noqa: BLE001 - telemetry is auxiliary: a flush
            # failure (summarize bug, disk full, read-only fs) must not
            # kill an otherwise healthy training run
            logger.exception("run_resilient: telemetry flush failed")

    def _telemetry_state_path() -> str:
        # raw carried-state sidecar beside the summary, so a resumed run
        # CONTINUES the accumulation instead of restarting from zero
        return telemetry_path + ".state.npz"

    def _save():
        nonlocal saves, last_save_t
        runtime.fault_point("driver.save")
        save_train_state(checkpoint_dir, de, state, is_chief=is_chief)
        _flush_telemetry()
        saves += 1
        last_save_t = time.monotonic()

    def _sentinel(write: bool, **fields):
        if checkpoint_dir is None or not _chief():
            return
        path = resume_sentinel_path(checkpoint_dir)
        if not write:
            if os.path.exists(path):
                os.remove(path)
            return
        tmp = path + ".tmp"
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump(dict(fields, time=time.time()), f)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)

    step = start_step - 1
    steps_run = 0
    skipped = 0
    consecutive = 0
    last_good = start_step - 1
    last_loss: Optional[float] = None
    preempted = False
    stop_reason = "exhausted"
    check_ids = (de is not None
                 and (de.invalid_id_policy == "raise"
                      or de.ragged_overflow_raise))
    t0 = time.monotonic()

    with _PreemptCatcher() as catcher:
        for step, item in enumerate(batches, start=start_step):
            if until_step is not None and step >= until_step:
                stop_reason = "until_step"
                break
            runtime.fault_point("driver.step")
            if runtime.preempt_step() == step:
                # the preemption drill: a REAL self-SIGTERM at this step
                # boundary, caught by the handler like any external one
                os.kill(os.getpid(), signal.SIGTERM)
            try:
                cat_inputs, batch = item
            except (TypeError, ValueError) as e:
                raise ValueError(
                    "run_resilient data must yield (cat_inputs, batch) "
                    f"pairs; got {type(item).__name__}") from e
            if check_ids:
                de.check_inputs(cat_inputs)

            if telemetry_state is not None:
                # telemetry-threaded steps return the carried state LAST
                out = step_fn(state, cat_inputs, batch, telemetry_state)
                telemetry_state = out[-1]
                out = out[:-1]
            else:
                out = step_fn(state, cat_inputs, batch)
            loss, state = out[0], out[1]
            metrics = out[2] if len(out) > 2 else None
            steps_run += 1

            # ---- host view of the on-device guard ------------------------
            last_loss = _as_float(loss)
            skipped_now = not math.isfinite(last_loss)
            if not skipped_now and metrics is not None \
                    and "skipped_steps" in metrics:
                # the guard can also skip on non-finite GRADIENT energy
                # with a finite loss — the on-device flag is the
                # authoritative verdict when the step is instrumented
                skipped_now = float(
                    np.asarray(metrics["skipped_steps"]).max()) > 0
            if not skipped_now:
                consecutive = 0
                last_good = step
            else:
                consecutive += 1
                skipped += 1
                obs.counter_inc("nonfinite_steps")
                logger.warning(
                    "run_resilient: non-finite step %d (loss %r, "
                    "%d consecutive; guard %s)", step, last_loss,
                    consecutive,
                    "on" if obs.nanguard_enabled() else "OFF")
                if consecutive >= escalate_after:
                    if checkpoint_dir is not None:
                        # under the guard the state still holds the last
                        # good values — park them before dying
                        _save()
                    raise runtime.NonFiniteLossError(
                        f"non-finite loss/gradients for {consecutive} "
                        f"consecutive steps (through step {step}); last "
                        "good step: "
                        f"{last_good}. Params/optimizer state are held at "
                        "the last good values"
                        + (f" and checkpointed to {checkpoint_dir!r}"
                           if checkpoint_dir else "")
                        + (" (DETPU_NANGUARD=0: updates were NOT guarded "
                           "— the saved state may be poisoned)"
                           if not obs.nanguard_enabled() else ""))

            # ---- metrics / escalations ----------------------------------
            if metrics is not None:
                if de is not None and de.ragged_overflow_raise:
                    overflow = float(np.asarray(
                        metrics["id_overflow"]).sum())
                    if overflow > 0:
                        raise runtime.InvalidInputError(
                            f"step {step}: {int(overflow)} ragged id(s) "
                            "overflowed their static capacity "
                            "(ragged_overflow_raise)")
                if (metrics_interval
                        and step % metrics_interval == 0):
                    host_metrics = obs.fetch_metrics(metrics)
                    if metrics_logger is not None:
                        metrics_logger.log_step(host_metrics, step=step)

            if on_step is not None and on_step(step, last_loss, metrics,
                                               state):
                stop_reason = "on_step"
                break

            # ---- checkpoint cadence -------------------------------------
            if checkpoint_dir is not None and not catcher.fired:
                due_steps = (checkpoint_every_steps
                             and (step + 1) % checkpoint_every_steps == 0)
                due_time = (checkpoint_every_s
                            and time.monotonic() - last_save_t
                            >= checkpoint_every_s)
                if due_steps or due_time:
                    _save()

            # ---- preemption: finish-step -> checkpoint -> sentinel ------
            if catcher.fired:
                preempted = True
                stop_reason = "preempted"
                if checkpoint_dir is not None:
                    _save()
                    _sentinel(True, step=int(state.step),
                              signal=int(catcher.fired),
                              reason="preempted")
                break

    elapsed = time.monotonic() - t0
    if not preempted:
        runtime.fault_point("driver.final")
        if checkpoint_dir is not None and save_on_exit:
            _save()
        else:
            _flush_telemetry()  # no final checkpoint, but the report
            # should still reflect the completed run
        _sentinel(False)

    result = ResilientResult(
        state=state, step=int(state.step), steps_run=steps_run,
        preempted=preempted, skipped_steps=skipped,
        checkpoints_saved=saves, last_loss=last_loss,
        stop_reason=stop_reason, elapsed_s=elapsed,
        telemetry=telemetry_state)
    if preempted and exit_on_preempt and checkpoint_dir is not None:
        # exit code 83 asserts "checkpointed, requeue me" — only true
        # when a checkpoint dir exists; an uncheckpointed preemption
        # returns normally so the caller can wind down gracefully
        logger.warning(
            "run_resilient: preempted at step %d — checkpointed, exiting "
            "with code %d", result.step, PREEMPT_EXIT_CODE)
        sys.exit(PREEMPT_EXIT_CODE)
    return result

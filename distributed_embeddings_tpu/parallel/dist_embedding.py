"""Hybrid-parallel distributed embedding over a TPU mesh.

TPU-native re-design of the reference's ``DistributedEmbedding``
(``distributed_embeddings/python/layers/dist_model_parallel.py:199-505``).
The capability surface is the same — model-parallel tables + data-parallel
dense layers stitched by two all-to-alls per step — but the execution model is
JAX SPMD instead of Horovod MPMD:

* **One program, W mesh positions.** The reference runs one Python process per
  GPU, each building only its local tables. Here a single program runs on every
  device inside ``jax.shard_map``; per-rank table heterogeneity is *data*, not
  program: the exchange is laid out as rank-uniform group regions at static
  offsets, and small per-rank plan tensors (``parallel/plan.py``) indexed by
  ``lax.axis_index`` tell each device which table rows its slots read. One
  compiled program serves every rank — O(#groups) heavy HLO ops, independent
  of world size and table count (an earlier design's ``lax.switch`` over
  rank-specialized branches compiled O(world x tables) HLO and hit a
  compile-time cliff at the 2002-table colossal scale).
* **Parameters as width-grouped, lane-packed stacked tables.** Each rank's
  tables of width ``w`` stack row-major into one 2-D slab, and narrow widths
  pack ``p = 128//w`` logical rows per 128-lane physical row
  (``ops/packed_slab.py``): the global parameter is a dict
  ``{width: [world, phys_cap_w, phys_w]}`` sharded over the mesh axis, where
  ``phys_w = 128`` for ``w < 128`` and ``w`` otherwise. Full-tile rows are
  the layout XLA's TPU backend has fast row-gather/scatter paths for
  (measured ~10/15 ns per row vs ~22/100 ns for sub-tile rows — see
  ``docs/perf_tpu.md``), and the width grouping gives SPMD-uniform pytree
  shapes across ranks (padding rows absorb imbalance). This replaces the
  reference's per-rank ``tf.Variable`` lists.
* **Collectives.** ``hvd.alltoall(splits=...)`` (variable splits,
  ``dist_model_parallel.py:282``) has no ragged JAX primitive on every backend,
  so id blocks are padded to the max per-rank split and exchanged with
  ``lax.all_to_all`` — ids are cheap. The mp→dp output exchange
  (``dist_model_parallel.py:301``) pads widths to the max per-rank output width.
  Autodiff of ``all_to_all`` provides the backward exchange exactly like
  Horovod's registered alltoall gradient.

Input contract (distributed path): per feature either a dense int array
(``[local_batch]`` or ``[local_batch, hotness]``), a static-capacity
:class:`~..ops.embedding_lookup.Ragged` (values ``[cap]``, row_splits
``[local_batch+1]``; combiner required), or a
:class:`~..ops.embedding_lookup.SparseIds` COO batch (converted to CSR on
entry — beyond the reference, whose distributed path is dense-only while its
local layers accept sparse). Identical batch and capacities on every rank. **Ids must lie in ``[0, input_dim)``** — same contract as the
reference (TF's gather on out-of-range ids is undefined on GPU). Out-of-range
ids here are clipped in the forward (a safety net so a bad id cannot read a
neighbouring table in the slab) but routed to the dropped sentinel in the
sparse backward, so a clipped id trains nothing: don't rely on the clip. Ragged features travel inside the padded id all-to-all as
``[values(cap), lengths(b)]`` blocks — the variable-hotness capability the
reference reaches through its custom kernel (``embedding_lookup_ops.py:79-80``).

**Module layout.** This file is the orchestrator: parameter/layout
ownership, input normalization, checkpointing, metrics, telemetry, and
streaming. The step's executor phases live in three sibling modules the
:class:`~.schedule.StepSchedule` names — :mod:`.exchange` (block
assembly + the three all-to-alls), :mod:`.lookup` (plan-driven gathers
and combiners), and :mod:`.apply` (the manual sparse backward + the
per-width optimizer scatters). The split is pure code motion from the
former monolith: the traced step — and therefore the compiled HLO, the
census pass budgets, and the trajectory CRCs — is bit-for-bit unchanged.
"""

from __future__ import annotations

import functools
import os
from typing import Any, Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from flax import struct
from jax import lax

from .. import compat
from ..utils import obs
from ..utils import runtime as _runtime
from ..layers.embedding import default_embeddings_init
from ..ops.embedding_lookup import Ragged, SparseIds, row_to_split
from ..ops import packed_slab as ps
from . import apply as apply_mod
from . import exchange as exchange_mod
from . import lookup as lookup_mod
from . import plan as plan_mod
from . import schedule as schedule_mod
from .strategy import DistEmbeddingStrategy

EmbedParams = Dict[str, jax.Array]

# Checkpoint streaming chunk: 128M elements, the reference's scatter-update
# chunk size (``dist_model_parallel.py:362-380``); also keeps every single
# host<->device transfer below the 2^31-element indexing cliff the reference
# engineered around (``:388-409,426-438``).
CHECKPOINT_CHUNK_ELEMS = 128 * 1024 * 1024


@functools.partial(jax.jit, donate_argnums=0)
def _write_rows(buf: jax.Array, chunk: jax.Array, start) -> jax.Array:
    """Donated row-range write into a shard buffer (in-place on backends with
    donation; at worst one transient shard copy)."""
    return lax.dynamic_update_slice(buf, chunk, (start, 0))


@struct.dataclass
class MpInputs:
    """Model-parallel input batch (``dp_input=False``).

    The reference's mp-input mode feeds each rank its *local* tables' ids for
    the full global batch, skipping the dp→mp id all-to-all entirely
    (``dist_model_parallel.py:213,267-288``; the DLRM example's default input
    path, ``examples/dlrm/main.py:57,161-190``). In SPMD form that per-rank
    block is exactly the ``ids_recv`` layout the dp path's all-to-all would
    have produced, packed once on host by :meth:`DistributedEmbedding.pack_mp_inputs`:

    * ``packed``: ``[world_dest, world_src, l_max]`` globally (shard over the
      mesh axis on dim 0; inside ``shard_map`` each device sees
      ``[1, world_src, l_max]``). Row ``[r, s]`` holds source-shard ``s``'s
      local batch of ids for every input owned by rank ``r``, laid out in the
      rank-uniform group-region format of ``parallel/plan.py`` (the same
      layout the dp path's id all-to-all produces).
    * ``hots``: static per-global-input encoding — an int (dense hotness) or
      ``("r", capacity)`` for a ragged feature. Must be globally known (the
      exchange layout is derived from it).
    * ``local_batch``: static per-shard batch size ``b``.
    """

    packed: jax.Array
    hots: tuple = struct.field(pytree_node=False)
    local_batch: int = struct.field(pytree_node=False)


def _wkey(width: int) -> str:
    return f"w{width}"


def _pvary(x: jax.Array, axis_name: str) -> jax.Array:
    """Mark a constant as device-varying over ``axis_name`` so it can join
    varying values in collectives/switch branches under VMA typing (identity
    on pre-VMA jax — see :mod:`..compat`)."""
    return compat.pvary(x, axis_name)


class DistributedEmbedding:
    """Shards embedding tables across a mesh axis and exchanges activations
    with two all-to-alls per step.

    Args:
      embeddings: list of :class:`...layers.Embedding` modules or config dicts
        (``input_dim``, ``output_dim``, optional ``combiner``,
        ``embeddings_initializer``).
      world_size: mesh-axis size (model-parallel positions == data-parallel
        positions, as in the reference).
      strategy: ``basic | memory_balanced | memory_optimized |
        comm_balanced | telemetry_balanced`` (``comm_balanced`` balances
        per-(width, inputs) table counts so the padded output exchange
        wastes the fewest bytes; ``telemetry_balanced`` balances measured
        per-table traffic and needs ``table_loads`` — see
        ``parallel/strategy.py``).
      column_slice_threshold: max elements per slice; larger tables are split
        width-wise into power-of-2 slices.
      row_slice: max elements per table slice for ROW-wise (vocab-range)
        slicing — the mode the reference declares but never implements
        (``dist_model_parallel.py:225,233-234``; its docstring leaves the
        type "TBD", so an int threshold mirroring
        ``column_slice_threshold`` is used here). Tables over the threshold
        split into power-of-2 row-range slices placed like any other table;
        each slice serves only ids in its range (out-of-range ids read zero
        rows forward and drop backward) and the slice outputs sum. A table
        already split by ``column_slice_threshold`` is not row-sliced.
      masked_reads: if True, out-of-range ids on NON-sliced tables read a
        ZERO row in the forward instead of clipping into the last row
        (out-of-range backward always drops). Costs one compare+select per
        gathered row; makes bad-pipeline ids visible as zeros instead of
        silently training on the clipped row's values. Row-sliced tables
        use masked reads regardless (their correctness depends on it).
      invalid_id_policy: what negative / out-of-vocab ids do — the single
        ingestion-point policy for every input path (dense, ragged,
        sparse, mp-packed):

        * ``'clamp'`` (default, the historical behavior): the forward
          READ clamps into the table (negatives read row 0, overflow
          reads the last row) and the backward drops the id — a bad id
          reads a defined row but trains nothing.
        * ``'drop'``: invalid ids contribute a ZERO row forward and drop
          backward (forces ``masked_reads``) — a bad id neither reads
          nor trains anything.
        * ``'raise'``: eager (host-visible) ingestion —
          :meth:`check_inputs`, called automatically on concrete inputs
          and by the resilient driver before each dispatch — raises
          :class:`~...utils.runtime.InvalidInputError` naming the input
          and the offending count. Inside an already-jitted step the ids
          are tracers; there the read behaves like ``'clamp'`` and the
          violation surfaces through the ``invalid_id_count`` step
          metric (which ``parallel.resilient.run_resilient`` escalates).

        All three policies surface the per-rank count of invalid live ids
        as ``invalid_id_count`` in :meth:`step_metrics`.
      ragged_overflow_raise: opt-in escalation for ragged batches whose
        claimed row lengths overflow their static capacity (ids silently
        truncated otherwise): :meth:`check_inputs` raises
        :class:`~...utils.runtime.InvalidInputError`, and the resilient
        driver escalates on a nonzero ``id_overflow`` metric.
      dp_input: if True (default) inputs are data-parallel shards
        ``[local_batch, ...]`` per global feature. If False, inputs are
        model-parallel: a :class:`MpInputs` built by :meth:`pack_mp_inputs`
        (each rank holds the full global batch of ids for its local tables;
        no id all-to-all runs).
      input_table_map: ``input[i]`` uses ``table[input_table_map[i]]``.
      input_hotness: optional per-input hotness hint; lets ``comm_balanced``
        model the exchange groups exactly (see ``strategy.py``).
      table_loads: per-table measured traffic weights for the
        ``telemetry_balanced`` strategy (see ``strategy.py``; derive them
        with :func:`...analysis.telemetry.table_loads_from_summary`).
      axis_name: mesh axis the executor runs under (inside ``shard_map``).
      compute_dtype: output/communication dtype. Embedding reads and combiner
        reductions stay in the parameter dtype; outputs are cast to
        ``compute_dtype`` *before* the mp→dp all-to-all — the reference's
        mixed-precision pre-comm cast (``dist_model_parallel.py:300,499``) —
        halving exchange bytes with bf16. Backward cotangents arrive in
        ``compute_dtype``, ride the reverse exchange, and are cast back up at
        the optimizer scatter. ``None`` keeps the parameter dtype end-to-end.
      schedule: the :class:`~.schedule.StepSchedule` the trainer's hybrid
        step executes and the schedule auditor certifies. ``None`` /
        ``"serialized"`` (default) is the honest serialized baseline
        (streaming layers declare their already-measured admission-staging
        overlap); ``"pipelined"`` — or an explicit
        :func:`~.schedule.pipelined_schedule` — opts into the K-microbatch
        software-pipelined step (``DETPU_MICROBATCH`` resolves K for the
        string form): the global batch splits into K chains inside one
        jitted step so microbatch ``k+1``'s exchanges overlap microbatch
        ``k``'s dense compute, with gradients accumulated so the applied
        update matches the serialized step (K=1 is bitwise the serialized
        program; the per-device batch must divide by K).
    """

    def __init__(self,
                 embeddings: Sequence[Any],
                 world_size: int,
                 strategy: str = "basic",
                 column_slice_threshold: Optional[int] = None,
                 row_slice: Optional[Any] = None,
                 dp_input: bool = True,
                 input_table_map: Optional[Sequence[int]] = None,
                 axis_name: str = "data",
                 compute_dtype: Optional[Any] = None,
                 input_hotness: Optional[Sequence[int]] = None,
                 masked_reads: bool = False,
                 invalid_id_policy: str = "clamp",
                 ragged_overflow_raise: bool = False,
                 table_loads: Optional[Sequence[float]] = None,
                 schedule=None):
        if row_slice is not None and (isinstance(row_slice, bool)
                                      or not isinstance(row_slice, int)):
            # bool subclasses int: row_slice=True would silently mean
            # threshold 1 (slice EVERY table world-ways)
            raise TypeError(
                "row_slice takes an int element threshold (the reference "
                "left the type 'TBD'; see the class docstring)")
        if invalid_id_policy not in ("clamp", "drop", "raise"):
            raise ValueError(
                f"invalid_id_policy must be 'clamp' | 'drop' | 'raise', "
                f"got {invalid_id_policy!r}")
        self.world_size = int(world_size)
        self.axis_name = axis_name
        self.dp_input = dp_input
        self.compute_dtype = compute_dtype
        self.invalid_id_policy = invalid_id_policy
        self.ragged_overflow_raise = bool(ragged_overflow_raise)
        # 'drop' rides the masked-read machinery: zero forward read,
        # dropped backward — exactly the drop semantics, per slot
        self.masked_reads = bool(masked_reads) or invalid_id_policy == "drop"
        self.strategy = DistEmbeddingStrategy(
            embeddings, self.world_size, strategy=strategy,
            input_table_map=input_table_map,
            column_slice_threshold=column_slice_threshold,
            input_hotness=input_hotness,
            row_slice_threshold=row_slice,
            table_loads=table_loads)
        if len(self.strategy.global_configs) < self.world_size:
            raise NotImplementedError(
                "Fewer tables than mesh positions is not supported "
                "(reference constraint, dist_model_parallel.py:252-253)")

        # slice multiplicity per global table (column slicing)
        self._slices_per_table = [0] * len(self.strategy.global_configs)
        for rank_ids in self.strategy.table_ids_list:
            for tid in rank_ids:
                self._slices_per_table[tid] += 1

        # streaming (dynamic-vocab) tables: {tid: (capacity, buckets)}.
        # The declared input_dim IS the physical slab footprint
        # (capacity slots + shared bucket rows), so every capacity/
        # checkpoint/re-shard subsystem prices and moves the table like
        # any static one; only the id INTERPRETATION changes (external
        # ids remap through the jit-carried slot map, parallel/
        # streaming.py). Sliced streaming tables are rejected — a slot
        # map cannot span slices.
        self.streaming_tables: Dict[int, tuple] = {}
        for tid, cfg in enumerate(self.strategy.global_configs):
            sc = cfg.get("streaming")
            if not sc:
                continue
            cap, nb = int(sc["capacity"]), int(sc["buckets"])
            if cap <= 0 or nb <= 0:
                raise ValueError(
                    f"table {tid}: streaming capacity/buckets must be "
                    f"positive, got {sc!r}")
            if cap + nb != int(cfg["input_dim"]):
                raise ValueError(
                    f"table {tid}: streaming capacity {cap} + buckets "
                    f"{nb} must equal input_dim {cfg['input_dim']} (the "
                    "slab holds the slots followed by the shared bucket "
                    "rows)")
            if self._slices_per_table[tid] != 1:
                raise NotImplementedError(
                    f"table {tid} is row/column-sliced "
                    f"({self._slices_per_table[tid]} slices): streaming "
                    "tables must stay unsliced (the slot map cannot span "
                    "slices) — raise the slice thresholds or shrink the "
                    "capacity")
            self.streaming_tables[tid] = (cap, nb)
        self._streaming_arrays_cache: Dict[int, list] = {}

        # Width-grouped stacked-table layout: per rank, tables of equal width
        # stack row-major into one 2-D slab; slab row capacity is the max over
        # ranks so the params pytree is SPMD-uniform. Narrow widths store
        # lane-PACKED (p = 128//w logical rows per physical 128-lane row, see
        # ops/packed_slab.py) so row gathers/scatters hit XLA's full-tile
        # fast path; each table starts at a physical-row boundary.
        widths = sorted({int(c["output_dim"])
                         for cfgs in self.strategy.local_configs_list
                         for c in cfgs})
        self.widths: List[int] = widths
        # row_offsets_list[rank][m] = first LOGICAL row of local table m
        self.row_offsets_list: List[List[int]] = []
        per_rank_rows = []  # [rank][width] -> logical rows used (aligned)
        for cfgs in self.strategy.local_configs_list:
            used = {w: 0 for w in widths}
            offsets = []
            for c in cfgs:
                w = int(c["output_dim"])
                offsets.append(used[w])
                used[w] += ps.align_rows(int(c["input_dim"]), w)
            self.row_offsets_list.append(offsets)
            per_rank_rows.append(used)
        self.rows_cap: Dict[int, int] = {
            w: max(max(max(r[w] for r in per_rank_rows), 1),
                   ps.pack_factor(w)) for w in widths}
        # physical slab geometry per width
        self.phys_cap: Dict[int, int] = {
            w: ps.packed_shape(ps.align_rows(self.rows_cap[w], w), w)[0]
            for w in widths}
        self.phys_w: Dict[int, int] = {w: ps.phys_width(w) for w in widths}
        self.rows_cap = {w: ps.align_rows(self.rows_cap[w], w)
                         for w in widths}
        # exchange plans are (input signature, batch)-dependent; built lazily
        self._plan_cache: Dict[tuple, plan_mod.ExchangePlan] = {}
        # the explicit step schedule the orchestrator runs and the
        # schedule auditor certifies (parallel/schedule.py): phase names,
        # declared ordering, declared overlap. The default is the honest
        # serialized baseline — with the one overlap streaming programs
        # ALREADY have (the admission-staging chain hides the out/grad
        # exchanges) declared when dynamic tables exist, so
        # tools/schedule_audit.py certifies it against the compiled DAG.
        # schedule="pipelined" (or a pipelined_schedule(K)) opts the
        # trainer into the K-microbatch latency-hiding step; K=1 and the
        # default trace the bitwise-identical serialized program.
        self.schedule = schedule_mod.resolve_schedule(
            schedule, streaming=bool(self.streaming_tables))

    # ------------------------------------------------------------------ params

    def _init_rank_width(self, key, rank: int, width: int, dtype) -> jax.Array:
        """One rank's PACKED slab for one width: per-table initializers
        stacked row-major at physical-row boundaries; column slices
        initialize independently like the reference's per-slice layers
        (``dist_model_parallel.py:256-259``).

        The *default* initializer (an elementwise uniform) is generated
        directly in the packed physical shape — reshaping a logical
        ``[rows, w]`` slab on device would force a lane-padded T(8,128)
        intermediate (8x memory for w=16, an instant OOM at zoo scale), and
        for an elementwise distribution the layout is immaterial. A
        *user-supplied* initializer keeps its documented contract: it is
        called with the logical ``(rows, w)`` shape (shape-dependent
        initializers like ``variance_scaling`` see the true fan-in/out) and
        the result is packed with strided slices, avoiding the padded
        reshape."""
        p = ps.pack_factor(width)
        pw = self.phys_w[width]
        cfgs = self.strategy.local_configs_list[rank]
        # tables write into a preallocated slab (in-place update chain under
        # jit) instead of list+concat: concat would hold all parts AND the
        # result live at once — 2x the slab in HBM, an OOM at uncapped
        # Criteo scale (8.7 GB of bf16 tables)
        buf = jnp.zeros((self.phys_cap[width], pw), dtype)
        pos = 0
        for m, cfg in enumerate(cfgs):
            if int(cfg["output_dim"]) != width:
                continue
            user_init = cfg.get("embeddings_initializer")
            rows = int(cfg["input_dim"])
            rows_al = ps.align_rows(rows, width)
            if user_init is None:
                t = default_embeddings_init(
                    jax.random.fold_in(key, m),
                    (rows_al // p, p * width), dtype)
            else:
                t = user_init(jax.random.fold_in(key, m), (rows, width),
                              dtype)
                if rows_al - rows:
                    t = jnp.concatenate(
                        [t, jnp.zeros((rows_al - rows, width), dtype)])
                if p > 1:  # pack: phys row i, lane j <- logical row i*p+j
                    t = jnp.concatenate([t[j::p] for j in range(p)], axis=1)
            # dynamic_update_slice would silently clamp an overrun into the
            # previous table's rows; fail loudly on planner/capacity drift
            assert pos + t.shape[0] <= self.phys_cap[width], (
                width, pos, t.shape, self.phys_cap[width])
            buf = lax.dynamic_update_slice(buf, t.astype(dtype), (pos, 0))
            pos += t.shape[0]
        return buf

    def init(self, key, dtype=jnp.float32, mesh=None) -> EmbedParams:
        """Build the global param dict ``{width: [world, rows_cap, width]}``.

        With ``mesh`` given, each device's shard is initialized by its own
        small program and assembled with
        ``jax.make_array_from_single_device_arrays`` — no single jit ever
        materializes more than one rank's slab (the reference forces huge
        inits off-accelerator for the same reason, ``embedding.py:28-38``),
        and on multi-host meshes each process initializes only its
        addressable shards.

        Fast path: a width group whose tables ALL use the default initializer
        (an elementwise uniform) is generated as ONE partitioned
        ``jax.random.uniform`` over the whole ``[world, phys_cap, phys_w]``
        slab — one small compile regardless of table count (the per-table
        path compiles O(tables) HLO per device and dominated colossal-scale
        startup). Layout padding rows/lanes then hold random values instead
        of zeros; nothing reads them (forward clips ids in-table, checkpoint
        paths slice exact row ranges).
        """
        keys = jax.random.split(key, self.world_size)

        default_widths = {
            w: all(c.get("embeddings_initializer") is None
                   for cfgs in self.strategy.local_configs_list
                   for c in cfgs if int(c["output_dim"]) == w)
            for w in self.widths}

        def fast_uniform(w, sharding=None):
            shape = (self.world_size, self.phys_cap[w], self.phys_w[w])
            fn = jax.jit(
                lambda k: default_embeddings_init(k, shape, dtype),
                **({"out_shardings": sharding} if sharding is not None else {}))
            return fn(jax.random.fold_in(key, w))

        if mesh is None:
            out = {}
            slow = [w for w in self.widths if not default_widths[w]]
            for w in self.widths:
                if default_widths[w]:
                    out[_wkey(w)] = fast_uniform(w)
            if slow:
                def build():
                    return {
                        _wkey(w): jnp.stack([
                            self._init_rank_width(keys[r], r, w, dtype)
                            for r in range(self.world_size)])
                        for w in slow}
                out.update(jax.jit(build)())
            return out

        out = {}
        for w in self.widths:
            if default_widths[w]:
                sharding = jax.sharding.NamedSharding(
                    mesh, jax.sharding.PartitionSpec(self.axis_name))
                out[_wkey(w)] = fast_uniform(w, sharding)
                continue

            def init_shard(dev, r0, r1, w=w):
                def build(ks):
                    return jnp.stack([
                        self._init_rank_width(ks[r], r, w, dtype)
                        for r in range(r0, r1)])
                with jax.default_device(dev):
                    shard = jax.jit(build)(keys)
                # default_device does not bind committed inputs (a committed
                # PRNG key would drag every shard to its own device); commit
                # the result explicitly (no-copy when already on dev)
                return jax.device_put(shard, dev)

            out[_wkey(w)] = self._assemble_sharded(mesh, w, init_shard)
        return out

    def _assemble_sharded(self, mesh, width: int, build_shard) -> jax.Array:
        """Assemble one width's global packed ``[world, phys_cap, phys_w]``
        slab from per-device shards built by ``build_shard(dev, r0, r1)`` —
        only this process's addressable shards are materialized (multi-host
        safe)."""
        shape = (self.world_size, self.phys_cap[width], self.phys_w[width])
        sharding = jax.sharding.NamedSharding(
            mesh, jax.sharding.PartitionSpec(self.axis_name))
        arrays = []
        for dev, idx in sharding.devices_indices_map(shape).items():
            if dev.process_index != jax.process_index():
                continue
            r0, r1, _ = idx[0].indices(self.world_size)
            arrays.append(build_shard(dev, r0, r1))
        return jax.make_array_from_single_device_arrays(
            shape, sharding, arrays)

    def local_view(self, params: EmbedParams) -> EmbedParams:
        """Squeeze the leading world axis of per-device slabs
        (``[1, rows, w]`` inside shard_map / world_size==1 → ``[rows, w]``).
        Tree-mapped so nested optimizer state (e.g. Adam's ``(m, v, t)``)
        squeezes leaf-wise."""
        return jax.tree.map(
            lambda v: (v.reshape(v.shape[-2], v.shape[-1])
                       if hasattr(v, "ndim") and v.ndim == 3 else v), params)

    def stacked_view(self, params: EmbedParams) -> EmbedParams:
        """Re-add the leading world axis for P(axis) out_specs."""
        return jax.tree.map(
            lambda v: (v.reshape(1, *v.shape)
                       if hasattr(v, "ndim") and v.ndim == 2 else v), params)

    def _table_rows(self, rank: int, m: int):
        cfg = self.strategy.local_configs_list[rank][m]
        w = int(cfg["output_dim"])
        roff = self.row_offsets_list[rank][m]
        return _wkey(w), roff, int(cfg["input_dim"]), w

    # ----------------------------------------------------------------- forward

    @staticmethod
    def _dense_enc(shape, comb) -> tuple:
        """Static routing descriptor of a dense input: ``("d", hotness,
        num_slots)``. With a combiner the LAST dim is the reduced hotness
        and every lead position beyond the batch becomes its own slot (the
        reference flattens N-D inputs through its exchange and lets the
        local layer reduce the trailing dim, ``dist_model_parallel.py:
        273-288`` + ``embedding.py:115-132``); without one, every id is a
        hotness-1 slot."""
        dims = tuple(int(d) for d in shape[1:])
        if comb:
            h = dims[-1] if dims else 1
            ns = int(np.prod(dims[:-1], dtype=np.int64)) if len(dims) > 1 \
                else 1
            return ("d", h, ns)
        ns = int(np.prod(dims, dtype=np.int64)) if dims else 1
        return ("d", 1, ns)

    @staticmethod
    def _enc_of_hot(h) -> tuple:
        """MpInputs ``hots`` entry -> routing descriptor: an int is a 2-D
        dense hotness; tuples pass through (``("r"|"rw", cap)`` ragged,
        ``("d", hot, num_slots)`` N-D dense)."""
        if isinstance(h, (tuple, list)):
            k = h[0]
            if k == "d":
                return ("d", int(h[1]), int(h[2]) if len(h) > 2 else 1)
            return (k, int(h[1]))
        return ("d", int(h), 1)

    @staticmethod
    def _weight_bits(weights, cap: int, comm_dtype) -> jax.Array:
        """Per-id float weights -> int payload that rides the id exchange
        (bitcast f32->i32; widening to an int64 block preserves the bits)."""
        w = jnp.asarray(weights).astype(jnp.float32).reshape(cap)
        return lax.bitcast_convert_type(w, jnp.int32).astype(comm_dtype)

    def check_inputs(self, inputs) -> Optional[int]:
        """Eager (host-side) ingestion validation — the enforcement point
        of ``invalid_id_policy='raise'`` and ``ragged_overflow_raise``.

        Counts negative / out-of-vocab ids per input against the GLOBAL
        table vocab, and ragged row lengths claiming more ids than their
        static capacity. Under the ``'raise'`` policy any invalid id
        raises :class:`~...utils.runtime.InvalidInputError` naming the
        input and the offending range; with ``ragged_overflow_raise`` any
        capacity overflow does too. ``None`` entries (multi-host
        ``pack_mp_inputs`` partial batches) are skipped.

        Returns the total invalid-id count, or ``None`` when any input is
        a tracer — inside a jitted step nothing can be read eagerly; there
        the in-step ``invalid_id_count`` / ``id_overflow`` metrics carry
        the signal and the resilient driver escalates on the host.

        Cost: one device→host fetch per input when ids live on device —
        the price the ``'raise'`` policy opts into (call it from the input
        pipeline, where ids are still host numpy, to pay nothing).
        """
        import jax.core as _jcore

        if isinstance(inputs, MpInputs):
            # already validated id-by-id inside pack_mp_inputs (host
            # numpy); the packed block cannot be re-attributed to inputs
            return None
        if len(inputs) != self.strategy.num_inputs:
            raise ValueError(
                f"Expected {self.strategy.num_inputs} inputs, "
                f"got {len(inputs)}")
        total = 0
        for i, inp in enumerate(inputs):
            if inp is None:
                continue
            tid = self.strategy.input_table_map[i]
            vocab = int(self.strategy.global_configs[tid]["input_dim"])
            if isinstance(inp, SparseIds):
                arrs = (inp.values, inp.indices)
                values, splits, cap = inp.values, None, None
            elif isinstance(inp, Ragged):
                arrs = (inp.values, inp.row_splits)
                values, splits = inp.values, inp.row_splits
                cap = int(np.shape(inp.values)[0])
            else:
                arrs = (inp,)
                values, splits, cap = inp, None, None
            if any(isinstance(a, _jcore.Tracer) for a in arrs):
                return None
            ids = np.asarray(values)
            if isinstance(inp, SparseIds):
                # padding positions are marked by row >= dense_shape[0]
                # and carry ARBITRARY values (the SparseIds contract) —
                # only live positions are checkable
                rows_coo = np.asarray(inp.indices)
                if rows_coo.ndim == 2:
                    rows_coo = rows_coo[:, 0]
                ids = ids[rows_coo < inp.dense_shape[0]]
            if splits is not None:
                sp = np.asarray(splits)
                nnz = int(sp.reshape(-1)[-1])
                if nnz > cap:
                    total += nnz - cap
                    if self.ragged_overflow_raise:
                        raise _runtime.InvalidInputError(
                            f"input {i}: ragged row lengths claim {nnz} "
                            f"ids > static capacity {cap} — "
                            f"{nnz - cap} id(s) would be silently "
                            "truncated (ragged_overflow_raise)")
                ids = ids.reshape(-1)[:min(nnz, cap)]
            if tid in self.streaming_tables:
                # streaming tables accept the UNBOUNDED external id
                # space by design (the slot map hashes them in-range);
                # only negatives are invalid
                bad = int((ids < 0).sum())
            else:
                bad = int(((ids < 0) | (ids >= vocab)).sum())
            if bad:
                total += bad
                if self.invalid_id_policy == "raise":
                    raise _runtime.InvalidInputError(
                        f"input {i} (table {tid}): {bad} id(s) outside "
                        f"[0, {vocab}) — min {int(ids.min())}, max "
                        f"{int(ids.max())} — under invalid_id_policy="
                        "'raise'")
        return total

    def _normalize_inputs(self, inputs):
        """Promote to a common int dtype; dense inputs flatten to 2-D
        ``[batch, -1]``, :class:`~..ops.embedding_lookup.Ragged` inputs
        become ``("r"|"rw", values [cap], lengths [batch][, weight_bits])``
        records. Returns ``(entries, encs, shapes)`` where ``encs[i]`` is
        the static routing descriptor (``("d", hotness, num_slots)`` /
        ``("r"|"rw", capacity)``, the key the exchange plan is built from)
        and ``shapes[i]`` is the original dense shape (``None`` for
        ragged) so single-worker lookups preserve the reference's local
        output ranks."""
        if len(inputs) != self.strategy.num_inputs:
            raise ValueError(
                f"Expected {self.strategy.num_inputs} inputs, got {len(inputs)}")
        if self.invalid_id_policy == "raise" or self.ragged_overflow_raise:
            # the single ingestion point: eager callers (and the mp pack)
            # get host-side raises; traced callers fall through to the
            # invalid_id_count / id_overflow metrics (check_inputs
            # returns None on tracers)
            self.check_inputs(inputs)
        # COO sparse rides the ragged path: row ids -> CSR row_splits, the
        # same conversion the op layer's dispatcher does
        # (ops/embedding_lookup.py:row_to_split; reference
        # embedding_lookup_ops.py:90-96)
        inputs = [
            Ragged(values=inp.values,
                   row_splits=row_to_split(inp.indices, inp.dense_shape[0],
                                           dtype=inp.values.dtype),
                   weights=inp.weights)
            if isinstance(inp, SparseIds) else inp
            for inp in inputs]
        comm_dtype = jnp.int32
        for inp in inputs:
            arrs = ((inp.values, inp.row_splits) if isinstance(inp, Ragged)
                    else (inp,))
            if any(jnp.asarray(a).dtype == jnp.int64 for a in arrs):
                comm_dtype = jnp.int64
        out, encs, shapes = [], [], []
        for i, inp in enumerate(inputs):
            tid = self.strategy.input_table_map[i]
            comb = self.strategy.global_configs[tid].get("combiner")
            if isinstance(inp, Ragged):
                if not comb:
                    raise ValueError(
                        f"Ragged input {i} requires its table to have a "
                        "combiner (reference routes multi-hot ragged through "
                        "the combining kernel, embedding_lookup_ops.py:79-80)")
                values = jnp.asarray(inp.values).astype(comm_dtype)
                splits = jnp.asarray(inp.row_splits)
                lengths = (splits[1:] - splits[:-1]).astype(comm_dtype)
                cap = int(values.shape[0])
                if inp.weights is not None:
                    out.append(("rw", values, lengths,
                                self._weight_bits(inp.weights, cap,
                                                  comm_dtype)))
                    encs.append(("rw", cap))
                else:
                    out.append(("r", values, lengths))
                    encs.append(("r", cap))
                shapes.append(None)
            else:
                inp = jnp.asarray(inp).astype(comm_dtype)
                shapes.append(tuple(inp.shape))
                encs.append(self._dense_enc(inp.shape, comb))
                out.append(inp.reshape(inp.shape[0], -1) if inp.ndim != 1
                           else inp[:, None])
        return out, encs, shapes

    def pack_mp_inputs(self, inputs, dtype=None, mesh=None,
                       hots: Optional[Sequence[Any]] = None,
                       local_batch: Optional[int] = None,
                       as_numpy: bool = False) -> MpInputs:
        """Pack per-feature global-batch ids into :class:`MpInputs`.

        ``inputs[i]`` is ``[global_batch]`` / ``[global_batch, hotness]``
        dense ids, or a :class:`~..ops.embedding_lookup.Ragged` over the
        *global* batch (values ``[cap]``, row_splits ``[global_batch+1]``),
        ordered by data-parallel shard (shard ``s`` owns rows
        ``s*b:(s+1)*b``) — the natural order of a global batch. Host-side
        numpy; with ``mesh`` given the packed array is laid out sharded over
        ``axis_name`` so each device receives only its own block.

        On a multi-host data pipeline each process only needs the features its
        ranks own (reference ``examples/dlrm/main.py:166-176`` reads only the
        local tables' ``cat_*.bin``); entries for other ranks' features may be
        ``None`` — their packed blocks live on other processes' devices. In
        that case pass ``hots`` (per-input encoding of ALL inputs: an int
        hotness for dense, ``("r", per_shard_capacity)`` for ragged) and, if
        every entry is None, ``local_batch`` too: the packed layout must be
        identical on every process, so it cannot be inferred from local
        arrays alone.

        Ragged per-shard capacity: by default a global-batch ``Ragged`` input
        is packed with per-shard capacity equal to its *global* capacity
        (always safe; padded). Pass ``("r", cap)`` in ``hots`` to use a
        tighter static capacity — it must be the same on every process and
        every batch, and each shard's actual nnz must fit it (checked).

        Args:
          dtype: id dtype of the packed block; default promotes like the dp
            path (int64 if any provided array is int64, else int32).
          as_numpy: return the packed block as host numpy (no device
            conversion) — for pipeline benchmarking/staging where the
            caller owns placement. Mutually exclusive with ``mesh``.
        """
        if as_numpy and mesh is not None:
            raise ValueError("as_numpy=True returns a host array; it "
                             "cannot also be laid out on a mesh")
        world = self.world_size
        arrs = []
        for x in inputs:
            if x is None or isinstance(x, Ragged):
                arrs.append(x)
            else:
                a = np.asarray(x)
                arrs.append(a[:, None] if a.ndim == 1 else a)
        if len(arrs) != self.strategy.num_inputs:
            raise ValueError(
                f"Expected {self.strategy.num_inputs} inputs, got {len(arrs)}")
        if self.invalid_id_policy == "raise" or self.ragged_overflow_raise:
            # mp ingestion point: ids are host numpy here, so the 'raise'
            # policy costs nothing extra (None entries skipped)
            self.check_inputs(arrs)

        def glen(a):
            return (a.row_splits.shape[0] - 1 if isinstance(a, Ragged)
                    else a.shape[0])

        some = next((a for a in arrs if a is not None), None)
        if some is None:
            if local_batch is None or hots is None:
                raise ValueError(
                    "pack_mp_inputs with all-None inputs needs explicit "
                    "hots= and local_batch= (layout must match the owning "
                    "processes)")
            b = int(local_batch)
        else:
            gb = glen(some)
            if gb % world:
                raise ValueError(
                    f"Global batch {gb} not divisible by world size {world}")
            b = gb // world
            if local_batch is not None and int(local_batch) != b:
                raise ValueError(
                    f"local_batch={local_batch} contradicts inputs ({b})")
            for i, a in enumerate(arrs):
                if a is not None and glen(a) != gb:
                    raise ValueError(
                        f"Input {i} batch {glen(a)} != {gb}")
        def is64(a):
            if isinstance(a, Ragged):
                # same promotion rule as the dp path's _normalize_inputs
                return any(np.asarray(x).dtype == np.int64
                           for x in (a.values, a.row_splits))
            return a.dtype == np.int64

        if dtype is None:
            dtype = (jnp.int64 if any(a is not None and is64(a) for a in arrs)
                     else jnp.int32)

        # per-input encodings, hots-validated
        if hots is None and any(a is None for a in arrs):
            raise ValueError(
                "pack_mp_inputs with None entries needs explicit hots= "
                "(the encoding of every input must be globally known)")
        encs = []
        for i, a in enumerate(arrs):
            comb = self.strategy.global_configs[
                self.strategy.input_table_map[i]].get("combiner")
            if hots is not None:
                enc = self._enc_of_hot(hots[i])
            elif isinstance(a, Ragged):
                enc = (("rw" if a.weights is not None else "r"),
                       int(a.capacity))
            else:
                enc = self._dense_enc(a.shape, comb)
            if a is not None:
                if isinstance(a, Ragged) != (enc[0] in ("r", "rw")):
                    raise ValueError(
                        f"Input {i} encoding {enc} does not match the "
                        f"provided value type")
                if isinstance(a, Ragged) and \
                        (a.weights is not None) != (enc[0] == "rw"):
                    raise ValueError(
                        f"Input {i}: weighted ragged needs an ('rw', cap) "
                        f"hots entry, got {enc}")
                if enc[0] == "d":
                    canon = self._dense_enc(a.shape, comb)
                    # plan-equivalence, not tuple equality: without a
                    # combiner ("d", h, ns) and ("d", 1, h*ns) build the
                    # same hotness-1 slot layout (the legacy int-hots form)
                    ok = (enc[1:] == canon[1:] if comb
                          else enc[1] * enc[2] == canon[1] * canon[2])
                    if not ok:
                        raise ValueError(
                            f"Input {i} shape {a.shape} does not match "
                            f"hots[{i}]={hots[i] if hots else enc}")
            encs.append(enc)

        plan = self._get_plan(encs, b)
        np_dtype = np.dtype(jnp.dtype(dtype).name)
        packed_np = np.zeros((world, world, plan.l_max), np_dtype)
        for inst in plan.instances:
            a = arrs[inst.input_id]
            if a is None:
                continue
            g = plan.groups[inst.group]
            p0 = g.goff + inst.slot0 * g.blen
            span = inst.num_slots * g.blen
            if g.kind in ("r", "rw"):
                values = np.asarray(a.values)
                splits = np.asarray(a.row_splits)
                cap = g.hot
                for s in range(world):
                    lo, hi = int(splits[s * b]), int(splits[(s + 1) * b])
                    if hi - lo > cap:
                        raise ValueError(
                            f"Input {inst.input_id}: shard {s} nnz {hi - lo} "
                            f"exceeds per-shard capacity {cap}")
                    blk = np.zeros(g.blen, np_dtype)
                    blk[:hi - lo] = values[lo:hi]
                    blk[cap:cap + b] = np.diff(splits[s * b:(s + 1) * b + 1])
                    if g.kind == "rw":  # bitcast f32 weights into the block
                        wb = np.zeros(cap, np.float32)
                        wb[:hi - lo] = np.asarray(a.weights, np.float32
                                                  )[lo:hi]
                        blk[cap + b:] = wb.view(np.int32)
                    packed_np[inst.rank, s, p0:p0 + span] = blk
            else:
                # one vectorized slice-assign for all shards (a per-shard
                # python loop measured 10.5 ms/batch at the v5e-16 bench
                # shapes; this form is one numpy memcpy per feature)
                if inst.transposed:  # slot-major within each shard block
                    flat = (a.reshape(world, b, inst.num_slots, g.hot)
                            .transpose(0, 2, 1, 3).reshape(world, -1))
                else:
                    flat = a.reshape(world, -1)
                packed_np[inst.rank, :, p0:p0 + span] = flat
        if as_numpy:
            # host-side packing only (pipeline benchmarking / staging):
            # the caller owns the device placement
            packed = packed_np
        elif mesh is not None:
            sharding = jax.sharding.NamedSharding(
                mesh, jax.sharding.PartitionSpec(self.axis_name))
            # callback-per-shard works on multi-host meshes too: each process
            # materializes only its addressable blocks
            packed = jax.make_array_from_callback(
                packed_np.shape, sharding, lambda idx: packed_np[idx])
        else:
            packed = jnp.asarray(packed_np)
        hots_out = tuple(
            (enc[1] if enc[2] == 1 else enc) if enc[0] == "d" else enc
            for enc in encs)
        return MpInputs(packed=packed, hots=hots_out, local_batch=b)

    def __call__(self, params: EmbedParams, inputs) -> List[jax.Array]:
        """Forward pass.

        * ``world_size == 1``: plain local lookups, original output ranks
          preserved (reference ``call``, ``:493-500``).
        * distributed: must run inside ``shard_map`` with ``axis_name`` bound;
          ``params`` are this device's slabs (pass the global dict through
          ``in_specs=P(axis_name)``).
        """
        return self.forward_with_residuals(params, inputs)[0]

    def forward_with_residuals(self, params: EmbedParams, inputs,
                               streaming=None, phase_tag: str = ""):
        """Forward pass that also returns the routing residuals needed by
        :meth:`sparse_apply_gradients` (the manual sparse backward).

        Residuals carry the *model-parallel-side* ids (post-exchange), so the
        backward never re-runs the id all-to-all — mirroring how the reference
        backward reuses the forward op's inputs
        (``embedding_lookup_ops.py:116-122``).

        ``streaming``: dynamic-vocab mode (:mod:`.streaming`) —
        ``(config, state)`` remaps every streaming-table slot's external
        ids through this device's jit-carried slot map right after the
        id exchange (slot-map hits read their admitted slot, everything
        else reads its shared hash bucket) and STAGES this step's
        admission/eviction transitions; the return grows a third
        element, the per-width ``pending`` dict the trainer hands to
        :func:`.streaming.commit` next to the nan-guard.
        ``(config, state, False)`` is the read-only form (eval): remap
        only, no transitions, 2-tuple return.
        ``(config, state, "serve")`` is the pipelined trainer's
        per-microbatch form: read-only remap (each microbatch's lookup
        depends only on its own id exchange, never on the admission
        staging) PLUS a third return element — the raw per-width
        external-id :class:`~.streaming.WidthStream`\\ s of this call,
        which the trainer concatenates across microbatches and hands to
        :meth:`streaming_stage` for the ONE staging pass whose decisions
        are bitwise the serialized step's. The residuals carry the
        REMAPPED block, so the sparse backward, step metrics, and
        telemetry all operate on in-range internal rows.

        ``phase_tag`` suffixes every phase scope of this forward (the
        pipelined step's ``_mb{k}`` microbatch instances); empty (the
        default) leaves the serialized program's scopes — and therefore
        its compiled text — byte-identical to before.
        """
        params = self.local_view(params)

        if self.world_size == 1:
            # Single worker runs the SAME plan-driven lookup, minus the
            # exchanges: one gather+combine per (width, hotness) group
            # instead of a per-table loop (tiny zoo: 57 chains -> 4; the
            # batched ops amortize the per-chain pipeline overheads) and one
            # shared code path with the distributed executor. Reference
            # parity of output ranks (``call``, ``:493-500``) is restored
            # from the plan's flat [b, h*w] slots below.
            if isinstance(inputs, MpInputs):
                raise ValueError(
                    "world_size == 1 takes a plain input list (mp and dp "
                    "input coincide)")
            entries, encs, shapes = self._normalize_inputs(inputs)
            b = (entries[0][2].shape[0] if isinstance(entries[0], tuple)
                 else entries[0].shape[0])
            comm_dtype = (entries[0][1].dtype if isinstance(entries[0], tuple)
                          else entries[0].dtype)
            plan = self._get_plan(encs, b)
            ids_recv = exchange_mod.build_send_blocks(self, plan, entries,
                                                      comm_dtype)
            ids_recv, spending = self._streaming_remap(plan, ids_recv,
                                                       streaming,
                                                       tag=phase_tag)
            # slot-major group outputs: per-instance outputs are plain
            # slices, skipping the exchange-row transpose the single
            # worker never needs (only multi-slot instances pay a small
            # per-instance transpose)
            reds = lookup_mod.plan_lookup_groups(self, plan, params,
                                                 ids_recv, tag=phase_tag)
            outs = []
            for inst in plan.instances:  # worker order == input order here
                g = plan.groups[inst.group]
                red = reds[inst.group]  # [1, n, b, w]
                if inst.num_slots == 1:
                    o = red[0, inst.slot0]
                else:
                    o = lax.slice(
                        red, (0, inst.slot0, 0, 0),
                        (1, inst.slot0 + inst.num_slots, b, g.width)
                    )[0].transpose(1, 0, 2).reshape(b, -1)
                enc = encs[inst.input_id]
                shape = shapes[inst.input_id]
                # single-worker parity with the reference's local `call`
                # (:493-500): dense outputs keep the input's rank —
                # no combiner: shape[1:] + (w,); combiner: the lead dims
                # survive the trailing-dim reduction
                if enc[0] == "d" and shape is not None and len(shape) >= 2:
                    comb = self.strategy.global_configs[
                        self.strategy.input_table_map[inst.input_id]
                    ].get("combiner")
                    lead = shape[1:] if comb is None else shape[1:-1]
                    if comb is None or lead:
                        o = o.reshape((b,) + tuple(lead) + (g.width,))
                outs.append(o)
            result = [outs[i] for i in self.strategy.rev_global_input_ids]
            res = ("dist", ids_recv, tuple(encs), b)
            return ((result, res, spending) if spending is not None
                    else (result, res))

        world = self.world_size
        if self.dp_input:
            entries, encs, _ = self._normalize_inputs(inputs)

            def batch_of(e):
                return e[2].shape[0] if isinstance(e, tuple) else e.shape[0]

            b = batch_of(entries[0])
            for e in entries:
                if batch_of(e) != b:
                    raise ValueError("All inputs must share the batch dimension")
            comm_dtype = (entries[0][1].dtype if isinstance(entries[0], tuple)
                          else entries[0].dtype)
            plan = self._get_plan(encs, b)

            # --- dp -> mp id exchange (schedule phase "id_all_to_all",
            # parallel/exchange.py) -----------------------------------------
            ids_recv = exchange_mod.exchange_ids(self, plan, entries,
                                                 comm_dtype, tag=phase_tag)
        else:
            # --- model-parallel input: this rank already holds the global
            # batch of ids for its local tables; no id exchange runs
            # (reference :213,267: mp input skips the alltoall entirely).
            if not isinstance(inputs, MpInputs):
                raise ValueError(
                    "dp_input=False requires an MpInputs batch; build one "
                    "with pack_mp_inputs()")
            if len(inputs.hots) != self.strategy.num_inputs:
                raise ValueError(
                    f"Expected {self.strategy.num_inputs} hotness entries, "
                    f"got {len(inputs.hots)}")
            encs = [self._enc_of_hot(h) for h in inputs.hots]
            b = int(inputs.local_batch)
            plan = self._get_plan(encs, b)
            ids_recv = inputs.packed
            if ids_recv.ndim == 3:  # [1, world, l_max] shard inside shard_map
                ids_recv = ids_recv.reshape(ids_recv.shape[-2],
                                            ids_recv.shape[-1])
            if ids_recv.shape != (world, plan.l_max):
                raise ValueError(
                    f"MpInputs packed shape {ids_recv.shape} does not match "
                    f"the plan layout {(world, plan.l_max)}; repack with "
                    "pack_mp_inputs() from this DistributedEmbedding")
            if not jnp.issubdtype(ids_recv.dtype, jnp.integer):
                ids_recv = ids_recv.astype(jnp.int32)

        # --- streaming remap (dynamic-vocab tables) ------------------------
        ids_recv, spending = self._streaming_remap(plan, ids_recv, streaming,
                                                   tag=phase_tag)

        # --- rank-uniform local lookup (schedule phase family
        # "lookup_*", parallel/lookup.py) -----------------------------------
        mp_out = lookup_mod.plan_lookup(self, plan, params, ids_recv,
                                        tag=phase_tag)  # [world, b, s_max]

        # --- mp -> dp output exchange (schedule phase "out_all_to_all",
        # parallel/exchange.py) ---------------------------------------------
        dp_recv = exchange_mod.exchange_outputs(self, mp_out, tag=phase_tag)
        # dp_recv[r] = this rank's batch as computed by source rank r.

        # --- unpack (static slices), reorder, concat column slices ---------
        worker_order: List[jax.Array] = []
        for inst in plan.instances:
            g = plan.groups[inst.group]
            c0 = g.col + inst.slot0 * g.width
            ow = inst.num_slots * g.width
            worker_order.append(
                lax.slice(dp_recv, (inst.rank, 0, c0),
                          (inst.rank + 1, b, c0 + ow)).reshape(b, ow))
        result = [worker_order[i] for i in self.strategy.rev_global_input_ids]
        # reassemble slices in ascending input order (in-place collapse
        # invariant, strategy.create_sliced_configs): column slices
        # concatenate; row slices SUM (out-of-range reads were zeroed)
        ranges = (
            [(s, e, "cat") for s, e in self.strategy.sliced_out_ranges]
            + [(s, e, "sum")
               for s, e in self.strategy.row_sliced_out_ranges])
        for start, end, kind in sorted(ranges):
            if kind == "cat":
                result[start:end] = [
                    jnp.concatenate(result[start:end], axis=-1)]
            else:
                total = result[start]
                for part in result[start + 1:end]:
                    total = total + part
                result[start:end] = [total]
        res = ("dist", ids_recv, tuple(encs), b)
        return ((result, res, spending) if spending is not None
                else (result, res))

    # ------------------------------------------------- plan-driven executor

    def _get_plan(self, encs, b: int) -> plan_mod.ExchangePlan:
        key = (tuple(encs), int(b))
        p = self._plan_cache.get(key)
        if p is None:
            p = plan_mod.build_plan(self.strategy, self.row_offsets_list,
                                    encs, int(b))
            self._plan_cache[key] = p
        return p

    def _my_rank(self):
        """Mesh position under shard_map; static 0 for a single worker
        (which runs outside any mesh axis)."""
        return (lax.axis_index(self.axis_name) if self.world_size > 1 else 0)

    def _vary(self, x: jax.Array) -> jax.Array:
        """VMA-mark a constant when running under shard_map; identity for
        the single-worker (no mesh axis) path."""
        return _pvary(x, self.axis_name) if self.world_size > 1 else x

    def _plan_row(self, arr: np.ndarray, my) -> jax.Array:
        """This device's row of a ``[world, n]`` plan tensor. The tensor is a
        baked program constant; indexing it by ``lax.axis_index`` is what
        replaces rank-specialized branches."""
        c = self._vary(jnp.asarray(arr))
        return lax.dynamic_index_in_dim(c, my, keepdims=False)

    # ------------------------------------------------------ sparse backward

    def sparse_apply_gradients(self, params: EmbedParams, opt_state, residuals,
                               out_grads, optimizer, lr, scale=None,
                               enable=None):
        """Manual sparse backward + in-place optimizer update.

        Replaces autodiff w.r.t. the parameter slabs: ``out_grads`` are the
        cotangents of this layer's *outputs* (obtained by differentiating the
        dense model w.r.t. the embedding activations), routed back through the
        reverse output all-to-all and applied as per-row scatter updates —
        never materializing dense table gradients. This is the IndexedSlices
        pipeline of the reference (``dist_model_parallel.py:526-567`` + the
        grad kernel) in SPMD form.

        Args:
          params: this device's slabs (any leading world axis squeezed).
          opt_state: optimizer slab state from ``optimizer.init``.
          residuals: second output of :meth:`forward_with_residuals`.
          out_grads: list of cotangents matching the forward outputs.
          optimizer: :class:`~.optimizers.SparseSGD` /
            :class:`~.optimizers.SparseAdagrad`.
          lr: learning rate (scalar or traced).
          scale: gradient pre-scale; defaults to ``1/world_size``, matching the
            reference's mp-gradient scaling (``dist_model_parallel.py:542-546``)
            under a pmean-averaged data-parallel loss.
          enable: optional traced scalar bool — when False the whole update
            is skipped with slabs and slab-shaped optimizer state bitwise
            unchanged (every update row routes to the dropped sentinel; see
            :func:`~.apply.apply_width_streams`). The trainer's non-finite
            guard
            passes its finiteness verdict here.

        Returns:
          ``(new_params, new_opt_state)``.
        """
        return apply_mod.sparse_apply_gradients(
            self, params, opt_state, residuals, out_grads, optimizer,
            lr, scale=scale, enable=enable)

    # --------------------------------------------------------- observability

    def step_metrics(self, residuals, out_dtype=None) -> Dict[str, jax.Array]:
        """On-device exchange/overflow metrics of one forward, derived from
        the :meth:`forward_with_residuals` residuals — a handful of sums
        over tensors the step already holds (near-zero cost), jit-safe.

        Returns a plain dict (see :data:`~..utils.obs.STEP_METRIC_KEYS` for
        the full step-metrics schema; the grad-norm/loss/step entries are
        added by the trainer, which holds those values). Every entry is a
        per-device ``[1]`` array so that under ``shard_map`` with
        ``out_specs=P(axis_name)`` the rows concatenate into per-rank
        ``[world]`` vectors:

        * ``ids_routed`` — live (non-padding) ids this rank received
          through the id exchange: the static dense-slot count plus the
          dynamic ragged totals (claimed lengths clamped to capacity).
        * ``id_overflow`` — ragged ids CLAIMED by the row lengths beyond
          the slot's static capacity: every unit here is an id the lookup
          silently dropped (the "ragged ids silently overflow ``CAP``"
          failure made visible). Zero on healthy batches.
        * ``invalid_id_count`` — negative / out-of-vocab ids among the
          live ids this rank received (what the ``invalid_id_policy``
          clamped or dropped; row-sliced slots excluded — each id is
          in-range on exactly one slice). Zero on healthy batches.
        * ``id_a2a_bytes`` / ``out_a2a_bytes`` / ``grad_a2a_bytes`` —
          bytes leaving this chip per step for the dp→mp id exchange, the
          mp→dp activation exchange, and the reverse cotangent exchange
          (static consequences of the plan layout, included so a metrics
          record prices the padded exchange exactly like
          ``bench.plan_exchange_bytes`` does).
        * ``out_pad_frac`` — dead-column fraction of this rank's rows in
          the output exchange (the placement-imbalance signal
          ``comm_balanced`` minimizes).

        Args:
          residuals: second output of :meth:`forward_with_residuals`.
          out_dtype: dtype of the exchanged activations (the trainer
            passes the cotangent dtype); defaults to ``compute_dtype``
            or float32.
        """
        _, ids_recv, encs, b = residuals
        plan = self._get_plan(list(encs), b)
        world = self.world_size
        my = self._my_rank()
        id_bytes = jnp.dtype(ids_recv.dtype).itemsize
        out_bytes = jnp.dtype(out_dtype or self.compute_dtype
                              or jnp.float32).itemsize

        # static per-rank tallies baked from the plan (indexed by
        # lax.axis_index like every other plan tensor)
        dense_live = np.zeros((world, 1), np.int32)
        live_cols = np.zeros((world, 1), np.int32)
        for inst in plan.instances:
            g = plan.groups[inst.group]
            live_cols[inst.rank, 0] += plan.out_width(inst)
            if g.kind == "d":
                dense_live[inst.rank, 0] += world * b * inst.num_slots * g.hot
        routed = self._plan_row(dense_live, my).astype(jnp.int32)
        overflow = routed * 0  # zero that inherits routed's varying type
        invalid = routed * 0
        for gi, g in enumerate(plan.groups):
            region = lax.slice(ids_recv, (0, g.goff),
                               (world, g.goff + g.n * g.blen))
            rows = self._plan_row(plan.rows[gi], my)  # [n] per-slot vocab
            # invalid-id counting skips dead slots (their zero-filled ids
            # would compare against rows=0) and row-sliced slots (a valid
            # id is in-range on exactly ONE of its k slices — per-slot
            # counting would tally k-1 phantom invalids per id)
            slot_ok = ((self._plan_row(plan.valid[gi], my) > 0)
                       & (self._plan_row(plan.rsliced[gi], my) == 0))
            if g.kind == "d":
                ids = region.reshape(world, g.n, b, g.hot)
                bad = (((ids < 0) | (ids >= rows[None, :, None, None]))
                       & slot_ok[None, :, None, None])
                invalid = invalid + jnp.sum(bad, dtype=jnp.int32).reshape(1)
                continue
            r3 = region.reshape(world, g.n, g.blen)
            values = r3[:, :, :g.hot]
            lengths = r3[:, :, g.hot:g.hot + b]
            tot = jnp.sum(lengths, axis=2, dtype=jnp.int32)  # [world, n]
            # dead slots carry zero lengths by construction (senders fill
            # dead cells with zeros), so no valid-mask is needed here
            routed = routed + jnp.sum(jnp.minimum(tot, g.hot)).reshape(1)
            overflow = overflow + jnp.sum(
                jnp.maximum(tot - g.hot, 0)).reshape(1)
            # live ragged positions are packed from position 0 (senders
            # zero-fill past nnz), so a position index < clamped total
            # marks a real id
            live = (jnp.arange(g.hot, dtype=jnp.int32)[None, None, :]
                    < jnp.minimum(tot, g.hot)[:, :, None])
            bad = (((values < 0) | (values >= rows[None, :, None]))
                   & live & slot_ok[None, :, None])
            invalid = invalid + jnp.sum(bad, dtype=jnp.int32).reshape(1)
        off_chip = float(world - 1)
        return {
            "ids_routed": routed,
            "id_overflow": overflow,
            "invalid_id_count": invalid,
            "id_a2a_bytes": self._vary(jnp.full(
                (1,), off_chip * plan.l_max * id_bytes, jnp.float32)),
            "out_a2a_bytes": self._vary(jnp.full(
                (1,), off_chip * b * plan.s_max * out_bytes, jnp.float32)),
            "grad_a2a_bytes": self._vary(jnp.full(
                (1,), off_chip * b * plan.s_max * out_bytes, jnp.float32)),
            "out_pad_frac": 1.0 - (
                self._plan_row(live_cols, my).astype(jnp.float32)
                / float(max(plan.s_max, 1))),
        }

    def update_telemetry(self, tstate, residuals, config):
        """Fold one forward's routed ids into jit-carried access
        telemetry (:mod:`~..analysis.telemetry`): per width slab, the
        count-min sketch + top-k hot-row merge over the live logical
        slab rows this rank received; plus the rank's cumulative
        routed-id load. Pure jax ops on tensors the step already holds
        — no collectives, no host interop, static shapes (zero
        steady-state recompiles).

        One emission point per ``(width, kind)`` exchange group, each
        under its own ``obs.scope`` so a profile prices telemetry per
        group; groups of equal width fold into one sketch update.

        Args:
          tstate: this device's telemetry state
            (:func:`~..analysis.telemetry.local_state` view).
          residuals: second output of :meth:`forward_with_residuals` —
            or a LIST of them (the pipelined step's per-microbatch
            residuals): the per-width id streams of every residual
            concatenate into ONE sketch fold and ONE top-k merge, so the
            counted traffic matches the serialized step's (the count-min
            scatter-add is associative; a per-microbatch fold would
            merge candidates against partially-folded estimates).
          config: a :class:`~..analysis.telemetry.TelemetryConfig`
            (trace-time static).

        Returns:
          the updated telemetry state (same structure).
        """
        from ..analysis import telemetry as tel

        res_list = ([residuals] if residuals and residuals[0] == "dist"
                    else list(residuals))
        world = self.world_size
        my = self._my_rank()
        per_width: Dict[int, tuple] = {}
        for residuals in res_list:
            _, ids_recv, encs, b = residuals
            plan = self._get_plan(list(encs), b)
            for gi, g in enumerate(plan.groups):
                with obs.scope(f"telemetry_w{g.width}_{g.kind}"):
                    region = lax.slice(ids_recv, (0, g.goff),
                                       (world, g.goff + g.n * g.blen))
                    rows = self._plan_row(plan.rows[gi], my)
                    roff = self._plan_row(plan.roff[gi], my)
                    slot_ok = self._plan_row(plan.valid[gi], my) > 0
                    rbase = (self._plan_row(plan.rbase[gi], my)
                             if plan.rsliced[gi].any() else None)
                    if g.kind == "d":
                        ids = region.reshape(world, g.n, b, g.hot)
                        loc = (ids - rbase[None, :, None, None]
                               if rbase is not None else ids)
                        # live = in-range on THIS slot: row-sliced slots
                        # count each id on exactly the slice that owns
                        # it, dead and out-of-vocab ids drop (they train
                        # nothing either)
                        live = ((loc >= 0)
                                & (loc < rows[None, :, None, None])
                                & slot_ok[None, :, None, None])
                        grow = loc + roff[None, :, None, None]
                    else:
                        r3 = region.reshape(world, g.n, g.blen)
                        values = r3[:, :, :g.hot]
                        lengths = r3[:, :, g.hot:g.hot + b]
                        tot = jnp.sum(lengths, axis=2, dtype=jnp.int32)
                        pos_live = (
                            jnp.arange(g.hot, dtype=jnp.int32)[None, None,
                                                               :]
                            < jnp.minimum(tot, g.hot)[:, :, None])
                        loc = (values - rbase[None, :, None]
                               if rbase is not None else values)
                        live = (pos_live & (loc >= 0)
                                & (loc < rows[None, :, None])
                                & slot_ok[None, :, None])
                        grow = loc + roff[None, :, None]
                    acc = per_width.setdefault(g.width, ([], []))
                    acc[0].append(grow.astype(jnp.int32).reshape(-1))
                    acc[1].append(live.reshape(-1))
        new = dict(tstate)
        total = jnp.zeros((1,), jnp.float32)
        for w in sorted(per_width):
            idl, livel = per_width[w]
            ids = jnp.concatenate(idl)
            live = jnp.concatenate(livel)
            with obs.scope(f"telemetry_update_w{w}"):
                new[_wkey(w)] = tel.record_ids(tstate[_wkey(w)], ids,
                                               live, config)
            total = total + jnp.sum(live, dtype=jnp.float32).reshape(1)
        new["steps"] = tstate["steps"] + 1
        new["ids_total"] = tstate["ids_total"] + total
        return new

    # -------------------------------------------------- streaming vocab

    def _streaming_plan_arrays(self, plan) -> list:
        """Per-group ``[world, n]`` plan tensors of the streaming remap
        (``parallel/streaming.py``): per slot, whether its table is
        dynamic, the slot capacity, the shared-bucket count, and the
        (plan-invariant hash salt) global table id. Baked once per plan
        like every other plan tensor — plans are cached for the process
        lifetime, so ``id(plan)`` is a stable cache key."""
        key = id(plan)
        cached = self._streaming_arrays_cache.get(key)
        if cached is not None:
            return cached
        world = self.world_size
        out = [(np.zeros((world, g.n), np.int32),
                np.ones((world, g.n), np.int32),
                np.ones((world, g.n), np.int32),
                np.zeros((world, g.n), np.int32))
               for g in plan.groups]
        for inst in plan.instances:
            tid = self.strategy.input_table_map[inst.input_id]
            info = self.streaming_tables.get(tid)
            if info is None:
                continue
            dyn_a, cap_a, nb_a, tid_a = out[inst.group]
            sl = slice(inst.slot0, inst.slot0 + inst.num_slots)
            dyn_a[inst.rank, sl] = 1
            cap_a[inst.rank, sl] = info[0]
            nb_a[inst.rank, sl] = info[1]
            tid_a[inst.rank, sl] = tid
        self._streaming_arrays_cache[key] = out
        return out

    def _streaming_remap(self, plan, ids_recv, streaming, tag: str = ""):
        """Remap every streaming-table slot's external ids in the
        received block through the jit-carried slot map
        (:func:`.streaming.remap_width`) and, in update mode, stage the
        admission/eviction transitions.

        ``streaming`` is ``None`` (no-op), ``(config, state)`` (train:
        remap + stage), ``(config, state, False)`` (read-only remap —
        the eval path admits nothing), or ``(config, state, "serve")``
        (the pipelined per-microbatch form: read-only remap that ALSO
        returns this call's raw per-width external-id streams, under
        ``streaming_serve_w{w}{tag}`` scopes so each microbatch's serve
        chain stays a distinct phase). Returns ``(ids_recv, pending)``
        with ``pending`` a ``{width: (new_wstate, scrub_rows, stats)}``
        dict in update mode, a ``{width: WidthStream}`` dict in serve
        mode (feed :meth:`streaming_stage`), else ``None``. Pure jax on
        tensors the step already holds; static shapes throughout (0
        steady-state recompiles); only the modified group regions are
        rewritten (static-offset ``dynamic_update_slice``)."""
        if streaming is None:
            return ids_recv, None
        from . import streaming as streaming_mod

        if not self.streaming_tables:
            raise ValueError(
                "streaming= passed but no table declares a 'streaming' "
                "config entry")
        if len(streaming) == 2:
            config, sstate = streaming
            update = True
        else:
            config, sstate, update = streaming
        serve = update == "serve"
        if serve:
            update = False
        arrays = self._streaming_plan_arrays(plan)
        world = self.world_size
        my = self._my_rank()
        b = plan.b
        per_width: Dict[int, list] = {}
        sites = []  # (gi, width, start-within-width-stream, original vals,
        #             write-back mask, region tail or None)
        for gi, g in enumerate(plan.groups):
            dyn_a, cap_a, nb_a, tid_a = arrays[gi]
            if not dyn_a.any():
                continue
            with obs.scope(f"streaming_remap_w{g.width}_{g.kind}{tag}"):
                region = lax.slice(ids_recv, (0, g.goff),
                                   (world, g.goff + g.n * g.blen))
                dyn = self._plan_row(dyn_a, my)
                cap = self._plan_row(cap_a, my)
                nb = self._plan_row(nb_a, my)
                tid = self._plan_row(tid_a, my)
                roff = self._plan_row(plan.roff[gi], my)
                if g.kind == "d":
                    vals = region.reshape(world, g.n, b, g.hot)
                    bshape = vals.shape
                    dynm = jnp.broadcast_to(
                        dyn[None, :, None, None] > 0, bshape)
                    ex = (cap[None, :, None, None],
                          nb[None, :, None, None],
                          tid[None, :, None, None],
                          roff[None, :, None, None])
                    tail = None
                else:
                    r3 = region.reshape(world, g.n, g.blen)
                    vals = r3[:, :, :g.hot]
                    lengths = r3[:, :, g.hot:g.hot + b]
                    tot = jnp.sum(lengths, axis=2, dtype=jnp.int32)
                    pos_live = (
                        jnp.arange(g.hot, dtype=jnp.int32)[None, None, :]
                        < jnp.minimum(tot, g.hot)[:, :, None])
                    bshape = vals.shape
                    dynm = pos_live & (dyn[None, :, None] > 0)
                    ex = (cap[None, :, None], nb[None, :, None],
                          tid[None, :, None], roff[None, :, None])
                    tail = r3[:, :, g.hot:]
                capb, nbb, tidb, roffb = (
                    jnp.broadcast_to(x, bshape) for x in ex)
                acc = per_width.setdefault(g.width, [])
                start = sum(p[0].size for p in acc)
                acc.append((vals.reshape(-1), dynm.reshape(-1),
                            capb.reshape(-1), nbb.reshape(-1),
                            tidb.reshape(-1), roffb.reshape(-1)))
                sites.append((gi, g.width, start, vals, dynm, tail))

        remapped: Dict[int, jax.Array] = {}
        pending: Dict[int, tuple] = {}
        for w in sorted(per_width):
            pieces = per_width[w]
            stream = streaming_mod.WidthStream(
                ext=jnp.concatenate([p[0] for p in pieces]),
                live=jnp.concatenate([p[1] for p in pieces]),
                cap=jnp.concatenate([p[2] for p in pieces]),
                nbuckets=jnp.concatenate([p[3] for p in pieces]),
                tid=jnp.concatenate([p[4] for p in pieces]),
                roff=jnp.concatenate([p[5] for p in pieces]))
            # the serve half runs under its own (per-microbatch) phase in
            # pipelined steps — it feeds this microbatch's lookup, so it
            # must never share the staging phase the schedule declares
            # independent of the out/grad exchanges
            scope_name = (f"streaming_serve_w{w}{tag}" if serve
                          else f"streaming_admit_w{w}")
            with obs.scope(scope_name):
                local_rows, pend = streaming_mod.remap_width(
                    sstate[_wkey(w)], stream, self.rows_cap[w], config,
                    update=update)
            remapped[w] = local_rows
            if serve:
                pending[w] = stream
            elif pend is not None:
                pending[w] = pend

        for gi, w, start, vals, dynm, tail in sites:
            g = plan.groups[gi]
            new = lax.slice(remapped[w], (start,),
                            (start + vals.size,)).reshape(vals.shape)
            # write-back keeps non-streaming slots (which may share the
            # group), dead positions, and negative ids byte-identical —
            # the remap never widens/narrows the block dtype
            new_vals = jnp.where(dynm & (vals >= 0),
                                 new.astype(vals.dtype), vals)
            if tail is None:
                region_new = new_vals.reshape(world, g.n * g.blen)
            else:
                region_new = jnp.concatenate(
                    [new_vals, tail], axis=2).reshape(world, g.n * g.blen)
            ids_recv = lax.dynamic_update_slice(ids_recv, region_new,
                                                (0, g.goff))
        return ids_recv, (pending if (update or serve) else None)

    def streaming_stage(self, width_streams, config, sstate):
        """The pipelined step's ONE admission-staging pass: concatenate
        the per-microbatch raw external-id streams (the ``"serve"``-mode
        third return of :meth:`forward_with_residuals`, one dict per
        microbatch) and run :func:`.streaming.remap_width` in update
        mode over the combined stream — exactly the serialized step's
        staging input, so the sketch fold, admission estimates, and
        deterministic claim resolution are BITWISE the serialized
        decisions (the max-scatter tie-breaks are order-independent for
        duplicate ids, and the count-min fold is a plain scatter-add).
        Runs under the same ``streaming_admit_w{w}`` scopes as the
        serialized staging, so the schedule's declared overlap names one
        phase in both programs. Returns the ``pending`` dict
        :func:`.streaming.commit` consumes."""
        from . import streaming as streaming_mod

        widths = sorted({w for ws in width_streams for w in ws})
        pending: Dict[int, tuple] = {}
        for w in widths:
            parts = [ws[w] for ws in width_streams if w in ws]
            stream = streaming_mod.WidthStream(
                *(jnp.concatenate([getattr(p, f) for p in parts])
                  for f in streaming_mod.WidthStream._fields))
            with obs.scope(f"streaming_admit_w{w}"):
                _, pend = streaming_mod.remap_width(
                    sstate[_wkey(w)], stream, self.rows_cap[w], config,
                    update=True)
            pending[w] = pend
        return pending

    # ------------------------------------------------------------- checkpoint

    def _slice_plan(self):
        """Per-(rank, local table) checkpoint routing: ``plan[rank][m] =
        (table_id, slab_row_offset, rows, col_start, width, row_base)``
        where ``col_start`` is the slice's first column in the full
        (unsliced) source table — column slices are consumed in rank order,
        the reference's ``_slice_weight_for_rank`` math
        (``dist_model_parallel.py:346-361``) — and ``row_base`` is the
        slice's first global row (0 except for row slices, whose columns
        always span the full width)."""
        col_pos = {tid: 0 for tid in range(len(self.strategy.global_configs))}
        plan: List[List[tuple]] = []
        for r, cfgs in enumerate(self.strategy.local_configs_list):
            rank_plan = []
            for m, cfg in enumerate(cfgs):
                _, roff, rows, w = self._table_rows(r, m)
                tid = self.strategy.table_ids_list[r][m]
                if tid in self.strategy.row_sliced_tables:
                    rank_plan.append(
                        (tid, roff, rows, 0, w, int(cfg["_row_base"])))
                else:
                    rank_plan.append((tid, roff, rows, col_pos[tid], w, 0))
                    col_pos[tid] += w
            plan.append(rank_plan)
        return plan

    def _fetch_rows(self, v, rank: int, start: int, n: int,
                    to_host: bool = True) -> Optional[np.ndarray]:
        """Host copy of ``v[rank, start:start+n, :]`` without materializing
        anything bigger. For non-addressable shards (multi-host) the slice is
        jit-extracted with a fully-replicated out-sharding — the chunked
        allgather of the reference's ``get_weights``
        (``dist_model_parallel.py:441-447``) — so every process gets it.
        ``to_host=False`` still executes the collective fetch (every process
        must, SPMD) but skips the device->host copy and returns ``None``
        (the ``all_ranks=False`` mode of :meth:`get_weights`)."""
        if isinstance(v, np.ndarray):
            return np.asarray(v[rank, start:start + n, :]) if to_host \
                else None
        w = v.shape[2]
        if v.is_fully_addressable:
            # Slice on the owning shard's device — a single-device program
            # that transfers only the chunk (a dynamic_slice on the *global*
            # array would make GSPMD materialize a full replica per call).
            for shard in v.addressable_shards:
                r0, r1, _ = shard.index[0].indices(v.shape[0])
                if not (r0 <= rank < r1):
                    continue
                key = ("fetch_shard", shard.data.shape, v.dtype, n)
                fn = self._ckpt_jit_cache.get(key)
                if fn is None:
                    fn = jax.jit(lambda a, r, s: lax.dynamic_slice(
                        a, (r, s, 0), (1, n, w))[0])
                    self._ckpt_jit_cache[key] = fn
                res = fn(shard.data, rank - r0, start)
                return np.asarray(res) if to_host else None
            raise AssertionError("fully-addressable array with no owner shard")
        # Multi-host: every process needs the chunk but no process holds all
        # shards. A masked psum inside shard_map moves exactly one chunk over
        # the network — the reference's chunked allgather
        # (``dist_model_parallel.py:441-447``) — never a full replica.
        mesh = v.sharding.mesh
        axis = self.axis_name
        key = ("fetch_global", v.shape, v.dtype, n, id(mesh))
        fn = self._ckpt_jit_cache.get(key)
        if fn is None:
            P = jax.sharding.PartitionSpec
            blk = v.shape[0] // mesh.shape[axis]

            def local(ab, r, s):
                my = lax.axis_index(axis)
                rel = r - my * blk
                hit = (rel >= 0) & (rel < blk)
                rows = lax.dynamic_slice(
                    ab, (jnp.clip(rel, 0, blk - 1), s, 0), (1, n, w))[0]
                return lax.psum(jnp.where(hit, rows, 0), axis)

            fn = jax.jit(jax.shard_map(
                local, mesh=mesh, in_specs=(P(axis), P(), P()),
                out_specs=P()))
            self._ckpt_jit_cache[key] = fn
        res = fn(v, jnp.asarray(rank), jnp.asarray(start))
        return np.asarray(res) if to_host else None

    def get_weights(self, params: EmbedParams,
                    chunk_elems: int = CHECKPOINT_CHUNK_ELEMS,
                    all_ranks: bool = True) -> Optional[List[np.ndarray]]:
        """Reassemble the full (unsliced) global tables on host, streaming
        row chunks of at most ``chunk_elems`` elements.

        Equivalent of the reference's chunked-allgather ``get_weights``
        (``dist_model_parallel.py:411-485``): peak transient host memory is
        one chunk, not one model; tables over 2^31 elements stream fine; on
        multi-host meshes every process receives the full tables by default.

        Args:
          all_ranks: with ``False`` (the reference's rank-0-only mode,
            ``dist_model_parallel.py:411,419``) only process 0 assembles and
            returns the tables; other processes still participate in every
            collective fetch (SPMD requires it) but skip the device->host
            copy and the host-side buffers, and return ``None``. On a pod
            this keeps the full-model host footprint confined to the
            checkpoint-writing process.
        """
        keep = all_ranks or jax.process_index() == 0
        out = [self.get_table(params, tid, chunk_elems=chunk_elems,
                              all_ranks=all_ranks)
               for tid in range(len(self.strategy.global_configs))]
        return out if keep else None

    def get_table(self, params: EmbedParams, tid: int,
                  chunk_elems: int = CHECKPOINT_CHUNK_ELEMS,
                  all_ranks: bool = True) -> Optional[np.ndarray]:
        """Reassemble ONE global table on host (streamed like
        :meth:`get_weights`, which delegates here). Lets checkpoint writers
        cap host memory at one table instead of the whole model."""
        if not hasattr(self, "_ckpt_jit_cache"):
            self._ckpt_jit_cache = {}
        keep = all_ranks or jax.process_index() == 0
        params = self.stacked_view(params)
        cfg = self.strategy.global_configs[tid]
        out: Optional[np.ndarray] = None
        for r, rank_plan in enumerate(self._slice_plan()):
            for t2, roff, rows, c0, w, rb in rank_plan:
                if t2 != tid:
                    continue
                v = params[_wkey(w)]
                if keep and out is None:
                    out = np.empty(
                        (int(cfg["input_dim"]), int(cfg["output_dim"])),
                        v.dtype)
                p = ps.pack_factor(w)
                chunk_rows = max(p, (int(chunk_elems) // max(w, 1)) // p * p)
                for s in range(0, rows, chunk_rows):
                    n = min(chunk_rows, rows - s)
                    phys = self._fetch_rows(
                        v, r, (roff + s) // p, -(-n // p), to_host=keep)
                    if keep:
                        out[rb + s:rb + s + n, c0:c0 + w] = \
                            ps.unpack_rows_np(phys, w)[:n]
        return out if keep else None

    def _build_shard(self, loaded, dev, width: int, r0: int, r1: int,
                     dtype, chunk_elems: int) -> jax.Array:
        """Stream one device's packed slab shard ``[r1-r0, phys_cap,
        phys_w]``: zeros on-device, then donated row-range writes of at most
        ``chunk_elems`` elements read straight from the (possibly mmap'd)
        sources — never a host copy bigger than one chunk. Chunks are packed
        host-side at physical-row granularity."""
        p = ps.pack_factor(width)
        pw = self.phys_w[width]
        with jax.default_device(dev):
            buf = jnp.zeros((r1 - r0, self.phys_cap[width], pw), dtype)
        # commit to dev (no-copy) so later ops can't migrate an unwritten
        # buffer back to the default device
        buf = jax.device_put(buf, dev)
        shape3 = buf.shape
        buf = buf.reshape(-1, pw)
        plan = self._slice_plan()
        chunk_rows = max(p, (int(chunk_elems) // max(width, 1)) // p * p)
        for r in range(r0, r1):
            base = (r - r0) * self.phys_cap[width]
            for tid, roff, rows, c0, w, rb in plan[r]:
                if w != width:
                    continue
                src = loaded[tid]
                # exact-size check (a looser bound would let an oversized
                # source load silently truncated): row slices must tile the
                # declared global vocab, plain tables must equal it
                full = int(self.strategy.global_configs[tid]["input_dim"])
                if src.shape[0] != full:
                    raise ValueError(
                        f"Table {tid}: expected {full} rows, got "
                        f"{src.shape[0]}")
                for s in range(0, rows, chunk_rows):
                    n = min(chunk_rows, rows - s)
                    host = np.ascontiguousarray(
                        src[rb + s:rb + s + n, c0:c0 + w], dtype=dtype)
                    if n % p:  # pad into the table's alignment padding
                        host = np.concatenate(
                            [host, np.zeros((p - n % p, w), host.dtype)])
                    buf = _write_rows(buf, jax.device_put(
                        ps.pack_rows_np(host, width), dev),
                        base + (roff + s) // p)
        return buf.reshape(shape3)

    @staticmethod
    def _uid_lock_path() -> str:
        """Lock file for ``set_weights(use_lock=True)``: ONE lock per uid,
        so every concurrent load by this user serializes — the reference's
        ``use_lock`` likewise serializes ranks globally, not per
        checkpoint (``dist_model_parallel.py:329-331``). Scoped per uid
        because a fixed world-shared /tmp name would collide with, or be
        blocked by, other users' pre-existing lock files on a shared host
        (ADVICE r4). A per-checkpoint name was considered and rejected:
        one restore streams several component directories (tables/,
        emb_opt/*) whose loads must ALL serialize against other
        processes' — a directory-derived name would hand them different
        locks."""
        import tempfile
        return os.path.join(tempfile.gettempdir(),
                            f"detpu_set_weights_{os.getuid()}.lock")

    def set_weights(self, weights: Sequence[Any], mesh=None,
                    dtype=jnp.float32,
                    chunk_elems: int = CHECKPOINT_CHUNK_ELEMS,
                    use_lock: bool = False,
                    src_dtype=None) -> EmbedParams:
        """Build the sharded slab dict from full global tables (numpy arrays
        or ``np.load``-able paths, mmap'd like the reference,
        ``dist_model_parallel.py:337-339``).

        ``use_lock=True`` serializes the host-side shard building across
        processes — the reference's ``set_weights(..., use_lock=True)``,
        which rank-serializes globally via ``broadcast_object``
        (``dist_model_parallel.py:329-331,383-385``), for loading models
        whose per-process transient host footprint could not otherwise
        coexist. Two layers: co-located processes serialize on a per-uid
        file lock, and on a multi-process ``jax.distributed`` job the
        processes additionally take strict turns (process 0 first), gated
        by a cross-host barrier after each turn — full cross-rank
        serialization like the reference, machine boundaries included.
        The streaming chunked design mostly obviates the need (peak
        transient host memory is one chunk), but page-cache pressure from
        several processes mmap-reading the same checkpoint can still merit
        it.

        Streams per-slice row chunks directly into per-device shard buffers
        — the reference's 128M-element chunked ``scatter_update``
        (``dist_model_parallel.py:362-380``) — so peak transient host memory
        is one chunk regardless of model size, and >2^31-element tables never
        hit a single oversized transfer. On multi-host meshes each process
        builds only its addressable shards.

        ``src_dtype``: the dtype ``.npy`` sources were SAVED in. ``np.save``
        of an extension dtype (bfloat16) writes an opaque void descriptor
        that ``np.load`` cannot map back — such sources load as ``|V<n>``
        and are re-viewed as ``src_dtype`` here (required for bf16
        checkpoints; ``utils.checkpoint`` records it in ``meta.json``)."""
        from ..utils import runtime as _runtime

        _runtime.fault_point("checkpoint_read")
        loaded = [np.load(w, mmap_mode="r") if isinstance(w, str)
                  else np.asarray(w) for w in weights]
        if any(a.dtype.kind == "V" for a in loaded):
            if src_dtype is None:
                raise ValueError(
                    "sources carry an opaque (void) dtype — np.save of an "
                    "extension dtype like bfloat16 does not round-trip "
                    "through np.load; pass src_dtype= with the dtype they "
                    "were saved in")
            sdt = jnp.dtype(src_dtype)  # np.dtype instance (ml_dtypes-aware)
            loaded = [a.view(sdt) if a.dtype.kind == "V" else a
                      for a in loaded]
        if len(loaded) != len(self.strategy.global_configs):
            raise ValueError("set_weights needs one array per global table")
        for tid, (src, cfg) in enumerate(
                zip(loaded, self.strategy.global_configs)):
            want = (int(cfg["input_dim"]), int(cfg["output_dim"]))
            if tuple(src.shape) != want:
                # a narrower source would silently zero-fill under
                # dynamic_update_slice — reject shape drift up front
                raise ValueError(
                    f"Table {tid}: expected shape {want}, got {src.shape}")

        def build():
            out = {}
            for w in self.widths:
                if mesh is None:
                    # honor an active jax.default_device context (e.g.
                    # staging a bigger-than-HBM model on host), like the old
                    # asarray path
                    dev = jax.config.jax_default_device or jax.devices()[0]
                    if isinstance(dev, str):  # context also accepts
                        dev = jax.devices(dev)[0]  # platform names
                    out[_wkey(w)] = self._build_shard(
                        loaded, dev, w, 0, self.world_size, dtype,
                        chunk_elems)
                    continue
                out[_wkey(w)] = self._assemble_sharded(
                    mesh, w,
                    lambda dev, r0, r1, w=w: self._build_shard(
                        loaded, dev, w, r0, r1, dtype, chunk_elems))
            return out

        if not use_lock:
            return build()

        import fcntl

        def locked_build():
            # the file lock wraps ONLY this process's own build turn: held
            # across a barrier wait it would deadlock two co-located
            # processes of one job (A holds the lock waiting for B's
            # barrier; B waits on the lock)
            lock_file = open(self._uid_lock_path(), "w")
            fcntl.flock(lock_file, fcntl.LOCK_EX)
            try:
                return build()
            finally:
                fcntl.flock(lock_file, fcntl.LOCK_UN)
                lock_file.close()

        if jax.process_count() > 1:
            # strict process turns with a cross-host barrier after each —
            # the reference's broadcast_object rank serialization
            # (dist_model_parallel.py:329-331,383-385) across machine
            # boundaries, where a file lock cannot reach. Every process
            # joins every barrier (collective), sandwiching its own build
            # at its process index.
            from jax.experimental import multihost_utils
            me = jax.process_index()
            for p in range(me):
                multihost_utils.sync_global_devices(
                    f"detpu_set_weights_turn_{p}")
            out = locked_build()
            for p in range(me, jax.process_count()):
                multihost_utils.sync_global_devices(
                    f"detpu_set_weights_turn_{p}")
            return out
        return locked_build()

"""Hybrid-parallel distributed embedding over a TPU mesh.

TPU-native re-design of the reference's ``DistributedEmbedding``
(``distributed_embeddings/python/layers/dist_model_parallel.py:199-505``).
The capability surface is the same — model-parallel tables + data-parallel
dense layers stitched by two all-to-alls per step — but the execution model is
JAX SPMD instead of Horovod MPMD:

* **One program, W mesh positions.** The reference runs one Python process per
  GPU, each building only its local tables. Here a single program runs on every
  device inside ``jax.shard_map``; per-rank table heterogeneity is expressed as
  ``lax.switch`` over rank-specialized lookup branches, each with fully static
  shapes (table slice offsets, hotness, widths) so XLA tiles them onto the MXU.
* **Parameters as one sharded buffer.** Each rank's tables live row-major in a
  flat ``[capacity]`` slab; the global parameter is ``[world, capacity]``
  sharded over the mesh axis. This replaces per-rank ``tf.Variable`` lists and
  makes checkpointing/optimizers uniform.
* **Collectives.** ``hvd.alltoall(splits=...)`` (variable splits,
  ``dist_model_parallel.py:282``) has no ragged JAX primitive on every backend,
  so id blocks are padded to the max per-rank split and exchanged with
  ``lax.all_to_all`` — ids are cheap. The mp→dp output exchange
  (``dist_model_parallel.py:301``) pads widths to the max per-rank output width.
  Autodiff of ``all_to_all`` provides the backward exchange exactly like
  Horovod's registered alltoall gradient.

Input contract (distributed path): dense int arrays, ``[local_batch]`` or
``[local_batch, hotness]`` per feature, identical batch on every rank —
matching the reference's dense-only ``_call_base`` (``:261-311``).
"""

from __future__ import annotations

import functools
from typing import Any, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from ..layers.embedding import Embedding, default_embeddings_init
from ..ops.embedding_lookup import embedding_lookup
from .strategy import DistEmbeddingStrategy


def _out_width(config, hotness: int) -> int:
    """Per-input 2-D output width: combiner reduces hotness; no combiner
    flattens it (the reference reshapes every mp output to [batch, -1],
    ``dist_model_parallel.py:297,307``)."""
    w = int(config["output_dim"])
    return w if config.get("combiner") else w * hotness


class DistributedEmbedding:
    """Shards embedding tables across a mesh axis and exchanges activations
    with two all-to-alls per step.

    Args:
      embeddings: list of :class:`...layers.Embedding` modules or config dicts
        (``input_dim``, ``output_dim``, optional ``combiner``,
        ``embeddings_initializer``).
      world_size: mesh-axis size (model-parallel positions == data-parallel
        positions, as in the reference).
      strategy: ``basic | memory_balanced | memory_optimized``.
      column_slice_threshold: max elements per slice; larger tables are split
        width-wise into power-of-2 slices.
      row_slice: reserved (the reference declares-but-does-not-implement row
        slicing, ``dist_model_parallel.py:225,233-234``).
      dp_input: if True (default) inputs are data-parallel shards
        ``[local_batch, ...]`` per global feature. Model-parallel input is not
        yet wired in the SPMD executor.
      input_table_map: ``input[i]`` uses ``table[input_table_map[i]]``.
      axis_name: mesh axis the executor runs under (inside ``shard_map``).
    """

    def __init__(self,
                 embeddings: Sequence[Any],
                 world_size: int,
                 strategy: str = "basic",
                 column_slice_threshold: Optional[int] = None,
                 row_slice: Optional[Any] = None,
                 dp_input: bool = True,
                 input_table_map: Optional[Sequence[int]] = None,
                 axis_name: str = "data"):
        if row_slice is not None:
            raise NotImplementedError("Row slicing embedding is not supported yet!")
        if not dp_input:
            raise NotImplementedError(
                "Model-parallel input is not supported by the SPMD executor yet; "
                "use dp_input=True")
        self.world_size = int(world_size)
        self.axis_name = axis_name
        self.dp_input = dp_input
        self.strategy = DistEmbeddingStrategy(
            embeddings, self.world_size, strategy=strategy,
            input_table_map=input_table_map,
            column_slice_threshold=column_slice_threshold)
        if len(self.strategy.global_configs) < self.world_size:
            raise NotImplementedError(
                "Fewer tables than mesh positions is not supported "
                "(reference constraint, dist_model_parallel.py:252-253)")

        # Row-major layout of each rank's tables inside its flat slab.
        self.local_offsets_list: List[List[int]] = []
        sizes = []
        for cfgs in self.strategy.local_configs_list:
            offsets, acc = [], 0
            for c in cfgs:
                offsets.append(acc)
                acc += int(c["input_dim"]) * int(c["output_dim"])
            self.local_offsets_list.append(offsets)
            sizes.append(acc)
        self.capacity = max(max(sizes), 1)

    # ------------------------------------------------------------------ params

    def _init_rank_flat(self, key, rank: int, dtype) -> jax.Array:
        """Initialize one rank's slab: per-table initializers, flattened and
        concatenated; column slices are initialized independently like the
        reference's per-slice layers (``dist_model_parallel.py:256-259``)."""
        cfgs = self.strategy.local_configs_list[rank]
        keys = jax.random.split(key, max(len(cfgs), 1))
        parts = []
        for cfg, k in zip(cfgs, keys):
            init = cfg.get("embeddings_initializer") or default_embeddings_init
            shape = (int(cfg["input_dim"]), int(cfg["output_dim"]))
            parts.append(init(k, shape, dtype).reshape(-1))
        flat = jnp.concatenate(parts) if parts else jnp.zeros((0,), dtype)
        pad = self.capacity - flat.shape[0]
        if pad:
            flat = jnp.concatenate([flat, jnp.zeros((pad,), dtype)])
        return flat

    def init(self, key, dtype=jnp.float32, mesh=None) -> jax.Array:
        """Build the global ``[world, capacity]`` parameter buffer.

        With ``mesh`` given, the result is laid out sharded over
        ``(axis_name,)`` so each rank's slab materializes on its own device.
        """
        keys = jax.random.split(key, self.world_size)

        def build():
            return jnp.stack([self._init_rank_flat(keys[r], r, dtype)
                              for r in range(self.world_size)])

        if mesh is None:
            return jax.jit(build)()
        sharding = jax.sharding.NamedSharding(
            mesh, jax.sharding.PartitionSpec(self.axis_name))
        return jax.jit(build, out_shardings=sharding)()

    def local_table(self, flat_local: jax.Array, rank: int, m: int) -> jax.Array:
        """Static view of local table ``m`` of ``rank`` inside its slab."""
        cfg = self.strategy.local_configs_list[rank][m]
        rows, width = int(cfg["input_dim"]), int(cfg["output_dim"])
        off = self.local_offsets_list[rank][m]
        return lax.slice(flat_local, (off,), (off + rows * width,)).reshape(rows, width)

    # ----------------------------------------------------------------- forward

    def _normalize_inputs(self, inputs) -> List[jax.Array]:
        if len(inputs) != self.strategy.num_inputs:
            raise ValueError(
                f"Expected {self.strategy.num_inputs} inputs, got {len(inputs)}")
        comm_dtype = jnp.int32
        for inp in inputs:
            if jnp.asarray(inp).dtype == jnp.int64:
                comm_dtype = jnp.int64
        out = []
        for inp in inputs:
            inp = jnp.asarray(inp).astype(comm_dtype)
            out.append(inp[:, None] if inp.ndim == 1 else inp)
        return out

    def _lookup_local(self, flat_local: jax.Array, rank: int,
                      inputs: Sequence[jax.Array],
                      flatten_2d: bool) -> List[jax.Array]:
        """Per-rank local lookups (the hot loop, reference ``:291-294``)."""
        outs = []
        for inp, m in zip(inputs, self.strategy.local_map_list[rank]):
            cfg = self.strategy.local_configs_list[rank][m]
            table = self.local_table(flat_local, rank, m)
            combiner = cfg.get("combiner")
            if combiner:
                o = embedding_lookup(table, inp, combiner=combiner)
            else:
                o = embedding_lookup(table, inp)
            outs.append(o.reshape(o.shape[0], -1) if flatten_2d else o)
        return outs

    def __call__(self, flat_params: jax.Array, inputs) -> List[jax.Array]:
        """Forward pass.

        * ``world_size == 1``: ``flat_params`` is the rank-0 slab ``[capacity]``
          (or ``[1, capacity]``); plain local lookups, original output ranks
          preserved (reference ``call``, ``:493-500``).
        * distributed: must run inside ``shard_map`` with ``axis_name`` bound;
          ``flat_params`` is this device's slab ``[capacity]`` (pass the global
          ``[world, capacity]`` through ``in_specs=P(axis_name)`` and squeeze).
        """
        inputs = self._normalize_inputs(inputs)
        if flat_params.ndim == 2:
            flat_params = flat_params.reshape(-1)

        if self.world_size == 1:
            return self._lookup_local(flat_params, 0, inputs, flatten_2d=False)

        world = self.world_size
        b = inputs[0].shape[0]
        for inp in inputs:
            if inp.shape[0] != b:
                raise ValueError("All inputs must share the batch dimension")
        hots = [int(inp.shape[1]) for inp in inputs]
        comm_dtype = inputs[0].dtype

        # --- dp -> mp id exchange ------------------------------------------
        # Block for dest rank r: its inputs flattened and concatenated
        # (reference :273-282), padded to the max block length.
        block_lens = [b * sum(hots[i] for i in ids)
                      for ids in self.strategy.input_ids_list]
        l_max = max(max(block_lens), 1)
        blocks = []
        for ids in self.strategy.input_ids_list:
            if ids:
                blk = jnp.concatenate([inputs[i].reshape(-1) for i in ids])
            else:
                blk = jnp.zeros((0,), comm_dtype)
            if blk.shape[0] < l_max:
                blk = jnp.concatenate(
                    [blk, jnp.zeros((l_max - blk.shape[0],), comm_dtype)])
            blocks.append(blk)
        ids_send = jnp.stack(blocks)  # [world, l_max]
        ids_recv = lax.all_to_all(ids_send, self.axis_name, 0, 0, tiled=True)

        # --- rank-specialized local lookup (lax.switch over mesh position) --
        out_widths_list = [
            [_out_width(self._input_config(r, j), hots[i])
             for j, i in enumerate(ids)]
            for r, ids in enumerate(self.strategy.input_ids_list)]
        s_max = max(max((sum(ws) for ws in out_widths_list), default=1), 1)

        def branch(rank, flat_local, recv):
            ids = self.strategy.input_ids_list[rank]
            parsed, pos = [], 0
            for i in ids:
                seg = lax.slice(recv, (0, pos), (world, pos + b * hots[i]))
                parsed.append(seg.reshape(world * b, hots[i]))
                pos += b * hots[i]
            outs = self._lookup_local(flat_local, rank, parsed, flatten_2d=True)
            if outs:
                cat = jnp.concatenate(outs, axis=1)
            else:
                cat = jnp.zeros((world * b, 0), flat_local.dtype)
            pad = s_max - cat.shape[1]
            if pad:
                cat = jnp.concatenate(
                    [cat, jnp.zeros((world * b, pad), cat.dtype)], axis=1)
            return cat

        my_rank = lax.axis_index(self.axis_name)
        mp_out = lax.switch(
            my_rank,
            [functools.partial(branch, r) for r in range(world)],
            flat_params, ids_recv)  # [world*b, s_max]

        # --- mp -> dp output exchange --------------------------------------
        dp_recv = lax.all_to_all(
            mp_out.reshape(world, b, s_max), self.axis_name, 0, 0, tiled=True)
        # dp_recv[r] = this rank's batch as computed by source rank r.

        # --- unpack (rank-uniform), reorder, concat column slices ----------
        worker_order: List[jax.Array] = []
        for r, widths in enumerate(out_widths_list):
            pos = 0
            for w in widths:
                worker_order.append(
                    lax.slice(dp_recv, (r, 0, pos), (r + 1, b, pos + w)
                              ).reshape(b, w))
                pos += w
        result = [worker_order[i] for i in self.strategy.rev_global_input_ids]
        for start, end in self.strategy.sliced_out_ranges:
            result[start:end] = [jnp.concatenate(result[start:end], axis=-1)]
        return result

    def _input_config(self, rank: int, j: int):
        """Config of the table serving the j-th input routed to ``rank``."""
        m = self.strategy.local_map_list[rank][j]
        return self.strategy.local_configs_list[rank][m]

    # ------------------------------------------------------------- checkpoint

    def get_weights(self, flat_params) -> List[np.ndarray]:
        """Reassemble the full (unsliced) global tables on host.

        Equivalent of the reference's chunked-allgather ``get_weights``
        (``dist_model_parallel.py:411-485``); on a single host the sharded
        buffer is addressable, so this is per-rank parse + slice concat.
        """
        flat_params = np.asarray(jax.device_get(flat_params))
        if flat_params.ndim == 1:
            flat_params = flat_params[None]
        per_table: dict = {}
        for r, cfgs in enumerate(self.strategy.local_configs_list):
            pos = 0
            for m, cfg in enumerate(cfgs):
                rows, width = int(cfg["input_dim"]), int(cfg["output_dim"])
                tid = self.strategy.table_ids_list[r][m]
                chunk = flat_params[r, pos:pos + rows * width].reshape(rows, width)
                per_table.setdefault(tid, []).append(chunk)
                pos += rows * width
        result = []
        for tid in range(len(self.strategy.global_configs)):
            result.append(np.concatenate(per_table[tid], axis=1)
                          if len(per_table[tid]) > 1 else per_table[tid][0])
        return result

    def set_weights(self, weights: Sequence[Any], mesh=None,
                    dtype=jnp.float32) -> jax.Array:
        """Build the sharded ``[world, capacity]`` buffer from full global
        tables (numpy arrays or ``np.load``-able paths, mmap'd like the
        reference, ``dist_model_parallel.py:337-339``)."""
        loaded = [np.load(w, mmap_mode="r") if isinstance(w, str) else w
                  for w in weights]
        if len(loaded) != len(self.strategy.global_configs):
            raise ValueError("set_weights needs one array per global table")
        # Column offset of each slice, consumed in rank order per table.
        col_pos = {tid: 0 for tid in range(len(loaded))}
        out = np.zeros((self.world_size, self.capacity), np.float32)
        for r, cfgs in enumerate(self.strategy.local_configs_list):
            pos = 0
            for m, cfg in enumerate(cfgs):
                rows, width = int(cfg["input_dim"]), int(cfg["output_dim"])
                tid = self.strategy.table_ids_list[r][m]
                src = loaded[tid]
                if src.shape[0] != rows:
                    raise ValueError(
                        f"Table {tid}: expected {rows} rows, got {src.shape[0]}")
                start = col_pos[tid]
                out[r, pos:pos + rows * width] = np.ascontiguousarray(
                    src[:, start:start + width]).reshape(-1)
                col_pos[tid] = start + width
                pos += rows * width
        arr = jnp.asarray(out, dtype)
        if mesh is not None:
            sharding = jax.sharding.NamedSharding(
                mesh, jax.sharding.PartitionSpec(self.axis_name))
            arr = jax.device_put(arr, sharding)
        return arr

"""Hybrid-parallel gradient glue.

TPU equivalent of the reference's tape/broadcast monkey-patches
(``dist_model_parallel.py:509-567``): one backward pass produces two gradient
families —

* **dp** (dense/replicated) gradients are averaged across the mesh axis
  (``hvd.allreduce(op=Average)`` per var → ``lax.pmean`` over the pytree);
* **mp** (model-parallel embedding) gradients stay local, scaled by
  ``1/world_size`` so loss-mean-over-local-batch semantics match the averaged
  dp gradients (``dist_model_parallel.py:542-546``).

Instead of tagging variables with ``VariableSynchronization.NONE``
(``:258``), partitioning is expressed as a pytree mask: JAX params are plain
arrays, so callers say which subtree is model-parallel (for
:class:`.DistributedEmbedding` that is its width-grouped slab dict).
"""

from __future__ import annotations

from typing import Any, Callable

import jax
import jax.numpy as jnp
from jax import lax

from .. import compat


def _map_by_mask(fn_mp: Callable, fn_dp: Callable, mask: Any, tree: Any) -> Any:
    """Map ``fn_mp``/``fn_dp`` over ``tree`` leaves according to a boolean mask
    that may be a *prefix* of the tree (optax-style): mapping over the mask
    first lets each mask leaf own a whole subtree."""
    return jax.tree.map(
        lambda m, sub: jax.tree.map(fn_mp if m else fn_dp, sub),
        mask, tree)


def split_mp_dp(tree: Any, mp_mask: Any):
    """Split a pytree into (mp_part, dp_part) by a boolean mask pytree
    (prefix-broadcastable like optax masks); the two parts keep the full
    structure with ``None`` at the other family's leaves."""
    mp = _map_by_mask(lambda g: g, lambda g: None, mp_mask, tree)
    dp = _map_by_mask(lambda g: None, lambda g: g, mp_mask, tree)
    return mp, dp


def resolve_dp_gradient(g: jax.Array, axis_name: str) -> jax.Array:
    """Average a data-parallel gradient across the mesh axis, accounting for
    shard_map's varying-manual-axes (VMA) autodiff semantics.

    Inside ``shard_map`` with replication checking, differentiating a
    device-varying loss w.r.t. an *unvarying* (replicated, ``P()``-spec)
    parameter already inserts the cross-device ``psum`` — the transpose of the
    implicit broadcast — so the raw gradient equals the sum of per-device
    contributions and a further ``pmean`` would be an identity. A gradient
    that is still device-varying needs the explicit ``pmean``. Distinguish by
    the gradient's vma type.

    Requires shard_map's default replication checking (``check_vma=True``):
    under ``check_vma=False`` every value reports an empty vma set, the
    auto-psum does not happen, and this helper cannot tell the two cases
    apart. When no vma typing is present at all, fall back to ``pmean``
    (the pre-VMA semantics).
    """
    vma = compat.vma_of(g)
    if vma is None or axis_name in vma:
        return lax.pmean(g, axis_name)
    return g / compat.axis_size(axis_name)


def hybrid_gradients(grads: Any, mp_mask: Any, axis_name: str) -> Any:
    """Resolve a raw gradient pytree into hybrid-parallel gradients.

    Must run inside ``shard_map``/``pjit`` with ``axis_name`` bound. dp leaves
    are averaged over the axis (see :func:`resolve_dp_gradient`); mp leaves
    are divided by the axis size.
    """
    world = compat.axis_size(axis_name)
    return _map_by_mask(
        lambda g: None if g is None else g / world,
        lambda g: None if g is None else resolve_dp_gradient(g, axis_name),
        mp_mask, grads)


def broadcast_variables(params: Any, mp_mask: Any, axis_name: str,
                        root_rank: int = 0) -> Any:
    """Broadcast dp leaves from ``root_rank``; mp leaves pass through
    untouched (reference ``broadcast_variables``, ``:509-523``).

    Under JAX SPMD replicated arrays are identical by construction, so this is
    only needed when per-device state was deliberately diverged (e.g. seeded
    per-rank init); provided for capability parity and tests.
    """

    def bcast(p):
        if p is None:
            return p
        # psum of the root-masked value: broadcasts without materializing a
        # world-sized all_gather intermediate.
        root = lax.axis_index(axis_name) == root_rank
        return lax.psum(jnp.where(root, p, jnp.zeros_like(p)), axis_name)

    return _map_by_mask(lambda p: p, bcast, mp_mask, params)


def hybrid_value_and_grad(loss_fn: Callable, mp_mask: Any, axis_name: str):
    """``jax.value_and_grad`` wrapper applying :func:`hybrid_gradients` —
    the drop-in analogue of the reference's ``DistributedGradientTape``
    (``dist_model_parallel.py:526-567``)."""
    vg = jax.value_and_grad(loss_fn)

    def wrapped(params, *args, **kwargs):
        value, grads = vg(params, *args, **kwargs)
        return value, hybrid_gradients(grads, mp_mask, axis_name)

    return wrapped

"""Multi-host process bootstrap — the ``hvd.init`` equivalent.

The reference bootstraps one Horovod process per GPU and reads
``hvd.size/rank/local_rank`` everywhere (``dist_model_parallel.py:238-241``;
``examples/dlrm/main.py:152-157``). The TPU-native shape is different: one
process per *host*, all hosts joined into a single JAX runtime by
``jax.distributed.initialize``, after which every process sees the global
device list and SPMD programs span the pod — collectives ride ICI within a
slice and DCN across slices with no further involvement from this layer.

Launch recipe (v5e-16, 4 hosts x 4 chips):

    # on every host, same binary:
    import distributed_embeddings_tpu.parallel.bootstrap as bootstrap
    bootstrap.initialize()          # TPU pods: auto-detected, no args
    mesh = bootstrap.global_mesh()  # 16 devices, axis "data"

On clusters without TPU metadata (or for CPU multi-process tests), pass
``coordinator_address="host0:port", num_processes=N, process_id=i``
explicitly, mirroring ``jax.distributed.initialize``.
"""

from __future__ import annotations

import logging
import os
from typing import Optional, Sequence

import jax
import numpy as np

from .. import compat

logger = logging.getLogger(__name__)


def _cluster_expected() -> bool:
    """True when the environment clearly describes a multi-process job — in
    that case a failed join must raise, not silently degrade into N
    independent single-host runs (each believing it is chief)."""
    hosts = os.environ.get("TPU_WORKER_HOSTNAMES", "")
    if hosts and len(hosts.split(",")) > 1:
        return True
    for var in ("JAX_COORDINATOR_ADDRESS", "COORDINATOR_ADDRESS",
                "MEGASCALE_COORDINATOR_ADDRESS"):
        if os.environ.get(var):
            return True
    for var in ("SLURM_NTASKS", "OMPI_COMM_WORLD_SIZE", "PMI_SIZE"):
        v = os.environ.get(var)
        if v and v.isdigit() and int(v) > 1:
            return True
    return False


def _join_runtime(coordinator_address: Optional[str],
                  num_processes: Optional[int],
                  process_id: Optional[int],
                  local_device_ids: Optional[Sequence[int]]) -> None:
    """One join attempt (separated out so tests can stub it and
    ``DETPU_FAULT=slow:coordinator`` / ``raise:coordinator`` can target
    it without a real cluster)."""
    from ..utils import runtime

    runtime.fault_point("coordinator")
    if compat.distributed_is_initialized():
        # an earlier attempt that "failed" late (e.g. deadline fired on the
        # way out) actually completed — initialize() is not idempotent, so
        # re-invoking it would burn the whole retry budget on its
        # already-initialized guard
        return
    try:
        if coordinator_address is None and num_processes is None:
            jax.distributed.initialize()
        else:
            jax.distributed.initialize(
                coordinator_address=coordinator_address,
                num_processes=num_processes,
                process_id=process_id,
                local_device_ids=local_device_ids)
    except Exception:
        # clear any partially-set global state so the NEXT attempt really
        # rejoins instead of tripping the only-called-once guard. Bounded
        # by its own fresh deadline: the outer per-attempt alarm has
        # already fired by the time we get here, and a shutdown tearing
        # down a half-established connection can itself block
        try:
            with runtime.deadline(10.0, label="distributed shutdown"):
                jax.distributed.shutdown()
        except Exception:  # noqa: BLE001 - nothing (usable) was set up
            pass
        raise


def initialize(coordinator_address: Optional[str] = None,
               num_processes: Optional[int] = None,
               process_id: Optional[int] = None,
               local_device_ids: Optional[Sequence[int]] = None,
               timeout_s: Optional[float] = None,
               retries: int = 2) -> bool:
    """Join the multi-process JAX runtime; safe to call more than once.

    With no arguments, relies on ``jax.distributed.initialize``'s cluster
    auto-detection (TPU pod metadata, Slurm, GKE). Returns True if this call
    performed the initialization, False if it was already done or this is a
    plain single-process run (no args, no detectable cluster).

    Fault tolerance (``utils.runtime``): each join attempt is bounded by
    ``timeout_s`` (best-effort ``SIGALRM`` deadline; ``None`` = no bound)
    and a failed attempt is retried up to ``retries`` times with jittered
    backoff — a *slow* coordinator is a normal operating condition. What a
    failure ultimately means depends on the environment:

    * cluster expected (explicit coordinator args, or the environment
      announces a multi-process job): after the retry budget the error is
      re-raised as :class:`~..utils.runtime.CoordinatorUnreachable` — a pod
      must never silently fall apart into independent single-host trainings
      (each believing it is chief);
    * no cluster detectable: the failure degrades silently into a
      single-process run, as before.
    """
    if compat.distributed_is_initialized():
        return False
    from ..utils import runtime

    expected = (coordinator_address is not None or num_processes is not None
                or _cluster_expected())

    def join_once():
        with runtime.deadline(timeout_s, label="coordinator join"):
            _join_runtime(coordinator_address, num_processes, process_id,
                          local_device_ids)

    import time

    from ..utils import obs

    t0 = time.monotonic()
    if not expected:
        try:
            join_once()
        except Exception as e:  # noqa: BLE001 - single-host degradation
            logger.debug("single-process run (no cluster detected): %s", e)
            return False
        _log_join_success(coordinator_address, time.monotonic() - t0)
        return True
    retries_before = obs.counters().get("runtime_retries", 0)
    try:
        runtime.retry(join_once, max_attempts=retries + 1,
                      describe="coordinator join")
    except Exception as e:
        raise runtime.CoordinatorUnreachable(
            f"cluster expected (coordinator={coordinator_address!r}, "
            f"num_processes={num_processes!r}, detected="
            f"{_cluster_expected()}) but the runtime join kept failing "
            f"after {retries + 1} attempt(s): {e!r}") from e
    # runtime.retry already bumped the global retry counter per attempt;
    # mirror the delta into a bootstrap-specific counter so a metrics
    # record can distinguish "the coordinator was slow" from other retries
    delta = obs.counters().get("runtime_retries", 0) - retries_before
    if delta:
        obs.counter_inc("bootstrap_retries", delta)
    _log_join_success(coordinator_address, time.monotonic() - t0)
    return True


def _log_join_success(coordinator_address: Optional[str],
                      elapsed_s: float) -> None:
    """One INFO line on the success path (the failure paths already log):
    which coordinator, which process slot, how long the join took. Called
    once per successful :func:`initialize`, never per retry attempt."""
    addr = (coordinator_address
            or os.environ.get("JAX_COORDINATOR_ADDRESS")
            or os.environ.get("COORDINATOR_ADDRESS")
            or "auto-detected")
    try:
        pidx, pcnt = jax.process_index(), jax.process_count()
    except Exception:  # noqa: BLE001 - logging must never fail the join
        pidx, pcnt = -1, -1
    logger.info(
        "bootstrap: joined runtime as process %d/%d (coordinator %s) "
        "in %.2fs", pidx, pcnt, addr, elapsed_s)


def process_count() -> int:
    """Number of participating processes (``hvd.size`` is device count in the
    reference; here processes and devices are distinct — see :func:`world`)."""
    return jax.process_count()


def process_index() -> int:
    """This process's index (the reference's ``hvd.rank`` per-GPU analogue is
    a mesh position, not a process)."""
    return jax.process_index()


def world() -> int:
    """Total device count = the ``world_size`` to build
    :class:`~distributed_embeddings_tpu.parallel.DistributedEmbedding` with."""
    return jax.device_count()


def global_mesh(axis_name: str = "data") -> jax.sharding.Mesh:
    """One-axis mesh over every device in the job — the layout the hybrid
    trainer expects (mp positions == dp positions, like the reference)."""
    return jax.sharding.Mesh(np.array(jax.devices()), (axis_name,))


def shard_batch(mesh: jax.sharding.Mesh, tree, axis_name: str = "data"):
    """Assemble global batch arrays from *process-local* shards.

    Each process passes the rows its own data pipeline loaded (the
    reference's per-rank dataset slicing, ``examples/dlrm/main.py:166-190``);
    the result is a pytree of global ``jax.Array`` whose leading axis is
    sharded over ``axis_name``, ready for the hybrid train step.
    """
    sharding = jax.sharding.NamedSharding(
        mesh, jax.sharding.PartitionSpec(axis_name))
    return jax.tree.map(
        lambda x: jax.make_array_from_process_local_data(
            sharding, np.asarray(x)), tree)


def to_host(x) -> np.ndarray:
    """Full host copy of a (possibly process-spanning) array on every process
    — the reference's ``hvd.allgather`` eval-prediction gather
    (``examples/dlrm/main.py:230-243`` there)."""
    if isinstance(x, np.ndarray) or x.is_fully_addressable:
        return np.asarray(x)
    from jax.experimental import multihost_utils

    return np.asarray(multihost_utils.process_allgather(x, tiled=True))


def broadcast_seed(seed: int) -> int:
    """Agree on one seed across processes (the reference's
    ``hvd.broadcast_object(seed)``, ``dist_model_parallel_test.py:92-93``)."""
    from jax.experimental import multihost_utils

    arr = multihost_utils.broadcast_one_to_all(
        np.asarray(seed, dtype=np.int64))
    return int(arr)

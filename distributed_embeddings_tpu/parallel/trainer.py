"""Hybrid-parallel training step builder.

Composes the pieces the reference wires manually in its examples
(``examples/dlrm/main.py:201-210``: tape → ``DistributedGradientTape`` →
``optimizer.apply_gradients``) into one jitted SPMD step:

* dense (data-parallel) parameters: autodiff + ``lax.pmean`` + any optax
  transform;
* embedding (model-parallel) slabs: **no autodiff through the tables** — the
  dense model is differentiated w.r.t. the embedding *activations*, and those
  cotangents feed :meth:`DistributedEmbedding.sparse_apply_gradients`, which
  routes them through the reverse all-to-all and applies per-row scatter
  updates (the IndexedSlices path). The slab and its optimizer state are
  donated, so updates are in-place on device.
"""

from __future__ import annotations

from typing import Any, Callable, NamedTuple, Optional

import jax
import jax.numpy as jnp
import optax
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P

from ..utils import obs
from . import apply as apply_mod
from . import schedule as schedule_mod
from .dist_embedding import DistributedEmbedding
from .grads import resolve_dp_gradient


#: The SINGLE ordering registry of jit-carried trailing aux arguments to
#: the step builders (``make_hybrid_train_step`` / ``_loop`` /
#: ``_eval_step``): ``(kind, parameter_name)`` in the order the aux
#: states trail the fixed ``(state, cat_inputs, batch)`` prefix. Jit
#: donation indices, shard_map in/out specs, checkpoint aux manifests
#: and the resilient driver's rewind all address these positionally, so
#: the order is LOAD-BEARING: a builder that threads them in any other
#: order (or adds an undeclared one) silently donates / rewinds the
#: wrong buffer. The detlint rule ``donated-aux`` reads this tuple by
#: AST and fails ``make lint`` on any step-builder signature whose
#: trailing params are undeclared here or out of this order — add the
#: kind HERE first (future schedule state included), then thread it.
AUX_ARG_REGISTRY = (
    ("telemetry", "telem"),
    ("streaming", "stream"),
)


def _metric_specs(axis_name: str, extra=()):
    """shard_map out_specs for the step-metrics dict: every ``[1]``
    per-device entry concatenates into a ``[world]`` per-rank vector.
    ``extra`` appends conditional key sets (the ``stream_*`` metrics of
    dynamic-table steps)."""
    return {k: P(axis_name) for k in obs.STEP_METRIC_KEYS + tuple(extra)}


def _sq_sum(tree) -> jax.Array:
    """Sum of squares over every leaf of a gradient pytree, in f32."""
    return jax.tree.reduce(
        lambda a, g: a + jnp.sum(jnp.square(g.astype(jnp.float32))),
        tree, jnp.float32(0.0))


def _table_sentinels(de, out_grads, lr):
    """Per-table numerical health sentinels, computed from this device's
    embedding cotangents (O(ids) — never a slab-wide pass): the three
    ``table_*`` entries of :data:`~..utils.obs.STEP_METRIC_KEYS`, each
    ``[1, n_tables]`` so ``out_specs=P(axis)`` stacks them to
    ``[world, n_tables]``. The cotangent is what the sparse backward
    scatters into the slab (times ``lr/world`` for the linear SGD path),
    so a non-finite or exploding entry here IS the row update that would
    have poisoned — or did poison — the named table. Inputs sharing a
    table (``input_table_map``) fold into that table's entry; the update
    bound uses the ``1/world`` pre-scale :meth:`~.dist_embedding.
    DistributedEmbedding.sparse_apply_gradients` defaults to."""
    n_tables = len(de.strategy.global_configs)
    tmap = de.strategy.input_table_map
    per_input = []
    for g in out_grads:
        g32 = g.astype(jnp.float32)
        per_input.append((jnp.sum(jnp.square(g32)),
                          jnp.max(jnp.abs(g32)),
                          jnp.sum(jnp.logical_not(jnp.isfinite(g32)),
                                  dtype=jnp.int32)))
    # a device-varying REAL zero (shard_map vma): tables with no input
    # still need entries, and ``x * 0.0`` would be NaN exactly when the
    # cotangent is — the case these sentinels exist to count
    zvar = de._vary(jnp.float32(0.0))
    sq, mx, nf = [], [], []
    for t in range(n_tables):
        mine = [per_input[i] for i, tt in enumerate(tmap) if tt == t]
        sq.append(sum((m[0] for m in mine), zvar))
        mx.append(jnp.maximum(zvar,
                              jnp.stack([m[1] for m in mine]).max())
                  if mine else zvar)
        nf.append(sum((m[2].astype(jnp.float32) for m in mine), zvar))
    scale = jnp.float32(lr) / de.world_size
    return {
        "table_grad_norm": jnp.sqrt(jnp.stack(sq)).reshape(1, n_tables),
        "table_update_maxabs": (jnp.abs(scale)
                                * jnp.stack(mx)).reshape(1, n_tables),
        "table_nonfinite": jnp.stack(nf).reshape(1, n_tables),
    }


def _microbatch_count(de) -> int:
    """The schedule-declared microbatch count the step builders split
    by (1 = the serialized program, traced through the exact pre-
    pipelining code path)."""
    return int(getattr(de.schedule, "microbatches", 1) or 1)


def _microbatch_inputs(cat_inputs, batch, K: int):
    """Split one per-device batch into K microbatch slices along the
    leading batch dimension: ``[(cat_inputs_k, batch_k), ...]``.

    Dense categorical inputs and every ``batch`` pytree leaf slice rows
    ``[k*b/K, (k+1)*b/K)``. A :class:`~...ops.embedding_lookup.Ragged`
    keeps its FULL static capacity per microbatch (the id count per row
    is dynamic, so a smaller static capacity could truncate a skewed
    microbatch): values gather from the CSR offset of the microbatch's
    first row, row_splits rebase to 0. A COO
    :class:`~...ops.embedding_lookup.SparseIds` converts to CSR first —
    the same conversion the forward's input normalization applies.
    ``b % K != 0`` raises at trace time (unequal microbatches would
    break the exact mean-of-means loss accumulation)."""
    from ..ops.embedding_lookup import Ragged, SparseIds, row_to_split

    def norm(x):
        if isinstance(x, SparseIds):
            return Ragged(values=x.values,
                          row_splits=row_to_split(x.indices,
                                                  x.dense_shape[0]),
                          weights=x.weights)
        return x

    cats = [norm(c) for c in cat_inputs]

    def rows_of(x):
        return x.nrows if isinstance(x, Ragged) else x.shape[0]

    if cats:
        b = rows_of(cats[0])
    else:
        b = jax.tree_util.tree_leaves(batch)[0].shape[0]
    if b % K:
        raise ValueError(
            f"pipelined step: per-device batch {b} does not divide into "
            f"{K} microbatches — pick K | batch (DETPU_MICROBATCH / the "
            "pipelined_schedule argument)")
    mbb = b // K

    def slice_cat(x, k):
        if isinstance(x, Ragged):
            splits = x.row_splits
            lo = splits[k * mbb]
            sub = lax.slice_in_dim(splits, k * mbb, (k + 1) * mbb + 1,
                                   axis=0) - lo
            cap = x.values.shape[0]
            idx = lo + jnp.arange(cap, dtype=splits.dtype)
            vals = jnp.take(x.values, idx, mode="clip")
            wts = (jnp.take(x.weights, idx, mode="clip")
                   if x.weights is not None else None)
            return Ragged(values=vals, row_splits=sub, weights=wts)
        return lax.slice_in_dim(x, k * mbb, (k + 1) * mbb, axis=0)

    out = []
    for k in range(K):
        cats_k = [slice_cat(c, k) for c in cats]
        batch_k = jax.tree.map(
            lambda a, k=k: lax.slice_in_dim(a, k * mbb, (k + 1) * mbb,
                                            axis=0), batch)
        out.append((cats_k, batch_k))
    return out


def _apply_dense_and_assemble(de, state, emb_local, emb_opt_local,
                              new_emb, new_emb_opt, dense_grads,
                              dense_tx, ok, nan_guard):
    """Shared step epilogue of the serialized and pipelined bodies: the
    dense optimizer update, the non-finite guard's small-leaf
    where-selects, and the new-state assembly — ONE body so the guard's
    skip semantics can never drift between the two step variants.

    Slab-shaped leaves are already protected by the sentinel-gated
    scatters; only the small leaves need an explicit select — the dense
    params/opt state (MBs) and non-slab embedding-optimizer aux (Adam's
    step count), never the GB-scale slabs."""
    with obs.scope("dense_update"):
        updates, dense_opt_state = dense_tx.update(
            dense_grads, state.dense_opt_state, state.dense_params)
        dense_params = optax.apply_updates(state.dense_params, updates)

    if nan_guard:
        slab_shapes = {tuple(v.shape) for v in emb_local.values()}

        def sel(new, old):
            return jax.tree.map(lambda n, o: jnp.where(ok, n, o), new, old)

        new_emb_opt = jax.tree.map(
            lambda n, o: (n if tuple(n.shape) in slab_shapes
                          else jnp.where(ok, n, o)),
            new_emb_opt, emb_opt_local)
        dense_params = sel(dense_params, state.dense_params)
        dense_opt_state = sel(dense_opt_state, state.dense_opt_state)

    return HybridTrainState(
        emb_params=de.stacked_view(new_emb),
        emb_opt_state=de.stacked_view(new_emb_opt),
        dense_params=dense_params, dense_opt_state=dense_opt_state,
        step=state.step + 1)


def _finish_metrics(de, metrics, out_grads, dense_grads, loss, ok, state,
                    sstats, lr):
    """Shared tail of the instrumented step's metrics dict (sentinels,
    norms, loss/step/skip counters, ``stream_*`` stats) — the pipelined
    step passes the exactly-reassembled full-batch cotangents so every
    entry keeps serialized semantics."""
    with obs.scope("health_sentinels"):
        # per-table numerical health, next to the nan-guard: names WHICH
        # table's cotangents went non-finite/exploded (the recovery log's
        # "table 3 went unhealthy at step k", not just "step k skipped")
        metrics.update(_table_sentinels(de, out_grads, lr))
    # out_grads are device-varying; the pmean'd loss / resolved dense
    # grads / replicated step are not — _vary marks them for P(axis) out
    metrics["emb_grad_norm"] = jnp.sqrt(_sq_sum(out_grads)).reshape(1)
    metrics["dense_grad_norm"] = de._vary(
        jnp.sqrt(_sq_sum(dense_grads)).reshape(1))
    metrics["loss"] = de._vary(loss.astype(jnp.float32).reshape(1))
    skipped = ((1 - ok.astype(jnp.int32)).reshape(1) if ok is not None
               else jnp.zeros((1,), jnp.int32))
    metrics["skipped_steps"] = de._vary(skipped)
    metrics["step"] = de._vary(state.step.astype(jnp.int32).reshape(1))
    if sstats is not None:
        # this step's (guard-gated) slot-map transition counts — derived
        # from the device-varying routed ids, so P(axis) stacks them per
        # rank like every other metric
        for k, v in sstats.items():
            metrics[f"stream_{k}"] = v
    return metrics


def _pipelined_local_step(de, loss_fn, dense_tx, emb_optimizer,
                          lr_schedule, state, cat_inputs, batch, K,
                          with_metrics=False, nan_guard=False,
                          telemetry_cfg=None, telem=None,
                          streaming_cfg=None, sstate=None):
    """The K-microbatch software-pipelined hybrid step (ROADMAP item 2;
    built when ``de.schedule`` is a :func:`~.schedule.pipelined_schedule`
    with K > 1 — K == 1 never reaches here, it traces the serialized
    program bitwise).

    The per-device batch splits into K microbatches; each runs its own
    id-exchange → lookup → out-exchange → dense fwd/bwd chain under
    ``_mb{k}``-suffixed phase scopes. The chains share NO data
    dependencies until the accumulation point — all microbatches read
    the same parameters, gradients accumulate, ONE dense update and ONE
    sparse apply per width slab run at the end — so XLA's scheduler is
    free to ship microbatch k+1's all-to-alls while microbatch k's
    dense compute runs (the overlap the schedule declares and
    ``make schedule-audit`` / ``make phase-profile`` certify).

    Numerics vs the serialized step: the accumulation leans on the step
    builders' documented ``loss_fn`` contract — a *plain (unweighted)
    mean* over the per-device batch shard. Under that contract each
    microbatch loss is a mean over b/K rows, so per-row cotangents are
    K× the full-batch ones and the 1/K accumulation scale restores them
    exactly for power-of-two K. A loss that is NOT an unweighted mean —
    a sum reduction, or a masked/weighted mean whose denominator varies
    per row subset — violates that contract and silently trains a
    different trajectory under K > 1 (mean-of-means ≠ overall mean);
    keep such losses on the serialized schedule or fold the weighting
    into per-row terms of an unweighted mean. Dense gradients average across microbatches (one pmean per leaf,
    after accumulation — the psum census is K-invariant), the sparse
    apply concatenates the per-microbatch update streams into the same
    single scatter per width slab, and streaming admission stages ONCE
    over the concatenated raw id streams (bitwise the serialized
    decisions — :meth:`~.dist_embedding.DistributedEmbedding
    .streaming_stage`). K > 1 trajectories are float-rounding-
    equivalent, not bitwise: the scatter-add accumulation order over
    duplicate ids differs (microbatch-major instead of batch-major).
    """
    world = de.world_size
    if not de.dp_input:
        raise NotImplementedError(
            "pipelined schedules need dp inputs: mp-input mode has no id "
            "exchange to hide (use dp_input=True or a serialized "
            "schedule)")
    emb_local = de.local_view(state.emb_params)
    emb_opt_local = de.local_view(state.emb_opt_state)
    mbs = _microbatch_inputs(cat_inputs, batch, K)

    losses = []
    dense_grads_list = []
    out_grads_list = []
    res_list = []
    serve_list = []
    for k, (cats_k, batch_k) in enumerate(mbs):
        tag = schedule_mod.microbatch_tag(k)
        with obs.scope(f"embedding_forward{tag}"):
            if streaming_cfg is not None:
                outs, res, serve = de.forward_with_residuals(
                    emb_local, cats_k,
                    streaming=(streaming_cfg, sstate, "serve"),
                    phase_tag=tag)
                serve_list.append(serve)
            else:
                outs, res = de.forward_with_residuals(emb_local, cats_k,
                                                      phase_tag=tag)
        with obs.scope(schedule_mod.PHASE_DENSE + tag):
            loss_k, (dgrads_k, ograds_k) = jax.value_and_grad(
                loss_fn, argnums=(0, 1))(state.dense_params, outs,
                                         batch_k)
        losses.append(loss_k)
        dense_grads_list.append(dgrads_k)
        out_grads_list.append(ograds_k)
        res_list.append(res)

    inv_k = 1.0 / K
    loss = sum(losses[1:], losses[0]) * inv_k
    dense_grads = jax.tree.map(
        lambda *gs: sum(gs[1:], gs[0]) * inv_k, *dense_grads_list)
    if world > 1:
        loss = lax.pmean(loss, de.axis_name)
        dense_grads = jax.tree.map(
            lambda g: resolve_dp_gradient(g, de.axis_name), dense_grads)

    new_telem = None
    if telemetry_cfg is not None:
        # ONE sketch fold + top-k merge over every microbatch's routed
        # ids — the serialized step's telemetry input, reassembled
        with obs.scope("telemetry"):
            new_telem = de.update_telemetry(telem, res_list,
                                            telemetry_cfg)

    # the serialized step's full-batch cotangents, reassembled exactly:
    # concatenate per input across microbatches and undo the K× mean
    # scaling (exact for power-of-two K) — feeds the guard probe, the
    # health sentinels, and the grad-norm metrics with serialized
    # semantics
    cat_grads = [
        jnp.concatenate([og[i] for og in out_grads_list], axis=0) * inv_k
        for i in range(len(out_grads_list[0]))]

    ok = None
    if nan_guard:
        with obs.scope("nanguard"):
            # same lockstep-verdict construction as the serialized step
            # (one pmean — the psum census is K-invariant)
            probe = jnp.float32(0.0) * _sq_sum(cat_grads)
            if world > 1:
                probe = lax.pmean(probe, de.axis_name)
            ok = (jnp.isfinite(loss.astype(jnp.float32))
                  & jnp.isfinite(_sq_sum(dense_grads))
                  & jnp.isfinite(probe))

    lr = lr_schedule(state.step) if callable(lr_schedule) else lr_schedule

    spending = None
    if streaming_cfg is not None:
        # ONE admission-staging pass over the concatenated raw streams:
        # bitwise the serialized step's transition decisions, and an
        # independent compute chain next to every out/grad exchange
        spending = de.streaming_stage(serve_list, streaming_cfg, sstate)

    # per-microbatch reverse exchanges + stream rebuilds (each under its
    # own phase, overlapping other microbatches' dense compute), merged
    # into ONE optimizer scatter per width slab — grad accumulation
    # without a second pass over the slabs
    per_width = {}
    fallback = next(iter(emb_local.values())).dtype
    for k in range(K):
        tag = schedule_mod.microbatch_tag(k)
        with obs.scope(f"sparse_bwd{tag}"):
            pw = apply_mod.cotangent_width_streams(
                de, res_list[k], out_grads_list[k],
                fallback_dtype=fallback, tag=tag)
        for key, tris in pw.items():
            per_width.setdefault(key, []).extend(tris)
    with obs.scope("sparse_apply"):
        new_emb, new_emb_opt = apply_mod.apply_width_streams(
            de, emb_local, emb_opt_local, per_width, emb_optimizer, lr,
            scale=1.0 / (world * K), enable=ok)

    new_sstate = None
    sstats = None
    if streaming_cfg is not None:
        from . import streaming as streaming_mod

        with obs.scope("streaming_commit"):
            new_emb, new_emb_opt, new_sstate, sstats = streaming_mod.commit(
                de, new_emb, spending, sstate, enable=ok,
                opt_state=new_emb_opt, optimizer=emb_optimizer)

    new_state = _apply_dense_and_assemble(
        de, state, emb_local, emb_opt_local, new_emb, new_emb_opt,
        dense_grads, dense_tx, ok, nan_guard)
    aux_out = ()
    if new_telem is not None:
        aux_out += (new_telem,)
    if new_sstate is not None:
        aux_out += (new_sstate,)
    if not with_metrics:
        return (loss, new_state) + aux_out
    metrics = None
    out_dtype = cat_grads[0].dtype if cat_grads else None
    for res in res_list:
        m = de.step_metrics(res, out_dtype=out_dtype)
        if metrics is None:
            metrics = m
        else:
            for mk in m:
                if mk == "out_pad_frac":
                    continue  # static plan property, equal per microbatch
                metrics[mk] = metrics[mk] + m[mk]
    metrics = _finish_metrics(de, metrics, cat_grads, dense_grads, loss,
                              ok, state, sstats, lr)
    return (loss, new_state, metrics) + aux_out


def _hybrid_local_step(de, loss_fn, dense_tx, emb_optimizer, lr_schedule,
                       state, cat_inputs, batch, with_metrics=False,
                       nan_guard=False, telemetry_cfg=None, telem=None,
                       streaming_cfg=None, sstate=None):
    """One per-device hybrid step (shared by :func:`make_hybrid_train_step`
    and :func:`make_hybrid_train_loop`): forward, one backward producing dp
    gradients (pmean-averaged) and mp cotangents (manual sparse path), both
    optimizer updates, step counter bump.

    ``with_metrics=True`` (static, trace-time) additionally returns the
    :data:`~..utils.obs.STEP_METRIC_KEYS` dict — the embedding layer's
    exchange/overflow metrics plus loss, grad norms, and the step counter.

    ``nan_guard=True`` (static, trace-time; default follows
    ``DETPU_NANGUARD``, which defaults ON) checks the loss and both
    gradient energies for NaN/Inf *inside* the step and, on a non-finite
    verdict, skips the dense AND sparse updates so params and optimizer
    state come out bitwise-unchanged: the slab scatters route every row to
    the dropped sentinel (O(ids) masking, never a slab-wide select) and
    the small dense/aux leaves are ``where``-selected. The step counter
    still advances (the poisoned batch is skipped, not retried), the
    returned loss stays the true non-finite value so the host driver can
    count consecutive skips and escalate, and under ``with_metrics`` the
    per-device ``skipped_steps`` metric flags the skip.

    ``telemetry_cfg`` (static) + ``telem`` (this device's jit-carried
    access-telemetry state, :mod:`~..analysis.telemetry`): when given,
    the step folds the forward's routed ids into the hot-row sketches
    and load accumulators and RETURNS the updated telemetry state as its
    LAST element. Telemetry reads the same residual tensors the metrics
    do and touches nothing in the parameter/optimizer path — with it off
    the step is bit-for-bit the pre-telemetry program.

    ``streaming_cfg`` (static) + ``sstate`` (this device's jit-carried
    streaming-vocab state, :mod:`.streaming`): when given, the forward
    remaps every streaming table's external ids through the slot map
    (admitted ids read their slot, everything else its shared hash
    bucket) and STAGES the admission/eviction transitions; they COMMIT
    next to the nan-guard — a guard-skipped step leaves the slot map,
    sketch, and slabs bitwise-unchanged, exactly like the optimizer
    state, so the rollback/quarantine machinery sees one coherent
    trajectory. The updated streaming state returns as the step's LAST
    element (after the telemetry state when both ride).

    A ``de.schedule`` with ``microbatches > 1`` (a
    :func:`~.schedule.pipelined_schedule`) routes to
    :func:`_pipelined_local_step` — the K-microbatch latency-hiding
    program with identical call/return signature. K == 1 (the default
    and every serialized schedule) traces THIS body unchanged, so the
    serialized program stays bitwise the pre-pipelining step.
    """
    K = _microbatch_count(de)
    if K > 1:
        return _pipelined_local_step(
            de, loss_fn, dense_tx, emb_optimizer, lr_schedule, state,
            cat_inputs, batch, K, with_metrics=with_metrics,
            nan_guard=nan_guard, telemetry_cfg=telemetry_cfg, telem=telem,
            streaming_cfg=streaming_cfg, sstate=sstate)
    world = de.world_size
    # slabs are {width: [world, rows, w]} globally -> [rows, w] per device
    emb_local = de.local_view(state.emb_params)
    emb_opt_local = de.local_view(state.emb_opt_state)
    with obs.scope("embedding_forward"):
        if streaming_cfg is not None:
            outs, res, spending = de.forward_with_residuals(
                emb_local, cat_inputs, streaming=(streaming_cfg, sstate))
        else:
            outs, res = de.forward_with_residuals(emb_local, cat_inputs)
    new_telem = None
    if telemetry_cfg is not None:
        with obs.scope("telemetry"):
            new_telem = de.update_telemetry(telem, res, telemetry_cfg)

    with obs.scope("dense_forward_backward"):
        loss, (dense_grads, out_grads) = jax.value_and_grad(
            loss_fn, argnums=(0, 1))(state.dense_params, outs, batch)
    if world > 1:
        loss = lax.pmean(loss, de.axis_name)
        dense_grads = jax.tree.map(
            lambda g: resolve_dp_gradient(g, de.axis_name), dense_grads)

    ok = None
    if nan_guard:
        with obs.scope("nanguard"):
            # 0 * (local embedding-cotangent energy) is 0 when finite and
            # NaN otherwise; the pmean propagates one device's verdict to
            # every device so all ranks skip in LOCKSTEP — a half-applied
            # step would desync the replicated dense params from the
            # sharded slabs (the routed cotangent blocks carry the NaN to
            # every rank's scatter anyway)
            probe = jnp.float32(0.0) * _sq_sum(out_grads)
            if world > 1:
                probe = lax.pmean(probe, de.axis_name)
            ok = (jnp.isfinite(loss.astype(jnp.float32))
                  & jnp.isfinite(_sq_sum(dense_grads))
                  & jnp.isfinite(probe))

    lr = lr_schedule(state.step) if callable(lr_schedule) else lr_schedule
    with obs.scope("sparse_apply"):
        new_emb, new_emb_opt = de.sparse_apply_gradients(
            emb_local, emb_opt_local, res, out_grads, emb_optimizer, lr,
            enable=ok)

    new_sstate = None
    sstats = None
    if streaming_cfg is not None:
        from . import streaming as streaming_mod

        # commit AFTER the optimizer scatter and UNDER the guard verdict:
        # claimed rows zero post-apply (the evictee's last update is
        # dropped with its slot), slab-shaped optimizer moments reset to
        # the optimizer's fresh-row value in the same commit scatter (an
        # admitted id trains from a fresh-init row AND fresh-init
        # moments, not the evictee's leftovers), and a skipped step
        # leaves slot map, sketch, counters, slabs and moments
        # bitwise-unchanged
        with obs.scope("streaming_commit"):
            new_emb, new_emb_opt, new_sstate, sstats = streaming_mod.commit(
                de, new_emb, spending, sstate, enable=ok,
                opt_state=new_emb_opt, optimizer=emb_optimizer)

    new_state = _apply_dense_and_assemble(
        de, state, emb_local, emb_opt_local, new_emb, new_emb_opt,
        dense_grads, dense_tx, ok, nan_guard)
    aux_out = ()
    if new_telem is not None:
        aux_out += (new_telem,)
    if new_sstate is not None:
        aux_out += (new_sstate,)
    if not with_metrics:
        return (loss, new_state) + aux_out
    metrics = de.step_metrics(
        res, out_dtype=out_grads[0].dtype if out_grads else None)
    metrics = _finish_metrics(de, metrics, out_grads, dense_grads, loss,
                              ok, state, sstats, lr)
    return (loss, new_state, metrics) + aux_out


class HybridTrainState(NamedTuple):
    """All mutable training state. ``emb_params``/``emb_opt_state`` are the
    model-parallel slab dicts ``{width: [world, phys_rows, phys_width]}``
    (lane-packed for narrow widths, see ``ops/packed_slab.py``); the rest
    is replicated."""
    emb_params: Any
    emb_opt_state: Any
    dense_params: Any
    dense_opt_state: Any
    step: jax.Array


def _with_aux_signature(core, tel_on: bool, dyn_on: bool):
    """Give ``core(state, cat, batch, aux_tuple)`` the explicit
    positional signature its aux combination implies — jit donation and
    shard_map specs then address plain positional args (aux order:
    telemetry, then streaming)."""
    if tel_on and dyn_on:
        def step(state, cat_inputs, batch, telem, stream):
            return core(state, cat_inputs, batch, (telem, stream))
    elif tel_on:
        def step(state, cat_inputs, batch, telem):
            return core(state, cat_inputs, batch, (telem,))
    elif dyn_on:
        def step(state, cat_inputs, batch, stream):
            return core(state, cat_inputs, batch, (stream,))
    else:
        def step(state, cat_inputs, batch):
            return core(state, cat_inputs, batch, ())
    return step


def make_hybrid_train_step(de: DistributedEmbedding,
                           loss_fn: Callable,
                           dense_tx: optax.GradientTransformation,
                           emb_optimizer,
                           mesh=None,
                           lr_schedule=1.0,
                           with_metrics: Optional[bool] = None,
                           nan_guard: Optional[bool] = None,
                           telemetry=None,
                           dynamic=None):
    """Build ``step(state, cat_inputs, batch) -> (loss, state)``.

    Args:
      de: the distributed embedding layer.
      loss_fn: ``loss_fn(dense_params, emb_outputs, batch) -> scalar`` local
        mean loss over the per-device batch shard.
      dense_tx: optax transform for the dense (data-parallel) parameters.
      emb_optimizer: sparse slab optimizer (:class:`~.optimizers.SparseSGD` /
        :class:`~.optimizers.SparseAdagrad`).
      mesh: required when ``de.world_size > 1``.
      lr_schedule: embedding-optimizer learning rate — a constant or a
        ``step -> lr`` callable (the dense side can use optax schedules
        natively).
      with_metrics: instrument the step with on-device observability
        metrics — the step then returns ``(loss, state, metrics)`` where
        ``metrics`` is the :data:`~..utils.obs.STEP_METRIC_KEYS` dict of
        per-rank ``[world]`` vectors (exchange bytes, routed-id counts,
        ragged-overflow counters, grad norms). ``None`` (default) follows
        ``DETPU_OBS=1``, so an uninstrumented run keeps the 2-tuple
        signature and pays nothing.
      nan_guard: build the step with the on-device non-finite guard — a
        NaN/Inf loss or gradient energy skips BOTH optimizer updates with
        params and optimizer state bitwise-unchanged, advances the step
        counter, returns the true (non-finite) loss, and flags
        ``skipped_steps`` in the metrics. ``None`` (default) follows
        ``DETPU_NANGUARD``, which defaults ON (see
        :func:`~..utils.obs.nanguard_enabled`).
      telemetry: carry jit-threaded access telemetry
        (:mod:`~..analysis.telemetry`: per-table hot-row sketches +
        per-rank load accounting) through the step. EXPLICIT opt-in —
        off by default (``None``/``False``); ``True`` uses the
        ``DETPU_TELEMETRY_*`` sketch geometry; a
        :class:`~..analysis.telemetry.TelemetryConfig` pins it. (No env
        default: telemetry changes the step's CALL arity, so an env
        variable must never flip it under a 3-arg call site — the
        telemetry-aware entry points read ``DETPU_TELEMETRY``
        themselves.) When on,
        the step takes a fourth argument — the telemetry state from
        :func:`~..analysis.telemetry.init_telemetry` (donated, like the
        train state) — and returns the updated state as its LAST
        element: ``step(state, cat_inputs, batch, telem) -> (loss,
        state[, metrics], telem)``. The parameter/optimizer math is
        untouched: telemetry-off steps are bit-for-bit the pre-telemetry
        program, telemetry-on steps change only the extra output.

    ``dynamic`` opts the step into streaming-vocab mode
    (:mod:`.streaming`) with the same explicit-opt-in contract as
    ``telemetry`` (``None``/``False`` off, ``True`` env policy, a
    :class:`~.streaming.StreamingConfig` pins it): the step takes the
    jit-carried streaming state (:func:`~.streaming.init_streaming`,
    donated) as one more trailing argument — AFTER the telemetry state
    when both ride — and returns the updated state last. Under
    ``with_metrics`` the :data:`~..utils.obs.STREAMING_METRIC_KEYS`
    entries join the metrics dict.

    The returned step takes data-parallel shards: each categorical input
    ``[local_batch, hotness]`` and ``batch`` any pytree of per-device arrays
    the loss consumes (already sharded by the caller).
    """
    from ..analysis import telemetry as tel
    from . import streaming as streaming_mod

    world = de.world_size
    if with_metrics is None:
        with_metrics = obs.metrics_enabled()
    if nan_guard is None:
        nan_guard = obs.nanguard_enabled()
    tel_cfg = tel.resolve_config(telemetry)
    dyn_cfg = streaming_mod.resolve_config(dynamic)
    n_aux = (tel_cfg is not None) + (dyn_cfg is not None)

    def core(state: HybridTrainState, cat_inputs, batch, aux):
        i = 0
        telem = sstate = None
        if tel_cfg is not None:
            telem = tel.local_state(aux[i])
            i += 1
        if dyn_cfg is not None:
            sstate = streaming_mod.local_state(aux[i])
        out = _hybrid_local_step(de, loss_fn, dense_tx, emb_optimizer,
                                 lr_schedule, state, cat_inputs, batch,
                                 with_metrics=with_metrics,
                                 nan_guard=nan_guard,
                                 telemetry_cfg=tel_cfg, telem=telem,
                                 streaming_cfg=dyn_cfg, sstate=sstate)
        if not n_aux:
            return out
        head, aux_out = out[:-n_aux], list(out[-n_aux:])
        stacked = []
        if tel_cfg is not None:
            stacked.append(tel.stacked_state(aux_out.pop(0)))
        if dyn_cfg is not None:
            stacked.append(streaming_mod.stacked_state(aux_out.pop(0)))
        return head + tuple(stacked)

    local_step = _with_aux_signature(core, tel_cfg is not None,
                                     dyn_cfg is not None)
    donate = (0,) + tuple(range(3, 3 + n_aux))
    if world == 1:
        return jax.jit(local_step, donate_argnums=donate)

    if mesh is None:
        raise ValueError("mesh is required for world_size > 1")
    ax = de.axis_name
    state_specs = HybridTrainState(
        emb_params=P(ax), emb_opt_state=P(ax),
        dense_params=P(), dense_opt_state=P(), step=P())
    mspecs = _metric_specs(
        ax, obs.STREAMING_METRIC_KEYS if dyn_cfg is not None else ())
    out_specs = ((P(), state_specs, mspecs) if with_metrics
                 else (P(), state_specs))
    in_specs = (state_specs, P(ax), P(ax)) + (P(ax),) * n_aux
    out_specs = out_specs + (P(ax),) * n_aux

    sm = jax.shard_map(
        local_step, mesh=mesh,
        in_specs=in_specs,
        out_specs=out_specs)
    return jax.jit(sm, donate_argnums=donate)


def make_hybrid_train_loop(de: DistributedEmbedding,
                           loss_fn: Callable,
                           dense_tx: optax.GradientTransformation,
                           emb_optimizer,
                           mesh=None,
                           lr_schedule=1.0,
                           unroll: int = 1,
                           with_metrics: Optional[bool] = None,
                           nan_guard: Optional[bool] = None,
                           telemetry=None,
                           dynamic=None):
    """Multi-step training driver: ``loop(state, cat_stacks, batch_stacks)
    -> (losses [K], state)`` running K steps inside ONE compiled program via
    ``lax.scan``.

    ``with_metrics`` (default: follow ``DETPU_OBS=1``) instruments every
    scanned step like :func:`make_hybrid_train_step`: the loop then
    returns ``(losses [K], state, metrics)`` with each metrics entry
    stacked ``[K, world]`` (one row per scanned step).

    Per-step host dispatch costs real wall-clock (through this repo's
    benchmark tunnel it measured ~25 ms/step — 25% of the DLRM headline
    step); production TPU input pipelines amortize it by driving several
    steps per dispatch. Inputs carry a leading scan axis K: each categorical
    input ``[K, local_batch, ...]`` (Ragged: values ``[K, cap]``, row_splits
    ``[K, b+1]``), ``batch`` any pytree with leading K.

    The per-step semantics (gradients, optimizer updates, step counter) are
    exactly :func:`make_hybrid_train_step`'s — same ``local_step`` body,
    non-finite guard included (``nan_guard``, default ``DETPU_NANGUARD``):
    a poisoned batch inside the scan skips its own updates and the
    remaining scanned steps proceed from the untouched state.

    ``telemetry`` (explicit opt-in, same contract as
    :func:`make_hybrid_train_step`) threads the access-telemetry state
    through the scan carry exactly like the single step: ``loop(state,
    cat_stacks, batch_stacks, telem) -> (losses, state[, metrics],
    telem)`` — every scanned step folds its ids in, ONE carried state
    for the whole dispatch.

    ``dynamic`` (explicit opt-in, same contract as the single step's)
    threads the streaming-vocab state through the scan carry the same
    way — slot-map admissions/evictions accumulate across the scanned
    steps inside one compiled program; the state rides AFTER the
    telemetry state when both are on.
    """
    from ..analysis import telemetry as tel
    from . import streaming as streaming_mod

    world = de.world_size
    if with_metrics is None:
        with_metrics = obs.metrics_enabled()
    if nan_guard is None:
        nan_guard = obs.nanguard_enabled()
    tel_cfg = tel.resolve_config(telemetry)
    dyn_cfg = streaming_mod.resolve_config(dynamic)
    n_aux = (tel_cfg is not None) + (dyn_cfg is not None)

    def body(carry, xs):
        cat_inputs, batch = xs
        state = carry[0] if n_aux else carry
        aux = carry[1:] if n_aux else ()
        i = 0
        telem = sstate = None
        if tel_cfg is not None:
            telem = aux[i]
            i += 1
        if dyn_cfg is not None:
            sstate = aux[i]
        out = _hybrid_local_step(
            de, loss_fn, dense_tx, emb_optimizer, lr_schedule, state,
            cat_inputs, batch, with_metrics=with_metrics,
            nan_guard=nan_guard, telemetry_cfg=tel_cfg, telem=telem,
            streaming_cfg=dyn_cfg, sstate=sstate)
        new_aux = out[len(out) - n_aux:] if n_aux else ()
        out = out[:len(out) - n_aux] if n_aux else out
        if with_metrics:
            loss, state, metrics = out
            ys = (loss, metrics)
        else:
            loss, state = out
            ys = loss
        return ((state,) + tuple(new_aux) if n_aux else state), ys

    def run_scan(carry, cat_stacks, batch_stacks):
        # shared by world == 1 and shard_map (_hybrid_local_step already
        # pmeans the loss and resolves dp gradients for world > 1)
        carry, ys = lax.scan(body, carry, (cat_stacks, batch_stacks),
                             unroll=unroll)
        if with_metrics:
            losses, metrics = ys  # metrics leaves stacked [K, 1]
            return carry, (losses, metrics)
        return carry, (ys, None)

    def core(state, cat_stacks, batch_stacks, aux):
        # local/stacked views once per dispatch, not per scanned step
        i = 0
        locals_ = []
        if tel_cfg is not None:
            locals_.append(tel.local_state(aux[i]))
            i += 1
        if dyn_cfg is not None:
            locals_.append(streaming_mod.local_state(aux[i]))
        carry = (state,) + tuple(locals_) if n_aux else state
        carry, (losses, metrics) = run_scan(carry, cat_stacks,
                                            batch_stacks)
        state = carry[0] if n_aux else carry
        stacked = []
        if n_aux:
            aux_out = list(carry[1:])
            if tel_cfg is not None:
                stacked.append(tel.stacked_state(aux_out.pop(0)))
            if dyn_cfg is not None:
                stacked.append(streaming_mod.stacked_state(aux_out.pop(0)))
        head = ((losses, state, metrics) if with_metrics
                else (losses, state))
        return head + tuple(stacked)

    local_loop = _with_aux_signature(core, tel_cfg is not None,
                                     dyn_cfg is not None)
    donate = (0,) + tuple(range(3, 3 + n_aux))
    if world == 1:
        return jax.jit(local_loop, donate_argnums=donate)

    if mesh is None:
        raise ValueError("mesh is required for world_size > 1")
    ax = de.axis_name
    state_specs = HybridTrainState(
        emb_params=P(ax), emb_opt_state=P(ax),
        dense_params=P(), dense_opt_state=P(), step=P())
    loop_keys = obs.STEP_METRIC_KEYS + (
        obs.STREAMING_METRIC_KEYS if dyn_cfg is not None else ())
    out_specs = ((P(), state_specs,
                  {k: P(None, ax) for k in loop_keys})
                 if with_metrics else (P(), state_specs))
    in_specs = (state_specs, P(None, ax), P(None, ax)) + (P(ax),) * n_aux
    out_specs = out_specs + (P(ax),) * n_aux

    sm = jax.shard_map(
        local_loop, mesh=mesh,
        in_specs=in_specs,
        out_specs=out_specs)
    return jax.jit(sm, donate_argnums=donate)


def make_hybrid_eval_step(de: DistributedEmbedding,
                          pred_fn: Callable,
                          mesh=None,
                          dynamic=None,
                          donate_inputs: bool = False):
    """Build ``eval_step(state, cat_inputs, batch) -> global predictions``.

    The inference analogue of :func:`make_hybrid_train_step` — the reference
    evaluates by running the forward under Horovod and allgathering per-rank
    predictions (``examples/dlrm/main.py:230-243`` there); here the shard_map
    output spec ``P(axis)`` reassembles the global prediction array directly.

    Args:
      de: the distributed embedding layer.
      pred_fn: ``pred_fn(dense_params, emb_outputs, batch) -> predictions``
        over the per-device batch shard.
      mesh: required when ``de.world_size > 1``.
      dynamic: streaming-vocab mode (same resolution as the train step's
        ``dynamic=``): the eval step then takes the carried streaming
        state as a fourth argument — ``eval_step(state, cat_inputs,
        batch, stream)`` — and serves ids through the slot map
        READ-ONLY: admitted ids read their slots, everything else its
        shared bucket; no admissions, no state mutation (the state is
        not donated), so interleaved eval never perturbs the training
        trajectory.
      donate_inputs: donate the ``cat_inputs`` / ``batch`` argument
        buffers to the compiled forward — the serving-runtime mode
        (:mod:`.serving`): each flush builds fresh padded input arrays,
        so their buffers are dead the moment the step consumes them and
        XLA may reuse them in place. The state (and any streaming
        state) is NEVER donated — it must survive every call. Leave off
        for interactive eval where callers re-feed the same arrays.
    """
    from . import streaming as streaming_mod

    world = de.world_size
    dyn_cfg = streaming_mod.resolve_config(dynamic)

    if dyn_cfg is None:
        def local_eval(state: HybridTrainState, cat_inputs, batch):
            outs = de(state.emb_params, cat_inputs)
            return pred_fn(state.dense_params, outs, batch)
    else:
        def local_eval(state: HybridTrainState, cat_inputs, batch,
                       stream):
            outs, _ = de.forward_with_residuals(
                state.emb_params, cat_inputs,
                streaming=(dyn_cfg, streaming_mod.local_state(stream),
                           False))
            return pred_fn(state.dense_params, outs, batch)

    # inputs only: the state (and streaming state) must survive calls
    donate = (1, 2) if donate_inputs else ()
    if world == 1:
        return jax.jit(local_eval, donate_argnums=donate)
    if mesh is None:
        raise ValueError("mesh is required for world_size > 1")
    ax = de.axis_name
    state_specs = HybridTrainState(
        emb_params=P(ax), emb_opt_state=P(ax),
        dense_params=P(), dense_opt_state=P(), step=P())
    in_specs = (state_specs, P(ax), P(ax))
    if dyn_cfg is not None:
        in_specs = in_specs + (P(ax),)
    sm = jax.shard_map(
        local_eval, mesh=mesh,
        in_specs=in_specs,
        out_specs=P(ax))
    return jax.jit(sm, donate_argnums=donate)


def init_hybrid_state(de: DistributedEmbedding, emb_optimizer,
                      dense_params, dense_tx, key, mesh=None,
                      dtype=jnp.float32) -> HybridTrainState:
    """Initialize all state, with slabs laid out on the mesh."""
    emb_params = de.init(key, dtype=dtype, mesh=mesh)
    emb_opt_state = emb_optimizer.init(emb_params)
    if mesh is not None:
        sharding = NamedSharding(mesh, P(de.axis_name))
        emb_opt_state = jax.tree.map(
            lambda a: jax.device_put(a, sharding), emb_opt_state)
    return HybridTrainState(
        emb_params=emb_params,
        emb_opt_state=emb_opt_state,
        dense_params=dense_params,
        dense_opt_state=dense_tx.init(dense_params),
        step=jnp.zeros((), jnp.int32))


@jax.jit
def _clone(tree):
    # a + 0 (same dtype) forces a REAL output buffer per leaf — an
    # identity would let the runtime hand the input buffer back
    return jax.tree.map(lambda a: a + jnp.zeros((), a.dtype), tree)


def clone_pytree(tree):
    """Donation-safe deep copy of a jit-carried pytree: fresh device
    buffers holding the source's values, with dtypes and shardings
    preserved (the copy is an elementwise jit, so GSPMD keeps each
    leaf's placement).

    The hybrid train step donates its state every step, so any view
    that must outlive the step — the online runtime's published serving
    snapshots (``parallel/online.py``) — has to be a real copy; and the
    copy must preserve placement so the serving ladder's jit cache keys
    match across published versions (the 0-steady-state-recompiles
    contract). One compile per distinct pytree structure/shape set,
    cache hits thereafter."""
    return _clone(tree)

"""Jaxpr-level SPMD invariant auditor for the hybrid train step.

The whole value proposition of hybrid model/data parallelism is a tight
communication contract: per train step, the distributed embedding runs
exactly ONE id all-to-all and ONE activation all-to-all forward and ONE
cotangent all-to-all backward (plus the loss/dense-gradient pmeans the
data-parallel side owes). Nothing used to verify that — a refactor that
sneaks an extra ``all_gather`` into the sparse path, leaks a float64, or
routes a host callback through the jitted step only showed up as a silent
throughput drop in a later bench round.

:func:`audit_train_step` builds the step exactly like
:func:`~..parallel.trainer.make_hybrid_train_step` does, traces it
abstractly (``jax.make_jaxpr`` + ``jit(...).lower()`` — shapes and dtypes
only, nothing executes on a backend), and returns a structured
:class:`AuditReport`:

* **collective census** — every ``all_to_all`` / ``psum`` / ``all_gather``
  /``reduce_scatter`` / ``ppermute`` in the step, attributed to the
  ``obs.scope`` phase it was traced under, with per-device payload and
  estimated off-chip bytes; checked against the expected contract for the
  layer's configuration (:func:`expected_collectives`).
* **dtype audit** — any float64/complex128 value anywhere in the step is a
  violation (an x64 leak doubles exchange bytes and HBM traffic); the
  embedding-slab dtype must be preserved input-state -> output-state.
* **host-interop audit** — ``pure_callback`` / ``io_callback`` /
  ``debug_callback`` / infeed/outfeed inside the step are violations:
  every one is a device->host sync in the hot path.
* **donation audit** — the step donates its whole state
  (``donate_argnums=(0,)``); the lowered module must carry a donation
  marker (``jax.buffer_donor`` / ``tf.aliasing_output``) for every state
  leaf, or slab-sized buffers silently double in HBM.
* **recompile-hazard scan** — weak-typed step *arguments* (a Python
  scalar rode into the jitted signature; a weak->strong flip retraces) and
  a count of weak-typed captured literals (closure scalars baked into the
  program — rebuild the step per value and every build recompiles).

The auditor never talks to an accelerator: run it under
``JAX_PLATFORMS=cpu`` with ``--xla_force_host_platform_device_count=N``
for an N-position mesh (``tools/audit_step.py`` does exactly that).
"""

from __future__ import annotations

import dataclasses
import json
from typing import Any, Dict, Iterator, List, Optional, Sequence, Tuple

import jax
import jax.core as jcore
import numpy as np

from ..parallel import trainer as trainer_mod
from ..parallel.dist_embedding import DistributedEmbedding, MpInputs

# primitive-name classes: legacy shard_map (jax<=0.4.x) rewrites psum to
# psum2 under replication checking; newer jax keeps psum. all_gather has an
# *_invariant twin on some versions.
PSUM_PRIMS = frozenset({"psum", "psum2"})
ALL_TO_ALL_PRIMS = frozenset({"all_to_all"})
ALL_GATHER_PRIMS = frozenset({"all_gather", "all_gather_invariant"})
REDUCE_SCATTER_PRIMS = frozenset({"reduce_scatter"})
OTHER_COLLECTIVE_PRIMS = frozenset({"ppermute", "pmax", "pmin", "pgather"})
COLLECTIVE_PRIMS = (PSUM_PRIMS | ALL_TO_ALL_PRIMS | ALL_GATHER_PRIMS
                    | REDUCE_SCATTER_PRIMS | OTHER_COLLECTIVE_PRIMS)

#: primitives that cross the host<->device boundary inside a jitted step
HOST_INTEROP_PRIMS = frozenset({
    "pure_callback", "io_callback", "debug_callback", "callback",
    "outside_call", "infeed", "outfeed", "host_callback_call",
})

#: obs.scope phase -> contract role of an all_to_all traced under it
_A2A_ROLES = (
    ("id_all_to_all", "id_exchange_fwd"),
    ("out_all_to_all", "out_exchange_fwd"),
    ("grad_all_to_all", "grad_exchange_bwd"),
)

_FORBIDDEN_DTYPES = ("float64", "complex128")


class AuditError(RuntimeError):
    """Raised by :meth:`AuditReport.raise_on_violations` in strict use."""


@dataclasses.dataclass
class CollectiveRecord:
    """One collective op found in the traced step."""
    kind: str            # psum | all_to_all | all_gather | reduce_scatter...
    primitive: str       # exact jaxpr primitive name
    role: str            # contract role derived from the obs.scope phase
    scope: str           # full named_scope stack at the trace site
    shape: Tuple[int, ...]
    dtype: str
    payload_bytes: int   # per-device operand size
    offchip_bytes: int   # estimated bytes leaving the chip (all_to_all)

    def to_json(self) -> Dict[str, Any]:
        return dataclasses.asdict(self)


@dataclasses.dataclass
class AuditReport:
    """Structured result of one step audit. ``violations`` is empty iff
    every invariant holds; everything else is the evidence."""
    world: int
    dp_input: bool
    label: str
    collectives: List[CollectiveRecord]
    collective_counts: Dict[str, int]
    expected: Dict[str, Any]
    dtype_leaks: List[str]
    emb_dtype_changes: List[str]
    host_interop: List[str]
    donation: Dict[str, Any]
    recompile_hazards: List[str]
    weak_literals: int
    primitive_counts: Dict[str, int]
    violations: List[str]

    @property
    def ok(self) -> bool:
        return not self.violations

    def a2a_census(self) -> Dict[str, int]:
        """all_to_all count per contract role (the 2-fwd + 1-bwd check)."""
        out: Dict[str, int] = {}
        for c in self.collectives:
            if c.kind == "all_to_all":
                out[c.role] = out.get(c.role, 0) + 1
        return out

    def raise_on_violations(self) -> "AuditReport":
        if self.violations:
            raise AuditError(
                "step audit failed:\n  - " + "\n  - ".join(self.violations))
        return self

    def to_json(self) -> Dict[str, Any]:
        d = dataclasses.asdict(self)
        d["ok"] = self.ok
        d["a2a_census"] = self.a2a_census()
        return d

    def dumps(self, **kw: Any) -> str:
        return json.dumps(self.to_json(), **kw)


# ------------------------------------------------------------ jaxpr walking


def _sub_jaxprs(value: Any) -> Iterator[jcore.Jaxpr]:
    """Every Jaxpr nested inside an eqn-param value (pjit/shard_map/scan/
    cond branches/custom_*_call all stash theirs differently)."""
    if isinstance(value, jcore.Jaxpr):
        yield value
    elif isinstance(value, jcore.ClosedJaxpr):
        yield value.jaxpr
    elif isinstance(value, (list, tuple)):
        for v in value:
            yield from _sub_jaxprs(v)


def iter_eqns(jaxpr: jcore.Jaxpr) -> Iterator[jcore.JaxprEqn]:
    """Depth-first walk over every equation reachable from ``jaxpr``,
    descending through call/ control-flow primitives."""
    for eqn in jaxpr.eqns:
        yield eqn
        for v in eqn.params.values():
            for sub in _sub_jaxprs(v):
                yield from iter_eqns(sub)


def _scope_of(eqn: jcore.JaxprEqn) -> str:
    try:
        return str(eqn.source_info.name_stack)
    except Exception:  # noqa: BLE001 - name stacks are metadata, not load-bearing
        return ""


def _aval_of(var: Any) -> Optional[jcore.AbstractValue]:
    return getattr(var, "aval", None)


def _role_of_a2a(scope: str) -> str:
    for marker, role in _A2A_ROLES:
        if marker in scope:
            return role
    return "unscoped"


def _kind_of(prim: str) -> Optional[str]:
    if prim in ALL_TO_ALL_PRIMS:
        return "all_to_all"
    if prim in PSUM_PRIMS:
        return "psum"
    if prim in ALL_GATHER_PRIMS:
        return "all_gather"
    if prim in REDUCE_SCATTER_PRIMS:
        return "reduce_scatter"
    if prim in OTHER_COLLECTIVE_PRIMS:
        return prim
    return None


# --------------------------------------------------------------- the audits


def _collect(jaxpr: jcore.Jaxpr, world: int):
    """One walk, every census: collectives, dtype leaks, host interop,
    weak literals, primitive counts."""
    collectives: List[CollectiveRecord] = []
    dtype_leaks: List[str] = []
    host_interop: List[str] = []
    weak_literals = 0
    prim_counts: Dict[str, int] = {}
    seen_literal_ids = set()

    def leak_check(aval, where: str) -> None:
        name = getattr(getattr(aval, "dtype", None), "name", None)
        if name in _FORBIDDEN_DTYPES and len(dtype_leaks) < 32:
            shape = tuple(getattr(aval, "shape", ()))
            dtype_leaks.append(f"{name}{list(shape)} at {where}")

    for eqn in iter_eqns(jaxpr):
        prim = eqn.primitive.name
        prim_counts[prim] = prim_counts.get(prim, 0) + 1
        scope = _scope_of(eqn)
        where = f"{prim} [{scope}]" if scope else prim
        for v in eqn.outvars:
            aval = _aval_of(v)
            if aval is not None:
                leak_check(aval, where)
        for v in eqn.invars:
            if isinstance(v, jcore.Literal):
                aval = _aval_of(v)
                if (aval is not None and getattr(aval, "weak_type", False)
                        and id(v) not in seen_literal_ids):
                    seen_literal_ids.add(id(v))
                    weak_literals += 1
                # literal avals are also dtype-checked: a captured numpy
                # f64 constant is a leak even if every op output is f32
                if aval is not None:
                    leak_check(aval, where)
        if prim in HOST_INTEROP_PRIMS:
            host_interop.append(where)
        kind = _kind_of(prim)
        if kind is not None:
            aval = _aval_of(eqn.invars[0]) if eqn.invars else None
            shape = tuple(int(d) for d in getattr(aval, "shape", ()))
            dtype = getattr(getattr(aval, "dtype", None), "name", "?")
            payload = int(np.prod(shape, dtype=np.int64)
                          * np.dtype(dtype).itemsize) if shape and \
                dtype != "?" else 0
            offchip = (payload * (world - 1) // world
                       if kind == "all_to_all" and world > 1 else 0)
            collectives.append(CollectiveRecord(
                kind=kind, primitive=prim,
                role=(_role_of_a2a(scope) if kind == "all_to_all"
                      else ("nanguard" if "nanguard" in scope
                            else "unscoped")),
                scope=scope, shape=shape, dtype=dtype,
                payload_bytes=payload, offchip_bytes=offchip))
    return collectives, dtype_leaks, host_interop, weak_literals, prim_counts


def expected_collectives(de: DistributedEmbedding, *,
                         nan_guard: bool,
                         n_dense_leaves: int,
                         microbatches: Optional[int] = None
                         ) -> Dict[str, Any]:
    """The communication contract for one hybrid train step on ``de``.

    * all_to_all — the paper's exchange structure: dp input runs the id
      exchange + output exchange forward and the cotangent exchange
      backward (2 fwd + 1 bwd); mp input (``dp_input=False``) skips the id
      exchange (1 fwd + 1 bwd); a single worker runs none. A PIPELINED
      schedule (``de.schedule.microbatches == K > 1``; override with
      ``microbatches=``) runs each role once per microbatch — the
      ``_mb{k}``-scoped instances still carry the role marker in their
      scope, so the census buckets them correctly — and exactly K of
      each is the contract: K+1 means a microbatch leaked an extra
      exchange, K-1 means one got fused away with its batch semantics.
    * psum — what the data-parallel side owes: one loss ``pmean``, one
      ``pmean`` per dense-gradient leaf, plus the non-finite guard's
      verdict ``pmean`` when the guard is built in. K-INVARIANT: the
      pipelined step accumulates locally and resolves once — a psum
      count that grows with K is the per-microbatch-pmean regression
      this contract exists to catch.
    * all_gather / reduce_scatter — never: the design's point is that NO
      slab-sized collective exists (an all_gather of the tables is the
      failure mode the paper's layout avoids).
    """
    if de.world_size <= 1:
        return {"all_to_all_roles": {}, "all_to_all": 0, "psum": 0,
                "all_gather": 0, "reduce_scatter": 0}
    if microbatches is None:
        microbatches = int(getattr(de.schedule, "microbatches", 1) or 1)
    k = max(int(microbatches), 1)
    roles = (["out_exchange_fwd", "grad_exchange_bwd"]
             if not de.dp_input else
             ["id_exchange_fwd", "out_exchange_fwd", "grad_exchange_bwd"])
    return {
        "all_to_all_roles": {r: k for r in roles},
        "all_to_all": len(roles) * k,
        "psum": 1 + n_dense_leaves + (1 if nan_guard else 0),
        "all_gather": 0,
        "reduce_scatter": 0,
    }


def expected_eval_collectives(de: DistributedEmbedding) -> Dict[str, Any]:
    """The communication contract for one no-grad FORWARD on ``de`` —
    the serving runtime's compiled program (:mod:`~..parallel.serving`)
    and :func:`~..parallel.trainer.make_hybrid_eval_step`'s body.

    Half the train contract: the dp-input forward runs the id exchange
    and the output exchange (1 + 1), mp input only the output exchange,
    a single worker none — and NOTHING else: no cotangent exchange (no
    grad), no psum (no loss pmean, no dense-gradient resolution), and
    the same never-any-all_gather rule as training. A serve program
    that trips this census is quietly paying training-shaped
    communication per request.
    """
    if de.world_size <= 1:
        return {"all_to_all_roles": {}, "all_to_all": 0, "psum": 0,
                "all_gather": 0, "reduce_scatter": 0}
    roles = (["out_exchange_fwd"] if not de.dp_input
             else ["id_exchange_fwd", "out_exchange_fwd"])
    return {
        "all_to_all_roles": {r: 1 for r in roles},
        "all_to_all": len(roles),
        "psum": 0,
        "all_gather": 0,
        "reduce_scatter": 0,
    }


def _donation_audit(lowered_text: Optional[str],
                    expected_leaves: int) -> Dict[str, Any]:
    """Count donation markers in the lowered StableHLO. jax marks a donated
    parameter either with an established input/output alias
    (``tf.aliasing_output``) or a ``jax.buffer_donor`` attribute (alias
    left to the compiler); a state leaf with neither was silently dropped."""
    if lowered_text is None:
        return {"checked": False, "expected": expected_leaves,
                "donated": None, "dropped": None}
    aliased = lowered_text.count("tf.aliasing_output")
    donor = lowered_text.count("jax.buffer_donor")
    donated = aliased + donor
    return {"checked": True, "expected": expected_leaves,
            "donated": donated, "aliased": aliased, "donor_only": donor,
            "dropped": max(0, expected_leaves - donated)}


def _weak_arg_hazards(args) -> List[str]:
    """Weak-typed leaves among the step arguments: each is a Python scalar
    riding the jitted signature — a weak->strong flip (or an int->float
    drift in the calling code) retraces the whole step."""
    hazards = []
    flat, _ = jax.tree_util.tree_flatten(args)
    for i, leaf in enumerate(flat):
        weak = getattr(leaf, "weak_type", None)
        if weak is None:
            aval = getattr(leaf, "aval", None)
            weak = getattr(aval, "weak_type", False)
        if weak or isinstance(leaf, (int, float)) and not isinstance(
                leaf, bool) and not hasattr(leaf, "dtype"):
            hazards.append(
                f"arg leaf #{i}: weak-typed scalar "
                f"({type(leaf).__name__}) in the jitted signature — pass a "
                "committed jnp array instead")
    return hazards


def audit_step_fn(step_fn, args: Sequence[Any], *,
                  world: int = 1,
                  dp_input: bool = True,
                  expected: Optional[Dict[str, Any]] = None,
                  expected_donated: Optional[int] = None,
                  check_donation: bool = True,
                  label: str = "step") -> AuditReport:
    """Audit an arbitrary (jitted or plain) step callable against an
    expected-collectives contract.

    Abstract only: ``jax.make_jaxpr`` traces the function (nothing runs on
    a backend) and, when ``check_donation`` and ``step_fn`` is a jit
    wrapper, ``step_fn.lower(*args).as_text()`` supplies the donation
    attributes. ``args`` may be concrete arrays or
    ``jax.ShapeDtypeStruct`` pytrees.
    """
    report, _ = _audit_step_fn(
        step_fn, args, world=world, dp_input=dp_input, expected=expected,
        expected_donated=expected_donated, check_donation=check_donation,
        label=label)
    return report


def _audit_step_fn(step_fn, args: Sequence[Any], *,
                   world: int = 1,
                   dp_input: bool = True,
                   expected: Optional[Dict[str, Any]] = None,
                   expected_donated: Optional[int] = None,
                   check_donation: bool = True,
                   label: str = "step"):
    """:func:`audit_step_fn` plus the traced output shape tree (the
    train-step entry point compares state dtypes through it)."""
    jaxpr, out_shape = jax.make_jaxpr(step_fn, return_shape=True)(*args)
    (collectives, dtype_leaks, host_interop, weak_literals,
     prim_counts) = _collect(jaxpr.jaxpr, world)

    counts: Dict[str, int] = {}
    for c in collectives:
        counts[c.kind] = counts.get(c.kind, 0) + 1

    lowered_text = None
    if check_donation and hasattr(step_fn, "lower"):
        lowered_text = step_fn.lower(*args).as_text()
    donation = _donation_audit(
        lowered_text,
        expected_donated if expected_donated is not None else 0)

    hazards = _weak_arg_hazards(args)

    violations: List[str] = []
    if expected is not None:
        exp_roles = expected.get("all_to_all_roles", {})
        census: Dict[str, int] = {}
        for c in collectives:
            if c.kind == "all_to_all":
                census[c.role] = census.get(c.role, 0) + 1
        for role, n in exp_roles.items():
            got = census.get(role, 0)
            if got != n:
                violations.append(
                    f"all_to_all census: expected {n} x {role}, found "
                    f"{got} — the exchange contract is broken")
        for role, got in census.items():
            if role not in exp_roles:
                violations.append(
                    f"all_to_all census: unexpected all_to_all in role "
                    f"{role!r} ({got}x) — every exchange must run under "
                    "a known obs.scope phase")
        for kind in ("psum", "all_gather", "reduce_scatter"):
            exp_n = expected.get(kind)
            if exp_n is None:
                continue
            got = counts.get(kind, 0)
            if got != exp_n:
                detail = "; ".join(
                    f"{c.primitive}@{c.scope or 'unscoped'}"
                    for c in collectives if c.kind == kind) or "none"
                violations.append(
                    f"{kind} census: expected {exp_n}, found {got} "
                    f"({detail})")
        for kind in counts:
            if kind not in ("psum", "all_to_all", "all_gather",
                            "reduce_scatter") and kind not in expected:
                violations.append(
                    f"unexpected collective {kind} "
                    f"({counts[kind]}x) in the step")
    if dtype_leaks:
        violations.append(
            "f64/x64 leak: " + "; ".join(dtype_leaks[:8])
            + (" ..." if len(dtype_leaks) > 8 else ""))
    if host_interop:
        violations.append(
            "host interop inside the jitted step: "
            + "; ".join(host_interop[:8]))
    if donation["checked"] and donation["expected"] and donation["dropped"]:
        violations.append(
            f"donation audit: {donation['dropped']} of "
            f"{donation['expected']} state leaves carry no donation marker "
            "— those buffers silently double in HBM")
    violations.extend(hazards)

    return AuditReport(
        world=world, dp_input=dp_input, label=label,
        collectives=collectives, collective_counts=counts,
        expected=expected or {}, dtype_leaks=dtype_leaks,
        emb_dtype_changes=[], host_interop=host_interop,
        donation=donation, recompile_hazards=hazards,
        weak_literals=weak_literals, primitive_counts=prim_counts,
        violations=violations), out_shape


def build_abstract_step(de: DistributedEmbedding,
                        loss_fn,
                        dense_tx,
                        emb_optimizer,
                        cat_inputs,
                        batch,
                        mesh=None,
                        lr_schedule=1.0,
                        with_metrics: Optional[bool] = None,
                        nan_guard: Optional[bool] = None,
                        telemetry=None,
                        dynamic=None,
                        dense_params=None,
                        state=None):
    """Build the hybrid train step EXACTLY like
    :func:`~..parallel.trainer.make_hybrid_train_step` plus the abstract
    argument tuple to trace/compile it with — nothing materializes.

    The single build both static gates share: :func:`audit_train_step`
    (jaxpr/collective contract) and
    :func:`~.hlo_census.census_train_step` (optimized-HLO pass budget)
    audit the step this helper returns, so the two cannot drift into
    auditing different programs while each claims to audit "the" hybrid
    step. ``with_metrics``/``nan_guard`` default from the env (the step
    builder's convention); ``state`` is derived via ``eval_shape`` from
    ``dense_params`` when omitted; a telemetry config appends the
    abstract carried state as the fourth argument, and a streaming
    config (``dynamic=``, the step builder's argument) the abstract
    slot-map/sketch state after it — the aux order of
    :data:`~..parallel.trainer.AUX_ARG_REGISTRY`.

    Returns:
      ``(step, args, state, tel_cfg, with_metrics, nan_guard)``.
    """
    from ..utils import obs
    from ..parallel import streaming as streaming_mod
    from . import telemetry as tel

    if with_metrics is None:
        with_metrics = obs.metrics_enabled()
    if nan_guard is None:
        nan_guard = obs.nanguard_enabled()
    tel_cfg = tel.resolve_config(telemetry)
    dyn_cfg = streaming_mod.resolve_config(dynamic)

    if state is None:
        if dense_params is None:
            raise ValueError(
                "building an abstract hybrid step needs dense_params (to "
                "derive an abstract state) or an explicit state=")
        state = jax.eval_shape(
            lambda k, dp: trainer_mod.init_hybrid_state(
                de, emb_optimizer, dp, dense_tx, k),
            jax.random.key(0), dense_params)

    step = trainer_mod.make_hybrid_train_step(
        de, loss_fn, dense_tx, emb_optimizer, mesh=mesh,
        lr_schedule=lr_schedule, with_metrics=with_metrics,
        nan_guard=nan_guard, telemetry=tel_cfg if tel_cfg else False,
        dynamic=dyn_cfg if dyn_cfg else False)

    args: Tuple[Any, ...] = (state, cat_inputs, batch)
    if tel_cfg is not None:
        args = args + (jax.eval_shape(
            lambda: tel.init_telemetry(de, tel_cfg)),)
    if dyn_cfg is not None:
        args = args + (jax.eval_shape(
            lambda: streaming_mod.init_streaming(de, dyn_cfg)),)
    return step, args, state, tel_cfg, with_metrics, nan_guard


def audit_train_step(de: DistributedEmbedding,
                     loss_fn,
                     dense_tx,
                     emb_optimizer,
                     cat_inputs,
                     batch,
                     mesh=None,
                     lr_schedule=1.0,
                     with_metrics: Optional[bool] = None,
                     nan_guard: Optional[bool] = None,
                     telemetry=None,
                     dense_params=None,
                     state=None,
                     expected: Optional[Dict[str, Any]] = None,
                     label: str = "hybrid_train_step") -> AuditReport:
    """Build the hybrid train step exactly like
    :func:`~..parallel.trainer.make_hybrid_train_step` and audit it.

    ``telemetry`` follows the step builder's contract (explicit opt-in:
    ``True``/config = on): the telemetry-instrumented variant is audited
    with an abstract carried state as the fourth argument, and the SAME
    communication contract — access telemetry is rank-local by design
    (sketch scatter-adds + top-k merges, no collectives, no host
    interop), so a telemetry build that changes the census is a bug this
    audit catches. The donation audit grows by the telemetry leaves
    (the carried state is donated like the train state).

    Args mirror the step builder; additionally:

    Args:
      cat_inputs: the categorical inputs the step would receive — concrete
        arrays, ``jax.ShapeDtypeStruct`` leaves, ``Ragged``/:class:`MpInputs`
        of either. Only shapes/dtypes matter.
      batch: the loss batch pytree (same abstract-ok rule).
      dense_params: dense parameter pytree (or abstract shapes), used to
        derive the training state when ``state`` is not given.
      state: optional :class:`~..parallel.trainer.HybridTrainState` (or an
        abstract eval_shape of one). Built via
        ``jax.eval_shape(init_hybrid_state, ...)`` from ``dense_params``
        when omitted — nothing is materialized either way.
      expected: override for :func:`expected_collectives` (tests seed
        deliberately-wrong expectations through this).

    Returns:
      :class:`AuditReport`; call :meth:`AuditReport.raise_on_violations`
      for strict use.
    """
    step, args, state, tel_cfg, with_metrics, nan_guard = \
        build_abstract_step(
            de, loss_fn, dense_tx, emb_optimizer, cat_inputs, batch,
            mesh=mesh, lr_schedule=lr_schedule, with_metrics=with_metrics,
            nan_guard=nan_guard, telemetry=telemetry,
            dense_params=dense_params, state=state)

    if expected is None:
        expected = expected_collectives(
            de, nan_guard=nan_guard,
            n_dense_leaves=len(jax.tree_util.tree_leaves(
                state.dense_params)))

    donated = sum(len(jax.tree_util.tree_leaves(a))
                  for a in (state,) + args[3:])  # + the telemetry carry

    report, out_shape = _audit_step_fn(
        step, args,
        world=de.world_size, dp_input=de.dp_input, expected=expected,
        expected_donated=donated,
        label=label)

    # embedding-table dtype must be preserved end-to-end: state out is
    # (loss, new_state[, metrics]) — compare slab dtypes leaf-wise
    new_state = out_shape[1]
    in_emb = jax.tree_util.tree_leaves_with_path(state.emb_params)
    out_emb = jax.tree_util.tree_leaves_with_path(new_state.emb_params)
    changes = []
    for (pi, vi), (_, vo) in zip(in_emb, out_emb):
        di = getattr(vi, "dtype", None)
        do = getattr(vo, "dtype", None)
        if di is not None and do is not None and di != do:
            changes.append(
                f"emb_params{jax.tree_util.keystr(pi)}: {di} -> {do}")
    if changes:
        report.emb_dtype_changes = changes
        report.violations.append(
            "embedding-table dtype not preserved: " + "; ".join(changes))
    return report

"""On-device embedding access telemetry: hot-row sketches and per-rank
load accounting, carried as explicit jit state.

The paper's design shards tables because memory dominates and exchanges
activations because communication dominates — but the repo's existing
observability (``utils/obs.py`` step metrics) only says *how many* ids a
rank received per step, never *which rows* are hot or *how skewed* the
per-rank load is over time. Every placement optimization the ROADMAP
names (hot-row caching, skew-aware placement, table re-sharding) needs
exactly that signal, and it must come from inside the compiled step:
fetching ids to the host per step would serialize the input pipeline and
a ``pure_callback`` would put a device→host sync in the hot path (the
step auditor rejects both).

This module is the state + math of that telemetry; the emission points
live in :meth:`~..parallel.dist_embedding.DistributedEmbedding.
update_telemetry` (one per ``(width, kind)`` exchange group, each under
its own ``obs.scope``), and the threading lives in
:func:`~..parallel.trainer.make_hybrid_train_step` (``telemetry=``).
Three properties are load-bearing:

* **jit-carried** — the telemetry state is an ordinary pytree argument
  of the step (donated, like the train state), updated with pure jax
  ops: count-min-sketch scatter-adds and a top-k merge. No host
  callbacks, no recompiles after warmup (the state's shapes are static).
* **per-table top-k hot rows** — a count-min sketch per width slab
  (``[depth, buckets]`` int32; estimates never undercount) plus a
  carried top-k candidate buffer merged every step: the current batch's
  unique ids are scored against the sketch and the best ``k`` survive.
  Ids are *logical slab rows*, mapped back to ``(table, row)`` on host
  by :func:`hot_rows` via the layout the strategy already knows.
* **per-rank load accounting** — cumulative live routed ids per rank
  (total and per width), the time-integrated version of the per-step
  ``ids_routed`` metric: the imbalance signal placement decisions need.

Accuracy: a count-min sketch only ever OVER-estimates (collisions add),
so a row reported cold is truly cold; hot-row estimates are exact up to
collision noise ``~ total_ids / buckets`` per bucket. Counts saturate at
int32; long runs should read the top-k *ranking*, not absolute counts.

Like :mod:`.audit`, nothing here touches a backend at import.
"""

from __future__ import annotations

from typing import Any, Dict, List, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..utils import envvars

#: dead slot marker in the carried top-k id buffer
TOPK_EMPTY = -1
#: unique() fill marker for padding candidates (sorts after all real ids)
_CAND_PAD = np.iinfo(np.int32).max

# xxhash/murmur-style odd multipliers; depth d uses _MULTS[d % len]
# xor-folded with d so depths beyond len(_MULTS) stay distinct
_MULTS = np.array([0x9E3779B1, 0x85EBCA77, 0xC2B2AE3D, 0x27D4EB2F,
                   0x165667B1, 0xD3A2646D, 0xFD7046C5, 0xB55A4F09],
                  dtype=np.uint32)
_MIX = np.uint32(0x2C1B3C6D)


class TelemetryConfig(NamedTuple):
    """Static (trace-time) telemetry geometry. Hashable so step builders
    can close over it; every field is a compile-time constant."""

    depth: int = 4        #: count-min sketch rows (independent hashes)
    buckets: int = 2048   #: count-min sketch columns per row
    topk: int = 32        #: hot-row slots carried per width slab
    candidates: int = 128  #: per-step unique-id candidates merged into top-k


def telemetry_enabled() -> bool:
    """Whether ``DETPU_TELEMETRY`` asks for access telemetry (read at
    step-build time, trace-time static — like ``with_metrics``)."""
    return envvars.enabled("DETPU_TELEMETRY")


def config_from_env() -> TelemetryConfig:
    """The env-configured geometry (``DETPU_TELEMETRY_SKETCH_DEPTH`` /
    ``_SKETCH_WIDTH`` / ``_TOPK`` / ``_CANDIDATES``; 0 candidates means
    ``4 * topk``)."""
    topk = max(1, envvars.get_int("DETPU_TELEMETRY_TOPK"))
    cand = envvars.get_int("DETPU_TELEMETRY_CANDIDATES")
    return TelemetryConfig(
        depth=max(1, envvars.get_int("DETPU_TELEMETRY_SKETCH_DEPTH")),
        buckets=max(2, envvars.get_int("DETPU_TELEMETRY_SKETCH_WIDTH")),
        topk=topk,
        candidates=cand if cand > 0 else 4 * topk)


def resolve_config(telemetry) -> Optional[TelemetryConfig]:
    """Normalize a step builder's ``telemetry=`` argument: ``None``/
    ``False`` is off, ``True`` is the env-configured geometry, a
    :class:`TelemetryConfig` passes through.

    Telemetry is an EXPLICIT opt-in at step-build time — unlike
    ``with_metrics`` (which only grows the return tuple), telemetry
    changes the step's *call* arity, so an env variable must never flip
    it under an unsuspecting 3-arg call site. ``DETPU_TELEMETRY`` is
    consumed by the telemetry-aware entry points instead (the dlrm
    example, ``tools/obs_report.py``, the bench telemetry section),
    which pass ``telemetry=``/the carried state together.
    """
    if telemetry is None or telemetry is False:
        return None
    if telemetry is True:
        return config_from_env()
    if isinstance(telemetry, TelemetryConfig):
        return telemetry
    raise TypeError(
        f"telemetry= takes None | bool | TelemetryConfig, got "
        f"{type(telemetry).__name__}")


# ------------------------------------------------------------------- state


def _wkey(width: int) -> str:
    return f"w{width}"


def init_telemetry(de, config: Optional[TelemetryConfig] = None,
                   mesh=None) -> Dict[str, Any]:
    """Fresh telemetry state for ``de``: a plain-dict pytree whose leaves
    all carry a leading ``[world]`` axis (``local_state`` squeezes it
    inside the step, mirroring the slab convention), laid out over
    ``mesh`` when given so ``shard_map`` receives it pre-sharded.

    Per width slab: the count-min sketch, the top-k (ids, estimates)
    carry, and the width's cumulative live-id count; top-level: the step
    counter and the rank's cumulative routed-id total."""
    config = config or config_from_env()
    world = de.world_size

    def stacked(shape, dtype, fill=0):
        return jnp.full((world,) + shape, fill, dtype)

    state: Dict[str, Any] = {
        "steps": stacked((1,), jnp.int32),
        "ids_total": stacked((1,), jnp.float32),
    }
    for w in de.widths:
        state[_wkey(w)] = {
            "cms": stacked((config.depth, config.buckets), jnp.int32),
            "topk_ids": stacked((config.topk,), jnp.int32, TOPK_EMPTY),
            "topk_est": stacked((config.topk,), jnp.int32),
            "ids": stacked((1,), jnp.float32),
        }
    if mesh is not None:
        sharding = jax.sharding.NamedSharding(
            mesh, jax.sharding.PartitionSpec(de.axis_name))
        state = jax.tree.map(lambda a: jax.device_put(a, sharding), state)
    return state


def local_state(state):
    """Strip the leading world axis (``[1, ...]`` per-device leaves inside
    ``shard_map`` / world 1) — the telemetry twin of ``de.local_view``."""
    return jax.tree.map(lambda v: v[0], state)


def stacked_state(state):
    """Re-add the leading world axis for ``P(axis)`` out_specs."""
    return jax.tree.map(lambda v: v[None], state)


# -------------------------------------------------------------- sketch math


def _buckets_of(ids: jax.Array, depth: int, buckets: int) -> jax.Array:
    """``[depth, n]`` sketch columns for ``ids [n]`` (int32, >= 0): one
    multiply-xorshift hash per depth row. Uint32 arithmetic wraps mod
    2^32, which is exactly the mixing these constants are built for."""
    h0 = ids.astype(jnp.uint32)[None, :]
    d_ix = np.arange(depth)
    mults = jnp.asarray(_MULTS[d_ix % len(_MULTS)]
                        ^ d_ix.astype(np.uint32))[:, None]
    h = h0 * mults
    h = h ^ (h >> 15)
    h = h * _MIX
    h = h ^ (h >> 13)
    return (h % jnp.uint32(buckets)).astype(jnp.int32)


def cms_update(cms: jax.Array, ids: jax.Array,
               live: jax.Array) -> jax.Array:
    """Scatter-add ``live`` (bool/int ``[n]``) into ``cms [depth,
    buckets]`` at each depth's bucket of ``ids [n]`` (masked positions
    add 0 — no branching, SPMD-uniform)."""
    depth, buckets = cms.shape
    cols = _buckets_of(jnp.where(live, ids, 0), depth, buckets)
    rows = jnp.arange(depth, dtype=jnp.int32)[:, None]
    flat = (rows * buckets + cols).reshape(-1)
    inc = jnp.broadcast_to(live.astype(jnp.int32)[None, :],
                           cols.shape).reshape(-1)
    return cms.reshape(-1).at[flat].add(inc).reshape(depth, buckets)


def cms_query(cms: jax.Array, ids: jax.Array) -> jax.Array:
    """Count-min estimate ``[n]`` for ``ids [n]``: min over depth rows
    (never undercounts; collisions only inflate)."""
    depth, buckets = cms.shape
    cols = _buckets_of(jnp.maximum(ids, 0), depth, buckets)
    rows = jnp.arange(depth, dtype=jnp.int32)[:, None]
    return cms.reshape(-1)[(rows * buckets + cols).reshape(-1)] \
        .reshape(depth, -1).min(axis=0)


def record_ids(wstate: Dict[str, jax.Array], ids: jax.Array,
               live: jax.Array, config: TelemetryConfig
               ) -> Dict[str, jax.Array]:
    """Fold one step's id stream for one width slab into its telemetry
    state: sketch update, then a top-k merge of the step's unique ids
    (scored by the *updated* sketch) against the carried candidates.

    ``ids [n]`` are logical slab rows (garbage where ``live [n]`` is
    False); everything is static-shaped — the unique() is size-bounded by
    ``config.candidates`` and padded with a sentinel.
    """
    ids = ids.astype(jnp.int32).reshape(-1)
    live = live.reshape(-1)
    cms = cms_update(wstate["cms"], ids, live)

    # Candidate set: the step's hottest DISTINCT live ids by sketch
    # count. Two naive choices fail on a rank holding several tables in
    # one width slab: unique(size=K) keeps the K *smallest* ids (jnp
    # truncates in sorted order), so hot rows in later tables never get
    # nominated; and a plain top_k over per-position estimates saturates
    # all K slots with duplicates of the single hottest id. So: sort the
    # ids (dead positions to the pad sentinel), score only each id's
    # FIRST occurrence with its estimate, and top_k that — K distinct
    # ids, hottest first.
    ids_live = jnp.where(live, ids, _CAND_PAD)
    est_all = jnp.where(live, cms_query(cms, ids), -1)
    order = jnp.argsort(ids_live)
    sids = ids_live[order]
    first = jnp.concatenate(
        [jnp.ones((1,), bool), sids[1:] != sids[:-1]])
    score = jnp.where(first, est_all[order], -1)
    k_pool = min(config.candidates, int(score.shape[0]))
    pool_est, pool_ix = jax.lax.top_k(score, k_pool)
    pool = jnp.where(pool_est >= 0, sids[pool_ix], _CAND_PAD)
    cand = jnp.unique(pool, size=config.candidates, fill_value=_CAND_PAD)
    old_ids = wstate["topk_ids"]
    dup = (cand[:, None] == old_ids[None, :]).any(axis=1)
    cand_ok = (cand != _CAND_PAD) & ~dup
    cand_est = jnp.where(cand_ok, cms_query(cms, cand), -1)
    # carried slots re-query so their estimates keep growing; the carried
    # estimate is a floor (the sketch is monotone, so this only matters
    # at int32 saturation — and keeps the carried buffer load-bearing
    # instead of jit-dropped dead state)
    old_est = jnp.where(old_ids >= 0,
                        jnp.maximum(cms_query(cms, old_ids),
                                    wstate["topk_est"]),
                        -1)

    all_ids = jnp.concatenate([old_ids, cand])
    all_est = jnp.concatenate([old_est, cand_est])
    top_est, top_ix = jax.lax.top_k(all_est, config.topk)
    top_ids = jnp.where(top_est >= 0, all_ids[top_ix], TOPK_EMPTY)
    return {
        "cms": cms,
        "topk_ids": top_ids,
        "topk_est": jnp.maximum(top_est, 0),
        "ids": wstate["ids"] + jnp.sum(live, dtype=jnp.float32).reshape(1),
    }


# ------------------------------------------------------ state persistence


def save_telemetry_state(path: str, state) -> None:
    """Persist the raw carried state (atomic tmp+rename ``.npz``) so a
    resumed run can CONTINUE the accumulation — the sketch/top-k arrays
    themselves, not just the summary. Leaves are saved in pytree-flatten
    order (the structure is deterministic for a given model config)."""
    import os

    leaves = jax.tree_util.tree_leaves(state)
    arrays = {f"leaf_{i}": np.asarray(v) for i, v in enumerate(leaves)}
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        np.savez(f, **arrays)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)


def restore_telemetry_state(path: str, fresh_state):
    """Rebuild a carried state from :func:`save_telemetry_state` output,
    using ``fresh_state`` (an :func:`init_telemetry` result for the SAME
    model + config) as the structure/placement template. On any mismatch
    (config drift, torn file) the fresh state is returned unchanged —
    telemetry is auxiliary and must never block a resume."""
    try:
        with np.load(path) as loaded:
            leaves, treedef = jax.tree_util.tree_flatten(fresh_state)
            if len(loaded.files) != len(leaves):
                raise ValueError(
                    f"{len(loaded.files)} saved leaves != "
                    f"{len(leaves)} expected (telemetry config drift?)")
            out = []
            for i, leaf in enumerate(leaves):
                arr = loaded[f"leaf_{i}"]
                if arr.shape != leaf.shape or \
                        arr.dtype != np.asarray(leaf).dtype:
                    raise ValueError(
                        f"leaf {i}: saved {arr.shape}/{arr.dtype} != "
                        f"expected {leaf.shape}")
                sharding = getattr(leaf, "sharding", None)
                out.append(jax.device_put(arr, sharding)
                           if sharding is not None else jnp.asarray(arr))
            return jax.tree_util.tree_unflatten(treedef, out)
    except Exception:  # noqa: BLE001 - see docstring: never block a resume
        import logging

        logging.getLogger(__name__).exception(
            "telemetry state restore from %s failed; starting fresh", path)
        return fresh_state


# ------------------------------------------------------------ host analysis


def _fetch(state) -> Dict[str, Any]:
    """Host numpy copy of a telemetry state (single-host; on a pod call
    this on fully-addressable or process-allgathered state)."""
    return jax.tree.map(np.asarray, state)


def _slab_row_to_table(de, rank: int, width: int,
                       row: int) -> Optional[Tuple[int, int]]:
    """Map a logical slab row back to ``(global_table_id, table_row)``
    via the same layout the checkpoint plan uses (``row_offsets_list`` +
    per-rank local configs; row slices add their ``_row_base``)."""
    from ..ops import packed_slab as ps

    cfgs = de.strategy.local_configs_list[rank]
    for m, cfg in enumerate(cfgs):
        if int(cfg["output_dim"]) != width:
            continue
        roff = de.row_offsets_list[rank][m]
        span = ps.align_rows(int(cfg["input_dim"]), width)
        if roff <= row < roff + span:
            local = row - roff
            if local >= int(cfg["input_dim"]):
                return None  # alignment padding row (nothing live reads it)
            return (de.strategy.table_ids_list[rank][m],
                    local + int(cfg.get("_row_base", 0)))
    return None


def hot_rows(de, state, topk: Optional[int] = None
             ) -> Dict[int, List[Tuple[int, int]]]:
    """Per-global-table hot rows ``{table_id: [(row, est_count), ...]}``
    (descending estimate), decoded from every rank's carried top-k.

    Column-sliced tables surface the same ``(table, row)`` on several
    ranks (each slice sees every id); duplicates keep the MAX estimate —
    summing would multiply a hot row's count by its slice fan-out.
    """
    host = _fetch(state)
    per_table: Dict[int, Dict[int, int]] = {}
    for w in de.widths:
        ws = host[_wkey(w)]
        for r in range(de.world_size):
            for row, est in zip(ws["topk_ids"][r], ws["topk_est"][r]):
                if row < 0 or est <= 0:
                    continue
                hit = _slab_row_to_table(de, r, w, int(row))
                if hit is None:
                    continue
                tid, trow = hit
                tab = per_table.setdefault(tid, {})
                tab[trow] = max(tab.get(trow, 0), int(est))
    out: Dict[int, List[Tuple[int, int]]] = {}
    for tid, rows in per_table.items():
        ranked = sorted(rows.items(), key=lambda kv: (-kv[1], kv[0]))
        out[tid] = ranked[:topk] if topk else ranked
    return out


def load_balance(state) -> Dict[str, Any]:
    """Per-rank cumulative routed-id load + the imbalance ratio
    (max/mean; 1.0 is perfectly balanced — the number skew-aware
    placement wants to drive down)."""
    host = _fetch(state)
    loads = np.asarray(host["ids_total"]).reshape(-1).astype(float)
    mean = float(loads.mean()) if loads.size else 0.0
    return {
        "per_rank_ids": [float(x) for x in loads],
        "imbalance_ratio": (float(loads.max() / mean) if mean > 0
                            else 1.0),
        "steps": int(np.asarray(host["steps"]).reshape(-1)[0]),
    }


def zipf_alpha(counts: List[int]) -> Optional[float]:
    """Least-squares Zipf exponent of a descending count ranking
    (slope of ``log(count)`` on ``log(rank)``, negated): ~1 is classic
    recommender skew, ~0 is uniform. ``None`` below 3 usable points."""
    c = np.asarray([x for x in counts if x > 0], dtype=float)
    if c.size < 3:
        return None
    x = np.log(np.arange(1, c.size + 1, dtype=float))
    y = np.log(c)
    slope = np.polyfit(x, y, 1)[0]
    return float(-slope)


def table_loads_from_summary(summary: Dict[str, Any],
                             num_tables: int) -> List[float]:
    """Per-global-table traffic weights for the ``telemetry_balanced``
    planner (``parallel/strategy.py``), derived from a
    :func:`summarize_telemetry` dict (e.g. the ``<ckpt>.telemetry.json``
    the resilient driver flushes).

    The weight of a table is the sum of its surfaced hot-row count
    estimates — an under-count of total traffic (only the carried top-k
    surfaces), but under the Zipfian skew that motivates re-sharding the
    top-k holds most of the mass, and the planner only needs *relative*
    weights. Tables that never surfaced a hot row weigh 0 and fall back
    to byte balancing via the planner's tie-break."""
    loads = [0.0] * num_tables
    for t in summary.get("tables", []):
        tid = int(t.get("table_id", -1))
        if 0 <= tid < num_tables:
            loads[tid] = float(sum(int(c) for _, c in t.get("top_rows", [])))
    return loads


def summarize_telemetry(de, state, topk: Optional[int] = None
                        ) -> Dict[str, Any]:
    """JSON-able run summary: per-table hot rows (with a per-table Zipf
    exponent estimate), per-rank loads + imbalance ratio, per-width id
    totals, step count. The host half of the observatory —
    ``tools/obs_report.py`` renders it and the resilient driver flushes
    it alongside checkpoints."""
    host = _fetch(state)
    hot = hot_rows(de, host, topk=topk)
    tables = []
    for tid in sorted(hot):
        ranked = hot[tid]
        tables.append({
            "table_id": int(tid),
            "rows": int(de.strategy.global_configs[tid]["input_dim"]),
            "width": int(de.strategy.global_configs[tid]["output_dim"]),
            "top_rows": [[int(r), int(c)] for r, c in ranked],
            "zipf_alpha": zipf_alpha([c for _, c in ranked]),
        })
    per_width = {
        _wkey(w): [float(x) for x in
                   np.asarray(host[_wkey(w)]["ids"]).reshape(-1)]
        for w in de.widths}
    return dict(load_balance(host), tables=tables,
                per_width_ids=per_width)

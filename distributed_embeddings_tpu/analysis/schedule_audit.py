"""Schedule-graph auditor: static critical-path / overlap analysis of the
compiled step.

The jaxpr auditor (PR 4) checks which collectives we *ask for* and the
HLO census (PR 7) counts what XLA *emits* — but neither sees the
**dependency structure** between the emitted ops, which is exactly what
decides whether a pipelined step can hide communication under compute.
This module closes that gap: it extends the census's HLO text parsing to
capture **operands**, builds the full dependency DAG of the optimized
entry computation, attributes every node to its ``obs.scope`` phase,
prices every node under a bytes-based cost model (chip numbers from
:data:`~.plan_audit.CHIP_SPECS`; collective payloads priced off-chip
with the same ``(world-1)/world`` convention as the on-device
``*_a2a_bytes`` step metrics), computes the **critical path**, and
classifies each collective as

* **serialized-on** dense compute — no independent compute chain of
  sufficient modeled cost exists outside the collective's ancestor /
  descendant cones (nothing the scheduler could hide it under), or
* **overlappable-with** dense compute — such a chain exists, so a
  latency-hiding schedule is structurally possible.

On top of the graph sit two contract layers:

* declarative :class:`ScheduleContract`\\ s ("the ``id_all_to_all``
  phase holds >= 1 collective, serialized, on the critical path" — the
  documented baseline of today's unpipelined step), enforced by
  ``tools/schedule_audit.py --strict`` (= ``make schedule-audit``,
  inside ``make verify``);
* the :class:`~..parallel.schedule.StepSchedule` **declaration check**
  (:meth:`ScheduleReport.check_against_schedule`): every overlap a
  schedule *claims* must exist in the compiled program's DAG — a
  schedule that says "the exchange hides under dense compute" while XLA
  serialized them fails loudly. In the GSPMD framing (SNIPPETS.md [2],
  "8-chip → 6000-chip without changing application code") this is the
  scaling story: an overlap contract checked at trace time holds at any
  mesh size, because the DAG shape — unlike the wall clock — does not
  depend on how many chips run the program.

``tools/compare_bench.py::check_schedule`` gates the bench record's
``schedule`` section round over round: a candidate whose
``serialized_collective_fraction`` or modeled critical-path bytes GROW
fails, so overlap, once won, can never silently regress.

Like the census, everything here is ``lower().compile()`` + text
parsing: nothing executes on any backend.
"""

from __future__ import annotations

import dataclasses
import fnmatch
import json
import re
from typing import Any, Dict, List, Optional, Sequence, Set, Tuple

import jax

from .hlo_census import _DETPU_RE, _OPNAME_RE, _SHAPE_TOKEN_RE, _token_bytes
from .plan_audit import CHIP_SPECS, ChipSpec

#: HLO opcodes that move bytes across chips (priced over ICI, not HBM)
COLLECTIVE_OPS = frozenset((
    "all-to-all", "all-reduce", "all-gather", "reduce-scatter",
    "collective-permute", "collective-broadcast",
))

#: opcodes that are bookkeeping, not work — priced at ZERO cost and
#: excluded from the "independent compute that could hide a collective"
#: sum. A parameter is already resident in HBM, a get-tuple-element is a
#: pointer, and a broadcast is a splat the TPU backend fuses into its
#: consumer — counting any of them as hideable work would overstate both
#: the critical path and the overlap capacity (the CPU lowering used for
#: the static audit materializes some of them, but the model prices the
#: program, not the audit backend).
TRIVIAL_OPS = frozenset((
    "parameter", "constant", "iota", "get-tuple-element", "tuple",
    "bitcast", "broadcast", "copy", "after-all", "partition-id",
    "replica-id", "rng-get-and-update-state", "opt-barrier",
))

# computation header: `ENTRY %main.1_spmd (params...) -> type {` or
# `%fused_computation.1 (...) -> type {` (name with or without `%`)
_COMP_RE = re.compile(r"^(?P<entry>ENTRY\s+)?%?(?P<name>[\w.\-]+)\s*\(")
# instruction with captured name (the census regex, plus the name group)
_INST_NAME_RE = re.compile(
    r"^\s*(?:ROOT\s+)?%?(?P<name>[\w.\-]+)\s*=\s*"
    r"(?P<shape>\((?:[^()]|\([^()]*\))*\)|\S+)\s+"
    r"(?P<op>[a-z][\w\-]*)\(")
_CALLED_RE = re.compile(
    r"(?:calls|to_apply|body|condition)=%?([\w.\-]+)")
_BRANCHES_RE = re.compile(r"branch_computations=\{([^}]*)\}")
_NAME_TOKEN_RE = re.compile(r"%([\w.\-]+)")


class ScheduleGraphError(RuntimeError):
    """A malformed compiled module (unparseable text, dependency cycle,
    no roots) or a strict-mode contract failure
    (:meth:`ScheduleReport.raise_on_violations`)."""


# --------------------------------------------------------------- HLO parsing


@dataclasses.dataclass
class HloInstr:
    """One parsed HLO instruction (one DAG node candidate)."""
    name: str
    op: str
    shape: str                    # raw result-shape text
    operands: Tuple[str, ...]     # operand instruction names (same comp)
    called: Tuple[str, ...]       # called computation names
    op_name: str                  # metadata op_name (may be "")
    is_root: bool
    line: str                     # full raw line (byte accounting)

    @property
    def phase(self) -> str:
        """Full ``detpu/`` scope path, e.g.
        ``embedding_forward/id_all_to_all`` (may be ``""``)."""
        return "/".join(_DETPU_RE.findall(self.op_name))

    @property
    def phase_leaf(self) -> str:
        p = self.phase
        return p.rsplit("/", 1)[-1] if p else ""


@dataclasses.dataclass
class HloComputation:
    name: str
    is_entry: bool
    instructions: List[HloInstr]

    def by_name(self) -> Dict[str, HloInstr]:
        return {i.name: i for i in self.instructions}


def _split_operands(segment: str) -> List[str]:
    """Split an operand segment on top-level commas, respecting nested
    ``()``/``[]``/``{}`` (tuple-shaped operands, TPU tile suffixes like
    ``{1,0:T(8,128)}``, constant literals)."""
    out, depth, cur = [], 0, []
    for ch in segment:
        if ch in "([{":
            depth += 1
        elif ch in ")]}":
            depth -= 1
        if ch == "," and depth == 0:
            out.append("".join(cur))
            cur = []
        else:
            cur.append(ch)
    if cur:
        out.append("".join(cur))
    return out


def _operand_segment(line: str, start: int) -> Tuple[str, int]:
    """The text inside the operand parens opening at ``line[start] ==
    '('``; returns ``(segment, index_after_close)``."""
    depth = 0
    for i in range(start, len(line)):
        c = line[i]
        if c == "(":
            depth += 1
        elif c == ")":
            depth -= 1
            if depth == 0:
                return line[start + 1:i], i + 1
    return line[start + 1:], len(line)


def _operand_names(segment: str) -> Tuple[str, ...]:
    """Operand instruction names from a split chunk list: the LAST
    ``%name`` token of each chunk (typed form ``f32[2]{0} %x``), or the
    bare trailing identifier (untyped handwritten modules). Chunks
    holding no plausible name (constant literals, index comments) yield
    nothing — unknown names simply create no edge."""
    names = []
    for chunk in _split_operands(segment):
        toks = _NAME_TOKEN_RE.findall(chunk)
        if toks:
            names.append(toks[-1])
            continue
        tail = chunk.strip().split()
        if tail and re.fullmatch(r"[A-Za-z_][\w.\-]*", tail[-1]):
            names.append(tail[-1])
    return tuple(names)


def parse_hlo_module(txt: str) -> Dict[str, HloComputation]:
    """Parse optimized HLO module text into named computations with
    per-instruction operand lists. Pure text -> dataclasses."""
    comps: Dict[str, HloComputation] = {}
    cur: Optional[HloComputation] = None
    for line in txt.splitlines():
        stripped = line.strip()
        if cur is None:
            if not stripped or stripped.startswith(("HloModule",
                                                    "//", "#")):
                continue
            if stripped.endswith("{") and "=" not in stripped.split(
                    "(", 1)[0]:
                m = _COMP_RE.match(stripped)
                if m:
                    cur = HloComputation(
                        name=m.group("name"),
                        is_entry=bool(m.group("entry")), instructions=[])
                    comps[cur.name] = cur
            continue
        if stripped == "}":
            cur = None
            continue
        m = _INST_NAME_RE.match(line)
        if m is None:
            continue
        seg, after = _operand_segment(line, m.end() - 1)
        tail = line[after:]
        called = list(_CALLED_RE.findall(tail))
        bm = _BRANCHES_RE.search(tail)
        if bm:
            called += _NAME_TOKEN_RE.findall(bm.group(1))
        nm = _OPNAME_RE.search(line)
        cur.instructions.append(HloInstr(
            name=m.group("name"), op=m.group("op"),
            shape=m.group("shape"),
            operands=_operand_names(seg),
            called=tuple(called),
            op_name=nm.group(1) if nm else "",
            is_root=stripped.startswith("ROOT "),
            line=line))
    return comps


def entry_computation(comps: Dict[str, HloComputation]) -> HloComputation:
    for c in comps.values():
        if c.is_entry:
            return c
    raise ScheduleGraphError(
        f"no ENTRY computation among {sorted(comps)[:8]}... — "
        "unrecognized HLO text")


# ------------------------------------------------------------ the graph


@dataclasses.dataclass
class GraphNode:
    """One entry-computation instruction with its modeled cost."""
    instr: HloInstr
    index: int
    phase: str
    phase_leaf: str
    is_collective: bool
    is_trivial: bool
    result_bytes: int
    operand_bytes: int
    payload_bytes: int        # off-chip bytes for collectives, else 0
    cost_ns: float


def _shape_bytes(text: str) -> int:
    return sum(_token_bytes(dt, dims)
               for dt, dims in _SHAPE_TOKEN_RE.findall(text))


def _called_all_trivial(instr: HloInstr,
                        comps: Dict[str, HloComputation]) -> bool:
    """Whether a ``call``/``fusion`` wraps ONLY trivial work — the CPU
    backend outlines even zero-splat broadcasts into
    ``call(..., to_apply=%parallel_broadcast...)`` computations, which
    must not masquerade as hideable compute."""
    if not instr.called:
        return False
    saw_any = False
    for cname in instr.called:
        comp = comps.get(cname)
        if comp is None:
            return False
        for inner in comp.instructions:
            saw_any = True
            if inner.op not in TRIVIAL_OPS:
                return False
    return saw_any


def _resolve_phase(instr: HloInstr,
                   comps: Dict[str, HloComputation]) -> str:
    """A node's ``detpu`` phase path: its own ``op_name`` scope, else the
    majority scope of the computations it calls (fusions usually stamp
    the root op's scope on the fusion instruction itself; ``while`` loops
    from the scatter expander sometimes only scope the body)."""
    p = instr.phase
    if p:
        return p
    votes: Dict[str, int] = {}
    for cname in instr.called:
        comp = comps.get(cname)
        if comp is None:
            continue
        for inner in comp.instructions:
            ip = inner.phase
            if ip:
                votes[ip] = votes.get(ip, 0) + 1
    if not votes:
        return ""
    return max(sorted(votes), key=lambda k: votes[k])


class ScheduleGraph:
    """Dependency DAG of the optimized entry computation, with modeled
    per-node costs.

    Cost model (``ns ~= bytes / GBps`` — X GB/s moves ~X bytes per ns):

    * compute node: ``(result + operand bytes) / hbm_gbps`` — row ops and
      fusions on this class of model are HBM-bound (docs/perf_tpu.md);
    * collective node: ``payload / ici_eff_gbps`` where ``payload`` is
      the operand bytes times ``(world-1)/world`` — bytes actually
      leaving the chip, the SAME convention as the ``*_a2a_bytes`` step
      metrics and ``plan_audit``'s a2a pricing (an 8-way tiled
      all-to-all keeps 1/8 of its block local).
    """

    def __init__(self, comps: Dict[str, HloComputation], *,
                 world: int = 1, chip: ChipSpec = CHIP_SPECS["v5e"]):
        self.world = max(int(world), 1)
        self.chip = chip
        self.comps = comps
        entry = entry_computation(comps)
        self.entry = entry
        names = entry.by_name()
        off_frac = (self.world - 1) / self.world if self.world > 1 else 0.0
        self.nodes: List[GraphNode] = []
        index = {}
        for i, instr in enumerate(entry.instructions):
            res_b = _shape_bytes(instr.shape)
            # operand bytes from the full line minus the result shape
            # (shape tokens in the tail are the typed operand spellings)
            op_b = max(_shape_bytes(instr.line) - res_b, 0)
            is_coll = instr.op in COLLECTIVE_OPS or (
                instr.op == "custom-call" and "all_to_all" in instr.op_name)
            payload = int(op_b * off_frac) if is_coll else 0
            is_triv = instr.op in TRIVIAL_OPS or (
                instr.op in ("call", "fusion")
                and _called_all_trivial(instr, comps))
            if is_coll:
                cost = payload / max(chip.ici_eff_gbps, 1e-9)
            elif is_triv:
                cost = 0.0
            else:
                cost = (res_b + op_b) / max(chip.hbm_gbps, 1e-9)
            self.nodes.append(GraphNode(
                instr=instr, index=i,
                phase=_resolve_phase(instr, comps),
                phase_leaf="", is_collective=is_coll,
                is_trivial=is_triv,
                result_bytes=res_b, operand_bytes=op_b,
                payload_bytes=payload, cost_ns=cost))
            index[instr.name] = i
        for n in self.nodes:
            n.phase_leaf = (n.phase.rsplit("/", 1)[-1] if n.phase else "")
        # edges: operand -> consumer (unknown operand names create none)
        self.preds: List[List[int]] = [[] for _ in self.nodes]
        self.succs: List[List[int]] = [[] for _ in self.nodes]
        for n in self.nodes:
            for op_name_ in n.instr.operands:
                j = index.get(op_name_)
                if j is not None and j != n.index:
                    self.preds[n.index].append(j)
                    self.succs[j].append(n.index)
        self._topo: Optional[List[int]] = None

    # -- structure --------------------------------------------------------
    def roots(self) -> List[int]:
        """Sink nodes (no consumers). A compiled module always has at
        least one — the ROOT instruction."""
        return [n.index for n in self.nodes if not self.succs[n.index]]

    def topo_order(self) -> List[int]:
        """Kahn topological order; raises :class:`ScheduleGraphError` on
        a dependency cycle (impossible in well-formed SSA HLO — a cycle
        means the parser mis-read operands)."""
        if self._topo is not None:
            return self._topo
        indeg = [len(p) for p in self.preds]
        ready = [i for i, d in enumerate(indeg) if d == 0]
        out: List[int] = []
        while ready:
            i = ready.pop()
            out.append(i)
            for j in self.succs[i]:
                indeg[j] -= 1
                if indeg[j] == 0:
                    ready.append(j)
        if len(out) != len(self.nodes):
            stuck = [self.nodes[i].instr.name
                     for i, d in enumerate(indeg) if d > 0][:6]
            raise ScheduleGraphError(
                f"dependency cycle in parsed entry computation "
                f"(involving {stuck}) — operand extraction mis-read the "
                "module text")
        self._topo = out
        return out

    def _cone(self, start: int, edges: List[List[int]]) -> Set[int]:
        seen: Set[int] = set()
        stack = list(edges[start])
        while stack:
            i = stack.pop()
            if i in seen:
                continue
            seen.add(i)
            stack.extend(edges[i])
        return seen

    def ancestors(self, i: int) -> Set[int]:
        return self._cone(i, self.preds)

    def descendants(self, i: int) -> Set[int]:
        return self._cone(i, self.succs)

    def critical_path(self) -> List[int]:
        """Longest (max summed cost) source→sink chain, as node indices
        in execution order."""
        order = self.topo_order()
        dist = [0.0] * len(self.nodes)
        back: List[Optional[int]] = [None] * len(self.nodes)
        for i in order:
            best, arg = 0.0, None
            for p in self.preds[i]:
                if dist[p] > best:
                    best, arg = dist[p], p
            dist[i] = best + self.nodes[i].cost_ns
            back[i] = arg
        end = max(range(len(self.nodes)), key=lambda i: dist[i],
                  default=None)
        if end is None:
            return []
        path = []
        cur: Optional[int] = end
        while cur is not None:
            path.append(cur)
            cur = back[cur]
        return path[::-1]

    def independent_compute_ns(self, i: int) -> float:
        """Total modeled cost of REAL compute (non-trivial, non-collective
        nodes) neither upstream nor downstream of node ``i`` — the work a
        latency-hiding scheduler could run concurrently with it."""
        return sum(self.independent_compute_by_phase(i).values())

    def independent_compute_by_phase(self, i: int) -> Dict[str, float]:
        """The :meth:`independent_compute_ns` sum broken down by the
        independent nodes' ``detpu`` phase — what lets the schedule
        declaration check verify an overlap claim against the DECLARED
        partner phase rather than against any independent work."""
        cone = self.ancestors(i) | self.descendants(i) | {i}
        out: Dict[str, float] = {}
        for n in self.nodes:
            if (n.index in cone or n.is_collective or n.is_trivial
                    or n.cost_ns <= 0):
                continue
            out[n.phase] = out.get(n.phase, 0.0) + n.cost_ns
        return out


# ----------------------------------------------------------- the contracts


@dataclasses.dataclass(frozen=True)
class ScheduleContract:
    """One declarative expectation on the collectives of a phase.

    ``phase`` is an ``fnmatch`` glob tested against each collective's
    full ``detpu`` path AND its leaf (census convention). ``expect`` is
    ``"present"`` (>= ``min_count`` matching collectives), or
    ``"serialized"`` / ``"overlappable"`` (present AND every match
    classified so). ``on_critical_path`` additionally pins whether the
    matches sit on the modeled critical path."""
    phase: str
    expect: str = "present"
    min_count: int = 1
    on_critical_path: Optional[bool] = None
    reason: str = ""

    def __post_init__(self) -> None:
        if self.expect not in ("present", "serialized", "overlappable"):
            raise ValueError(
                f"ScheduleContract({self.phase!r}): expect must be "
                f"'present' | 'serialized' | 'overlappable', got "
                f"{self.expect!r}")


def declared_overlap_contracts(schedule) -> List[ScheduleContract]:
    """One ``expect="overlappable"`` contract per collective phase that
    DECLARES an overlap — the expectations a pipelined (or streaming)
    :class:`~..parallel.schedule.StepSchedule`'s claims imply. Running
    these next to :meth:`ScheduleReport.check_against_schedule` makes
    the gate two-sided: the declaration check verifies the claimed
    partner compute exists, and these verify the collective's GLOBAL
    classification flipped to overlappable (the serialized fraction the
    bench ratchet rides)."""
    out: List[ScheduleContract] = []
    for p in schedule.phases:
        if p.kind == "collective" and p.overlaps:
            out.append(ScheduleContract(
                p.name, expect="overlappable",
                reason=f"schedule '{schedule.name}' declares overlap "
                       f"with {list(p.overlaps)}"))
    return out


def baseline_contracts() -> List[ScheduleContract]:
    """The documented baseline of today's UNPIPELINED hybrid step: the
    id / out / grad all-to-alls exist, sit on the critical path, and are
    serialized against dense compute — the measured starting line the
    pipelined step (ROADMAP item 2) has to beat. A future overlap win
    ships a new schedule AND flips these to ``expect="overlappable"`` in
    the same PR; until then, a candidate that silently changes the
    dependency shape fails the gate either way."""
    why = ("unpipelined baseline: the exchange runs strictly between its "
           "producer and consumer phases")
    return [
        ScheduleContract("id_all_to_all", expect="serialized",
                         on_critical_path=True, reason=why),
        ScheduleContract("out_all_to_all", expect="serialized",
                         on_critical_path=True, reason=why),
        ScheduleContract("grad_all_to_all", expect="serialized",
                         on_critical_path=True, reason=why),
    ]


# -------------------------------------------------------------- the report


@dataclasses.dataclass
class CollectiveInfo:
    """One collective of the compiled step, classified."""
    name: str
    op: str
    phase: str
    phase_leaf: str
    payload_bytes: int
    cost_ns: float
    independent_compute_ns: float
    #: the independent compute broken down by its nodes' detpu phase —
    #: the declaration check verifies overlap claims against the
    #: DECLARED partner's share, not the global sum
    independent_by_phase: Dict[str, float]
    overlap_ratio: float          # independent compute / collective cost
    classification: str           # "serialized" | "overlappable"
    on_critical_path: bool

    def independent_matching(self, globs) -> float:
        """Independent compute attributable to phases matching any of
        ``globs`` — full path, leaf (census convention), or any single
        path COMPONENT, so a declared partner phase owns its nested
        sub-scopes (``embedding_forward_mb1/lookup_w4_d_mb1/
        packed_gather`` counts toward a ``lookup_*_mb1`` claim: the
        gather IS the lookup's compute)."""
        total = 0.0
        for phase, ns in self.independent_by_phase.items():
            parts = phase.split("/") if phase else []
            if any(fnmatch.fnmatchcase(phase, g)
                   or any(fnmatch.fnmatchcase(p, g) for p in parts)
                   for g in globs):
                total += ns
        return total

    def to_json(self) -> Dict[str, Any]:
        d = dataclasses.asdict(self)
        d["independent_by_phase"] = {
            k or "(unscoped)": round(v, 3)
            for k, v in self.independent_by_phase.items()}
        return d


@dataclasses.dataclass
class ScheduleReport:
    """Structured result of one schedule-graph audit."""
    label: str
    world: int
    chip: str
    backend: Optional[str]
    nodes: int
    edges: int
    collectives: List[CollectiveInfo]
    critical_path_ns: float
    critical_path_bytes: int
    critical_path_phases: List[Tuple[str, float]]   # condensed runs
    serialized_collective_fraction: float
    total_collective_ns: float
    total_compute_ns: float
    overlap_min_ratio: float
    violations: List[str]
    #: modeled cost summed per detpu phase path (non-trivial nodes,
    #: collectives included under their exchange phase) — the modeled
    #: half of the measured-vs-modeled drift table
    #: (:func:`~.phase_profile.calibrate` joins measured trace durations
    #: against exactly these keys)
    phase_cost_ns: Dict[str, float] = dataclasses.field(
        default_factory=dict)

    @property
    def ok(self) -> bool:
        return not self.violations

    def _matching(self, glob: str) -> List[CollectiveInfo]:
        return [c for c in self.collectives
                if fnmatch.fnmatchcase(c.phase, glob)
                or fnmatch.fnmatchcase(c.phase_leaf, glob)]

    def _add(self, msg: str) -> None:
        if msg not in self.violations:
            self.violations.append(msg)

    def check(self, contracts: Sequence[ScheduleContract]
              ) -> "ScheduleReport":
        """Evaluate contracts; violations accumulate (idempotent)."""
        for c in contracts:
            matched = self._matching(c.phase)
            why = f" — {c.reason}" if c.reason else ""
            if len(matched) < c.min_count:
                self._add(
                    f"schedule contract: phase '{c.phase}' expected >= "
                    f"{c.min_count} collective(s), found {len(matched)}"
                    f"{why}")
                continue
            for m in matched:
                if c.expect in ("serialized", "overlappable") \
                        and m.classification != c.expect:
                    self._add(
                        f"schedule contract: collective {m.name} in phase "
                        f"'{m.phase}' is {m.classification}, expected "
                        f"{c.expect} (cost {m.cost_ns:.1f} ns vs "
                        f"independent compute "
                        f"{m.independent_compute_ns:.1f} ns){why}")
                if c.on_critical_path is not None \
                        and m.on_critical_path != c.on_critical_path:
                    self._add(
                        f"schedule contract: collective {m.name} in phase "
                        f"'{m.phase}' on_critical_path="
                        f"{m.on_critical_path}, expected "
                        f"{c.on_critical_path}{why}")
        return self

    def check_against_schedule(self, schedule) -> "ScheduleReport":
        """Verify a :class:`~..parallel.schedule.StepSchedule`'s claims
        against the compiled reality:

        * every declared ``collective`` phase must match >= 1 compiled
          collective (a declared exchange that compiled to nothing means
          the schedule and the program drifted apart);
        * every declared **overlap** of a collective phase must exist in
          the DAG — each matching collective must be classified
          overlappable. A schedule claiming overlap over a serialized
          program is the lie ``--strict`` exists to catch.
        """
        for p in schedule.phases:
            if p.kind != "collective":
                continue
            matched = self._matching(p.name)
            if not matched:
                self._add(
                    f"schedule '{schedule.name}': declared collective "
                    f"phase '{p.name}' matches no compiled collective — "
                    "the schedule no longer describes the program")
                continue
            if not p.overlaps:
                continue
            for m in matched:
                # the claim is verified against the DECLARED partner's
                # independent-compute share, not the global sum — a
                # claim of "hides under dense compute" must not be
                # satisfied by some unrelated independent chain
                partner_ind = m.independent_matching(p.overlaps)
                if partner_ind < self.overlap_min_ratio * m.cost_ns:
                    self._add(
                        f"schedule '{schedule.name}': phase '{p.name}' "
                        f"declares overlap with {list(p.overlaps)} but "
                        f"the compiled program SERIALIZES collective "
                        f"{m.name} against it (independent "
                        f"{list(p.overlaps)} compute {partner_ind:.1f} "
                        f"ns < {self.overlap_min_ratio:.2f} x cost "
                        f"{m.cost_ns:.1f} ns) — the declared overlap "
                        "does not exist in what XLA emitted")
        return self

    def raise_on_violations(self) -> "ScheduleReport":
        if self.violations:
            raise ScheduleGraphError(
                "schedule audit failed:\n  - "
                + "\n  - ".join(self.violations))
        return self

    # -- serialization ----------------------------------------------------
    def summary(self) -> Dict[str, Any]:
        """The compact record the bench's ``schedule`` section embeds and
        ``tools/compare_bench.py::check_schedule`` gates."""
        return {
            "label": self.label,
            "world": self.world,
            "chip": self.chip,
            "serialized_collective_fraction":
                round(self.serialized_collective_fraction, 6),
            "critical_path_ns": round(self.critical_path_ns, 3),
            "critical_path_bytes": self.critical_path_bytes,
            "total_collective_ns": round(self.total_collective_ns, 3),
            "total_compute_ns": round(self.total_compute_ns, 3),
            "collectives": [
                {"phase": c.phase, "op": c.op,
                 "payload_bytes": c.payload_bytes,
                 "classification": c.classification,
                 "on_critical_path": c.on_critical_path}
                for c in self.collectives],
            "violations": list(self.violations),
        }

    def to_json(self) -> Dict[str, Any]:
        d = self.summary()
        d.update(
            backend=self.backend, nodes=self.nodes, edges=self.edges,
            overlap_min_ratio=self.overlap_min_ratio,
            critical_path_phases=[
                {"phase": p, "cost_ns": round(ns, 3)}
                for p, ns in self.critical_path_phases],
            phase_cost_ns={k or "(unscoped)": round(v, 3)
                           for k, v in self.phase_cost_ns.items()},
            collectives=[c.to_json() for c in self.collectives])
        return d

    def dumps(self, **kw: Any) -> str:
        return json.dumps(self.to_json(), **kw)

    def markdown(self) -> str:
        """The per-collective classification as a markdown table (docs /
        PR bodies) plus the condensed critical path."""
        lines = [
            "| collective | phase | payload | cost | independent "
            "compute | verdict | critical path |",
            "|---|---|---|---|---|---|---|",
        ]
        for c in self.collectives:
            lines.append(
                f"| `{c.name}` | `{c.phase}` | {c.payload_bytes} B "
                f"| {c.cost_ns:.1f} ns | "
                f"{c.independent_compute_ns:.1f} ns "
                f"| **{c.classification}** "
                f"| {'yes' if c.on_critical_path else 'no'} |")
        lines.append("")
        lines.append(
            f"critical path: {self.critical_path_ns:.1f} ns modeled, "
            f"{self.critical_path_bytes} bytes, "
            f"serialized_collective_fraction="
            f"{self.serialized_collective_fraction:.3f}")
        lines.append("phases on the path: " + " -> ".join(
            f"{p or '(unscoped)'} ({ns:.1f} ns)"
            for p, ns in self.critical_path_phases))
        return "\n".join(lines)


# ----------------------------------------------------------- entry points


def analyze_graph(graph: ScheduleGraph, *, label: str = "step",
                  backend: Optional[str] = None,
                  overlap_min_ratio: float = 1.0) -> ScheduleReport:
    """Classify a built :class:`ScheduleGraph` into a
    :class:`ScheduleReport` (no contracts applied yet).

    A collective is **overlappable** when the modeled independent
    compute outside its ancestor/descendant cones is at least
    ``overlap_min_ratio`` times its own cost — i.e. enough concurrent
    work exists to hide the whole transfer; anything less is
    **serialized** (partial hiding is a follow-up refinement, and a
    gate must not reward it prematurely)."""
    path = graph.critical_path()
    on_path = set(path)
    collectives: List[CollectiveInfo] = []
    ser_cost = tot_cost = 0.0
    for n in graph.nodes:
        if not n.is_collective:
            continue
        by_phase = graph.independent_compute_by_phase(n.index)
        ind = sum(by_phase.values())
        ratio = ind / n.cost_ns if n.cost_ns > 0 else float("inf")
        cls = ("overlappable" if ratio >= overlap_min_ratio
               else "serialized")
        tot_cost += n.cost_ns
        if cls == "serialized":
            ser_cost += n.cost_ns
        collectives.append(CollectiveInfo(
            name=n.instr.name, op=n.instr.op, phase=n.phase,
            phase_leaf=n.phase_leaf, payload_bytes=n.payload_bytes,
            cost_ns=n.cost_ns, independent_compute_ns=ind,
            independent_by_phase=by_phase,
            overlap_ratio=ratio, classification=cls,
            on_critical_path=n.index in on_path))
    # condensed critical path: consecutive same-phase nodes fold into one
    runs: List[Tuple[str, float]] = []
    for i in path:
        n = graph.nodes[i]
        if runs and runs[-1][0] == n.phase:
            runs[-1] = (n.phase, runs[-1][1] + n.cost_ns)
        else:
            runs.append((n.phase, n.cost_ns))
    phase_cost: Dict[str, float] = {}
    for n in graph.nodes:
        if n.is_trivial or n.cost_ns <= 0:
            continue
        phase_cost[n.phase] = phase_cost.get(n.phase, 0.0) + n.cost_ns
    return ScheduleReport(
        label=label, world=graph.world, chip=graph.chip.name,
        backend=backend,
        nodes=len(graph.nodes),
        edges=sum(len(s) for s in graph.succs),
        collectives=collectives,
        critical_path_ns=sum(graph.nodes[i].cost_ns for i in path),
        critical_path_bytes=sum(
            graph.nodes[i].payload_bytes if graph.nodes[i].is_collective
            else (0 if graph.nodes[i].is_trivial
                  else graph.nodes[i].result_bytes
                  + graph.nodes[i].operand_bytes)
            for i in path),
        critical_path_phases=runs,
        serialized_collective_fraction=(
            ser_cost / tot_cost if tot_cost > 0 else 0.0),
        total_collective_ns=tot_cost,
        total_compute_ns=sum(n.cost_ns for n in graph.nodes
                             if not n.is_collective and not n.is_trivial),
        overlap_min_ratio=overlap_min_ratio,
        violations=[],
        phase_cost_ns=phase_cost)


def audit_text(txt: str, *, label: str = "step", world: int = 1,
               chip: str = "v5e", backend: Optional[str] = None,
               overlap_min_ratio: float = 1.0) -> ScheduleReport:
    """Parse optimized HLO text, build the DAG, classify. Pure text ->
    dataclass (the census's ``census_of_text`` analogue)."""
    graph = ScheduleGraph(parse_hlo_module(txt), world=world,
                          chip=CHIP_SPECS[chip])
    if not graph.nodes:
        raise ScheduleGraphError(
            f"schedule audit of {label!r} parsed 0 entry instructions "
            f"from a {len(txt)}-byte module — unrecognized HLO text; "
            "the overlap gate cannot run on it")
    if not graph.roots():
        raise ScheduleGraphError(
            f"schedule audit of {label!r}: parsed graph has no sink "
            "nodes — operand extraction mis-read the module")
    graph.topo_order()   # cycle check up front, before any contract runs
    return analyze_graph(graph, label=label, backend=backend,
                         overlap_min_ratio=overlap_min_ratio)


def audit_step_fn(step_fn, args: Sequence[Any], *, world: int = 1,
                  label: str = "step", chip: str = "v5e",
                  schedule=None,
                  contracts: Optional[Sequence[ScheduleContract]] = None,
                  overlap_min_ratio: float = 1.0) -> ScheduleReport:
    """Compile a jitted step abstractly and audit its schedule graph.

    ``args`` may be concrete arrays or ``jax.ShapeDtypeStruct`` pytrees —
    ``step_fn.lower(*args).compile()`` never executes anything. Plain
    callables are wrapped in ``jax.jit`` first. ``schedule`` (a
    :class:`~..parallel.schedule.StepSchedule`) adds the declaration
    check; ``contracts`` adds the declarative expectations
    (``None`` applies none — callers pin their own baselines)."""
    if not hasattr(step_fn, "lower"):
        step_fn = jax.jit(step_fn)
    txt = step_fn.lower(*args).compile().as_text()
    try:
        backend = jax.default_backend()
    except Exception:  # noqa: BLE001 - stamp is best-effort
        backend = None
    rep = audit_text(txt, label=label, world=world, chip=chip,
                     backend=backend,
                     overlap_min_ratio=overlap_min_ratio)
    if schedule is not None:
        rep.check_against_schedule(schedule)
    if contracts:
        rep.check(contracts)
    return rep


def audit_train_step(de,
                     loss_fn,
                     dense_tx,
                     emb_optimizer,
                     cat_inputs,
                     batch,
                     mesh=None,
                     lr_schedule=1.0,
                     with_metrics: Optional[bool] = None,
                     nan_guard: Optional[bool] = None,
                     telemetry=None,
                     dynamic=None,
                     dense_params=None,
                     state=None,
                     chip: str = "v5e",
                     schedule=None,
                     contracts: Optional[Sequence[ScheduleContract]] = None,
                     overlap_min_ratio: float = 1.0,
                     label: str = "hybrid_train_step") -> ScheduleReport:
    """Build the hybrid train step exactly like
    :func:`~..parallel.trainer.make_hybrid_train_step` (the shared
    :func:`~.audit.build_abstract_step` harness, so this gate audits the
    same program as the jaxpr auditor and the HLO census) and audit its
    schedule graph.

    ``schedule=None`` checks the layer's own declared schedule
    (``de.schedule``); ``contracts=None`` applies
    :func:`baseline_contracts` — pass an explicit (possibly empty) list
    to override either."""
    from .audit import build_abstract_step

    from ..parallel.schedule import without_streaming

    step, args, _, _, _, _ = build_abstract_step(
        de, loss_fn, dense_tx, emb_optimizer, cat_inputs, batch,
        mesh=mesh, lr_schedule=lr_schedule, with_metrics=with_metrics,
        nan_guard=nan_guard, telemetry=telemetry, dynamic=dynamic,
        dense_params=dense_params, state=state)
    if schedule is None:
        schedule = de.schedule
        if dynamic is None or dynamic is False:
            # a streaming-capable layer trained WITHOUT dynamic=
            # executes the non-streaming program: its compiled DAG has
            # no admission-staging nodes, so the streaming overlap
            # declaration must not be checked against it
            schedule = without_streaming(schedule)
    if contracts is None:
        contracts = baseline_contracts() if de.world_size > 1 else []
    return audit_step_fn(
        step, args, world=de.world_size, label=label, chip=chip,
        schedule=schedule, contracts=contracts,
        overlap_min_ratio=overlap_min_ratio)
